"""Merged datastore views: read-only union over N stores.

The reference's MergedDataStoreView (geomesa-index-api/.../view/
MergedDataStoreView.scala + MergedQueryRunner): one logical store whose
queries fan out to every underlying store sharing the schema and
concatenate results (each store may optionally carry a pre-filter that
scopes which subset it contributes).
"""

from __future__ import annotations

import numpy as np

from .features.batch import FeatureBatch
from .filters.ast import And
from .planning.planner import Query

__all__ = ["MergedDataStoreView"]


class MergedDataStoreView:
    """Read-only union over stores exposing create-less query APIs."""

    def __init__(self, stores, filters=None):
        """``stores``: list of stores; ``filters``: optional per-store
        scope filters (parallel list, entries None or a Filter)."""
        if not stores:
            raise ValueError("need at least one store")
        self.stores = list(stores)
        self.filters = list(filters) if filters else [None] * len(stores)
        if len(self.filters) != len(self.stores):
            raise ValueError("filters must parallel stores")

    def get_schema(self, name: str):
        return self.stores[0].get_schema(name)

    def query(self, name: str, query="INCLUDE") -> FeatureBatch:
        q = query if isinstance(query, Query) else Query.of(query)
        parts = []
        for store, scope in zip(self.stores, self.filters):
            sq = q
            if scope is not None:
                from dataclasses import replace
                sq = replace(q, filter=And((q.filter, scope)),
                             hints=dict(q.hints))
            out = store.query(name, sq)
            if len(out):
                parts.append(out)
        if not parts:
            return FeatureBatch.empty(self.get_schema(name))
        merged = parts[0]
        for p in parts[1:]:
            merged = merged.concat(p)
        if q.sort_by:
            order = np.argsort(merged.column(q.sort_by), kind="stable")
            if q.sort_desc:
                order = order[::-1]
            merged = merged.take(order)
        if q.max_features is not None:
            merged = merged.take(np.arange(min(q.max_features, len(merged))))
        return merged

    def count(self, name: str, query="INCLUDE") -> int:
        return len(self.query(name, query))
