"""Raster / coverage store: geo-referenced tile pyramid with device mosaic.

The analog of the reference's raster store (geomesa-accumulo/
geomesa-accumulo-raster/.../data/AccumuloRasterStore.scala:35-160 —
rasters keyed by geohash with a lexicoded resolution qualifier, queried
by bbox + resolution, chips mosaicked client-side; WCS served on top).
TPU-first design: each resolution level keeps its tiles as ONE stacked
``(n, th, tw)`` device array plus an ``(n, 4)`` bbox array — the query
is a vectorized bbox-intersection mask, and ``mosaic()`` resamples all
candidate tiles into the output grid in a single jitted program
(gather + nearest-neighbor sampling on the MXU-adjacent VPU) instead of
per-chip host loops.
"""

from __future__ import annotations

from functools import lru_cache as _lru_cache

import numpy as np

__all__ = ["RasterStore", "RasterTile"]


class RasterTile:
    """One geo-referenced chip: ``data[row, col]`` covering ``bbox``
    (xmin, ymin, xmax, ymax); row 0 is the NORTH edge (image order)."""

    def __init__(self, data, bbox: tuple):
        self.data = np.asarray(data, dtype=np.float32)
        if self.data.ndim != 2:
            raise ValueError("tile data must be 2-D")
        self.bbox = tuple(float(v) for v in bbox)

    @property
    def resolution(self) -> float:
        """Degrees per pixel (x)."""
        return (self.bbox[2] - self.bbox[0]) / self.data.shape[1]


class _Level:
    """All tiles of one resolution, stacked device-side."""

    def __init__(self, tile_shape: tuple):
        self.tile_shape = tile_shape
        self.tiles: list[np.ndarray] = []
        self.bboxes: list[tuple] = []
        self._stacked = None     # jnp (n, th, tw)
        self._bbox_arr = None    # jnp (n, 4)

    def add(self, tile: RasterTile):
        if tile.data.shape != self.tile_shape:
            raise ValueError(
                f"tile shape {tile.data.shape} != level shape "
                f"{self.tile_shape}")
        self.tiles.append(tile.data)
        self.bboxes.append(tile.bbox)
        self._stacked = None

    def arrays(self):
        import jax.numpy as jnp
        if self._stacked is None:
            self._stacked = jnp.asarray(np.stack(self.tiles))
            self._bbox_arr = jnp.asarray(np.asarray(self.bboxes))
        return self._stacked, self._bbox_arr


class RasterStore:
    """Multi-resolution tile store with bbox query and device mosaic."""

    def __init__(self, name: str = "raster"):
        self.name = name
        self._levels: dict[float, _Level] = {}

    # -- ingest ------------------------------------------------------------
    def put(self, data, bbox: tuple) -> None:
        """Store one tile; its resolution level is derived from shape+bbox
        (the reference's lexicoded-resolution column role)."""
        tile = RasterTile(data, bbox)
        res = round(tile.resolution, 12)
        level = self._levels.get(res)
        if level is None:
            level = self._levels[res] = _Level(tile.data.shape)
        level.add(tile)

    @property
    def available_resolutions(self) -> list[float]:
        """Finest-first (AccumuloRasterStore.getAvailableResolutions)."""
        return sorted(self._levels)

    def count(self, resolution: float | None = None) -> int:
        if resolution is not None:
            # levels are keyed on rounded resolution (put() rounds the
            # same way), so a tile's own .resolution always matches
            lvl = self._levels.get(round(resolution, 12))
            return 0 if lvl is None else len(lvl.tiles)
        return sum(len(v.tiles) for v in self._levels.values())

    # -- query -------------------------------------------------------------
    def _pick_resolution(self, target: float | None) -> float | None:
        """Coarsest resolution that is still at least as fine as the
        request (the reference's resolution-selection rule); finest when
        unspecified."""
        if not self._levels:
            return None
        resolutions = self.available_resolutions
        if target is None:
            return resolutions[0]
        candidates = [r for r in resolutions if r <= target]
        return candidates[-1] if candidates else resolutions[0]

    def get_tiles(self, bbox: tuple, resolution: float | None = None):
        """Tiles intersecting bbox at the chosen level →
        list[RasterTile] (the getRasters chip iterator)."""
        res = self._pick_resolution(resolution)
        if res is None:
            return []
        level = self._levels[res]
        boxes = np.asarray(level.bboxes)
        xmin, ymin, xmax, ymax = (float(v) for v in bbox)
        hit = ((boxes[:, 0] < xmax) & (boxes[:, 2] > xmin)
               & (boxes[:, 1] < ymax) & (boxes[:, 3] > ymin))
        return [RasterTile(level.tiles[i], level.bboxes[i])
                for i in np.flatnonzero(hit)]

    def bounds(self, resolution: float | None = None) -> tuple | None:
        """Union envelope of stored tiles (AccumuloRasterStore.getBounds):
        over one level when given, else over all levels."""
        if resolution is not None:
            lvl = self._levels.get(round(resolution, 12))
            levels = [lvl] if lvl is not None else []
        else:
            levels = list(self._levels.values())
        boxes = [b for lvl in levels for b in lvl.bboxes]
        if not boxes:
            return None
        arr = np.asarray(boxes)
        return (float(arr[:, 0].min()), float(arr[:, 1].min()),
                float(arr[:, 2].max()), float(arr[:, 3].max()))

    def grid_range(self, resolution: float | None = None):
        """(cols, rows) covered by the level's extent at its resolution
        (the reference's getGridRange)."""
        res = self._pick_resolution(resolution)
        if res is None:
            return None
        bb = self.bounds(res)
        return (int(round((bb[2] - bb[0]) / res)),
                int(round((bb[3] - bb[1]) / res)))

    # -- pyramid ----------------------------------------------------------
    def build_pyramid(self, levels: int = 3) -> list[float]:
        """Derive coarser resolution levels from the finest by 2×2 mean
        pooling each tile (the ingest-time pyramid the reference stores
        per lexicoded resolution; raster/ingest RasterMetadata) — one
        vectorized pooling op per level over the stacked tiles.  Returns
        the resolutions now available."""
        if not self._levels:
            return []
        res = self.available_resolutions[0]
        for _ in range(levels):
            src = self._levels[round(res, 12)]
            th, tw = src.tile_shape
            if th % 2 or tw % 2 or th < 2 or tw < 2:
                break
            stacked = np.stack(src.tiles)
            pooled = stacked.reshape(
                len(src.tiles), th // 2, 2, tw // 2, 2).mean(axis=(2, 4))
            res = res * 2
            key = round(res, 12)
            if key in self._levels:
                continue
            lvl = self._levels[key] = _Level((th // 2, tw // 2))
            for i, bb in enumerate(src.bboxes):
                lvl.tiles.append(pooled[i].astype(np.float32))
                lvl.bboxes.append(bb)
        return self.available_resolutions

    # -- persistence ------------------------------------------------------
    def save(self, path: str) -> None:
        """Persist every level as one npz (stacked tiles + bboxes) — the
        durable-store role of the reference's raster tables."""
        payload: dict = {"name": np.asarray(self.name)}
        for i, (res, lvl) in enumerate(sorted(self._levels.items())):
            payload[f"res_{i}"] = np.asarray(res)
            payload[f"tiles_{i}"] = np.stack(lvl.tiles)
            payload[f"bboxes_{i}"] = np.asarray(lvl.bboxes)
        np.savez_compressed(path, **payload)

    @classmethod
    def load(cls, path: str) -> "RasterStore":
        with np.load(path) as z:
            store = cls(str(z["name"]))
            i = 0
            while f"res_{i}" in z:
                tiles = z[f"tiles_{i}"]
                bboxes = z[f"bboxes_{i}"]
                for t, bb in zip(tiles, bboxes):
                    store.put(t, tuple(bb))
                i += 1
        return store

    def mosaic(self, bbox: tuple, width: int, height: int,
               resolution: float | None = None, nodata: float = np.nan):
        """Resample every intersecting tile into one ``(height, width)``
        grid over ``bbox`` — the client-side mosaic step
        (raster/util/RasterUtils mosaicking), executed as a single
        jitted device program.  Later tiles win where chips overlap.
        Returns a host numpy array."""
        import jax.numpy as jnp

        res = self._pick_resolution(resolution)
        if res is None:
            return np.full((height, width), nodata, dtype=np.float32)
        level = self._levels[res]
        tiles, tb = level.arrays()
        th, tw = level.tile_shape
        build = _mosaic_program(height, width, th, tw)
        bounds = jnp.asarray([float(v) for v in bbox])
        return np.asarray(build(tiles, tb, bounds, jnp.float32(nodata)))


@_lru_cache(maxsize=64)
def _mosaic_program(height: int, width: int, th: int, tw: int):
    """Jitted mosaic keyed only by static shapes — bbox/nodata are traced
    arguments, so repeated mosaics at new windows reuse the compile."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def build(tiles, tb, bounds, nodata):
        xmin, ymin, xmax, ymax = bounds[0], bounds[1], bounds[2], bounds[3]
        # output pixel centers (row 0 = north)
        px = xmin + (jnp.arange(width) + 0.5) * (xmax - xmin) / width
        py = ymax - (jnp.arange(height) + 0.5) * (ymax - ymin) / height
        gx = jnp.broadcast_to(px[None, :], (height, width))
        gy = jnp.broadcast_to(py[:, None], (height, width))

        def paint(canvas, args):
            tile, box = args
            bx0, by0, bx1, by1 = box[0], box[1], box[2], box[3]
            inside = (gx >= bx0) & (gx < bx1) & (gy > by0) & (gy <= by1)
            # nearest-neighbor source pixel
            col = jnp.clip(((gx - bx0) / (bx1 - bx0) * tw).astype(
                jnp.int32), 0, tw - 1)
            row = jnp.clip(((by1 - gy) / (by1 - by0) * th).astype(
                jnp.int32), 0, th - 1)
            sampled = tile[row, col]
            return jnp.where(inside, sampled, canvas), None

        canvas = jnp.full((height, width), nodata)
        canvas, _ = jax.lax.scan(paint, canvas, (tiles, tb))
        return canvas

    return build
