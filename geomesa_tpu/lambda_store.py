"""LambdaDataStore: merged transient (stream) + persistent store.

The reference's geomesa-lambda module: recent writes live in a Kafka-fed
in-memory cache; a background persister periodically flushes features
older than an expiry window into the durable store; queries merge both
layers with the transient layer winning on id collisions
(geomesa-lambda/.../LambdaDataStore.scala, stream/kafka/KafkaStore.scala,
DataStorePersistence.scala).
"""

from __future__ import annotations

import time

import numpy as np

from .features.batch import FeatureBatch
from .planning.planner import Query
from .stream.store import StreamDataStore

__all__ = ["LambdaDataStore"]


class LambdaDataStore:
    """Transient stream cache over a persistent TpuDataStore."""

    def __init__(self, persistent, stream: StreamDataStore | None = None,
                 expiry_ms: int = 60_000, clock=time.time):
        self.persistent = persistent
        self.stream = stream or StreamDataStore()
        self.expiry_ms = expiry_ms
        self._clock = clock
        self._write_ms: dict[tuple, float] = {}   # (type, fid) → write time
        #: lean persistent layer: stream fid → implicit row id of its
        #: persisted row (the upsert mapping — lean stores mint row ids,
        #: so replacement = tombstone the old row + append the new one)
        self._persisted_row: dict[tuple, str] = {}

    def _lean_store(self, name: str):
        """The persistent layer's lean _SchemaStore, or None (duck-typed:
        any store without the lean profile flushes by explicit id)."""
        st = getattr(self.persistent, "_store", None)
        if st is None:
            return None
        st = st(name)
        return st if getattr(st, "lean", False) else None

    # -- schema -----------------------------------------------------------
    def create_schema(self, name: str, spec: str):
        sft = self.persistent.create_schema(name, spec)
        self.stream.create_schema(name, spec)
        return sft

    def get_schema(self, name: str):
        return self.persistent.get_schema(name)

    # -- writes go to the transient layer ---------------------------------
    def write(self, name: str, fid: str, attributes: dict) -> None:
        self.stream.write(name, fid, attributes)
        self._write_ms[(name, fid)] = self._clock() * 1000.0

    def delete(self, name: str, fid: str) -> None:
        self.stream.delete(name, fid)
        self._write_ms.pop((name, fid), None)

    # -- persistence flusher (DataStorePersistence analog) ----------------
    def persist(self, name: str, now_ms: float | None = None) -> int:
        """Move expired transient features into the persistent store.
        Returns the number persisted.  Call periodically (the reference
        runs this on a scheduled executor per type)."""
        self.stream.consume(name)
        cache = self.stream.cache(name)
        now = self._clock() * 1000.0 if now_ms is None else now_ms
        expired = [fid for fid in cache.all_feature_ids()
                   if now - self._write_ms.get((name, fid), 0.0)
                   >= self.expiry_ms]
        lean = self._lean_store(name)
        if lean is not None and lean.multihost:
            # SPMD: the flush's delete/write are collectives — a
            # process with nothing expired must still enter them when
            # any peer flushes (agreed gate, empty local batch)
            from .parallel.multihost import agreed_int
            if agreed_int(len(expired), "max") == 0:
                return 0
        elif not expired:
            return 0
        batch = (cache.snapshot(expired) if expired
                 else FeatureBatch.empty(self.get_schema(name)))
        if lean is not None:
            # lean persistence (round-4 VERDICT #10): the generational
            # store mints implicit row ids, so the flusher owns the
            # fid→row upsert mapping — re-persisted fids tombstone
            # their old row, the batch appends with fresh row ids (the
            # DataStorePersistence role over the LSM-shaped store)
            old = [self._persisted_row.pop((name, str(f)), None)
                   for f in batch.ids]
            self.persistent.delete(
                name, [r for r in old if r is not None])
            base = len(lean.batch)
            prefix = lean.batch.id_prefix
            self.persistent.write(
                name, FeatureBatch(batch.sft, dict(batch.columns),
                                   ids=None, geoms=batch.geoms))
            for i, fid in enumerate(batch.ids):
                self._persisted_row[(name, str(fid))] = \
                    f"{prefix}{base + i}"
        elif len(batch):
            # upsert: a feature persisted earlier and then re-written
            # transiently must replace, not duplicate, its stored row
            if hasattr(self.persistent, "delete"):
                self.persistent.delete(name, batch.ids)
            self.persistent.write(name, batch)
        for fid in expired:
            cache.remove(fid)
            self._write_ms.pop((name, fid), None)
        return len(expired)

    # -- merged reads ------------------------------------------------------
    def query(self, name: str, query="INCLUDE") -> FeatureBatch:
        """Union of transient + persistent hits; transient wins on id."""
        self.stream.consume(name)
        q = query if isinstance(query, Query) else Query.of(query)
        transient = self.stream.query(name, q)
        persistent = self.persistent.query(name, q)
        if len(transient) == 0:
            return persistent
        if len(persistent) == 0:
            return transient
        if self._lean_store(name) is not None:
            # transient-wins by the persisted-row MAPPING: lean row ids
            # are store-minted, so the shadowed rows are the ones a
            # currently-transient fid previously persisted (a stream
            # fid that happens to look like a row id shadows nothing)
            mapped = {self._persisted_row.get((name, str(i)))
                      for i in transient.ids}
            keep = np.array([str(i) not in mapped
                             for i in persistent.ids])
        else:
            t_ids = set(str(i) for i in transient.ids)
            keep = np.array([str(i) not in t_ids
                             for i in persistent.ids])
        merged = transient.concat(persistent.take(np.flatnonzero(keep)))
        if q.max_features is not None:
            merged = merged.take(np.arange(min(q.max_features, len(merged))))
        return merged

    def count(self, name: str) -> int:
        return len(self.query(name))
