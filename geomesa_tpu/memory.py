"""In-memory indexed feature engine with incremental maintenance.

The analog of the reference's geomesa-memory module — GeoCQEngine
(memory/cqengine/GeoCQEngine.scala): a CQEngine-backed feature
collection with per-attribute indexes plus geo predicates, used where
features churn constantly (the Kafka live cache).  Unlike the
TpuDataStore (bulk-sorted device indexes, rebuild-on-write), this engine
maintains hash/sorted/spatial indexes incrementally per insert/remove —
the streaming-update trade-off the reference makes the same way.

Index selection: equality/IN → hash index; range → sorted index (rebuilt
lazily per query after mutations, amortized); bbox → bucket grid; other
filters fall back to a full scan with vectorized evaluation.
"""

from __future__ import annotations

import bisect
import threading

import numpy as np

from .features.batch import FeatureBatch
from .features.feature_type import FeatureType
from .filters import ast as fast
from .filters.evaluate import evaluate_filter
from .utils.spatial_index import BucketIndex

__all__ = ["GeoCQEngine"]


class _HashIndex:
    """value → set(fid); equality/IN lookups (CQEngine HashIndex)."""

    def __init__(self):
        self.by_value: dict = {}

    def insert(self, fid, value):
        self.by_value.setdefault(value, set()).add(fid)

    def remove(self, fid, value):
        s = self.by_value.get(value)
        if s is not None:
            s.discard(fid)
            if not s:
                del self.by_value[value]

    def equals(self, value) -> set:
        return set(self.by_value.get(value, ()))

    def isin(self, values) -> set:
        out: set = set()
        for v in values:
            out |= self.by_value.get(v, set())
        return out


class _SortedIndex:
    """Sorted (value, fid) pairs for range queries (NavigableIndex);
    rebuilt lazily after mutations — O(n log n) on first range query,
    O(log n + k) per query after."""

    def __init__(self):
        self._pairs: list = []
        self._keys: list = []
        self._stale = False

    def insert(self, fid, value):
        self._stale = True

    def remove(self, fid, value):
        self._stale = True

    def _rebuild(self, live: dict):
        self._pairs = sorted((v, f) for f, v in live.items() if v is not None)
        self._keys = [p[0] for p in self._pairs]
        self._stale = False

    def range(self, live: dict, lo, hi, lo_inc=True, hi_inc=True) -> set:
        if self._stale:
            self._rebuild(live)
        keys = self._keys
        i = (bisect.bisect_left(keys, lo) if lo_inc
             else bisect.bisect_right(keys, lo)) if lo is not None else 0
        j = (bisect.bisect_right(keys, hi) if hi_inc
             else bisect.bisect_left(keys, hi)) if hi is not None else len(keys)
        return {f for _, f in self._pairs[i:j]}


class GeoCQEngine:
    """Incrementally-indexed in-memory feature collection."""

    def __init__(self, sft: FeatureType):
        self.sft = sft
        # one lock serializes mutation vs. query — the engine is built for
        # churny live-cache use where readers and the consumer thread race
        self._lock = threading.RLock()
        self._features: dict[str, dict] = {}       # fid → attribute dict
        self._xy: dict[str, tuple] = {}            # fid → (x, y)
        self._spatial = BucketIndex()
        self._hash: dict[str, _HashIndex] = {}
        self._sorted: dict[str, _SortedIndex] = {}
        for a in sft.attributes:
            if a.is_geometry:
                continue
            self._hash[a.name] = _HashIndex()
            if a.type in ("int", "long", "float", "double", "date"):
                self._sorted[a.name] = _SortedIndex()

    def __len__(self) -> int:
        return len(self._features)

    # -- mutation ----------------------------------------------------------
    def insert(self, fid: str, attrs: dict, x: float, y: float):
        """Insert or replace one feature (the live-cache upsert)."""
        with self._lock:
            self._insert(fid, attrs, x, y)

    def _insert(self, fid: str, attrs: dict, x: float, y: float):
        fid = str(fid)
        if fid in self._features:
            self._remove(fid)
        self._features[fid] = attrs
        self._xy[fid] = (float(x), float(y))
        self._spatial.insert(fid, float(x), float(y))
        for name, idx in self._hash.items():
            idx.insert(fid, attrs.get(name))
        for name, idx in self._sorted.items():
            idx.insert(fid, attrs.get(name))

    def insert_batch(self, batch: FeatureBatch):
        x, y = batch.geom_xy()
        names = [a.name for a in self.sft.attributes if not a.is_geometry]
        cols = {n: batch.column(n) for n in names if n in batch.columns}
        with self._lock:
            for i in range(len(batch)):
                attrs = {n: c[i] for n, c in cols.items()}
                self._insert(str(batch.ids[i]), attrs, x[i], y[i])

    def remove(self, fid: str) -> bool:
        with self._lock:
            return self._remove(fid)

    def _remove(self, fid: str) -> bool:
        fid = str(fid)
        attrs = self._features.pop(fid, None)
        if attrs is None:
            return False
        self._xy.pop(fid, None)
        self._spatial.remove(fid)
        for name, idx in self._hash.items():
            idx.remove(fid, attrs.get(name))
        for name, idx in self._sorted.items():
            idx.remove(fid, attrs.get(name))
        return True

    def clear(self):
        with self._lock:
            # reset in place — replacing the lock itself would let an
            # in-flight reader race a post-clear writer
            self._features.clear()
            self._xy.clear()
            self._spatial.clear()
            for idx in self._hash.values():
                idx.by_value.clear()
            for idx in self._sorted.values():
                idx._pairs, idx._keys, idx._stale = [], [], False

    # -- query -------------------------------------------------------------
    def query(self, filt) -> FeatureBatch:
        """Evaluate a Filter/ECQL over the collection using the best
        available index; returns a columnar batch of the hits."""
        from .filters.ecql import parse_ecql
        if isinstance(filt, str):
            filt = parse_ecql(filt)
        with self._lock:
            ids = self._candidates(filt)
            if ids is None:
                ids = set(self._features)
            batch = self._to_batch(sorted(ids))
        if len(batch) == 0:
            return batch
        mask = evaluate_filter(filt, batch)
        return batch.take(np.flatnonzero(mask))

    def _live_values(self, attr: str) -> dict:
        return {fid: attrs.get(attr)
                for fid, attrs in self._features.items()}

    def _candidates(self, f) -> set | None:
        """Index-driven candidate set; None = no usable index (full scan).
        Always a superset of the true hits (exact filter re-check runs
        vectorized afterwards)."""
        if isinstance(f, fast.And):
            best = None
            for part in f.filters:
                c = self._candidates(part)
                if c is not None:
                    best = c if best is None else (best & c)
            return best
        if isinstance(f, fast.Or):
            out: set = set()
            for part in f.filters:
                c = self._candidates(part)
                if c is None:
                    return None
                out |= c
            return out
        if isinstance(f, fast.BBox):
            return set(self._spatial.query(f.xmin, f.ymin, f.xmax, f.ymax))
        if isinstance(f, (fast.Intersects, fast.Within, fast.DWithin)):
            env = f.geometry.envelope
            pad = getattr(f, "distance", 0.0)
            return set(self._spatial.query(env.xmin - pad, env.ymin - pad,
                                           env.xmax + pad, env.ymax + pad))
        if isinstance(f, fast.PropertyCompare) and f.prop in self._hash:
            if f.op == "=":
                return self._hash[f.prop].equals(f.value)
            if f.op in ("<", "<=", ">", ">=") and f.prop in self._sorted:
                live = self._live_values(f.prop)
                if f.op == "<":
                    return self._sorted[f.prop].range(live, None, f.value,
                                                      hi_inc=False)
                if f.op == "<=":
                    return self._sorted[f.prop].range(live, None, f.value)
                if f.op == ">":
                    return self._sorted[f.prop].range(live, f.value, None,
                                                      lo_inc=False)
                return self._sorted[f.prop].range(live, f.value, None)
            return None
        if isinstance(f, fast.In) and f.prop in self._hash:
            return self._hash[f.prop].isin(f.values)
        if isinstance(f, fast.Between) and f.prop in self._sorted:
            return self._sorted[f.prop].range(self._live_values(f.prop),
                                              f.lo, f.hi)
        if isinstance(f, fast.During) and f.prop in self._sorted:
            return self._sorted[f.prop].range(self._live_values(f.prop),
                                              f.lo_ms, f.hi_ms)
        if isinstance(f, fast.IdFilter):
            return {i for i in map(str, f.ids) if i in self._features}
        return None

    def _to_batch(self, fids: list) -> FeatureBatch:
        if not fids:
            return FeatureBatch.empty(self.sft)
        data: dict = {}
        for a in self.sft.attributes:
            if a.is_geometry:
                if a.name == self.sft.default_geom:
                    xs = np.array([self._xy[f][0] for f in fids])
                    ys = np.array([self._xy[f][1] for f in fids])
                    data[a.name] = (xs, ys)
                continue
            data[a.name] = np.asarray(
                [self._features[f].get(a.name) for f in fids], dtype=object)
        return FeatureBatch.from_dict(self.sft, data,
                                      ids=np.asarray(fids, dtype=object))
