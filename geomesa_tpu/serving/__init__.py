"""The fused serving plane (ISSUE 17).

The store answers queries one at a time; a *server* answers thousands
of concurrent ones.  This package closes that gap with query fusion:
concurrent compatible queries — same schema, lean z3 point path, same
bbox+time-window predicate shape, same visibility/mask state — coalesce
into ONE batched decompose + multi-window device scan (the existing
``query_many`` program), and the per-request hit positions demultiplex
back out bit-exact against solo execution.  Incompatible queries
(interceptors, non-point schemas, id filters, projections, sorts)
bypass untouched.

Layered over the planes that already exist:

* per-tenant deficit-weighted round-robin batch assembly over the
  PR 8 :class:`~geomesa_tpu.resilience.AdmissionGate` (tenant from a
  ``TENANT`` query hint or the web ``X-Tenant`` header) so one hot
  tenant cannot starve the queue;
* cooperative deadlines compose — expired riders drop before dispatch,
  a fused batch runs under its members' minimum remaining margin, and
  a timed-out rider never poisons the batch (survivors re-dispatch);
* ``serving.*`` spans/metrics (fan-in ratio, coalesce wait, batch
  size, per-tenant shed) flow into ``/metrics.prom``.

Entry points: :meth:`TpuDataStore.query_fused` and the web
``GET /query`` Arrow stream (which picks its hit positions up from the
demuxed fused result).  docs/serving.md is the operator contract.
"""

from __future__ import annotations

from .fusion import FusedOutcome, FusionScheduler, extract_fused_window

__all__ = ["FusionScheduler", "FusedOutcome", "extract_fused_window"]
