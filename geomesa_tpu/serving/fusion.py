"""Query fusion: N concurrent compatible queries → ONE device dispatch.

The scheduler is a per-compatibility-key coalescing queue.  The first
arrival becomes the batch LEADER and lingers up to
``geomesa.serving.fuse.window.ms`` collecting riders (or until
``geomesa.serving.fuse.max.batch`` requests are queued); it then
assembles a batch by deficit-weighted round-robin across per-tenant
FIFO queues, runs the store's batched multi-window program once on its
own thread, and demultiplexes per-request hit positions back to every
member.  Riders left in the queue promote a new leader and form the
next batch — under sustained load the plane pipelines batch after
batch with no dedicated scheduler thread.

Deadline composition (ISSUE 16 semantics carry over):

* a rider whose deadline expires while QUEUED drops out before
  dispatch (``QueryTimeout`` or empty-partial, per its own flag);
* a batch dispatches under its members' MINIMUM remaining margin, in
  partial mode — expiry stops the scan at a yield point instead of
  poisoning every member;
* when the batch scope expires, exactly the members whose own
  deadlines passed time out; survivors' partial hits are DISCARDED and
  the survivors re-dispatch in a follow-up batch (each round retires
  at least the minimum-margin member, so the loop is bounded).

Admission interplay: the scheduler never touches the gate — every
entry point acquires its own token BEFORE submitting (FIFO-fair after
this PR), so the in-flight gauge stays truthful per request and a
fused batch can never self-deadlock a small gate.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from dataclasses import dataclass

import numpy as np

from ..config import ServingProperties
from ..filters.ast import And, BBox, During, Or
from ..metrics import (SERVING_BATCH_WINDOWS, SERVING_COALESCE_MS,
                       SERVING_FANIN, SERVING_FUSED_BATCHES,
                       SERVING_FUSED_REQUESTS, SERVING_RIDER_EXPIRED,
                       SERVING_TENANT_SHED)
from ..metrics import registry as _registry
from ..resilience import (Backpressure, CancelScope, QueryTimeout,
                          deadline_scope)

__all__ = ["FusionScheduler", "FusedOutcome", "extract_fused_window"]

_SEGMENT_RE = re.compile(r"[^A-Za-z0-9_:\-]")


def _tenant_segment(tenant: str) -> str:
    """Tenant id as a metric-key segment (the naming contract allows
    ``[A-Za-z0-9_:-]``; anything else folds to ``_``)."""
    return _SEGMENT_RE.sub("_", tenant) or "default"


def extract_fused_window(sft, f):
    """Invert the filter shapes ``query_windows`` builds back into one
    ``(boxes, lo_ms, hi_ms)`` window, or None when the filter is not a
    pure bbox(+time) predicate over this schema's default geometry.

    Accepted shapes (exactly what the per-window fallback emits, so a
    fused scan answers the same question the planner would):
    ``BBox(geom, …)``, ``Or((BBox, …))``, and either of those wrapped
    in ``And((spatial, During(dtg, lo, hi)))``.
    """
    lo = hi = None
    spatial = f
    if isinstance(f, And):
        if len(f.filters) != 2:
            return None
        a, b = f.filters
        if isinstance(b, During):
            spatial, temporal = a, b
        elif isinstance(a, During):
            spatial, temporal = b, a
        else:
            return None
        if not sft.dtg_field or temporal.prop != sft.dtg_field:
            return None
        lo, hi = temporal.lo_ms, temporal.hi_ms
    parts = spatial.filters if isinstance(spatial, Or) else (spatial,)
    if not parts:
        return None
    boxes = []
    for p in parts:
        if not isinstance(p, BBox) or p.prop != sft.geom_field:
            return None
        boxes.append((p.xmin, p.ymin, p.xmax, p.ymax))
    return tuple(boxes), lo, hi


@dataclass
class FusedOutcome:
    """What ``submit`` hands back: the member's exact hit positions and
    whether its deadline expired (partial mode only — without
    ``partial`` an expiry raises instead).  ``coalesce_ms`` is this
    member's wait inside the fuse window; ``dispatch_ms`` the wall time
    of the batch round(s) it rode — the caller stamps both onto its
    root span so the SLO plane can attribute a rider's wall clock
    (riders block in ``submit`` while the LEADER's thread runs the
    batch, so their own traces record no scan spans)."""

    positions: np.ndarray
    timed_out: bool = False
    coalesce_ms: float = 0.0
    dispatch_ms: float = 0.0


class _Member:
    __slots__ = ("window", "tenant", "scope", "partial", "enqueued_at",
                 "queued", "done", "positions", "error", "timed_out",
                 "coalesce_ms", "dispatch_ms")

    def __init__(self, window, tenant, scope, partial):
        self.window = window
        self.tenant = tenant
        self.scope = scope
        self.partial = partial
        self.enqueued_at = 0.0
        self.queued = True
        self.done = False
        self.positions = None
        self.error = None
        self.timed_out = False
        self.coalesce_ms = 0.0
        self.dispatch_ms = 0.0


class _FuseQueue:
    """One compatibility key's coalescing state: per-tenant FIFO
    deques, the deficit-round-robin rotation, and the current leader."""

    __slots__ = ("tenants", "rr", "deficit", "size", "leader")

    def __init__(self):
        self.tenants: dict[str, deque] = {}
        self.rr: list[str] = []
        self.deficit: dict[str, int] = {}
        self.size = 0
        self.leader: _Member | None = None


class FusionScheduler:
    """Coalesce concurrent compatible queries into shared dispatches.

    One instance per datastore; ``submit`` blocks the calling thread
    until its request's fused result is ready (the leader role rotates
    among request threads — there is no scheduler thread to die)."""

    def __init__(self):
        self._cond = threading.Condition(threading.Lock())
        self._queues: dict = {}

    # -- public -----------------------------------------------------------
    def submit(self, key, window, dispatch, *, scope: CancelScope | None
               = None, partial: bool = False, tenant: str = "",
               schema: str = "") -> FusedOutcome:
        """Enqueue one request and block until its demuxed positions
        are ready.  ``dispatch`` is the batched program: it takes a
        list of ``(boxes, lo, hi)`` windows and returns one position
        array per window (the datastore binds schema + capacity
        bucketing into it).  Raises :class:`Backpressure` when this
        tenant's queue is at its ceiling, :class:`QueryTimeout` when
        the member's deadline expires without ``partial``."""
        window_ms = float(ServingProperties.FUSE_WINDOW_MS.get() or 0.0)
        max_batch = max(1, int(ServingProperties.FUSE_MAX_BATCH.get() or 1))
        queue_max = int(ServingProperties.TENANT_QUEUE_MAX.get() or 0)
        quantum = max(1, int(ServingProperties.TENANT_QUANTUM.get() or 1))
        me = _Member(window, tenant, scope, partial)
        with self._cond:
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = _FuseQueue()
            dq = q.tenants.get(tenant)
            if queue_max > 0 and dq is not None and len(dq) >= queue_max:
                _registry.counter(SERVING_TENANT_SHED).inc()
                _registry.counter(
                    f"{SERVING_TENANT_SHED}.{_tenant_segment(tenant)}").inc()
                raise Backpressure(
                    f"serving queue full for tenant "
                    f"{tenant or 'default'!r} ({queue_max} queued)",
                    retry_after_s=max(0.05, window_ms / 1000.0))
            if dq is None:
                dq = q.tenants[tenant] = deque()
                q.rr.append(tenant)
            me.enqueued_at = time.perf_counter()
            dq.append(me)
            q.size += 1
            if q.leader is None:
                q.leader = me
            elif q.size >= max_batch:
                # a full batch dispatches immediately — wake the
                # collecting leader out of its linger wait
                self._cond.notify_all()
            batch = None
            while batch is None:
                if me.done:
                    return self._finish(me)
                if q.leader is me:
                    batch = self._collect(q, me, window_ms, max_batch,
                                          quantum)
                    q.leader = None
                    self._cond.notify_all()
                    break
                # rider: wait for my batch's result (or my own deadline)
                if (me.queued and me.scope is not None
                        and me.scope.poll()):
                    self._unlink(q, me)
                    me.done, me.timed_out = True, True
                    me.coalesce_ms = (time.perf_counter()
                                      - me.enqueued_at) * 1000.0
                    _registry.counter(SERVING_RIDER_EXPIRED).inc()
                    return self._finish(me)
                rem = None
                if me.scope is not None:
                    r = me.scope.remaining_ms()
                    rem = None if r is None else max(r / 1000.0, 0.0005)
                self._cond.wait(rem)
                if q.leader is None and not me.done and me.queued:
                    # leader promotion: the previous leader took its
                    # batch and left; the first queued waiter to wake
                    # leads the next one
                    q.leader = me
        # lock dropped — run the fused dispatch on this (leader) thread
        try:
            self._run_batch(batch, dispatch, schema)
        finally:
            with self._cond:
                self._cond.notify_all()
        return self._finish(me)

    @property
    def queued(self) -> int:
        with self._cond:
            return sum(q.size for q in self._queues.values())

    # -- internals --------------------------------------------------------
    def _collect(self, q, leader, window_ms, max_batch, quantum):
        """Leader linger: wait out the fuse window (bounded by the
        leader's own remaining deadline margin) or a full batch, then
        assemble.  Lock held throughout (waits release it)."""
        deadline = leader.enqueued_at + window_ms / 1000.0
        if leader.scope is not None:
            r = leader.scope.remaining_ms()
            if r is not None:
                deadline = min(deadline,
                               time.perf_counter() + r / 1000.0)
        while q.size < max_batch:
            w = deadline - time.perf_counter()
            if w <= 0:
                break
            self._cond.wait(w)
        return self._assemble(q, leader, max_batch, quantum)

    def _assemble(self, q, leader, max_batch, quantum):
        """Deficit-weighted round-robin batch assembly: the leader is
        force-included first, then each tenant in rotation earns
        ``quantum`` window-credits per pass and dequeues that many
        requests — a flooding tenant drains one quantum per pass while
        every other tenant's head-of-line request rides the same batch.
        Idle tenants carry no credit (deficit resets when their queue
        empties, classic DRR)."""
        batch = [leader]
        self._unlink(q, leader)
        while q.size > 0 and len(batch) < max_batch:
            for tenant in list(q.rr):
                dq = q.tenants.get(tenant)
                if dq is None or not dq:
                    continue
                q.deficit[tenant] = q.deficit.get(tenant, 0) + quantum
                while dq and q.deficit[tenant] > 0 \
                        and len(batch) < max_batch:
                    m = dq.popleft()
                    m.queued = False
                    q.size -= 1
                    q.deficit[tenant] -= 1
                    if m.scope is not None and m.scope.poll():
                        # expired while queued: drop before dispatch
                        m.done, m.timed_out = True, True
                        m.coalesce_ms = (time.perf_counter()
                                         - m.enqueued_at) * 1000.0
                        _registry.counter(SERVING_RIDER_EXPIRED).inc()
                        continue
                    batch.append(m)
                if not dq:
                    q.deficit[tenant] = 0
                    del q.tenants[tenant]
                    q.rr.remove(tenant)
                if len(batch) >= max_batch:
                    break
        # rotate so the same tenant is not always served first
        if q.rr:
            q.rr.append(q.rr.pop(0))
        return batch

    def _unlink(self, q, m):
        if not m.queued:
            return
        m.queued = False
        dq = q.tenants.get(m.tenant)
        if dq is not None:
            try:
                dq.remove(m)
                q.size -= 1
            except ValueError:
                pass
            if not dq:
                q.deficit[m.tenant] = 0
                del q.tenants[m.tenant]
                q.rr.remove(m.tenant)

    def _run_batch(self, batch, dispatch, schema):
        """Execute one fused batch (leader's thread, no scheduler
        lock).  Sets every member's positions/error/timed_out and
        ``done``; the caller notifies waiters afterwards."""
        from ..obs import span as obs_span
        pending = [m for m in batch if not m.done]
        first_round = True
        while pending:
            margin = None
            for m in pending:
                if m.scope is not None:
                    r = m.scope.remaining_ms()
                    if r is not None:
                        margin = r if margin is None else min(margin, r)
            windows = [m.window for m in pending]
            t0 = time.perf_counter()
            if first_round:
                for m in pending:
                    m.coalesce_ms = (t0 - m.enqueued_at) * 1000.0
                    _registry.timer(SERVING_COALESCE_MS).update(
                        m.coalesce_ms)
                first_round = False
            try:
                with obs_span("serving.fuse", schema=schema,
                              batch=len(pending),
                              windows=len(windows)) as sp:
                    if margin is not None:
                        # the batch runs under its members' minimum
                        # remaining margin, in partial mode: expiry
                        # stops the scan at a yield point — it never
                        # raises out of a shared dispatch
                        bscope = CancelScope(margin, True)
                        with deadline_scope(scope=bscope):
                            hits = dispatch(windows)
                        expired_mid = bscope.timed_out
                    else:
                        hits = dispatch(windows)
                        expired_mid = False
                    sp.set_attr("hits",
                                int(sum(len(h) for h in hits)))
                    sp.set_attr("partial", bool(expired_mid))
            except BaseException as e:
                for m in pending:
                    m.error = e
                    m.done = True
                return
            round_ms = (time.perf_counter() - t0) * 1000.0
            for m in pending:
                # accumulate across re-dispatch rounds: a survivor's
                # total dispatch wall is every round it rode
                m.dispatch_ms += round_ms
            _registry.counter(SERVING_FUSED_BATCHES).inc()
            _registry.counter(SERVING_FUSED_REQUESTS).inc(len(pending))
            _registry.histogram(SERVING_FANIN).update(float(len(pending)))
            _registry.histogram(SERVING_BATCH_WINDOWS).update(
                float(len(windows)))
            if not expired_mid:
                for m, h in zip(pending, hits):
                    m.positions = h
                    m.done = True
                return
            # the minimum-margin member(s) expired mid-dispatch: they
            # time out (their partial hits are exact over what WAS
            # scanned); survivors' results may be short of windows that
            # never scanned — discard and re-dispatch the survivors
            # under the new (longer) minimum margin.  Each round
            # retires at least one member, so this terminates.
            survivors = []
            for m, h in zip(pending, hits):
                if m.scope is not None and m.scope.poll():
                    m.timed_out = True
                    m.positions = h if m.partial else None
                    m.done = True
                    _registry.counter(SERVING_RIDER_EXPIRED).inc()
                else:
                    survivors.append(m)
            if len(survivors) == len(pending):
                # cannot happen (the batch scope's deadline is never
                # earlier than the min member deadline), but a stuck
                # loop must fail loud rather than spin
                for m in pending:
                    m.error = RuntimeError(
                        "fused batch expired with no expired member")
                    m.done = True
                return
            pending = survivors

    def _finish(self, me) -> FusedOutcome:
        if me.error is not None:
            raise me.error
        if me.timed_out:
            if me.partial:
                pos = (me.positions if me.positions is not None
                       else np.empty(0, dtype=np.int64))
                return FusedOutcome(pos, timed_out=True,
                                    coalesce_ms=round(me.coalesce_ms, 3),
                                    dispatch_ms=round(me.dispatch_ms, 3))
            raise QueryTimeout(
                "fused query deadline expired"
                + ("" if me.scope is None else
                   f" after {me.scope.elapsed_ms():.1f} ms"),
                elapsed_ms=(None if me.scope is None
                            else me.scope.elapsed_ms()))
        return FusedOutcome(me.positions, timed_out=False,
                            coalesce_ms=round(me.coalesce_ms, 3),
                            dispatch_ms=round(me.dispatch_ms, 3))
