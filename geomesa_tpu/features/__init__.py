"""Feature model: schemas and columnar feature batches.

Replaces the reference's SimpleFeatureType/SimpleFeature object model
(geomesa-utils/.../geotools/SimpleFeatureTypes.scala,
geomesa-features/.../ScalaSimpleFeature.scala) with a TPU-first design:
schemas are lightweight descriptors, and feature data is a
structure-of-arrays batch (numpy/jax columns) rather than per-row objects
— the layout device kernels consume directly.  Row serialization codecs
(Kryo/Avro) are replaced by columnar interchange (arrow / parquet via
pyarrow) at the edges.
"""

from .batch import FeatureBatch
from .feature_type import AttributeSpec, FeatureType, parse_spec
