"""LeanBatch: chunked columnar storage for the store's lean profile.

The reference serves "tens of billions of points" through one DataStore
facade (docs/user/introduction.rst:24, GeoMesaDataStore.scala:48)
because rows live on the cluster, not the client.  The TPU-native
analog at single-host scale: the schema's columns accumulate as CHUNK
LISTS of numpy arrays (one per write, concatenated lazily per column),
feature ids are IMPLICIT (the id of row ``r`` is ``str(r)`` — minted
monotonically by append order, never reused), and query results
materialize real :class:`FeatureBatch` objects only for the HIT rows.

This keeps the per-write cost O(chunk) — a FeatureBatch.concat per
write would be O(n) each, O(n²) for a streaming build — and avoids the
two O(n)-objects killers at 100M+ rows: an object-dtype id array
(~60 B/row of pointer+string overhead) and per-write visibility
relabeling.

Point schemas with a time attribute ride the lean Z3 index; round-5
adds non-point schemas (polygons/lines) riding the generational lean
XZ2 index — their packed geometries accumulate as chunk lists too,
concatenated lazily (round-4 VERDICT #4's XZ parity at scale).
"""

from __future__ import annotations

import numpy as np

from .batch import FeatureBatch
from .feature_type import FeatureType

__all__ = ["LeanBatch", "ChunkView"]


class ChunkView:
    """Minimal column-view 'batch' for streaming paths that never need
    feature ids (stats observe, lean index appends): ``len``,
    ``column``, ``columns``, ``geom_xy``, ``take``.  Avoids the O(chunk)
    id-string materialization a real FeatureBatch would pay."""

    def __init__(self, sft: FeatureType, columns: dict, n: int,
                 geoms=None):
        for name, col in columns.items():
            if len(col) != n:
                # the invariant FeatureBatch.__post_init__ enforces —
                # a ragged chunk would silently misalign the store
                raise ValueError(f"column {name!r} has length "
                                 f"{len(col)}, expected {n}")
        if geoms is not None and len(geoms) != n:
            raise ValueError(f"geometry column has length {len(geoms)},"
                             f" expected {n}")
        self.sft = sft
        self.columns = columns
        #: packed non-point geometries riding the chunk (round-5: lean
        #: XZ2 schemas stream polygons through the same write path)
        self.geoms = geoms
        self._n = n

    def __len__(self) -> int:
        return self._n

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def geom_xy(self, name: str | None = None):
        name = name or self.sft.default_geom
        return self.columns[f"{name}_x"], self.columns[f"{name}_y"]

    def take(self, positions, columns=None) -> "ChunkView":
        positions = np.asarray(positions)
        return ChunkView(self.sft,
                         {k: v[positions] for k, v in self.columns.items()
                          if columns is None or k in columns},
                         len(positions),
                         geoms=(self.geoms.take(positions)
                                if self.geoms is not None else None))


class LeanBatch:
    """FeatureBatch-compatible chunked column store (module doc).

    Supports the planner surface: ``len``, ``column``, ``columns``,
    ``geom_xy``, ``geom_bbox`` (running envelope), ``take`` (→ real
    FeatureBatch of the requested rows).  ``ids`` raises — any code
    path touching the full id array would silently materialize
    O(n) Python strings; the planner materializes ids per-result via
    ``take`` instead."""

    def __init__(self, sft: FeatureType, id_prefix: str = ""):
        self.sft = sft
        self._chunks: dict[str, list] = {}
        self._flat: dict[str, np.ndarray] = {}
        self._n = 0
        #: packed (non-point) geometry chunks, lazily concatenated —
        #: None for point schemas (their geometry is the x/y columns)
        self._geom_chunks: list = []
        self._geoms_flat = None
        #: implicit-id prefix — multihost stores prefix per process
        #: (``p{proc}.``) so local row ids stay globally unique
        self.id_prefix = id_prefix
        #: running dataset envelope (xmin, ymin, xmax, ymax)
        self.envelope: tuple | None = None

    @property
    def geoms(self):
        """Packed non-point geometries (lazy chunk concat, kept flat —
        one host copy); None for point schemas."""
        if not self._geom_chunks:
            return None
        if self._geoms_flat is None:
            from ..geometry.packed import PackedGeometry
            flat = PackedGeometry.concat_many(self._geom_chunks)
            self._geoms_flat = flat
            self._geom_chunks = [flat]
        return self._geoms_flat

    def __len__(self) -> int:
        return self._n

    # -- ingest -----------------------------------------------------------
    def append_batch(self, fb: FeatureBatch) -> None:
        """Append one write's columns by reference (no copy)."""
        if self._chunks and set(fb.columns) != set(self._chunks):
            raise ValueError(
                "lean writes must provide the same columns every time "
                f"(have {sorted(self._chunks)}, got {sorted(fb.columns)})")
        for k, v in fb.columns.items():
            self._chunks.setdefault(k, []).append(np.asarray(v))
            self._flat.pop(k, None)
        self._n += len(fb)
        if fb.geoms is not None:
            self._geom_chunks.append(fb.geoms)
            self._geoms_flat = None
            bb = fb.geoms.bbox
            if len(bb):
                self._fold_env(float(bb[:, 0].min()),
                               float(bb[:, 1].min()),
                               float(bb[:, 2].max()),
                               float(bb[:, 3].max()))
            return
        gx, gy = fb.geom_xy(self.sft.geom_field)
        if len(gx):
            self._fold_env(float(np.min(gx)), float(np.min(gy)),
                           float(np.max(gx)), float(np.max(gy)))

    def host_bytes(self) -> int:
        """Host RAM of the column store (the storage report's
        ``storage.<schema>.batch_bytes`` source, obs/resource):
        attribute/coordinate chunk arrays plus packed-geometry SoA
        buffers, deduplicated by identity so the finalize step (which
        keeps the flat array in BOTH ``_flat`` and ``_chunks``) never
        double-counts.  Object-dtype columns count pointer width only
        (their string payloads are Python-heap, not column store)."""
        total, seen = 0, set()
        for parts in self._chunks.values():
            for a in parts:
                if id(a) not in seen:
                    seen.add(id(a))
                    total += int(getattr(a, "nbytes", 0))
        for g in self._geom_chunks:
            for a in (g.kinds, g.coords, g.ring_offsets,
                      g.part_ring_offsets, g.geom_part_offsets, g.bbox):
                if id(a) not in seen:
                    seen.add(id(a))
                    total += int(getattr(a, "nbytes", 0))
        return total

    def _fold_env(self, lo_x, lo_y, hi_x, hi_y):
        if self.envelope is None:
            self.envelope = (lo_x, lo_y, hi_x, hi_y)
        else:
            e = self.envelope
            self.envelope = (min(e[0], lo_x), min(e[1], lo_y),
                             max(e[2], hi_x), max(e[3], hi_y))

    # -- column access ----------------------------------------------------
    def column(self, name: str) -> np.ndarray:
        """Finalized (flat) column; concatenates chunks once and keeps
        the single flat array (chunk refs dropped → one host copy)."""
        if name not in self._flat:
            parts = self._chunks[name]
            flat = parts[0] if len(parts) == 1 else np.concatenate(parts)
            self._flat[name] = flat
            self._chunks[name] = [flat]
        return self._flat[name]

    @property
    def columns(self) -> dict:
        return {k: self.column(k) for k in self._chunks}

    def geom_xy(self, name: str | None = None):
        name = name or self.sft.default_geom
        return self.column(f"{name}_x"), self.column(f"{name}_y")

    def geom_bbox(self, name: str | None = None) -> np.ndarray:
        """Per-feature bboxes — packed envelopes for non-point schemas,
        synthesized from x/y for points.  O(n·4) floats: callers at
        lean scale should prefer ``envelope`` (the store's get_bounds
        does)."""
        if self.geoms is not None:
            return self.geoms.bbox
        x, y = self.geom_xy(name)
        return np.stack([x, y, x, y], axis=1)

    @property
    def ids(self):
        raise AttributeError(
            "LeanBatch has implicit ids (row r ⇔ str(r)); materializing "
            "the full id array is O(n) strings — use take(rows) for "
            "result ids, or row_ids(rows)")

    def row_ids(self, rows: np.ndarray) -> np.ndarray:
        """Feature ids of the given rows (hits-sized)."""
        p = self.id_prefix
        return np.array([f"{p}{int(r)}" for r in rows], dtype=object)

    def row_ids_vec(self, rows: np.ndarray) -> np.ndarray:
        """Feature ids of the given rows as a fixed-width unicode
        array — the vectorized twin of :meth:`row_ids` (identical
        strings, ZERO per-row Python objects: int→str conversion runs
        inside numpy, and the Arrow encoder consumes the U-dtype
        buffer directly).  The streaming result path (arrow/stream,
        ISSUE 14) mints every feature id this way."""
        ids = np.asarray(rows, dtype=np.int64).astype("U20")
        if self.id_prefix:
            ids = np.char.add(self.id_prefix, ids)
        return ids

    def take_view(self, positions: np.ndarray,
                  columns=None) -> ChunkView:
        """Hit-row gather WITHOUT feature-id materialization: one
        vectorized numpy take per requested column (+ packed
        geometries), returning a :class:`ChunkView`.  This is the
        row-gather of the Arrow-native result path and of the
        planner's residual re-check — the two places the O(hits)
        id-string cost of :meth:`take` used to dominate result
        construction (ISSUE 14)."""
        positions = np.asarray(positions, dtype=np.int64)
        names = (self._chunks if columns is None
                 else [k for k in self._chunks if k in columns])
        cols = {k: self.column(k)[positions] for k in names}
        geoms = (self.geoms.take(positions)
                 if self.geoms is not None else None)
        return ChunkView(self.sft, cols, len(positions), geoms=geoms)

    def take(self, positions: np.ndarray,
             columns=None) -> FeatureBatch:
        """Materialize a real FeatureBatch for the requested rows (the
        only place full feature rows come into existence).  ``columns``
        restricts which physical columns materialize — the planner's
        projection push-down: ``sum(score)`` over 100M hit rows copies
        ONE float64 column, not the geometry columns too."""
        positions = np.asarray(positions, dtype=np.int64)
        names = (self._chunks if columns is None
                 else [k for k in self._chunks if k in columns])
        cols = {k: self.column(k)[positions] for k in names}
        geoms = (self.geoms.take(positions)
                 if self.geoms is not None else None)
        return FeatureBatch(self.sft, cols, self.row_ids(positions),
                            geoms)

    def slice_view(self, lo: int, hi: int) -> "ChunkView":
        """Zero-copy row-range view (chunked stats recompute / export
        iterate these; no ids materialized)."""
        cols = {k: self.column(k)[lo:hi] for k in self._chunks}
        return ChunkView(self.sft, cols, hi - lo)
