"""Columnar feature batches (SoA), the unit of ingest and query results.

The TPU-first replacement for per-row SimpleFeatures + Kryo payloads
(geomesa-features/.../kryo/KryoFeatureSerializer.scala): features live as
parallel columns —

* point geometry → two float64 columns ``<geom>_x`` / ``<geom>_y``
* non-point geometry → a :class:`PackedGeometry` + a (N, 4) bbox column
* date → int64 epoch-millis
* string → numpy object array host-side (dictionary-encode on demand)
* numerics/bool → natural numpy dtypes

The reference's "lazy deserialization" trick (KryoBufferSimpleFeature
reading only touched attributes) becomes simply *column projection* —
touch only the columns a query needs.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..geometry.packed import PackedGeometry, pack_geometries
from .feature_type import FeatureType

__all__ = ["FeatureBatch", "build_columns"]

_DTYPES = {
    "int": np.int32,
    "long": np.int64,
    "float": np.float32,
    "double": np.float64,
    "bool": np.bool_,
    "date": np.int64,  # epoch millis
}


@dataclass
class FeatureBatch:
    """N features of one FeatureType as columns."""

    sft: FeatureType
    columns: dict                    # name -> np.ndarray (see module doc)
    ids: np.ndarray | None = None    # feature ids (object array of str) or None
    geoms: PackedGeometry | None = None  # packed non-point default geometry
    ids_explicit: bool = True        # False when ids were auto-generated

    def __post_init__(self):
        n = len(self)
        for name, col in self.columns.items():
            if len(col) != n:
                raise ValueError(
                    f"column {name!r} has length {len(col)}, expected {n}")
        if self.ids is None:
            self.ids = np.array([str(i) for i in range(n)], dtype=object)
            self.ids_explicit = False

    def __len__(self) -> int:
        if self.columns:
            return len(next(iter(self.columns.values())))
        return 0 if self.geoms is None else len(self.geoms)

    # -- constructors -----------------------------------------------------
    @classmethod
    def from_dict(cls, sft: FeatureType, data: dict, ids=None) -> "FeatureBatch":
        """Build from a dict of attribute name → values.

        Geometry attributes accept Geometry objects (packed automatically);
        the point default-geometry fast path accepts ``(x, y)`` tuples of
        arrays under the geometry attribute name.
        """
        columns, geoms = build_columns(sft, data)
        ids_arr = None if ids is None else np.asarray(ids, dtype=object)
        return cls(sft, columns, ids_arr, geoms, ids_explicit=ids is not None)

    @classmethod
    def empty(cls, sft: FeatureType) -> "FeatureBatch":
        """Zero-row batch with correctly-typed columns for every attribute
        (including the geometry x/y fast path) — safe to geom_xy/concat."""
        data: dict = {}
        for attr in sft.attributes:
            if attr.is_geometry:
                if attr.name == sft.default_geom:
                    data[attr.name] = ((np.empty(0), np.empty(0))
                                       if attr.type == "point" else [])
            elif attr.type == "date":
                data[attr.name] = np.empty(0, dtype=np.int64)
            elif attr.type in ("string", "bytes", "json"):
                data[attr.name] = np.empty(0, dtype=object)
            else:
                data[attr.name] = np.empty(0, dtype=_DTYPES[attr.type])
        return cls.from_dict(sft, data, ids=np.empty(0, dtype=object))

    # -- access -----------------------------------------------------------
    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def geom_xy(self, name: str | None = None):
        name = name or self.sft.default_geom
        return self.columns[f"{name}_x"], self.columns[f"{name}_y"]

    def geom_bbox(self, name: str | None = None) -> np.ndarray:
        name = name or self.sft.default_geom
        key = f"{name}_bbox"
        if key in self.columns:
            return self.columns[key]
        x, y = self.geom_xy(name)
        return np.stack([x, y, x, y], axis=1)

    def take(self, positions: np.ndarray,
             columns=None) -> "FeatureBatch":
        """Row subset (gather) — used to materialize query results.
        ``columns`` restricts which columns are gathered (projection
        push-down; ids and packed geometries still gather)."""
        cols = {k: v[positions] for k, v in self.columns.items()
                if columns is None or k in columns}
        geoms = None
        if self.geoms is not None:
            geoms = self.geoms.take(positions)
        return FeatureBatch(self.sft, cols, self.ids[positions], geoms)

    def concat(self, other: "FeatureBatch") -> "FeatureBatch":
        if other.sft.name != self.sft.name:
            raise ValueError("cannot concat batches of different schemas")
        cols = {
            k: np.concatenate([v, other.columns[k]]) for k, v in self.columns.items()
        }
        if (self.geoms is None) != (other.geoms is None):
            raise ValueError(
                "cannot concat: one batch has packed geometries, the other none")
        geoms = None
        if self.geoms is not None and other.geoms is not None:
            geoms = self.geoms.concat(other.geoms)
        return FeatureBatch(
            self.sft, cols, np.concatenate([self.ids, other.ids]), geoms)


def build_columns(sft: FeatureType, data: dict):
    """Normalize a dict of attribute values into the canonical column
    layout (module doc) — the shared ingest step of FeatureBatch.from_dict
    and the lean profile's chunked writes (which skip FeatureBatch id
    materialization entirely).  Returns ``(columns, packed_geoms)``."""
    columns: dict = {}
    geoms = None
    for attr in sft.attributes:
        if attr.name not in data:
            continue
        vals = data[attr.name]
        if attr.is_geometry:
            if attr.type == "point":
                # canonical point layout is the x/y fast path — whether
                # given as (x, y) arrays or Point objects — so batches
                # concat regardless of construction style
                if isinstance(vals, tuple):
                    x, y = vals
                elif (isinstance(vals, list) and vals
                      and isinstance(vals[0], (tuple, list))
                      and len(vals[0]) == 2
                      and not isinstance(vals[0][0], (tuple, list))):
                    # list of (x, y) coordinate pairs
                    arr = np.asarray(vals, dtype=np.float64)
                    x, y = arr[:, 0], arr[:, 1]
                else:
                    pts = (vals if isinstance(vals, PackedGeometry)
                           else pack_geometries(vals))
                    if pts.kinds.size and not (pts.kinds == 0).all():
                        raise ValueError(
                            f"attribute {attr.name!r} is typed Point but "
                            "got non-point geometries")
                    xy = pts.coords[pts.ring_offsets[:-1]] if pts.kinds.size \
                        else np.empty((0, 2))
                    x, y = xy[:, 0], xy[:, 1]
                columns[f"{attr.name}_x"] = np.asarray(x, dtype=np.float64)
                columns[f"{attr.name}_y"] = np.asarray(y, dtype=np.float64)
            else:
                packed = vals if isinstance(vals, PackedGeometry) else pack_geometries(vals)
                if attr.name == sft.default_geom:
                    geoms = packed
                columns[f"{attr.name}_bbox"] = packed.bbox
                if packed.kinds.size and (packed.kinds == 0).all():
                    # pure point column: also expose x/y fast path
                    pts = packed.coords[packed.ring_offsets[:-1]]
                    columns[f"{attr.name}_x"] = pts[:, 0]
                    columns[f"{attr.name}_y"] = pts[:, 1]
        elif attr.type == "date":
            vals = np.asarray(vals)
            if vals.dtype.kind == "M":
                vals = vals.astype("M8[ms]").astype(np.int64)
            if vals.dtype == object and any(v is None for v in vals):
                # sparse values (live-cache partial attrs): stay object;
                # filter evaluation treats None as non-matching
                columns[attr.name] = vals
            else:
                columns[attr.name] = vals.astype(np.int64)
        elif attr.type in ("string", "bytes", "json"):
            columns[attr.name] = np.asarray(vals, dtype=object)
        else:
            arr = np.asarray(vals)
            if arr.dtype == object and any(v is None for v in arr):
                columns[attr.name] = arr
            else:
                columns[attr.name] = arr.astype(_DTYPES[attr.type])
    return columns, geoms
