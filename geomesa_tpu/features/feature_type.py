"""Feature type (schema) system.

Mirrors the capability of the reference's SimpleFeatureTypes spec strings
(geomesa-utils/.../geotools/SimpleFeatureTypes.scala; parser at
utils/.../sft/SimpleFeatureSpecParser.scala): a schema is declared as

    "name:String,age:Int,dtg:Date,*geom:Point:srid=4326;geomesa.z3.interval=week"

— comma-separated ``name:Type[:opt=val…]`` attributes, ``*`` marking the
default geometry, and trailing ``;key=value`` user-data options (index
configuration: ``geomesa.z3.interval``, ``geomesa.xz.precision``,
``geomesa.indices.enabled``, …).
"""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["AttributeSpec", "FeatureType", "parse_spec"]

# canonical attribute type names (lower) → normalized name
_TYPES = {
    "string": "string",
    "int": "int", "integer": "int",
    "long": "long",
    "float": "float",
    "double": "double",
    "boolean": "bool", "bool": "bool",
    "date": "date", "timestamp": "date",
    "uuid": "string",
    "bytes": "bytes",
    "json": "json",
    "point": "point",
    "linestring": "linestring",
    "polygon": "polygon",
    "multipoint": "multipoint",
    "multilinestring": "multilinestring",
    "multipolygon": "multipolygon",
    "geometry": "geometry",
    "geometrycollection": "geometry",
}

GEOM_TYPES = {
    "point", "linestring", "polygon", "multipoint", "multilinestring",
    "multipolygon", "geometry",
}


@dataclass(frozen=True)
class AttributeSpec:
    name: str
    type: str                      # normalized type name
    options: dict = field(default_factory=dict)

    @property
    def is_geometry(self) -> bool:
        return self.type in GEOM_TYPES

    @property
    def indexed(self) -> bool:
        return str(self.options.get("index", "false")).lower() == "true"


@dataclass(frozen=True)
class FeatureType:
    name: str
    attributes: tuple            # tuple[AttributeSpec, ...]
    default_geom: str | None = None
    user_data: dict = field(default_factory=dict)

    def __post_init__(self):
        names = [a.name for a in self.attributes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate attribute names in {names}")

    @property
    def attribute_names(self) -> list[str]:
        return [a.name for a in self.attributes]

    def attribute(self, name: str) -> AttributeSpec:
        for a in self.attributes:
            if a.name == name:
                return a
        raise KeyError(f"no attribute {name!r} in schema {self.name!r}")

    @property
    def geom_field(self) -> str | None:
        return self.default_geom

    @property
    def column_groups(self) -> dict:
        """Named attribute subsets from per-attribute ``column-groups``
        options (``|``-separated), the reference's ColumnGroups
        (index/conf/ColumnGroups.scala:27-78): queries hinting a group
        read only that group's columns.  The default geometry and dtg
        are members of every group (the reference always writes them to
        each column family)."""
        groups: dict = {}
        for a in self.attributes:
            raw = a.options.get("column-groups", "")
            for g in (x.strip() for x in raw.split("|") if x.strip()):
                groups.setdefault(g, []).append(a.name)
        if groups:
            always = [n for n in (self.default_geom, self.dtg_field) if n]
            for names in groups.values():
                for n in reversed(always):
                    if n not in names:
                        names.insert(0, n)
        return groups

    @property
    def dtg_field(self) -> str | None:
        """Default date attribute: explicit ``geomesa.index.dtg`` user-data
        or the first Date attribute (the reference's convention)."""
        explicit = self.user_data.get("geomesa.index.dtg")
        if explicit:
            return explicit
        for a in self.attributes:
            if a.type == "date":
                return a.name
        return None

    @property
    def z3_interval(self) -> str:
        return self.user_data.get("geomesa.z3.interval", "week")

    @property
    def xz_precision(self) -> int:
        return int(self.user_data.get("geomesa.xz.precision", 12))

    @property
    def enabled_indices(self) -> list[str] | None:
        """Explicit index list (``geomesa.indices.enabled``) or None for
        defaults-by-schema-shape."""
        raw = self.user_data.get("geomesa.indices.enabled")
        if not raw:
            return None
        return [s.strip() for s in raw.split(",") if s.strip()]

    @property
    def is_points(self) -> bool:
        return (
            self.default_geom is not None
            and self.attribute(self.default_geom).type == "point"
        )

    def spec_string(self) -> str:
        parts = []
        for a in self.attributes:
            star = "*" if a.name == self.default_geom else ""
            opts = "".join(f":{k}={v}" for k, v in a.options.items())
            type_name = {v: v for v in _TYPES.values()}[a.type]
            # canonical capitalization
            pretty = {
                "string": "String", "int": "Int", "long": "Long",
                "float": "Float", "double": "Double", "bool": "Boolean",
                "date": "Date", "bytes": "Bytes", "point": "Point",
                "linestring": "LineString", "polygon": "Polygon",
                "multipoint": "MultiPoint", "multilinestring": "MultiLineString",
                "multipolygon": "MultiPolygon", "geometry": "Geometry",
                "json": "Json",
            }[type_name]
            parts.append(f"{star}{a.name}:{pretty}{opts}")
        spec = ",".join(parts)
        if self.user_data:
            spec += ";" + ",".join(f"{k}={v}" for k, v in self.user_data.items())
        return spec


def _split_quoted(s: str, sep: str) -> list[str]:
    """Split on ``sep`` outside single-quoted runs (user-data list values
    are quoted in specs, e.g. ``geomesa.indices.enabled='z3,id'``)."""
    out, buf, quoted = [], [], False
    for ch in s:
        if ch == "'":
            quoted = not quoted
            buf.append(ch)
        elif ch == sep and not quoted:
            out.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    out.append("".join(buf))
    return out


def parse_spec(name: str, spec: str) -> FeatureType:
    """Parse a spec string into a FeatureType."""
    spec = spec.strip()
    user_data: dict = {}
    if ";" in spec:
        spec, _, ud = spec.partition(";")
        for kv in _split_quoted(ud, ","):
            if not kv.strip():
                continue
            k, _, v = kv.partition("=")
            user_data[k.strip()] = v.strip().strip("'\"")

    attributes: list[AttributeSpec] = []
    default_geom = None
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        is_default = part.startswith("*")
        if is_default:
            part = part[1:]
        pieces = part.split(":")
        if len(pieces) < 2:
            raise ValueError(f"invalid attribute spec {part!r}")
        attr_name, type_name = pieces[0].strip(), pieces[1].strip().lower()
        if type_name not in _TYPES:
            raise ValueError(f"unknown attribute type {pieces[1]!r}")
        options = {}
        for opt in pieces[2:]:
            k, _, v = opt.partition("=")
            options[k.strip()] = v.strip()
        attr = AttributeSpec(attr_name, _TYPES[type_name], options)
        attributes.append(attr)
        if is_default:
            default_geom = attr_name
    if default_geom is None:
        for a in attributes:
            if a.is_geometry:
                default_geom = a.name
                break
    return FeatureType(name, tuple(attributes), default_geom, user_data)
