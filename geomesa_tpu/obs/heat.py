"""Access-temperature tracking: WHICH data queries actually touch.

The workload half of ISSUE 12.  The store already reports where every
byte lives (obs/resource, PR 4) and how long every query takes
(obs/trace, PR 3) — but nothing records which *generations* those
queries read, and both the temperature-driven tier autopilot (ROADMAP
item 6) and admission control (item 1) need exactly that hot/cold
picture.  This module is their data plane:

* every lean scan path (z3/attr/xz2/xz3 query, density, sketch —
  single-chip and sharded) reports per-generation **touches** through
  :func:`record_index_scan`: scans, bytes read, rows matched, the
  residency tier at access time, last-access timestamp;
* touches fold into an **exponentially-decayed temperature**: a touch
  at time ``t`` contributes ``exp(-(now - t)/τ)``
  (``geomesa.obs.heat.tau.s``), accumulated incrementally as
  ``temp = temp·exp(-Δt/τ) + weight`` so no touch history is kept.
  The weight is 1.0 for a touch that MATCHED rows (or whose match
  count is unknowable, e.g. a density partial) and 0.0 for a probe
  that found nothing — a generation every query probes but none draws
  from stays cold;
* :func:`heat_report` joins the tracked entries with the storage
  report's per-generation placement (tier, resident bytes) and ranks
  hot → cold — generations the storage report knows but no query ever
  touched appear at temperature 0, so the coldest data is visible,
  not just the warmest;
* :func:`publish_heat_gauges` folds per-(schema, index) aggregates
  into ``heat.*`` registry gauges for ``/metrics.prom``;
  ``GET /debug/heat`` (web/app.py) serves the full ranked report.

Per-generation detail lives in the REPORT, not the registry — the
same bounded-gauge-key contract as ``storage.*`` (generation ids
churn under compaction).  On compaction the merged run INHERITS its
sources' decayed temperatures (:func:`merge_index_generations`), so
the autopilot's picture survives LSM maintenance instead of resetting
hot data to cold.

Tracking is process-local (per-process view; no collectives) and
thread-safe; host-tier match counts are attributed proportionally to
run size (the stacked host seek loses per-run attribution by design).
With ``geomesa.obs.heat.enabled=false`` every record site costs one
cached bool read.
"""

from __future__ import annotations

import math
import threading
import time

from ..config import ObsProperties, config_generation
from ..metrics import registry as _metrics

__all__ = ["HeatTracker", "heat_tracker", "heat_enabled",
           "record_index_scan", "merge_index_generations",
           "heat_report", "publish_heat_gauges"]

#: cached ``geomesa.obs.heat.enabled`` keyed on config_generation() —
#: the scan hot path pays one int compare, not the override lock
_cfg_gen = -1
_cfg_enabled = True


def heat_enabled() -> bool:
    global _cfg_gen, _cfg_enabled
    gen = config_generation()
    if gen != _cfg_gen:
        _cfg_enabled = ObsProperties.HEAT_ENABLED.to_bool()
        _cfg_gen = gen
    return _cfg_enabled


class _HeatEntry:
    """Touch counters + the incrementally-decayed temperature for one
    (schema, index, generation)."""

    __slots__ = ("scans", "hits", "bytes_read", "rows_matched", "tier",
                 "first_ts", "last_ts", "temp", "temp_ts")

    def __init__(self, now: float):
        self.scans = 0
        self.hits = 0
        self.bytes_read = 0
        self.rows_matched = 0
        self.tier = ""
        self.first_ts = now
        self.last_ts = now
        self.temp = 0.0
        self.temp_ts = now

    def decayed(self, now: float, tau: float) -> float:
        dt = now - self.temp_ts
        if dt <= 0.0:
            return self.temp
        return self.temp * math.exp(-dt / tau)

    def touch(self, now: float, tau: float, tier: str, bytes_read: int,
              rows_matched, weight: float) -> None:
        self.scans += 1
        self.bytes_read += int(bytes_read)
        if rows_matched:
            self.rows_matched += int(rows_matched)
        if weight > 0.0:
            self.hits += 1
        self.tier = tier
        self.last_ts = now
        self.temp = self.decayed(now, tau) + weight
        self.temp_ts = now


class HeatTracker:
    """Process-wide decayed-temperature store keyed
    ``(schema, index, gen_id)``.  ``tau_s``/``max_entries`` pin the
    knobs for tests; by default they re-resolve from the
    ``geomesa.obs.heat.*`` options per call (live-tunable)."""

    def __init__(self, tau_s: float | None = None,
                 max_entries: int | None = None):
        self._tau_override = tau_s
        self._max_override = max_entries
        #: guarded-by: self._lock — scans, compaction merges, report
        #: snapshots and eviction all race on this map
        self._entries: dict[tuple, _HeatEntry] = {}
        self._lock = threading.Lock()

    def tau_s(self) -> float:
        if self._tau_override is not None:
            return float(self._tau_override)
        return max(1e-3, float(ObsProperties.HEAT_TAU_S.get()))

    def _max_entries(self) -> int:
        if self._max_override is not None:
            return int(self._max_override)
        return max(16, ObsProperties.HEAT_MAX_ENTRIES.to_int())

    def record(self, scope: tuple, touches, now: float | None = None
               ) -> None:
        """Fold one scan's per-generation touches in.  ``scope`` is
        ``(schema, index_key)``; each touch is ``(gen_id, tier,
        rows_scanned, bytes_read, rows_matched)`` where ``rows_matched
        is None`` means the path cannot attribute matches (density /
        sketch partials) and counts as a full-weight access."""
        now = time.time() if now is None else float(now)
        tau = self.tau_s()
        schema, index = scope
        with self._lock:
            for gen_id, tier, _rows, bytes_read, matched in touches:
                key = (schema, index, int(gen_id))
                e = self._entries.get(key)
                if e is None:
                    e = self._entries[key] = _HeatEntry(now)
                weight = 1.0 if (matched is None or matched > 0) else 0.0
                e.touch(now, tau, tier, bytes_read, matched, weight)
            if len(self._entries) > self._max_entries():
                self._evict_coldest(now, tau)

    # gm-lint: holds: self._lock (record() evicts inside its fold)
    def _evict_coldest(self, now: float, tau: float) -> None:
        """Drop the coldest ~10% (lock held) — amortized so a store
        with churning generations never grows the table unbounded."""
        n_drop = max(1, len(self._entries) // 10)
        ranked = sorted(self._entries.items(),
                        key=lambda kv: (kv[1].decayed(now, tau),
                                        kv[1].last_ts))
        for key, _ in ranked[:n_drop]:
            del self._entries[key]

    def merge_generations(self, scope: tuple, dead_ids, new_id: int,
                          now: float | None = None) -> None:
        """Compaction epilogue: the merged run inherits its sources'
        summed decayed temperature and counters (hot data must not
        read as cold just because maintenance renamed it)."""
        now = time.time() if now is None else float(now)
        tau = self.tau_s()
        schema, index = scope
        with self._lock:
            dead = [self._entries.pop((schema, index, int(g)), None)
                    for g in dead_ids]
            dead = [e for e in dead if e is not None]
            if not dead:
                return
            key = (schema, index, int(new_id))
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = _HeatEntry(now)
            for d in dead:
                e.scans += d.scans
                e.hits += d.hits
                e.bytes_read += d.bytes_read
                e.rows_matched += d.rows_matched
                e.temp = e.decayed(now, tau) + d.decayed(now, tau)
                e.temp_ts = now
                e.first_ts = min(e.first_ts, d.first_ts)
                e.last_ts = max(e.last_ts, d.last_ts)

    def drop(self, scope: tuple, gen_ids) -> None:
        schema, index = scope
        with self._lock:
            for g in gen_ids:
                self._entries.pop((schema, index, int(g)), None)

    def snapshot(self, now: float | None = None) -> dict:
        """``{(schema, index, gen_id): {...}}`` with temperatures
        decayed to ``now``."""
        now = time.time() if now is None else float(now)
        tau = self.tau_s()
        with self._lock:
            items = list(self._entries.items())
        return {key: {"temperature": e.decayed(now, tau),
                      "scans": e.scans, "hits": e.hits,
                      "bytes_read": e.bytes_read,
                      "rows_matched": e.rows_matched,
                      "tier": e.tier, "last_access_ts": e.last_ts,
                      "first_access_ts": e.first_ts,
                      "updated_ts": e.temp_ts}
                for key, e in items}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: process-wide tracker (the shared-registry/tracer analog for heat)
heat_tracker = HeatTracker()


def record_index_scan(index, touches) -> None:
    """Record one scan's touches against ``index``'s heat scope.  The
    datastore stamps ``heat_scope = (schema, index_key)`` on every
    lean index it builds; directly-constructed indexes (tests, bench)
    record under ``("_", <class name>)`` — tracked for overhead
    honesty, just never joined with a storage report."""
    scope = getattr(index, "heat_scope", None) \
        or ("_", type(index).__name__)
    heat_tracker.record(scope, touches)


def merge_index_generations(index, dead_ids, new_id: int) -> None:
    """Compaction hook: fold dead generations' heat into the merged
    run (no-op when tracking is off or nothing was tracked)."""
    if not heat_enabled():
        return
    scope = getattr(index, "heat_scope", None) \
        or ("_", type(index).__name__)
    heat_tracker.merge_generations(scope, dead_ids, new_id)


def _placement_map(storage: dict) -> dict:
    """``(schema, index, gen_id) -> placement`` from a storage report
    (per-generation device/host residency, obs/resource)."""
    out: dict = {}
    for schema, entry in storage.get("schemas", {}).items():
        for key, st in entry.get("indexes", {}).items():
            for g in st.get("generations", ()):  # lean indexes only
                out[(schema, key, int(g["gen_id"]))] = {
                    "tier": g.get("tier", ""),
                    "rows": int(g.get("rows", 0)),
                    "device_bytes": int(g.get("device_bytes", 0)),
                    "host_bytes": int(g.get("host_bytes", 0))}
    return out


#: stale-entry pruning grace (s): a tracker entry UPDATED within this
#: window is never pruned even when the storage snapshot lacks its
#: generation — a compaction merge credit or a scan of a just-opened
#: generation lands milliseconds around the placement walk, and racing
#: the prune must not erase it (the next report reconciles)
_PRUNE_GRACE_S = 10.0


def heat_report(store, tracker: HeatTracker | None = None,
                now: float | None = None, limit: int | None = None,
                storage: dict | None = None) -> dict:
    """The ranked hot→cold picture: every tracked touch entry joined
    with its generation's CURRENT placement from the storage report,
    plus zero-temperature rows for generations the storage report
    knows but no query ever touched.  Entries whose generation no
    longer exists (compacted away without a merge credit, schema
    removed) are pruned from the tracker for scopes the storage
    report covers — after a grace window, so a racing compaction's
    merge credit survives — and the table self-bounds under churn.

    Ranking: temperature desc, then last access desc, then gen_id.
    ``limit`` truncates the ranked list (the ``?limit=`` paging knob);
    aggregates always cover everything.  ``storage`` reuses an
    already-computed storage report instead of walking the store
    again (the one-walk-per-scrape discipline)."""
    from .resource import storage_report
    now = time.time() if now is None else float(now)
    tracker = tracker if tracker is not None else heat_tracker
    if storage is None:
        storage = storage_report(store, audit=False)
    placement = _placement_map(storage)
    covered_scopes = {(s, i) for (s, i, _g) in placement}
    snap = tracker.snapshot(now=now)
    rows: list = []
    stale: dict = {}
    for key, e in snap.items():
        schema, index, gen_id = key
        updated_ts = e.pop("updated_ts")
        place = placement.get(key)
        if place is None:
            if ((schema, index) in covered_scopes
                    and now - updated_ts > _PRUNE_GRACE_S):
                # this store's scope, but the generation is gone —
                # prune (foreign scopes are left alone: another store
                # in this process may own them; freshly-updated
                # entries get the grace window above)
                stale.setdefault((schema, index), []).append(gen_id)
            continue
        rows.append({"schema": schema, "index": index, "gen_id": gen_id,
                     **{k: (round(v, 6) if isinstance(v, float) else v)
                        for k, v in e.items()},
                     "placement": place})
    for scope, gens in stale.items():
        tracker.drop(scope, gens)
    for key, place in placement.items():
        if key in snap:
            continue
        schema, index, gen_id = key
        rows.append({"schema": schema, "index": index, "gen_id": gen_id,
                     "temperature": 0.0, "scans": 0, "hits": 0,
                     "bytes_read": 0, "rows_matched": 0,
                     "tier": place["tier"], "last_access_ts": 0.0,
                     "first_access_ts": 0.0, "placement": place})
    rows.sort(key=lambda r: (-r["temperature"], -r["last_access_ts"],
                             r["schema"], r["index"], r["gen_id"]))
    for rank, r in enumerate(rows, start=1):
        r["rank"] = rank
    aggregates: dict = {}
    for r in rows:
        agg = aggregates.setdefault(f"{r['schema']}.{r['index']}", {
            "temperature": 0.0, "scans": 0, "bytes_read": 0,
            "rows_matched": 0, "generations": 0, "touched": 0})
        agg["temperature"] += r["temperature"]
        agg["scans"] += r["scans"]
        agg["bytes_read"] += r["bytes_read"]
        agg["rows_matched"] += r["rows_matched"]
        agg["generations"] += 1
        agg["touched"] += 1 if r["scans"] else 0
    for agg in aggregates.values():
        agg["temperature"] = round(agg["temperature"], 6)
    return {
        "generated_ts": round(now, 3),
        "tau_s": tracker.tau_s(),
        "enabled": heat_enabled(),
        "tracked_entries": len(tracker),
        "generations": rows if limit is None else rows[:limit],
        "indexes": aggregates,
    }


#: serializes gauge publication (the storage-gauge discipline: the
#: publish-then-retire sequence must not interleave across scrapes)
_publish_lock = threading.Lock()


def publish_heat_gauges(store, report: dict | None = None,
                        storage: dict | None = None) -> dict:
    """Fold a heat report's per-(schema, index) aggregates into
    ``heat.*`` registry gauges so the workload picture scrapes from
    ``/metrics.prom`` alongside ``storage.*``:

    * ``heat.<schema>.<index>.{temperature,scans,bytes_read,
      rows_matched}``
    * ``heat.total.{temperature,tracked_generations}``

    Under multihost every process runs the same SPMD scans and
    records the same touches, and the mesh scrape
    (``/metrics.prom?mesh=1``) SUMS gauges across processes — so all
    heat values publish divided by the process count, the
    ``publish_storage_gauges`` shared-value discipline.  Per-store key
    tracking + stale-key retirement likewise mirror the storage
    gauges (bounded key set under schema churn).  ``storage`` is the
    optional already-computed storage report for the fresh-report
    path.  Returns the report used."""
    if report is None:
        report = heat_report(store, storage=storage)
    procs = 1
    if getattr(store, "_multihost", False):
        import jax
        procs = max(1, jax.process_count())
    published: set = set()

    def _set(key: str, value) -> None:
        _metrics.gauge(key).set(value / procs if procs > 1 else value)
        published.add(key)

    with _publish_lock:
        total_temp = 0.0
        for scope, agg in report["indexes"].items():
            base = f"heat.{scope}"
            _set(f"{base}.temperature", agg["temperature"])
            _set(f"{base}.scans", agg["scans"])
            _set(f"{base}.bytes_read", agg["bytes_read"])
            _set(f"{base}.rows_matched", agg["rows_matched"])
            total_temp += agg["temperature"]
        # totals LAST: a schema literally named "total" must never
        # leave its values in the rollup keys
        _set("heat.total.temperature", round(total_temp, 6))
        _set("heat.total.tracked_generations",
             report["tracked_entries"])
        prev = getattr(store, "_heat_gauge_keys", set())
        for stale in prev - published:
            _metrics.remove(stale)
        store._heat_gauge_keys = published
    return report
