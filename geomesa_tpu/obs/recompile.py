"""XLA recompile tracking: turn silent retraces into a metric.

The classic TPU-stack performance cliff is the SILENT recompile: a
shape/dtype/static-arg drift re-traces a jitted program and a query
that ran in 5 ms suddenly takes 20 s, with nothing in any log.  This
module hooks jax's monitoring stream
(``jax.monitoring.register_event_duration_secs_listener``): every
``.../backend_compile_duration`` event increments ``jax.compile.count``,
feeds its duration to the ``jax.compile.ms`` timer, and — when a query
trace is active — stamps ``jax.recompiles`` onto the current span, so
a slow trace SHOWS that it paid a compile.

For jax builds without the monitoring API there is a wrapped-jit
fallback: :func:`counting_jit` wraps ``jax.jit`` and counts executable-
cache growth per call into ``jax.compile.fallback_count`` — coarser
(no durations), and OPT-IN: it only sees functions a caller wrapped
with it, so on listener-less builds the recompile budget covers
exactly the jits routed through ``counting_jit`` (the budget tests
check :func:`installed` and skip rather than pass vacuously).

Installation is idempotent and happens at ``geomesa_tpu.obs`` import
when ``geomesa.obs.recompile.track`` is on (the default).  jax offers
no listener deregistration, so the hook lives for the process — it is
a few counter increments per compile, i.e. free.
"""

from __future__ import annotations

import threading

from ..metrics import (
    JAX_COMPILE_COUNT, JAX_COMPILE_FALLBACK, JAX_COMPILE_MS,
    registry as _metrics,
)

__all__ = ["install", "installed", "compile_count", "counting_jit",
           "CountingJit"]

_installed = False
_lock = threading.Lock()


def _on_duration(event: str, duration_secs: float, **kw) -> None:
    if not event.endswith("backend_compile_duration"):
        return
    _metrics.counter(JAX_COMPILE_COUNT).inc()
    _metrics.timer(JAX_COMPILE_MS).update(duration_secs * 1e3)
    from .trace import current_span
    sp = current_span()
    if sp is not None:
        sp.add_attr("jax.recompiles", 1)


def install() -> bool:
    """Register the compile-event listener (idempotent).  Returns
    whether the listener is active — False means this jax has no
    monitoring API and callers should lean on :func:`counting_jit`."""
    global _installed
    with _lock:
        if _installed:
            return True
        try:
            import jax.monitoring as monitoring
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            return False
        _installed = True
        return True


def installed() -> bool:
    return _installed


def compile_count() -> int:
    """Backend compiles seen so far — diff two readings around a warm
    region to assert a recompile budget.  With the listener installed
    this covers EVERY XLA backend compile in the process; without it,
    it falls back to ``jax.compile.fallback_count``, which only counts
    functions explicitly wrapped with :func:`counting_jit` — check
    :func:`installed` when the budget must be process-wide."""
    n = _metrics.counter(JAX_COMPILE_COUNT).count
    if n == 0 and not _installed:
        return _metrics.counter(JAX_COMPILE_FALLBACK).count
    return n


class CountingJit:
    """Wrapped-jit fallback counter: delegates to ``jax.jit(fn)`` and
    counts executable-cache growth after each call (each growth step =
    one trace+compile) into ``jax.compile.fallback_count``."""

    def __init__(self, fn, **jit_kwargs):
        import jax
        self._jitted = jax.jit(fn, **jit_kwargs)
        self._last_cache = 0

    def __getattr__(self, name):
        return getattr(self._jitted, name)

    def __call__(self, *args, **kwargs):
        out = self._jitted(*args, **kwargs)
        try:
            n = int(self._jitted._cache_size())
        except Exception:
            return out
        if n > self._last_cache:
            _metrics.counter(JAX_COMPILE_FALLBACK).inc(n - self._last_cache)
            self._last_cache = n
        return out


def counting_jit(fn=None, **jit_kwargs):
    """``jax.jit`` drop-in that also counts recompiles (usable bare or
    with jit kwargs, like the decorator it wraps)."""
    if fn is None:
        return lambda f: CountingJit(f, **jit_kwargs)
    return CountingJit(fn, **jit_kwargs)
