"""The SLO plane: objectives, burn rates, exemplars, alerts.

This is the aggregation half of ISSUE 20 (attribution.py is the
decomposition half): a :class:`SloPlane` registered as a tracer finish
hook folds every completed ``query`` / ``write`` / ``tile.render``
trace into

- per-class stage timers — ``slo.<class>.stage.<stage>.ms`` — the
  "where did the p99 millisecond go" answer ROADMAP item 1 asks for,
- per-class and per-tenant RED metrics (``slo.<class>.requests`` /
  ``.errors`` / ``.total.ms``; ``slo.tenant.<t>.*``),
- rolling time-bucket windows per (class, tenant) that back
  multi-window (5m/1h) **error-budget burn** gauges against the
  objectives declared in ``geomesa.slo.objectives``, and
- an :class:`ExemplarHistogram` per class whose buckets retain the
  newest offending ``trace_id`` — emitted in OpenMetrics exemplar
  syntax (``# {trace_id="..."}``) appended to ``/metrics.prom``, so a
  dashboard bucket is one click from its span tree at ``/traces/<id>``.

Burn rate is the standard SRE multi-window construction: the fraction
of requests that were *bad* (errored, or slower than the class
objective latency) divided by the budget ``1 - target``.  A burn of
1.0 spends exactly the budget over the window; the alert fires
edge-triggered when BOTH the short (5m) and long (1h) windows exceed
``geomesa.slo.burn.alert`` — the long window keeps a brief spike from
paging, the short window re-arms the alert quickly once the incident
ends.  Crossings land in a bounded ring served at ``/debug/alerts``.

Coverage note: the plane sees only traces the tracer RECORDS.  With
the default ``always`` sampler that is every request; under ``ratio``
sampling the SLO numbers are a sample, and under ``never`` the plane
is blind (documented in docs/slo.md).  Exemplars additionally require
the trace to be *retained* (resolvable at ``/traces/<id>``) — an
un-retained trace updates every aggregate but leaves no exemplar.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque

from ..config import SloProperties, config_generation
from ..metrics import (
    ALERT_SLO_ACTIVE, ALERT_SLO_FIRED, registry as _metrics,
)
from . import attribution
from .prom import metric_name
from .trace import Trace

__all__ = ["SloPlane", "ExemplarHistogram", "Objective", "slo_plane"]

_SEGMENT_RE = re.compile(r"[^A-Za-z0-9_:\-]")

#: same log-bucket geometry as the registry histograms (metrics.py):
#: bucket b holds values in (BASE**(b-1), BASE**b]
_Q_BASE = 1.15
_Q_LOG = math.log(_Q_BASE)


class Objective:
    """One class's SLO: requests complete under ``latency_ms`` with
    ``target`` success fraction (e.g. 250 ms at 0.99)."""

    __slots__ = ("cls", "latency_ms", "target")

    def __init__(self, cls: str, latency_ms: float, target: float):
        self.cls = cls
        self.latency_ms = float(latency_ms)
        self.target = min(max(float(target), 0.0), 0.999999)

    def to_json(self) -> dict:
        return {"class": self.cls, "latency_ms": self.latency_ms,
                "target": self.target}


def _parse_objectives(spec: str) -> dict[str, Objective]:
    """Parse ``geomesa.slo.objectives``: comma-separated
    ``class:latency_ms:target`` triples.  Malformed entries are
    skipped (config must never crash the serving path)."""
    out: dict[str, Objective] = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.rsplit(":", 2)
        if len(bits) != 3:
            continue
        try:
            out[bits[0]] = Objective(bits[0], float(bits[1]),
                                     float(bits[2]))
        except ValueError:
            continue
    return out


class ExemplarHistogram:
    """A latency histogram whose buckets remember the newest trace_id
    that landed in them — the join key between a bad bucket on a
    dashboard and the span tree that explains it.

    Kept OUTSIDE the metric registry (the registry's histograms carry
    no per-bucket metadata and the naming lint walks registry keys):
    this renders itself directly as OpenMetrics classic-histogram text
    with exemplar suffixes, appended after ``prometheus_text`` output.
    """

    __slots__ = ("_buckets", "_count", "_sum", "_lock")

    def __init__(self):
        # bucket index -> [count, trace_id, value, ts]
        self._buckets: dict[int, list] = {}
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def update(self, value_ms: float, trace_id: str = "") -> None:
        b = 0 if value_ms <= 0 else int(
            math.ceil(math.log(value_ms) / _Q_LOG))
        with self._lock:
            ent = self._buckets.get(b)
            if ent is None:
                ent = self._buckets[b] = [0, "", 0.0, 0.0]
            ent[0] += 1
            if trace_id:
                ent[1] = trace_id
                ent[2] = value_ms
                ent[3] = time.time()
            self._count += 1
            self._sum += value_ms

    def exemplars(self) -> list[dict]:
        """Retained exemplars, slowest bucket first (the /debug/slo
        "worst recent traces" surface)."""
        with self._lock:
            items = [(b, list(e)) for b, e in self._buckets.items()
                     if e[1]]
        items.sort(reverse=True)
        return [{"bucket_le_ms": round(_Q_BASE ** b, 3),
                 "trace_id": e[1], "value_ms": round(e[2], 3),
                 "ts": e[3]} for b, e in items]

    def render(self, name: str) -> list[str]:
        """OpenMetrics classic histogram lines: cumulative buckets
        (exemplar-suffixed where one is retained), +Inf, _sum/_count."""
        with self._lock:
            items = sorted((b, list(e)) for b, e in self._buckets.items())
            count, total = self._count, self._sum
        lines = [f"# TYPE {name} histogram"]
        cum = 0
        for b, e in items:
            cum += e[0]
            le = repr(round(_Q_BASE ** b, 6))
            line = f'{name}_bucket{{le="{le}"}} {cum}'
            if e[1]:
                line += (f' # {{trace_id="{e[1]}"}} '
                         f"{repr(round(e[2], 6))}")
            lines.append(line)
        lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{name}_sum {repr(round(total, 6))}")
        lines.append(f"{name}_count {count}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._count = 0
            self._sum = 0.0


class SloPlane:
    """Aggregates attribution results into SLO signals (see module
    docstring).  One process-wide instance (``slo_plane``) is wired as
    a tracer finish hook at obs package import."""

    def __init__(self):
        self._lock = threading.RLock()
        # (class, tenant) -> deque of [bucket_idx, count, bad, errors]
        self._windows: dict[tuple[str, str], deque] = {}
        self._exemplars: dict[str, ExemplarHistogram] = {}
        # class -> [root_ms_sum, unattributed_ms_sum] for the residual
        # gauge (cumulative — a ratio of totals, not of quantiles)
        self._residual: dict[str, list] = {}
        self._alerts: deque = deque(maxlen=128)
        self._alert_active: dict[str, bool] = {}
        self._tenants: set[str] = set()
        # config-generation cache (same discipline as Tracer)
        self._cfg_gen = -1
        self._cfg_enabled = True
        self._cfg_objectives: dict[str, Objective] = {}
        self._cfg_short_s = 300.0
        self._cfg_long_s = 3600.0
        self._cfg_bucket_s = 10.0
        self._cfg_burn_alert = 10.0
        self._cfg_tenants_max = 64

    def _refresh_config(self) -> None:
        gen = config_generation()
        if gen != self._cfg_gen:
            self._cfg_enabled = SloProperties.ENABLED.to_bool()
            self._cfg_objectives = _parse_objectives(
                SloProperties.OBJECTIVES.get())
            self._cfg_short_s = float(SloProperties.WINDOW_SHORT_S.get())
            self._cfg_long_s = float(SloProperties.WINDOW_LONG_S.get())
            self._cfg_bucket_s = max(
                1.0, float(SloProperties.BUCKET_S.get()))
            self._cfg_burn_alert = float(SloProperties.BURN_ALERT.get())
            self._cfg_tenants_max = SloProperties.TENANTS_MAX.to_int()
            cap = SloProperties.ALERTS_CAPACITY.to_int()
            if cap != (self._alerts.maxlen or 0):
                with self._lock:
                    self._alerts = deque(self._alerts, maxlen=max(1, cap))
            self._cfg_gen = gen

    # -- identity helpers -------------------------------------------------
    def _tenant_key(self, tenant: str) -> str:
        """Sanitized, bounded tenant label: past ``geomesa.slo.
        tenants.max`` distinct tenants, new ones fold into ``other``
        so a tenant-id flood cannot balloon the registry."""
        t = _SEGMENT_RE.sub("_", tenant) if tenant else ""
        if not t:
            return "default"
        with self._lock:
            if t in self._tenants:
                return t
            if len(self._tenants) >= self._cfg_tenants_max:
                return "other"
            self._tenants.add(t)
            return t

    def classes(self) -> tuple[str, ...]:
        self._refresh_config()
        return tuple(self._cfg_objectives)

    # -- ingestion --------------------------------------------------------
    def on_trace_finish(self, trace: Trace, retained: bool) -> None:
        """Tracer finish hook: attribute the trace and fold it in.
        Fast-exits for disabled plane or classes with no objective."""
        self._refresh_config()
        if not self._cfg_enabled:
            return
        root = trace.root_span
        if root is None or root.name not in self._cfg_objectives:
            return
        att = attribution.attribute(trace)
        if att is None:
            return
        cls = att["class"]
        obj = self._cfg_objectives[cls]
        tenant = self._tenant_key(att["tenant"])
        total_ms = att["total_ms"]
        error = att["error"]
        bad = error or total_ms > obj.latency_ms

        for stage, ms in att["stages"].items():
            if ms > 0.0:
                _metrics.timer(f"slo.{cls}.stage.{stage}.ms").update(ms)
        _metrics.timer(f"slo.{cls}.total.ms").update(total_ms)
        _metrics.counter(f"slo.{cls}.requests").inc()
        if error:
            _metrics.counter(f"slo.{cls}.errors").inc()
        _metrics.counter(f"slo.tenant.{tenant}.requests").inc()
        _metrics.timer(f"slo.tenant.{tenant}.ms").update(total_ms)
        if error:
            _metrics.counter(f"slo.tenant.{tenant}.errors").inc()

        with self._lock:
            res = self._residual.setdefault(cls, [0.0, 0.0])
            res[0] += att["root_ms"]
            res[1] += att["stages"]["unattributed"]
            hist = self._exemplars.get(cls)
            if hist is None:
                hist = self._exemplars[cls] = ExemplarHistogram()
        # exemplars only for retained traces: an exemplar that 404s at
        # /traces/<id> is worse than none
        hist.update(total_ms, att["trace_id"] if retained else "")
        self._fold_window(cls, tenant, bad, error)
        self._check_alert(cls, obj)

    def observe_web(self, endpoint: str, tenant: str, status: int,
                    total_ms: float, drain_ms: float = 0.0,
                    aborted: bool = False) -> None:
        """Web middleware feed: per-endpoint RED plus the web_drain
        stage (response streaming time — outside the datastore root
        span, so only the WSGI layer can see it).  Endpoint RED is
        separate from class RED on purpose: a request can 400 before
        any trace exists."""
        self._refresh_config()
        if not self._cfg_enabled:
            return
        ep = _SEGMENT_RE.sub("_", endpoint) or "other"
        _metrics.counter(f"slo.web.{ep}.requests").inc()
        _metrics.timer(f"slo.web.{ep}.ms").update(total_ms)
        if aborted or status >= 500:
            _metrics.counter(f"slo.web.{ep}.errors").inc()
        if drain_ms > 0.0:
            cls = {"query": "query", "tiles": "tile.render"}.get(ep)
            if cls is not None and cls in self._cfg_objectives:
                _metrics.timer(f"slo.{cls}.stage.web_drain.ms").update(
                    drain_ms)

    def _fold_window(self, cls: str, tenant: str, bad: bool,
                     error: bool) -> None:
        now = time.time()
        idx = int(now / self._cfg_bucket_s)
        horizon = idx - int(self._cfg_long_s / self._cfg_bucket_s) - 1
        with self._lock:
            win = self._windows.setdefault((cls, tenant), deque())
            if win and win[-1][0] == idx:
                ent = win[-1]
            else:
                ent = [idx, 0, 0, 0]
                win.append(ent)
            ent[1] += 1
            ent[2] += 1 if bad else 0
            ent[3] += 1 if error else 0
            while win and win[0][0] < horizon:
                win.popleft()

    # -- burn -------------------------------------------------------------
    def burn(self, cls: str, window_s: float) -> float:
        """Error-budget burn for ``cls`` over the trailing
        ``window_s``: bad fraction / (1 - target), summed across
        tenants.  0.0 with no traffic (no news is good news)."""
        self._refresh_config()
        obj = self._cfg_objectives.get(cls)
        if obj is None:
            return 0.0
        lo = int((time.time() - window_s) / self._cfg_bucket_s)
        total = bad = 0
        with self._lock:
            for (c, _t), win in self._windows.items():
                if c != cls:
                    continue
                for idx, n, b, _e in win:
                    if idx >= lo:
                        total += n
                        bad += b
        if total == 0:
            return 0.0
        budget = 1.0 - obj.target
        return (bad / total) / budget if budget > 0 else 0.0

    def _check_alert(self, cls: str, obj: Objective) -> None:
        """Edge-triggered multi-window alert: fire when BOTH windows
        burn over threshold; re-arm when the short window recovers."""
        thr = self._cfg_burn_alert
        if thr <= 0:
            return
        short = self.burn(cls, self._cfg_short_s)
        longb = self.burn(cls, self._cfg_long_s)
        with self._lock:
            active = self._alert_active.get(cls, False)
            if short > thr and longb > thr and not active:
                self._alert_active[cls] = True
                self._alerts.append({
                    "ts": time.time(), "class": cls,
                    "burn_short": round(short, 3),
                    "burn_long": round(longb, 3),
                    "threshold": thr,
                    "objective": obj.to_json(),
                })
                _metrics.counter(ALERT_SLO_FIRED).inc()
            elif active and short <= thr:
                self._alert_active[cls] = False
            _metrics.gauge(ALERT_SLO_ACTIVE).set(
                sum(1 for v in self._alert_active.values() if v))

    # -- read surfaces ----------------------------------------------------
    def publish(self) -> None:
        """Refresh the derived gauges (burn per window, residual pct)
        — called by the /metrics.prom handler before snapshotting, the
        same publish-on-scrape discipline as the storage gauges."""
        self._refresh_config()
        if not self._cfg_enabled:
            return
        for cls in self._cfg_objectives:
            _metrics.gauge(f"slo.{cls}.burn.5m").set(
                round(self.burn(cls, self._cfg_short_s), 4))
            _metrics.gauge(f"slo.{cls}.burn.1h").set(
                round(self.burn(cls, self._cfg_long_s), 4))
            with self._lock:
                res = self._residual.get(cls)
            if res and res[0] > 0:
                _metrics.gauge(f"slo.{cls}.residual.pct").set(
                    round(100.0 * res[1] / res[0], 3))

    def exposition(self) -> str:
        """OpenMetrics exemplar histograms, one per class with traffic
        (``geomesa_slo_query_latency_ms`` etc.) — appended verbatim
        after the ``prometheus_text`` body by the /metrics.prom
        handler."""
        self._refresh_config()
        if not self._cfg_enabled:
            return ""
        with self._lock:
            hists = sorted(self._exemplars.items())
        lines: list[str] = []
        for cls, hist in hists:
            lines.extend(hist.render(
                metric_name(f"slo.{cls}.latency.ms")))
        return "\n".join(lines) + ("\n" if lines else "")

    def report(self) -> dict:
        """The /debug/slo JSON join: objectives, current burn, residual
        pct, active alerts, and the worst recent exemplar traces per
        class."""
        self._refresh_config()
        out = {"enabled": self._cfg_enabled, "classes": {},
               "alerts_active": sorted(
                   c for c, v in self._alert_active.items() if v)}
        for cls, obj in sorted(self._cfg_objectives.items()):
            with self._lock:
                res = self._residual.get(cls)
                hist = self._exemplars.get(cls)
            out["classes"][cls] = {
                "objective": obj.to_json(),
                "burn_5m": round(self.burn(cls, self._cfg_short_s), 4),
                "burn_1h": round(self.burn(cls, self._cfg_long_s), 4),
                "residual_pct": (round(100.0 * res[1] / res[0], 3)
                                 if res and res[0] > 0 else 0.0),
                "exemplars": hist.exemplars()[:8] if hist else [],
            }
        return out

    def alerts(self, limit: int | None = None,
               cls: str | None = None) -> list[dict]:
        """Recent burn-alert crossings, newest first."""
        with self._lock:
            items = list(self._alerts)
        items.reverse()
        if cls is not None:
            items = [a for a in items if a["class"] == cls]
        if limit is not None:
            items = items[:max(0, int(limit))]
        return items

    def reset(self) -> None:
        """Test hook: drop all windows/exemplars/alerts (registry keys
        are the caller's problem — tests use a fresh registry or accept
        accumulation)."""
        with self._lock:
            self._windows.clear()
            self._exemplars.clear()
            self._residual.clear()
            self._alerts.clear()
            self._alert_active.clear()
            self._tenants.clear()
            self._cfg_gen = -1


#: process-wide SLO plane (wired to the tracer in obs/__init__.py)
slo_plane = SloPlane()
