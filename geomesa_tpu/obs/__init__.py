"""Unified observability: tracing + quantile metrics + recompile watch.

The cross-cutting layer ISSUE 5 adds so a slow query on a 1B-row lean
store decomposes into plan / range-decomposition / device dispatch /
host-spill scan / cache-miss time instead of one opaque number:

* :mod:`.trace` — Dapper-style spans with contextvar propagation,
  always/ratio/slow samplers, ring + JSONL exporters, a slow-query log,
  and the :func:`device_span` helper that attributes block-until-ready
  device time to the owning query;
* :mod:`.recompile` — the XLA recompile tracker (jax.monitoring
  listener + wrapped-jit fallback) that turns silent retraces into
  ``jax.compile.*`` metrics and span attributes;
* :mod:`.prom` — Prometheus text exposition over metric snapshots
  (p50/p95/p99 from the log-bucketed histograms in metrics.py);
* :mod:`.resource` — storage/HBM accounting (ISSUE 9): the
  ``storage.*`` gauges, the ``/debug/storage`` report, and the
  accounted-vs-actual-nbytes reconciliation audit;
* :mod:`.explain_analyze` — EXPLAIN ANALYZE: the plan narration
  merged with measured actuals (estimate vs rows scanned/matched,
  per-phase ms), served at ``/explain``;
* :mod:`.heat` — access-temperature tracking (ISSUE 12): per-(schema,
  index, generation) touch counters decayed into a temperature score,
  the ranked hot→cold ``/debug/heat`` report joined with storage
  placement, and the ``heat.*`` gauges — the workload data plane the
  tier autopilot consumes;
* :mod:`.jobs` — the background-job registry (ISSUE 12):
  ingest/compaction runs with phase spans, progress, and terminal
  outcomes, served at ``/debug/jobs``.

Everything configures through the ``geomesa.obs.*`` system properties
(config.ObsProperties); docs/observability.md is the operator contract.
"""

from __future__ import annotations

from ..config import ObsProperties
from .explain_analyze import (
    ExplainAnalyzeResult, explain_analyze, explain_analyze_sql,
)
from .heat import (
    HeatTracker, heat_enabled, heat_report, heat_tracker,
    merge_index_generations, publish_heat_gauges, record_index_scan,
)
from .jobs import JobRecord, JobRegistry, jobs_registry
from .prom import prometheus_text
from .recompile import compile_count, counting_jit, install as \
    install_recompile_tracker
from .attribution import STAGES as SLO_STAGES, attribute
from .resource import publish_storage_gauges, storage_report
from .slo import ExemplarHistogram, Objective, SloPlane, slo_plane
from .trace import (
    AlwaysSampler, JsonlExporter, NeverSampler, RatioSampler,
    RingExporter, Sampler, SlowOnlySampler, Span, Trace, Tracer,
    current_span, current_trace_id, device_span, obs_count, span, tracer,
)

__all__ = ["Span", "Trace", "Tracer", "Sampler", "AlwaysSampler",
           "NeverSampler", "RatioSampler", "SlowOnlySampler",
           "RingExporter", "JsonlExporter", "tracer", "span",
           "device_span", "current_span", "current_trace_id", "obs_count",
           "prometheus_text", "compile_count", "counting_jit",
           "install_recompile_tracker",
           "storage_report", "publish_storage_gauges",
           "ExplainAnalyzeResult", "explain_analyze",
           "explain_analyze_sql",
           "HeatTracker", "heat_tracker", "heat_enabled",
           "record_index_scan", "merge_index_generations",
           "heat_report", "publish_heat_gauges",
           "JobRecord", "JobRegistry", "jobs_registry",
           "SloPlane", "slo_plane", "ExemplarHistogram", "Objective",
           "SLO_STAGES", "attribute"]

# the recompile listener is process-global and effectively free — hook
# it as soon as observability loads (gated by the option so fully
# instrumentation-silent runs stay possible)
if ObsProperties.RECOMPILE_TRACK.to_bool():
    install_recompile_tracker()

# the SLO plane feeds off finished root traces; the hook itself
# fast-exits when geomesa.slo.enabled is off, so wiring it
# unconditionally costs one list iteration per finished trace
tracer.add_finish_hook(slo_plane.on_trace_finish)
