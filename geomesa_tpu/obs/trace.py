"""Dapper-style query tracing: spans, samplers, exporters, one tracer.

The unified observability layer ISSUE 5 adds over the three previously
disconnected pieces (metrics.py counters, audit.py events, planning/
explain.py text traces): a query is ONE trace — a ``trace_id`` plus a
tree of timed :class:`Span`\\ s (plan / decompose / scan-device /
scan-host / post-filter, each carrying attributes like device ms, runs
and bytes scanned, cache hits) — propagated through the call stack via
a ``contextvars.ContextVar`` so index internals attach to whatever
query is running without plumbing a handle through every signature.

Sampling is head+tail: the sampler decides at the root span whether to
RECORD (``sample``) and at trace end whether to RETAIN (``retain``) —
``always`` records everything, ``ratio`` records a fraction, ``slow``
records everything but retains only traces at/over the slow threshold
(tail-based, since a root's duration is unknowable up front).
While the slow log is enabled (``geomesa.obs.slow.ms`` > 0), every
finished trace at/over the threshold also lands in the dedicated
slow-query log — including roots the ratio sampler head-declined,
which record but route only to the slow log — so the one query you
need to explain is the one that was kept (the ``never`` sampler is a
true off switch and bypasses this).

Spans are process-local only: nothing here enters a collective, so
tracing can never diverge a multihost program.  When tracing is
disabled (or a root was not sampled) every ``span()`` yields a shared
no-op whose methods do nothing — the hot path pays one contextvar read.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import sys
import threading
import time
from collections import deque

from ..config import ObsProperties
from ..metrics import (
    LEAN_DEVICE_DISPATCHES, LEAN_DEVICE_MS, OBS_SPANS_DROPPED,
    registry as _metrics,
)

__all__ = ["Span", "Trace", "Tracer", "Sampler", "AlwaysSampler",
           "NeverSampler", "RatioSampler", "SlowOnlySampler",
           "RingExporter", "JsonlExporter", "tracer", "span",
           "device_span", "current_span", "current_trace_id",
           "obs_count"]


#: process-local id source: ``uuid4`` reads ``os.urandom`` (~80 µs per
#: id — measured dominating span cost); a Mersenne stream seeded from
#: urandom once gives the same 64-bit uniqueness for ~1 µs
_ids = random.Random()


def _new_id() -> str:
    return f"{_ids.getrandbits(64):016x}"


class Span:
    """One timed phase of a trace.  ``duration_ms`` (alias ``ms``) is
    set when the ``span()`` block exits; ``attributes`` is free-form
    (JSON-safe values only — it serializes on export)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start_ts",
                 "duration_ms", "attributes", "_t0")

    recording = True

    def __init__(self, trace_id: str, parent_id: str | None, name: str,
                 attributes: dict):
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.name = name
        self.start_ts = time.time()
        self.duration_ms = 0.0
        self.attributes = attributes
        self._t0 = time.perf_counter()

    @property
    def ms(self) -> float:
        return self.duration_ms

    def set_attr(self, key: str, value) -> None:
        self.attributes[key] = value

    def add_attr(self, key: str, n=1) -> None:
        """Accumulate a numeric attribute (cache hit counts, device ms
        rollups — anything incremented from multiple sites)."""
        self.attributes[key] = self.attributes.get(key, 0) + n

    def to_json(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "start_ts": self.start_ts,
                "duration_ms": round(self.duration_ms, 3),
                "attributes": self.attributes}


class _NoopSpan:
    """Shared do-nothing span: what ``span()`` yields when tracing is
    off or the root was not sampled."""

    __slots__ = ()
    recording = False
    trace_id = ""
    span_id = ""
    parent_id = None
    name = ""
    duration_ms = 0.0
    ms = 0.0
    start_ts = 0.0
    attributes: dict = {}

    def set_attr(self, key, value) -> None:
        pass

    def add_attr(self, key, n=1) -> None:
        pass

    def to_json(self) -> dict:
        return {}


NOOP_SPAN = _NoopSpan()


class Trace:
    """A finished (or in-flight) trace: its id, root span, and every
    finished span in FINISH order (the root is appended last)."""

    __slots__ = ("trace_id", "spans", "root_span")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.spans: list[Span] = []
        self.root_span: Span | None = None

    @property
    def name(self) -> str:
        return self.root_span.name if self.root_span is not None else ""

    @property
    def duration_ms(self) -> float:
        return (self.root_span.duration_ms
                if self.root_span is not None else 0.0)

    def summary(self) -> dict:
        root = self.root_span
        return {"trace_id": self.trace_id, "name": self.name,
                "duration_ms": round(self.duration_ms, 3),
                "spans": len(self.spans),
                "start_ts": root.start_ts if root else 0.0,
                "attributes": dict(root.attributes) if root else {}}

    def to_json(self) -> dict:
        return {"trace_id": self.trace_id, "name": self.name,
                "duration_ms": round(self.duration_ms, 3),
                "spans": [s.to_json() for s in self.spans]}


# -- samplers -------------------------------------------------------------
class Sampler:
    """Head (``sample``) + tail (``retain``) decisions; base = always."""

    def sample(self, name: str) -> bool:
        return True

    def retain(self, trace: Trace) -> bool:
        return True


class AlwaysSampler(Sampler):
    pass


class NeverSampler(Sampler):
    def sample(self, name: str) -> bool:
        return False


class RatioSampler(Sampler):
    """Record a fraction of root spans (head-based)."""

    def __init__(self, ratio: float):
        self.ratio = max(0.0, min(1.0, float(ratio)))

    def sample(self, name: str) -> bool:
        return random.random() < self.ratio


class SlowOnlySampler(Sampler):
    """Record everything, retain only slower-than-threshold traces
    (tail-based — duration is unknowable at the head)."""

    def __init__(self, threshold_ms: float):
        self.threshold_ms = float(threshold_ms)

    def retain(self, trace: Trace) -> bool:
        return trace.duration_ms >= self.threshold_ms


_ALWAYS = AlwaysSampler()


# -- exporters ------------------------------------------------------------
class RingExporter:
    """Bounded in-memory trace store (the /traces readback surface)."""

    def __init__(self, capacity: int = 256):
        self._traces: deque[Trace] = deque(maxlen=int(capacity))
        self._lock = threading.Lock()

    def export(self, trace: Trace) -> None:
        with self._lock:
            self._traces.append(trace)

    def traces(self) -> list[Trace]:
        with self._lock:
            return list(self._traces)

    def get(self, trace_id: str) -> Trace | None:
        with self._lock:
            for t in self._traces:
                if t.trace_id == trace_id:
                    return t
        return None

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


class JsonlExporter:
    """Append finished traces as JSON lines (the durable sink; same
    line-buffered open-once discipline as audit.JsonlAuditWriter).

    The sink is size-capped: once the live file would pass HALF of
    ``geomesa.obs.trace.max_bytes`` (or the explicit ``max_bytes``),
    it rotates to ``<path>.1`` (replacing any previous rollover), so a
    long bench run retains the newest ~N MB of traces across at most
    two files instead of growing without bound.  A cap of <= 0
    disables rotation."""

    def __init__(self, path: str, max_bytes: int | None = None):
        self.path = path
        self._max_override = max_bytes
        self._lock = threading.Lock()
        self._file = None
        self._bytes = 0

    def _max_bytes(self) -> int:
        if self._max_override is not None:
            return int(self._max_override)
        return ObsProperties.TRACE_MAX_BYTES.to_int()

    def export(self, trace: Trace) -> None:
        line = json.dumps(trace.to_json(), default=str) + "\n"
        with self._lock:
            if self._file is None:
                self._file = open(self.path, "a", buffering=1)
                try:
                    self._bytes = os.path.getsize(self.path)
                except OSError:
                    self._bytes = 0
            cap = self._max_bytes()
            if (cap > 0 and self._bytes
                    and self._bytes + len(line) > cap // 2):
                self._rotate()
            self._file.write(line)
            self._bytes += len(line)

    def _rotate(self) -> None:
        """Roll the live file to ``<path>.1`` (lock held).  One rolled
        predecessor is kept, so total retention is bounded by the cap
        (half live + half rolled)."""
        try:
            self._file.close()
        except OSError:
            pass   # flush failure (e.g. ENOSPC) — fall through: the
            #        replace/reopen below still bound the sink
        # None while reopening: if open() raises, the next export
        # retries from a clean slate instead of writing to a closed file
        self._file = None
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass   # a lost rollover only loses history, never traces
        self._file = open(self.path, "a", buffering=1)
        # re-stat instead of assuming 0: if the replace failed, the old
        # contents are still in the live file and must keep counting
        # against the cap, or a persistent failure grows it unbounded
        try:
            self._bytes = os.path.getsize(self.path)
        except OSError:
            self._bytes = 0

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


# -- tracer ---------------------------------------------------------------
class _Ctx:
    """Contextvar node: the active trace (None = declined root — child
    spans short-circuit to the no-op), current span, and the sampler
    that made the root decision."""

    __slots__ = ("trace", "span", "sampler")

    def __init__(self, trace, span, sampler):
        self.trace = trace
        self.span = span
        self.sampler = sampler


_current: contextvars.ContextVar = contextvars.ContextVar(
    "geomesa_obs_span", default=None)
_DECLINED = _Ctx(None, NOOP_SPAN, _ALWAYS)
#: active EXPLAIN ANALYZE collector (Tracer.capture): roots opened in
#: this context RECORD regardless of sampler/enabled and their
#: finished traces land in the collector — an explicit "explain this
#: query" ask must never come back empty because the operator had
#: sampling turned down
_capture: contextvars.ContextVar = contextvars.ContextVar(
    "geomesa_obs_capture", default=None)


class Tracer:
    """Creates spans, finishes traces, routes them to exporters and the
    slow-query log.  The sampler kind and slow threshold re-resolve from
    ``geomesa.obs.*`` options per root/finish (live-tunable); a sampler
    passed to the constructor pins the choice instead."""

    def __init__(self, sampler: Sampler | None = None, exporters=None,
                 slow_capacity: int | None = None):
        self._pinned_sampler = sampler
        self.exporters = list(exporters) if exporters is not None else [
            RingExporter(ObsProperties.TRACE_CAPACITY.to_int())]
        self.slow_log = RingExporter(
            slow_capacity if slow_capacity is not None
            else ObsProperties.SLOW_CAPACITY.to_int())
        # resolved-config cache keyed on config_generation(): the span
        # hot path pays one plain int read, not the override lock; any
        # set_property/clear_property bumps the generation and the next
        # span re-resolves (env-var changes need a set_property nudge)
        self._cfg_gen = -1
        self._cfg_enabled = True
        self._cfg_sampler: Sampler = _ALWAYS
        self._cfg_slow_ms = 0.0
        self._cfg_max_spans = 0
        # finish hooks: called for EVERY naturally finished root trace
        # (the SLO plane's feed) with (trace, retained) — retained says
        # whether the trace also landed in the exporters, i.e. whether
        # its trace_id will resolve at /traces/<id>
        self._finish_hooks: list = []

    def _refresh_config(self) -> None:
        from ..config import config_generation
        gen = config_generation()
        if gen != self._cfg_gen:
            self._cfg_enabled = ObsProperties.ENABLED.to_bool()
            self._cfg_sampler = self._resolve_sampler()
            self._cfg_slow_ms = float(ObsProperties.SLOW_MS.get())
            self._cfg_max_spans = ObsProperties.TRACE_MAX_SPANS.to_int()
            self._cfg_gen = gen

    def add_finish_hook(self, fn) -> None:
        """Register ``fn(trace, retained)`` to run on every finished
        root trace (after exporter/slow-log routing).  Hooks must be
        cheap and must not raise — a raising hook is logged and the
        query proceeds."""
        if fn not in self._finish_hooks:
            self._finish_hooks.append(fn)

    def remove_finish_hook(self, fn) -> None:
        try:
            self._finish_hooks.remove(fn)
        except ValueError:
            pass

    @property
    def ring(self) -> RingExporter | None:
        for e in self.exporters:
            if isinstance(e, RingExporter):
                return e
        return None

    def _resolve_sampler(self) -> Sampler:
        if self._pinned_sampler is not None:
            return self._pinned_sampler
        kind = str(ObsProperties.SAMPLER.get()).lower()
        if kind == "ratio":
            return RatioSampler(float(ObsProperties.SAMPLE_RATIO.get()))
        if kind in ("slow", "slow-only", "slow_only"):
            return SlowOnlySampler(float(ObsProperties.SLOW_MS.get()))
        if kind in ("never", "off", "none"):
            return NeverSampler()
        return _ALWAYS

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        """Open a span: a root (new trace, sampler consulted) when no
        span is active in this context, else a child of the current
        one.  Yields the :class:`Span` (or the shared no-op)."""
        self._refresh_config()
        if not self._cfg_enabled and _capture.get() is None:
            yield NOOP_SPAN
            return
        parent = _current.get()
        if parent is not None and parent.trace is None:
            yield NOOP_SPAN       # inside a declined trace
            return
        sampled = True
        natural = True
        if parent is None:
            sampler = self._cfg_sampler
            sampled = sampler.sample(name)
            # would this root have recorded WITHOUT a capture in play?
            # Capture-only roots must stay out of the shared ring and
            # slow log — an operator who turned tracing off (or 'never')
            # asked for those surfaces to stay silent
            natural = self._cfg_enabled and (
                sampled or (self._cfg_slow_ms > 0
                            and not isinstance(sampler, NeverSampler)))
            if not sampled and _capture.get() is None \
                    and (self._cfg_slow_ms <= 0
                         or isinstance(sampler, NeverSampler)):
                # head-declined with the slow log off — or tracing
                # explicitly 'never': the genuinely free path, no
                # trace object at all
                token = _current.set(_DECLINED)
                try:
                    yield NOOP_SPAN
                finally:
                    _current.reset(token)
                return
            # head-declined roots still RECORD while the slow log is
            # on (a 30s query must be explainable even when the ratio
            # sampler would have dropped it) — _finish routes them to
            # the slow log only, never the exporters
            trace = Trace(_new_id())
            sp = Span(trace.trace_id, None, name, dict(attributes))
            trace.root_span = sp
        else:
            trace = parent.trace
            sampler = parent.sampler
            if self._cfg_max_spans > 0 \
                    and len(trace.spans) >= self._cfg_max_spans:
                # pathological trace (10k-generation scan): stop
                # recording children, count the overflow on the root so
                # the truncation is visible in the span tree
                if trace.root_span is not None:
                    trace.root_span.add_attr("spans.dropped", 1)
                _metrics.counter(OBS_SPANS_DROPPED).inc()
                yield NOOP_SPAN
                return
            sp = Span(trace.trace_id, parent.span.span_id, name,
                      dict(attributes))
        token = _current.set(_Ctx(trace, sp, sampler))
        try:
            yield sp
        finally:
            exc = sys.exc_info()[1]
            if exc is not None:
                # the SLO plane's error signal: a root that exits via
                # an exception is a failed request for RED accounting
                sp.set_attr("error", type(exc).__name__)
            sp.duration_ms = (time.perf_counter() - sp._t0) * 1e3
            trace.spans.append(sp)
            _current.reset(token)
            if parent is None:
                self._finish(trace, sampler, sampled, natural)

    def _finish(self, trace: Trace, sampler: Sampler,
                sampled: bool = True, natural: bool = True) -> None:
        retained = natural and sampled and sampler.retain(trace)
        if retained:
            for e in self.exporters:
                try:
                    e.export(trace)
                except Exception:
                    # a broken sink (ENOSPC in the JSONL file, a dead
                    # disk) must never fail the QUERY whose trace this
                    # is — same discipline as PeriodicReporter
                    import logging
                    logging.getLogger("geomesa_tpu.obs").warning(
                        "trace exporter failed", exc_info=True)
        cap = _capture.get()
        if cap is not None:
            # EXPLAIN ANALYZE collector: gets every root finished in
            # its context, independent of the sampler's verdict
            cap.export(trace)
        if natural:
            slow_ms = self._cfg_slow_ms
            if slow_ms > 0 and trace.duration_ms >= slow_ms:
                self.slow_log.export(trace)
            for h in self._finish_hooks:
                try:
                    h(trace, retained)
                except Exception:
                    import logging
                    logging.getLogger("geomesa_tpu.obs").warning(
                        "trace finish hook failed", exc_info=True)

    @contextlib.contextmanager
    def capture(self, capacity: int = 16):
        """Force-record root spans opened in this context and collect
        their finished traces locally (the EXPLAIN ANALYZE hook):
        yields a :class:`RingExporter` that receives every root trace
        finished inside the block, regardless of the configured
        sampler — and even with ``geomesa.obs.enabled=false``, since
        an explicit explain request IS the ask to trace.  The shared
        ring and slow log receive a captured trace only when the root
        would have recorded WITHOUT the capture (the ``natural`` gate
        in ``_finish``), so capturing never makes tracing-off or
        'never' surfaces non-silent."""
        collector = RingExporter(capacity)
        token = _capture.set(collector)
        try:
            yield collector
        finally:
            _capture.reset(token)

    def find(self, trace_id: str) -> Trace | None:
        """Look a trace up across the ring exporter and the slow log."""
        ring = self.ring
        t = ring.get(trace_id) if ring is not None else None
        return t if t is not None else self.slow_log.get(trace_id)


#: process-wide tracer (the shared-MetricRegistry analog for traces)
tracer = Tracer()


def span(name: str, **attributes):
    """Module-level shorthand for ``tracer.span`` — the one import the
    instrumented layers need."""
    return tracer.span(name, **attributes)


def current_span() -> Span | None:
    """The recording span active in this context, else None."""
    ctx = _current.get()
    return ctx.span if ctx is not None and ctx.trace is not None else None


def current_trace_id() -> str:
    """The active trace id, or "" — what audit events stamp."""
    ctx = _current.get()
    return ctx.trace.trace_id if ctx is not None and ctx.trace is not None \
        else ""


#: the device metrics are process singletons — resolve them once so a
#: dispatch pays the metric's own lock, not a registry lookup too
_DEV_DISPATCHES = _metrics.counter(LEAN_DEVICE_DISPATCHES)
_DEV_MS = _metrics.timer(LEAN_DEVICE_MS)


@contextlib.contextmanager
def device_span(name: str, **attributes):
    """A span around one device dispatch.  The block is expected to
    block until the dispatch's results are host-addressable (the call
    sites all materialize with ``np.asarray``/``block_until_ready``),
    so the measured wall time IS the device round-trip; it records as
    the span's ``device_ms``, accumulates onto the trace ROOT (whole-
    query device attribution), and feeds the ``lean.device.*``
    metrics whether or not a trace is active."""
    t0 = time.perf_counter()
    with tracer.span(name, kind="device", **attributes) as sp:
        try:
            yield sp
        finally:
            ms = (time.perf_counter() - t0) * 1e3
            _DEV_DISPATCHES.inc()
            _DEV_MS.update(ms)
            sp.set_attr("device_ms", round(ms, 3))
            ctx = _current.get()
            if ctx is not None and ctx.trace is not None \
                    and ctx.trace.root_span is not None \
                    and ctx.trace.root_span is not sp:
                ctx.trace.root_span.add_attr("device_ms", round(ms, 3))


def obs_count(metric_name: str, n: int = 1) -> None:
    """Increment a registry counter AND mirror it onto the current
    span's attributes — how cache hits/misses and other per-query
    events attribute to the query that caused them."""
    _metrics.counter(metric_name).inc(n)
    sp = current_span()
    if sp is not None:
        sp.add_attr(metric_name, n)
