"""Prometheus text exposition of a metrics snapshot.

Renders a ``MetricRegistry.snapshot()`` (or a multihost-merged one from
``parallel/stats.allreduce_metrics_snapshot``) in the text exposition
format (version 0.0.4): counters as ``<name>_total``, gauges (the
``storage.*`` byte levels) as plain gauge samples, histograms/timers
as summaries with p50/p95/p99 quantile samples plus ``_sum``/``_count``
— what ``GET /metrics.prom`` serves (web/app.py).

Metric names sanitize dot-separated registry keys into the Prometheus
charset under a ``geomesa_`` prefix (``query.pts.plan_ms`` →
``geomesa_query_pts_plan_ms``).  Empty histograms render with zero
quantiles — never ``inf``/``nan``, which scrapers reject.
"""

from __future__ import annotations

import math
import re

__all__ = ["prometheus_text"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: quantile sample keys in the snapshot → Prometheus quantile labels
_QUANTILES = (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99"))


def _fmt(v) -> str:
    f = float(v)
    if math.isnan(f) or math.isinf(f):
        f = 0.0
    return repr(round(f, 6))


def metric_name(key: str) -> str:
    return "geomesa_" + _NAME_RE.sub("_", key)


def prometheus_text(snapshot: dict) -> str:
    lines: list[str] = []
    for key in sorted(snapshot):
        vals = snapshot[key]
        name = metric_name(key)
        if "value" in vals and "mean" not in vals:   # gauge (levels)
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_fmt(vals['value'])}")
            continue
        if "mean" not in vals:           # plain counter
            lines.append(f"# TYPE {name}_total counter")
            lines.append(f"{name}_total {int(vals.get('count', 0))}")
            continue
        lines.append(f"# TYPE {name} summary")
        for skey, label in _QUANTILES:
            lines.append(f'{name}{{quantile="{label}"}} '
                         f"{_fmt(vals.get(skey, 0.0))}")
        count = int(vals.get("count", 0))
        total = vals.get("total", float(vals.get("mean", 0.0)) * count)
        lines.append(f"{name}_sum {_fmt(total)}")
        lines.append(f"{name}_count {count}")
    return "\n".join(lines) + "\n"
