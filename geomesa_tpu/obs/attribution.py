"""Stage attribution: decompose a finished trace into a fixed ledger.

The SLO plane (ISSUE 20) needs "where did the p99 millisecond go" per
query class — which means every finished ``query`` / ``write`` /
``tile.render`` root must decompose into the SAME fixed set of stages
regardless of which physical spans it happened to record.  This module
is that mapping: pure functions over a :class:`~.trace.Trace`, no
registry access, no config reads — the SLO plane owns aggregation.

Attribution is **exclusive-time**: a span contributes its own wall ms
minus the summed wall ms of its direct children, clamped at zero.
Without the subtraction, a ``query.materialize`` chunk span that wraps
a ``query.scan.device`` device dispatch would bill the same
milliseconds to both stages and the ledger would sum past the root.

Three stages never appear as spans and come from root attributes
instead:

- ``queue`` — ``admission.queue_ms``: the admission gate acquires its
  ticket BEFORE the root span opens (deliberately: queue time is not
  the query's fault), so the wait is stamped onto the root afterwards.
- ``coalesce`` — ``coalesce.ms``: a fused query's non-executing wall
  inside the fusion scheduler — the coalescing-window linger plus
  wake-up/demux latency (datastore stamps ``submit wall - dispatch``).
- ``device_scan`` also absorbs ``fused.dispatch.ms`` — but ONLY when
  the trace has no ``serving.fuse`` span: the fusion LEADER runs the
  batch on its own request thread, so its trace already contains the
  fuse span as a child and counting the attribute too would double-
  bill the dispatch.  Riders (whose traces never see the fuse span)
  get the batch cost via the attribute.

``unattributed`` is the residual: root wall ms minus every in-root
stage (queue and web_drain happen OUTSIDE the root span's wall and are
excluded from the subtraction).  The acceptance gate keeps it under
10% of root wall on the warm fused bench.
"""

from __future__ import annotations

from .trace import Trace

__all__ = ["STAGES", "SPAN_STAGE", "attribute"]

#: the fixed stage ledger — every attribution result has exactly these
#: keys, so ``slo.<class>.stage.<stage>.ms`` is a closed metric family
STAGES = ("queue", "coalesce", "plan", "decompose", "device_scan",
          "host_scan", "post_filter", "materialize", "web_drain",
          "unattributed")

#: span name -> stage.  Unmapped spans (pure structural wrappers, or
#: future additions) fall into the residual, which is what makes the
#: residual gauge a watchdog for attribution drift.
SPAN_STAGE = {
    # query pipeline
    "query.plan": "plan",
    "query.replan": "plan",
    "query.decompose": "decompose",
    "query.scan.device": "device_scan",
    "query.scan.host": "host_scan",
    "query.scan.degraded": "host_scan",
    "query.post_filter": "post_filter",
    "query.materialize": "materialize",
    # fusion leader: the batch runs inline on the leader's thread
    "serving.fuse": "device_scan",
    # write pipeline
    "write.encode": "plan",
    "write.index": "decompose",
    "write.device": "device_scan",
    "write.spill": "device_scan",
    "write.seal": "host_scan",
    "write.observe": "post_filter",
    # tile rendering (density query under the hood)
    "lean.density": "device_scan",
    "lean.sketch": "plan",
}

#: stages whose time is OUTSIDE the root span's wall clock — excluded
#: from the residual subtraction and added on top for ``total_ms``
_OUT_OF_ROOT = ("queue", "web_drain", "unattributed")


def attribute(trace: Trace) -> dict | None:
    """Decompose ``trace`` into the stage ledger.

    Returns ``None`` for traces with no root span (nothing to
    attribute), else a dict::

        {"class": root name, "tenant": str, "trace_id": str,
         "total_ms": queue + root wall, "root_ms": root wall,
         "error": bool, "stages": {stage: ms for stage in STAGES}}
    """
    root = trace.root_span
    if root is None:
        return None

    ledger = {s: 0.0 for s in STAGES}

    # exclusive time per span: subtract direct children's wall ms
    child_ms: dict[str, float] = {}
    has_fuse_span = False
    for sp in trace.spans:
        if sp.parent_id is not None:
            child_ms[sp.parent_id] = (child_ms.get(sp.parent_id, 0.0)
                                      + sp.duration_ms)
        if sp.name == "serving.fuse":
            has_fuse_span = True
    for sp in trace.spans:
        if sp is root:
            continue
        stage = SPAN_STAGE.get(sp.name)
        if stage is None:
            continue
        excl = sp.duration_ms - child_ms.get(sp.span_id, 0.0)
        if excl > 0.0:
            ledger[stage] += excl

    attrs = root.attributes
    queue_ms = float(attrs.get("admission.queue_ms", 0.0) or 0.0)
    ledger["queue"] = queue_ms
    ledger["coalesce"] += float(attrs.get("coalesce.ms", 0.0) or 0.0)
    if not has_fuse_span:
        # rider: the batch ran on the leader's thread — the only record
        # of the device work is the stamped dispatch attribute
        ledger["device_scan"] += float(
            attrs.get("fused.dispatch.ms", 0.0) or 0.0)

    in_root = sum(ms for s, ms in ledger.items() if s not in _OUT_OF_ROOT)
    ledger["unattributed"] = max(0.0, root.duration_ms - in_root)

    return {
        "class": root.name,
        "tenant": str(attrs.get("tenant", "") or ""),
        "trace_id": trace.trace_id,
        "total_ms": queue_ms + root.duration_ms,
        "root_ms": root.duration_ms,
        "error": "error" in attrs,
        "stages": ledger,
    }
