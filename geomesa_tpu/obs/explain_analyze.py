"""EXPLAIN ANALYZE: the plan narration merged with measured actuals.

The reference's ``explainQuery`` (geomesa-index-api/.../index/planning/
QueryPlanner + Explainer) narrates what the planner WOULD do; this is
that surface with measured numbers (ISSUE 9): one API call runs the
query under a forced trace capture (obs/trace.Tracer.capture — records
regardless of the configured sampler), collects the planner's
hierarchical explain text AND the finished span tree, and renders them
merged — strategy choice with every option's estimated cost, the
chosen estimate (``plan.estimate.rows``), actual rows scanned/matched,
the mispredict ratio, per-phase wall ms and device ms.

Two entry points:

* :func:`explain_analyze` — one planner query against one schema
  (``TpuDataStore.explain_analyze`` delegates here; the web layer
  serves it at ``GET /explain?schema=...&cql=...``).
* :func:`explain_analyze_sql` — a SQL text (``sql.sql_query``); every
  store query the SQL executes inside the capture window is collected,
  so a join shows BOTH side's traces (``GET /explain?sql=...``).

Everything here is read-path observability: the query runs exactly as
it normally would (results included in the summary), and nothing
enters a collective beyond what the query itself does.
"""

from __future__ import annotations

import time

__all__ = ["ExplainAnalyzeResult", "explain_analyze",
           "explain_analyze_sql"]


def _span_tree(trace) -> dict | None:
    """Nest a finished trace's flat span list into a tree (children in
    start order), each node carrying name/ms/attributes."""
    if trace is None:
        return None
    children: dict = {}
    root = None
    for s in trace.spans:
        if s.parent_id is None:
            root = s
        else:
            children.setdefault(s.parent_id, []).append(s)

    def node(s) -> dict:
        kids = sorted(children.get(s.span_id, ()),
                      key=lambda c: c.start_ts)
        return {"name": s.name, "duration_ms": round(s.duration_ms, 3),
                "attributes": dict(s.attributes),
                "children": [node(c) for c in kids]}

    return node(root) if root is not None else None


def _fmt_attr(v):
    return round(v, 3) if isinstance(v, float) else v


def _render_tree(node: dict, lines: list, prefix: str = "",
                 last: bool = True) -> None:
    attrs = " ".join(f"{k}={_fmt_attr(v)}"
                     for k, v in node["attributes"].items()
                     if not isinstance(v, dict))
    tick = "└─ " if last else "├─ "
    lines.append(f"{prefix}{tick}{node['name']} "
                 f"{node['duration_ms']:.1f}ms"
                 + (f"  [{attrs}]" if attrs else ""))
    ext = "   " if last else "│  "
    kids = node["children"]
    for i, c in enumerate(kids):
        _render_tree(c, lines, prefix + ext, i == len(kids) - 1)


def _summary_from(trace) -> dict:
    """Pull the estimate-vs-actual numbers the planner stamped on the
    root span (planning/planner.run) into a flat summary."""
    out = {"trace_id": None, "duration_ms": 0.0}
    if trace is None or trace.root_span is None:
        return out
    root = trace.root_span
    a = root.attributes
    out.update({
        "trace_id": trace.trace_id,
        "duration_ms": round(trace.duration_ms, 3),
        "hits": a.get("hits"),
        "device_ms": a.get("device_ms"),
        "estimate_rows": a.get("plan.estimate.rows"),
        "actual_scanned": a.get("plan.actual.scanned"),
        "actual_matched": a.get("plan.actual.matched"),
        "estimate_ratio": a.get("plan.estimate.ratio"),
        "estimate_source": a.get("plan.estimate.source"),
        "replanned": bool(a.get("plan.replanned", False)),
    })
    for s in trace.spans:
        if s.name == "query.plan":
            out.setdefault("strategy", s.attributes.get("strategy"))
            opts = s.attributes.get("plan.options")
            if opts:
                out["options"] = opts
    return out


class ExplainAnalyzeResult:
    """One explain-analyze run: summary numbers, span tree(s), planner
    narration, and renderers (``render()`` text / ``to_json()``)."""

    def __init__(self, target: str, traces: list, plan_text: str = "",
                 result_summary: dict | None = None,
                 wall_ms: float = 0.0):
        #: what was explained: ``schema:<name>`` or ``sql``
        self.target = target
        self.traces = list(traces)
        self.plan_text = plan_text
        self.result_summary = result_summary or {}
        self.wall_ms = round(wall_ms, 3)

    @property
    def trace(self):
        """The primary (last-finished) trace, if any was recorded."""
        return self.traces[-1] if self.traces else None

    @property
    def summary(self) -> dict:
        return _summary_from(self.trace)

    def tree(self) -> dict | None:
        return _span_tree(self.trace)

    def to_json(self) -> dict:
        return {
            "target": self.target,
            "wall_ms": self.wall_ms,
            "summary": self.summary,
            "result": self.result_summary,
            "plans": [_span_tree(t) for t in self.traces],
            "narration": self.plan_text.splitlines(),
        }

    def render(self) -> str:
        lines = [f"EXPLAIN ANALYZE {self.target} "
                 f"({self.wall_ms:.1f}ms wall)"]
        s = self.summary
        if s.get("trace_id"):
            est, act = s.get("estimate_rows"), s.get("actual_scanned")
            lines.append(
                f"  strategy={s.get('strategy')} "
                f"estimated_rows={est} "
                f"({s.get('estimate_source') or 'heuristic'}) "
                f"scanned={act} "
                f"matched={s.get('actual_matched')} "
                f"ratio={s.get('estimate_ratio')}x "
                f"hits={s.get('hits')} "
                f"device_ms={_fmt_attr(s.get('device_ms'))}"
                + (" REPLANNED" if s.get("replanned") else ""))
            if s.get("options"):
                opts = " ".join(f"{k}={v}"
                                for k, v in s["options"].items())
                lines.append(f"  options: {opts}")
        for t in self.traces:
            tree = _span_tree(t)
            if tree is not None:
                _render_tree(tree, lines)
        if self.plan_text:
            lines.append("Plan narration:")
            lines.extend("  " + ln for ln in self.plan_text.splitlines())
        return "\n".join(lines)


def explain_analyze(store, name: str, query="INCLUDE"
                    ) -> ExplainAnalyzeResult:
    """Run one planner query under forced trace capture and return the
    merged plan + actuals (module doc)."""
    from ..planning.explain import ExplainString
    from ..planning.planner import Query
    from .trace import tracer
    q = query if isinstance(query, Query) else Query.of(query)
    ex = ExplainString()
    t0 = time.perf_counter()
    with tracer.capture() as cap:
        result = store.query_result(name, q, explain=ex)
    wall_ms = (time.perf_counter() - t0) * 1e3
    return ExplainAnalyzeResult(
        target=f"schema:{name}", traces=cap.traces(),
        plan_text=str(ex),
        result_summary={"hits": int(len(result.positions)),
                        "strategy": result.strategy.index,
                        "plan_ms": round(result.plan_time_ms, 3),
                        "scan_ms": round(result.scan_time_ms, 3)},
        wall_ms=wall_ms)


def explain_analyze_sql(store, text: str) -> ExplainAnalyzeResult:
    """Run a SQL text under forced trace capture; every store query it
    executes (both sides of a join, per-branch scans) is collected."""
    from ..sql import sql_query
    from .trace import tracer
    t0 = time.perf_counter()
    with tracer.capture(capacity=64) as cap:
        value = sql_query(store, text)
    wall_ms = (time.perf_counter() - t0) * 1e3
    if hasattr(value, "__len__") and not isinstance(value, (str, dict)):
        result = {"rows": int(len(value))}
    elif isinstance(value, dict):
        result = {"columns": sorted(value)}
    else:
        result = {"value": value if isinstance(value, (int, float, str))
                  else str(value)}
    return ExplainAnalyzeResult(target="sql", traces=cap.traces(),
                                result_summary=result, wall_ms=wall_ms)
