"""Storage/HBM accounting: where the bytes live, audited.

The resource half of ISSUE 9.  Every lean index tier already *budgets*
HBM from per-slot constants (``device_bytes``/``host_key_bytes`` and
the ``hbm_budget_bytes`` rebalance); this module turns that accounting
into an operator surface and — crucially — AUDITS it:

* :func:`storage_report` walks a :class:`TpuDataStore` and collects
  every index's ``storage_stats()`` (the accounted view: device runs
  vs host-spilled runs, per generation, sentinel padding buffers, the
  sealed-partial density/sketch caches) plus the column store's host
  bytes, and then independently re-derives the SAME totals from
  **actual array nbytes** (jax/numpy buffers walked generically).  The
  two views reconcile per direction with a documented tolerance — a
  drift means the budget constants no longer match the real dtypes,
  i.e. the HBM budget itself is silently wrong (the failure mode that
  busts "1B rows on fixed HBM").
* :func:`publish_storage_gauges` folds the report into ``storage.*``
  registry gauges so ``/metrics.prom`` scrapes resident bytes like any
  other metric (mesh-wide views SUM per-process gauges through
  ``metrics.merge_snapshots`` — host residency is per-process).
* ``GET /debug/storage`` (web/app.py) serves the full report.

Reconciliation tolerances (pinned by tests/test_zz_resource_obs.py):

* **device**: exact (1% float slack).  Device runs are fixed-capacity
  columns of the exact dtypes the constants describe.
* **host**: accounted may OVERSTATE actual by up to 35%.  Spilled-run
  accounting charges ``KEYS_BYTES`` per row, but once runs fold into
  the stacked host store (z3 HostStack) the bin column is recovered
  from the segment table instead of being stored — 4 of 16/20 bytes
  per row evaporate.
* **sentinel**: accounted may overstate by up to 25% — the full-tier
  sentinel shares one zeros buffer between its x and y columns.
* **caches**: exact (partials self-report ``nbytes``).

Tolerances are ONE-directional: they excuse overstatement only.
Accounting that UNDERSTATES actual residency beyond 1% float slack
fails in every direction — real bytes exceeding what the budget
believes is exactly the failure the audit exists to catch.

Per-generation byte detail lives in the REPORT, not the registry —
generation ids churn under compaction and gauges must stay a bounded
key set (docs/observability.md naming contract).
"""

from __future__ import annotations

import threading
import time

from ..metrics import registry as _metrics

__all__ = ["storage_report", "publish_storage_gauges",
           "index_actual_nbytes"]

#: documented reconciliation tolerances, percent of actual (module doc)
TOLERANCE_PCT = {"device": 1.0, "host": 35.0, "sentinel": 25.0,
                 "cache": 1.0}

#: array attributes a generation may carry, across every lean variant
#: (z3: bins/z/pos/x/y/t — attr/xz: keys/sec/gid)
_GEN_ARRAYS = ("bins", "z", "pos", "x", "y", "t", "keys", "sec", "gid")
#: array attributes of a spilled HostRun (z3 family)
_RUN_ARRAYS = ("bins", "z", "pos")


def _add_arrays(total: int, seen: set, *arrays) -> int:
    """Sum ``nbytes`` over arrays, deduplicated by identity — sentinel
    tuples alias one zeros buffer for x AND y, and re-pointed host-run
    views must not double-count against their owning stack."""
    for a in arrays:
        if a is None or isinstance(a, (int, float)):
            continue
        if id(a) in seen:
            continue
        seen.add(id(a))
        total += int(getattr(a, "nbytes", 0))
    return total


def _spilled_bytes(sp, seen: set) -> int:
    """Bytes of an attr-core ``spilled`` payload: one ``[k, s, g]``
    part (single-chip) or a list of parts (sharded)."""
    if not sp:
        return 0
    if isinstance(sp[0], (list, tuple)):
        return sum(_spilled_bytes(p, seen) for p in sp)
    total = 0
    for a in sp:
        total = _add_arrays(total, seen, a)
    return total


def index_actual_nbytes(idx) -> dict:
    """Independently re-derive one lean index's resident bytes from
    ACTUAL array nbytes (device runs, host runs, sentinel buffers,
    partial caches) — the audit side of the reconciliation.  Works
    across all six lean variants by walking the generation/sentinel
    shapes generically; facades are unwrapped via ``_core``."""
    core = getattr(idx, "_core", idx)
    seen: set = set()
    dev = host = 0
    for g in getattr(core, "generations", ()):
        if getattr(g, "tier", None) == "host":
            run = getattr(g, "run", None)
            if run is not None:
                host = _add_arrays(host, seen,
                                   *(getattr(run, n, None)
                                     for n in _RUN_ARRAYS))
            for r in (getattr(g, "runs", None) or ()):
                host = _add_arrays(host, seen,
                                   *(getattr(r, n, None)
                                     for n in _RUN_ARRAYS))
            host += _spilled_bytes(getattr(g, "spilled", None), seen)
        else:
            dev = _add_arrays(dev, seen,
                              *(getattr(g, n, None)
                                for n in _GEN_ARRAYS))
    sent = 0
    sentinels = getattr(core, "_sentinels", None)
    if isinstance(sentinels, dict):
        for v in sentinels.values():
            if isinstance(v, tuple):
                sent = _add_arrays(sent, seen, *v)
            else:   # a sharded sentinel generation object
                sent = _add_arrays(sent, seen,
                                   *(getattr(v, n, None)
                                     for n in _GEN_ARRAYS))
    tup = getattr(core, "_sentinel", None)
    if isinstance(tup, tuple):
        sent = _add_arrays(sent, seen, *tup)
    gen = getattr(core, "_sentinel_gen", None)
    if gen is not None:
        sent = _add_arrays(sent, seen,
                           *(getattr(gen, n, None) for n in _GEN_ARRAYS))
    cache = 0
    for name in ("_density_cache", "_sketch_cache"):
        c = getattr(core, name, None)
        if c is not None:
            cache += int(c.cached_bytes())
    return {"device_bytes": dev, "host_bytes": host,
            "sentinel_bytes": sent, "cache_bytes": cache}


def _accounted_cache_bytes(stats: dict) -> int:
    return sum(int(c.get("bytes", 0))
               for c in (stats.get("caches") or {}).values())


def _batch_bytes(batch) -> int:
    """Host bytes of a schema's column store: LeanBatch.host_bytes for
    the lean profile, summed column nbytes for a plain FeatureBatch."""
    if batch is None:
        return 0
    if hasattr(batch, "host_bytes"):
        return int(batch.host_bytes())
    total, seen = 0, set()
    total = _add_arrays(total, seen, *getattr(batch, "columns", {}).values())
    return total


def _reconcile(accounted: int, actual: int, kind: str) -> dict:
    """One-DIRECTIONAL verdict: the per-kind tolerance only excuses
    OVERSTATEMENT (accounting charges bytes the arrays dropped — the
    bins-recovered / shared-zeros cases in the module doc);
    UNDERSTATEMENT beyond float slack means real residency exceeds
    what the budget believes — the dangerous direction — and always
    fails."""
    tol_over = TOLERANCE_PCT[kind]
    tol_under = TOLERANCE_PCT["device"]     # 1% slack, every kind
    if actual:
        delta_pct = (accounted - actual) / actual * 100.0
    else:
        delta_pct = 100.0 if accounted else 0.0
    return {"accounted": int(accounted), "actual": int(actual),
            "delta_pct": round(delta_pct, 2), "tolerance_pct": tol_over,
            "ok": -tol_under <= delta_pct <= tol_over}


def storage_report(store, audit: bool = True) -> dict:
    """Walk a TpuDataStore: accounted storage per schema/index, actual
    nbytes audit, and the reconciliation verdict (module doc).

    ``audit=False`` skips the actual-nbytes walk and reconciliation —
    the cheap accounted-only form the per-scrape gauge refresh uses
    (the gauges publish accounted values; re-walking every resident
    array on a 15-second scrape cadence would be pure waste)."""
    schemas: dict = {}
    acc = {"device_bytes": 0, "host_bytes": 0, "sentinel_bytes": 0,
           "cache_bytes": 0, "batch_bytes": 0}
    act = {"device_bytes": 0, "host_bytes": 0, "sentinel_bytes": 0,
           "cache_bytes": 0}
    for name, s in store._schemas.items():
        batch_bytes = _batch_bytes(s.batch)
        entry: dict = {
            "rows": 0 if s.batch is None else len(s.batch),
            "lean": bool(s.lean),
            "batch_host_bytes": batch_bytes,
            "indexes": {},
        }
        acc["batch_bytes"] += batch_bytes
        for key, idx in s._indexes.items():
            if hasattr(idx, "storage_stats"):
                st = idx.storage_stats()
                acc["device_bytes"] += int(st.get("device_bytes", 0))
                acc["host_bytes"] += int(st.get("host_bytes", 0))
                acc["sentinel_bytes"] += int(st.get("sentinel_bytes", 0))
                acc["cache_bytes"] += _accounted_cache_bytes(st)
                if audit:
                    actual = index_actual_nbytes(idx)
                    st["actual"] = actual
                    act["device_bytes"] += actual["device_bytes"]
                    act["host_bytes"] += actual["host_bytes"]
                    act["sentinel_bytes"] += actual["sentinel_bytes"]
                    act["cache_bytes"] += actual["cache_bytes"]
            else:
                # non-generational (full-fat) indexes: presence + rows
                # only — their residency is the batch's columns, which
                # batch_host_bytes already covers
                st = {"kind": type(idx).__name__}
                try:
                    st["rows"] = len(idx)
                except TypeError:
                    pass
            entry["indexes"][key] = st
        schemas[name] = entry
    out = {
        "generated_ts": round(time.time(), 3),
        "schemas": schemas,
        "totals": dict(acc),
    }
    if audit:
        recon = {
            "device": _reconcile(acc["device_bytes"],
                                 act["device_bytes"], "device"),
            "host": _reconcile(acc["host_bytes"], act["host_bytes"],
                               "host"),
            "sentinel": _reconcile(acc["sentinel_bytes"],
                                   act["sentinel_bytes"], "sentinel"),
            "cache": _reconcile(acc["cache_bytes"], act["cache_bytes"],
                                "cache"),
        }
        out["actual_totals"] = dict(act)
        out["reconciliation"] = {
            **recon,
            "within_tolerance": all(v["ok"] for v in recon.values()),
        }
    return out


#: serializes gauge publication — concurrent scrapes must not race the
#: publish-then-retire sequence
_publish_lock = threading.Lock()


def publish_storage_gauges(store, report: dict | None = None) -> dict:
    """Set the ``storage.*`` registry gauges from a (fresh or given)
    storage report, so resident bytes scrape from ``/metrics.prom``
    alongside every other metric.  Returns the report used (fresh
    reports skip the nbytes audit — gauges only need accounted values).

    Gauge taxonomy (docs/observability.md):

    * ``storage.total.{device,host,sentinel,cache,batch}_bytes``
    * ``storage.<schema>.batch_bytes``
    * ``storage.<schema>.<index>.{device,host,cache}_bytes``

    Under multihost, device/sentinel values are divided by the process
    count before publishing: every process accounts the same mesh-wide
    HBM, and the mesh scrape (``/metrics.prom?mesh=1``) SUMS gauges
    across processes — publishing each process's SHARE makes the
    merged total read true resident bytes, not N× them.  Host/batch/
    cache bytes are genuinely per-process and publish unscaled.

    The previously-published key set is tracked PER STORE (two stores
    sharing one process registry must not retire each other's live
    gauges); publishes serialize on a module lock so concurrent
    scrapes cannot race the publish-then-retire sequence."""
    report = (report if report is not None
              else storage_report(store, audit=False))
    procs = 1
    if getattr(store, "_multihost", False):
        import jax
        procs = max(1, jax.process_count())

    published: set = set()

    def _set(key: str, value, shared: bool = False) -> None:
        _metrics.gauge(key).set(value / procs if shared else value)
        published.add(key)

    with _publish_lock:
        for schema, entry in report["schemas"].items():
            _set(f"storage.{schema}.batch_bytes",
                 entry["batch_host_bytes"])
            for key, st in entry["indexes"].items():
                if "device_bytes" not in st:
                    continue
                base = f"storage.{schema}.{key}"
                _set(f"{base}.device_bytes", st["device_bytes"],
                     shared=True)
                _set(f"{base}.host_bytes", st["host_bytes"])
                _set(f"{base}.cache_bytes", _accounted_cache_bytes(st))
        # totals LAST so a schema literally named "total" can never
        # leave its per-schema values in the process-total keys
        tot = report["totals"]
        for leaf in ("device_bytes", "sentinel_bytes"):
            _set(f"storage.total.{leaf}", tot[leaf], shared=True)
        for leaf in ("host_bytes", "cache_bytes", "batch_bytes"):
            _set(f"storage.total.{leaf}", tot[leaf])
        prev = getattr(store, "_storage_gauge_keys", set())
        for stale in prev - published:
            _metrics.remove(stale)
        store._storage_gauge_keys = published
    return report
