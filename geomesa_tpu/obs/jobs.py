"""Background-job registry: ingest/compaction runs as first-class,
inspectable records.

The job half of ISSUE 12.  ``IngestJob``/``CompactionJob`` (jobs.py)
used to run invisibly — an ingest stall or a compaction storm left no
trace beyond its side effects.  Every run now registers here:

* a :class:`JobRecord` with a process-unique id, kind, free-form
  detail, **phase spans** (name + wall ms + attributes, recorded in
  the registry itself so ``/debug/jobs`` sees them even when the
  tracer's sampler declined the trace), live **progress** counters,
  and a **terminal outcome** — ``succeeded`` or ``failed`` (with the
  error), stamped even when the job raises;
* each run also opens a ``job.<kind>`` root span (phases are
  ``job.phase`` children), so a sampled job's trace appears in
  ``/traces`` with the job id linking the two surfaces;
* ``job.<kind>.runs`` / ``job.<kind>.failures`` counters and a
  ``job.<kind>.ms`` timer land in the shared registry (the ``job``
  namespace of the metric naming contract);
* ``GET /debug/jobs`` (web/app.py) lists active + recent records,
  newest first, with ``?kind=`` / ``?state=`` / ``?limit=`` filters.

Finished records are retained in a bounded deque
(``geomesa.obs.jobs.capacity``); active records live until their
context exits.  Registration is process-local and thread-safe —
concurrent jobs (an ingest racing a compaction) record independently.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import deque

from ..config import ObsProperties
from ..metrics import registry as _metrics
from .trace import current_trace_id, span as obs_span

__all__ = ["JobRecord", "JobRegistry", "jobs_registry"]


class JobRecord:
    """One job run.  ``state`` walks running → succeeded | failed."""

    __slots__ = ("job_id", "kind", "detail", "state", "start_ts",
                 "end_ts", "duration_ms", "phases", "progress", "error",
                 "trace_id")

    def __init__(self, job_id: str, kind: str, detail: dict):
        self.job_id = job_id
        self.kind = kind
        self.detail = detail
        self.state = "running"
        self.start_ts = time.time()
        self.end_ts = 0.0
        self.duration_ms = 0.0
        self.phases: list[dict] = []
        self.progress: dict = {}
        self.error = ""
        self.trace_id = ""

    def to_json(self) -> dict:
        return {"job_id": self.job_id, "kind": self.kind,
                "detail": dict(self.detail), "state": self.state,
                "start_ts": round(self.start_ts, 3),
                "end_ts": round(self.end_ts, 3),
                "duration_ms": round(self.duration_ms, 3),
                "phases": [dict(p) for p in self.phases],
                "progress": dict(self.progress), "error": self.error,
                "trace_id": self.trace_id}


class _ActiveJob:
    """The handle a running job drives: phases + progress."""

    def __init__(self, record: JobRecord):
        self.record = record

    @property
    def job_id(self) -> str:
        return self.record.job_id

    @contextlib.contextmanager
    def phase(self, name: str, **attributes):
        """One timed phase: recorded into the registry record always,
        and as a ``job.phase`` child span when the trace records."""
        entry = {"name": name, "ms": 0.0, **attributes}
        t0 = time.perf_counter()
        try:
            with obs_span("job.phase", job=self.record.kind,
                          phase=name, **attributes):
                yield entry
        finally:
            entry["ms"] = round((time.perf_counter() - t0) * 1e3, 3)
            self.record.phases.append(entry)

    def progress(self, **counters) -> None:
        """Merge live progress counters (files done, rows ingested…)
        into the record — readable from /debug/jobs mid-run."""
        self.record.progress.update(counters)


class JobRegistry:
    """Process-wide registry of active + recently-finished jobs."""

    def __init__(self, capacity: int | None = None):
        self._capacity_override = capacity
        #: guarded-by: self._lock — concurrent jobs register/retire here
        self._active: dict[str, JobRecord] = {}
        #: guarded-by: self._lock — bounded finished-record ring
        self._recent: deque[JobRecord] = deque()
        #: guarded-by: self._lock — the process-unique id source
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def _capacity(self) -> int:
        """Retention re-resolves per finished job (live-tunable, like
        every other ``geomesa.obs.*`` knob) unless pinned for tests."""
        if self._capacity_override is not None:
            return max(1, int(self._capacity_override))
        return max(1, ObsProperties.JOBS_CAPACITY.to_int())

    @contextlib.contextmanager
    def run(self, kind: str, **detail):
        """Register one job run: yields the :class:`_ActiveJob`
        handle; the record gets a terminal outcome on EVERY exit path
        (an exception marks it failed with the error and re-raises —
        a crashed ingest must be visible, not vanish)."""
        with self._lock:
            job_id = f"{kind}-{next(self._ids)}"
            rec = JobRecord(job_id, kind, detail)
            self._active[job_id] = rec
        _metrics.counter(f"job.{kind}.runs").inc()
        t0 = time.perf_counter()
        try:
            with obs_span(f"job.{kind}", job_id=job_id, **detail):
                rec.trace_id = current_trace_id()
                yield _ActiveJob(rec)
            rec.state = "succeeded"
        except BaseException as e:
            rec.state = "failed"
            rec.error = repr(e)
            _metrics.counter(f"job.{kind}.failures").inc()
            raise
        finally:
            rec.duration_ms = (time.perf_counter() - t0) * 1e3
            rec.end_ts = time.time()
            _metrics.timer(f"job.{kind}.ms").update(rec.duration_ms)
            with self._lock:
                self._active.pop(job_id, None)
                self._recent.append(rec)
                cap = self._capacity()
                while len(self._recent) > cap:
                    self._recent.popleft()

    def jobs(self, kind: str | None = None, state: str | None = None,
             limit: int | None = None) -> list[JobRecord]:
        """Active jobs first, then finished newest-first."""
        with self._lock:
            rows = list(self._active.values()) + list(
                reversed(self._recent))
        if kind is not None:
            rows = [r for r in rows if r.kind == kind]
        if state is not None:
            rows = [r for r in rows if r.state == state]
        return rows if limit is None else rows[:limit]

    def get(self, job_id: str) -> JobRecord | None:
        with self._lock:
            rec = self._active.get(job_id)
            if rec is not None:
                return rec
            for r in self._recent:
                if r.job_id == job_id:
                    return r
        return None

    def clear(self) -> None:
        """Drop FINISHED records (tests); active jobs keep running."""
        with self._lock:
            self._recent.clear()


#: process-wide registry (the tracer/heat_tracker analog for jobs)
jobs_registry = JobRegistry()
