"""geomesa_tpu: a TPU-native spatio-temporal indexing and analytics framework.

A ground-up re-design of the capabilities of GeoMesa (reference:
/root/reference, v2.4.0-SNAPSHOT) for TPU hardware: space-filling-curve
indexing of point/line/polygon + time data, cost-based query planning with
z-range decomposition, pushed-down candidate filtering, and distributed
aggregation — expressed as JAX/XLA array programs over HBM-resident
structure-of-arrays columns, sharded across device meshes.

Where the reference keeps rows in distributed sorted KV stores and runs
filters in server-side iterators (Accumulo iterators / HBase coprocessors),
this framework keeps sorted SoA columns in HBM, vmaps curve encoding and
predicate masks over millions of features per chip, and reduces aggregates
over ICI with `jax.lax.psum`.

The library requires 64-bit integer support (z-values are 62/63-bit morton
codes, matching the reference's key layout, e.g.
geomesa-z3/.../curve/Z3SFC.scala:21 — 21 bits/dim × 3 dims); x64 mode is
enabled at import.
"""

from jax import config as _jax_config

_jax_config.update("jax_enable_x64", True)

__version__ = "0.1.0"
