"""geomesa_tpu: a TPU-native spatio-temporal indexing and analytics framework.

A ground-up re-design of the capabilities of GeoMesa (reference:
/root/reference, v2.4.0-SNAPSHOT) for TPU hardware: space-filling-curve
indexing of point/line/polygon + time data, cost-based query planning with
z-range decomposition, pushed-down candidate filtering, and distributed
aggregation — expressed as JAX/XLA array programs over HBM-resident
structure-of-arrays columns, sharded across device meshes.

Where the reference keeps rows in distributed sorted KV stores and runs
filters in server-side iterators (Accumulo iterators / HBase coprocessors),
this framework keeps sorted SoA columns in HBM, vmaps curve encoding and
predicate masks over millions of features per chip, and reduces aggregates
over ICI with `jax.lax.psum`.

The library requires 64-bit integer support (z-values are 62/63-bit morton
codes, matching the reference's key layout, e.g.
geomesa-z3/.../curve/Z3SFC.scala:21 — 21 bits/dim × 3 dims); x64 mode is
enabled here for whenever jax loads — WITHOUT importing jax: the package
``__init__`` must stay pure-stdlib so that jax-free subpackages (the
``analysis`` static analyzer, which cold CI shards run with no
accelerator stack) import without dragging in the device runtime
(pinned by a subprocess test in tests/test_zzzz_static_analysis.py).
``JAX_ENABLE_X64`` is read by jax's config at its own import; if some
embedder imported jax *first*, the live config is updated instead —
both paths land exactly where the old eager ``jax.config.update``
did.

One DELIBERATE difference from the old in-process update: the env var
is inherited by child processes, so jax workers an embedder spawns
after importing this package also run x64.  For this library that is
the correct default — its multihost/benchmark subprocesses need the
same 64-bit keys — but an embedder spawning unrelated jax children
can override by clearing ``JAX_ENABLE_X64`` in the child env.
"""

import os as _os
import sys as _sys

_os.environ["JAX_ENABLE_X64"] = "1"
if "jax" in _sys.modules:  # jax beat us here: flip the live config too
    _sys.modules["jax"].config.update("jax_enable_x64", True)

__version__ = "0.1.0"
