"""XZ2 curve: extended-Z ordering for objects with spatial extension.

Implements the XZ-Ordering scheme (Böhm, Klump & Kriegel: "XZ-Ordering: A
Space-Filling Curve for Objects with Spatial Extension") that the reference
uses to index non-point geometries by bounding box
(geomesa-z3/.../curve/XZ2SFC.scala):

* An object's bbox is assigned the quadtree cell whose *extended* footprint
  (the cell doubled in width and height) encloses it, at the deepest
  possible resolution ``length ≤ g`` (XZ2SFC.scala:54-77).
* Cells are numbered by *sequence codes*: a pre-order quadtree numbering
  where entering quadrant ``q`` at depth ``i`` adds
  ``1 + q·(4^(g-i)-1)/3`` (Definition 2; XZ2SFC.scala:264-286).
* A query window is covered by the union of (a) full subtree intervals
  ``[cs, cs + (4^(g-l+1)-1)/3]`` for contained cells (Lemma 3;
  XZ2SFC.scala:297-306) and (b) singleton intervals ``[cs, cs]`` for every
  overlapping ancestor cell — the latter catch *large* objects stored at
  coarse cells.

TPU-first design notes: the reference's per-object ``sequenceCode`` is a
data-dependent double-precision descent loop.  Here the descent is
algebraic: the quadrant digit at depth ``i`` is a bit pair of the
integerized cell coordinates, so a whole batch of bboxes is encoded with
``g`` fixed vectorized steps (no branching) — jit/vmap friendly, runs on
the VPU.  Range decomposition is the same level-synchronous frontier sweep
as :mod:`geomesa_tpu.curve.ranges` (replacing the reference's work-queue
BFS, XZ2SFC.scala:146-252), on host.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax.numpy as jnp
import numpy as np

from ..config import DEFAULT_MAX_RANGES

__all__ = ["XZ2SFC", "xz2_sfc", "DEFAULT_G"]

DEFAULT_G = 12  # reference default XZ precision (geomesa.xz.precision)


def _iv_table(g: int) -> np.ndarray:
    """IV[i] = (4^(g-i) - 1) / 3 for i in [0, g] — the subtree sizes."""
    if g > 30:
        raise ValueError("g must be <= 30 to fit sequence codes in int64")
    return np.array([(4 ** (g - i) - 1) // 3 for i in range(g + 1)],
                    dtype=np.int64)


@dataclass(frozen=True)
class XZ2SFC:
    """XZ2 curve over a lon/lat (or custom) 2-D domain, resolution ``g``."""

    g: int = DEFAULT_G
    x_lo: float = -180.0
    x_hi: float = 180.0
    y_lo: float = -90.0
    y_hi: float = 90.0

    # -- normalization ----------------------------------------------------
    def _normalize(self, xmin, ymin, xmax, ymax, xp):
        xs = self.x_hi - self.x_lo
        ys = self.y_hi - self.y_lo
        nxmin = xp.clip((xp.asarray(xmin, xp.float64) - self.x_lo) / xs, 0.0, 1.0)
        nymin = xp.clip((xp.asarray(ymin, xp.float64) - self.y_lo) / ys, 0.0, 1.0)
        nxmax = xp.clip((xp.asarray(xmax, xp.float64) - self.x_lo) / xs, 0.0, 1.0)
        nymax = xp.clip((xp.asarray(ymax, xp.float64) - self.y_lo) / ys, 0.0, 1.0)
        return nxmin, nymin, nxmax, nymax

    # -- encode -----------------------------------------------------------
    def index(self, xmin, ymin, xmax, ymax, xp=jnp):
        """Vectorized bbox → sequence code (int64).

        Matches XZ2SFC.index: resolution = min(g, l1 or l1+1) where
        l1 = floor(-log2(max bbox side)) and l1+1 applies when the bbox
        spans at most two cells at that finer resolution on both axes.
        """
        g = self.g
        nxmin, nymin, nxmax, nymax = self._normalize(xmin, ymin, xmax, ymax, xp)

        max_dim = xp.maximum(nxmax - nxmin, nymax - nymin)
        # l1 = floor(log(maxDim) / log(0.5)) — same float formula as the
        # reference so length choices agree to the ulp; maxDim == 0 → g
        log_half = float(np.log(0.5))
        with np.errstate(divide="ignore"):
            l1 = xp.where(
                max_dim > 0.0,
                xp.floor(xp.log(xp.maximum(max_dim, 1e-300)) / log_half).astype(xp.int32),
                g,
            )
        l1 = xp.clip(l1, 0, g)
        # check if the finer level l1+1 still fits: the object must span at
        # most 2 cells of width w2 on each axis
        w2 = xp.exp2(-(l1 + 1).astype(xp.float64))
        fits_x = nxmax <= xp.floor(nxmin / w2) * w2 + 2.0 * w2
        fits_y = nymax <= xp.floor(nymin / w2) * w2 + 2.0 * w2
        length = xp.where((l1 < g) & fits_x & fits_y, l1 + 1, l1)

        return self._sequence_code(nxmin, nymin, length, xp)

    def _sequence_code(self, nx, ny, length, xp):
        """Sequence code of the cell containing (nx, ny) at depth ``length``.

        Algebraic form of the reference's descent: quadrant digit at depth i
        is ``bit_x(i) + 2*bit_y(i)`` of the integerized coordinates, so
        ``cs = length + Σ_{i<length} digit_i * IV[i]``.
        """
        g = self.g
        iv = xp.asarray(_iv_table(g))
        scale = float(1 << g)
        kx = xp.minimum(xp.floor(nx * scale), scale - 1).astype(xp.int64)
        ky = xp.minimum(xp.floor(ny * scale), scale - 1).astype(xp.int64)
        cs = xp.asarray(length, xp.int64) + xp.zeros_like(kx)
        length = xp.asarray(length)
        for i in range(g):
            bx = (kx >> (g - 1 - i)) & 1
            by = (ky >> (g - 1 - i)) & 1
            digit = bx + 2 * by
            cs = cs + xp.where(i < length, digit * iv[i], 0)
        return cs

    # -- decompose --------------------------------------------------------
    def ranges(self, queries, max_ranges: int | None = None) -> np.ndarray:
        """Covering sequence-code ranges for OR'd query windows.

        Level-synchronous sweep (host numpy): at each level the frontier of
        candidate cells is classified against all windows at once using the
        *extended* footprints; contained cells emit full subtree intervals,
        overlapping cells emit their singleton code and descend.  Returns
        merged ``(R, 2)`` int64 inclusive ranges.
        """
        budget = DEFAULT_MAX_RANGES if max_ranges is None else int(max_ranges)
        g = self.g
        iv = _iv_table(g)
        windows = np.atleast_2d(np.asarray(queries, dtype=np.float64))
        wxmin, wymin, wxmax, wymax = self._normalize(
            windows[:, 0], windows[:, 1], windows[:, 2], windows[:, 3], np
        )

        from .. import native

        res = native.xz_ranges_native(
            np.stack([wxmin, wymin], axis=1), np.stack([wxmax, wymax], axis=1),
            dims=2, g=g, budget=budget)
        if res is not None:
            return res

        # frontier: integer cell coords (kx, ky) at the current level and the
        # running sequence code prefix of each cell
        kx = np.array([0], dtype=np.int64)
        ky = np.array([0], dtype=np.int64)
        cs = np.array([0], dtype=np.int64)  # code of the parent prefix path
        out_lo: list[np.ndarray] = []
        out_hi: list[np.ndarray] = []
        emitted = 0

        for level in range(1, g + 1):
            if kx.size == 0:
                break
            # expand to children: quadrant digit q ∈ {0,1,2,3} = bx + 2*by
            q = np.arange(4, dtype=np.int64)
            bx, by = q & 1, q >> 1
            ckx = (kx[:, None] << 1) + bx[None, :]
            cky = (ky[:, None] << 1) + by[None, :]
            # child code: entering quadrant q at depth (level-1) adds
            # 1 + q * IV[level-1]
            ccs = cs[:, None] + 1 + q[None, :] * iv[level - 1]
            ckx, cky, ccs = ckx.ravel(), cky.ravel(), ccs.ravel()

            w = 0.5 ** level
            x0 = ckx * w
            y0 = cky * w
            xe = x0 + 2 * w  # extended footprint
            ye = y0 + 2 * w
            contained = (
                (wxmin[None, :] <= x0[:, None])
                & (wymin[None, :] <= y0[:, None])
                & (wxmax[None, :] >= xe[:, None])
                & (wymax[None, :] >= ye[:, None])
            ).any(axis=1)
            overlaps = (
                (wxmax[None, :] >= x0[:, None])
                & (wymax[None, :] >= y0[:, None])
                & (wxmin[None, :] <= xe[:, None])
                & (wymin[None, :] <= ye[:, None])
            ).any(axis=1)

            full = contained
            partial = overlaps & ~contained
            if full.any():
                c = ccs[full]
                out_lo.append(c)
                out_hi.append(c + iv[level - 1])  # Lemma 3: (4^(g-l+1)-1)/3
                emitted += c.size
            if not partial.any():
                kx = np.empty(0, dtype=np.int64)
                break
            rest_kx, rest_ky, rest_cs = ckx[partial], cky[partial], ccs[partial]
            if level == g or emitted + rest_cs.size * 4 > budget:
                # bottom out: cover each remaining cell's whole subtree
                out_lo.append(rest_cs)
                out_hi.append(rest_cs + iv[level - 1])
                kx = np.empty(0, dtype=np.int64)
                break
            # partial matches emit their own code (large objects stored at
            # this cell) and descend
            out_lo.append(rest_cs)
            out_hi.append(rest_cs.copy())
            emitted += rest_cs.size
            kx, ky, cs = rest_kx, rest_ky, rest_cs

        from .ranges import merge_ranges

        if not out_lo:
            return np.empty((0, 2), dtype=np.int64)
        los = np.concatenate(out_lo)
        his = np.concatenate(out_hi)
        return merge_ranges(los, his)


@lru_cache(maxsize=None)
def xz2_sfc(g: int = DEFAULT_G) -> XZ2SFC:
    return XZ2SFC(g)
