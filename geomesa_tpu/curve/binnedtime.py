"""Time binning: epoch millis → (bin, offset) per Day/Week/Month/Year period.

Matches the reference's ``BinnedTime`` (geomesa-z3/.../curve/BinnedTime.scala):

=======  ====================  ==============  =============
period   bin                   offset          max date
=======  ====================  ==============  =============
day      days since epoch      millis in day   2059-09-18
week     weeks since epoch     seconds in wk   2598-01-04
month    months since epoch    seconds in mo   4700-08-31
year     years since epoch     minutes in yr   34737-12-31
=======  ====================  ==============  =============

Bins are int16 ("Short"), offsets int64.  Day/Week are pure integer
division; Month/Year are calendar-aware and computed with numpy datetime64
month/year arithmetic on host (the "host LUT" strategy — these run during
ingest key-gen and query planning, never inside a jitted kernel; device
kernels only ever see the resulting ``(bin, offset)`` ints).

``max_offset`` values (BinnedTime.scala maxOffset): day 86_400_000 ms,
week 604_800 s, month 31*86_400 s, year 52*7*24*60 min — note month/year
use a fixed upper bound, not per-bin actual length, so the time dimension
normalizer is period-independent.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np

__all__ = [
    "TimePeriod", "BinnedTime", "max_offset", "to_binned_time",
    "from_binned_time", "time_to_bin", "max_date_ms", "bin_to_ms",
]

MS_PER_DAY = 86_400_000
MS_PER_WEEK = 7 * MS_PER_DAY
MAX_BIN = 32767  # int16 max; bins are "Short" in the reference


class TimePeriod(str, enum.Enum):
    DAY = "day"
    WEEK = "week"
    MONTH = "month"
    YEAR = "year"

    @classmethod
    def parse(cls, s: "TimePeriod | str") -> "TimePeriod":
        if isinstance(s, TimePeriod):
            return s
        return cls(s.lower())


@dataclass(frozen=True)
class BinnedTime:
    bin: int
    offset: int


def max_offset(period: TimePeriod) -> int:
    """Max offset value (inclusive upper normalization bound) per period."""
    period = TimePeriod.parse(period)
    if period is TimePeriod.DAY:
        return MS_PER_DAY          # millis in a day
    if period is TimePeriod.WEEK:
        return MS_PER_WEEK // 1000  # seconds in a week
    if period is TimePeriod.MONTH:
        return 31 * 86_400          # seconds in the longest month
    return 52 * 7 * 24 * 60         # minutes in 52 weeks


def _as_ms_array(ms) -> np.ndarray:
    return np.asarray(ms, dtype=np.int64)


def _month_index(ms: np.ndarray) -> np.ndarray:
    """Calendar months since 1970-01 (UTC)."""
    return (ms.astype("M8[ms]").astype("M8[M]") - np.datetime64(0, "M")).astype(np.int64)


def _year_index(ms: np.ndarray) -> np.ndarray:
    """Calendar years since 1970 (UTC)."""
    return (ms.astype("M8[ms]").astype("M8[Y]") - np.datetime64(0, "Y")).astype(np.int64)


def _month_start_s(month_idx: np.ndarray) -> np.ndarray:
    return (np.datetime64(0, "M") + month_idx.astype("m8[M]")).astype("M8[s]").astype(np.int64)


def _year_start_s(year_idx: np.ndarray) -> np.ndarray:
    return (np.datetime64(0, "Y") + year_idx.astype("m8[Y]")).astype("M8[s]").astype(np.int64)


def to_binned_time(ms, period: TimePeriod, validate: bool = True):
    """Vectorized epoch-millis → (bin:int16-ranged int64, offset:int64).

    Mirrors BinnedTime.timeToBinnedTime (BinnedTime.scala:73-80): bins count
    periods since the java epoch, offsets are millis (day), seconds
    (week/month) or minutes (year) into the bin.
    """
    period = TimePeriod.parse(period)
    ms = _as_ms_array(ms)
    if validate and np.any(ms < 0):
        raise ValueError("date before minimum indexable value (1970-01-01)")
    if period is TimePeriod.DAY:
        bins = ms // MS_PER_DAY
        offs = ms - bins * MS_PER_DAY
    elif period is TimePeriod.WEEK:
        bins = ms // MS_PER_WEEK
        offs = (ms - bins * MS_PER_WEEK) // 1000
    elif period is TimePeriod.MONTH:
        bins = _month_index(ms)
        offs = ms // 1000 - _month_start_s(bins)
    else:
        bins = _year_index(ms)
        offs = (ms // 1000 - _year_start_s(bins)) // 60
    if validate and np.any(bins > MAX_BIN):
        raise ValueError(f"date exceeds maximum indexable value for period {period.value}")
    return bins.astype(np.int64), offs.astype(np.int64)


def time_to_bin(ms, period: TimePeriod, validate: bool = True):
    return to_binned_time(ms, period, validate=validate)[0]


def bin_to_ms(bins, period: TimePeriod) -> np.ndarray:
    """Epoch millis of the start of each bin."""
    period = TimePeriod.parse(period)
    bins = np.asarray(bins, dtype=np.int64)
    if period is TimePeriod.DAY:
        return bins * MS_PER_DAY
    if period is TimePeriod.WEEK:
        return bins * MS_PER_WEEK
    if period is TimePeriod.MONTH:
        return _month_start_s(bins) * 1000
    return _year_start_s(bins) * 1000


def from_binned_time(bins, offsets, period: TimePeriod) -> np.ndarray:
    """Inverse: (bin, offset) → epoch millis of the represented instant."""
    period = TimePeriod.parse(period)
    bins = np.asarray(bins, dtype=np.int64)
    offsets = np.asarray(offsets, dtype=np.int64)
    start = bin_to_ms(bins, period)
    if period is TimePeriod.DAY:
        return start + offsets
    if period in (TimePeriod.WEEK, TimePeriod.MONTH):
        return start + offsets * 1000
    return start + offsets * 60_000


def max_date_ms(period: TimePeriod) -> int:
    """Exclusive max indexable epoch-millis for a period (bin fits int16)."""
    return int(bin_to_ms(np.int64(MAX_BIN + 1), period))
