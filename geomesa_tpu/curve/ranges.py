"""Z-range decomposition: query boxes → covering morton-code ranges.

The reference outsources this to ``sfcurve``'s ``Z2.zranges`` / ``Z3.zranges``
(external dependency, geomesa-z3/pom.xml:16-17; called from
curve/Z2SFC.scala:52 and curve/Z3SFC.scala:61) and implements the analogous
BFS itself only for XZ curves (curve/XZ2SFC.scala:146-252).  This module
implements the decomposition once, generically over dimensionality, as a
**vectorized level-synchronous quad/octree sweep** in numpy: at each level
the whole frontier of candidate cells is classified (contained / overlapping
/ disjoint) with dense array comparisons — no per-node recursion or work
queue — which keeps planner latency low and translates directly to a
device formulation later if range decomposition ever needs to move on-chip.

Ranges are *covering* (a superset of the exact query cells) whenever the
``max_ranges`` budget truncates the descent — exactly the contract the
reference planner relies on (QueryProperties.ScanRangesTarget = 2000,
index/conf/QueryProperties.scala:22), with precise filtering re-applied to
candidates afterwards (filters/Z3Filter.scala semantics).  With no budget
pressure the result is exact and merged, matching sfcurve's output (e.g.
box (2,2)-(3,6) at any precision → 3 ranges, see Z2Test.scala
"calculate ranges").
"""

from __future__ import annotations

import numpy as np

from ..config import DEFAULT_MAX_RANGES
from .zorder import deinterleave2, deinterleave3

__all__ = ["zranges", "merge_ranges"]


def _deinterleave(z: np.ndarray, dims: int):
    if dims == 2:
        x, y = deinterleave2(z, xp=np)
        return np.stack([x, y])
    x, y, t = deinterleave3(z, xp=np)
    return np.stack([x, y, t])


def merge_ranges(los: np.ndarray, his: np.ndarray) -> np.ndarray:
    """Sort + merge overlapping/adjacent inclusive [lo, hi] ranges → (R, 2)."""
    if los.size == 0:
        return np.empty((0, 2), dtype=np.int64)
    order = np.argsort(los, kind="stable")
    los, his = los[order], np.maximum.accumulate(his[order])
    # a range starts a new group when its lo is beyond the running hi + 1
    new_group = np.ones(los.shape, dtype=bool)
    new_group[1:] = los[1:] > his[:-1] + 1
    n_groups = int(np.count_nonzero(new_group))
    out = np.empty((n_groups, 2), dtype=np.int64)
    out[:, 0] = los[new_group]
    # his is a running max in sorted order, so the last element of each group
    # carries that group's max hi
    last_of_group = np.ones(los.shape, dtype=bool)
    last_of_group[:-1] = new_group[1:]
    out[:, 1] = his[last_of_group]
    return out


def zranges(
    mins: np.ndarray,
    maxs: np.ndarray,
    dims: int,
    bits: int,
    max_ranges: int | None = None,
    max_levels: int | None = None,
) -> np.ndarray:
    """Decompose normalized-int query boxes into covering z ranges.

    Args:
      mins, maxs: ``(B, dims)`` inclusive per-dimension normalized bounds.
      dims: 2 (quadtree) or 3 (octree).
      bits: bits per dimension (31 for Z2, 21 for Z3).
      max_ranges: budget on emitted ranges before merging; descent stops and
        remaining frontier cells are emitted as covering ranges once
        exceeded.  Defaults to 2000 (the reference planner's scan-ranges
        target).
      max_levels: optional cap on tree depth (coarser, fewer ranges) —
        the analog of sfcurve's ``precision`` argument.

    Returns:
      ``(R, 2)`` int64 array of inclusive, sorted, disjoint, merged
      ``[lo, hi]`` z ranges whose union covers (and with an unexhausted
      budget, exactly equals) the query cells.
    """
    mins = np.atleast_2d(np.asarray(mins, dtype=np.int64))
    maxs = np.atleast_2d(np.asarray(maxs, dtype=np.int64))
    if mins.shape != maxs.shape or mins.shape[1] != dims:
        raise ValueError(f"expected (B, {dims}) box bounds, got {mins.shape}/{maxs.shape}")
    budget = DEFAULT_MAX_RANGES if max_ranges is None else int(max_ranges)
    depth_cap = bits if max_levels is None else min(bits, int(max_levels))

    from .. import native

    res = native.zranges_native(mins, maxs, dims, bits, budget, depth_cap)
    if res is not None:
        return res

    mins = mins.astype(np.uint64)
    maxs = maxs.astype(np.uint64)
    fanout = 1 << dims

    # boxes as (B, d) for broadcasting against the (n, d) frontier
    bmin, bmax = mins, maxs

    frontier = np.zeros(1, dtype=np.uint64)  # z of each cell's min corner
    out_lo: list[np.ndarray] = []
    out_hi: list[np.ndarray] = []
    emitted = 0

    for level in range(depth_cap + 1):
        if frontier.size == 0:
            break
        side = np.uint64(1) << np.uint64(bits - level)        # cells per dim
        zsize = np.uint64(1) << np.uint64(dims * (bits - level))  # z extent
        cmin = _deinterleave(frontier, dims).T                 # (n, d)
        cmax = cmin + (side - np.uint64(1))
        # classify against every box: (n, B, d) -> (n,)
        contained = np.logical_and(
            cmin[:, None, :] >= bmin[None, :, :],
            cmax[:, None, :] <= bmax[None, :, :],
        ).all(axis=2).any(axis=1)
        overlaps = np.logical_and(
            cmin[:, None, :] <= bmax[None, :, :],
            cmax[:, None, :] >= bmin[None, :, :],
        ).all(axis=2).any(axis=1)

        if level == depth_cap:
            # bottom: emit every overlapping cell whole
            contained = overlaps
        emit = frontier[contained]
        if emit.size:
            out_lo.append(emit)
            out_hi.append(emit + (zsize - np.uint64(1)))
            emitted += emit.size
        rest = frontier[overlaps & ~contained]
        if rest.size == 0:
            break
        if emitted + rest.size * fanout > budget:
            # budget exhausted: emit the remaining frontier as covering ranges
            out_lo.append(rest)
            out_hi.append(rest + (zsize - np.uint64(1)))
            break
        child_zsize = np.uint64(1) << np.uint64(dims * (bits - level - 1))
        offsets = (np.arange(fanout, dtype=np.uint64) * child_zsize)[None, :]
        frontier = (rest[:, None] + offsets).reshape(-1)

    if not out_lo:
        return np.empty((0, 2), dtype=np.int64)
    los = np.concatenate(out_lo).astype(np.int64)
    his = np.concatenate(out_hi).astype(np.int64)
    return merge_ranges(los, his)
