"""Legacy (semi-normalized) curves, kept for on-disk back-compat.

The reference retains deprecated curve variants whose dimension
normalization uses ``ceil`` with a precision of ``2^p - 1`` values
(SemiNormalizedDimension, curve/NormalizedDimension.scala:82-97) so that
data written by old versions can still be read/deleted (LegacyZ2SFC.scala,
LegacyZ3SFC.scala).  Same here: these produce the OLD key values — use
them only to interpret indexes built by earlier key layouts.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from .binnedtime import TimePeriod, max_offset
from .zorder import deinterleave2, deinterleave3, interleave2, interleave3

__all__ = ["SemiNormalizedDimension", "LegacyZ2SFC", "LegacyZ3SFC",
           "legacy_z2_sfc", "legacy_z3_sfc"]


@dataclass(frozen=True)
class SemiNormalizedDimension:
    """``normalize(x) = ceil((x - min) / (max - min) * precision)`` with
    max index = ``precision`` — the deprecated binning that does not
    correctly bin the lower bound (NormalizedDimension.scala:84-87)."""

    min: float
    max: float
    precision: int          # count of bins - 1 (e.g. 2^21 - 1)

    @property
    def max_index(self) -> int:
        return self.precision

    def normalize(self, x, xp=jnp):
        x = xp.asarray(x, dtype=xp.float64)
        i = xp.ceil((x - self.min) / (self.max - self.min)
                    * self.precision).astype(xp.int64)
        return xp.clip(i, 0, self.max_index).astype(xp.int32)

    def denormalize(self, i, xp=np):
        i = xp.asarray(i).astype(xp.float64)
        return xp.where(
            i == 0, self.min,
            (i - 0.5) * (self.max - self.min) / self.precision + self.min)

    def normalize_scalar(self, x: float) -> int:
        i = math.ceil((x - self.min) / (self.max - self.min) * self.precision)
        return max(0, min(self.max_index, int(i)))


@dataclass(frozen=True)
class LegacyZ2SFC:
    """Z2 with semi-normalized 31-bit dims (LegacyZ2SFC.scala)."""

    bits: int = 31

    @property
    def lon(self) -> SemiNormalizedDimension:
        return SemiNormalizedDimension(-180.0, 180.0, (1 << self.bits) - 1)

    @property
    def lat(self) -> SemiNormalizedDimension:
        return SemiNormalizedDimension(-90.0, 90.0, (1 << self.bits) - 1)

    def index(self, x, y, xp=jnp):
        return interleave2(self.lon.normalize(x, xp=xp),
                           self.lat.normalize(y, xp=xp), xp=xp).astype(xp.int64)

    def invert(self, z, xp=np):
        ix, iy = deinterleave2(z, xp=xp)
        return self.lon.denormalize(ix, xp=xp), self.lat.denormalize(iy, xp=xp)

    def ranges(self, xy, max_ranges=None, max_levels=None) -> np.ndarray:
        """Covering z ranges in the LEGACY normalization space — lets v1
        index layouts serve queries (the reference keeps LegacyZ2SFC
        queryable, index/index/z2/legacy/Z2IndexV1.scala)."""
        from .ranges import zranges
        boxes = np.atleast_2d(np.asarray(xy, dtype=np.float64))
        mins = np.stack([[self.lon.normalize_scalar(b[0]),
                          self.lat.normalize_scalar(b[1])] for b in boxes])
        maxs = np.stack([[self.lon.normalize_scalar(b[2]),
                          self.lat.normalize_scalar(b[3])] for b in boxes])
        return zranges(mins, maxs, dims=2, bits=self.bits,
                       max_ranges=max_ranges, max_levels=max_levels)


@dataclass(frozen=True)
class LegacyZ3SFC:
    """Z3 with semi-normalized dims: 2^21-1 lon/lat, 2^20-1 time
    (LegacyZ3SFC.scala:16-21)."""

    period: TimePeriod = TimePeriod.WEEK

    @property
    def lon(self) -> SemiNormalizedDimension:
        return SemiNormalizedDimension(-180.0, 180.0, (1 << 21) - 1)

    @property
    def lat(self) -> SemiNormalizedDimension:
        return SemiNormalizedDimension(-90.0, 90.0, (1 << 21) - 1)

    @property
    def time(self) -> SemiNormalizedDimension:
        return SemiNormalizedDimension(
            0.0, float(max_offset(self.period)), (1 << 20) - 1)

    def index(self, x, y, t, xp=jnp):
        return interleave3(self.lon.normalize(x, xp=xp),
                           self.lat.normalize(y, xp=xp),
                           self.time.normalize(t, xp=xp), xp=xp).astype(xp.int64)

    def invert(self, z, xp=np):
        ix, iy, it = deinterleave3(z, xp=xp)
        return (self.lon.denormalize(ix, xp=xp),
                self.lat.denormalize(iy, xp=xp),
                self.time.denormalize(it, xp=xp))

    @property
    def whole_period(self) -> tuple[int, int]:
        return (0, int(self.time.max_index))

    def ranges(self, xy, t, max_ranges=None, max_levels=None) -> np.ndarray:
        """Covering z ranges in the LEGACY normalization space (21-bit
        lon/lat × 20-bit time; the time dim's high bit is simply never
        set, so the uniform-bit decomposition stays valid) — lets v1
        layouts serve queries (LegacyZ3SFC.scala / Z3IndexV1)."""
        from .ranges import zranges
        boxes = np.atleast_2d(np.asarray(xy, dtype=np.float64))
        times = np.atleast_2d(np.asarray(t, dtype=np.int64))
        mins, maxs = [], []
        for b in boxes:
            for tlo, thi in times:
                mins.append([self.lon.normalize_scalar(b[0]),
                             self.lat.normalize_scalar(b[1]),
                             self.time.normalize_scalar(float(tlo))])
                maxs.append([self.lon.normalize_scalar(b[2]),
                             self.lat.normalize_scalar(b[3]),
                             self.time.normalize_scalar(float(thi))])
        return zranges(np.asarray(mins), np.asarray(maxs), dims=3,
                       bits=21, max_ranges=max_ranges,
                       max_levels=max_levels)


_Z2 = LegacyZ2SFC()
_Z3_CACHE: dict[TimePeriod, LegacyZ3SFC] = {}


def legacy_z2_sfc() -> LegacyZ2SFC:
    return _Z2


def legacy_z3_sfc(period: TimePeriod | str = TimePeriod.WEEK) -> LegacyZ3SFC:
    period = TimePeriod(period) if not isinstance(period, TimePeriod) else period
    if period not in _Z3_CACHE:
        _Z3_CACHE[period] = LegacyZ3SFC(period)
    return _Z3_CACHE[period]
