"""Dimension normalization: map doubles in [min, max] to ints in [0, 2^p).

Semantics match the reference's ``BitNormalizedDimension``
(geomesa-z3/.../curve/NormalizedDimension.scala:60-71) bit-for-bit so that
index hit-sets are identical:

* ``normalize(x) = maxIndex if x >= max else floor((x - min) * normalizer)``
  with ``normalizer = 2^p / (max - min)`` computed in float64.
* ``denormalize(i)`` returns the *center* of bin ``min(i, maxIndex)``.

The normalize path is branch-light (one select) and vectorizes on the VPU;
it is the first stage of the key-generation kernel (the reference's hot
write-path loop, index/index/z3/Z3IndexKeySpace.scala:64-96).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

__all__ = ["NormalizedDimension", "normalized_lon", "normalized_lat", "normalized_time"]


@dataclass(frozen=True)
class NormalizedDimension:
    """Maps doubles within [min, max] to ints in [0, 2^precision)."""

    min: float
    max: float
    precision: int

    def __post_init__(self):
        if not (0 < self.precision < 32):
            raise ValueError("precision (bits) must be in [1, 31]")

    @property
    def bins(self) -> int:
        return 1 << self.precision

    @property
    def max_index(self) -> int:
        return self.bins - 1

    @property
    def _normalizer(self) -> float:
        return self.bins / (self.max - self.min)

    @property
    def _denormalizer(self) -> float:
        return (self.max - self.min) / self.bins

    # -- vectorized (device or numpy) -------------------------------------
    def normalize(self, x, xp=jnp):
        """Vectorized normalize; values >= max clamp to max_index.

        Out-of-range low values are clamped to ``min`` (the reference's
        "lenient" mode, Z3SFC.scala:42-47); strict bounds checking is a
        host-side validation concern, not a device one.
        """
        x = xp.asarray(x, dtype=xp.float64)
        x = xp.maximum(x, self.min)
        # int64 intermediate: floor((max-min)*normalizer) == 2^p overflows
        # int32 before the clamp for x == max
        i = xp.floor((x - self.min) * self._normalizer).astype(xp.int64)
        return xp.clip(i, 0, self.max_index).astype(xp.int32)

    def denormalize(self, i, xp=jnp):
        """Vectorized bin-center denormalize (matches reference rounding)."""
        i = xp.minimum(xp.asarray(i).astype(xp.float64), float(self.max_index))
        return self.min + (i + 0.5) * self._denormalizer

    # -- scalar (host planning path) --------------------------------------
    def normalize_scalar(self, x: float) -> int:
        if x >= self.max:
            return self.max_index
        i = math.floor((x - self.min) * self._normalizer)
        return max(0, min(self.max_index, int(i)))

    def denormalize_scalar(self, i: int) -> float:
        i = min(i, self.max_index)
        return self.min + (i + 0.5) * self._denormalizer

    def in_bounds_scalar(self, x: float) -> bool:
        return self.min <= x <= self.max


def normalized_lon(precision: int) -> NormalizedDimension:
    return NormalizedDimension(-180.0, 180.0, precision)


def normalized_lat(precision: int) -> NormalizedDimension:
    return NormalizedDimension(-90.0, 90.0, precision)


def normalized_time(precision: int, max_offset: float) -> NormalizedDimension:
    return NormalizedDimension(0.0, float(max_offset), precision)
