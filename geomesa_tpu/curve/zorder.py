"""Morton (z-order) bit interleaving — the bit algebra under every z index.

The reference outsources this to the external ``sfcurve-zorder`` library
(``org.locationtech.sfcurve.zorder.{Z2, Z3}``; dependency declared at
geomesa-z3/pom.xml:16-17, call sites geomesa-z3/.../curve/Z2SFC.scala:52 and
Z3SFC.scala:61).  Here it is implemented directly with magic-bit shuffles so
the same code runs vectorized on device (jax.numpy, under jit/vmap) and on
host (numpy) for planning and oracles.

Bit convention (matches sfcurve, verified against the reference's
geomesa-z3/src/test/.../Z2Test.scala "split" expectations):

* 2-D: ``z = split2(x) | split2(y) << 1`` — x occupies even bits, 31 bits
  per dimension → 62-bit z.
* 3-D: ``z = split3(x) | split3(y) << 1 | split3(t) << 2`` — x occupies bits
  0, 3, 6, …; 21 bits per dimension → 63-bit z.

All functions take/return unsigned-64 arrays (or int64, converted), and are
pure elementwise ops: they vectorize trivially under ``vmap`` and fuse into
surrounding XLA programs.  ``xp`` selects the array namespace (jax.numpy on
device, numpy on host) — the arithmetic is identical.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = [
    "split2", "combine2", "interleave2", "deinterleave2",
    "split3", "combine3", "interleave3", "deinterleave3",
    "MAX_2D_BITS", "MAX_3D_BITS",
]

# 31 bits/dim for 2-D (Z2SFC default, curve/Z2SFC.scala:15);
# 21 bits/dim for 3-D (Z3SFC default, curve/Z3SFC.scala:21).
MAX_2D_BITS = 31
MAX_3D_BITS = 21


def _u64(xp, value):
    return xp.uint64(value)


def split2(x, xp=jnp):
    """Spread the low 32 bits of ``x`` onto even bit positions of a u64."""
    x = xp.asarray(x).astype(xp.uint64) & _u64(xp, 0x00000000FFFFFFFF)
    x = (x ^ (x << _u64(xp, 16))) & _u64(xp, 0x0000FFFF0000FFFF)
    x = (x ^ (x << _u64(xp, 8))) & _u64(xp, 0x00FF00FF00FF00FF)
    x = (x ^ (x << _u64(xp, 4))) & _u64(xp, 0x0F0F0F0F0F0F0F0F)
    x = (x ^ (x << _u64(xp, 2))) & _u64(xp, 0x3333333333333333)
    x = (x ^ (x << _u64(xp, 1))) & _u64(xp, 0x5555555555555555)
    return x


def combine2(z, xp=jnp):
    """Gather even bits of ``z`` back into a contiguous low-32-bit value."""
    x = xp.asarray(z).astype(xp.uint64) & _u64(xp, 0x5555555555555555)
    x = (x ^ (x >> _u64(xp, 1))) & _u64(xp, 0x3333333333333333)
    x = (x ^ (x >> _u64(xp, 2))) & _u64(xp, 0x0F0F0F0F0F0F0F0F)
    x = (x ^ (x >> _u64(xp, 4))) & _u64(xp, 0x00FF00FF00FF00FF)
    x = (x ^ (x >> _u64(xp, 8))) & _u64(xp, 0x0000FFFF0000FFFF)
    x = (x ^ (x >> _u64(xp, 16))) & _u64(xp, 0x00000000FFFFFFFF)
    return x


def interleave2(x, y, xp=jnp):
    """Morton-interleave two dimension indices: x → even bits, y → odd."""
    return split2(x, xp) | (split2(y, xp) << _u64(xp, 1))


def deinterleave2(z, xp=jnp):
    """Inverse of :func:`interleave2`; returns ``(x, y)`` as uint64."""
    z = xp.asarray(z).astype(xp.uint64)
    return combine2(z, xp), combine2(z >> _u64(xp, 1), xp)


def split3(x, xp=jnp):
    """Spread the low 21 bits of ``x`` to every third bit position."""
    x = xp.asarray(x).astype(xp.uint64) & _u64(xp, 0x1FFFFF)
    x = (x | (x << _u64(xp, 32))) & _u64(xp, 0x1F00000000FFFF)
    x = (x | (x << _u64(xp, 16))) & _u64(xp, 0x1F0000FF0000FF)
    x = (x | (x << _u64(xp, 8))) & _u64(xp, 0x100F00F00F00F00F)
    x = (x | (x << _u64(xp, 4))) & _u64(xp, 0x10C30C30C30C30C3)
    x = (x | (x << _u64(xp, 2))) & _u64(xp, 0x1249249249249249)
    return x


def combine3(z, xp=jnp):
    """Gather every third bit of ``z`` into a contiguous low-21-bit value."""
    x = xp.asarray(z).astype(xp.uint64) & _u64(xp, 0x1249249249249249)
    x = (x ^ (x >> _u64(xp, 2))) & _u64(xp, 0x10C30C30C30C30C3)
    x = (x ^ (x >> _u64(xp, 4))) & _u64(xp, 0x100F00F00F00F00F)
    x = (x ^ (x >> _u64(xp, 8))) & _u64(xp, 0x1F0000FF0000FF)
    x = (x ^ (x >> _u64(xp, 16))) & _u64(xp, 0x1F00000000FFFF)
    x = (x ^ (x >> _u64(xp, 32))) & _u64(xp, 0x1FFFFF)
    return x


def interleave3(x, y, t, xp=jnp):
    """Morton-interleave three dims: x → bits 0,3,…; y → 1,4,…; t → 2,5,…"""
    return (
        split3(x, xp)
        | (split3(y, xp) << _u64(xp, 1))
        | (split3(t, xp) << _u64(xp, 2))
    )


def deinterleave3(z, xp=jnp):
    """Inverse of :func:`interleave3`; returns ``(x, y, t)`` as uint64."""
    z = xp.asarray(z).astype(xp.uint64)
    return (
        combine3(z, xp),
        combine3(z >> _u64(xp, 1), xp),
        combine3(z >> _u64(xp, 2), xp),
    )
