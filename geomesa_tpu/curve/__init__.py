"""Space-filling-curve layer: the compute core of the framework.

Mirrors the capability surface of the reference's ``geomesa-z3`` module plus
the external ``sfcurve-zorder`` dependency it relies on: dimension
normalization, time binning, morton interleaving, Z2/Z3 (and XZ2/XZ3)
curves, and z-range decomposition.
"""

from .binnedtime import (
    BinnedTime,
    TimePeriod,
    bin_to_ms,
    from_binned_time,
    max_date_ms,
    max_offset,
    time_to_bin,
    to_binned_time,
)
from .legacy import LegacyZ2SFC, LegacyZ3SFC, legacy_z2_sfc, legacy_z3_sfc
from .normalize import NormalizedDimension, normalized_lat, normalized_lon, normalized_time
from .ranges import merge_ranges, zranges
from .sfc import Z2SFC, Z3SFC, z2_sfc, z3_sfc
from .zorder import (
    MAX_2D_BITS,
    MAX_3D_BITS,
    combine2,
    combine3,
    deinterleave2,
    deinterleave3,
    interleave2,
    interleave3,
    split2,
    split3,
)
