"""Space-filling curves: Z2 (2-D points) and Z3 (2-D points + binned time).

TPU-native re-design of the reference's curve layer
(geomesa-z3/.../curve/Z2SFC.scala, Z3SFC.scala): ``index`` is a pure
vectorized array program (normalize → magic-bit interleave) that runs
identically under numpy (host planning) and jax.numpy (device ingest
kernels, under jit/vmap over millions of points); ``ranges`` is the host
planner path producing covering z ranges via the level-synchronous
decomposition in :mod:`geomesa_tpu.curve.ranges`.

Key facts mirrored from the reference:
* Z2: 31 bits/dim over lon [-180,180], lat [-90,90] (Z2SFC.scala:15).
* Z3: 21 bits/dim over lon, lat, and time-offset [0, max_offset(period)]
  (Z3SFC.scala:21-28); one curve instance per time period, cached.
* index() validates bounds on host; the vectorized path clamps
  ("lenient", Z3SFC.scala:42-47) since device code cannot raise.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax.numpy as jnp
import numpy as np

from .binnedtime import TimePeriod, max_offset
from .normalize import NormalizedDimension, normalized_lat, normalized_lon, normalized_time
from .ranges import zranges
from .zorder import (
    MAX_2D_BITS,
    MAX_3D_BITS,
    deinterleave2,
    deinterleave3,
    interleave2,
    interleave3,
)

__all__ = ["Z2SFC", "Z3SFC", "z2_sfc", "z3_sfc"]


@dataclass(frozen=True)
class Z2SFC:
    """2-D morton curve over lon/lat."""

    precision: int = MAX_2D_BITS

    @property
    def lon(self) -> NormalizedDimension:
        return normalized_lon(self.precision)

    @property
    def lat(self) -> NormalizedDimension:
        return normalized_lat(self.precision)

    def index(self, x, y, xp=jnp):
        """Vectorized (x, y) → z (int64); out-of-bounds values clamp."""
        ix = self.lon.normalize(x, xp=xp)
        iy = self.lat.normalize(y, xp=xp)
        return interleave2(ix, iy, xp=xp).astype(xp.int64)

    def invert(self, z, xp=np):
        ix, iy = deinterleave2(z, xp=xp)
        return self.lon.denormalize(ix, xp=xp), self.lat.denormalize(iy, xp=xp)

    def ranges(self, xy, max_ranges=None, max_levels=None) -> np.ndarray:
        """Covering z ranges for lon/lat boxes ``[(xmin, ymin, xmax, ymax)]``."""
        boxes = np.atleast_2d(np.asarray(xy, dtype=np.float64))
        mins = np.stack(
            [
                [self.lon.normalize_scalar(b[0]), self.lat.normalize_scalar(b[1])]
                for b in boxes
            ]
        )
        maxs = np.stack(
            [
                [self.lon.normalize_scalar(b[2]), self.lat.normalize_scalar(b[3])]
                for b in boxes
            ]
        )
        return zranges(mins, maxs, dims=2, bits=self.precision,
                       max_ranges=max_ranges, max_levels=max_levels)


@dataclass(frozen=True)
class Z3SFC:
    """3-D morton curve over lon/lat and a time offset within a period bin."""

    period: TimePeriod = TimePeriod.WEEK
    precision: int = MAX_3D_BITS

    @property
    def lon(self) -> NormalizedDimension:
        return normalized_lon(self.precision)

    @property
    def lat(self) -> NormalizedDimension:
        return normalized_lat(self.precision)

    @property
    def time(self) -> NormalizedDimension:
        return normalized_time(self.precision, float(max_offset(self.period)))

    @property
    def whole_period(self) -> tuple[int, int]:
        return (0, int(self.time.max))

    def index(self, x, y, t, xp=jnp):
        """Vectorized (x, y, t-offset) → z (int64); clamps out-of-bounds."""
        ix = self.lon.normalize(x, xp=xp)
        iy = self.lat.normalize(y, xp=xp)
        it = self.time.normalize(t, xp=xp)
        return interleave3(ix, iy, it, xp=xp).astype(xp.int64)

    def invert(self, z, xp=np):
        ix, iy, it = deinterleave3(z, xp=xp)
        return (
            self.lon.denormalize(ix, xp=xp),
            self.lat.denormalize(iy, xp=xp),
            self.time.denormalize(it, xp=xp),
        )

    def ranges(self, xy, t, max_ranges=None, max_levels=None) -> np.ndarray:
        """Covering z ranges for the cross product of lon/lat boxes and
        time-offset intervals (both inclusive), mirroring Z3SFC.ranges."""
        boxes = np.atleast_2d(np.asarray(xy, dtype=np.float64))
        times = np.atleast_2d(np.asarray(t, dtype=np.int64))
        mins, maxs = [], []
        for b in boxes:
            for tlo, thi in times:
                mins.append(
                    [
                        self.lon.normalize_scalar(b[0]),
                        self.lat.normalize_scalar(b[1]),
                        self.time.normalize_scalar(float(tlo)),
                    ]
                )
                maxs.append(
                    [
                        self.lon.normalize_scalar(b[2]),
                        self.lat.normalize_scalar(b[3]),
                        self.time.normalize_scalar(float(thi)),
                    ]
                )
        return zranges(np.asarray(mins), np.asarray(maxs), dims=3,
                       bits=self.precision, max_ranges=max_ranges,
                       max_levels=max_levels)


@lru_cache(maxsize=None)
def z2_sfc(precision: int = MAX_2D_BITS) -> Z2SFC:
    return Z2SFC(precision)


@lru_cache(maxsize=None)
def z3_sfc(period: TimePeriod | str = TimePeriod.WEEK, precision: int = MAX_3D_BITS) -> Z3SFC:
    return Z3SFC(TimePeriod.parse(period), precision)
