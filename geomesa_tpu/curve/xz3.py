"""XZ3 curve: extended-Z ordering in 3-D (x, y, binned-time) for geometries
with extent + time.

Octree generalization of :mod:`geomesa_tpu.curve.xz2`, mirroring the
reference's XZ3SFC (geomesa-z3/.../curve/XZ3SFC.scala): the third dimension
is the time *offset within a period bin* normalized by ``max_offset``, one
curve instance per (g, period).  Sequence codes are pre-order octree
numbers — entering octant ``q`` at depth ``i`` adds
``1 + q·(8^(g-i)-1)/7`` (XZ3SFC.scala:275-301); full-subtree intervals add
``(8^(g-l+1)-1)/7`` (Lemma 3, :315-321).

As with XZ2, the encode path here is algebraic (octant digits = bit
triples of integerized min-corner coords), vectorizing the reference's
data-dependent descent into ``g`` fixed VPU steps.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax.numpy as jnp
import numpy as np

from ..config import DEFAULT_MAX_RANGES

from .binnedtime import TimePeriod, max_offset

__all__ = ["XZ3SFC", "xz3_sfc", "DEFAULT_G"]

DEFAULT_G = 12


def _iv_table8(g: int) -> np.ndarray:
    """IV[i] = (8^(g-i) - 1) / 7 for i in [0, g]."""
    if g > 20:
        raise ValueError("g must be <= 20 to fit XZ3 sequence codes in int64")
    return np.array([(8 ** (g - i) - 1) // 7 for i in range(g + 1)],
                    dtype=np.int64)


@dataclass(frozen=True)
class XZ3SFC:
    """XZ3 curve over lon/lat × time-offset-in-bin, resolution ``g``."""

    period: TimePeriod = TimePeriod.WEEK
    g: int = DEFAULT_G
    x_lo: float = -180.0
    x_hi: float = 180.0
    y_lo: float = -90.0
    y_hi: float = 90.0

    @property
    def z_lo(self) -> float:
        return 0.0

    @property
    def z_hi(self) -> float:
        return float(max_offset(self.period))

    def _normalize(self, vals, xp):
        (xmin, ymin, zmin, xmax, ymax, zmax) = vals
        xs = self.x_hi - self.x_lo
        ys = self.y_hi - self.y_lo
        zs = self.z_hi - self.z_lo
        n = lambda v, lo, size: xp.clip(
            (xp.asarray(v, xp.float64) - lo) / size, 0.0, 1.0)
        return (
            n(xmin, self.x_lo, xs), n(ymin, self.y_lo, ys), n(zmin, self.z_lo, zs),
            n(xmax, self.x_lo, xs), n(ymax, self.y_lo, ys), n(zmax, self.z_lo, zs),
        )

    # -- encode -----------------------------------------------------------
    def index(self, xmin, ymin, zmin, xmax, ymax, zmax, xp=jnp):
        """Vectorized (bbox, time-range-in-bin) → sequence code (int64)."""
        g = self.g
        nxmin, nymin, nzmin, nxmax, nymax, nzmax = self._normalize(
            (xmin, ymin, zmin, xmax, ymax, zmax), xp)
        max_dim = xp.maximum(
            xp.maximum(nxmax - nxmin, nymax - nymin), nzmax - nzmin)
        log_half = float(np.log(0.5))
        with np.errstate(divide="ignore"):
            l1 = xp.where(
                max_dim > 0.0,
                xp.floor(xp.log(xp.maximum(max_dim, 1e-300)) / log_half).astype(xp.int32),
                g,
            )
        l1 = xp.clip(l1, 0, g)
        w2 = xp.exp2(-(l1 + 1).astype(xp.float64))
        fits = lambda mn, mx: mx <= xp.floor(mn / w2) * w2 + 2.0 * w2
        length = xp.where(
            (l1 < g) & fits(nxmin, nxmax) & fits(nymin, nymax) & fits(nzmin, nzmax),
            l1 + 1, l1)
        return self._sequence_code(nxmin, nymin, nzmin, length, xp)

    def _sequence_code(self, nx, ny, nz, length, xp):
        g = self.g
        iv = xp.asarray(_iv_table8(g))
        scale = float(1 << g)
        kx = xp.minimum(xp.floor(nx * scale), scale - 1).astype(xp.int64)
        ky = xp.minimum(xp.floor(ny * scale), scale - 1).astype(xp.int64)
        kz = xp.minimum(xp.floor(nz * scale), scale - 1).astype(xp.int64)
        cs = xp.asarray(length, xp.int64) + xp.zeros_like(kx)
        length = xp.asarray(length)
        for i in range(g):
            bx = (kx >> (g - 1 - i)) & 1
            by = (ky >> (g - 1 - i)) & 1
            bz = (kz >> (g - 1 - i)) & 1
            digit = bx + 2 * by + 4 * bz
            cs = cs + xp.where(i < length, digit * iv[i], 0)
        return cs

    # -- decompose --------------------------------------------------------
    def ranges(self, queries, max_ranges: int | None = None) -> np.ndarray:
        """Covering ranges for OR'd (xmin, ymin, zmin, xmax, ymax, zmax)
        windows (user space; z = time offset in bin)."""
        budget = DEFAULT_MAX_RANGES if max_ranges is None else int(max_ranges)
        g = self.g
        iv = _iv_table8(g)
        windows = np.atleast_2d(np.asarray(queries, dtype=np.float64))
        wxmin, wymin, wzmin, wxmax, wymax, wzmax = self._normalize(
            (windows[:, 0], windows[:, 1], windows[:, 2],
             windows[:, 3], windows[:, 4], windows[:, 5]), np)

        from .. import native

        res = native.xz_ranges_native(
            np.stack([wxmin, wymin, wzmin], axis=1),
            np.stack([wxmax, wymax, wzmax], axis=1),
            dims=3, g=g, budget=budget)
        if res is not None:
            return res

        kx = np.array([0], dtype=np.int64)
        ky = np.array([0], dtype=np.int64)
        kz = np.array([0], dtype=np.int64)
        cs = np.array([0], dtype=np.int64)
        out_lo: list[np.ndarray] = []
        out_hi: list[np.ndarray] = []
        emitted = 0

        for level in range(1, g + 1):
            if kx.size == 0:
                break
            q = np.arange(8, dtype=np.int64)
            bx, by, bz = q & 1, (q >> 1) & 1, q >> 2
            ckx = (kx[:, None] << 1) + bx[None, :]
            cky = (ky[:, None] << 1) + by[None, :]
            ckz = (kz[:, None] << 1) + bz[None, :]
            ccs = cs[:, None] + 1 + q[None, :] * iv[level - 1]
            ckx, cky, ckz, ccs = ckx.ravel(), cky.ravel(), ckz.ravel(), ccs.ravel()

            w = 0.5 ** level
            x0, y0, z0 = ckx * w, cky * w, ckz * w
            xe, ye, ze = x0 + 2 * w, y0 + 2 * w, z0 + 2 * w
            contained = (
                (wxmin[None, :] <= x0[:, None]) & (wymin[None, :] <= y0[:, None])
                & (wzmin[None, :] <= z0[:, None]) & (wxmax[None, :] >= xe[:, None])
                & (wymax[None, :] >= ye[:, None]) & (wzmax[None, :] >= ze[:, None])
            ).any(axis=1)
            overlaps = (
                (wxmax[None, :] >= x0[:, None]) & (wymax[None, :] >= y0[:, None])
                & (wzmax[None, :] >= z0[:, None]) & (wxmin[None, :] <= xe[:, None])
                & (wymin[None, :] <= ye[:, None]) & (wzmin[None, :] <= ze[:, None])
            ).any(axis=1)

            full = contained
            partial = overlaps & ~contained
            if full.any():
                c = ccs[full]
                out_lo.append(c)
                out_hi.append(c + iv[level - 1])
                emitted += c.size
            if not partial.any():
                kx = np.empty(0, dtype=np.int64)
                break
            rkx, rky, rkz, rcs = ckx[partial], cky[partial], ckz[partial], ccs[partial]
            if level == g or emitted + rcs.size * 8 > budget:
                out_lo.append(rcs)
                out_hi.append(rcs + iv[level - 1])
                kx = np.empty(0, dtype=np.int64)
                break
            out_lo.append(rcs)
            out_hi.append(rcs.copy())
            emitted += rcs.size
            kx, ky, kz, cs = rkx, rky, rkz, rcs

        from .ranges import merge_ranges

        if not out_lo:
            return np.empty((0, 2), dtype=np.int64)
        return merge_ranges(np.concatenate(out_lo), np.concatenate(out_hi))


@lru_cache(maxsize=None)
def xz3_sfc(period: TimePeriod | str = TimePeriod.WEEK, g: int = DEFAULT_G) -> XZ3SFC:
    return XZ3SFC(TimePeriod.parse(period), g)
