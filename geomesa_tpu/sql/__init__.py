"""Spatial analytics API: the Spark-analog layer.

The reference's geomesa-spark stack contributes JTS UDTs + ~40 ``st_*``
UDFs and a SQL relation with spatial-predicate push-down
(geomesa-spark/geomesa-spark-jts/.../udf/*, geomesa-spark-sql/.../
SQLRules.scala).  Here: :mod:`functions` is the vectorized st_* library
over columns, and :class:`SpatialFrame` is the datastore-backed frame
whose ``where`` pushes ECQL predicates into the query planner.
"""

from . import functions as st
from .frame import SpatialFrame
from .join import explain_join, parse_join, sql_join
from .parser import parse_sql, sql_query

__all__ = ["st", "SpatialFrame", "sql_query", "parse_sql",
           "sql_join", "parse_join", "explain_join"]
