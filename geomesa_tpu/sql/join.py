"""SQL joins: inner equi-joins and spatial joins between two schemas
(round-4 VERDICT #8 — the reference's Spark SQL surface runs joins over
spatial relations with push-down on each side,
geomesa-spark/geomesa-spark-sql/.../GeoMesaSparkSQL.scala +
org/apache/spark/sql/SQLRules.scala).

Shape::

    SELECT a.name, b.score FROM evt a JOIN obs b ON a.site = b.site
        WHERE a.score > 50 AND b.kind = 'x' [LIMIT n]
    SELECT ... FROM regions a JOIN points b
        ON st_intersects(a.geom, b.geom) WHERE ...

Planning: WHERE terms must be fully qualified; each term pushes down
into ITS side's indexed scan (the SQLRules split), the join itself runs
on the host columns:

* equi-join — when the left side's distinct key set is small it becomes
  an ``IN`` filter on the right side (served by the attribute index,
  the JoinProcess trick); the pairing is a hash join either way.
* spatial join — the left hits' envelopes batch into ONE
  ``query_windows`` dispatch against the right side's z3 index (the
  BatchScanner shape), then the exact geometry predicate filters the
  candidate pairs.
"""

from __future__ import annotations

import re

import numpy as np

from ..planning.planner import Query

__all__ = ["parse_join", "is_join", "sql_join", "explain_join"]

_JOIN_CLAUSE = re.compile(
    r"^\s*SELECT\s+(?P<select>.+?)\s+FROM\s+(?P<lt>\w+)(?:\s+AS)?"
    r"\s+(?P<la>\w+)\s+JOIN\s+(?P<rt>\w+)(?:\s+AS)?\s+(?P<ra>\w+)"
    r"\s+ON\s+(?P<on>.+?)"
    r"(?:\s+WHERE\s+(?P<where>.+?))?"
    r"(?:\s+LIMIT\s+(?P<limit>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL)

_ON_EQ = re.compile(r"^(\w+)\.(\w+)\s*=\s*(\w+)\.(\w+)$")
_ON_SPATIAL = re.compile(
    r"^st_(intersects|dwithin)\s*\(\s*(\w+)\.(\w+)\s*,\s*(\w+)\.(\w+)"
    r"\s*(?:,\s*([0-9.eE+-]+)\s*)?\)$", re.IGNORECASE)

#: join queries keep JOIN-free clauses out of scope loudly
_UNSUPPORTED = re.compile(r"\b(GROUP\s+BY|HAVING|ORDER\s+BY)\b",
                          re.IGNORECASE)

#: cap on left-side hits for the spatial join's window batch — beyond
#: this the batched windows would dominate; raise a clear error rather
#: than degrade silently
SPATIAL_JOIN_MAX_LEFT = 65_536


def _mask_literals(text: str) -> str:
    """Replace single-quoted literal CONTENTS with spaces (same length,
    quotes kept) so structural regexes can never match inside data
    (review r5)."""
    return re.sub(r"'[^']*'",
                  lambda m: "'" + " " * (len(m.group(0)) - 2) + "'",
                  text)


def is_join(text: str) -> bool:
    """Structural detection — the FROM clause must carry the join shape
    (``FROM t a JOIN``) OUTSIDE string literals; join-shaped data in a
    literal must not hijack a normal query (review r5)."""
    return bool(re.search(
        r"\bFROM\s+\w+(?:\s+AS)?\s+\w+\s+JOIN\b", _mask_literals(text),
        re.IGNORECASE))


class ParsedJoin:
    def __init__(self, left, right, la, ra, on_kind, on_payload,
                 select, where_left, where_right, limit):
        self.left, self.right = left, right
        self.la, self.ra = la, ra
        self.on_kind = on_kind          # 'equi' | 'intersects' | 'dwithin'
        self.on_payload = on_payload    # (lcol, rcol[, dist])
        self.select = select            # [(alias_side, col, out_name)]
        self.where_left = where_left    # ECQL or None
        self.where_right = where_right
        self.limit = limit


def parse_join(text: str) -> ParsedJoin:
    if _UNSUPPORTED.search(text):
        raise ValueError(
            "JOIN queries support SELECT/ON/WHERE/LIMIT only — "
            "aggregate the join output in the caller")
    m = _JOIN_CLAUSE.match(text)
    if not m:
        raise ValueError(
            f"unsupported JOIN statement: {text!r} (expected SELECT ... "
            "FROM <schema> <alias> JOIN <schema> <alias> ON "
            "<a.x = b.y | st_intersects(a.geom, b.geom)> [WHERE ...] "
            "[LIMIT n])")
    la, ra = m.group("la"), m.group("ra")
    if la == ra:
        raise ValueError(f"join aliases must differ (both {la!r})")
    on = m.group("on").strip()
    em = _ON_EQ.match(on)
    sm = _ON_SPATIAL.match(on)
    if em:
        s1, c1, s2, c2 = em.groups()
        sides = {s1: c1, s2: c2}
        if set(sides) != {la, ra}:
            raise ValueError(
                f"ON must reference both aliases {la!r} and {ra!r}")
        kind, payload = "equi", (sides[la], sides[ra])
    elif sm:
        fn, s1, c1, s2, c2, dist = sm.groups()
        if {s1, s2} != {la, ra}:
            raise ValueError(
                f"ON must reference both aliases {la!r} and {ra!r}")
        if s1 != la:     # normalize to (left geom, right geom)
            c1, c2 = c2, c1
        kind = fn.lower()
        if kind == "dwithin":
            if dist is None:
                raise ValueError("st_dwithin needs a distance (meters)")
            payload = (c1, c2, float(dist))
        else:
            payload = (c1, c2)
    else:
        raise ValueError(
            f"unsupported ON condition {on!r} (expected "
            "<a.x = b.y>, st_intersects(a.g, b.g) or "
            "st_dwithin(a.g, b.g, meters))")
    # SELECT list: qualified columns with optional aliases, or *
    select = []
    sel = m.group("select").strip()
    if sel != "*":
        for part in (p.strip() for p in sel.split(",")):
            pm = re.match(r"^(\w+)\.(\w+)(?:\s+AS\s+(\w+))?$", part,
                          re.IGNORECASE)
            if not pm:
                raise ValueError(
                    f"unsupported JOIN projection {part!r} (use "
                    "qualified columns: <alias>.<col> [AS name])")
            side, col, out = pm.groups()
            if side not in (la, ra):
                raise ValueError(f"unknown alias {side!r} in projection "
                                 f"{part!r} (have {la!r}, {ra!r})")
            select.append((side, col, out or f"{side}.{col}"))
    # WHERE: AND-split; every term fully on one side.  BETWEEN's
    # internal AND is repaired after the split (review r5)
    wl, wr = [], []
    raw = m.group("where")
    if raw:
        parts = re.split(r"\s+AND\s+", raw.strip(),
                         flags=re.IGNORECASE)
        terms: list = []
        for p in parts:
            if terms and re.search(r"\bBETWEEN\s+\S+$", terms[-1],
                                   re.IGNORECASE):
                terms[-1] = f"{terms[-1]} AND {p}"
            else:
                terms.append(p)
        for term in terms:
            # detect and rewrite alias-qualified tokens OUTSIDE string
            # literals only — `b.note = 'a.x'` is a single-side term
            # and the literal must survive untouched (review r5)
            masked = _mask_literals(term)
            refs = {s for s, _ in re.findall(r"\b(\w+)\.(\w+)", masked)
                    if s in (la, ra)}
            if len(refs) != 1:
                raise ValueError(
                    f"JOIN WHERE term {term!r} must reference exactly "
                    "one side (qualify columns with the table alias); "
                    "cross-side predicates belong in ON")
            side = refs.pop()
            stripped = ""
            last = 0
            for m2 in re.finditer(rf"\b{side}\.(\w+)", term):
                # skip matches inside literals (masked shows spaces)
                if masked[m2.start():m2.end()] != m2.group(0):
                    continue
                stripped += term[last:m2.start()] + m2.group(1)
                last = m2.end()
            stripped += term[last:]
            (wl if side == la else wr).append(stripped)
    from .parser import _rewrite_where
    where_left = _rewrite_where(" AND ".join(wl)) if wl else None
    where_right = _rewrite_where(" AND ".join(wr)) if wr else None
    return ParsedJoin(
        m.group("lt"), m.group("rt"), la, ra, kind, payload, select,
        where_left, where_right,
        int(m.group("limit")) if m.group("limit") else None)


#: left distinct-key cap for pushing the equi-join as an IN filter on
#: the right side's attribute index (the JoinProcess trick)
_IN_PUSHDOWN_MAX = 10_000


def _pairs_equi(store, q: ParsedJoin, lres):
    lcol, rcol = q.on_payload
    lv = lres.batch.column(lcol)
    uniq = (np.unique(lv[lv != np.array(None)])
            if lv.dtype == object else np.unique(lv))
    from ..filters.ast import And, In
    from ..filters.ecql import parse_ecql
    rfilter = (parse_ecql(q.where_right) if q.where_right
               else None)
    if 0 < len(uniq) <= _IN_PUSHDOWN_MAX:
        semi = In(rcol, tuple(uniq.tolist()))
        rfilter = semi if rfilter is None else And((rfilter, semi))
    rres = store.query_result(q.right,
                              Query(filter=rfilter) if rfilter
                              else Query())
    rv = rres.batch.column(rcol)
    import pandas as pd
    # SQL NULL semantics: NULL never equals NULL — mask None rows on
    # BOTH sides so results cannot depend on whether the IN push-down
    # fired (review r5: pandas merge pairs None==None)
    li = np.arange(len(lv))
    rj = np.arange(len(rv))

    def _non_null(vals, rows):
        if vals.dtype == object:
            keep = vals != np.array(None)
        elif vals.dtype.kind == "f":
            # pandas merge pairs NaN==NaN; SQL says NULL never matches
            keep = ~np.isnan(vals)
        else:
            return vals, rows
        return vals[keep], rows[keep]

    lv, li = _non_null(lv, li)
    rv, rj = _non_null(rv, rj)
    lp = pd.DataFrame({"i": li, "k": lv})
    rp = pd.DataFrame({"j": rj, "k": rv})
    merged = lp.merge(rp, on="k", how="inner")
    return (merged["i"].to_numpy(), merged["j"].to_numpy(), rres)


class _RightSlice:
    """Quacks like a QueryResult for sql_join's column stage: the
    candidate rows ONLY (never the whole right table — review r5)."""

    def __init__(self, batch):
        self.batch = batch


def _pairs_spatial(store, q: ParsedJoin, lres):
    from ..features.batch import FeatureBatch
    from ..geometry.predicates import (
        geometry_intersects, point_in_polygon,
    )
    from ..process.knn import haversine_m
    lbatch = lres.batch
    n_l = len(lbatch)
    r_sft = store.get_schema(q.right)
    if n_l > SPATIAL_JOIN_MAX_LEFT:
        raise ValueError(
            f"spatial join: left side matched {n_l} features "
            f"(cap {SPATIAL_JOIN_MAX_LEFT}) — tighten the left WHERE")
    if n_l == 0:
        return (np.empty(0, np.int64), np.empty(0, np.int64),
                _RightSlice(FeatureBatch.empty(r_sft)))
    dist_m = q.on_payload[2] if q.on_kind == "dwithin" else 0.0
    # shape validation happens BEFORE any scan, not inside the
    # candidate loop — an unsupported shape must error loudly even
    # when no candidates surface (review r5)
    if q.on_kind == "dwithin" and not (
            r_sft.is_points
            and store.get_schema(q.left).is_points):
        raise ValueError("st_dwithin joins support point-to-point "
                         "schemas (use st_intersects for polygon "
                         "relations)")
    lgeoms = ([lbatch.geoms.geometry(i) for i in range(n_l)]
              if lbatch.geoms is not None else None)
    if lgeoms is not None:
        envs = [g.envelope.as_tuple() for g in lgeoms]
    else:
        lx, ly = lbatch.geom_xy()
        envs = [(lx[i], ly[i], lx[i], ly[i]) for i in range(n_l)]
    windows = []
    for e in envs:
        pad_lat = float(np.degrees(dist_m / 6_371_008.8)) * 1.05
        # longitude degrees shrink by cos(lat): pad by the window's
        # worst-case latitude or the join silently drops true pairs
        # past ~48 deg (review r5)
        cos = max(0.01, float(np.cos(np.radians(
            min(88.0, max(abs(e[1]) , abs(e[3])) + pad_lat)))))
        pad_lon = pad_lat / cos
        windows.append(([(e[0] - pad_lon, e[1] - pad_lat,
                          e[2] + pad_lon, e[3] + pad_lat)],
                        None, None))
    # ONE batched windows dispatch against the right index; only the
    # CANDIDATE rows ever materialize (tombstones/visibility are
    # already applied by query_windows)
    hits = store.query_windows(q.right, windows)
    flat = ([np.asarray(h, np.int64) for h in hits if len(h)]
            or [np.empty(0, np.int64)])
    union = np.unique(np.concatenate(flat))
    st_r = store._store(q.right)
    rb = st_r.batch.take(union) if len(union) \
        else FeatureBatch.empty(r_sft)
    if q.where_right and len(union):
        from ..filters.ecql import parse_ecql
        from ..filters.evaluate import evaluate_filter
        mask = evaluate_filter(parse_ecql(q.where_right), rb)
        union = union[mask]
        rb = rb.take(np.flatnonzero(mask))
    rmap = {int(p): j for j, p in enumerate(union)}
    r_pts = r_sft.is_points
    rx, ry = rb.geom_xy() if (r_pts and len(rb)) else (None, None)
    li, rj = [], []
    for i, cand in enumerate(hits):
        rows = [rmap[int(c)] for c in cand if int(c) in rmap]
        if not rows:
            continue
        rows = np.asarray(rows, np.int64)
        if q.on_kind == "dwithin":
            d = haversine_m(envs[i][0], envs[i][1], rx[rows], ry[rows])
            keep = rows[d <= dist_m]
        elif r_pts and lgeoms is not None:
            inside = point_in_polygon(rx[rows], ry[rows], lgeoms[i])
            keep = rows[inside]
        elif r_pts:
            keep = rows[(rx[rows] == envs[i][0])
                        & (ry[rows] == envs[i][1])]
        else:
            # non-point right side: exact pairwise predicate; a POINT
            # left side wraps its coordinate as a geometry (review r5:
            # this branch crashed on lgeoms=None)
            if lgeoms is not None:
                lg = lgeoms[i]
            else:
                from ..geometry.types import Point
                lg = Point(float(envs[i][0]), float(envs[i][1]))
            keep = np.asarray(
                [r for r in rows if geometry_intersects(
                    lg, rb.geoms.geometry(int(r)))], np.int64)
        li.extend([i] * len(keep))
        rj.extend(keep.tolist())
    return (np.asarray(li, np.int64), np.asarray(rj, np.int64),
            _RightSlice(rb))


def _require_single_process(store, q: ParsedJoin) -> None:
    """JOIN pairing indexes ``.batch`` — each process's LOCAL rows — by
    query positions, which are GLOBAL gids on a multihost store: rows
    living on another process would silently vanish from the join
    output.  Refuse loudly until both sides' key/geometry columns are
    allgathered (the correct fix; not yet implemented).  A
    multihost-MODE store on a single process holds every row locally,
    so the hazard only exists past one process."""
    import jax
    if jax.process_count() <= 1:
        return
    for name in (q.left, q.right):
        st = store._store(name)
        if getattr(st, "multihost", False):
            raise NotImplementedError(
                f"sql_join over multihost schema {name!r}: join "
                "pairing indexes process-local batches with global gid "
                "positions, so cross-process pairs would be silently "
                "dropped — allgather both sides' join columns or run "
                "the join on a single-process store")


def sql_join(store, text: str) -> dict:
    """Execute a JOIN statement; returns a dict of output columns.

    Multihost stores are rejected (NotImplementedError): see
    :func:`_require_single_process`."""
    q = parse_join(text)
    _require_single_process(store, q)
    lres = store.query_result(
        q.left, Query.of(q.where_left) if q.where_left else Query())
    if q.on_kind == "equi":
        li, rj, rres = _pairs_equi(store, q, lres)
    else:
        li, rj, rres = _pairs_spatial(store, q, lres)
    if q.limit is not None:
        li, rj = li[:q.limit], rj[:q.limit]
    lb, rb = lres.batch, rres.batch
    select = q.select or (
        [(q.la, a.name, f"{q.la}.{a.name}") for a in lb.sft.attributes
         if not a.is_geometry]
        + [(q.ra, a.name, f"{q.ra}.{a.name}") for a in rb.sft.attributes
           if not a.is_geometry])
    out: dict = {}
    for side, col, name in select:
        batch, rows = (lb, li) if side == q.la else (rb, rj)
        if name in out:
            raise ValueError(f"duplicate output column {name!r} — "
                             "alias one side with AS")
        out[name] = np.asarray(batch.column(col))[rows]
    return out


def explain_join(store, text: str) -> str:
    """The join plan: each side's pushed-down strategy (via the store's
    explain) + the join method — the SQLRules push-down made visible."""
    q = parse_join(text)
    parts = [f"JOIN plan: {q.left} {q.la} {q.on_kind.upper()} "
             f"{q.right} {q.ra} ON {q.on_payload}"]
    parts.append(f"-- left side ({q.left}): WHERE "
                 f"{q.where_left or 'INCLUDE'}")
    parts.append(store.explain(
        q.left, Query.of(q.where_left) if q.where_left else Query()))
    parts.append(f"-- right side ({q.right}): WHERE "
                 f"{q.where_right or 'INCLUDE'}"
                 + (" + semi-join IN push-down (attribute index) when "
                    "the left key set is small"
                    if q.on_kind == "equi" else
                    " + batched envelope windows (z3 index)"))
    parts.append(store.explain(
        q.right, Query.of(q.where_right) if q.where_right else Query()))
    return "\n".join(parts)
