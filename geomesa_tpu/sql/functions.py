"""Vectorized st_* spatial functions.

The ~40 UDFs the reference registers for Spark SQL
(geomesa-spark/geomesa-spark-jts/src/main/scala/.../udf/
{GeometricConstructorFunctions, GeometricAccessorFunctions,
GeometricPredicateFunctions, GeometricOutputFunctions,
SpatialRelationFunctions, GeometricCastFunctions}.scala), re-expressed as
numpy-vectorized column functions.  Point columns are ``(x, y)`` array
pairs; geometry columns are object arrays of
:class:`~geomesa_tpu.geometry.types.Geometry`.
"""

from __future__ import annotations

import numpy as np

from ..geometry.types import (
    Envelope, Geometry, LineString, MultiPoint, MultiPolygon, Point, Polygon,
)
from ..geometry.wkt import geometry_from_wkt as parse_wkt
from ..geometry.wkt import geometry_to_wkt as to_wkt
from ..geometry.wkb import wkb_decode, wkb_encode
from ..geometry.predicates import point_in_polygon
from ..process.knn import EARTH_RADIUS_M, haversine_m

__all__ = [
    # constructors
    "st_point", "st_makePoint", "st_geomFromWKT", "st_geomFromWKB",
    "st_makeBBOX", "st_makeBox2D", "st_makePolygon", "st_makeLine",
    # accessors
    "st_x", "st_y", "st_envelope", "st_exteriorRing", "st_numPoints",
    "st_pointN", "st_isValid", "st_geometryType", "st_centroid",
    # outputs / casts
    "st_asText", "st_asBinary", "st_castToPoint", "st_castToPolygon",
    "st_castToLineString",
    # predicates
    "st_contains", "st_within", "st_intersects", "st_disjoint", "st_equals",
    "st_crosses", "st_bbox_intersects", "st_dwithin",
    # relations / measures
    "st_distance", "st_distanceSphere", "st_area", "st_length",
    "st_lengthSphere", "st_translate", "st_bufferPoint",
]


def _geoms(col) -> np.ndarray:
    return np.atleast_1d(np.asarray(col, dtype=object))


# -- constructors -----------------------------------------------------------

def st_point(x, y):
    """Point column as an (x, y) array pair."""
    return np.atleast_1d(np.asarray(x, np.float64)), \
        np.atleast_1d(np.asarray(y, np.float64))


st_makePoint = st_point


def st_geomFromWKT(col) -> np.ndarray:
    return np.array([parse_wkt(s) for s in np.atleast_1d(col)], dtype=object)


def st_geomFromWKB(col) -> np.ndarray:
    return np.array([wkb_decode(b) for b in np.atleast_1d(col)], dtype=object)


def st_makeBBOX(xmin, ymin, xmax, ymax) -> np.ndarray:
    args = np.broadcast_arrays(*(np.atleast_1d(np.asarray(a, np.float64))
                                 for a in (xmin, ymin, xmax, ymax)))
    return np.array(
        [Polygon.from_envelope(Envelope(*vals)) for vals in zip(*args)],
        dtype=object)


st_makeBox2D = st_makeBBOX


def st_makePolygon(shell_lines) -> np.ndarray:
    return np.array([Polygon(l.coords if isinstance(l, LineString) else l)
                     for l in _geoms(shell_lines)], dtype=object)


def st_makeLine(points_list) -> LineString:
    pts = [(p.x, p.y) if isinstance(p, Point) else tuple(p)
           for p in points_list]
    return LineString(np.asarray(pts))


# -- accessors --------------------------------------------------------------

def st_x(col) -> np.ndarray:
    if isinstance(col, tuple):
        return np.asarray(col[0], np.float64)
    return np.array([g.x if isinstance(g, Point) else np.nan
                     for g in _geoms(col)])


def st_y(col) -> np.ndarray:
    if isinstance(col, tuple):
        return np.asarray(col[1], np.float64)
    return np.array([g.y if isinstance(g, Point) else np.nan
                     for g in _geoms(col)])


def st_envelope(col) -> np.ndarray:
    return np.array([g.envelope for g in _geoms(col)], dtype=object)


def st_exteriorRing(col) -> np.ndarray:
    return np.array(
        [LineString(g.shell) if isinstance(g, Polygon) else None
         for g in _geoms(col)], dtype=object)


def st_numPoints(col) -> np.ndarray:
    def npts(g):
        if isinstance(g, Point):
            return 1
        if isinstance(g, (LineString, MultiPoint)):
            return len(g.coords)
        if isinstance(g, Polygon):
            return len(g.shell) + sum(len(h) for h in g.holes)
        if isinstance(g, MultiPolygon):
            return sum(len(p.shell) + sum(len(h) for h in p.holes)
                       for p in g.polygons)
        return sum(len(l.coords) for l in getattr(g, "lines", ()))
    return np.array([npts(g) for g in _geoms(col)], dtype=np.int64)


def st_pointN(col, n: int) -> np.ndarray:
    def pick(g):
        coords = g.coords if isinstance(g, (LineString, MultiPoint)) else (
            g.shell if isinstance(g, Polygon) else None)
        if coords is None:
            return None
        i = n - 1 if n > 0 else len(coords) + n   # 1-based, negatives wrap
        if 0 <= i < len(coords):
            return Point(float(coords[i, 0]), float(coords[i, 1]))
        return None
    return np.array([pick(g) for g in _geoms(col)], dtype=object)


def st_isValid(col) -> np.ndarray:
    def ok(g):
        try:
            return bool(g is not None and g.envelope is not None)
        except Exception:
            return False
    return np.array([ok(g) for g in _geoms(col)])


def st_geometryType(col) -> np.ndarray:
    return np.array([g.geom_type for g in _geoms(col)], dtype=object)


def st_centroid(col) -> np.ndarray:
    def cen(g):
        if isinstance(g, Point):
            return g
        if isinstance(g, (LineString, MultiPoint)):
            c = g.coords.mean(axis=0)
        elif isinstance(g, Polygon):
            c = g.shell[:-1].mean(axis=0)
        elif isinstance(g, MultiPolygon):
            c = np.vstack([p.shell[:-1] for p in g.polygons]).mean(axis=0)
        else:
            c = np.vstack([l.coords for l in g.lines]).mean(axis=0)
        return Point(float(c[0]), float(c[1]))
    return np.array([cen(g) for g in _geoms(col)], dtype=object)


# -- outputs / casts --------------------------------------------------------

def st_asText(col) -> np.ndarray:
    return np.array([to_wkt(g) for g in _geoms(col)], dtype=object)


def st_asBinary(col) -> np.ndarray:
    return np.array([wkb_encode(g) for g in _geoms(col)], dtype=object)


def _cast(col, cls) -> np.ndarray:
    return np.array([g if isinstance(g, cls) else None for g in _geoms(col)],
                    dtype=object)


def st_castToPoint(col):
    return _cast(col, Point)


def st_castToPolygon(col):
    return _cast(col, Polygon)


def st_castToLineString(col):
    return _cast(col, LineString)


# -- predicates -------------------------------------------------------------

def _points_xy(col):
    if isinstance(col, tuple):
        return (np.atleast_1d(np.asarray(col[0], np.float64)),
                np.atleast_1d(np.asarray(col[1], np.float64)))
    gs = _geoms(col)
    return (np.array([g.x for g in gs]), np.array([g.y for g in gs]))


def st_contains(geom: Geometry, col) -> np.ndarray:
    """geom contains points/geoms of ``col`` (vectorized over the column)."""
    x, y = _points_xy(col)
    if isinstance(geom, (Polygon, MultiPolygon)):
        return point_in_polygon(x, y, geom)
    env = geom.envelope
    return (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) & (y <= env.ymax)


def st_within(col, geom: Geometry) -> np.ndarray:
    return st_contains(geom, col)


def st_intersects(geom: Geometry, col) -> np.ndarray:
    return st_contains(geom, col)


def st_disjoint(geom: Geometry, col) -> np.ndarray:
    return ~st_contains(geom, col)


def st_equals(col_a, col_b) -> np.ndarray:
    ax, ay = _points_xy(col_a)
    bx, by = _points_xy(col_b)
    return (ax == bx) & (ay == by)


def st_crosses(geom: Geometry, col) -> np.ndarray:
    # point columns: crosses degenerates to intersects-boundary ≈ contains
    return st_contains(geom, col)


def st_bbox_intersects(env: Envelope, col) -> np.ndarray:
    x, y = _points_xy(col)
    return (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) & (y <= env.ymax)


def st_dwithin(geom: Geometry, col, distance_m: float) -> np.ndarray:
    x, y = _points_xy(col)
    if isinstance(geom, Point):
        return haversine_m(geom.x, geom.y, x, y) <= distance_m
    # non-point: envelope-expand test then centroid distance (approximate)
    c = st_centroid([geom])[0]
    return haversine_m(c.x, c.y, x, y) <= distance_m


# -- relations / measures ---------------------------------------------------

def st_distance(col_a, col_b) -> np.ndarray:
    """Cartesian (degree-space) distance between point columns."""
    ax, ay = _points_xy(col_a)
    bx, by = _points_xy(col_b)
    return np.hypot(ax - bx, ay - by)


def st_distanceSphere(col_a, col_b) -> np.ndarray:
    ax, ay = _points_xy(col_a)
    bx, by = _points_xy(col_b)
    return haversine_m(ax, ay, bx, by)


def st_area(col) -> np.ndarray:
    def area(g):
        if isinstance(g, Polygon):
            return _ring_area(g.shell) - sum(_ring_area(h) for h in g.holes)
        if isinstance(g, MultiPolygon):
            return sum(_ring_area(p.shell)
                       - sum(_ring_area(h) for h in p.holes)
                       for p in g.polygons)
        return 0.0
    return np.array([area(g) for g in _geoms(col)])


def _ring_area(ring: np.ndarray) -> float:
    x, y = ring[:, 0], ring[:, 1]
    return 0.5 * abs(np.sum(x[:-1] * y[1:] - x[1:] * y[:-1]))


def st_length(col) -> np.ndarray:
    def length(g):
        if isinstance(g, LineString):
            d = np.diff(g.coords, axis=0)
            return float(np.hypot(d[:, 0], d[:, 1]).sum())
        if hasattr(g, "lines"):
            return sum(length(l) for l in g.lines)
        return 0.0
    return np.array([length(g) for g in _geoms(col)])


def st_lengthSphere(col) -> np.ndarray:
    def length(g):
        if isinstance(g, LineString):
            c = g.coords
            return float(haversine_m(c[:-1, 0], c[:-1, 1],
                                     c[1:, 0], c[1:, 1]).sum())
        if hasattr(g, "lines"):
            return sum(length(l) for l in g.lines)
        return 0.0
    return np.array([length(g) for g in _geoms(col)])


def st_translate(col, dx: float, dy: float):
    if isinstance(col, tuple):
        return (np.asarray(col[0]) + dx, np.asarray(col[1]) + dy)

    def move(g):
        if isinstance(g, Point):
            return Point(g.x + dx, g.y + dy)
        if isinstance(g, LineString):
            return LineString(g.coords + [dx, dy])
        if isinstance(g, Polygon):
            return Polygon(g.shell + [dx, dy],
                           tuple(h + [dx, dy] for h in g.holes))
        raise ValueError(f"st_translate: unsupported {g.geom_type}")
    return np.array([move(g) for g in _geoms(col)], dtype=object)


def st_bufferPoint(col, distance_m: float, segments: int = 32) -> np.ndarray:
    """Geodesic point buffer → polygon (the reference's st_bufferPoint,
    used for dwithin-style joins)."""
    x, y = _points_xy(col)
    ang = np.linspace(0, 2 * np.pi, segments, endpoint=False)
    dlat = np.degrees(distance_m / EARTH_RADIUS_M)
    out = []
    for xi, yi in zip(x, y):
        cos = max(0.01, np.cos(np.radians(yi)))
        ring = np.stack([xi + dlat / cos * np.cos(ang),
                         yi + dlat * np.sin(ang)], axis=1)
        out.append(Polygon(ring))
    return np.array(out, dtype=object)
