"""Vectorized st_* spatial functions.

The ~40 UDFs the reference registers for Spark SQL
(geomesa-spark/geomesa-spark-jts/src/main/scala/.../udf/
{GeometricConstructorFunctions, GeometricAccessorFunctions,
GeometricPredicateFunctions, GeometricOutputFunctions,
SpatialRelationFunctions, GeometricCastFunctions}.scala), re-expressed as
numpy-vectorized column functions.  Point columns are ``(x, y)`` array
pairs; geometry columns are object arrays of
:class:`~geomesa_tpu.geometry.types.Geometry`.
"""

from __future__ import annotations

import numpy as np

from ..geometry.types import (
    Envelope, Geometry, LineString, MultiPoint, MultiPolygon, Point, Polygon,
)
from ..geometry.wkt import geometry_from_wkt as parse_wkt
from ..geometry.wkt import geometry_to_wkt as to_wkt
from ..geometry.wkb import wkb_decode, wkb_encode
from ..geometry.predicates import point_in_polygon
from ..process.knn import EARTH_RADIUS_M, haversine_m

__all__ = [
    # constructors
    "st_point", "st_makePoint", "st_geomFromWKT", "st_geomFromWKB",
    "st_makeBBOX", "st_makeBox2D", "st_makePolygon", "st_makeLine",
    # accessors
    "st_x", "st_y", "st_envelope", "st_exteriorRing", "st_numPoints",
    "st_pointN", "st_isValid", "st_geometryType", "st_centroid",
    # outputs / casts
    "st_asText", "st_asBinary", "st_castToPoint", "st_castToPolygon",
    "st_castToLineString",
    # predicates
    "st_contains", "st_within", "st_intersects", "st_disjoint", "st_equals",
    "st_crosses", "st_bbox_intersects", "st_dwithin",
    # relations / measures
    "st_distance", "st_distanceSphere", "st_area", "st_length",
    "st_lengthSphere", "st_translate", "st_bufferPoint",
]


def _geoms(col) -> np.ndarray:
    return np.atleast_1d(np.asarray(col, dtype=object))


# -- constructors -----------------------------------------------------------

def st_point(x, y):
    """Point column as an (x, y) array pair."""
    return np.atleast_1d(np.asarray(x, np.float64)), \
        np.atleast_1d(np.asarray(y, np.float64))


st_makePoint = st_point


def st_geomFromWKT(col) -> np.ndarray:
    return np.array([parse_wkt(s) for s in np.atleast_1d(col)], dtype=object)


def st_geomFromWKB(col) -> np.ndarray:
    return np.array([wkb_decode(b) for b in np.atleast_1d(col)], dtype=object)


def st_makeBBOX(xmin, ymin, xmax, ymax) -> np.ndarray:
    args = np.broadcast_arrays(*(np.atleast_1d(np.asarray(a, np.float64))
                                 for a in (xmin, ymin, xmax, ymax)))
    return np.array(
        [Polygon.from_envelope(Envelope(*vals)) for vals in zip(*args)],
        dtype=object)


st_makeBox2D = st_makeBBOX


def st_makePolygon(shell_lines) -> np.ndarray:
    return np.array([Polygon(l.coords if isinstance(l, LineString) else l)
                     for l in _geoms(shell_lines)], dtype=object)


def st_makeLine(points_list) -> LineString:
    pts = [(p.x, p.y) if isinstance(p, Point) else tuple(p)
           for p in points_list]
    return LineString(np.asarray(pts))


# -- accessors --------------------------------------------------------------

def st_x(col) -> np.ndarray:
    if isinstance(col, tuple):
        return np.asarray(col[0], np.float64)
    return np.array([g.x if isinstance(g, Point) else np.nan
                     for g in _geoms(col)])


def st_y(col) -> np.ndarray:
    if isinstance(col, tuple):
        return np.asarray(col[1], np.float64)
    return np.array([g.y if isinstance(g, Point) else np.nan
                     for g in _geoms(col)])


def st_envelope(col) -> np.ndarray:
    return np.array([g.envelope for g in _geoms(col)], dtype=object)


def st_exteriorRing(col) -> np.ndarray:
    return np.array(
        [LineString(g.shell) if isinstance(g, Polygon) else None
         for g in _geoms(col)], dtype=object)


def st_numPoints(col) -> np.ndarray:
    def npts(g):
        if isinstance(g, Point):
            return 1
        if isinstance(g, (LineString, MultiPoint)):
            return len(g.coords)
        if isinstance(g, Polygon):
            return len(g.shell) + sum(len(h) for h in g.holes)
        if isinstance(g, MultiPolygon):
            return sum(len(p.shell) + sum(len(h) for h in p.holes)
                       for p in g.polygons)
        return sum(len(l.coords) for l in getattr(g, "lines", ()))
    return np.array([npts(g) for g in _geoms(col)], dtype=np.int64)


def st_pointN(col, n: int) -> np.ndarray:
    def pick(g):
        coords = g.coords if isinstance(g, (LineString, MultiPoint)) else (
            g.shell if isinstance(g, Polygon) else None)
        if coords is None:
            return None
        i = n - 1 if n > 0 else len(coords) + n   # 1-based, negatives wrap
        if 0 <= i < len(coords):
            return Point(float(coords[i, 0]), float(coords[i, 1]))
        return None
    return np.array([pick(g) for g in _geoms(col)], dtype=object)


def st_isValid(col) -> np.ndarray:
    def ok(g):
        try:
            return bool(g is not None and g.envelope is not None)
        except Exception:
            return False
    return np.array([ok(g) for g in _geoms(col)])


def st_geometryType(col) -> np.ndarray:
    return np.array([g.geom_type for g in _geoms(col)], dtype=object)


def st_centroid(col) -> np.ndarray:
    def cen(g):
        if isinstance(g, Point):
            return g
        if isinstance(g, (LineString, MultiPoint)):
            c = g.coords.mean(axis=0)
        elif isinstance(g, Polygon):
            c = g.shell[:-1].mean(axis=0)
        elif isinstance(g, MultiPolygon):
            c = np.vstack([p.shell[:-1] for p in g.polygons]).mean(axis=0)
        else:
            c = np.vstack([l.coords for l in g.lines]).mean(axis=0)
        return Point(float(c[0]), float(c[1]))
    return np.array([cen(g) for g in _geoms(col)], dtype=object)


# -- outputs / casts --------------------------------------------------------

def st_asText(col) -> np.ndarray:
    return np.array([to_wkt(g) for g in _geoms(col)], dtype=object)


def st_asBinary(col) -> np.ndarray:
    return np.array([wkb_encode(g) for g in _geoms(col)], dtype=object)


def _cast(col, cls) -> np.ndarray:
    return np.array([g if isinstance(g, cls) else None for g in _geoms(col)],
                    dtype=object)


def st_castToPoint(col):
    return _cast(col, Point)


def st_castToPolygon(col):
    return _cast(col, Polygon)


def st_castToLineString(col):
    return _cast(col, LineString)


# -- predicates -------------------------------------------------------------

def _points_xy(col):
    if isinstance(col, tuple):
        return (np.atleast_1d(np.asarray(col[0], np.float64)),
                np.atleast_1d(np.asarray(col[1], np.float64)))
    gs = _geoms(col)
    return (np.array([g.x for g in gs]), np.array([g.y for g in gs]))


def st_contains(geom: Geometry, col) -> np.ndarray:
    """geom contains points/geoms of ``col`` (vectorized over the column)."""
    x, y = _points_xy(col)
    if isinstance(geom, (Polygon, MultiPolygon)):
        return point_in_polygon(x, y, geom)
    env = geom.envelope
    return (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) & (y <= env.ymax)


def st_within(col, geom: Geometry) -> np.ndarray:
    return st_contains(geom, col)


def st_intersects(geom: Geometry, col) -> np.ndarray:
    return st_contains(geom, col)


def st_disjoint(geom: Geometry, col) -> np.ndarray:
    return ~st_contains(geom, col)


def st_equals(col_a, col_b) -> np.ndarray:
    ax, ay = _points_xy(col_a)
    bx, by = _points_xy(col_b)
    return (ax == bx) & (ay == by)


def st_crosses(geom: Geometry, col) -> np.ndarray:
    # point columns: crosses degenerates to intersects-boundary ≈ contains
    return st_contains(geom, col)


def st_bbox_intersects(env: Envelope, col) -> np.ndarray:
    x, y = _points_xy(col)
    return (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) & (y <= env.ymax)


def st_dwithin(geom: Geometry, col, distance_m: float) -> np.ndarray:
    x, y = _points_xy(col)
    if isinstance(geom, Point):
        return haversine_m(geom.x, geom.y, x, y) <= distance_m
    # non-point: envelope-expand test then centroid distance (approximate)
    c = st_centroid([geom])[0]
    return haversine_m(c.x, c.y, x, y) <= distance_m


# -- relations / measures ---------------------------------------------------

def st_distance(col_a, col_b) -> np.ndarray:
    """Cartesian (degree-space) distance between point columns."""
    ax, ay = _points_xy(col_a)
    bx, by = _points_xy(col_b)
    return np.hypot(ax - bx, ay - by)


def st_distanceSphere(col_a, col_b) -> np.ndarray:
    ax, ay = _points_xy(col_a)
    bx, by = _points_xy(col_b)
    return haversine_m(ax, ay, bx, by)


def st_area(col) -> np.ndarray:
    def area(g):
        if isinstance(g, Polygon):
            return _ring_area(g.shell) - sum(_ring_area(h) for h in g.holes)
        if isinstance(g, MultiPolygon):
            return sum(_ring_area(p.shell)
                       - sum(_ring_area(h) for h in p.holes)
                       for p in g.polygons)
        return 0.0
    return np.array([area(g) for g in _geoms(col)])


def _ring_area(ring: np.ndarray) -> float:
    x, y = ring[:, 0], ring[:, 1]
    return 0.5 * abs(np.sum(x[:-1] * y[1:] - x[1:] * y[:-1]))


def st_length(col) -> np.ndarray:
    def length(g):
        if isinstance(g, LineString):
            d = np.diff(g.coords, axis=0)
            return float(np.hypot(d[:, 0], d[:, 1]).sum())
        if hasattr(g, "lines"):
            return sum(length(l) for l in g.lines)
        return 0.0
    return np.array([length(g) for g in _geoms(col)])


def st_lengthSphere(col) -> np.ndarray:
    def length(g):
        if isinstance(g, LineString):
            c = g.coords
            return float(haversine_m(c[:-1, 0], c[:-1, 1],
                                     c[1:, 0], c[1:, 1]).sum())
        if hasattr(g, "lines"):
            return sum(length(l) for l in g.lines)
        return 0.0
    return np.array([length(g) for g in _geoms(col)])


def st_translate(col, dx: float, dy: float):
    if isinstance(col, tuple):
        return (np.asarray(col[0]) + dx, np.asarray(col[1]) + dy)

    def move(g):
        if isinstance(g, Point):
            return Point(g.x + dx, g.y + dy)
        if isinstance(g, LineString):
            return LineString(g.coords + [dx, dy])
        if isinstance(g, Polygon):
            return Polygon(g.shell + [dx, dy],
                           tuple(h + [dx, dy] for h in g.holes))
        raise ValueError(f"st_translate: unsupported {g.geom_type}")
    return np.array([move(g) for g in _geoms(col)], dtype=object)


def st_bufferPoint(col, distance_m: float, segments: int = 32) -> np.ndarray:
    """Geodesic point buffer → polygon (the reference's st_bufferPoint,
    used for dwithin-style joins)."""
    x, y = _points_xy(col)
    ang = np.linspace(0, 2 * np.pi, segments, endpoint=False)
    dlat = np.degrees(distance_m / EARTH_RADIUS_M)
    out = []
    for xi, yi in zip(x, y):
        cos = max(0.01, np.cos(np.radians(yi)))
        ring = np.stack([xi + dlat / cos * np.cos(ang),
                         yi + dlat * np.sin(ang)], axis=1)
        out.append(Polygon(ring))
    return np.array(out, dtype=object)


# -- round-2 additions: the remaining UDFs of the reference's set -----------
# (SpatialRelationFunctions / GeometricAccessorFunctions /
#  GeometricProcessingFunctions / GeometricOutputFunctions — see module doc)

def st_boundary(col) -> np.ndarray:
    """OGC boundary: polygon → exterior ring LineString (holes →
    MultiLineString), line → MultiPoint endpoints, point → empty
    MultiPoint (ST_Boundary)."""
    from ..geometry.types import MultiLineString

    def boundary(g):
        if isinstance(g, Polygon):
            rings = [np.vstack([g.shell, g.shell[:1]])
                     if not np.array_equal(g.shell[0], g.shell[-1])
                     else g.shell]
            rings += [np.vstack([h, h[:1]])
                      if not np.array_equal(h[0], h[-1]) else h
                      for h in g.holes]
            if len(rings) == 1:
                return LineString(rings[0])
            return MultiLineString(tuple(LineString(r) for r in rings))
        if isinstance(g, LineString):
            return MultiPoint(np.vstack([g.coords[0], g.coords[-1]]))
        return MultiPoint(np.empty((0, 2)))
    return np.array([boundary(g) for g in _geoms(col)], dtype=object)


def st_dimension(col) -> np.ndarray:
    """Topological dimension (ST_Dimension): point 0, line 1, area 2."""
    def dim(g):
        if isinstance(g, (Point, MultiPoint)):
            return 0
        if isinstance(g, LineString) or hasattr(g, "lines"):
            return 1
        return 2
    return np.array([dim(g) for g in _geoms(col)], dtype=np.int32)


def st_coordDim(col) -> np.ndarray:
    """Coordinate dimension — always 2 here (ST_CoordDim)."""
    return np.full(len(_geoms(col)), 2, dtype=np.int32)


def st_isEmpty(col) -> np.ndarray:
    def empty(g):
        if isinstance(g, Point):
            return False
        if isinstance(g, MultiPoint):
            return len(g.coords) == 0
        if isinstance(g, LineString):
            return len(g.coords) == 0
        if isinstance(g, Polygon):
            return len(g.shell) == 0
        if hasattr(g, "geoms"):
            return len(g.geoms) == 0
        if hasattr(g, "lines"):
            return len(g.lines) == 0
        if hasattr(g, "polygons"):
            return len(g.polygons) == 0
        return False
    return np.array([empty(g) for g in _geoms(col)], dtype=bool)


def st_isClosed(col) -> np.ndarray:
    """Line start == end (ST_IsClosed; non-lines are vacuously closed)."""
    def closed(g):
        if isinstance(g, LineString):
            return bool(len(g.coords) > 1
                        and np.array_equal(g.coords[0], g.coords[-1]))
        if hasattr(g, "lines"):
            return all(closed(l) for l in g.lines)
        return True
    return np.array([closed(g) for g in _geoms(col)], dtype=bool)


def st_isCollection(col) -> np.ndarray:
    from ..geometry.types import MultiLineString
    return np.array([isinstance(g, (MultiPoint, MultiLineString,
                                    MultiPolygon))
                     for g in _geoms(col)], dtype=bool)


def st_isSimple(col) -> np.ndarray:
    """No self-intersection (ST_IsSimple) — proper segment-crossing test
    for lines; points/valid polygons are simple."""
    from ..geometry.predicates import segments_cross_properly

    def simple(g):
        if isinstance(g, LineString) and len(g.coords) > 2:
            p1, p2 = g.coords[:-1], g.coords[1:]
            n = len(p1)
            for i in range(n):
                # non-adjacent segment pairs only
                js = np.arange(i + 2, n)
                if i == 0 and len(js) and np.array_equal(
                        g.coords[0], g.coords[-1]):
                    js = js[:-1]  # closing segment is adjacent to first
                if len(js):
                    hit = segments_cross_properly(
                        np.repeat(p1[i:i + 1], len(js), 0),
                        np.repeat(p2[i:i + 1], len(js), 0),
                        p1[js], p2[js])
                    if hit.any():
                        return False
        return True
    return np.array([simple(g) for g in _geoms(col)], dtype=bool)


def st_isRing(col) -> np.ndarray:
    """Closed AND simple (ST_IsRing)."""
    return st_isClosed(col) & st_isSimple(col)


def st_numGeometries(col) -> np.ndarray:
    def num(g):
        for attr in ("geoms", "lines", "polygons"):
            if hasattr(g, attr):
                return len(getattr(g, attr))
        if isinstance(g, MultiPoint):
            return len(g.coords)
        return 1
    return np.array([num(g) for g in _geoms(col)], dtype=np.int32)


def st_geometryN(col, n: int) -> np.ndarray:
    """1-based n-th member geometry, None when out of range
    (ST_GeometryN null semantics)."""
    def nth(g):
        if isinstance(g, MultiPoint):
            return (Point(*g.coords[n - 1])
                    if 1 <= n <= len(g.coords) else None)
        for attr in ("geoms", "lines", "polygons"):
            if hasattr(g, attr):
                members = getattr(g, attr)
                return members[n - 1] if 1 <= n <= len(members) else None
        return g if n == 1 else None
    return np.array([nth(g) for g in _geoms(col)], dtype=object)


def st_interiorRingN(col, n: int) -> np.ndarray:
    """1-based n-th interior ring of a polygon (ST_InteriorRingN)."""
    def ring(g):
        if isinstance(g, Polygon) and len(g.holes) >= n:
            return LineString(g.holes[n - 1])
        return None
    return np.array([ring(g) for g in _geoms(col)], dtype=object)


def st_closestPoint(col, target: Geometry) -> np.ndarray:
    """Closest point ON each column geometry to ``target``'s
    representative point (ST_ClosestPoint, planar)."""
    tx, ty = (target.x, target.y) if isinstance(target, Point) else (
        st_centroid([target])[0].x, st_centroid([target])[0].y)

    def closest(g):
        from ..geometry.predicates import all_vertices
        if isinstance(g, Point):
            return Point(g.x, g.y)
        segs = []
        if isinstance(g, LineString):
            segs = [(g.coords[:-1], g.coords[1:])]
        elif isinstance(g, Polygon):
            sh = np.vstack([g.shell, g.shell[:1]])
            segs = [(sh[:-1], sh[1:])]
        if segs:
            best, bd = None, np.inf
            for p1, p2 in segs:
                d = p2 - p1
                denom = np.maximum((d ** 2).sum(axis=1), 1e-18)
                t = np.clip(((tx - p1[:, 0]) * d[:, 0]
                             + (ty - p1[:, 1]) * d[:, 1]) / denom, 0, 1)
                cx = p1[:, 0] + t * d[:, 0]
                cy = p1[:, 1] + t * d[:, 1]
                dist = np.hypot(cx - tx, cy - ty)
                i = int(np.argmin(dist))
                if dist[i] < bd:
                    bd, best = dist[i], Point(float(cx[i]), float(cy[i]))
            return best
        v = all_vertices(g)
        d = np.hypot(v[:, 0] - tx, v[:, 1] - ty)
        i = int(np.argmin(d))
        return Point(float(v[i, 0]), float(v[i, 1]))
    return np.array([closest(g) for g in _geoms(col)], dtype=object)


def st_covers(geom: Geometry, col) -> np.ndarray:
    """geom covers the column geometries — containment including the
    boundary (ST_Covers; for point columns equals boundary-inclusive
    contains)."""
    x, y = _points_xy(col)
    if isinstance(geom, (Polygon, MultiPolygon)):
        return point_in_polygon(x, y, geom, include_boundary=True)
    env = geom.envelope
    return (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) & (y <= env.ymax)


def st_touches(geom: Geometry, col) -> np.ndarray:
    """Boundaries meet but interiors do not (ST_Touches) — for point
    columns: the point lies ON geom's boundary."""
    from ..geometry.predicates import points_on_rings, _rings_of
    x, y = _points_xy(col)
    if isinstance(geom, (Polygon, MultiPolygon)):
        return points_on_rings(x, y, _rings_of(geom), eps=1e-12)
    if isinstance(geom, LineString):
        a, b = geom.coords[0], geom.coords[-1]
        return (((x == a[0]) & (y == a[1]))
                | ((x == b[0]) & (y == b[1])))
    return np.zeros(len(x), dtype=bool)


def st_overlaps(col_a, col_b) -> np.ndarray:
    """Same-dimension geometries whose interiors intersect but neither
    contains the other (ST_Overlaps).  Point columns can never overlap
    (equal points are ST_Equals, not overlaps)."""
    ga, gb = _geoms(col_a), _geoms(col_b)
    if len(ga) and isinstance(ga[0], tuple):
        return np.zeros(len(ga), dtype=bool)
    from ..geometry.predicates import geometry_intersects, geometry_within
    out = np.zeros(len(ga), dtype=bool)
    for i, (a, b) in enumerate(zip(ga, gb)):
        if isinstance(a, Point) or isinstance(b, Point):
            continue
        out[i] = (geometry_intersects(a, b)
                  and not geometry_within(a, b)
                  and not geometry_within(b, a))
    return out


def st_geoHash(col, precision: int = 9) -> np.ndarray:
    """Geohash of each point (ST_GeoHash)."""
    from ..utils.geohash import geohash_encode
    x, y = _points_xy(col)
    return geohash_encode(x, y, precision)


def st_pointFromGeoHash(col) -> tuple:
    """Cell-center point column from geohashes (ST_PointFromGeoHash)."""
    from ..utils.geohash import geohash_decode
    lon, lat, _, _ = geohash_decode(np.asarray(col, dtype=object))
    return lon, lat


def st_geomFromGeoHash(col) -> np.ndarray:
    """Cell polygon from geohashes (ST_GeomFromGeoHash)."""
    from ..utils.geohash import geohash_decode
    lon, lat, elon, elat = geohash_decode(np.asarray(col, dtype=object))
    out = []
    for cx, cy, ex, ey in zip(lon, lat, elon, elat):
        out.append(Polygon([(cx - ex, cy - ey), (cx + ex, cy - ey),
                            (cx + ex, cy + ey), (cx - ex, cy + ey)]))
    return np.array(out, dtype=object)


def st_asGeoJSON(col) -> np.ndarray:
    """GeoJSON geometry strings (ST_AsGeoJSON)."""
    import json as _json
    from ..geometry.geojson import geometry_to_geojson
    if isinstance(col, tuple):
        x, y = col
        return np.array([_json.dumps({"type": "Point",
                                      "coordinates": [float(a), float(b)]})
                         for a, b in zip(np.atleast_1d(x), np.atleast_1d(y))],
                        dtype=object)
    return np.array([_json.dumps(geometry_to_geojson(g))
                     for g in _geoms(col)], dtype=object)


def st_asLatLonText(col) -> np.ndarray:
    """DMS "DDdMM'SS.sss"N DDDdMM'SS.sss"E" strings for points
    (ST_AsLatLonText)."""
    x, y = _points_xy(col)

    def dms(v, pos, neg):
        h = pos if v >= 0 else neg
        v = abs(v)
        d = int(v)
        m = int((v - d) * 60)
        s = (v - d - m / 60) * 3600
        return f"{d}°{m:02d}'{s:06.3f}\"{h}"

    return np.array([f"{dms(b, 'N', 'S')} {dms(a, 'E', 'W')}"
                     for a, b in zip(x, y)], dtype=object)


def st_aggregateDistanceSphere(col) -> float:
    """Total haversine path length over an ordered point column
    (ST_AggregateDistanceSphere)."""
    x, y = _points_xy(col)
    if len(x) < 2:
        return 0.0
    return float(haversine_m(x[:-1], y[:-1], x[1:], y[1:]).sum())


def _clip_ring_x(ring: np.ndarray, x0: float, keep_leq: bool):
    """Sutherland–Hodgman half-plane clip of a closed ring against the
    vertical line ``x == x0`` (keep x<=x0 or x>=x0).  Returns the clipped
    closed ring or None when nothing survives."""
    pts = np.asarray(ring, dtype=np.float64)
    if len(pts) > 1 and np.array_equal(pts[0], pts[-1]):
        pts = pts[:-1]
    out: list = []
    n = len(pts)
    for i in range(n):
        a, b = pts[i], pts[(i + 1) % n]
        ina = a[0] <= x0 if keep_leq else a[0] >= x0
        inb = b[0] <= x0 if keep_leq else b[0] >= x0
        if ina:
            out.append((a[0], a[1]))
        if ina != inb:
            f = (x0 - a[0]) / (b[0] - a[0])
            out.append((x0, a[1] + f * (b[1] - a[1])))
    if len(out) < 3:
        return None
    out.append(out[0])
    return np.asarray(out)


def st_antimeridianSafeGeom(col) -> np.ndarray:
    """Split polygons that cross the ±180 antimeridian into a
    MultiPolygon of in-range halves (ST_antimeridianSafeGeom) — the
    ACTUAL ring clipped at lon=180, not its envelope (the reference
    splits the true geometry, SQLFunctions' antimeridian handling)."""
    def fix(g):
        if not isinstance(g, Polygon):
            return g
        xs = g.shell[:, 0]
        if xs.max() - xs.min() <= 180.0:
            return g
        # west-positive wrap: shift negative lons +360 so the ring is
        # contiguous in [0, 360], then clip the SHIFTED ring at 180
        def shift(ring):
            r = np.asarray(ring, dtype=np.float64).copy()
            r[:, 0] = np.where(r[:, 0] < 0, r[:, 0] + 360.0, r[:, 0])
            return r
        shell = shift(g.shell)
        parts = []
        east = _clip_ring_x(shell, 180.0, keep_leq=True)
        if east is not None:
            holes = tuple(h for h in (
                _clip_ring_x(shift(hh), 180.0, True) for hh in g.holes)
                if h is not None)
            parts.append(Polygon(east, holes))
        west = _clip_ring_x(shell, 180.0, keep_leq=False)
        if west is not None:
            west = west.copy()
            west[:, 0] -= 360.0
            holes = []
            for hh in g.holes:
                c = _clip_ring_x(shift(hh), 180.0, False)
                if c is not None:
                    c = c.copy()
                    c[:, 0] -= 360.0
                    holes.append(c)
            parts.append(Polygon(west, tuple(holes)))
        if not parts:
            return g
        return parts[0] if len(parts) == 1 else MultiPolygon(tuple(parts))
    return np.array([fix(g) for g in _geoms(col)], dtype=object)


def _typed_from_wkt(col, want: type, name: str) -> np.ndarray:
    geoms = st_geomFromWKT(col)
    for g in geoms:
        if not isinstance(g, want):
            raise ValueError(f"{name}: expected {want.__name__}, "
                             f"got {type(g).__name__}")
    return geoms


def st_pointFromText(col) -> np.ndarray:
    return _typed_from_wkt(col, Point, "st_pointFromText")


def st_lineFromText(col) -> np.ndarray:
    return _typed_from_wkt(col, LineString, "st_lineFromText")


def st_polygonFromText(col) -> np.ndarray:
    return _typed_from_wkt(col, Polygon, "st_polygonFromText")


def st_mPointFromText(col) -> np.ndarray:
    return _typed_from_wkt(col, MultiPoint, "st_mPointFromText")


def st_mLineFromText(col) -> np.ndarray:
    from ..geometry.types import MultiLineString
    return _typed_from_wkt(col, MultiLineString, "st_mLineFromText")


def st_mPolyFromText(col) -> np.ndarray:
    return _typed_from_wkt(col, MultiPolygon, "st_mPolyFromText")


def st_byteArray(col) -> np.ndarray:
    """UTF-8 bytes of strings (ST_ByteArray)."""
    return np.array([s.encode("utf-8") for s in np.atleast_1d(
        np.asarray(col, dtype=object))], dtype=object)


__all__ += [
    "st_boundary", "st_dimension", "st_coordDim", "st_isEmpty",
    "st_isClosed", "st_isCollection", "st_isSimple", "st_isRing",
    "st_numGeometries", "st_geometryN", "st_interiorRingN",
    "st_closestPoint", "st_covers", "st_touches", "st_overlaps",
    "st_geoHash", "st_pointFromGeoHash", "st_geomFromGeoHash",
    "st_asGeoJSON", "st_asLatLonText", "st_aggregateDistanceSphere",
    "st_antimeridianSafeGeom", "st_pointFromText", "st_lineFromText",
    "st_polygonFromText", "st_mPointFromText", "st_mLineFromText",
    "st_mPolyFromText", "st_byteArray",
]


# -- SQL projection bridge --------------------------------------------------

#: st_* functions usable as SELECT-list expressions (single geometry/
#: value column plus optional numeric literal args); the grammar's
#: projection surface of the reference's SQLTypes UDF registration
#: (geomesa-spark-sql SQLGeometricAccessorFunctions etc.)
PROJECTABLE = {
    "st_x", "st_y", "st_asText", "st_geometryType", "st_isValid",
    "st_numPoints", "st_centroid", "st_envelope", "st_area",
    "st_length", "st_lengthSphere", "st_bufferPoint", "st_translate",
    "st_geoHash",
}

#: projectable functions defined over POINT columns only (validated
#: pre-scan; review r5)
_POINT_ONLY = {"st_x", "st_y", "st_geoHash", "st_bufferPoint"}

#: projectable functions whose OUTPUT is geometry objects — their
#: aliases cannot drive ORDER BY (geometries have no order)
GEOM_VALUED = {"st_centroid", "st_envelope", "st_bufferPoint",
               "st_translate"}


def resolve_projectable(name: str, attr=None, n_args: int = 0) -> str:
    """Validate a SELECT-list st_* call and return its canonical
    function name — the SINGLE definition of projectability, shared by
    the parser's pre-scan validation and :func:`apply_function` (every
    check here is scan-independent: an unknown name, wrong arity, or
    non-geometry column must not cost a 100M-row query first)."""
    import inspect

    canonical = {f.lower(): f for f in PROJECTABLE}.get(name.lower())
    if canonical is None:       # SQL function names are case-blind
        raise ValueError(
            f"{name} is not a projectable function (supported: "
            f"{sorted(PROJECTABLE)})")
    params = list(inspect.signature(
        globals()[canonical]).parameters.values())[1:]   # [0] = column
    required = sum(1 for p in params
                   if p.default is inspect.Parameter.empty)
    if not required <= n_args <= len(params):
        raise ValueError(
            f"{canonical} takes {required}"
            + (f"–{len(params)}" if len(params) > required else "")
            + f" argument(s) after the column, got {n_args}")
    if attr is not None and not attr.is_geometry:
        raise ValueError(
            f"{canonical} needs a geometry column, and "
            f"{attr.name!r} is {attr.type}")
    if (canonical in _POINT_ONLY and attr is not None
            and attr.type != "point"):
        # scan-independent: a polygon column reaching _points_xy would
        # crash AFTER the scan ran (review r5)
        raise ValueError(
            f"{canonical} needs a Point column, and {attr.name!r} is "
            f"{attr.type} (use st_centroid first)")
    return canonical


def apply_function(batch, name: str, col: str, *args):
    """Evaluate a projectable st_* function over a result batch's
    column (hit-sized — expressions run AFTER the scan, the
    post-push-down stage of the reference's catalyst plan).  Point
    layouts feed st_x/st_y their (x, y) tuple directly; other
    functions see geometry objects (materialized per hit row)."""
    attr = batch.sft.attribute(col)
    canonical = resolve_projectable(name, attr, len(args))
    fn = globals()[canonical]
    packed = getattr(batch, "geoms", None)
    if packed is not None and col == batch.sft.default_geom:
        # the packed store holds exactly the DEFAULT geometry — keying
        # on `geoms is not None` alone would silently answer for the
        # wrong column
        val = np.array([packed.geometry(i)
                        for i in range(len(batch))], dtype=object)
    elif f"{col}_x" in batch.columns:
        if canonical in ("st_x", "st_y", "st_geoHash"):
            val = batch.geom_xy(col)
        else:
            x, y = batch.geom_xy(col)
            val = np.array([Point(float(a), float(b))
                            for a, b in zip(x, y)], dtype=object)
    else:
        raise ValueError(
            f"geometry column {col!r} is not projectable here: "
            "only the default (packed) geometry or point-layout "
            "columns can feed st_* expressions")
    return fn(val, *args)
