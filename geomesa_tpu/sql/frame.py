"""SpatialFrame: datastore-backed columnar frame with predicate push-down.

The role of the reference's Spark integration (SpatialRDDProvider →
GeoMesaSparkSQL relation + SQLRules catalyst push-down,
geomesa-spark/geomesa-spark-sql/.../GeoMesaSparkSQL.scala, SQLRules.scala):
a lazy frame over one schema whose ``where`` clauses accumulate and are
pushed into the datastore's query planner as one ECQL conjunction at
``collect`` time — the index does the spatial work, not the frame.
Post-scan transforms (select / with_column / group_by aggregation) run
vectorized on the result columns; ``to_arrow`` hands off to the Arrow
interchange path for downstream analytics.
"""

from __future__ import annotations

import numpy as np

from ..features.batch import FeatureBatch
from ..filters.ast import And, Filter, Include
from ..filters.ecql import parse_ecql
from ..planning.planner import Query

__all__ = ["SpatialFrame"]


class SpatialFrame:
    """Lazy query-frame over one schema of a datastore."""

    def __init__(self, store, type_name: str, _filter: Filter = Include,
                 _props: list | None = None, _limit: int | None = None):
        self.store = store
        self.type_name = type_name
        self._filter = _filter
        self._props = _props
        self._limit = _limit

    # -- lazy builders (push-down accumulators) ---------------------------
    def where(self, predicate) -> "SpatialFrame":
        """AND an ECQL string (or Filter) into the pushed-down query."""
        f = parse_ecql(predicate) if isinstance(predicate, str) else predicate
        combined = f if self._filter is Include else And((self._filter, f))
        return SpatialFrame(self.store, self.type_name, combined,
                            self._props, self._limit)

    filter = where

    def select(self, *props) -> "SpatialFrame":
        return SpatialFrame(self.store, self.type_name, self._filter,
                            list(props), self._limit)

    def limit(self, n: int) -> "SpatialFrame":
        return SpatialFrame(self.store, self.type_name, self._filter,
                            self._props, n)

    # -- execution --------------------------------------------------------
    def _query(self) -> Query:
        return Query(filter=self._filter, properties=self._props,
                     max_features=self._limit)

    def collect(self) -> FeatureBatch:
        return self.store.query(self.type_name, self._query())

    def count(self) -> int:
        return len(self.collect())

    def explain(self) -> str:
        return self.store.explain(self.type_name, self._query())

    # -- post-scan vectorized ops ----------------------------------------
    def with_column(self, name: str, fn) -> dict:
        """Collect and add a computed column: fn(batch) → np.ndarray."""
        batch = self.collect()
        cols = dict(batch.columns)
        cols[name] = np.asarray(fn(batch))
        return cols

    def group_by(self, key: str, aggs: dict) -> dict:
        """Aggregate: ``aggs`` maps output name → (column, fn) with fn in
        {"count", "sum", "min", "max", "mean"}."""
        batch = self.collect()
        uniq, out = group_aggregate(batch.column(key), batch.column,
                                    aggs)
        return {key: uniq, **out}

    def to_arrow(self):
        from ..io.export import to_arrow
        return to_arrow(self.collect())

    # (group_aggregate lives at module level — shared with the SQL
    # text parser's expression-GROUP BY path)

    def to_pandas(self):  # pragma: no cover - convenience
        return self.to_arrow().to_pandas()


def group_aggregate(keys: np.ndarray, col_of, spec: dict):
    """Shared GROUP BY reduction over an arbitrary key array (the one
    definition behind SpatialFrame.group_by AND the SQL parser's
    expression-GROUP BY): ``col_of(name) -> np.ndarray`` supplies the
    aggregate inputs; ``spec`` maps output name → (column, fn) with fn
    in {"count", "sum", "min", "max", "mean"}.  Returns
    ``(unique_keys, {name: reduced})``."""
    keys = np.asarray(keys)
    keys = keys.astype(str) if keys.dtype == object else keys
    uniq, inverse = np.unique(keys, return_inverse=True)
    out: dict = {}
    for name, (col, fn) in spec.items():
        if fn == "count":
            out[name] = np.bincount(inverse, minlength=len(uniq))
            continue
        raw = np.asarray(col_of(col))
        if (raw.dtype == object or raw.dtype.kind in "US") \
                and fn in ("min", "max"):
            # string min/max: lexicographic per group (sum/mean on
            # strings still fail loudly in the float cast below)
            if not len(uniq):
                out[name] = raw.astype(str)[:0]
                continue
            order = np.lexsort((raw.astype(str), inverse))
            firsts = np.searchsorted(inverse[order],
                                     np.arange(len(uniq)))
            pick = (firsts if fn == "min"
                    else np.append(firsts[1:], len(raw)) - 1)
            out[name] = raw.astype(str)[order][pick]
            continue
        vals = raw.astype(np.float64)
        if fn == "sum":
            out[name] = np.bincount(inverse, weights=vals,
                                    minlength=len(uniq))
        elif fn == "mean":
            s = np.bincount(inverse, weights=vals, minlength=len(uniq))
            c = np.bincount(inverse, minlength=len(uniq))
            out[name] = s / np.maximum(c, 1)
        elif fn in ("min", "max"):
            red = np.full(len(uniq), np.inf if fn == "min" else -np.inf)
            np.minimum.at(red, inverse, vals) if fn == "min" else \
                np.maximum.at(red, inverse, vals)
            out[name] = red
        else:
            raise ValueError(f"unknown aggregation {fn!r}")
    return uniq, out
