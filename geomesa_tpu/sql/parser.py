"""SQL text front-end: SELECT statements lowered onto SpatialFrame.

The user surface of the reference's Spark SQL integration
(geomesa-spark/geomesa-spark-sql/.../GeoMesaSparkSQL.scala +
SQLRules.scala: SQL text → catalyst plan → spatial predicates pushed
into the datastore query).  Here the planner IS the datastore's, so the
"catalyst" stage reduces to: parse the statement, rewrite ``st_*``
spatial calls into ECQL predicates (the push-down rule), and lower
projection / WHERE / ORDER BY / LIMIT onto a :class:`SpatialFrame`;
GROUP BY aggregations run vectorized on the scan result.

Supported grammar (single table, no joins — the reference's pushed
fragment; anything beyond it belongs in the caller's dataframe code)::

    SELECT <*|cols|aggs|DISTINCT col> FROM <schema>
      [WHERE <predicate>] [GROUP BY <col>]
      [HAVING <alias|agg(col)> <op> <literal> [AND ...]]
      [ORDER BY <col> [ASC|DESC]] [LIMIT <n>]

``SELECT <group-col> FROM t GROUP BY <group-col>`` (no aggregates) and
``SELECT DISTINCT col`` serve the distinct-values idiom; HAVING terms
may aggregate beyond the SELECT list (computed as hidden columns).
Expression projections — ``SELECT st_x(geom) AS lon, name FROM t`` —
accept the projectable st_* surface (functions.PROJECTABLE): the scan
pushes down, expressions evaluate on the hit rows, and the result is
a dict of columns.

Aggregates: count(*), count(col), sum/min/max/avg(col) with optional
``AS alias`` — grouped (GROUP BY) or GLOBAL (no GROUP BY: one scan,
vectorized reductions; a bare count(*) short-circuits to the planner's
count path).
WHERE accepts ECQL predicates directly plus the Spark-style spatial
calls ``st_intersects/st_contains/st_within/st_dwithin(geom,
st_geomFromWKT('...'))`` which rewrite to their ECQL forms.
"""

from __future__ import annotations

import re

import numpy as np

from .frame import SpatialFrame

__all__ = ["sql_query", "parse_sql"]

_CLAUSE = re.compile(
    r"^\s*SELECT\s+(?P<select>.+?)\s+FROM\s+(?P<table>\w+)"
    r"(?:\s+WHERE\s+(?P<where>.+?))?"
    r"(?:\s+GROUP\s+BY\s+(?P<group>\w+))?"
    r"(?:\s+HAVING\s+(?P<having>.+?))?"
    r"(?:\s+ORDER\s+BY\s+(?P<order>\w+)(?:\s+(?P<dir>ASC|DESC))?)?"
    r"(?:\s+LIMIT\s+(?P<limit>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL)

#: one HAVING term: an alias or aggregate call compared to a literal;
#: terms join with AND (the pushed fragment — OR/expressions belong in
#: the caller's dataframe code, like the rest of the grammar)
_HAVING_TERM = re.compile(
    r"^(?:(?P<alias>\w+)|(?P<fn>count|sum|min|max|avg|mean)\s*\(\s*"
    r"(?P<col>\*|\w+)\s*\))\s*(?P<op><=|>=|<>|!=|=|<|>)\s*"
    r"(?P<num>'[^']*'|\S+)$", re.IGNORECASE)

#: a well-formed numeric literal — the HAVING literal validator ('1e'
#: or '+-3' must cost the grammar's descriptive error, never a raw
#: float() ValueError; round-4 ADVICE)
_NUM_LIT = re.compile(r"^[+-]?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?$")

#: aggregate functions whose output is always numeric — a string
#: literal compared against one is a type error the parser can report
#: (min/max inherit their column's type, so strings stay legal there)
_NUMERIC_FNS = frozenset({"count", "sum", "avg", "mean"})

_OPS = {
    "=": lambda a, b: a == b, "!=": lambda a, b: a != b,
    "<>": lambda a, b: a != b, "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b, ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def _order_limit(out: dict, order, descending, limit) -> dict:
    """Shared ORDER BY / LIMIT over a dict-of-columns result (grouped
    aggregations and expression projections use the same contract)."""
    if order is not None:
        idx = np.argsort(np.asarray(out[order]), kind="stable")
        if descending:
            idx = idx[::-1]
        if limit is not None:
            idx = idx[:limit]
        return {k: np.asarray(v)[idx] for k, v in out.items()}
    if limit is not None:
        return {k: np.asarray(v)[:limit] for k, v in out.items()}
    return out

_AGG = re.compile(r"^(count|sum|min|max|avg|mean)\s*\(\s*(\*|\w+)\s*\)"
                  r"(?:\s+AS\s+(\w+))?$", re.IGNORECASE)

#: expression projection: a projectable st_* call over one column with
#: optional numeric literal args — SELECT st_x(geom) AS lon, ...
_EXPR = re.compile(r"^(st_\w+)\s*\(\s*(\w+)"
                   r"((?:\s*,\s*[0-9.eE+-]+)*)\s*\)"
                   r"(?:\s+AS\s+(\w+))?$", re.IGNORECASE)

#: Spark-SQL spatial call → ECQL predicate rewrites (the SQLRules
#: push-down step).  ``st_geomFromWKT('WKT')`` unwraps to the bare WKT.
#: Both argument orders are accepted; with the LITERAL first, contains/
#: within invert (st_contains(lit, col) ⇔ col WITHIN lit) and the
#: symmetric predicates keep their name.
_ST_CALL = re.compile(
    r"st_(intersects|contains|within|crosses|touches|overlaps)\s*\(\s*"
    r"(\w+)\s*,\s*st_geomFromWKT\s*\(\s*'([^']+)'\s*\)\s*\)",
    re.IGNORECASE)
_ST_CALL_GEOM_FIRST = re.compile(
    r"st_(intersects|contains|within|crosses|touches|overlaps)\s*\(\s*"
    r"st_geomFromWKT\s*\(\s*'([^']+)'\s*\)\s*,\s*(\w+)\s*\)",
    re.IGNORECASE)
_ST_DWITHIN = re.compile(
    r"st_dwithin\s*\(\s*(\w+)\s*,\s*st_geomFromWKT\s*\(\s*'([^']+)'\s*\)"
    r"\s*,\s*([0-9.eE+-]+)\s*\)", re.IGNORECASE)
_SWAP = {"CONTAINS": "WITHIN", "WITHIN": "CONTAINS"}


def _rewrite_where(text: str) -> str:
    """st_* spatial calls → ECQL predicates (push-down rewrite)."""
    def sub(m):
        return f"{m.group(1).upper()}({m.group(2)}, {m.group(3)})"

    def sub_geom_first(m):
        op = m.group(1).upper()
        return f"{_SWAP.get(op, op)}({m.group(3)}, {m.group(2)})"

    text = _ST_CALL.sub(sub, text)
    text = _ST_CALL_GEOM_FIRST.sub(sub_geom_first, text)
    text = _ST_DWITHIN.sub(
        lambda m: f"DWITHIN({m.group(1)}, {m.group(2)}, {m.group(3)}, "
                  "meters)", text)
    return text


class ParsedSQL:
    def __init__(self, table, columns, aggs, where, group, order,
                 descending, limit, bare_count_star=False, having=None,
                 exprs=None):
        self.table = table
        self.columns = columns      # projection names, or None for *
        self.aggs = aggs            # [(fn, col, alias)] when aggregating
        #: [(fn, col, args, alias)] st_* expression projections
        self.exprs = exprs or []
        #: the statement is exactly an un-aliased ``SELECT count(*)`` —
        #: the one global-aggregate shape that returns a bare scalar
        self.bare_count_star = bare_count_star
        #: [(target, op, literal)] AND-terms; target is an alias str or
        #: an (fn, col) aggregate pair
        self.having = having or []
        self.where = where          # ECQL string or None
        self.group = group
        self.order = order
        self.descending = descending
        self.limit = limit


def parse_sql(text: str) -> ParsedSQL:
    m = _CLAUSE.match(text)
    if not m:
        raise ValueError(f"unsupported SQL statement: {text!r} (expected "
                         "SELECT ... FROM <schema> [WHERE ...] "
                         "[GROUP BY ...] [ORDER BY ...] [LIMIT n])")
    select = m.group("select").strip()
    group = m.group("group")
    dm = re.match(r"^DISTINCT\s+(\w+)$", select, re.IGNORECASE)
    if dm:
        # SELECT DISTINCT col ⇔ SELECT col GROUP BY col
        if group is not None and group != dm.group(1):
            raise ValueError("SELECT DISTINCT col supports grouping "
                             "only by that column")
        select, group = dm.group(1), dm.group(1)
    elif re.match(r"^DISTINCT\b", select, re.IGNORECASE):
        raise ValueError("DISTINCT supports a single column")
    columns = None
    aggs = []
    exprs = []
    explicit_alias = []
    if select != "*":
        # split on top-level commas only (st_translate(geom, 1, 2) has
        # commas inside the call)
        parts = [p.strip() for p in
                 re.split(r",(?![^()]*\))", select)]
        plain = []
        for p in parts:
            am = _AGG.match(p)
            em = _EXPR.match(p) if am is None else None
            if am:
                fn = am.group(1).lower()
                fn = "mean" if fn == "avg" else fn
                col = am.group(2)
                alias = am.group(3) or f"{fn}_{col}".replace("*", "rows")
                explicit_alias.append(am.group(3) is not None)
                aggs.append((fn, col, alias))
            elif em:
                fn = em.group(1).lower()
                args = tuple(int(a) if re.match(r"^[+-]?\d+$", a)
                             else float(a) for a in
                             em.group(3).replace(",", " ").split())
                alias = em.group(4) or f"{fn}_{em.group(2)}"
                exprs.append((fn, em.group(2), args, alias))
            else:
                if not re.match(r"^\w+$", p):
                    raise ValueError(f"unsupported projection {p!r}")
                plain.append(p)
        columns = plain or None
        if aggs and (plain or exprs) and m.group("group") is None:
            raise ValueError("mixing columns and aggregates needs GROUP BY")
        if exprs and (aggs or group is not None):
            # GROUP BY <expr alias> (e.g. GROUP BY st_geohash(geom, 4)
            # AS gh … — the round-4 weak-#7 wall): exactly one
            # expression, which IS the group key, plus aggregates
            if not (group is not None and len(exprs) == 1
                    and exprs[0][3] == group and not plain):
                raise ValueError(
                    "expression projections compose with GROUP BY only "
                    "as the group key (SELECT st_fn(col) AS k, aggs... "
                    "GROUP BY k); aggregate other expression outputs "
                    "in the caller")
        seen: set = set(plain)
        expr_group_alias = (exprs[0][3] if exprs and group is not None
                            and exprs[0][3] == group else None)
        for _, _, alias in ([(None, None, a) for _, _, _, a in exprs]
                            + aggs):
            if alias in seen:
                # results are keyed by alias — a duplicate would
                # silently collapse to the last aggregate
                raise ValueError(
                    f"duplicate aggregate alias {alias!r}: use AS to "
                    "name each aggregate uniquely")
            if (group is not None and alias == group
                    and alias != expr_group_alias):
                # same dict: an alias shadowing the group column would
                # silently replace the group labels with the aggregate
                # (the expression key's OWN alias IS the group column
                # by design — GROUP BY st_fn(col) AS k)
                raise ValueError(
                    f"aggregate alias {alias!r} collides with the "
                    "GROUP BY column — alias it differently")
            seen.add(alias)
    where = m.group("where")
    if where is not None:
        where = _rewrite_where(where.strip())
    having = []
    if m.group("having") is not None:
        if group is None:
            raise ValueError("HAVING requires GROUP BY (use WHERE for "
                             "row predicates)")
        for term in re.split(r"\s+AND\s+", m.group("having").strip(),
                             flags=re.IGNORECASE):
            tm = _HAVING_TERM.match(term.strip())
            if not tm:
                raise ValueError(
                    f"unsupported HAVING term {term!r} (expected "
                    "<alias|agg(col)> <op> <literal>, AND-joined)")
            if tm.group("alias"):
                target = tm.group("alias")
            else:
                fn = tm.group("fn").lower()
                target = ("mean" if fn == "avg" else fn,
                          tm.group("col"))
            lit = tm.group("num")
            # resolve the aggregate fn behind an alias too, so
            # `HAVING n > 'abc'` (n = count(*)) errors at parse time
            # like the inline form does
            fn = tm.group("fn")
            if fn is None:
                fn = next((f for f, _c, a in aggs
                           if a == tm.group("alias")), None)
            if lit.startswith("'"):
                if not re.fullmatch(r"'[^']*'", lit):
                    raise ValueError(
                        f"unsupported HAVING term {term!r}: "
                        f"unterminated or malformed string literal "
                        f"{lit}")
                if fn and fn.lower() in _NUMERIC_FNS:
                    raise ValueError(
                        f"unsupported HAVING term {term!r}: "
                        f"{fn.lower()}(...) is numeric but "
                        f"the literal {lit} is a string")
                lit = lit[1:-1]
            else:
                if not _NUM_LIT.match(lit):
                    raise ValueError(
                        f"unsupported HAVING term {term!r}: {lit!r} is "
                        "not a number or quoted string literal")
                lit = float(lit)
            having.append((target, tm.group("op"), lit))
    return ParsedSQL(
        table=m.group("table"), columns=columns, aggs=aggs, where=where,
        group=group,
        order=m.group("order"),
        descending=(m.group("dir") or "").upper() == "DESC",
        limit=int(m.group("limit")) if m.group("limit") else None,
        bare_count_star=(len(aggs) == 1 and not columns
                         and aggs[0][:2] == ("count", "*")
                         and not explicit_alias[0]),
        having=having, exprs=exprs)


def sql_query(store, text: str):
    """Execute a SELECT against a TpuDataStore.

    Returns a :class:`FeatureBatch` for row queries, a dict of columns
    for GROUP BY aggregations (or for JOIN queries — ``SELECT a.x, b.y
    FROM s1 a JOIN s2 b ON …``), a dict of scalars for global
    aggregates (``SELECT sum(x), avg(y) FROM t WHERE …``), or a scalar
    for a bare global count(*).
    """
    from .join import is_join, sql_join
    if is_join(text):
        return sql_join(store, text)
    q = parse_sql(text)
    frame = SpatialFrame(store, q.table)
    if q.where:
        frame = frame.where(q.where)
    if q.aggs and q.group is None:
        # global aggregates: one scan, vectorized reductions over the
        # hit columns (SELECT sum(x), avg(y), min(z) FROM t WHERE ...)
        for fn, col, _ in q.aggs:
            if col == "*" and fn != "count":
                raise ValueError(f"{fn}(*) is not defined — "
                                 "aggregate a column")
        # LIMIT is a semantic no-op on the single result row and stays
        # accepted (count(*) ... LIMIT 1 is a common probe idiom);
        # ORDER BY names a column of a one-row result and is rejected
        # like any other unsupported shape
        if q.order is not None:
            raise ValueError(
                "ORDER BY does not apply to a global aggregate "
                "(the result is a single row)")
        if all(col == "*" for _, col, _ in q.aggs):
            # count(*)-only: the planner's count path, no row scan.
            # A bare un-aliased count(*) keeps its scalar contract;
            # aliased/multiple forms return the dict like every other
            # global aggregate
            cnt = frame.count()
            if q.bare_count_star:
                return cnt
            return {alias: cnt for _, _, alias in q.aggs}
        # project ONLY the aggregated columns — a sum(score) over a
        # 100M-row store must not materialize the geometry columns
        cols = sorted({col for _, col, _ in q.aggs if col != "*"})
        frame = frame.select(*cols)
        batch = frame.collect()
        out: dict = {}
        for fn, col, alias in q.aggs:
            if col == "*":
                out[alias] = len(batch)
                continue
            vals = np.asarray(batch.column(col))
            if len(vals) == 0:
                out[alias] = 0 if fn == "count" else None
                continue
            if fn != "count" and not np.issubdtype(vals.dtype,
                                                   np.number):
                # reject non-numeric columns, like the GROUP BY path:
                # numpy's object-array sum would CONCATENATE a string
                # column (O(n²) copying) instead of erroring.  Numeric
                # dtypes reduce natively — an int64 sum must stay
                # exact, not round through float64
                raise ValueError(
                    f"{fn}({col}) needs a numeric column; "
                    f"{col!r} is not numeric")
            out[alias] = {
                "count": lambda v: int(len(v)),
                "sum": lambda v: v.sum(),
                "min": lambda v: v.min(),
                "max": lambda v: v.max(),
                "mean": lambda v: v.mean(),
            }[fn](vals)
        return out
    if q.group is not None:
        if not q.aggs and q.columns is None and not q.exprs:
            raise ValueError("SELECT * with GROUP BY is not defined — "
                             "project the group column or aggregates")
        stray = [c for c in (q.columns or []) if c != q.group]
        if stray:
            raise ValueError(
                f"column {stray[0]!r} must appear in the GROUP BY "
                "clause or be used in an aggregate function")
        spec = {alias: (q.group if col == "*" else col,
                        "count" if fn == "count" else fn)
                for fn, col, alias in q.aggs}
        # HAVING terms naming an un-projected aggregate compute it as a
        # hidden column (standard SQL: HAVING may aggregate beyond the
        # SELECT list), dropped after the mask
        having_cols = []
        hidden = []
        by_agg = {(fn, col): alias for fn, col, alias in q.aggs}
        for i, (target, op, lit) in enumerate(q.having):
            if isinstance(target, str):
                if target != q.group and target not in spec:
                    raise ValueError(
                        f"HAVING references {target!r}, which is not "
                        "the GROUP BY column or an aggregate alias "
                        f"(have: {sorted([q.group, *spec])})")
                having_cols.append((target, op, lit))
            else:
                fn, col = target
                alias = by_agg.get((fn, col))
                if alias is None:
                    alias = f"__having_{i}"
                    spec[alias] = (q.group if col == "*" else col,
                                   "count" if fn == "count" else fn)
                    hidden.append(alias)
                having_cols.append((alias, op, lit))
        if not spec:
            # SELECT <group-col> FROM t GROUP BY <group-col> — the
            # DISTINCT idiom; a hidden count drives the grouping
            spec["__distinct"] = (q.group, "count")
            hidden.append("__distinct")
        expr_key = next((e for e in q.exprs if e[3] == q.group), None)
        if expr_key is not None:
            # GROUP BY <expr alias>: ONE scan (push-down + projection
            # to the referenced columns), the key computed on the hit
            # batch, then the shared reduction (the catalyst
            # project-then-aggregate split)
            from .frame import group_aggregate
            from .functions import (
                GEOM_VALUED, apply_function, resolve_projectable,
            )
            fn, col, args, alias = expr_key
            sft_g = store.get_schema(q.table)
            if any(a.name == alias for a in sft_g.attributes):
                # `min(v)` must mean the COLUMN v — an expression alias
                # shadowing a schema attribute would silently aggregate
                # the group keys instead (review r5)
                raise ValueError(
                    f"expression alias {alias!r} shadows a schema "
                    f"attribute of {q.table!r} — alias it differently")
            canonical = resolve_projectable(fn, sft_g.attribute(col),
                                            len(args))
            if canonical in GEOM_VALUED:
                raise ValueError(
                    f"GROUP BY {alias!r} is not defined: {canonical} "
                    "produces geometry values (group by st_geohash/"
                    "st_x/st_y or another scalar expression)")
            needed = sorted({col} | {c for c, _ in spec.values()
                                     if c != "*" and c != q.group})
            batch = frame.select(*needed).collect()
            keys = np.asarray(apply_function(batch, fn, col, *args))
            uniq, red = group_aggregate(
                keys,
                lambda c: keys if c == q.group else batch.column(c),
                spec)
            out = {q.group: uniq, **red}
        else:
            out = frame.group_by(q.group, spec)
        if having_cols:
            keep = np.ones(len(np.asarray(out[q.group])), dtype=bool)
            for alias, op, lit in having_cols:
                keep &= _OPS[op](np.asarray(out[alias]), lit)
            out = {k: np.asarray(v)[keep] for k, v in out.items()}
        for alias in hidden:
            out.pop(alias, None)
        if q.order is not None and q.order not in out:
            raise ValueError(
                f"ORDER BY column {q.order!r} is not in the aggregation "
                f"output (have: {sorted(out)}); order by the GROUP BY "
                "column or an aggregate alias")
        return _order_limit(out, q.order, q.descending, q.limit)
    from ..planning.planner import Query
    if q.exprs:
        # expression projections: the scan pushes down (filter,
        # referenced base columns, and sort/limit when the sort key is
        # a schema attribute); st_* expressions evaluate on the hit
        # batch (the post-push-down stage of the catalyst plan) and
        # the result is a dict of columns keyed by projection name
        from .functions import (
            GEOM_VALUED, apply_function, resolve_projectable,
        )
        sft = store.get_schema(q.table)
        # every scan-independent validation runs BEFORE the scan — an
        # unknown function/column/arity must not cost a 100M-row query
        # first (resolve_projectable is the single definition)
        for fn, col, args, alias in q.exprs:
            canonical = resolve_projectable(fn, sft.attribute(col),
                                            len(args))
            if q.order == alias and canonical in GEOM_VALUED:
                raise ValueError(
                    f"ORDER BY {alias!r} is not defined: "
                    f"{canonical} produces geometry values (order by "
                    "st_x/st_y/a measure instead)")
        for c in (q.columns or []):
            if sft.attribute(c).is_geometry:
                raise ValueError(
                    f"project the geometry column {c!r} through an "
                    "expression (st_asText/st_x/st_y) in an "
                    "expression query")
        aliases = {alias for _, _, _, alias in q.exprs}
        attr_names = {a.name for a in sft.attributes}
        # ORDER BY resolves aliases first (post-sort), then any schema
        # attribute (pre-projection pushdown — the plain path's
        # behavior)
        pushed_sort = (q.order if q.order is not None
                       and q.order not in aliases
                       and q.order in attr_names else None)
        base = sorted({col for _, col, _, _ in q.exprs}
                      | set(q.columns or []))
        query = Query(filter=frame._filter, properties=base,
                      sort_by=pushed_sort, sort_desc=q.descending,
                      max_features=q.limit if (pushed_sort
                                               or q.order is None)
                      else None)
        batch = store.query(q.table, query)
        out = {}
        for c in (q.columns or []):
            out[c] = np.asarray(batch.column(c))
        for fn, col, args, alias in q.exprs:
            out[alias] = np.asarray(apply_function(batch, fn, col,
                                                   *args))
        if pushed_sort is not None:
            return out
        if q.order is not None and q.order not in out:
            raise ValueError(
                f"ORDER BY column {q.order!r} is not in the "
                f"projection output or the schema (have: "
                f"{sorted(set(out) | attr_names)})")
        return _order_limit(out, q.order, q.descending, q.limit)
    # row query: projection / sort / limit push into the planner Query
    query = Query(filter=frame._filter, properties=q.columns,
                  sort_by=q.order, sort_desc=q.descending,
                  max_features=q.limit)
    return store.query(q.table, query)
