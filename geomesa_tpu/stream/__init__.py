"""Streaming layer: message bus + live feature cache (the reference's
geomesa-kafka: GeoMessage protocol, producers/consumers, in-memory
spatially-indexed cache with feature events)."""

from .messages import GeoMessage
from .broker import InProcessBroker
from .polling import PollingStreamSource
from .registry import AvroMessageCodec, SchemaRegistry
from .store import StreamDataStore, LiveFeatureCache

__all__ = ["GeoMessage", "InProcessBroker", "StreamDataStore",
           "LiveFeatureCache", "PollingStreamSource", "SchemaRegistry",
           "AvroMessageCodec"]
