"""Schema-registry Avro message codec for the streaming layer.

The analog of the reference's Confluent integration
(geomesa-kafka/.../confluent/*: a Kafka store variant whose record
values are Confluent-framed Avro — magic byte 0x00 + 4-byte big-endian
schema id + Avro binary — resolved against a schema registry).  Here the
registry is in-process (subject → schema id → FeatureType), the framing
is identical, and the payload uses the framework's own Avro record codec
(io/avro.encode_record), so messages interop with standard Avro tooling.
"""

from __future__ import annotations

import struct
import threading

from ..features.feature_type import FeatureType, parse_spec
from ..io.avro import avro_schema, decode_record, encode_record

__all__ = ["SchemaRegistry", "AvroMessageCodec"]

_MAGIC = 0x00


class SchemaRegistry:
    """subject → versioned schemas with global ids (Confluent REST model,
    in-process)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_id: dict[int, FeatureType] = {}
        self._subjects: dict[str, list[int]] = {}
        self._next_id = 1

    def register(self, subject: str, sft_or_spec) -> int:
        """Register a schema version under a subject; returns its id
        (idempotent for an identical latest version)."""
        sft = (sft_or_spec if isinstance(sft_or_spec, FeatureType)
               else parse_spec(subject, sft_or_spec))
        with self._lock:
            versions = self._subjects.setdefault(subject, [])
            if versions:
                latest = self._by_id[versions[-1]]
                if latest.spec_string() == sft.spec_string():
                    return versions[-1]
            sid = self._next_id
            self._next_id += 1
            self._by_id[sid] = sft
            versions.append(sid)
            return sid

    def get(self, schema_id: int) -> FeatureType:
        with self._lock:
            if schema_id not in self._by_id:
                raise KeyError(f"no schema with id {schema_id}")
            return self._by_id[schema_id]

    def latest(self, subject: str) -> tuple[int, FeatureType]:
        with self._lock:
            versions = self._subjects.get(subject)
            if not versions:
                raise KeyError(f"no such subject {subject!r}")
            return versions[-1], self._by_id[versions[-1]]

    def avro_schema(self, schema_id: int) -> dict:
        """The Avro record schema JSON for a registered id."""
        return avro_schema(self.get(schema_id))


class AvroMessageCodec:
    """Confluent-framed Avro feature messages.

    ``encode(subject, fid, attrs)`` → ``b"\\x00" + id(4B BE) + avro``;
    ``decode(data)`` resolves the embedded schema id and returns
    ``(sft, fid, attrs)`` — so consumers need no out-of-band schema.
    """

    def __init__(self, registry: SchemaRegistry):
        self.registry = registry

    def encode(self, subject: str, fid: str, attrs: dict) -> bytes:
        sid, sft = self.registry.latest(subject)
        return (bytes([_MAGIC]) + struct.pack(">I", sid)
                + encode_record(sft, fid, attrs))

    def decode(self, data: bytes):
        if not data or data[0] != _MAGIC:
            raise ValueError("not a schema-registry framed message")
        (sid,) = struct.unpack_from(">I", data, 1)
        sft = self.registry.get(sid)
        fid, attrs, _ = decode_record(sft, data, pos=5)
        return sft, fid, attrs
