"""StreamDataStore: live layer over the message bus.

The reference's KafkaDataStore: producers publish GeoMessages; consumers
maintain an in-memory spatially-indexed cache of current feature state
(KafkaFeatureCacheImpl over BucketIndex grids, geomesa-kafka/.../index/
KafkaFeatureCacheImpl.scala:30-45), fire feature events to listeners
(GeoMesaFeatureListener), and serve queries from the cache via the local
query runner (KafkaQueryRunner).  Here:

* :class:`LiveFeatureCache` — id → attribute dict + BucketIndex grid.
* :class:`StreamDataStore` — write side publishes messages; ``consume()``
  drains the broker (call from a poll loop or a thread), applies
  mutations, and notifies listeners.  Queries evaluate the full filter
  over a columnar snapshot of the cache (LocalQueryRunner semantics —
  no curve index; the live set is small and hot).
"""

from __future__ import annotations

import threading

import numpy as np

from ..features.batch import FeatureBatch
from ..features.feature_type import FeatureType, parse_spec
from ..filters.evaluate import evaluate_filter
from ..planning.planner import Query
from ..utils.spatial_index import BucketIndex
from .broker import InProcessBroker
from .messages import GeoMessage

__all__ = ["LiveFeatureCache", "StreamDataStore"]


class LiveFeatureCache:
    """Current state of a streamed feature type, queryable by bbox."""

    def __init__(self, sft: FeatureType):
        self.sft = sft
        self.index = BucketIndex()
        self._features: dict[str, dict] = {}
        self._lock = threading.RLock()

    def put(self, fid: str, attributes: dict) -> None:
        with self._lock:
            self._features[fid] = attributes
            gx, gy = self._geom_of(attributes)
            if gx is not None:
                self.index.insert(fid, gx, gy)

    def _geom_of(self, attributes: dict):
        g = attributes.get(self.sft.geom_field)
        if g is None:
            return None, None
        if isinstance(g, (tuple, list)) and len(g) == 2:
            return float(g[0]), float(g[1])
        x = getattr(g, "x", None)
        y = getattr(g, "y", None)
        return (float(x), float(y)) if x is not None else (None, None)

    def remove(self, fid: str) -> bool:
        with self._lock:
            self.index.remove(fid)
            return self._features.pop(fid, None) is not None

    def clear(self) -> None:
        with self._lock:
            self._features.clear()
            self.index.clear()

    def __len__(self) -> int:
        return len(self._features)

    def all_feature_ids(self) -> list:
        """Every cached feature id — including features without geometry
        (which are absent from the spatial index)."""
        with self._lock:
            return list(self._features)

    def snapshot(self, fids=None) -> FeatureBatch:
        """Columnar snapshot of (a subset of) the cache."""
        with self._lock:
            if fids is None:
                fids = list(self._features)
            feats = [self._features[f] for f in fids if f in self._features]
            fids = [f for f in fids if f in self._features]
        data: dict = {}
        for a in self.sft.attributes:
            vals = [f.get(a.name) for f in feats]
            if a.is_geometry:
                xs = np.array([v[0] if isinstance(v, (tuple, list))
                               else getattr(v, "x", np.nan) for v in vals])
                ys = np.array([v[1] if isinstance(v, (tuple, list))
                               else getattr(v, "y", np.nan) for v in vals])
                data[a.name] = (xs, ys)
            elif a.type in ("int", "long", "date"):
                data[a.name] = np.array(
                    [0 if v is None else int(v) for v in vals], dtype=np.int64)
            elif a.type in ("float", "double"):
                data[a.name] = np.array(
                    [np.nan if v is None else float(v) for v in vals])
            else:
                data[a.name] = np.array(vals, dtype=object)
        return FeatureBatch.from_dict(
            self.sft, data, ids=np.array(fids, dtype=object))


class StreamDataStore:
    """Kafka-analog live store: publish mutations, consume into a cache."""

    def __init__(self, broker: InProcessBroker | None = None,
                 group: str = "default", registry=None):
        """``registry``: an optional
        :class:`~geomesa_tpu.stream.registry.SchemaRegistry` — when given,
        change-message payloads ride as Confluent-framed Avro (magic byte +
        schema id + Avro binary) instead of the JSON codec, the reference's
        geomesa-kafka-confluent variant."""
        self.broker = broker or InProcessBroker()
        self.group = group
        self.registry = registry
        self._codec = None
        if registry is not None:
            from .registry import AvroMessageCodec
            self._codec = AvroMessageCodec(registry)
        self._schemas: dict[str, FeatureType] = {}
        self._caches: dict[str, LiveFeatureCache] = {}
        self._listeners: dict[str, list] = {}
        #: per-offset apply-failure counts; after MAX_APPLY_ATTEMPTS the
        #: record is dead-lettered (skipped) so one bad-but-decodable
        #: message cannot block its partition forever
        self._apply_failures: dict = {}

    MAX_APPLY_ATTEMPTS = 3

    # -- schema -----------------------------------------------------------
    def create_schema(self, name: str, spec: str) -> FeatureType:
        sft = parse_spec(name, spec)
        self._schemas[name] = sft
        self._caches[name] = LiveFeatureCache(sft)
        self.broker.create_topic(name)
        if self.registry is not None:
            self.registry.register(name, sft)
        return sft

    def get_schema(self, name: str) -> FeatureType:
        return self._schemas[name]

    @property
    def type_names(self) -> list:
        return sorted(self._schemas)

    def add_listener(self, name: str, fn) -> None:
        """fn(GeoMessage) called after each applied mutation."""
        self._listeners.setdefault(name, []).append(fn)

    # -- producer side ----------------------------------------------------
    def write(self, name: str, fid: str, attributes: dict) -> None:
        if self._codec is not None:
            self.broker.send(name, fid, self._codec.encode(
                name, fid, attributes))
            return
        msg = GeoMessage.change(fid, attributes)
        self.broker.send(name, fid, msg.to_bytes())

    def write_batch(self, name: str, batch: FeatureBatch) -> int:
        sft = self._schemas[name]
        x = y = None
        if sft.geom_field:
            x, y = batch.geom_xy()
        for i in range(len(batch)):
            attrs = {}
            for a in sft.attributes:
                if a.is_geometry:
                    attrs[a.name] = (float(x[i]), float(y[i]))
                elif a.name in batch.columns:
                    v = batch.columns[a.name][i]
                    attrs[a.name] = v.item() if hasattr(v, "item") else v
            self.write(name, str(batch.ids[i]), attrs)
        return len(batch)

    def delete(self, name: str, fid: str) -> None:
        self.broker.send(name, fid, GeoMessage.delete(fid).to_bytes())

    def clear(self, name: str) -> None:
        self.broker.send(name, None, GeoMessage.clear().to_bytes())

    # -- consumer side ----------------------------------------------------
    def consume(self, name: str, max_records: int = 10_000) -> int:
        """Drain pending messages into the live cache; returns applied
        count.  At-least-once: offsets commit after application."""
        cache = self._caches[name]
        records = self.broker.poll(self.group, name, max_records)
        positions: dict = {}
        applied = 0
        try:
            for (part, off), raw in records:
                try:
                    if self._codec is not None and raw[:1] == b"\x00":
                        _, fid, attrs = self._codec.decode(raw)
                        msg = GeoMessage.change(fid, attrs)
                    else:
                        msg = GeoMessage.from_bytes(raw)
                except Exception:  # noqa: BLE001 — poison message: skip,
                    # log, and STILL advance the offset; replaying bytes
                    # that can never decode would wedge the group forever
                    import logging
                    logging.getLogger(__name__).exception(
                        "dropping undecodable message at %s/%s[%d]@%d",
                        name, self.group, part, off)
                    positions[part] = off + 1
                    continue
                # apply/listener failures are NOT poison: propagate without
                # committing this offset so the message is redelivered
                # (at-least-once) — but only MAX_APPLY_ATTEMPTS times, after
                # which the record is dead-lettered so a deterministically
                # failing message cannot block its partition forever
                key = (name, part, off)
                try:
                    if msg.kind == "change":
                        cache.put(msg.feature_id, msg.attributes)
                    elif msg.kind == "delete":
                        cache.remove(msg.feature_id)
                    else:
                        cache.clear()
                    for fn in self._listeners.get(name, ()):
                        fn(msg)
                except Exception:
                    n_fail = self._apply_failures.get(key, 0) + 1
                    self._apply_failures[key] = n_fail
                    if n_fail < self.MAX_APPLY_ATTEMPTS:
                        raise
                    import logging
                    logging.getLogger(__name__).exception(
                        "dead-lettering message after %d failed apply "
                        "attempts at %s/%s[%d]@%d", n_fail, name,
                        self.group, part, off)
                else:
                    self._apply_failures.pop(key, None)
                    applied += 1
                positions[part] = off + 1
        finally:
            if positions:
                self.broker.commit(self.group, name, positions)
        return applied

    # -- query side (LocalQueryRunner semantics) --------------------------
    def cache(self, name: str) -> LiveFeatureCache:
        return self._caches[name]

    def query(self, name: str, query="INCLUDE") -> FeatureBatch:
        q = query if isinstance(query, Query) else Query.of(query)
        cache = self._caches[name]
        snap = cache.snapshot()
        if len(snap) == 0:
            return snap
        mask = evaluate_filter(q.filter, snap)
        out = snap.take(np.flatnonzero(mask))
        if q.max_features is not None:
            out = out.take(np.arange(min(q.max_features, len(out))))
        return out
