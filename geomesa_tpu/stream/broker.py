"""In-process message broker: topics, partitions, offsets.

The test/embedded analog of the reference's Kafka dependency (its suites
run EmbeddedKafka, geomesa-kafka/.../EmbeddedKafka.scala) — and the
production seam: the broker interface (send / poll / commit) is what a
real Kafka client would implement.  Messages are keyed by feature id and
hashed over partitions, preserving the reference's per-key ordering
guarantee (GeoMessageSerializer keys messages by id for exactly this).
"""

from __future__ import annotations

import threading
import zlib

__all__ = ["InProcessBroker"]


class _Topic:
    def __init__(self, partitions: int):
        self.partitions = [[] for _ in range(partitions)]
        self.lock = threading.Lock()


class InProcessBroker:
    """Thread-safe topic → partition log store with consumer offsets."""

    def __init__(self, num_partitions: int = 4):
        self.num_partitions = num_partitions
        self._topics: dict[str, _Topic] = {}
        self._offsets: dict[tuple, int] = {}   # (group, topic, part) → offset
        self._lock = threading.Lock()

    def _topic(self, name: str) -> _Topic:
        with self._lock:
            t = self._topics.get(name)
            if t is None:
                t = self._topics[name] = _Topic(self.num_partitions)
            return t

    def create_topic(self, name: str) -> None:
        self._topic(name)

    def send(self, topic: str, key: str | None, value: bytes) -> tuple:
        """Append; returns (partition, offset)."""
        t = self._topic(topic)
        part = (zlib.crc32((key or "").encode()) % self.num_partitions
                if key is not None else 0)
        with t.lock:
            t.partitions[part].append(value)
            return part, len(t.partitions[part]) - 1

    def poll(self, group: str, topic: str, max_records: int = 1000) -> list:
        """Fetch records past the group's committed offsets (at-least-once:
        offsets advance only via :meth:`commit`)."""
        t = self._topic(topic)
        out = []
        with t.lock:
            for part in range(self.num_partitions):
                off = self._offsets.get((group, topic, part), 0)
                log = t.partitions[part]
                take = log[off:off + max_records]
                out.extend(((part, off + i), v) for i, v in enumerate(take))
        return out

    def commit(self, group: str, topic: str, positions: dict) -> None:
        """positions: partition → next offset to read."""
        with self._lock:
            for part, off in positions.items():
                key = (group, topic, part)
                self._offsets[key] = max(self._offsets.get(key, 0), off)

    def end_offsets(self, topic: str) -> dict:
        t = self._topic(topic)
        with t.lock:
            return {p: len(log) for p, log in enumerate(t.partitions)}
