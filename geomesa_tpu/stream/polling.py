"""Generic polling stream source.

The analog of the reference's geomesa-stream module (a camel-based
generic source DataStore polling external endpoints and converting
records into features).  Here the source polls a directory glob for new
or grown files, runs them through a converter, and hands batches to a
sink — a TpuDataStore, a StreamDataStore broker, or any callable.
"""

from __future__ import annotations

import glob
import os
import threading

__all__ = ["PollingStreamSource"]


class PollingStreamSource:
    """Polls ``pattern`` for file growth; converts new bytes to features.

    ``sink`` is either an object with ``write(type_name, batch)`` (a
    datastore) or a callable ``fn(batch)``.
    """

    def __init__(self, pattern: str, converter, sink, type_name: str = "",
                 interval_s: float = 1.0):
        self.pattern = pattern
        self.converter = converter
        self.sink = sink
        self.type_name = type_name
        self.interval_s = interval_s
        self._offsets: dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def poll_once(self) -> int:
        """One sweep: read any new bytes per file, convert, deliver.
        Returns features delivered (the camel route's exchange count)."""
        delivered = 0
        for path in sorted(glob.glob(self.pattern)):
            size = os.path.getsize(path)
            seen = self._offsets.get(path, 0)
            if size < seen:
                # truncation/rotation (logrotate copytruncate): restart
                # from the top instead of stalling or resuming mid-stream
                seen = self._offsets[path] = 0
            if size <= seen:
                continue
            with open(path, "rb") as f:
                f.seek(seen)
                chunk = f.read(size - seen)
            # deliver only whole lines; remainder re-reads next poll
            last_nl = chunk.rfind(b"\n")
            if last_nl < 0:
                continue
            batch = self.converter.convert(chunk[:last_nl + 1])
            if len(batch):
                if callable(self.sink):
                    self.sink(batch)
                else:
                    self.sink.write(self.type_name, batch)
                delivered += len(batch)
            # advance only after successful convert+deliver: a transient
            # sink failure re-reads the chunk next poll instead of
            # silently dropping it
            self._offsets[path] = seen + last_nl + 1
        return delivered

    # -- background loop ---------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 — keep polling
                    import logging
                    logging.getLogger(__name__).exception("poll failed")

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
