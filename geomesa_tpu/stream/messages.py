"""GeoMessage: the change/delete/clear wire protocol of the streaming
layer (the reference's kafka GeoMessage + serialization,
geomesa-kafka/.../data/GeoMessage.scala, GeoMessageSerializer.scala)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field

__all__ = ["GeoMessage"]


@dataclass(frozen=True)
class GeoMessage:
    """One mutation: kind in {"change", "delete", "clear"}.

    ``change`` carries a feature payload (dict of attribute → value, plus
    id); ``delete`` carries the feature id; ``clear`` drops everything.
    """

    kind: str
    feature_id: str | None = None
    attributes: dict = field(default_factory=dict)

    KINDS = ("change", "delete", "clear")

    def __post_init__(self):
        if self.kind not in self.KINDS:
            raise ValueError(f"bad message kind {self.kind!r}")
        if self.kind == "change" and self.feature_id is None:
            raise ValueError("change requires a feature id")
        if self.kind == "delete" and self.feature_id is None:
            raise ValueError("delete requires a feature id")

    @classmethod
    def change(cls, fid: str, attributes: dict) -> "GeoMessage":
        return cls("change", fid, dict(attributes))

    @classmethod
    def delete(cls, fid: str) -> "GeoMessage":
        return cls("delete", fid)

    @classmethod
    def clear(cls) -> "GeoMessage":
        return cls("clear")

    # -- wire codec (JSON; the reference uses a kryo-framed binary) -------
    def to_bytes(self) -> bytes:
        return json.dumps({"k": self.kind, "i": self.feature_id,
                           "a": self.attributes}, default=str).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "GeoMessage":
        d = json.loads(raw.decode())
        return cls(d["k"], d.get("i"), d.get("a") or {})
