"""Sketch-fed cardinality estimation: the costing half of closing the
cost-based-planning loop (ISSUE 19, ROADMAP item 4).

The :class:`CardinalityEstimator` answers the `StrategyDecider`'s
selectivity questions from the cached per-generation sketches the lean
indexes already maintain (ISSUE 2's ``RunSketch`` count-min tables and
histograms, and the Z3 cell-count partials), instead of whole-store
stats with magic fallbacks — the ``StatsBasedEstimator`` /
``CostEvaluator`` split of the reference's planning stack, fed by
observed per-generation data:

* **z3** — ``z3_cell_counts(bits)`` gives an exact row count per
  (time-bin, z-prefix cell) over every generation (sealed partials
  cached by the index, live run re-folded).  A query estimate runs the
  SAME covering-range decomposition the scan will run
  (``plan_z3_query``), coarsens the range bounds to cell granularity,
  and sums cell counts with two ``searchsorted`` probes per range — so
  the estimate is of the scan's *candidate superset*, exactly what
  ``plan.estimate.ratio`` audits against;
* **attribute** — ``sketch_scan(SketchFold(...))`` gives one merged
  count-min table (equals / IN via min-over-depth probes hashed
  bit-identically to the fold) and, for numeric attributes with a
  min/max stat, a fixed-bin histogram (ranges via pro-rated bin
  coverage).

Both tiers cache their merged table per **generation signature** —
``tuple((gen_id, rows) per generation)`` — so a warm repeat costs two
numpy probes and zero device dispatches: appends grow the live run's
row count and compaction mints fresh gen_ids, each changing the
signature and invalidating naturally (the LSM-compaction discipline of
the index-side ``PartialCache``).

When a question is out of sketch reach (non-lean store, string ranges,
index not yet built) the decider falls back to the legacy whole-store
stats tier, then to the named heuristic constants
(``geomesa.planning.selectivity.*`` — docs/planning.md).
"""

from __future__ import annotations

import math

import numpy as np

__all__ = ["CardinalityEstimator"]

#: z-prefix bits per cell of the z3 estimation table, adaptive to the
#: data's time-bin span: as fine as the device fold's cell-table
#: budget allows (``nb << bits <= _Z3_CELL_BUDGET``) so nearby-but-
#:  disjoint boxes land in different cells, never finer than the
#: ceiling (~6 bits/dim: ~5.6 deg lon x ~2.8 deg lat) or coarser than
#: the floor
_Z3_CELL_BITS_MIN, _Z3_CELL_BITS_MAX = 10, 18
#: per-dispatch dense cell-table budget of the estimation fold
#: (int64 slots; 4M slots = 32 MB device scratch at the extreme)
_Z3_CELL_BUDGET = 1 << 22
#: covering-range budget for the *estimation* decomposition — host-side
#: numpy recursion, so a fine budget costs ~ms; it must out-resolve the
#: cell table or every range rounds up to whole cells and a sliver box
#: charges for its neighbors' mass (the scan's own default target)
_EST_RANGES = 2048
#: count-min / histogram shape of the estimator's attribute folds
_ATTR_DEPTH, _ATTR_WIDTH, _ATTR_BINS = 4, 2048, 128
#: sketch-sized scan budget clamp: floor keeps boundary-bin splits
#: meaningful, ceiling mirrors index/z3_lean._MAX_RANGES_PER_WINDOW
_MAX_RANGES_FLOOR, _MAX_RANGES_CEIL = 512, 1 << 14

_NUMERIC_HIST_TYPES = frozenset(
    {"int", "integer", "long", "float", "double"})


def _gen_signature(idx) -> tuple | None:
    """Cache key over an index's generation set: compaction mints new
    gen_ids and appends grow the live run's row count, so any change
    to the data changes the signature."""
    gens = getattr(idx, "generations", None)
    if gens is None:
        return None
    return tuple(
        (int(g.gen_id), int(getattr(g, "n", None) or
                            getattr(g, "n_slots", 0) or 0))
        for g in gens)


class CardinalityEstimator:
    """Per-schema-store selectivity oracle over the lean indexes'
    cached sketches.  Constructed lazily and cached on the
    ``_SchemaStore`` — one estimator, one set of merged tables, shared
    by every query against the schema."""

    def __init__(self, store):
        self.store = store
        self._z3_cached = None    # (signature, keys, cumsum, idx, bits)
        self._attr_cached: dict = {}  # attr -> (sig, sketch, fold, idx)

    # -- z3 spatiotemporal tier --------------------------------------

    @staticmethod
    def _cell_bits(idx) -> int:
        """Finest cell resolution whose dense fold table fits the
        budget given the data's time-bin span.  Deterministic in the
        index's time extent, which only moves on writes — and writes
        change the generation signature, so a cached table never mixes
        resolutions."""
        from ..curve.binnedtime import to_binned_time
        t0 = np.int64(max(0, idx.t_min_ms or 0))
        t1 = np.int64(max(0, idx.t_max_ms or 0))
        b0, _ = to_binned_time(t0, idx.period)
        b1, _ = to_binned_time(t1, idx.period)
        nb = max(1, int(b1) - int(b0) + 1)
        bits = _Z3_CELL_BITS_MAX
        while bits > _Z3_CELL_BITS_MIN and (nb << bits) > _Z3_CELL_BUDGET:
            bits -= 1
        return bits

    def _z3_table(self):
        idx = self.store._indexes.get("z3")
        if idx is None or not hasattr(idx, "z3_cell_counts"):
            return None
        sig = _gen_signature(idx)
        cached = self._z3_cached
        if cached is not None and cached[0] == sig:
            return cached
        bits = self._cell_bits(idx)
        cells = idx.z3_cell_counts(bits)
        cpb = 1 << bits
        flat = np.fromiter((b * cpb + c for b, c in cells),
                           np.int64, len(cells))
        cnt = np.fromiter(cells.values(), np.int64, len(cells))
        order = np.argsort(flat)
        keys = flat[order]
        cum = np.concatenate([np.zeros(1, np.int64),
                              np.cumsum(cnt[order])])
        cached = (sig, keys, cum, idx, bits)
        self._z3_cached = cached
        return cached

    def z3_rows(self, boxes, intervals) -> int | None:
        """Estimated candidate rows of a z3 scan over ``boxes`` ×
        ``intervals`` (each ``(lo_ms, hi_ms)``, None = open end), or
        None when the sketch tier can't answer (not a lean z3 store,
        index not built yet)."""
        table = self._z3_table()
        if table is None or not len(boxes):
            return None
        _, keys, cum, idx, bits = table
        if not len(keys):
            return 0
        from ..index.z3 import plan_z3_query
        cpb = 1 << bits
        shift = np.int64(63 - bits)
        total = 0
        for lo, hi in intervals:
            lo, hi = idx._clamp_time(lo, hi)
            if lo > hi:
                continue
            plan = plan_z3_query(boxes, int(lo), int(hi), idx.period,
                                 _EST_RANGES, sfc=idx.sfc)
            if not len(plan.rbin):
                continue
            clo = plan.rbin.astype(np.int64) * cpb + (plan.rzlo >> shift)
            chi = plan.rbin.astype(np.int64) * cpb + (plan.rzhi >> shift)
            # coarsening to cells can make adjacent ranges overlap:
            # merge before summing so no cell counts twice
            order = np.argsort(clo, kind="stable")
            clo, chi = clo[order], chi[order]
            keep_hi = np.maximum.accumulate(chi)
            starts = np.r_[True, clo[1:] > keep_hi[:-1] + 1]
            seg = np.cumsum(starts) - 1
            mlo = clo[starts]
            mhi = np.full(len(mlo), np.iinfo(np.int64).min)
            np.maximum.at(mhi, seg, chi)
            li = np.searchsorted(keys, mlo, "left")
            ri = np.searchsorted(keys, mhi, "right")
            total += int((cum[ri] - cum[li]).sum())
        return min(total, int(cum[-1]))

    # -- attribute tier ----------------------------------------------

    def _attr_sketch(self, attr: str):
        idx = self.store._indexes.get(f"attr:{attr}")
        if idx is None or not hasattr(idx, "sketch_scan"):
            return None
        sig = _gen_signature(idx)
        cached = self._attr_cached.get(attr)
        if cached is not None and cached[0] == sig:
            return cached
        fold = self._attr_fold(attr, idx)
        sketch = idx.sketch_scan(fold)
        cached = (sig, sketch, fold, idx)
        self._attr_cached[attr] = cached
        return cached

    def _attr_fold(self, attr: str, idx):
        from ..stats.sketch import SketchFold
        bins, hlo, hhi = 0, 0.0, 1.0
        if getattr(idx, "attr_type", "string") in _NUMERIC_HIST_TYPES:
            mm = self.store.stats_map().get(f"{attr}_minmax")
            try:
                lo = float(mm.min)
                hi = float(mm.max)
            except (AttributeError, TypeError, ValueError):
                lo = hi = 0.0
            if hi > lo:
                bins, hlo, hhi = _ATTR_BINS, lo, hi
        return SketchFold(bins=bins, hlo=hlo, hhi=hhi,
                          depth=_ATTR_DEPTH, width=_ATTR_WIDTH)

    def attr_equals_rows(self, attr: str, values) -> int | None:
        """Estimated rows matching ``attr IN (values)`` from the
        merged count-min table; None when unanswerable."""
        cached = self._attr_sketch(attr)
        if cached is None:
            return None
        _, sketch, fold, idx = cached
        from ..stats.sketch import sketch_equals_count
        total = 0
        for v in values:
            est = sketch_equals_count(sketch, fold, v, idx.attr_type)
            if est is None:
                return None
            total += est
        return total

    def attr_range_rows(self, attr: str, lo, hi) -> int | None:
        """Estimated rows with ``lo <= attr <= hi`` (None bound =
        open) from the merged histogram; None when the fold carries no
        histogram (string attribute, no min/max stat yet)."""
        cached = self._attr_sketch(attr)
        if cached is None:
            return None
        _, sketch, fold, _ = cached
        from ..stats.sketch import sketch_range_count
        return sketch_range_count(sketch, fold, lo, hi)

    # -- scan-budget sizing ------------------------------------------

    @staticmethod
    def size_max_ranges(est_rows: float) -> int:
        """Covering-range budget sized from estimated candidate rows:
        sparse queries keep a coarse cheap decomposition, dense ones
        earn a finer one (less gather over-scan).  Monotone, clamped,
        and deterministic — a warm repeat gets the same budget, so
        padded scan shapes stay stable (zero warm recompiles)."""
        sized = 16.0 * math.sqrt(max(0.0, float(est_rows)) + 1.0)
        return int(min(_MAX_RANGES_CEIL, max(_MAX_RANGES_FLOOR, sized)))
