"""Filter strategies and cost-based index selection.

Mirrors the reference's strategy machinery: per-index applicability
heuristics (geomesa-index-api/.../index/strategies/
{SpatioTemporalFilterStrategy, SpatialFilterStrategy,
AttributeFilterStrategy, IdFilterStrategy}.scala) and the cost-based
decider (planning/StrategyDecider.scala:67-112,140-152) that estimates
per-strategy feature counts from stats and picks the cheapest.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import numpy as np

from ..config import PlanningProperties, QueryProperties
from ..features.feature_type import FeatureType
from ..filters.ast import (
    And, Between, Filter, IdFilter, In, Like, Or,
    PropertyCompare, _Exclude,
)
from ..filters.extract import extract_geometries, extract_intervals
from ..stats.stat import EnumerationStat, Frequency, Histogram, MinMax, TopK
from .explain import Explainer, ExplainNull

__all__ = ["FilterStrategy", "StrategyDecider"]


@dataclass
class FilterStrategy:
    """A candidate execution strategy: which index serves the query and at
    what estimated cost (feature count to scan)."""

    #: 'z3' | 'z2' | 'xz3' | 'xz2' | 'id' | 'attr:<name>' | 'or-split'
    #: | 'full' | 'none'
    index: str
    cost: float
    geometries: tuple = ()      # extracted query geometries
    intervals: tuple = ()       # extracted (lo_ms, hi_ms)
    ids: tuple = ()             # extracted feature ids
    attr_values: tuple = ()     # attribute predicate descriptors
    branches: tuple = ()        # ('or-split') per-branch FilterStrategy
    #: which estimator tier produced ``cost``: 'sketch' (per-generation
    #: sketches), 'stats' (whole-store stats), 'heuristic' (named
    #: defaults), or 'observed' (a replan folded a scan's actual in)
    source: str = "heuristic"
    #: sketch-sized covering-range budget for z3/xz scans; None = the
    #: geomesa.scan.ranges.target default
    max_ranges: int | None = None

    def __repr__(self):
        return f"FilterStrategy({self.index}, cost={self.cost:.0f})"


def _collect_id_filters(f: Filter) -> tuple:
    if isinstance(f, IdFilter):
        return tuple(f.ids)
    if isinstance(f, And):
        out = []
        for p in f.filters:
            out.extend(_collect_id_filters(p))
        return tuple(out)
    return ()


def _collect_attr_predicates(f: Filter, indexed: set[str]) -> list:
    """(attr, kind, payload) descriptors for indexed-attribute predicates
    at the top AND level."""
    out = []
    if isinstance(f, And):
        for p in f.filters:
            out.extend(_collect_attr_predicates(p, indexed))
        return out
    if isinstance(f, PropertyCompare) and f.prop in indexed:
        if f.op == "=":
            out.append((f.prop, "equals", f.value))
        elif f.op in ("<", "<="):
            out.append((f.prop, "range", (None, f.value, True, f.op == "<=")))
        elif f.op in (">", ">="):
            out.append((f.prop, "range", (f.value, None, f.op == ">=", True)))
    elif isinstance(f, Between) and f.prop in indexed:
        out.append((f.prop, "range", (f.lo, f.hi, True, True)))
    elif isinstance(f, In) and f.prop in indexed:
        out.append((f.prop, "in", tuple(f.values)))
    elif isinstance(f, Like) and f.prop in indexed and not f.case_insensitive:
        pat = f.pattern
        if pat and "%" not in pat[:-1] and pat.endswith("%") and "_" not in pat:
            out.append((f.prop, "prefix", pat[:-1]))
    return out


class StrategyDecider:
    """Enumerate viable strategies for a filter and pick the cheapest."""

    def __init__(self, sft: FeatureType, stats: dict | None = None,
                 total_count: int = 0,
                 allowed_indices: set[str] | None = None,
                 attr_z3_tier: bool = True,
                 servable_attrs: set[str] | None = None,
                 estimator=None):
        """``allowed_indices`` further restricts the offered strategies
        beyond the schema's ``geomesa.indices.enabled`` user data (the
        store's lean profile serves {z3, id, attr} plus full scans).
        ``attr_z3_tier``: whether the store's attribute index carries a
        z3 secondary (full-fat yes; the lean generational attribute
        index tiers by DATE only) — costing a spatial discount the
        index cannot deliver would mis-prefer attr over z3.
        ``servable_attrs``: the attributes the store can actually
        index-serve (None = every indexed attribute) — the lean
        lexicode covers numerics/dates/strings only, and offering a
        strategy the executor must reject would turn a fallback-able
        query into an error.
        ``estimator``: a ``planning.estimator.CardinalityEstimator``
        answering selectivity questions from per-generation sketches —
        the preferred costing tier when it can answer (ISSUE 19);
        ignored while ``geomesa.planning.estimator.enabled`` is off."""
        self.sft = sft
        self.stats = stats or {}
        self.total = max(1, total_count)
        self.allowed_indices = allowed_indices
        self.attr_z3_tier = attr_z3_tier
        self.servable_attrs = servable_attrs
        self.estimator = (
            estimator if estimator is not None
            and PlanningProperties.ESTIMATOR_ENABLED.to_bool() else None)
        #: every option the last decide() costed (chosen included) — a
        #: best-effort MIRROR for embedders; concurrent deciders must
        #: use the per-call return of :meth:`decide_with_options`
        #: instead (the fused serving plane submits concurrently, and
        #: instance state would clobber cross-thread)
        self.last_options: tuple = ()

    # -- cost estimates (StatsBasedEstimator spirit) ----------------------
    def _spatial_fraction(self, geometries) -> float:
        """Estimated fraction of the data a query geometry set covers:
        the intersection with the DATA extent (the maintained bbox
        sketch) over that extent — a box covering all the data costs
        ~1.0 even when it is tiny against the world, so a selective
        attribute strategy can beat z3 there (round-4 VERDICT #1's
        wide-bbox + selective-attribute case)."""
        if not geometries:
            return 1.0
        bb = self.stats.get(f"{self.sft.geom_field}_bbox")
        if bb is not None and not bb.is_empty:
            x0, y0, x1, y1 = bb.bounds

            def axis(qlo, qhi, lo, hi):
                if hi - lo <= 0:   # degenerate extent: in or out
                    return 1.0 if qlo <= lo <= qhi else 0.0
                return max(0.0, (min(qhi, hi) - max(qlo, lo)) / (hi - lo))

            inter = sum(axis(g.envelope.as_tuple()[0],
                             g.envelope.as_tuple()[2], x0, x1)
                        * axis(g.envelope.as_tuple()[1],
                               g.envelope.as_tuple()[3], y0, y1)
                        for g in geometries)
            return min(1.0, inter)
        area = sum(g.envelope.area for g in geometries)
        return min(1.0, area / (360.0 * 180.0))

    def _temporal_fraction(self, intervals) -> float:
        if not intervals:
            return 1.0
        mm: MinMax | None = self.stats.get("dtg_minmax")
        if mm is None or mm.is_empty or mm.max == mm.min:
            return 0.1
        span = float(mm.max - mm.min)
        covered = 0.0
        for lo, hi in intervals:
            lo = mm.min if lo is None else lo
            hi = mm.max if hi is None else hi
            covered += max(0.0, min(float(hi), float(mm.max)) - max(float(lo), float(mm.min)))
        return min(1.0, covered / span)

    def _attr_cost(self, attr: str, kind: str, payload) -> tuple[float, str]:
        """(cost, source) of an attribute predicate from whole-store
        stats, falling back to the named heuristic selectivities
        (``geomesa.planning.selectivity.*`` — the old bare ``total/10``
        and ``total/4`` magic constants, now operator-tunable)."""
        enum: EnumerationStat | None = self.stats.get(f"{attr}_enumeration")
        freq: Frequency | None = self.stats.get(f"{attr}_frequency")
        hist: Histogram | None = self.stats.get(f"{attr}_histogram")
        if kind == "equals":
            if enum is not None and not enum.is_empty:
                return float(enum.counts.get(
                    payload, enum.counts.get(str(payload), 0))), "stats"
            if freq is not None and not freq.is_empty:
                return float(freq.count(payload)), "stats"
            return self.total * float(
                PlanningProperties.SELECTIVITY_EQUALS_DEFAULT.get()), \
                "heuristic"
        if kind == "in":
            total, source = 0.0, "stats"
            for v in payload:
                c, s = self._attr_cost(attr, "equals", v)
                total += c
                if s != "stats":
                    source = s
            return total, source
        if kind == "range" and hist is not None and not hist.is_empty:
            lo, hi, *_ = payload
            return float(hist.estimate_range(
                float(lo) if lo is not None else hist.lo,
                float(hi) if hi is not None else hist.hi)), "stats"
        return self.total * float(
            PlanningProperties.SELECTIVITY_RANGE_DEFAULT.get()), "heuristic"

    # -- sketch tier (planning/estimator.py, ISSUE 19) --------------------
    def _frac_source(self, spatial: bool, temporal: bool) -> str:
        """Whether the fraction-product cost for a z-index strategy was
        stats-backed ('stats') or ran on fallback constants
        ('heuristic')."""
        ok = True
        if spatial:
            bb = self.stats.get(f"{self.sft.geom_field}_bbox")
            ok = bb is not None and not bb.is_empty
        if ok and temporal:
            mm = self.stats.get("dtg_minmax")
            ok = mm is not None and not mm.is_empty and mm.max != mm.min
        return "stats" if ok else "heuristic"

    def _estimate_z3(self, geometries, intervals):
        """Sketch-tier candidate estimate for a z3 scan, or None when
        the tier can't answer (no estimator, non-lean store, no z3
        cell-count sketch).  Estimation must never fail a plan."""
        if self.estimator is None or not intervals:
            return None
        boxes = [g.envelope.as_tuple() for g in geometries]
        if not boxes:
            boxes = [(-180.0, -90.0, 180.0, 90.0)]
        try:
            return self.estimator.z3_rows(boxes, intervals)
        except Exception:
            return None

    def _estimate_attr(self, attr: str, kind: str, payload):
        """Sketch-tier row estimate for an attribute predicate, or
        None when the tier can't answer."""
        if self.estimator is None:
            return None
        try:
            if kind == "equals":
                return self.estimator.attr_equals_rows(attr, (payload,))
            if kind == "in":
                return self.estimator.attr_equals_rows(attr, payload)
            if kind == "range":
                lo, hi, *_ = payload
                return self.estimator.attr_range_rows(attr, lo, hi)
        except Exception:
            return None
        return None

    # -- strategy enumeration ---------------------------------------------
    def _enabled(self, index: str) -> bool:
        """Schema-level index restriction (``geomesa.indices.enabled``
        user data — the reference's per-schema index configuration,
        RichSimpleFeatureType.getIndices): a disabled index is never
        offered as a strategy."""
        if (self.allowed_indices is not None
                and index not in self.allowed_indices):
            return False
        enabled = self.sft.enabled_indices
        return enabled is None or index in enabled

    def strategies(self, f: Filter) -> list[FilterStrategy]:
        sft = self.sft
        out: list[FilterStrategy] = []

        ids = _collect_id_filters(f)
        if ids and self._enabled("id"):
            out.append(FilterStrategy("id", float(len(ids)), ids=ids))

        geom = sft.geom_field
        dtg = sft.dtg_field
        geoms = extract_geometries(f, geom) if geom else None
        intervals = extract_intervals(f, dtg) if dtg else None

        if geoms is not None and geoms.disjoint or intervals is not None and intervals.disjoint:
            return [FilterStrategy("none", 0.0)]

        spatial = bool(geoms and geoms.values)
        # fully-bounded intervals serve either z index; the z3 POINT index
        # also serves half-open intervals because it clamps them to the
        # data's time extent (the reference requires bounded intervals,
        # SpatioTemporalFilterStrategy — clamping removes that need here)
        all_ivs = tuple(intervals.values) if intervals else ()
        bounded = tuple(iv for iv in all_ivs
                        if iv[0] is not None and iv[1] is not None)
        usable = all_ivs if sft.is_points else bounded
        temporal = bool(usable)

        sp_frac = self._spatial_fraction(geoms.values if geoms else ())
        tm_frac = self._temporal_fraction(usable)

        if temporal and dtg:
            idx = "z3" if sft.is_points else "xz3"
            if self._enabled(idx):
                qgeoms = tuple(geoms.values) if geoms else ()
                cost = self.total * sp_frac * tm_frac
                source, mr = self._frac_source(spatial, True), None
                est = self._estimate_z3(qgeoms, usable)
                if est is not None:
                    cost, source = float(est), "sketch"
                    mr = self.estimator.size_max_ranges(est)
                out.append(FilterStrategy(
                    idx, max(1.0, cost), geometries=qgeoms,
                    intervals=usable, source=source, max_ranges=mr))
        if spatial:
            idx = "z2" if sft.is_points else "xz2"
            if self._enabled(idx):
                cost = self.total * sp_frac
                # de-prioritize pure-spatial when a tighter temporal plan
                # exists
                out.append(FilterStrategy(
                    idx, max(1.0, cost), geometries=tuple(geoms.values),
                    intervals=tuple(intervals.values) if intervals else (),
                    source=self._frac_source(True, False)))
            elif (not temporal and dtg and sft.is_points
                  and self._enabled("z3")):
                # no z2 available (e.g. the lean profile serves only the
                # z3 scale index): a pure-spatial query runs on z3 with
                # an OPEN interval, which the point index clamps to the
                # data's time extent — same trick that admits half-open
                # intervals above
                qgeoms = tuple(geoms.values)
                cost = self.total * sp_frac
                source, mr = self._frac_source(True, False), None
                est = self._estimate_z3(qgeoms, ((None, None),))
                if est is not None:
                    cost, source = float(est), "sketch"
                    mr = self.estimator.size_max_ranges(est)
                out.append(FilterStrategy(
                    "z3", max(1.0, cost), geometries=qgeoms,
                    intervals=((None, None),), source=source,
                    max_ranges=mr))
            elif (not temporal and dtg and not sft.is_points
                  and self._enabled("xz3")):
                # the non-point analog: a lean XZ3 schema (no xz2
                # available) serves pure-spatial queries with an open
                # clamped interval
                out.append(FilterStrategy(
                    "xz3", max(1.0, self.total * sp_frac),
                    geometries=tuple(geoms.values),
                    intervals=((None, None),),
                    source=self._frac_source(True, False)))

        indexed = ({a.name for a in sft.attributes if a.indexed}
                   if self._enabled("attr") else set())
        if self.servable_attrs is not None:
            indexed &= self.servable_attrs
        for attr, kind, payload in _collect_attr_predicates(f, indexed):
            cost, source = self._attr_cost(attr, kind, payload)
            est = self._estimate_attr(attr, kind, payload)
            if est is not None:
                cost, source = float(est), "sketch"
            # secondary tiers narrow equality/IN runs (tiered-range
            # assembly, api/GeoMesaFeatureIndex.scala:248-338): the date
            # tier by the temporal fraction; the z3 tier (schemas with
            # point geom + dtg) by the spatial fraction too
            tiered_ivs = all_ivs if dtg and kind in ("equals", "in") else ()
            tiered_geoms = ()
            if tiered_ivs:
                cost *= self._temporal_fraction(all_ivs)
            if (dtg and geom and sft.is_points and kind in ("equals", "in")
                    and spatial and self.attr_z3_tier):
                tiered_geoms = tuple(geoms.values)
                cost *= sp_frac
            out.append(FilterStrategy(
                f"attr:{attr}", max(1.0, cost),
                attr_values=((attr, kind, payload),),
                intervals=tiered_ivs, geometries=tiered_geoms,
                source=source))

        # the full-scan cost is the maintained row count — exact
        out.append(FilterStrategy("full", float(self.total),
                                  source="stats"))
        return out

    def decide(self, f: Filter, explain: Explainer | None = None,
               forced: str | None = None) -> FilterStrategy:
        """``forced`` pins the strategy to a named index (the reference's
        QUERY_INDEX hint, index/planning/StrategyDecider.scala:67-79:
        a requested index bypasses cost comparison)."""
        return self.decide_with_options(f, explain, forced)[0]

    def decide_with_options(
            self, f: Filter, explain: Explainer | None = None,
            forced: str | None = None,
            observed: dict | None = None,
    ) -> tuple[FilterStrategy, tuple]:
        """:meth:`decide` plus every option costed, returned PER CALL —
        the thread-safe surface (the fused serving plane runs
        concurrent decides; ``last_options`` instance state would
        clobber cross-thread).  ``observed`` maps strategy-index names
        to actual candidate counts a replanning query measured
        mid-scan (planning/adaptive.py): a named strategy's cost is
        replaced by its observed actual before comparison."""
        explain = explain or ExplainNull()
        chosen, options = self._decide(f, observed)
        self.last_options = tuple(options)
        explain.push("Strategy selection:")
        for o in options:
            explain(lambda o=o: f"option {o.index}: estimated cost "
                    f"{o.cost:.0f} [{o.source}]")
        if forced is not None:
            match = [o for o in options
                     if o.index == forced or o.index.startswith(f"{forced}:")]
            if not match:
                raise ValueError(
                    f"QUERY_INDEX hint requested {forced!r} but no such "
                    f"strategy applies (have: "
                    f"{sorted(o.index for o in options)})")
            chosen = min(match, key=lambda o: o.cost)
            explain(lambda: f"forced by QUERY_INDEX hint: {chosen.index}")
        if chosen.index == "full" and QueryProperties.BLOCK_FULL_TABLE_SCANS.to_bool():
            raise RuntimeError(
                "full-table scan required but blocked "
                "(geomesa.scan.block.full.table=true)")
        explain(lambda: f"chosen: {chosen.index} (cost {chosen.cost:.0f}, "
                f"source {chosen.source})")
        explain.pop()
        return chosen, tuple(options)

    def _reobserve(self, o: FilterStrategy, observed: dict) -> FilterStrategy:
        """Fold a replanning query's measured candidate count into the
        strategy it was measured on (the probe count IS that
        strategy's candidate cardinality — no estimation left)."""
        if o.index not in observed:
            return o
        cost = max(1.0, float(observed[o.index]))
        mr = o.max_ranges
        if self.estimator is not None and o.index in ("z3", "xz3"):
            mr = self.estimator.size_max_ranges(cost)
        return replace(o, cost=cost, source="observed", max_ranges=mr)

    def _decide(self, f: Filter,
                observed: dict | None = None) -> tuple[FilterStrategy, list]:
        if isinstance(f, _Exclude):
            return FilterStrategy("none", 0.0), []
        options = self.strategies(f)
        if observed:
            options = [self._reobserve(o, observed) for o in options]
        chosen = min(options, key=lambda o: o.cost)
        if chosen.index == "full":
            # OR-split (FilterSplitter's disjunction handling,
            # planning/FilterSplitter.scala:294-307): when every branch of
            # a top-level OR is individually indexable and the summed
            # branch costs beat one full scan, serve the query per branch
            from ..filters.ast import Or
            if isinstance(f, Or):
                branch = [(p, self._decide(p, observed)[0])
                          for p in f.filters]
                if all(st.index != "full" for _, st in branch):
                    total = sum(st.cost for _, st in branch)
                    if total < chosen.cost:
                        split = FilterStrategy("or-split", total,
                                               branches=tuple(branch))
                        return split, options + [split]
        return chosen, options
