"""Query interceptors: user-pluggable query rewrites.

The reference's QueryInterceptor SPI (index-api planning/
QueryInterceptor.scala): per-schema classes loaded from the SFT user-data
key ``geomesa.query.interceptors``, each given a chance to rewrite the
query before planning (e.g. enforcing a default time range, injecting
sampling hints, blocking expensive predicates).
"""

from __future__ import annotations

import importlib
from typing import Protocol, runtime_checkable

__all__ = ["QueryInterceptor", "load_interceptors", "apply_interceptors",
           "GuardedQueryInterceptor"]

USER_DATA_KEY = "geomesa.query.interceptors"


@runtime_checkable
class QueryInterceptor(Protocol):
    def rewrite(self, sft, query):  # pragma: no cover - protocol
        """Return the (possibly modified) query."""
        ...


class GuardedQueryInterceptor:
    """Example guard: reject full-table scans (Filter == INCLUDE) —
    the QueryProperties.BlockFullTableScans behavior
    (index/conf/QueryProperties.scala:37-44) expressed as an interceptor."""

    def rewrite(self, sft, query):
        from ..filters.ast import Include

        if query.filter is Include or type(query.filter).__name__ == "Include":
            raise ValueError(
                f"full-table scan blocked on {sft.name!r} by interceptor")
        return query


def load_interceptors(sft) -> list:
    """Instantiate the interceptor classes named in the SFT's user data
    (comma-separated ``module:Class`` or ``module.Class`` paths).  A
    schema carrying ``geomesa.age.off`` user data auto-attaches the
    age-off interceptor (the reference attaches its age-off iterator at
    table-configuration time the same way)."""
    raw = sft.user_data.get(USER_DATA_KEY, "")
    out = []
    for name in (n.strip() for n in str(raw).split(",") if n.strip()):
        if ":" in name:
            mod, cls = name.split(":", 1)
        else:
            mod, _, cls = name.rpartition(".")
        out.append(getattr(importlib.import_module(mod), cls)())
    from ..age_off import AGE_OFF_KEY, AgeOffInterceptor
    if AGE_OFF_KEY in sft.user_data:
        out.append(AgeOffInterceptor())
    return out


def apply_interceptors(interceptors, sft, query):
    for it in interceptors:
        query = it.rewrite(sft, query)
    return query
