"""Adaptive mid-query replanning: the scan-side half of closing the
cost-based-planning loop (ISSUE 19).

The planner installs a :class:`ReplanScope` (ambient, per task — the
``resilience/deadline.py`` contextvar discipline) around a strategy's
scan carrying the decider's row estimate and the configured divergence
threshold.  The lean scan loops call :func:`check_replan` at their
candidate-count probe points — the one cheap counting dispatch every
lean family runs BEFORE any gather — and when the observed candidate
count exceeds ``threshold × estimate`` the scan aborts by raising
:class:`ReplanSignal`.  The planner catches it, re-enters the
``StrategyDecider`` with the observed actual folded in, and re-scans
under the new strategy.

Contracts:

* **one replan per query** — the scope disarms on its first raise, and
  the planner's second scan runs outside any scope;
* **bit-exact results** — the probe precedes every gather, so an abort
  discards no collected hits, and the re-scan's candidate superset
  passes through the same residual ``evaluate_filter`` re-check as any
  other scan;
* **multihost-safe** — sharded probes feed *global* fetched totals
  (process-invariant), so every process raises (or doesn't) at the
  same agreed point with the same observed count.

Only an *under*-estimate triggers: observed ≫ estimate means the
chosen strategy is scanning far more than costed and an alternative
may be cheaper.  An over-estimate (scan cheaper than predicted) is
free — aborting it would only add latency.
"""

from __future__ import annotations

import contextlib
from contextvars import ContextVar

__all__ = [
    "ReplanSignal", "ReplanScope", "replan_scope", "check_replan",
    "current_replan_scope",
]


class ReplanSignal(Exception):
    """Raised at a scan probe point when observed candidates diverge
    past the scope threshold.  Carries the probe point, the observed
    candidate count, and the estimate it diverged from.  Caught ONLY
    by ``QueryPlanner`` — never by scan code."""

    def __init__(self, point: str, observed: int, estimate: float):
        super().__init__(
            f"replan at {point}: observed {int(observed)} candidates "
            f"vs estimate {estimate:.0f}")
        self.point = point
        self.observed = int(observed)
        self.estimate = float(estimate)


class ReplanScope:
    """One query's replan budget: the estimate to diverge from, the
    trigger ratio, a row floor (tiny scans never replan — the abort
    costs more than finishing), and a one-shot arm."""

    __slots__ = ("estimate", "threshold", "min_rows", "armed")

    def __init__(self, estimate: float, threshold: float,
                 min_rows: int = 0):
        self.estimate = float(estimate)
        self.threshold = float(threshold)
        self.min_rows = int(min_rows)
        self.armed = self.threshold > 0.0


_current_scope: ContextVar[ReplanScope | None] = ContextVar(
    "geomesa_replan_scope", default=None)


def current_replan_scope() -> ReplanScope | None:
    """The ambient scope, or None outside any replan-armed scan."""
    return _current_scope.get()


@contextlib.contextmanager
def replan_scope(estimate: float, threshold: float, min_rows: int = 0):
    """Install a :class:`ReplanScope` for the duration of one scan."""
    scope = ReplanScope(estimate, threshold, min_rows)
    token = _current_scope.set(scope)
    try:
        yield scope
    finally:
        _current_scope.reset(token)


def check_replan(point: str, observed: int) -> None:
    """Probe-point hook: raise :class:`ReplanSignal` when ``observed``
    candidates diverge past the ambient scope's threshold.  Fast no-op
    (one contextvar read) outside a scope — the fused serving plane
    and direct index callers never pay for it."""
    scope = _current_scope.get()
    if scope is None or not scope.armed:
        return
    if observed < scope.min_rows:
        return
    if observed + 1.0 < scope.threshold * (scope.estimate + 1.0):
        return
    scope.armed = False
    raise ReplanSignal(point, observed, scope.estimate)
