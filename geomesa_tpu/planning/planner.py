"""Query planner: strategy → index scan → residual filter → transforms.

The orchestration layer mirroring the reference's QueryPlanner
(geomesa-index-api/.../index/planning/QueryPlanner.scala:41-134): choose a
strategy (StrategyDecider), run the chosen index's scan to get candidate
positions, apply the full filter as a vectorized re-check (the reference's
secondary-filter / FilterTransformIterator role), then projection, sort
and max-features (configureQuery's hint handling, :157-230).

Exactness contract: whatever the index strategy returns is treated as a
*candidate superset*; the final mask is always the full filter evaluated
on candidates, so results are oracle-equal regardless of strategy.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from ..features.batch import FeatureBatch
from ..features.feature_type import FeatureType
from ..filters.ast import And, Filter, IdFilter, Include, Not, Or, _Include
from ..filters.ecql import parse_ecql
from ..filters.evaluate import evaluate_filter
from .adaptive import ReplanSignal
from .explain import Explainer, ExplainNull
from .strategy import FilterStrategy, StrategyDecider

__all__ = ["Query", "QueryPlanner", "QueryResult"]


@dataclass
class Query:
    """A query against one schema (the GeoTools Query analog)."""

    filter: Filter = Include
    properties: list | None = None       # projection; None = all
    sort_by: str | None = None           # attribute name
    sort_desc: bool = False
    max_features: int | None = None
    crs: str | None = None               # output CRS; None = storage (4326)
    hints: dict = field(default_factory=dict)

    @classmethod
    def of(cls, filter_or_ecql="INCLUDE", **kw) -> "Query":
        f = (parse_ecql(filter_or_ecql)
             if isinstance(filter_or_ecql, str) else filter_or_ecql)
        return cls(filter=f, **kw)


@dataclass
class QueryResult:
    #: materialized hit rows — ``None`` when the caller asked for
    #: positions only (``materialize=False``: the Arrow-native result
    #: path encodes columns straight from the store, ISSUE 14)
    batch: FeatureBatch | None
    positions: np.ndarray
    strategy: FilterStrategy
    plan_time_ms: float
    scan_time_ms: float
    #: this process's rows in final result order — equal to
    #: ``positions`` single-host; under multihost ``positions`` are
    #: global gids and this is the local slice
    local_rows: np.ndarray | None = None
    #: set when a ``timeout_ms`` deadline expired mid-scan and the
    #: caller asked for ``partial_results`` — the rows present are
    #: exact hits over what WAS scanned before the deadline (ISSUE 16)
    timed_out: bool = False


class QueryTimeoutError(TimeoutError):
    """Query exceeded ``geomesa.query.timeout`` (the reference's
    ThreadManagement reaper killing runaway scans,
    index/utils/ThreadManagement.scala + GeoMesaFeatureReader.scala:31)."""


class QueryPlanner:
    """Plans and runs queries against a store's in-memory index set."""

    def __init__(self, sft: FeatureType, store):
        self.sft = sft
        self.store = store  # _SchemaStore (datastore.py)

    def run(self, query: Query, explain: Explainer | None = None,
            allowed: np.ndarray | None = None,
            materialize: bool = True) -> QueryResult:
        """Plan and execute.  ``allowed`` is an optional per-feature bool
        mask (row-level security) applied before sort/limit so that
        ``max_features`` fills from authorized rows only.

        ``materialize=False`` skips the result-batch gather entirely
        (positions/local_rows only — no per-row feature ids, no column
        copies): the Arrow streaming path (ISSUE 14) encodes its
        record batches straight from the store's columns instead."""
        explain = explain or ExplainNull()
        store = self.store
        batch = store.batch
        explain.push(lambda: f"Planning query on '{self.sft.name}' "
                             f"({len(batch)} features)")
        explain(lambda: f"Filter: {query.filter!r}")

        from ..config import QueryProperties
        timeout_s = QueryProperties.QUERY_TIMEOUT.to_int()
        deadline = (time.perf_counter() + timeout_s) if timeout_s else None

        from ..resilience import check_cancel

        def check_deadline(stage: str):
            if deadline is not None and time.perf_counter() > deadline:
                raise QueryTimeoutError(
                    f"query on {self.sft.name!r} exceeded "
                    f"{timeout_s}s during {stage}")
            # the per-query ``timeout_ms`` deadline (ISSUE 16) checks at
            # the same phase boundaries the legacy reaper does: raises
            # are per-process BETWEEN collective phases, the precedent
            # this module already set for multihost safety
            check_cancel(f"planner.{stage}")

        from ..obs import span as obs_span
        from ..utils.profiling import profile
        with profile("query.plan") as plan_span, \
                obs_span("query.plan") as psp:
            # multihost: global count + merged stats — every process
            # must cost strategies identically or the collective
            # dispatches would diverge (deadlock)
            stats = store.stats_map()
            n_plan = (stats["count"].count
                      if getattr(store, "multihost", False) else len(batch))
            lean = getattr(store, "lean", False)
            est_fn = getattr(store, "estimator", None)
            decider = StrategyDecider(
                self.sft, stats, n_plan,
                allowed_indices=getattr(store, "query_indices", None),
                attr_z3_tier=not lean,
                servable_attrs=(set(store._lean_attr_names())
                                if lean else None),
                estimator=(est_fn() if callable(est_fn) else None))
            strategy, options = decider.decide_with_options(
                query.filter, explain,
                forced=query.hints.get("QUERY_INDEX"))
            psp.set_attr("strategy", strategy.index)
            # estimate audit (ISSUE 9): the chosen estimate, which
            # estimator tier produced it (ISSUE 19), and every option's
            # cost land on the plan span, so the cost model the decider
            # used is reconstructable from the trace
            psp.set_attr("plan.estimate.rows", round(float(strategy.cost), 1))
            psp.set_attr("plan.estimate.source", strategy.source)
            if psp.recording and options:
                psp.set_attr("plan.options",
                             {o.index: round(float(o.cost), 1)
                              for o in options})
        plan_ms = plan_span.ms
        check_deadline("planning")

        mh = getattr(store, "multihost", False)
        t1 = time.perf_counter()
        replanned = False
        with profile("query.scan"), \
                obs_span("query.scan", strategy=strategy.index) as ssp:
            try:
                with self._replan_scope_for(strategy, query):
                    candidates = self._scan(strategy, query, explain)
            except ReplanSignal as sig:
                # adaptive mid-query replan (ISSUE 19): the scan's probe
                # observed candidates diverging past the threshold —
                # re-decide with the actual folded in, re-scan ONCE
                strategy, candidates = self._replan(
                    sig, strategy, decider, query, explain)
                replanned = True
                ssp.set_attr("strategy", strategy.index)
            ssp.set_attr("candidates",
                         -1 if candidates is None else int(len(candidates)))
        check_deadline("index scan")
        with obs_span("query.post_filter") as fsp:
            if candidates is None:  # full scan (of this process's rows)
                mask = evaluate_filter(query.filter, batch)
                positions = np.flatnonzero(mask)
            else:
                # multihost: candidates are GLOBAL gids — each process
                # residual-filters only ITS gid-decoded rows, next to the
                # data (the server-side filter role; no global batch exists)
                cand = (store.local_rows_of(candidates) if mh
                        else candidates)
                if len(cand):
                    # lean column stores re-check through an id-free
                    # ChunkView: a full take() would mint O(candidates)
                    # feature-id strings just to throw them away — the
                    # cost class ISSUE 14 removes from the serving path.
                    # Id-predicated filters still need real ids.
                    if (hasattr(batch, "take_view")
                            and not _filter_needs_ids(query.filter)):
                        sub = batch.take_view(cand)
                    else:
                        sub = batch.take(cand)
                    mask = evaluate_filter(query.filter, sub)
                    positions = cand[mask]
                else:
                    positions = np.asarray(cand, dtype=np.int64)
            fsp.set_attr("hits", int(len(positions)))
        scan_ms = (time.perf_counter() - t1) * 1000
        check_deadline("filtering")
        explain(lambda: f"Scan: {len(positions)} hits "
                        f"(plan {plan_ms:.1f}ms, scan {scan_ms:.1f}ms)")
        # estimate-vs-actual close-out (ISSUE 9): actual rows scanned
        # (candidate superset; the whole table on a full scan) and
        # matched, plus the mispredict ratio, land on the enclosing
        # query span and feed the plan.estimate.ratio histogram — the
        # baseline the item-4 sketch-driven planner must beat.  Both
        # sides are process-local (no collective), and under multihost
        # the estimate and the candidate gids are both GLOBAL, so the
        # ratio compares like with like.
        actual_scanned = int(n_plan if candidates is None
                             else len(candidates))
        ratio = (float(strategy.cost) + 1.0) / (actual_scanned + 1.0)
        from ..metrics import PLAN_ESTIMATE_RATIO, registry as _metrics
        _metrics.histogram(PLAN_ESTIMATE_RATIO).update(ratio)
        from ..obs import current_span
        root = current_span()
        if root is not None:
            root.set_attr("plan.estimate.rows",
                          round(float(strategy.cost), 1))
            root.set_attr("plan.estimate.source", strategy.source)
            root.set_attr("plan.actual.scanned", actual_scanned)
            root.set_attr("plan.actual.matched", int(len(positions)))
            root.set_attr("plan.estimate.ratio", round(ratio, 4))
            if replanned:
                root.set_attr("plan.replanned", True)
        explain(lambda: f"Estimate audit: predicted {strategy.cost:.0f} "
                        f"rows ({strategy.source}), scanned "
                        f"{actual_scanned}, matched "
                        f"{len(positions)} (ratio {ratio:.2f}x)")

        if allowed is not None and len(positions):
            positions = positions[allowed[positions]]
        if "SAMPLING" in query.hints and len(positions):
            # 1-in-n result thinning, optionally per attribute group —
            # the reference's SAMPLING/SAMPLE_BY query hints
            # (SamplingIterator + FeatureSampler); multihost thins per
            # process (the reference samples per scan thread the same
            # way, utils/FeatureSampler)
            from ..process.sampling import sample_positions
            n_samp = int(query.hints["SAMPLING"])
            by = query.hints.get("SAMPLE_BY")
            keys = batch.column(by)[positions] if by else None
            positions = sample_positions(positions, n_samp, keys)
            explain(lambda: f"Sampled 1-in-{n_samp}"
                            + (f" per {by}" if by else ""))
        if mh:
            positions, local_rows = self._finalize_multihost(
                positions, batch, query, store)
        else:
            positions = self._sort_limit(positions, batch, query)
            local_rows = positions
        if not materialize:
            return QueryResult(None, positions, strategy, plan_ms,
                               scan_ms, local_rows=local_rows)
        properties = query.properties
        if properties is None and "COLUMN_GROUP" in query.hints:
            group = query.hints["COLUMN_GROUP"]
            groups = self.sft.column_groups
            if group not in groups:
                raise ValueError(f"no column group {group!r} on "
                                 f"{self.sft.name!r}")
            properties = groups[group]
        take_cols = None
        if properties is not None:
            # projection pushes INTO the take: only the projected
            # physical columns are gathered/copied for the hit rows —
            # a sum(score) over millions of hits must not materialize
            # the geometry columns first (_project then just rebinds
            # the schema)
            take_cols = set()
            for p in properties:
                if self.sft.attribute(p).is_geometry:
                    take_cols.update((f"{p}_x", f"{p}_y", f"{p}_bbox"))
                else:
                    take_cols.add(p)
        result_batch = batch.take(local_rows, columns=take_cols)
        if properties is not None:
            result_batch = _project(result_batch, properties)
        if query.crs:
            # result-side reprojection (QueryPlanner.scala:74-81)
            from ..geometry.crs import reproject_batch
            result_batch = reproject_batch(result_batch, query.crs)
            explain(lambda: f"Reprojected to {query.crs}")
        explain.pop()
        return QueryResult(result_batch, positions, strategy, plan_ms,
                           scan_ms, local_rows=local_rows)

    # -- adaptive replanning (ISSUE 19) -----------------------------------
    def _replan_scope_for(self, strategy: FilterStrategy, query: Query):
        """A replan scope around one strategy's scan, or a null context
        when replanning can't help: disabled by config, strategy pinned
        by a QUERY_INDEX hint, no probe on the chosen path ('none' /
        'id' / 'full'), or an or-split (its per-branch probe counts
        can't re-cost the split as a whole)."""
        import contextlib
        if (query.hints.get("QUERY_INDEX") is not None
                or strategy.index in ("none", "id", "full", "or-split")):
            return contextlib.nullcontext()
        from ..config import PlanningProperties
        threshold = float(PlanningProperties.REPLAN_THRESHOLD.get())
        if threshold <= 0.0:
            return contextlib.nullcontext()
        from .adaptive import replan_scope
        return replan_scope(float(strategy.cost), threshold,
                            int(PlanningProperties.REPLAN_MIN_ROWS.get()))

    def _replan(self, sig: ReplanSignal, strategy: FilterStrategy,
                decider: StrategyDecider, query: Query,
                explain: Explainer) -> tuple[FilterStrategy, np.ndarray]:
        """One bounded mid-query replan: the aborted scan's observed
        candidate count replaces the mispredicted strategy's cost and
        the decider re-runs; the re-scan executes OUTSIDE any replan
        scope, so a query replans at most once.  Bit-exactness is
        structural — the probe-point abort happened before any gather
        (nothing collected, nothing lost), and the new strategy's
        candidate superset passes the same residual filter as always.
        Multihost-safe: probe totals are fetched GLOBAL values, so
        every process raises at the same agreed point and re-decides
        identically."""
        from ..metrics import PLAN_REPLANNED, registry as _metrics
        from ..obs import span as obs_span
        with obs_span("query.replan", from_strategy=strategy.index,
                      observed=int(sig.observed),
                      estimate=round(float(sig.estimate), 1)) as rsp:
            _metrics.counter(PLAN_REPLANNED).inc()
            explain(lambda: f"Replanning: {strategy.index} observed "
                            f"{sig.observed} candidates at {sig.point} "
                            f"vs estimate {sig.estimate:.0f}")
            try:
                new, _ = decider.decide_with_options(
                    query.filter, explain,
                    observed={strategy.index: float(sig.observed)})
            except RuntimeError:
                # blocked full-table scan surfaced by the re-decide:
                # finish under the original strategy rather than fail a
                # query that was already admitted and running
                new = strategy
            rsp.set_attr("to_strategy", new.index)
            candidates = self._scan(new, query, explain)
        return new, candidates

    # -- strategy execution ----------------------------------------------
    def _scan(self, strategy: FilterStrategy, query: Query,
              explain: Explainer) -> np.ndarray | None:
        store = self.store
        name = strategy.index
        if name == "none":
            return np.empty(0, dtype=np.int64)
        if name == "or-split":
            explain(lambda: f"OR-split across {len(strategy.branches)} "
                            "indexed branches")
            return self._scan_or_split(strategy, query, explain)
        if name == "full":
            explain("Executing full-table scan")
            return None
        explain(lambda: f"Executing {name} index scan")
        if name == "id":
            # id index is host-local; multihost lifts the per-process
            # rows into the global gid space (encode + allgather); the
            # appended tail joins BEFORE the lift (tail rows are local)
            cand = store.id_index().query(strategy.ids)
            tail = store.index_tail("id")
            if tail is not None and len(tail):
                cand = _union([cand, tail])
            return store.to_global_candidates(cand)
        if name.startswith("attr:"):
            attr = name[5:]
            idx = store.attribute_index(attr)
            (a, kind, payload) = strategy.attr_values[0]
            # covering secondary refinement for the tiers; exactness
            # comes from run()'s residual filter as always
            sec_window = None
            z3_ranges = None
            if strategy.intervals and idx.secondary is not None:
                los = [iv[0] for iv in strategy.intervals]
                his = [iv[1] for iv in strategy.intervals]
                sec_window = (None if any(v is None for v in los) else min(los),
                              None if any(v is None for v in his) else max(his))
            if (idx.sec_z is not None
                    and (strategy.geometries or strategy.intervals)):
                z3_ranges = self._attr_z3_ranges(strategy)
            if kind == "equals":
                cand = idx.query_equals(payload, sec_window, z3_ranges)
            elif kind == "in":
                cand = idx.query_in(payload, sec_window, z3_ranges)
            elif kind == "range":
                lo, hi, lo_inc, hi_inc = payload
                cand = idx.query_range(lo, hi, lo_inc, hi_inc)
            elif kind == "prefix":
                cand = idx.query_prefix(payload)
            else:
                raise ValueError(f"unknown attribute query {kind!r}")
            return self._add_tail(cand, name)
        boxes = [g.envelope.as_tuple() for g in strategy.geometries] or [
            (-180.0, -90.0, 180.0, 90.0)
        ]
        if name == "z3":
            idx = store.z3_index()
            # sketch-sized decomposition budget (ISSUE 19): only ever
            # set by the lean estimator, whose index accepts the kwarg
            mr = ({} if strategy.max_ranges is None
                  else {"max_ranges": int(strategy.max_ranges)})
            if len(strategy.intervals) > 1:
                # auto-batch disjoint time windows into ONE device
                # dispatch (the multi-window BatchScanner pattern —
                # VERDICT r1 weak #4; single-window scans are
                # dispatch-latency-bound through a remote tunnel)
                explain(lambda: f"Auto-batched {len(strategy.intervals)} "
                                "time windows into one dispatch")
                parts = idx.query_many(
                    [(boxes, lo, hi) for lo, hi in strategy.intervals],
                    **mr)
                return _union(list(parts))
            parts = [idx.query(boxes, lo, hi, **mr)
                     for lo, hi in strategy.intervals]
            return _union(parts)
        if name == "z2":
            return store.z2_index().query(boxes)
        if name == "xz3":
            idx = store.xz3_index()
            # temporal-only: scan the whole world (a strategy with no
            # geometry used to produce ZERO scan parts and silently
            # empty results — review r5)
            from ..geometry.types import Polygon as _Poly
            geoms_q = strategy.geometries or (
                _Poly([(-180.0, -90.0), (180.0, -90.0),
                       (180.0, 90.0), (-180.0, 90.0)]),)
            parts = []
            for g in geoms_q:
                for lo, hi in strategy.intervals:
                    parts.append(idx.query(g, lo, hi, exact=False))
            return self._add_tail(_union(parts), "xz3")
        if name == "xz2":
            idx = store.xz2_index()
            parts = [idx.query(g, exact=False) for g in strategy.geometries or ()]
            return self._add_tail(_union(parts), "xz2")
        raise ValueError(f"unknown strategy {name!r}")

    def _add_tail(self, cand: np.ndarray, key: str) -> np.ndarray:
        """Union rows appended after a kept index's build into its
        candidate set (write-path incremental maintenance: kept indexes
        serve their covered rows; the tail rides as unconditional
        candidates and the residual filter keeps results exact).
        Multihost: tails are per-process local rows; the presence
        decision is AGREED so every process enters the lift
        collective."""
        store = self.store
        tail = store.index_tail(key) if hasattr(store, "index_tail") \
            else None
        n_tail = 0 if tail is None else len(tail)
        if getattr(store, "multihost", False):
            from ..parallel.multihost import agreed_int
            if agreed_int(n_tail, "max") == 0:
                return cand
            tail = (tail if tail is not None
                    else np.empty(0, dtype=np.int64))
            return _union([cand, store.to_global_candidates(tail)])
        if n_tail == 0:
            return cand
        return _union([cand, tail])

    def _scan_or_split(self, strategy: FilterStrategy, query: Query,
                       explain: Explainer) -> np.ndarray | None:
        """Execute an OR-split, auto-batching its z3/z2 branches into
        single multi-window device dispatches (FilterSplitter's
        disjunction rewrite served the BatchScanner way,
        planning/FilterSplitter.scala:294-307 — VERDICT r1 item 8).
        Branches on other indexes scan individually as before; the
        planner's full-OR residual re-check keeps the union exact."""
        store = self.store
        world = (-180.0, -90.0, 180.0, 90.0)
        z3_windows: list = []
        z2_sets: list = []
        rest: list = []
        for _, st in strategy.branches:
            bx = [g.envelope.as_tuple() for g in st.geometries] or [world]
            if st.index == "z3" and st.intervals:
                z3_windows.extend((bx, lo, hi) for lo, hi in st.intervals)
            elif st.index == "z2":
                z2_sets.append(bx)
            else:
                rest.append(st)
        parts = []
        if len(z3_windows) > 1:
            explain(lambda: f"Auto-batched {len(z3_windows)} z3 windows "
                            "into one dispatch")
            parts.extend(store.z3_index().query_many(z3_windows))
        elif z3_windows:
            bx, lo, hi = z3_windows[0]
            parts.append(store.z3_index().query(bx, lo, hi))
        if len(z2_sets) > 1:
            explain(lambda: f"Auto-batched {len(z2_sets)} z2 box sets "
                            "into one dispatch")
            parts.extend(store.z2_index().query_many(z2_sets))
        elif z2_sets:
            parts.append(store.z2_index().query(z2_sets[0]))
        for st in rest:
            cand = self._scan(st, query, explain)
            if cand is None:
                # a full-scan branch inside a split would silently lose
                # its rows from the union — degrade the whole split to
                # one full scan instead
                return None
            parts.append(cand)
        parts = [p for p in parts if len(p)]
        # candidates are per-branch supersets; run()'s single full-OR
        # re-check makes the final hit set exact
        return _union(parts) if parts else np.empty(0, dtype=np.int64)

    def _attr_z3_ranges(self, strategy: FilterStrategy):
        """Covering (bin, zlo, zhi) plan for the attribute index's z3
        tier; open time bounds clamp to the data's extent (the same
        clamping the primary z3 index applies)."""
        from ..index.z3 import plan_z3_query

        # data extent from the maintained MinMax stat (O(1)); fall back
        # to one column scan only when stats are absent
        mm = self.store.stats_map().get("dtg_minmax")
        if mm is not None and not mm.is_empty:
            data_lo, data_hi = int(mm.min), int(mm.max)
        else:
            dtg = self.store.batch.column(self.sft.dtg_field)
            if len(dtg) == 0:
                return None
            data_lo, data_hi = int(dtg.min()), int(dtg.max())
        lo, hi = data_lo, data_hi
        if strategy.intervals:
            los = [iv[0] for iv in strategy.intervals]
            his = [iv[1] for iv in strategy.intervals]
            if not any(v is None for v in los):
                lo = max(lo, min(los))
            if not any(v is None for v in his):
                hi = min(hi, max(his))
        boxes = ([g.envelope.as_tuple() for g in strategy.geometries]
                 or [(-180.0, -90.0, 180.0, 90.0)])
        plan = plan_z3_query(boxes, lo, hi, self.sft.z3_interval,
                             max_ranges=256)
        if plan.num_ranges == 0:
            return None
        return plan.rbin, plan.rzlo, plan.rzhi

    def _finalize_multihost(self, local: np.ndarray, batch: FeatureBatch,
                            query: Query, store):
        """Assemble the GLOBAL result gid list from per-process survivor
        rows (hits-bounded allgather — the client-merge Reducer role),
        applying sort/limit with global semantics.  Returns
        ``(global_gids, local_rows_in_global_order)``; each process's
        result batch is its own slice of the global order."""
        import jax

        from ..parallel.multihost import allgather_concat, allgather_strings
        from ..parallel.scan import decode_gids

        local = np.asarray(local, dtype=np.int64)
        gids = np.asarray(store.gids_of(local), dtype=np.int64)
        if query.sort_by:
            keys = batch.column(query.sort_by)[local]
            if keys.dtype == object:
                # match _sort_limit's object contract: (is_none, value)
                # ascending — numeric comparables gather as floats (str
                # would order 10 before 9), everything else as strings
                none = np.array([k is None for k in keys], dtype=bool)
                vals = [k for k in keys if k is not None]
                # agreed across processes: numeric only if EVERY
                # process's keys are numeric (divergent dtypes would
                # mismatch the gather collectives)
                import numbers

                from ..parallel.multihost import agreed_int
                numeric = bool(agreed_int(
                    int(all(isinstance(v, numbers.Real) for v in vals)),
                    "min"))
                ints = numeric and bool(agreed_int(
                    int(all(isinstance(v, numbers.Integral)
                            and -(2 ** 62) < int(v) < 2 ** 62
                            for v in vals)),
                    "min"))
                if ints:
                    # exact int64 gather: float64 would collapse values
                    # past 2^53 (e.g. nanosecond epochs), breaking order
                    # parity with _sort_limit's exact comparisons
                    safe = np.array([0 if k is None else int(k)
                                     for k in keys], dtype=np.int64)
                    all_keys = allgather_concat(safe)
                elif numeric:
                    safe = np.array([0.0 if k is None else float(k)
                                     for k in keys])
                    all_keys = allgather_concat(safe)
                else:
                    all_keys = allgather_strings(np.array(
                        ["" if k is None else str(k) for k in keys],
                        dtype=object))
                all_none = allgather_concat(none)
            else:
                all_keys = allgather_concat(keys)
                all_none = np.zeros(len(all_keys), dtype=bool)
            all_gids = allgather_concat(gids)
            # stable (is_none, value) ascending sort, then a FULL
            # reverse for descending — exactly _sort_limit's order[::-1]
            # (which puts Nones first on descending sorts)
            order = np.lexsort((np.arange(len(all_keys)),
                                all_keys, all_none))
            if query.sort_desc:
                order = order[::-1]
            positions = all_gids[order]
        else:
            positions = np.sort(allgather_concat(gids))
        if query.max_features is not None:
            positions = positions[: query.max_features]
        procs, rows = decode_gids(positions)
        return positions, rows[procs == jax.process_index()]

    def _sort_limit(self, positions: np.ndarray, batch: FeatureBatch,
                    query: Query) -> np.ndarray:
        if query.sort_by:
            keys = batch.column(query.sort_by)[positions]
            if keys.dtype == object:
                # object columns may mix None (masked/sparse values) with
                # comparables: sort Nones last, stably
                order = np.asarray(sorted(
                    range(len(keys)),
                    key=lambda i: (keys[i] is None, keys[i]
                                   if keys[i] is not None else 0)),
                    dtype=np.int64)
            else:
                order = np.argsort(keys, kind="stable")
            if query.sort_desc:
                order = order[::-1]
            positions = positions[order]
        if query.max_features is not None:
            positions = positions[: query.max_features]
        return positions


def _filter_needs_ids(f: Filter) -> bool:
    """Does any node of the filter read feature ids?  (IdFilter is the
    one evaluate_filter branch touching ``batch.ids`` — id-free filters
    may re-check over an id-less ChunkView.)"""
    if isinstance(f, IdFilter):
        return True
    if isinstance(f, (And, Or)):
        return any(_filter_needs_ids(p) for p in f.filters)
    if isinstance(f, Not):
        return _filter_needs_ids(f.filter)
    return False


def _union(parts: list[np.ndarray]) -> np.ndarray:
    parts = [p for p in parts if len(p)]
    if not parts:
        return np.empty(0, dtype=np.int64)
    return np.unique(np.concatenate(parts))


def _project(batch: FeatureBatch, properties: list) -> FeatureBatch:
    """Column projection (the reference's transform schemas,
    QueryPlanner.setQueryTransforms)."""
    keep: dict = {}
    for p in properties:
        attr = batch.sft.attribute(p)
        if attr.is_geometry:
            for suffix in ("_x", "_y", "_bbox"):
                if f"{p}{suffix}" in batch.columns:
                    keep[f"{p}{suffix}"] = batch.columns[f"{p}{suffix}"]
        else:
            keep[p] = batch.columns[p]
    sub_attrs = tuple(a for a in batch.sft.attributes if a.name in properties)
    sub_sft = FeatureType(batch.sft.name, sub_attrs,
                          batch.sft.default_geom if batch.sft.default_geom in properties else None,
                          batch.sft.user_data)
    return FeatureBatch(sub_sft, keep, batch.ids,
                        batch.geoms if sub_sft.default_geom else None)
