"""Explain tracing: hierarchical, lazily-evaluated query-plan traces.

Mirrors the reference's Explainer (geomesa-index-api/.../index/utils/
Explainer.scala:18-42): ``push``/``pop`` indentation levels, lazy message
evaluation (callables are only invoked when the sink is active), and
pluggable sinks — string buffer, logging, stdout, or the null sink.
"""

from __future__ import annotations

import logging
from typing import Callable

__all__ = ["Explainer", "ExplainString", "ExplainPrintln", "ExplainLogging",
           "ExplainNull"]


class Explainer:
    """Base explainer; subclasses implement ``output``."""

    active: bool = True

    def __init__(self):
        self._level = 0

    def output(self, text: str) -> None:
        raise NotImplementedError

    def __call__(self, msg, *lazy_parts) -> "Explainer":
        if self.active:
            text = msg() if callable(msg) else str(msg)
            for part in lazy_parts:
                text += part() if callable(part) else str(part)
            self.output("  " * self._level + text)
        return self

    def push(self, msg=None) -> "Explainer":
        if msg is not None:
            self(msg)
        self._level += 1
        return self

    def pop(self) -> "Explainer":
        self._level = max(0, self._level - 1)
        return self


class ExplainString(Explainer):
    """Accumulate the trace into a string (the `explain` CLI sink)."""

    def __init__(self):
        super().__init__()
        self._lines: list[str] = []

    def output(self, text: str) -> None:
        self._lines.append(text)

    def __str__(self) -> str:
        return "\n".join(self._lines)


class ExplainPrintln(Explainer):
    def output(self, text: str) -> None:
        print(text)


class ExplainLogging(Explainer):
    def __init__(self, logger: logging.Logger | None = None,
                 level: int = logging.DEBUG):
        super().__init__()
        self._logger = logger or logging.getLogger("geomesa_tpu.plan")
        self._log_level = level

    def output(self, text: str) -> None:
        self._logger.log(self._log_level, text)


class ExplainNull(Explainer):
    active = False

    def output(self, text: str) -> None:
        pass
