"""Query planning: strategy selection, plan assembly, explain tracing.

The analog of the reference's planning stack
(geomesa-index-api/.../index/planning/): QueryPlanner, FilterSplitter,
StrategyDecider, Explainer, LocalQueryRunner.
"""

from .adaptive import ReplanSignal, check_replan, replan_scope
from .estimator import CardinalityEstimator
from .explain import ExplainLogging, ExplainNull, ExplainString, Explainer
from .planner import QueryPlanner, QueryResult
from .strategy import FilterStrategy, StrategyDecider

__all__ = [
    "Explainer", "ExplainString", "ExplainLogging", "ExplainNull",
    "QueryPlanner", "QueryResult", "FilterStrategy", "StrategyDecider",
    "CardinalityEstimator", "ReplanSignal", "check_replan",
    "replan_scope",
]
