"""Stat-sketch push-down partials for the lean tiered indexes.

The reference answers ``Stat`` specs server-side (StatsScan,
iterators/StatsScan.scala:125): each tablet folds its rows into
mergeable sketches and ships only the sketch, never the rows.  On the
lean tiered store the same split falls out of the KEY layout instead of
a row scan (ISSUE 3):

* the attribute index's key IS the order-preserving int64 lexicode of
  the value (index/attr_lean), so for numeric/date attributes a run's
  sorted ``(key, sec)`` columns decode straight back to exact values
  and timestamps — MinMax / Histogram / DescriptiveStats / Frequency /
  TopK / Enumeration (and Count) fold per run with NO row access;
* the z3 index's key decodes to coarse (bin, cell) pairs — exactly
  Z3Histogram's domain (utils/stats/Z3Histogram.scala:34).

This module owns the shared pieces: the per-run mergeable partial
(:class:`RunSketch`), the fold configuration / cache-spec key
(:class:`SketchFold`), the traced fold body both the single-chip jit
and the shard_map program inline (:func:`device_fold_body`), the
stacked host-tier fold with per-run attribution
(:func:`fold_attr_runs`), and the spec classifier
(:func:`plan_pushdown`) ``stats_process`` gates on.

**Exactness** (docs/stats_pushdown.md): int/long/date keys are the
value; float/double keys are the invertible IEEE-754 bit transform —
both decode exactly.  String keys are 8-byte PREFIX codes (ties alias)
so every string-valued stat falls back to materialization.  The only
lossy corner is the key clamp at ``int64 max - 1`` (index/attr_lean
``encode_attr_values``), which aliases the two topmost encodable
values — and NaN floats, which the lexicode sorts last while a numpy
oracle would propagate.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .stat import (
    CountStat, DescriptiveStats, EnumerationStat, Frequency, Histogram,
    MinMax, SeqStat, TopK, Z3HistogramStat, _hash_col,
)

__all__ = ["SketchFold", "RunSketch", "PushPlan", "plan_pushdown",
           "decode_attr_keys", "decode_attr_key", "device_fold_body",
           "fold_attr_runs", "fill_stats_from_partial",
           "EXACT_DECODE_TYPES"]

_I64_MIN = np.int64(np.iinfo(np.int64).min)
_I64_MAX = np.int64(np.iinfo(np.int64).max)
#: the attr index's sentinel padding key (index/attr_lean)
_SENTINEL_KEY = _I64_MAX

#: attribute types whose int64 lexicode decodes EXACTLY back to the
#: value (strings are prefix codes — never pushable)
EXACT_DECODE_TYPES = frozenset(
    {"int", "integer", "long", "date", "float", "double"})
_FLOAT_TYPES = frozenset({"float", "double"})


def decode_attr_keys(keys: np.ndarray, attr_type: str) -> np.ndarray:
    """Inverse of :func:`index.attr_lean.encode_attr_values` for the
    exactly-decodable types (int64 for ints/dates, float64 for
    floats)."""
    k = np.asarray(keys, np.int64)
    if attr_type.lower() in _FLOAT_TYPES:
        bits = np.where(k < 0, (np.int64(-1) - k) ^ _I64_MIN, k)
        return bits.astype(np.int64).view(np.float64)
    return k


def decode_attr_key(key, attr_type: str):
    """Scalar twin of :func:`decode_attr_keys` (python int / float)."""
    v = decode_attr_keys(np.array([key], np.int64), attr_type)[0]
    return float(v) if attr_type.lower() in _FLOAT_TYPES else int(v)


@dataclass(frozen=True)
class SketchFold:
    """Configuration of one per-run sketch fold over an attribute
    index — ALSO the partial-cache spec key, so two stats requests
    needing the same fold over the same sec window share cached
    sealed-run partials."""

    slo: int = int(_I64_MIN)    # inclusive sec (dtg-ms) window
    shi: int = int(_I64_MAX)
    bins: int = 0               # histogram bins (0 = no histogram)
    hlo: float = 0.0
    hhi: float = 1.0
    depth: int = 0              # count-min depth (0 = no sketch)
    width: int = 0
    want_values: bool = False   # exact value→count fold (TopK/Enum)


@dataclass
class RunSketch:
    """One run's mergeable stat partial: moments + key-space min/max
    (decoded lazily — order-preserving keys make ``min(keys)`` equal
    ``encode(min(values))``), an optional fixed-bin histogram, an
    optional count-min table, and an optional exact value→count map.
    A monoid, like every sketch in stats/stat.py."""

    count: int = 0
    kmin: int | None = None     # encoded-key min over matched rows
    kmax: int | None = None
    vsum: float = 0.0
    vsumsq: float = 0.0
    hist: np.ndarray | None = None
    cms: np.ndarray | None = None
    values: dict | None = None

    def merge(self, other: "RunSketch") -> "RunSketch":
        out = RunSketch(self.count + other.count, self.kmin, self.kmax,
                        self.vsum + other.vsum,
                        self.vsumsq + other.vsumsq)
        if other.kmin is not None:
            out.kmin = (other.kmin if out.kmin is None
                        else min(out.kmin, other.kmin))
            out.kmax = (other.kmax if out.kmax is None
                        else max(out.kmax, other.kmax))
        if self.hist is not None or other.hist is not None:
            a, b = self.hist, other.hist
            out.hist = (np.array(a if b is None else b
                                 if a is None else a + b, np.int64))
        if self.cms is not None or other.cms is not None:
            a, b = self.cms, other.cms
            out.cms = (np.array(a if b is None else b
                                if a is None else a + b, np.int64))
        if self.values is not None or other.values is not None:
            out.values = dict(self.values or {})
            for v, n in (other.values or {}).items():
                out.values[v] = out.values.get(v, 0) + n
        return out

    def __add__(self, other):
        return self.merge(other)

    @property
    def nbytes(self) -> int:
        """Host bytes this partial retains (the cache byte ceiling)."""
        n = 64
        if self.hist is not None:
            n += self.hist.nbytes
        if self.cms is not None:
            n += self.cms.nbytes
        if self.values is not None:
            n += 48 * len(self.values)
        return n

    def to_json(self) -> dict:
        return {"count": self.count, "kmin": self.kmin,
                "kmax": self.kmax, "vsum": self.vsum,
                "vsumsq": self.vsumsq,
                "hist": None if self.hist is None else self.hist.tolist(),
                "cms": None if self.cms is None else self.cms.tolist(),
                "values": (None if self.values is None
                           else [[v, n] for v, n in self.values.items()])}

    @classmethod
    def from_json(cls, obj: dict) -> "RunSketch":
        return cls(
            int(obj["count"]),
            None if obj["kmin"] is None else int(obj["kmin"]),
            None if obj["kmax"] is None else int(obj["kmax"]),
            float(obj["vsum"]), float(obj["vsumsq"]),
            None if obj["hist"] is None
            else np.asarray(obj["hist"], np.int64),
            None if obj["cms"] is None
            else np.asarray(obj["cms"], np.int64),
            None if obj["values"] is None
            else {v: int(n) for v, n in obj["values"]})


# ---------------------------------------------------------------------------
# device fold body (traced inline by the single-chip jit AND the
# sharded shard_map program — one definition, no drift)
# ---------------------------------------------------------------------------

def _decode_f64_j(k):
    """jnp twin of :func:`decode_attr_keys` for float lexicodes."""
    import jax
    import jax.numpy as jnp
    bits = jnp.where(k < 0, (jnp.int64(-1) - k) ^ jnp.int64(_I64_MIN), k)
    return jax.lax.bitcast_convert_type(bits, jnp.float64)


def _splitmix_j(h):
    import jax.numpy as jnp
    h = (h ^ (h >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    h = (h ^ (h >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return h ^ (h >> jnp.uint64(31))


def device_fold_body(k, s, slo, shi, hlo, hhi, *, bins: int, depth: int,
                     width: int, is_float: bool):
    """One run's sketch fold over its device-resident (key, sec)
    columns: masked moments (int64 key min/max — exact at any
    magnitude), a bincount histogram matching ``Histogram.observe``'s
    outlier-clamped binning, and count-min rows hashed bit-identically
    to the host sketch (stats/stat._hash_col's numeric path — the
    parallel.stats._frequency_program discipline).  Returns
    ``(count, kmin, kmax, vsum, vsumsq, hist, cms)``; hist/cms are
    zero-size when not requested so shapes stay static."""
    import jax.numpy as jnp
    mask = (k != jnp.int64(_SENTINEL_KEY)) & (s >= slo) & (s <= shi)
    vf = _decode_f64_j(k) if is_float else k.astype(jnp.float64)
    count = jnp.sum(mask).astype(jnp.int64)
    kmin = jnp.min(jnp.where(mask, k, jnp.int64(_I64_MAX)))
    kmax = jnp.max(jnp.where(mask, k, jnp.int64(_I64_MIN)))
    vsum = jnp.sum(jnp.where(mask, vf, 0.0))
    vsumsq = jnp.sum(jnp.where(mask, vf * vf, 0.0))
    one = jnp.where(mask, 1, 0).astype(jnp.int64)
    if bins:
        norm = bins / (hhi - hlo)
        b = jnp.clip(((vf - hlo) * norm).astype(jnp.int32), 0, bins - 1)
        # NaN values drop from the histogram ONLY (matching the
        # materializing oracle: np.histogram ignores NaN and the
        # outlier clamp's comparisons are False for it) — Count and
        # the other folds still see the row
        one_h = jnp.where(jnp.isnan(vf), jnp.int64(0), one) \
            if is_float else one
        hist = jnp.zeros((bins,), jnp.int64).at[b].add(one_h)
    else:
        hist = jnp.zeros((0,), jnp.int64)
    if depth:
        if is_float:
            # canonicalize non-finite / out-of-range floats to numpy's
            # INT64_MIN truncation before the int64 cast (_hash_col)
            flo = jnp.float64(np.iinfo(np.int64).min)
            ok = (jnp.isfinite(vf) & (vf >= flo)
                  & (vf < jnp.float64(2.0 ** 63)))
            v64 = jnp.where(ok, vf, flo).astype(jnp.int64)
        else:
            v64 = k            # exact: never round-trip ints through f64
        rows = []
        for d in range(depth):
            seed = jnp.uint64((d + 1) * 0x9E3779B97F4A7C15
                              & 0xFFFFFFFFFFFFFFFF)
            h = _splitmix_j(v64.astype(jnp.uint64) ^ seed)
            hb = (h % jnp.uint64(width)).astype(jnp.int32)
            rows.append(jnp.zeros((width,), jnp.int64).at[hb].add(one))
        cms = jnp.stack(rows)
    else:
        cms = jnp.zeros((0, 0), jnp.int64)
    return count, kmin, kmax, vsum, vsumsq, hist, cms


# ---------------------------------------------------------------------------
# host-tier fold: ONE stacked pass with per-run attribution
# ---------------------------------------------------------------------------

def fold_attr_runs(runs: list, fold: SketchFold,
                   attr_type: str) -> list[RunSketch]:
    """Fold host-resident ``(key, sec)`` runs into one
    :class:`RunSketch` each in a SINGLE stacked vectorized pass: every
    run's rows concatenate with an owning-run id, the sec mask and
    value decode run once, and per-run partials come out of
    id-segmented bincounts / ``minimum.at`` folds — flat overhead in
    run count (the HostStack discipline, round-4 VERDICT #9)."""
    n_runs = len(runs)
    parts = [RunSketch(
        hist=np.zeros(fold.bins, np.int64) if fold.bins else None,
        cms=(np.zeros((fold.depth, fold.width), np.int64)
             if fold.depth else None),
        values={} if fold.want_values else None)
        for _ in range(n_runs)]
    if not n_runs:
        return parts
    ks = np.concatenate([np.asarray(k, np.int64) for k, _ in runs])
    ss = np.concatenate([np.asarray(s, np.int64) for _, s in runs])
    rid = np.repeat(np.arange(n_runs),
                    [len(k) for k, _ in runs]).astype(np.int64)
    mask = ((ks != _SENTINEL_KEY) & (ss >= np.int64(fold.slo))
            & (ss <= np.int64(fold.shi)))
    km, rm = ks[mask], rid[mask]
    counts = np.bincount(rm, minlength=n_runs)
    kmin = np.full(n_runs, _I64_MAX)
    kmax = np.full(n_runs, _I64_MIN)
    np.minimum.at(kmin, rm, km)
    np.maximum.at(kmax, rm, km)
    is_float = attr_type.lower() in _FLOAT_TYPES
    vals = decode_attr_keys(km, attr_type)
    vf = vals.astype(np.float64)
    vsum = np.bincount(rm, weights=vf, minlength=n_runs)
    vsumsq = np.bincount(rm, weights=vf * vf, minlength=n_runs)
    for i, p in enumerate(parts):
        p.count = int(counts[i])
        if p.count:
            p.kmin, p.kmax = int(kmin[i]), int(kmax[i])
        p.vsum, p.vsumsq = float(vsum[i]), float(vsumsq[i])
    if fold.bins:
        norm = fold.bins / (fold.hhi - fold.hlo)
        ok = ~np.isnan(vf) if is_float else slice(None)
        with np.errstate(invalid="ignore"):
            b = np.clip(((vf[ok] - fold.hlo) * norm).astype(np.int64),
                        0, fold.bins - 1)
        flat = np.bincount(rm[ok] * fold.bins + b,
                           minlength=n_runs * fold.bins)
        for i, p in enumerate(parts):
            p.hist = flat[i * fold.bins:(i + 1) * fold.bins] \
                .astype(np.int64)
    if fold.depth:
        col = vf if is_float else km
        for d in range(fold.depth):
            h = (_hash_col(col, d + 1)
                 % np.uint64(fold.width)).astype(np.int64)
            flat = np.bincount(rm * fold.width + h,
                               minlength=n_runs * fold.width)
            for i, p in enumerate(parts):
                p.cms[d] = flat[i * fold.width:(i + 1) * fold.width]
    if fold.want_values and len(km):
        order = np.lexsort((vals, rm))
        rs, vs = rm[order], vals[order]
        edge = np.r_[True, (rs[1:] != rs[:-1]) | (vs[1:] != vs[:-1])]
        starts = np.flatnonzero(edge)
        lens = np.diff(np.r_[starts, len(vs)])
        uv = vs[starts].tolist()
        ur = rs[starts]
        for v, r, n in zip(uv, ur, lens.tolist()):
            parts[int(r)].values[v] = parts[int(r)].values.get(v, 0) + n
    return parts


# ---------------------------------------------------------------------------
# sketch queries (planning/estimator.py's selectivity probes)
# ---------------------------------------------------------------------------

def sketch_equals_count(sk: RunSketch, fold: SketchFold, value,
                        attr_type: str) -> int | None:
    """Estimated rows with ``attr == value`` from a (merged) sketch:
    the exact value map when the fold carried one, else the count-min
    table's min-over-depth probe — hashed exactly as the fold hashed
    (``_hash_col`` over decoded floats for float types, encoded int64
    keys otherwise), so the probe hits the same buckets the device and
    host folds filled.  None when the sketch can't answer."""
    if sk.count == 0:
        return 0
    is_float = attr_type.lower() in _FLOAT_TYPES
    if sk.cms is None or not fold.depth or not fold.width:
        return None
    from ..index.attr_lean import encode_attr_value
    try:
        if is_float:
            col = np.array([float(value)], np.float64)
        else:
            col = np.array([int(encode_attr_value(value, attr_type))],
                           np.int64)
    except (TypeError, ValueError, OverflowError):
        return None
    est = None
    for d in range(fold.depth):
        h = int(_hash_col(col, d + 1)[0] % np.uint64(fold.width))
        row = int(sk.cms[d, h])
        est = row if est is None else min(est, row)
    return est


def sketch_range_count(sk: RunSketch, fold: SketchFold, lo,
                       hi) -> int | None:
    """Estimated rows with ``lo <= attr <= hi`` (None bound = open)
    from a (merged) sketch's fixed-bin histogram, pro-rating the two
    partial edge bins.  None when the fold carried no histogram."""
    if sk.count == 0:
        return 0
    if sk.hist is None or not fold.bins:
        return None
    width = (fold.hhi - fold.hlo) / fold.bins
    if not width > 0:
        return None
    try:
        b_lo = (-np.inf if lo is None
                else (float(lo) - fold.hlo) / width)
        b_hi = (np.inf if hi is None
                else (float(hi) - fold.hlo) / width)
    except (TypeError, ValueError):
        return None
    if b_hi < b_lo:
        return 0
    # a bound past the histogram extent covers the whole edge bin —
    # matching fold time, where outliers clamp into the edge bins
    i0 = np.arange(fold.bins, dtype=np.float64)
    cover = np.clip(np.minimum(b_hi, i0 + 1.0) - np.maximum(b_lo, i0),
                    0.0, 1.0)
    return int(round(float((cover * sk.hist).sum())))


# ---------------------------------------------------------------------------
# spec classification (the stats_process gate)
# ---------------------------------------------------------------------------

@dataclass
class PushPlan:
    """One executable push-down: per-attribute folds (with the stats
    they serve), whole-extent Z3Histograms, the Count stats, and which
    source supplies the count ('attr:<name>' rides a fold; 'rows' is
    the agreed live-row total for whole-extent windows)."""

    attr_groups: dict = field(default_factory=dict)
    z3hists: list = field(default_factory=list)
    counts: list = field(default_factory=list)
    count_source: str = "rows"


def plan_pushdown(stats: list, attr_types: dict, lean_kind: str,
                  geom_field: str, dtg_field: str | None,
                  slo: int, shi: int, t_open: bool,
                  z3_period=None) -> PushPlan | None:
    """Classify a parsed spec list into an executable push-down plan,
    or ``None`` when ANY sub-stat needs row materialization.

    ``attr_types`` maps lean-INDEXED attribute names to their schema
    types; only exactly-decodable types push (module doc).  ``t_open``
    says the window covers the whole time extent — required by
    Z3Histogram (cell-granular time) and by the row-count source; attr
    folds filter ``sec`` exactly for ANY window."""
    groups: dict[str, dict] = {}
    plan = PushPlan()

    def _grp(attr):
        return groups.setdefault(attr, {
            "hist": None, "freq": None, "want_values": False,
            "stats": []})

    for s in stats:
        if isinstance(s, CountStat):
            plan.counts.append(s)
            continue
        attr = getattr(s, "attr", None)
        if isinstance(s, Z3HistogramStat):
            from ..curve.binnedtime import TimePeriod
            if (lean_kind == "z3" and t_open
                    and s.geom == geom_field and s.dtg == dtg_field
                    and z3_period is not None
                    and z3_period == TimePeriod.parse(s.period)):
                plan.z3hists.append(s)
                continue
            return None
        if attr not in attr_types \
                or attr_types[attr].lower() not in EXACT_DECODE_TYPES:
            return None
        g = _grp(attr)
        if isinstance(s, (MinMax, DescriptiveStats)):
            pass
        elif isinstance(s, Histogram):
            cfg = (s.bins, s.lo, s.hi)
            if g["hist"] is not None and g["hist"] != cfg:
                return None   # two binnings would need two folds
            g["hist"] = cfg
        elif isinstance(s, Frequency):
            cfg = (s.depth, s.width)
            if g["freq"] is not None and g["freq"] != cfg:
                return None
            g["freq"] = cfg
        elif isinstance(s, (TopK, EnumerationStat)):
            g["want_values"] = True
        else:
            return None       # GroupBy / string stats / unknown kinds
        g["stats"].append(s)

    if plan.counts and not groups:
        if not t_open:
            # a selective time window needs the exact sec filter of an
            # attr fold — ride any indexed numeric attribute
            ride = next((a for a, t in attr_types.items()
                         if t.lower() in EXACT_DECODE_TYPES), None)
            if ride is None:
                return None
            _grp(ride)
    if not groups and not plan.z3hists and not plan.counts:
        return None
    for attr, g in groups.items():
        hist = g["hist"] or (0, 0.0, 1.0)
        freq = g["freq"] or (0, 0)
        plan.attr_groups[attr] = (SketchFold(
            slo=int(slo), shi=int(shi),
            bins=int(hist[0]), hlo=float(hist[1]), hhi=float(hist[2]),
            depth=int(freq[0]), width=int(freq[1]),
            want_values=bool(g["want_values"])), g["stats"])
    if plan.attr_groups:
        plan.count_source = f"attr:{next(iter(plan.attr_groups))}"
    return plan


def fill_stats_from_partial(stats: list, part: RunSketch,
                            attr_type: str) -> None:
    """Populate the user-facing stats an attr fold serves from its
    merged :class:`RunSketch` (the client-side Reducer step)."""
    is_float = attr_type.lower() in _FLOAT_TYPES
    vmin = (None if part.kmin is None
            else decode_attr_key(part.kmin, attr_type))
    vmax = (None if part.kmax is None
            else decode_attr_key(part.kmax, attr_type))
    for s in stats:
        if isinstance(s, MinMax):
            s.min, s.max = vmin, vmax
        elif isinstance(s, DescriptiveStats):
            s.n = part.count
            if part.count:
                s.mean = part.vsum / part.count
                s.m2 = max(part.vsumsq - part.count * s.mean * s.mean,
                           0.0)
                s.min = float(vmin)
                s.max = float(vmax)
        elif isinstance(s, Histogram):
            if part.hist is not None:
                s.counts = np.asarray(part.hist, np.int64)
        elif isinstance(s, Frequency):
            if part.cms is not None:
                s.table = np.asarray(part.cms, np.int64)
        elif isinstance(s, EnumerationStat):
            s.counts = dict(part.values or {})
        elif isinstance(s, TopK):
            # the fold is an EXACT value→count map, so feeding it
            # through observe_counts yields a top-k at least as tight
            # as the space-saving sketch's bounded-error contract
            vals = part.values or {}
            if vals:
                uv = np.array(list(vals.keys()),
                              dtype=np.float64 if is_float else np.int64)
                s.observe_counts(uv, np.array(list(vals.values()),
                                              np.int64))


def flatten_stats(stat) -> list:
    """A spec's sub-stats as a flat list (SeqStat or single)."""
    return list(stat.stats) if isinstance(stat, SeqStat) else [stat]
