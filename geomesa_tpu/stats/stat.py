"""Mergeable summary statistics over feature columns.

Reference surface: geomesa-utils/.../stats/ — ``Stat`` trait (observe,
``+``/``+=`` merge, isEquivalent, toJson at Stat.scala:31-90), the sketch
implementations, and the ``StatParser`` DSL.  The vendored clearspring
sketches (CountMinSketch / StreamSummary) are re-expressed directly:
Frequency is a numpy count-min table, TopK a space-saving summary.

Every stat is a monoid: ``observe(column)`` folds a batch in, ``a + b``
merges two partials (shard-local → global), ``to_json``/``stat_from_json``
round-trips for the metadata catalog.
"""

from __future__ import annotations

import json
import math
import re
import zlib
from dataclasses import dataclass, field

import numpy as np

from ..curve.binnedtime import TimePeriod, to_binned_time
from ..curve.sfc import z3_sfc

__all__ = [
    "Stat", "CountStat", "MinMax", "Histogram", "Z3HistogramStat",
    "Frequency", "TopK", "EnumerationStat", "GroupBy", "DescriptiveStats",
    "SeqStat", "parse_stat", "stat_from_json",
]


class Stat:
    """Base: a mergeable, serializable summary over one or more columns."""

    kind: str = "stat"

    def observe(self, batch) -> None:
        """Fold a FeatureBatch (or dict of columns) into this stat."""
        raise NotImplementedError

    def unobserve(self, batch) -> None:
        """Remove a batch (only supported by invertible stats)."""
        raise NotImplementedError(f"{type(self).__name__} is not invertible")

    def merge(self, other: "Stat") -> "Stat":
        raise NotImplementedError

    def __add__(self, other: "Stat") -> "Stat":
        return self.merge(other)

    @property
    def is_empty(self) -> bool:
        raise NotImplementedError

    def to_json(self) -> dict:
        raise NotImplementedError

    #: dataclass fields that configure a stat (vs accumulated state)
    _CONFIG_FIELDS = frozenset({
        "attr", "geom", "dtg", "period", "bits", "bins", "lo", "hi", "k",
        "spec", "width", "depth"})

    def fresh_copy(self) -> "Stat":
        """A new, empty stat with the same configuration — used to
        recompute sketches over row subsets (e.g. visibility-filtered)."""
        import dataclasses
        kwargs = {f.name: getattr(self, f.name)
                  for f in dataclasses.fields(self)
                  if f.name in self._CONFIG_FIELDS}
        return type(self)(**kwargs)


def _col(batch, name):
    if hasattr(batch, "column"):
        return batch.column(name)
    return np.asarray(batch[name])


@dataclass
class CountStat(Stat):
    kind = "count"
    count: int = 0

    def observe(self, batch):
        self.count += len(batch)

    def unobserve(self, batch):
        self.count -= len(batch)

    def merge(self, other):
        return CountStat(self.count + other.count)

    @property
    def is_empty(self):
        return self.count == 0

    def to_json(self):
        return {"kind": self.kind, "count": self.count}


@dataclass
class MinMax(Stat):
    kind = "minmax"
    attr: str = ""
    min: object = None
    max: object = None

    def observe(self, batch):
        col = _col(batch, self.attr)
        if len(col) == 0:
            return
        lo, hi = col.min(), col.max()
        self.min = lo if self.min is None else min(self.min, lo)
        self.max = hi if self.max is None else max(self.max, hi)

    def merge(self, other):
        out = MinMax(self.attr, self.min, self.max)
        if other.min is not None:
            out.min = other.min if out.min is None else min(out.min, other.min)
            out.max = other.max if out.max is None else max(out.max, other.max)
        return out

    @property
    def is_empty(self):
        return self.min is None

    @property
    def bounds(self):
        return (self.min, self.max)

    def to_json(self):
        as_py = lambda v: v.item() if hasattr(v, "item") else v
        return {"kind": self.kind, "attr": self.attr,
                "min": as_py(self.min), "max": as_py(self.max)}


@dataclass
class BBoxStat(Stat):
    """Data envelope of a geometry attribute — the planner's spatial
    selectivity DENOMINATOR: a query box is fractioned against the
    data's extent, not the whole world (reference: MinMax[Geometry]
    feeding StatsBasedEstimator's spatial estimates)."""

    kind = "bbox"
    attr: str = ""
    xmin: float | None = None
    ymin: float | None = None
    xmax: float | None = None
    ymax: float | None = None

    def observe(self, batch):
        try:
            x = _col(batch, f"{self.attr}_x")
            y = _col(batch, f"{self.attr}_y")
        except (KeyError, AttributeError):
            try:   # non-point schemas: per-row envelopes (n, 4)
                bb = np.asarray(_col(batch, f"{self.attr}_bbox"))
                if bb.ndim != 2 or not len(bb):
                    return
                self._fold(bb[:, 0].min(), bb[:, 1].min(),
                           bb[:, 2].max(), bb[:, 3].max())
                return
            except (KeyError, AttributeError):
                return
        if len(x) == 0:
            return
        self._fold(x.min(), y.min(), x.max(), y.max())

    def _fold(self, x0, y0, x1, y1):
        if self.xmin is None:
            self.xmin, self.ymin = float(x0), float(y0)
            self.xmax, self.ymax = float(x1), float(y1)
        else:
            self.xmin = min(self.xmin, float(x0))
            self.ymin = min(self.ymin, float(y0))
            self.xmax = max(self.xmax, float(x1))
            self.ymax = max(self.ymax, float(y1))

    def merge(self, other):
        out = BBoxStat(self.attr, self.xmin, self.ymin,
                       self.xmax, self.ymax)
        if other.xmin is not None:
            out._fold(other.xmin, other.ymin, other.xmax, other.ymax)
        return out

    @property
    def is_empty(self):
        return self.xmin is None

    @property
    def bounds(self):
        return (self.xmin, self.ymin, self.xmax, self.ymax)

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr,
                "xmin": self.xmin, "ymin": self.ymin,
                "xmax": self.xmax, "ymax": self.ymax}


@dataclass
class Histogram(Stat):
    """Fixed-bin numeric histogram (the planner's selectivity source —
    reference: utils/stats/Histogram with binned Bounds)."""

    kind = "histogram"
    attr: str = ""
    bins: int = 0
    lo: float = 0.0
    hi: float = 1.0
    counts: np.ndarray | None = None

    def __post_init__(self):
        if self.counts is None:
            self.counts = np.zeros(self.bins, dtype=np.int64)

    def observe(self, batch):
        col = np.asarray(_col(batch, self.attr), dtype=np.float64)
        c, _ = np.histogram(col, bins=self.bins, range=(self.lo, self.hi))
        # clamp outliers into edge bins, as the reference does
        below = np.count_nonzero(col < self.lo)
        above = np.count_nonzero(col > self.hi)
        self.counts += c
        if self.bins:
            self.counts[0] += below
            self.counts[-1] += above

    def merge(self, other):
        if (self.bins, self.lo, self.hi) != (other.bins, other.lo, other.hi):
            raise ValueError("cannot merge histograms with different binning")
        return Histogram(self.attr, self.bins, self.lo, self.hi,
                         self.counts + other.counts)

    @property
    def is_empty(self):
        return int(self.counts.sum()) == 0

    @property
    def total(self) -> int:
        return int(self.counts.sum())

    def estimate_range(self, lo: float, hi: float) -> int:
        """Estimated count in [lo, hi] assuming uniform within bins."""
        if self.total == 0 or hi < self.lo or lo > self.hi:
            return 0
        width = (self.hi - self.lo) / self.bins
        est = 0.0
        for b in range(self.bins):
            b_lo = self.lo + b * width
            b_hi = b_lo + width
            overlap = max(0.0, min(hi, b_hi) - max(lo, b_lo))
            if overlap > 0 and width > 0:
                est += self.counts[b] * (overlap / width)
        return int(round(est))

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr, "bins": self.bins,
                "lo": self.lo, "hi": self.hi, "counts": self.counts.tolist()}


@dataclass
class Z3HistogramStat(Stat):
    """Histogram over coarse Z3 cells — spatio-temporal selectivity
    (reference: utils/stats/Z3Histogram.scala:34)."""

    kind = "z3histogram"
    geom: str = "geom"
    dtg: str = "dtg"
    period: str = "week"
    bits: int = 10                     # top bits of z kept
    counts: dict = field(default_factory=dict)  # (bin, cell) -> count

    def observe(self, batch):
        x, y = batch.geom_xy(self.geom)
        t = _col(batch, self.dtg)
        period = TimePeriod.parse(self.period)
        bins, offs = to_binned_time(t, period)
        sfc = z3_sfc(period)
        z = sfc.index(x, y, offs.astype(np.float64), xp=np).astype(np.int64)
        cells = z >> (63 - self.bits)
        keys = np.stack([bins, cells], axis=1)
        uniq, cnt = np.unique(keys, axis=0, return_counts=True)
        for (b, c), n in zip(uniq, cnt):
            k = (int(b), int(c))
            self.counts[k] = self.counts.get(k, 0) + int(n)

    def merge(self, other):
        out = Z3HistogramStat(self.geom, self.dtg, self.period, self.bits,
                              dict(self.counts))
        for k, v in other.counts.items():
            out.counts[k] = out.counts.get(k, 0) + v
        return out

    @property
    def is_empty(self):
        return not self.counts

    def to_json(self):
        return {"kind": self.kind, "geom": self.geom, "dtg": self.dtg,
                "period": self.period, "bits": self.bits,
                "counts": [[k[0], k[1], v] for k, v in sorted(self.counts.items())]}


def _string_digest(col: np.ndarray) -> np.ndarray:
    """Seed-INDEPENDENT 64-bit digest of a string column's UTF-8 bytes
    (two crc32 lanes).  Computed once per column; every per-depth sketch
    hash then derives via the seeded splitmix finalize — which is what
    lets the DEVICE count-min sketch serve string columns bit-identically
    (round-4 VERDICT #8): the digest column ships to the device as plain
    int64 and the device's numeric hash path takes over unchanged."""
    return np.fromiter(
        ((zlib.crc32(b) | (zlib.crc32(b, 0x9E3779B9) << 32))
         for b in (str(v).encode() for v in col)),
        dtype=np.uint64, count=len(col))


def _hash_col(col: np.ndarray, seed: int) -> np.ndarray:
    """Stable vectorized 64-bit hash of a column (numeric or object)."""
    if col.dtype == object:
        # digest once, then the SAME seeded path as numerics — one
        # Python-loop pass per column instead of one per sketch depth,
        # and exactly what the device sketch computes from the digest
        out = _string_digest(col)
        out ^= np.uint64(seed * 0x9E3779B97F4A7C15 & 0xFFFFFFFFFFFFFFFF)
    else:
        arr = col
        if np.issubdtype(arr.dtype, np.floating):
            # canonicalize non-finite / out-of-range floats BEFORE the
            # int64 cast: the raw C cast is platform-dependent (x86
            # gives INT64_MIN, aarch64 gives 0 / INT64_MAX) and the
            # device sketch must hash identically everywhere
            lo = float(np.iinfo(np.int64).min)
            ok = np.isfinite(arr) & (arr >= lo) & (arr < 2.0 ** 63)
            with np.errstate(invalid="ignore"):
                arr = np.where(ok, arr, lo)
        out = arr.astype(np.int64).view(np.uint64).copy()
        out ^= np.uint64(seed * 0x9E3779B97F4A7C15 & 0xFFFFFFFFFFFFFFFF)
    # splitmix64 finalize
    out = (out ^ (out >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    out = (out ^ (out >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return out ^ (out >> np.uint64(31))


@dataclass
class Frequency(Stat):
    """Count-min sketch: approximate per-value frequencies (reference:
    utils/stats/Frequency + vendored clearspring CountMinSketch)."""

    kind = "frequency"
    attr: str = ""
    depth: int = 4
    width: int = 1024
    table: np.ndarray | None = None

    def __post_init__(self):
        if self.table is None:
            self.table = np.zeros((self.depth, self.width), dtype=np.int64)

    def observe(self, batch):
        col = _col(batch, self.attr)
        for d in range(self.depth):
            h = _hash_col(col, d + 1) % np.uint64(self.width)
            np.add.at(self.table[d], h.astype(np.int64), 1)

    def count(self, value) -> int:
        col = np.asarray([value], dtype=object if isinstance(value, str) else None)
        est = None
        for d in range(self.depth):
            h = int(_hash_col(col, d + 1)[0] % np.uint64(self.width))
            c = int(self.table[d, h])
            est = c if est is None else min(est, c)
        return est

    def merge(self, other):
        if (self.depth, self.width) != (other.depth, other.width):
            raise ValueError("cannot merge frequency sketches of different shape")
        return Frequency(self.attr, self.depth, self.width,
                         self.table + other.table)

    @property
    def is_empty(self):
        return int(self.table.sum()) == 0

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr, "depth": self.depth,
                "width": self.width, "table": self.table.tolist()}


@dataclass
class TopK(Stat):
    """Space-saving top-k (reference: utils/stats/TopK + StreamSummary)."""

    kind = "topk"
    attr: str = ""
    k: int = 10
    counters: dict = field(default_factory=dict)

    @property
    def _capacity(self) -> int:
        return self.k * 10

    def observe(self, batch):
        col = _col(batch, self.attr)
        uniq, cnt = np.unique(col.astype(str) if col.dtype == object else col,
                              return_counts=True)
        self.observe_counts(uniq, cnt)

    def observe_counts(self, uniq, cnt) -> None:
        """Fold pre-aggregated (values, counts) — lets the write path
        compute ONE unique per column for every sketch that needs it
        (the facade ingest profile showed duplicate unique/astype
        passes dominating host time)."""
        for v, n in zip(uniq.tolist(), cnt.tolist()):
            if v in self.counters:
                self.counters[v] += n
            elif len(self.counters) < self._capacity:
                self.counters[v] = n
            else:
                # space-saving: replace the min counter
                mv = min(self.counters, key=self.counters.get)
                self.counters[v] = self.counters.pop(mv) + n

    def topk(self, n: int | None = None):
        n = n or self.k
        return sorted(self.counters.items(), key=lambda kv: -kv[1])[:n]

    def merge(self, other):
        out = TopK(self.attr, self.k, dict(self.counters))
        for v, n in other.counters.items():
            out.counters[v] = out.counters.get(v, 0) + n
        if len(out.counters) > out._capacity:
            out.counters = dict(sorted(out.counters.items(),
                                       key=lambda kv: -kv[1])[:out._capacity])
        return out

    @property
    def is_empty(self):
        return not self.counters

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr, "k": self.k,
                "counters": self.counters}


@dataclass
class EnumerationStat(Stat):
    """Exact value → count map (reference: utils/stats/EnumerationStat)."""

    kind = "enumeration"
    attr: str = ""
    counts: dict = field(default_factory=dict)

    def observe(self, batch):
        col = _col(batch, self.attr)
        uniq, cnt = np.unique(col.astype(str) if col.dtype == object else col,
                              return_counts=True)
        self.observe_counts(uniq, cnt)

    def observe_counts(self, uniq, cnt) -> None:
        """Fold pre-aggregated (values, counts) — see TopK."""
        for v, n in zip(uniq.tolist(), cnt.tolist()):
            self.counts[v] = self.counts.get(v, 0) + n

    def merge(self, other):
        out = EnumerationStat(self.attr, dict(self.counts))
        for v, n in other.counts.items():
            out.counts[v] = out.counts.get(v, 0) + n
        return out

    @property
    def is_empty(self):
        return not self.counts

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr, "counts": self.counts}


@dataclass
class DescriptiveStats(Stat):
    """Streaming mean/variance/min/max (reference: utils/stats/
    DescriptiveStats, Welford-mergeable)."""

    kind = "descriptive"
    attr: str = ""
    n: int = 0
    mean: float = 0.0
    m2: float = 0.0
    min: float = math.inf
    max: float = -math.inf

    def observe(self, batch):
        col = np.asarray(_col(batch, self.attr), dtype=np.float64)
        if len(col) == 0:
            return
        other = DescriptiveStats(
            self.attr, len(col), float(col.mean()),
            float(((col - col.mean()) ** 2).sum()),
            float(col.min()), float(col.max()))
        merged = self.merge(other)
        self.__dict__.update(merged.__dict__)

    def merge(self, other):
        if other.n == 0:
            return DescriptiveStats(**dict(self.__dict__))
        if self.n == 0:
            return DescriptiveStats(**dict(other.__dict__))
        n = self.n + other.n
        delta = other.mean - self.mean
        mean = self.mean + delta * other.n / n
        m2 = self.m2 + other.m2 + delta * delta * self.n * other.n / n
        return DescriptiveStats(self.attr, n, mean, m2,
                                min(self.min, other.min),
                                max(self.max, other.max))

    @property
    def variance(self) -> float:
        return self.m2 / (self.n - 1) if self.n > 1 else 0.0

    @property
    def stddev(self) -> float:
        return math.sqrt(self.variance)

    @property
    def is_empty(self):
        return self.n == 0

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr, "n": self.n,
                "mean": self.mean, "m2": self.m2, "min": self.min,
                "max": self.max}


@dataclass
class GroupBy(Stat):
    """Group a sub-stat by the values of an attribute (reference:
    utils/stats/GroupBy)."""

    kind = "groupby"
    attr: str = ""
    spec: str = ""                     # sub-stat DSL, e.g. "Count()"
    groups: dict = field(default_factory=dict)

    def observe(self, batch):
        col = _col(batch, self.attr)
        keys = col.astype(str) if col.dtype == object else col
        for v in np.unique(keys).tolist():
            sel = np.flatnonzero(keys == v)
            sub = self.groups.get(v)
            if sub is None:
                sub = parse_stat(self.spec)
                self.groups[v] = sub
            sub.observe(batch.take(sel) if hasattr(batch, "take")
                        else {k: np.asarray(c)[sel] for k, c in batch.items()})

    def merge(self, other):
        out = GroupBy(self.attr, self.spec, dict(self.groups))
        for v, sub in other.groups.items():
            out.groups[v] = sub if v not in out.groups else out.groups[v] + sub
        return out

    @property
    def is_empty(self):
        return not self.groups

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr, "spec": self.spec,
                "groups": {str(k): v.to_json() for k, v in self.groups.items()}}


@dataclass
class SeqStat(Stat):
    """A sequence of stats observed together (the DSL's ';' composition)."""

    kind = "seq"
    stats: list = field(default_factory=list)

    def fresh_copy(self) -> "Stat":
        return SeqStat([s.fresh_copy() for s in self.stats])

    def observe(self, batch):
        for s in self.stats:
            s.observe(batch)

    def merge(self, other):
        return SeqStat([a + b for a, b in zip(self.stats, other.stats)])

    @property
    def is_empty(self):
        return all(s.is_empty for s in self.stats)

    def to_json(self):
        return {"kind": self.kind, "stats": [s.to_json() for s in self.stats]}


# ---------------------------------------------------------------------------
# DSL parser: "Count();MinMax(attr);Histogram(attr,20,0,100);TopK(attr)"
# (reference: utils/stats/Stat.scala apply + StatParser)
# ---------------------------------------------------------------------------

_CALL_RE = re.compile(r"^\s*(\w+)\s*\((.*)\)\s*$", re.DOTALL)


def _parse_one(spec: str) -> Stat:
    m = _CALL_RE.match(spec)
    if not m:
        raise ValueError(f"invalid stat spec: {spec!r}")
    name, arg_str = m.group(1).lower(), m.group(2)
    if name == "groupby":
        # args: attribute, then a nested stat spec (may contain parens/commas)
        attr, _, sub = arg_str.partition(",")
        return GroupBy(attr.strip(), sub.strip())
    args = [a.strip().strip("'\"") for a in arg_str.split(",")] if arg_str.strip() else []
    if name == "count":
        return CountStat()
    if name == "minmax":
        return MinMax(args[0])
    if name == "histogram":
        return Histogram(args[0], int(args[1]), float(args[2]), float(args[3]))
    if name == "z3histogram":
        return Z3HistogramStat(args[0], args[1],
                               args[2] if len(args) > 2 else "week",
                               int(args[3]) if len(args) > 3 else 10)
    if name == "frequency":
        return Frequency(args[0],
                         int(args[1]) if len(args) > 1 else 4,
                         int(args[2]) if len(args) > 2 else 1024)
    if name == "topk":
        return TopK(args[0], int(args[1]) if len(args) > 1 else 10)
    if name == "enumeration":
        return EnumerationStat(args[0])
    if name == "descriptivestats" or name == "stats":
        return DescriptiveStats(args[0])
    raise ValueError(f"unknown stat {name!r}")


def parse_stat(spec: str) -> Stat:
    """Parse the ';'-separated stat DSL into a Stat (SeqStat if several)."""
    parts = [p for p in spec.split(";") if p.strip()]
    if not parts:
        raise ValueError("empty stat spec")
    stats = [_parse_one(p) for p in parts]
    return stats[0] if len(stats) == 1 else SeqStat(stats)


_KINDS = {}


def observe_shared(stats, batch) -> None:
    """Observe every stat over one chunk with shared per-column
    intermediates: TopK and EnumerationStat over the same attribute
    fold ONE ``np.unique`` (and one object→str cast) instead of one
    each — the write-path profile showed those duplicate passes
    dominating facade ingest host time (round-4 VERDICT weak #3)."""
    shared: dict[str, list] = {}
    rest: list = []
    for s in (stats.values() if isinstance(stats, dict) else stats):
        if isinstance(s, (TopK, EnumerationStat)):
            shared.setdefault(s.attr, []).append(s)
        else:
            rest.append(s)
    for attr, ss in shared.items():
        col = _col(batch, attr)   # missing column raises, like observe
        if col.dtype == object:
            try:
                # hash-based factorize beats sort-based np.unique ~5x
                # on object strings (0.19s vs 1.06s per 4M, measured)
                import pandas as pd
                codes, uniq = pd.factorize(col, sort=False)
                valid = codes >= 0     # factorize drops None/NaN
                cnt = np.bincount(codes[valid] if not valid.all()
                                  else codes, minlength=len(uniq))
                uniq = np.asarray(uniq, dtype=object).astype(str)
                n_na = len(codes) - int(valid.sum())
                if n_na:
                    # label NA values exactly as astype(str) would
                    # ("None" / "nan"), so the incremental path and the
                    # recompute path report identical keys (review r5)
                    sub = col[~valid]
                    n_none = sum(1 for v in sub if v is None)
                    if n_none:
                        uniq = np.append(uniq, "None")
                        cnt = np.append(cnt, n_none)
                    if n_na - n_none:
                        uniq = np.append(uniq, "nan")
                        cnt = np.append(cnt, n_na - n_none)
            except ImportError:  # pragma: no cover
                uniq, cnt = np.unique(col.astype(str),
                                      return_counts=True)
        else:
            uniq, cnt = np.unique(col, return_counts=True)
        for s in ss:
            s.observe_counts(uniq, cnt)
    for s in rest:
        s.observe(batch)


def stat_from_json(obj: dict) -> Stat:
    """Inverse of to_json for every stat kind."""
    kind = obj["kind"]
    if kind == "count":
        return CountStat(obj["count"])
    if kind == "minmax":
        return MinMax(obj["attr"], obj["min"], obj["max"])
    if kind == "bbox":
        return BBoxStat(obj["attr"], obj["xmin"], obj["ymin"],
                        obj["xmax"], obj["ymax"])
    if kind == "histogram":
        return Histogram(obj["attr"], obj["bins"], obj["lo"], obj["hi"],
                         np.asarray(obj["counts"], dtype=np.int64))
    if kind == "z3histogram":
        return Z3HistogramStat(
            obj["geom"], obj["dtg"], obj["period"], obj["bits"],
            {(int(b), int(c)): int(v) for b, c, v in obj["counts"]})
    if kind == "frequency":
        return Frequency(obj["attr"], obj["depth"], obj["width"],
                         np.asarray(obj["table"], dtype=np.int64))
    if kind == "topk":
        return TopK(obj["attr"], obj["k"], dict(obj["counters"]))
    if kind == "enumeration":
        return EnumerationStat(obj["attr"], dict(obj["counts"]))
    if kind == "descriptive":
        return DescriptiveStats(obj["attr"], obj["n"], obj["mean"], obj["m2"],
                                obj["min"], obj["max"])
    if kind == "groupby":
        g = GroupBy(obj["attr"], obj["spec"])
        g.groups = {k: stat_from_json(v) for k, v in obj["groups"].items()}
        return g
    if kind == "seq":
        return SeqStat([stat_from_json(s) for s in obj["stats"]])
    raise ValueError(f"unknown stat kind {kind!r}")
