"""Stats sketches: summary statistics for cost-based planning and
distributed aggregation.

Capability match for the reference's ``Stat`` algebra
(geomesa-utils/.../stats/Stat.scala:31-90 — observe/merge/serialize — with
implementations CountStat, MinMax, Histogram, Z3Histogram, Frequency
(count-min), TopK, EnumerationStat, GroupBy, DescriptiveStats, and the
``Stat("Count();MinMax(x)")`` parser DSL).  TPU-first difference: stats
observe whole *columns* (vectorized numpy; device reductions for the hot
ones), not one feature at a time, and every sketch is a mergeable monoid so
per-shard partials combine with ``+`` — the same contract the reference's
distributed StatsScan relies on (index/iterators/StatsScan.scala).
"""

from .stat import (
    BBoxStat,
    CountStat,
    DescriptiveStats,
    EnumerationStat,
    Frequency,
    GroupBy,
    Histogram,
    MinMax,
    SeqStat,
    Stat,
    TopK,
    Z3HistogramStat,
    parse_stat,
    stat_from_json,
)
