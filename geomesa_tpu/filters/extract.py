"""Filter analysis for the planner: CNF rewrite and geometry/interval
extraction.

Mirrors the roles of the reference's FilterHelper
(geomesa-filter/.../FilterHelper.scala — ``extractGeometries`` :102,
``extractIntervals`` :151) and the CNF rewrite in
geomesa-filter/.../package.scala:52: the planner needs, per query, the
spatial envelopes and temporal intervals that an index can serve, plus the
leftover predicate to re-check.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..geometry.types import Envelope, Geometry, Point, Polygon
from .ast import (
    And, BBox, Contains, Crosses, During, DWithin, Exclude, Filter,
    GeomEquals, Overlaps, Touches,
    Include, Intersects, Not, Or, Within, _Exclude, _Include,
)

__all__ = ["FilterValues", "extract_geometries", "extract_intervals", "to_cnf",
           "split_cnf_clauses"]


@dataclass(frozen=True)
class FilterValues:
    """Extracted values: a disjunction of geometries or intervals.

    ``disjoint=True`` means the filter is provably empty (e.g. two
    non-overlapping AND'd bboxes — FilterHelper models this the same way)."""

    values: tuple = ()
    disjoint: bool = False

    def __bool__(self) -> bool:
        return bool(self.values) and not self.disjoint


def to_cnf(f: Filter) -> Filter:
    """Rewrite into conjunctive normal form (bounded distribution).

    The reference rewrites filters to CNF before splitting
    (geomesa-filter/.../package.scala:52, used by FilterSplitter); the
    same distribution laws apply here, with Not pushed to leaves.
    """
    f = _push_not(f, negate=False)
    return _distribute_or(f)


def _push_not(f: Filter, negate: bool) -> Filter:
    if isinstance(f, Not):
        return _push_not(f.filter, not negate)
    if isinstance(f, And):
        parts = tuple(_push_not(p, negate) for p in f.filters)
        return Or(parts) if negate else And(parts)
    if isinstance(f, Or):
        parts = tuple(_push_not(p, negate) for p in f.filters)
        return And(parts) if negate else Or(parts)
    if isinstance(f, _Include):
        return Exclude if negate else Include
    if isinstance(f, _Exclude):
        return Include if negate else Exclude
    return Not(f) if negate else f


def _flatten(cls, filters):
    out = []
    for f in filters:
        if isinstance(f, cls):
            out.extend(_flatten(cls, f.filters))
        else:
            out.append(f)
    return out


def _distribute_or(f: Filter) -> Filter:
    if isinstance(f, And):
        parts = [_distribute_or(p) for p in _flatten(And, f.filters)]
        clauses = []
        for p in parts:
            if isinstance(p, And):
                clauses.extend(p.filters)
            else:
                clauses.append(p)
        return And(tuple(clauses)) if len(clauses) > 1 else clauses[0]
    if isinstance(f, Or):
        parts = [_distribute_or(p) for p in _flatten(Or, f.filters)]
        # distribute OR over any AND child: (a ∧ b) ∨ c → (a ∨ c) ∧ (b ∨ c)
        for i, p in enumerate(parts):
            if isinstance(p, And):
                rest = parts[:i] + parts[i + 1:]
                new = And(tuple(
                    Or(tuple([clause, *rest])) for clause in p.filters
                ))
                return _distribute_or(new)
        return Or(tuple(parts)) if len(parts) > 1 else parts[0]
    return f


def split_cnf_clauses(f: Filter) -> list[Filter]:
    """Top-level AND clauses of the CNF form."""
    cnf = to_cnf(f)
    if isinstance(cnf, And):
        return list(cnf.filters)
    return [cnf]


def _geom_envelope_values(f: Filter, prop: str) -> "FilterValues | None":
    """Geometry values contributed by a single node (None = no constraint)."""
    if isinstance(f, BBox) and f.prop == prop:
        return FilterValues((Polygon.from_envelope(f.envelope),))
    if isinstance(f, (Intersects, Within, Contains, GeomEquals,
                      Touches, Crosses, Overlaps)) and f.prop == prop:
        return FilterValues((f.geometry,))
    if isinstance(f, DWithin) and f.prop == prop:
        env = f.geometry.envelope
        deg = f.degrees  # covering degree equivalent for metric distances
        grown = Envelope(env.xmin - deg, env.ymin - deg,
                         env.xmax + deg, env.ymax + deg)
        return FilterValues((Polygon.from_envelope(grown),))
    return None


def extract_geometries(f: Filter, prop: str) -> FilterValues:
    """Extract the union-of-geometries this filter constrains ``prop`` to.

    AND intersects envelopes (detecting disjoint → provably-empty), OR
    unions the alternatives; any branch without a spatial constraint makes
    the whole OR unconstrained — the same conservative semantics as
    FilterHelper.extractGeometries.
    """
    if isinstance(f, And):
        current: FilterValues | None = None
        for part in f.filters:
            vals = extract_geometries(part, prop)
            if vals.disjoint:
                return FilterValues(disjoint=True)
            if not vals.values:
                continue
            if current is None:
                current = vals
            else:
                # intersect at envelope granularity
                kept = []
                for g in current.values:
                    for h in vals.values:
                        inter = g.envelope.intersection(h.envelope)
                        if inter is None:
                            continue
                        # keep the original (more precise) geometry when its
                        # envelope IS the intersection, else the envelope box
                        if inter == g.envelope:
                            kept.append(g)
                        elif inter == h.envelope:
                            kept.append(h)
                        else:
                            kept.append(Polygon.from_envelope(inter))
                if not kept:
                    return FilterValues(disjoint=True)
                current = FilterValues(tuple(kept))
        return current if current is not None else FilterValues()
    if isinstance(f, Or):
        out = []
        for part in f.filters:
            vals = extract_geometries(part, prop)
            if vals.disjoint:
                continue
            if not vals.values:
                return FilterValues()  # unconstrained branch
            out.extend(vals.values)
        return FilterValues(tuple(out))
    if isinstance(f, Not):
        return FilterValues()  # negated spatial predicates are not indexable
    if isinstance(f, _Exclude):
        return FilterValues(disjoint=True)
    vals = _geom_envelope_values(f, prop)
    return vals if vals is not None else FilterValues()


def extract_intervals(f: Filter, prop: str) -> FilterValues:
    """Extract (lo_ms, hi_ms) intervals constraining ``prop``.

    Open bounds become ±``None``; AND intersects, OR unions — mirroring
    FilterHelper.extractIntervals."""
    if isinstance(f, And):
        current: FilterValues | None = None
        for part in f.filters:
            vals = extract_intervals(part, prop)
            if vals.disjoint:
                return FilterValues(disjoint=True)
            if not vals.values:
                continue
            if current is None:
                current = vals
            else:
                kept = []
                for (alo, ahi) in current.values:
                    for (blo, bhi) in vals.values:
                        lo = blo if alo is None else alo if blo is None else max(alo, blo)
                        hi = bhi if ahi is None else ahi if bhi is None else min(ahi, bhi)
                        if lo is None or hi is None or lo <= hi:
                            kept.append((lo, hi))
                if not kept:
                    return FilterValues(disjoint=True)
                current = FilterValues(tuple(kept))
        return current if current is not None else FilterValues()
    if isinstance(f, Or):
        out = []
        for part in f.filters:
            vals = extract_intervals(part, prop)
            if vals.disjoint:
                continue
            if not vals.values:
                return FilterValues()
            out.extend(vals.values)
        return FilterValues(tuple(out))
    if isinstance(f, Not):
        return FilterValues()
    if isinstance(f, _Exclude):
        return FilterValues(disjoint=True)
    if isinstance(f, During) and f.prop == prop:
        return FilterValues(((f.lo_ms, f.hi_ms),))
    return FilterValues()
