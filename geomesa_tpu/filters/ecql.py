"""ECQL text parser: the query language front door.

A recursive-descent parser for the subset of (E)CQL the reference's users
actually write (GeoTools ECQL is the reference's parser; the grammar here
covers the predicates its planner understands — spatial, temporal,
comparison, logical).  Examples:

    BBOX(geom, -10, 35, 15, 52) AND dtg DURING 2018-01-01T00:00:00Z/2018-01-08T00:00:00Z
    INTERSECTS(geom, POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))
    name = 'alice' OR age >= 21
    vessel_id IN ('a', 'b') AND NOT flag = 'x'
"""

from __future__ import annotations

import datetime as _dt
import re

from ..geometry.wkt import geometry_from_wkt
from .ast import (
    And, BBox, Between, Contains, During, DWithin, Exclude, Filter,
    GeomEquals, IdFilter, In, Include, Intersects, Like, Not, Or,
    PropertyCompare, Within, Touches, Crosses, Overlaps,
)

__all__ = ["parse_ecql", "parse_iso_ms"]

_TOKEN_RE = re.compile(
    r"""
    \s*(?:
        (?P<string>'(?:[^']|'')*')
      | (?P<datetime>\d{4}-\d{2}-\d{2}T[\d:.]+Z?)
      | (?P<number>-?\d+\.?\d*(?:[eE][+-]?\d+)?)
      | (?P<op><=|>=|<>|!=|=|<|>)
      | (?P<punct>[(),/])
      | (?P<qword>"[^"]*")
      | (?P<word>[$A-Za-z_][A-Za-z0-9_.:\[\]]*)
    )""",
    re.VERBOSE,
)

_KEYWORDS = {
    "AND", "OR", "NOT", "IN", "LIKE", "ILIKE", "BETWEEN", "DURING", "BEFORE",
    "AFTER", "INCLUDE", "EXCLUDE", "BBOX", "INTERSECTS", "CONTAINS", "WITHIN",
    "DWITHIN", "DISJOINT", "EQUALS", "BEYOND", "IS", "NULL", "TEQUALS",
    "TOUCHES", "CROSSES", "OVERLAPS",
}

_GEOM_WORDS = {
    "POINT", "LINESTRING", "POLYGON", "MULTIPOINT", "MULTILINESTRING",
    "MULTIPOLYGON",
}


def _iso_ms(s: str) -> int:
    s = s.strip()
    if s.endswith("Z"):
        s = s[:-1]
    dt = _dt.datetime.fromisoformat(s).replace(tzinfo=_dt.timezone.utc)
    epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
    delta = dt - epoch
    return delta.days * 86_400_000 + delta.seconds * 1000 + delta.microseconds // 1000


def parse_iso_ms(s: str) -> int:
    """ISO-8601 (UTC assumed) → epoch millis."""
    return _iso_ms(s)


class _Tokens:
    def __init__(self, text: str):
        self.text = text
        self.toks: list[tuple[str, str]] = []
        pos = 0
        while pos < len(text):
            if text[pos].isspace():
                pos += 1
                continue
            m = _TOKEN_RE.match(text, pos)
            if not m:
                raise ValueError(f"cannot tokenize ECQL at: {text[pos:pos+30]!r}")
            kind = m.lastgroup
            val = m.group(kind)
            if kind == "qword":
                # double-quoted property name (json-path props, reserved
                # words as attributes): stays a distinct token kind so
                # keyword matching never applies to it
                val = val[1:-1]
            self.toks.append((kind, val))
            pos = m.end()
        self.i = 0

    def peek(self, ahead: int = 0):
        j = self.i + ahead
        return self.toks[j] if j < len(self.toks) else (None, None)

    def next(self):
        tok = self.peek()
        self.i += 1
        return tok

    def expect(self, value: str):
        kind, val = self.next()
        if val is None or (val != value and val.upper() != value):
            got = "end of input" if val is None else repr(val)
            raise ValueError(f"expected {value!r}, got {got} in {self.text!r}")
        return val

    def at_word(self, word: str) -> bool:
        kind, val = self.peek()
        return kind == "word" and val.upper() == word


def parse_ecql(text: str) -> Filter:
    text = text.strip()
    if not text or text.upper() == "INCLUDE":
        return Include
    if text.upper() == "EXCLUDE":
        return Exclude
    toks = _Tokens(text)
    f = _parse_or(toks)
    if toks.peek()[0] is not None:
        raise ValueError(f"unexpected trailing tokens in {text!r}")
    return f


def _parse_or(toks: _Tokens) -> Filter:
    parts = [_parse_and(toks)]
    while toks.at_word("OR"):
        toks.next()
        parts.append(_parse_and(toks))
    return parts[0] if len(parts) == 1 else Or(tuple(parts))


def _parse_and(toks: _Tokens) -> Filter:
    parts = [_parse_unary(toks)]
    while toks.at_word("AND"):
        toks.next()
        parts.append(_parse_unary(toks))
    return parts[0] if len(parts) == 1 else And(tuple(parts))


def _parse_unary(toks: _Tokens) -> Filter:
    if toks.at_word("NOT"):
        toks.next()
        return Not(_parse_unary(toks))
    kind, val = toks.peek()
    if kind == "punct" and val == "(":
        toks.next()
        inner = _parse_or(toks)
        toks.expect(")")
        return inner
    return _parse_predicate(toks)


def _parse_wkt(toks: _Tokens):
    """Re-assemble a WKT literal from tokens (numbers, parens, commas)."""
    kind, word = toks.next()
    if kind != "word" or word.upper() not in _GEOM_WORDS:
        raise ValueError(f"expected WKT geometry, got {word!r}")
    parts = [word.upper()]
    depth = 0
    while True:
        kind, val = toks.peek()
        if kind is None:
            break
        if kind == "punct" and val == "(":
            depth += 1
            parts.append("(")
            toks.next()
        elif kind == "punct" and val == ")":
            if depth == 0:
                break
            depth -= 1
            parts.append(")")
            toks.next()
            if depth == 0:
                break
        elif kind == "punct" and val == ",":
            parts.append(",")
            toks.next()
        elif kind == "number":
            parts.append(val)
            toks.next()
        else:
            break
    return geometry_from_wkt(" ".join(parts))


def _literal(kind: str, val: str):
    if kind == "string":
        return val[1:-1].replace("''", "'")
    if kind == "number":
        f = float(val)
        return int(f) if f.is_integer() and "." not in val and "e" not in val.lower() else f
    if kind == "datetime":
        return _iso_ms(val)
    if kind == "word" and val.lower() in ("true", "false"):
        # boolean literals (the CQL spec's booleanValueExpression)
        return val.lower() == "true"
    raise ValueError(f"expected literal, got {val!r}")


def _parse_literal_list(toks: _Tokens, what: str) -> list:
    """Parse '( literal, literal, … )' after IN."""
    toks.expect("(")
    values = []
    while True:
        k, v = toks.next()
        values.append(_literal(k, v))
        k, v = toks.next()
        if v == ")":
            break
        if v != ",":
            raise ValueError(f"bad {what} list near {v!r}")
    return values


def _parse_predicate(toks: _Tokens) -> Filter:
    kind, val = toks.next()
    if kind not in ("word", "qword"):
        raise ValueError(f"expected predicate, got {val!r}")
    if kind == "qword":
        # quoted: always a property name, never a keyword
        return _parse_property_predicate(toks, val)
    upper = val.upper()

    if upper == "INCLUDE":
        return Include
    if upper == "EXCLUDE":
        return Exclude

    if upper == "IN":
        # bare IN list = feature-id filter (GeoTools convention)
        return IdFilter(tuple(str(v) for v in _parse_literal_list(toks, "id")))

    if upper == "BBOX":
        toks.expect("(")
        _, prop = toks.next()
        nums = []
        for _ in range(4):
            toks.expect(",")
            nums.append(float(toks.next()[1]))
        # optional CRS argument, ignored
        if toks.peek()[1] == ",":
            toks.next()
            toks.next()
        toks.expect(")")
        return BBox(prop, *nums)

    if upper in ("INTERSECTS", "CONTAINS", "WITHIN", "DISJOINT", "EQUALS",
                 "TOUCHES", "CROSSES", "OVERLAPS"):
        toks.expect("(")
        _, prop = toks.next()
        toks.expect(",")
        geom = _parse_wkt(toks)
        toks.expect(")")
        if upper == "DISJOINT":  # exact complement of INTERSECTS
            return Not(Intersects(prop, geom))
        if upper == "EQUALS":
            return GeomEquals(prop, geom)
        cls = {"INTERSECTS": Intersects, "CONTAINS": Contains,
               "WITHIN": Within, "TOUCHES": Touches, "CROSSES": Crosses,
               "OVERLAPS": Overlaps}[upper]
        return cls(prop, geom)

    if upper in ("DWITHIN", "BEYOND"):
        toks.expect("(")
        _, prop = toks.next()
        toks.expect(",")
        geom = _parse_wkt(toks)
        toks.expect(",")
        dist = float(toks.next()[1])
        # optional units, either ", kilometers" (ECQL) or a bare word —
        # converted to meters via the reference's multiplier
        # (GeometryProcessing.metersMultiplier); no units = degrees
        meters = False
        if toks.peek()[1] == ",":
            toks.next()
        if toks.peek()[0] == "word" and toks.peek()[1].upper() not in _KEYWORDS:
            unit = toks.next()[1].lower()
            mult = {"meters": 1.0, "kilometers": 1000.0, "feet": 0.3048,
                    "statute": None, "nautical": None}.get(unit, 1.0)
            if mult is None:  # two-word units: 'statute miles' etc.
                word2 = toks.next()[1].lower()
                mult = {"statute miles": 1609.347,
                        "nautical miles": 1852.0}.get(f"{unit} {word2}", 1.0)
            dist *= mult
            meters = True
        toks.expect(")")
        dw = DWithin(prop, geom, dist, meters=meters)
        return Not(dw) if upper == "BEYOND" else dw

    # property-led predicates
    return _parse_property_predicate(toks, val)


def _parse_property_predicate(toks: _Tokens, prop: str) -> Filter:
    kind, val = toks.next()
    if kind == "word":
        upper = val.upper()
        if upper == "DURING":
            _, lo = toks.next()
            toks.expect("/")
            _, hi = toks.next()
            return During(prop, _iso_ms(lo), _iso_ms(hi))
        if upper in ("BEFORE", "AFTER", "TEQUALS"):
            _, t = toks.next()
            ms = _iso_ms(t)
            if upper == "BEFORE":
                return During(prop, None, ms - 1)
            if upper == "AFTER":
                return During(prop, ms + 1, None)
            return During(prop, ms, ms)
        if upper == "IN":
            return In(prop, tuple(_parse_literal_list(toks, "IN")))
        if upper in ("LIKE", "ILIKE"):
            k, v = toks.next()
            return Like(prop, _literal(k, v), case_insensitive=(upper == "ILIKE"))
        if upper == "BETWEEN":
            k, v = toks.next()
            lo = _literal(k, v)
            if not toks.at_word("AND"):
                raise ValueError("BETWEEN requires AND")
            toks.next()
            k, v = toks.next()
            return Between(prop, lo, _literal(k, v))
        if upper == "IS":
            # IS [NOT] NULL → not supported as storage has no nulls yet;
            # IS NULL matches nothing, IS NOT NULL matches everything
            if toks.at_word("NOT"):
                toks.next()
                toks.expect("NULL")
                return Include
            toks.expect("NULL")
            return Exclude
        raise ValueError(f"unsupported predicate {val!r} after {prop!r}")
    if kind == "op":
        op = "<>" if val == "!=" else val
        k, v = toks.next()
        lit = _literal(k, v)
        # date comparisons normalize onto During intervals
        if k == "datetime":
            if op == "=":
                return During(prop, lit, lit)
            if op == "<":
                return During(prop, None, lit - 1)
            if op == "<=":
                return During(prop, None, lit)
            if op == ">":
                return During(prop, lit + 1, None)
            if op == ">=":
                return During(prop, lit, None)
        return PropertyCompare(prop, op, lit)
    raise ValueError(f"cannot parse predicate starting at {prop!r}")
