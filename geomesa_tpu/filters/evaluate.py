"""Vectorized filter evaluation over FeatureBatches.

The columnar replacement for the reference's FastFilterFactory / CQL
row-at-a-time evaluation (geomesa-filter, used server-side by
FilterTransformIterator): a filter evaluates to one boolean mask over the
whole batch, each predicate a dense numpy op over its column.  This is
both the full-scan path (LocalQueryRunner analog,
index/planning/LocalQueryRunner.scala:44-130) and the exact re-check
applied to index candidates.
"""

from __future__ import annotations

import re

import numpy as np

from ..features.batch import FeatureBatch
from ..geometry.predicates import (
    bbox_intersects,
    geometry_distance,
    geometry_intersects,
    geometry_within,
    point_in_polygon,
    points_on_rings,
    points_to_geometry_dist,
)
from ..geometry.types import (
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from .ast import (
    And, BBox, Between, Contains, Crosses, During, DWithin, Filter,
    GeomEquals, Overlaps, Touches,
    IdFilter, In, Intersects, Like, Not, Or, PropertyCompare, Within,
    _Exclude, _Include,
)

__all__ = ["evaluate_filter"]


def _use_xy_fast_path(batch: FeatureBatch, prop: str) -> bool:
    """True when the property's x/y columns are the right source: either
    it is a secondary point attribute, or the default geometry with no
    packed (non-point) storage.  The packed column only ever holds the
    DEFAULT geometry, so other props must never fall through to it."""
    if f"{prop}_x" not in batch.columns:
        return False
    return prop != batch.sft.default_geom or batch.geoms is None


def _like_regex(pattern: str, case_insensitive: bool) -> re.Pattern:
    # SQL LIKE: % = any run, _ = single char
    esc = re.escape(pattern).replace("%", ".*").replace("_", ".")
    return re.compile("^" + esc + "$", re.IGNORECASE if case_insensitive else 0)


def _geom_mask_polygonal(batch: FeatureBatch, prop: str, geom, op: str) -> np.ndarray:
    """Spatial mask for a query geometry over the batch's geometry column
    (point fast path or packed geometries), honoring the operator."""
    n = len(batch)
    if _use_xy_fast_path(batch, prop):
        x, y = batch.columns[f"{prop}_x"], batch.columns[f"{prop}_y"]
        if op in ("crosses", "overlaps"):
            # a point feature can never cross anything (its interior has
            # dimension 0) and overlaps requires equal dimensions with a
            # partial interior share a lone point cannot provide
            return np.zeros(n, dtype=bool)
        if op == "touches":
            from ..geometry.predicates import _rings_of
            if isinstance(geom, (Polygon, MultiPolygon)):
                return points_on_rings(x, y, _rings_of(geom))
            if isinstance(geom, (LineString, MultiLineString)):
                lines = ([geom] if isinstance(geom, LineString)
                         else list(geom.lines))
                out = np.zeros(n, dtype=bool)
                for l in lines:
                    for e in (l.coords[0], l.coords[-1]):
                        out |= (x == e[0]) & (y == e[1])
                return out
            return np.zeros(n, dtype=bool)
        if op == "contains":
            # a point can only contain (and only intersects-equal) a point
            if isinstance(geom, Point):
                return (x == geom.x) & (y == geom.y)
            return np.zeros(n, dtype=bool)
        if isinstance(geom, (Polygon, MultiPolygon)):
            # intersects == within for point features
            return point_in_polygon(x, y, geom)
        if isinstance(geom, Point):
            return (x == geom.x) & (y == geom.y)
        if isinstance(geom, MultiPoint):
            out = np.zeros(n, dtype=bool)
            for qx, qy in geom.coords:
                out |= (x == qx) & (y == qy)
            return out
        # linear query geometry: point must lie on a segment
        if isinstance(geom, LineString):
            rings = [geom.coords]
        elif isinstance(geom, MultiLineString):
            rings = [l.coords for l in geom.lines]
        else:
            raise NotImplementedError(f"spatial op over {geom.geom_type}")
        env = geom.envelope
        near = (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) & (y <= env.ymax)
        out = np.zeros(n, dtype=bool)
        if near.any():
            idx = np.flatnonzero(near)
            out[idx] = points_on_rings(x[idx], y[idx], rings)
        return out
    # packed geometries: bbox prefilter + exact object test.  The packed
    # column only ever stores the DEFAULT geometry — refuse rather than
    # silently answer for a different property
    packed = batch.geoms
    if packed is None or prop != batch.sft.default_geom:
        raise KeyError(f"no geometry column for {prop!r}")
    env = geom.envelope
    cand = bbox_intersects(packed.bbox, env.as_tuple())
    out = np.zeros(n, dtype=bool)
    if op == "intersects":
        # batched exact predicate over the SoA buffers — the hot residual
        # re-check runs vectorized, not per-candidate (round-3 next #4)
        from ..geometry.predicates import packed_intersects
        idx = np.flatnonzero(cand)
        out[idx] = packed_intersects(packed, geom, idx)
        return out
    for i in np.flatnonzero(cand):
        gi = packed.geometry(int(i))
        if op == "within":
            out[i] = geometry_within(gi, geom)
        elif op == "contains":
            out[i] = geometry_within(geom, gi)
        elif op == "touches":
            from ..geometry.predicates import geometry_touches
            out[i] = geometry_touches(gi, geom)
        elif op == "crosses":
            from ..geometry.predicates import geometry_crosses
            out[i] = geometry_crosses(gi, geom)
        elif op == "overlaps":
            from ..geometry.predicates import geometry_overlaps
            out[i] = geometry_overlaps(gi, geom)
        else:
            raise NotImplementedError(op)
    return out


def _canonical_ring(coords: np.ndarray) -> tuple:
    """Orientation- and start-point-invariant form of a closed ring: the
    lexicographically smallest rotation over both directions (ECQL/JTS
    EQUALS is topological, so POLYGON((0 0,2 0,2 2,0 2,0 0)) equals the
    same ring started elsewhere or wound the other way)."""
    pts = [tuple(p) for p in np.asarray(coords, dtype=np.float64)]
    if len(pts) > 1 and pts[0] == pts[-1]:
        pts = pts[:-1]
    best = None
    for seq in (pts, pts[::-1]):
        for s in range(len(seq)):
            rot = tuple(seq[s:] + seq[:s])
            if best is None or rot < best:
                best = rot
    return best or ()


def _canonical_geom(g) -> tuple:
    """Hashable topological-equality key for a geometry."""
    if isinstance(g, Point):
        return ("point", (g.x, g.y))
    if isinstance(g, MultiPoint):
        return ("multipoint",
                tuple(sorted(tuple(p) for p in np.asarray(g.coords))))
    if isinstance(g, LineString):
        pts = tuple(tuple(p) for p in np.asarray(g.coords))
        return ("line", min(pts, pts[::-1]))
    if isinstance(g, MultiLineString):
        return ("multiline",
                tuple(sorted(_canonical_geom(l)[1] for l in g.lines)))
    if isinstance(g, Polygon):
        return ("polygon", _canonical_ring(g.shell),
                tuple(sorted(_canonical_ring(h) for h in g.holes)))
    if isinstance(g, MultiPolygon):
        return ("multipolygon",
                tuple(sorted(_canonical_geom(p)[1:] for p in g.polygons)))
    return ("other", repr(g))


def _prop_column(batch: FeatureBatch, prop: str) -> np.ndarray:
    """Resolve a property reference to a column.

    ``$.attr.path.to.value`` digs into a json-typed attribute (the
    reference's json-path attribute queries, features/kryo/json/*):
    the first path segment names the attribute, the rest walks the
    parsed document of each row.
    """
    if not prop.startswith("$."):
        return batch.column(prop)
    import json as _json

    from ..geojson.query import json_path_get
    rest = prop[2:]
    first, _, inner = rest.partition(".")
    # a bracket on the first segment indexes into the attribute's value:
    # $.props[0].name → attribute "props", path "[0].name"
    attr, bracket, idx = first.partition("[")
    if bracket:
        inner = f"[{idx}.{inner}" if inner else f"[{idx}"
    col = batch.column(attr)

    def parse(v):
        if not (isinstance(v, (str, bytes)) and v):
            return v
        try:
            return _json.loads(v)
        except ValueError:
            return None  # malformed json row: non-matching, not fatal

    docs = [parse(v) for v in col]
    if not inner:
        return np.asarray(docs, dtype=object)
    return np.asarray([None if d is None else json_path_get(d, "$." + inner)
                       for d in docs], dtype=object)


def _safe_compare(col: np.ndarray, value, op: str) -> np.ndarray:
    """Ordering comparison tolerant of None/mixed entries in object
    columns (json-path results): non-comparable rows are False."""
    if col.dtype != object:
        return {"<": col < value, "<=": col <= value,
                ">": col > value, ">=": col >= value}[op]
    import operator as _op
    fn = {"<": _op.lt, "<=": _op.le, ">": _op.gt, ">=": _op.ge}[op]
    out = np.zeros(len(col), dtype=bool)
    for i, v in enumerate(col):
        if v is None:
            continue
        try:
            out[i] = fn(v, value)
        except TypeError:
            pass
    return out


def evaluate_filter(f: Filter, batch: FeatureBatch) -> np.ndarray:
    """Evaluate a filter to a boolean mask over the batch."""
    n = len(batch)
    if isinstance(f, _Include):
        return np.ones(n, dtype=bool)
    if isinstance(f, _Exclude):
        return np.zeros(n, dtype=bool)
    if isinstance(f, And):
        mask = np.ones(n, dtype=bool)
        for p in f.filters:
            mask &= evaluate_filter(p, batch)
        return mask
    if isinstance(f, Or):
        mask = np.zeros(n, dtype=bool)
        for p in f.filters:
            mask |= evaluate_filter(p, batch)
        return mask
    if isinstance(f, Not):
        return ~evaluate_filter(f.filter, batch)
    if isinstance(f, BBox):
        if _use_xy_fast_path(batch, f.prop):
            x = batch.columns[f"{f.prop}_x"]
            y = batch.columns[f"{f.prop}_y"]
            return (x >= f.xmin) & (x <= f.xmax) & (y >= f.ymin) & (y <= f.ymax)
        # non-point geometries: exact intersects against the box polygon
        # (the reference's default strict-bbox behavior; loose mode would
        # stop at the bbox prefilter)
        box_poly = Polygon.from_envelope(f.envelope)
        return _geom_mask_polygonal(batch, f.prop, box_poly, "intersects")
    if isinstance(f, Intersects):
        return _geom_mask_polygonal(batch, f.prop, f.geometry, "intersects")
    if isinstance(f, Within):
        return _geom_mask_polygonal(batch, f.prop, f.geometry, "within")
    if isinstance(f, Contains):
        return _geom_mask_polygonal(batch, f.prop, f.geometry, "contains")
    if isinstance(f, Touches):
        return _geom_mask_polygonal(batch, f.prop, f.geometry, "touches")
    if isinstance(f, Crosses):
        return _geom_mask_polygonal(batch, f.prop, f.geometry, "crosses")
    if isinstance(f, Overlaps):
        return _geom_mask_polygonal(batch, f.prop, f.geometry, "overlaps")
    if isinstance(f, DWithin):
        env = f.geometry.envelope
        deg = f.degrees
        window = (env.xmin - deg, env.ymin - deg,
                  env.xmax + deg, env.ymax + deg)
        if _use_xy_fast_path(batch, f.prop):
            x = batch.columns[f"{f.prop}_x"]
            y = batch.columns[f"{f.prop}_y"]
            if isinstance(f.geometry, Point):
                if f.meters:
                    # exact great-circle test for metric distances
                    from ..process.knn import haversine_m
                    return (haversine_m(f.geometry.x, f.geometry.y, x, y)
                            <= f.distance)
                d2 = (x - f.geometry.x) ** 2 + (y - f.geometry.y) ** 2
                return d2 <= deg ** 2
            # bbox prefilter bounds the (points × segments) distance work
            near = ((x >= window[0]) & (x <= window[2])
                    & (y >= window[1]) & (y <= window[3]))
            out = np.zeros(n, dtype=bool)
            if near.any():
                idx = np.flatnonzero(near)
                out[idx] = (points_to_geometry_dist(x[idx], y[idx],
                                                    f.geometry)
                            <= deg)
            return out
        packed = batch.geoms
        if packed is None or f.prop != batch.sft.default_geom:
            raise KeyError(f"no geometry column for {f.prop!r}")
        # bbox prefilter expanded by the distance, then exact per candidate
        cand = bbox_intersects(packed.bbox, window)
        out = np.zeros(n, dtype=bool)
        for i in np.flatnonzero(cand):
            out[i] = (geometry_distance(packed.geometry(int(i)), f.geometry)
                      <= deg)
        return out
    if isinstance(f, GeomEquals):
        from ..geometry.types import Point as _Pt
        if _use_xy_fast_path(batch, f.prop):
            x = batch.columns[f"{f.prop}_x"]
            y = batch.columns[f"{f.prop}_y"]
            if not isinstance(f.geometry, _Pt):
                return np.zeros(n, dtype=bool)
            return (x == f.geometry.x) & (y == f.geometry.y)
        packed = batch.geoms
        if packed is None or f.prop != batch.sft.default_geom:
            raise KeyError(f"no geometry column for {f.prop!r}")
        env = f.geometry.envelope
        # exact-equality prefilter: equal geometries have equal bboxes
        cand = ((packed.bbox[:, 0] == env.xmin)
                & (packed.bbox[:, 1] == env.ymin)
                & (packed.bbox[:, 2] == env.xmax)
                & (packed.bbox[:, 3] == env.ymax))
        out = np.zeros(n, dtype=bool)
        want = _canonical_geom(f.geometry)
        for i in np.flatnonzero(cand):
            out[i] = _canonical_geom(packed.geometry(int(i))) == want
        return out
    if isinstance(f, During):
        col = _prop_column(batch, f.prop)
        mask = np.ones(n, dtype=bool)
        if f.lo_ms is not None:
            mask &= _safe_compare(col, f.lo_ms, ">=")
        if f.hi_ms is not None:
            mask &= _safe_compare(col, f.hi_ms, "<=")
        return mask
    if isinstance(f, PropertyCompare):
        col = _prop_column(batch, f.prop)
        if f.op == "=":
            return np.asarray(col == f.value)
        if f.op == "<>":
            mask = np.asarray(col != f.value)
            if col.dtype == object:
                # a missing (None) value matches nothing, <> included
                mask &= np.array([v is not None for v in col])
            return mask
        return _safe_compare(col, f.value, f.op)
    if isinstance(f, Between):
        col = _prop_column(batch, f.prop)
        return _safe_compare(col, f.lo, ">=") & _safe_compare(col, f.hi, "<=")
    if isinstance(f, In):
        col = _prop_column(batch, f.prop)
        # one hashed pass instead of a scan per value (high-cardinality
        # joins feed thousands of values); np.isin promotes dtypes the
        # same way `col == v` does, so semantics match the loop below
        if len(f.values) > 4:
            if col.dtype == object:
                return np.isin(col.astype(str),
                               np.array([str(v) for v in f.values]))
            vals = np.array(list(f.values))
            # only when value dtype is compatible with the column: a mixed
            # list like [1, 'a'] promotes to '<U21', and np.isin would then
            # compare numbers to strings and silently match nothing
            if (vals.dtype != object
                    and (vals.dtype.kind == col.dtype.kind
                         or (vals.dtype.kind in "iuf"
                             and col.dtype.kind in "iuf"))):
                return np.isin(col, vals)
        mask = np.zeros(n, dtype=bool)
        for v in f.values:
            mask |= col == v
        return mask
    if isinstance(f, IdFilter):
        wanted = set(f.ids)
        return np.array([str(v) in wanted for v in batch.ids], dtype=bool)
    if isinstance(f, Like):
        col = _prop_column(batch, f.prop)
        rx = _like_regex(f.pattern, f.case_insensitive)
        return np.array([v is not None and bool(rx.match(str(v)))
                         for v in col], dtype=bool)
    raise NotImplementedError(f"cannot evaluate {type(f).__name__}")
