"""Filter algebra: predicate AST, ECQL text parsing, geometry/interval
extraction and vectorized evaluation.

The capability surface of the reference's ``geomesa-filter`` module
(FilterHelper extraction at geomesa-filter/.../FilterHelper.scala:102/151,
CNF/DNF rewrites at package.scala:52/171, FastFilterFactory optimized
evaluation) rebuilt for columnar data: filters evaluate as numpy masks
over whole FeatureBatches instead of per-row CQL interpretation.
"""

from .ast import (
    And,
    Attribute,
    BBox,
    Between,
    Contains,
    During,
    DWithin,
    Exclude,
    Filter,
    IdFilter,
    In,
    Include,
    Intersects,
    Like,
    Not,
    Or,
    PropertyCompare,
    Within,
)
from .ecql import parse_ecql
from .evaluate import evaluate_filter
from .extract import FilterValues, extract_geometries, extract_intervals, to_cnf

__all__ = [
    "And", "Attribute", "BBox", "Between", "Contains", "During", "DWithin",
    "Exclude", "Filter", "IdFilter", "In", "Include", "Intersects", "Like", "Not", "Or",
    "PropertyCompare", "Within", "parse_ecql", "evaluate_filter",
    "FilterValues", "extract_geometries", "extract_intervals", "to_cnf",
]
