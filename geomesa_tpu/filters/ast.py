"""Filter AST: the framework's predicate language.

Replaces the reference's dependency on GeoTools ``org.opengis.filter``
objects with small immutable dataclasses.  The node set covers what the
reference's planner understands (FilterHelper / strategy heuristics):
spatial (BBOX/INTERSECTS/CONTAINS/WITHIN/DWITHIN), temporal (DURING,
BEFORE/AFTER via comparisons), attribute comparisons, logical combinators
and the INCLUDE/EXCLUDE constants.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence

from ..geometry.types import Envelope, Geometry

__all__ = [
    "Filter", "Include", "Exclude", "And", "Or", "Not", "BBox", "Intersects",
    "Contains", "Within", "DWithin", "GeomEquals", "Touches",
    "Crosses", "Overlaps", "During",
    "PropertyCompare", "Between", "In", "IdFilter", "Like", "Attribute",
]


@dataclass(frozen=True)
class Attribute:
    """A property reference by name."""
    name: str


class Filter:
    """Base class for all filter nodes."""

    def __and__(self, other: "Filter") -> "Filter":
        return And((self, other))

    def __or__(self, other: "Filter") -> "Filter":
        return Or((self, other))

    def __invert__(self) -> "Filter":
        return Not(self)


@dataclass(frozen=True)
class _Include(Filter):
    def __repr__(self):
        return "INCLUDE"


@dataclass(frozen=True)
class _Exclude(Filter):
    def __repr__(self):
        return "EXCLUDE"


Include = _Include()
Exclude = _Exclude()


@dataclass(frozen=True)
class And(Filter):
    filters: tuple

    def __post_init__(self):
        object.__setattr__(self, "filters", tuple(self.filters))


@dataclass(frozen=True)
class Or(Filter):
    filters: tuple

    def __post_init__(self):
        object.__setattr__(self, "filters", tuple(self.filters))


@dataclass(frozen=True)
class Not(Filter):
    filter: Filter


@dataclass(frozen=True)
class BBox(Filter):
    prop: str
    xmin: float
    ymin: float
    xmax: float
    ymax: float

    @property
    def envelope(self) -> Envelope:
        return Envelope(self.xmin, self.ymin, self.xmax, self.ymax)


@dataclass(frozen=True)
class Intersects(Filter):
    prop: str
    geometry: Geometry


@dataclass(frozen=True)
class Contains(Filter):
    """Query geometry contains the feature geometry? No — CQL CONTAINS(prop, g)
    means the feature geometry contains g."""
    prop: str
    geometry: Geometry


@dataclass(frozen=True)
class Within(Filter):
    """Feature geometry within the query geometry."""
    prop: str
    geometry: Geometry


@dataclass(frozen=True)
class DWithin(Filter):
    """Feature geometry within ``distance`` of the query geometry.

    ``distance`` is in degrees unless ``meters`` is set (the ECQL units
    suffix, converted via the reference's meters multiplier,
    GeometryProcessing.metersMultiplier/distanceDegrees)."""
    prop: str
    geometry: Geometry
    distance: float
    meters: bool = False

    @property
    def degrees(self) -> float:
        """Covering degree-space equivalent of the distance (the larger
        lon-degree equivalent at the geometry's latitude, mirroring the
        reference's buffer-by-east-degrees rewrite)."""
        if not self.meters:
            return self.distance
        import math
        env = self.geometry.envelope
        lat = min(89.0, max(abs(env.ymin), abs(env.ymax)))
        return self.distance / (111_320.0 * max(0.017, math.cos(math.radians(lat))))


@dataclass(frozen=True)
class Touches(Filter):
    """Boundaries meet, interiors do not (CQL TOUCHES)."""
    prop: str
    geometry: Geometry


@dataclass(frozen=True)
class Crosses(Filter):
    """Interiors intersect in a lower dimension (CQL CROSSES)."""
    prop: str
    geometry: Geometry


@dataclass(frozen=True)
class Overlaps(Filter):
    """Same-dimension interiors partially shared (CQL OVERLAPS)."""
    prop: str
    geometry: Geometry


@dataclass(frozen=True)
class GeomEquals(Filter):
    """Feature geometry exactly equals the query geometry (ECQL EQUALS)."""
    prop: str
    geometry: Geometry


@dataclass(frozen=True)
class During(Filter):
    """Temporal interval predicate: lo <= t <= hi (epoch millis).

    ``None`` bounds are open (the reference models these as ±∞ bounds in
    extractIntervals)."""
    prop: str
    lo_ms: int | None
    hi_ms: int | None


@dataclass(frozen=True)
class PropertyCompare(Filter):
    """prop <op> literal with op in =, <>, <, <=, >, >=."""
    prop: str
    op: str
    value: Any

    _OPS = ("=", "<>", "<", "<=", ">", ">=")

    def __post_init__(self):
        if self.op not in self._OPS:
            raise ValueError(f"bad comparison op {self.op!r}")


@dataclass(frozen=True)
class Between(Filter):
    prop: str
    lo: Any
    hi: Any


@dataclass(frozen=True)
class In(Filter):
    prop: str
    values: tuple

    def __post_init__(self):
        object.__setattr__(self, "values", tuple(self.values))


@dataclass(frozen=True)
class IdFilter(Filter):
    """Feature-id filter (GeoTools ``Filter.id`` / bare ``IN ('id1', …)``) —
    served by the record/id index."""
    ids: tuple

    def __post_init__(self):
        object.__setattr__(self, "ids", tuple(str(i) for i in self.ids))


@dataclass(frozen=True)
class Like(Filter):
    """SQL LIKE with % and _ wildcards (the attribute-index prefix-scan
    candidate in the reference's planner)."""
    prop: str
    pattern: str
    case_insensitive: bool = False
