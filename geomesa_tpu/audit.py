"""Query auditing: per-query event records.

The analog of the reference's audit subsystem (index/audit/QueryEvent.scala,
accumulo/audit/AccumuloAuditService.scala — async writes of per-query
records with filter, hints, timings, hit counts into a store table, with
REST readback via geomesa-web's QueryAuditEndpoint).  Here events go to a
pluggable writer: in-memory ring (tests/inspection) or JSONL file.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field

__all__ = ["QueryEvent", "AuditWriter", "InMemoryAuditWriter",
           "JsonlAuditWriter"]


@dataclass
class QueryEvent:
    """One executed query (QueryEvent.scala fields, minus the KV row)."""

    store: str
    type_name: str
    user: str
    filter: str
    hints: dict = field(default_factory=dict)
    plan_time_ms: float = 0.0
    scan_time_ms: float = 0.0
    hits: int = 0
    timestamp: float = field(default_factory=time.time)
    #: correlating trace id (obs/trace.py) — "" when the query ran
    #: untraced; a slow audit record joins to its full span tree via
    #: ``GET /traces/<trace_id>``
    trace_id: str = ""

    def to_json(self) -> str:
        return json.dumps(asdict(self), default=str)


class AuditWriter:
    """Base: synchronous no-op; subclasses persist events."""

    def write_event(self, event: QueryEvent) -> None:  # pragma: no cover
        pass


class InMemoryAuditWriter(AuditWriter):
    """Bounded in-memory event log."""

    def __init__(self, capacity: int = 10_000):
        self.events: deque[QueryEvent] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def write_event(self, event: QueryEvent) -> None:
        with self._lock:
            self.events.append(event)

    def query_events(self, type_name: str | None = None,
                     since: float | None = None) -> list[QueryEvent]:
        with self._lock:
            out = list(self.events)
        if type_name is not None:
            out = [e for e in out if e.type_name == type_name]
        if since is not None:
            out = [e for e in out if e.timestamp >= since]
        return out


class JsonlAuditWriter(AuditWriter):
    """Append events as JSON lines (the file-sink analog of the
    reference's async audit table writes).

    The file handle stays open (line-buffered) so the query hot path pays
    one buffered write, not an open/close round trip, and the JSON
    serialization happens outside the lock.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._file = None

    def write_event(self, event: QueryEvent) -> None:
        line = event.to_json() + "\n"
        with self._lock:
            if self._file is None:
                self._file = open(self.path, "a", buffering=1)
            self._file.write(line)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
