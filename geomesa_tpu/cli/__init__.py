"""Command-line tools: the ``geomesa-tpu`` CLI.

Capability match for the reference's JCommander command tree
(geomesa-tools/.../Runner.scala:21-146: create-schema / ingest / export /
explain / stats-* / delete-*), argparse-based, operating on a filesystem
catalog directory instead of a cluster connection.
"""

from .main import main

__all__ = ["main"]
