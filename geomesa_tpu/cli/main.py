"""geomesa-tpu CLI entry point.

Usage examples (mirroring the reference's tools):

    geomesa-tpu create-schema -c /data/cat -f gdelt \
        -s "actor:String,dtg:Date,*geom:Point;geomesa.z3.interval=week"
    geomesa-tpu ingest -c /data/cat -f gdelt -C conv.json events.csv
    geomesa-tpu export -c /data/cat -f gdelt -q "BBOX(geom,-10,35,15,52)" -F geojson
    geomesa-tpu explain -c /data/cat -f gdelt -q "..."
    geomesa-tpu stats-count / stats-bounds / stats-top-k
    geomesa-tpu get-type-names / describe-schema / remove-schema
"""

from __future__ import annotations

import argparse
import json
import sys


def _store(args):
    from ..datastore import TpuDataStore
    return TpuDataStore(args.catalog)


def cmd_create_schema(args):
    ds = _store(args)
    sft = ds.create_schema(args.feature_name, args.spec)
    print(f"created schema {sft.name}: {sft.spec_string()}")


def cmd_get_type_names(args):
    for n in _store(args).type_names:
        print(n)


def cmd_describe_schema(args):
    sft = _store(args).get_schema(args.feature_name)
    print(f"{sft.name}")
    for a in sft.attributes:
        star = "*" if a.name == sft.default_geom else " "
        opts = " ".join(f"{k}={v}" for k, v in a.options.items())
        print(f"  {star}{a.name}: {a.type} {opts}".rstrip())
    for k, v in sft.user_data.items():
        print(f"  {k}={v}")


def cmd_remove_schema(args):
    _store(args).remove_schema(args.feature_name)
    print(f"removed {args.feature_name}")


def cmd_migrate_schema(args):
    """Upgrade a schema's index layouts to the current versions (the
    reference's index-format migration commands)."""
    ds = _store(args)
    old = ds.migrate_schema(args.feature_name)
    from ..datastore import CURRENT_INDEX_VERSIONS
    changed = {k: (v, CURRENT_INDEX_VERSIONS[k])
               for k, v in old.items() if v != CURRENT_INDEX_VERSIONS[k]}
    if not changed:
        print(f"{args.feature_name}: already at current index versions")
    else:
        for k, (a, b) in sorted(changed.items()):
            print(f"{args.feature_name}: {k} v{a} -> v{b}")


def cmd_index_versions(args):
    """Show a schema's recorded index-layout versions."""
    ds = _store(args)
    store = ds._store(args.feature_name)
    from ..index.registry import supported_indices
    supported = set(supported_indices(store.sft))
    for name, v in sorted(store.index_versions.items()):
        mark = "" if name in supported else "  (not applicable)"
        print(f"{name}: v{v}{mark}")


def cmd_ingest(args):
    ds = _store(args)
    sft = ds.get_schema(args.feature_name)
    from ..io.converters import EvaluationContext, converter_from_config

    total = 0
    ec = EvaluationContext()
    if args.converter:
        with open(args.converter) as f:
            conv = converter_from_config(sft, json.load(f))
        for path in args.files:
            if conv.wants_path:
                # shapefile/jdbc sources are paths (sidecar files, db handles)
                batch = conv.convert(path, ec)
            else:
                with open(path, "rb") as f:
                    batch = conv.convert(f.read(), ec)
            if len(batch):
                total += ds.write(args.feature_name, batch)
    else:
        from ..io.export import from_parquet
        for path in args.files:
            if not path.endswith(".parquet"):
                raise SystemExit(
                    "ingest without -C/--converter supports parquet only")
            batch = from_parquet(path, sft)
            total += ds.write(args.feature_name, batch)
            ec.success += len(batch)
    ds.flush(args.feature_name)
    print(f"ingested {total} features ({ec.failure} failed)")


def cmd_export(args):
    ds = _store(args)
    from ..planning.planner import Query
    q = Query.of(args.cql, max_features=args.max_features)
    batch = ds.query(args.feature_name, q)
    fmt = args.format
    if fmt == "csv":
        from ..io.export import to_csv
        out = to_csv(batch)
        _write_out(args.output, out)
    elif fmt == "geojson":
        from ..io.export import to_geojson
        _write_out(args.output, to_geojson(batch))
    elif fmt == "parquet":
        from ..io.export import to_parquet
        if not args.output:
            raise SystemExit("parquet export requires -o/--output")
        to_parquet(batch, args.output)
    elif fmt == "arrow":
        import pyarrow as pa
        from ..io.export import to_arrow
        if not args.output:
            raise SystemExit("arrow export requires -o/--output")
        with pa.OSFile(args.output, "wb") as sink:
            table = to_arrow(batch)
            with pa.ipc.new_file(sink, table.schema) as w:
                w.write_table(table)
    elif fmt == "gml":
        from ..io.export import to_gml
        _write_out(args.output, to_gml(batch))
    elif fmt == "leaflet":
        from ..io.export import to_leaflet
        _write_out(args.output, to_leaflet(batch))
    elif fmt == "avro":
        from ..io.avro import to_avro
        if not args.output:
            raise SystemExit("avro export requires -o/--output")
        to_avro(batch, args.output)
    elif fmt == "shp":
        from ..io.export import to_shapefile
        if not args.output:
            raise SystemExit("shp export requires -o/--output")
        to_shapefile(batch, args.output)
    elif fmt == "bin":
        from ..io.bin_encoder import encode_bin
        x, y = batch.geom_xy()
        dtg = (batch.column(batch.sft.dtg_field)
               if batch.sft.dtg_field else [0] * len(batch))
        track = (batch.column(args.track) if args.track else None)
        blob = encode_bin(x, y, dtg, track=track)
        if not args.output:
            sys.stdout.buffer.write(blob)
        else:
            with open(args.output, "wb") as f:
                f.write(blob)
    else:
        raise SystemExit(f"unknown format {fmt!r}")
    if args.output:
        print(f"exported {len(batch)} features to {args.output}")


def _write_out(path, text):
    if path:
        with open(path, "w") as f:
            f.write(text)
    else:
        print(text)


def cmd_explain(args):
    print(_store(args).explain(args.feature_name, args.cql))


def cmd_sql(args):
    """Run a SELECT statement (the geomesa-spark-sql user surface)."""
    import numpy as np

    from ..sql import sql_query
    out = sql_query(_store(args), args.statement)
    if isinstance(out, int):
        print(out)
        return
    if isinstance(out, dict):
        keys = list(out)
        print(",".join(keys))
        if any(np.ndim(out[k]) for k in keys):  # GROUP BY arrays
            for row in zip(*(out[k] for k in keys)):
                print(",".join(str(v) for v in row))
        else:                                   # global aggregates
            print(",".join("" if out[k] is None else str(out[k])
                           for k in keys))
        return
    names = [a.name for a in out.sft.attributes
             if not a.is_geometry and a.name in out.columns]
    gname = out.sft.default_geom
    packed = out.geoms is not None
    points = (gname and not packed and f"{gname}_x" in out.columns)
    print(",".join(["fid"] + names + ([gname] if packed or points else [])))
    from ..geometry.wkt import geometry_to_wkt
    xs = ys = None
    if points:
        xs, ys = out.geom_xy(gname)
    for i in range(len(out)):
        row = [str(out.ids[i])]
        row += [str(out.column(n)[i]) for n in names]
        if packed:
            row.append(geometry_to_wkt(out.geoms.geometry(i)))
        elif points:
            row.append(f"POINT ({float(xs[i])} {float(ys[i])})")
        print(",".join(row))


def cmd_stats_count(args):
    ds = _store(args)
    q = args.cql if args.cql else None
    print(ds.get_count(args.feature_name, q))


def cmd_stats_bounds(args):
    env = _store(args).get_bounds(args.feature_name)
    print("none" if env is None else env.as_tuple())


def cmd_stats_top_k(args):
    ds = _store(args)
    from ..process import stats_process
    s = stats_process(ds, args.feature_name, args.cql or "INCLUDE",
                      f"TopK({args.attribute})")
    for v, c in s.topk(args.k):
        print(f"{v}\t{c}")


def cmd_stats_histogram(args):
    ds = _store(args)
    from ..process import stats_process
    lo, hi = args.bounds.split(",") if args.bounds else (None, None)
    if lo is None:
        b = ds.get_attribute_bounds(args.feature_name, args.attribute)
        if b is None:
            raise SystemExit("no bounds available; pass --bounds lo,hi")
        lo, hi = b
    s = stats_process(ds, args.feature_name, args.cql or "INCLUDE",
                      f"Histogram({args.attribute},{args.bins},{lo},{hi})")
    for i, c in enumerate(s.counts):
        print(f"bin {i}\t{c}")


def cmd_stats_analyze(args):
    """Recompute stats from the stored data and persist them (the
    reference's stats-analyze command / StatsRunner)."""
    ds = _store(args)
    n = ds.stats_analyze(args.feature_name)
    print(f"analyzed {args.feature_name}: {n} features, stats persisted")


def cmd_age_off(args):
    """Expire old rows (tools age-off command analog)."""
    from ..age_off import age_off
    ds = _store(args)
    n = age_off(ds, args.feature_name, retention=args.retention,
                dry_run=args.dry_run)
    if args.dry_run:
        print(f"would age off {n} features from {args.feature_name}")
    else:
        ds.flush(args.feature_name)
        print(f"aged off {n} features from {args.feature_name}")


def cmd_fs_partitions(args):
    """List or compact a filesystem store's partitions (the reference's
    manage-partitions command over FSDS partition schemes)."""
    from ..fs import FileSystemDataStore
    fs = FileSystemDataStore(args.root)
    if args.compact:
        fs.compact(args.feature_name)
        print(f"compacted {args.feature_name}")
    info = fs.partition_info(args.feature_name)
    for name in sorted(info):
        print(f"{name}\t{info[name]['files']} file(s)"
              f"\t{info[name]['features']} features")


def cmd_flush(args):
    """Persist a schema's rows to the catalog (parquet; lean schemas
    write chunked crash-safe snapshots) — the checkpoint command."""
    ds = _store(args)
    ds.flush(args.feature_name)
    st = ds._store(args.feature_name)
    n = len(st.batch) if st.batch is not None else 0
    kind = "lean snapshot" if st.lean else "parquet"
    print(f"flushed {n} features of {args.feature_name} ({kind})")


def cmd_version(args):
    from .. import __version__
    print(f"geomesa-tpu {__version__}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="geomesa-tpu",
                                description="TPU-native spatio-temporal index tools")
    sub = p.add_subparsers(dest="command", required=True)

    def add(name, fn, **kw):
        sp = sub.add_parser(name, **kw)
        sp.set_defaults(fn=fn)
        return sp

    def catalog(sp, feature=True):
        sp.add_argument("-c", "--catalog", required=True,
                        help="catalog directory")
        if feature:
            sp.add_argument("-f", "--feature-name", required=True)

    sp = add("create-schema", cmd_create_schema, help="create a feature schema")
    catalog(sp)
    sp.add_argument("-s", "--spec", required=True, help="schema spec string")

    sp = add("get-type-names", cmd_get_type_names, help="list schemas")
    catalog(sp, feature=False)

    sp = add("describe-schema", cmd_describe_schema, help="describe a schema")
    catalog(sp)

    sp = add("remove-schema", cmd_remove_schema, help="remove a schema")
    catalog(sp)

    sp = add("migrate-schema", cmd_migrate_schema,
             help="upgrade index layouts to current versions")
    catalog(sp)

    sp = add("index-versions", cmd_index_versions,
             help="show a schema's index-layout versions")
    catalog(sp)

    sp = add("sql", cmd_sql, help="run a SELECT statement")
    catalog(sp, feature=False)
    sp.add_argument("statement", help="SELECT ... FROM <schema> ...")

    sp = add("ingest", cmd_ingest, help="ingest files")
    catalog(sp)
    sp.add_argument("-C", "--converter", help="converter config (json)")
    sp.add_argument("files", nargs="+")

    sp = add("export", cmd_export, help="query + export features")
    catalog(sp)
    sp.add_argument("-q", "--cql", default="INCLUDE")
    sp.add_argument("-F", "--format", default="csv",
                    choices=["csv", "geojson", "parquet", "arrow", "bin",
                             "gml", "leaflet", "avro", "shp"])
    sp.add_argument("-o", "--output")
    sp.add_argument("-m", "--max-features", type=int)
    sp.add_argument("--track", help="track-id attribute for bin export")

    sp = add("fs-partitions", cmd_fs_partitions,
             help="list/compact filesystem-store partitions")
    sp.add_argument("-r", "--root", required=True,
                    help="filesystem store root directory")
    sp.add_argument("-f", "--feature-name", required=True)
    sp.add_argument("--compact", action="store_true")

    sp = add("stats-analyze", cmd_stats_analyze,
             help="recompute and persist stats")
    catalog(sp)

    sp = add("flush", cmd_flush,
             help="checkpoint a schema's rows to the catalog")
    catalog(sp)

    sp = add("age-off", cmd_age_off, help="expire rows older than a "
                                          "retention period")
    catalog(sp)
    sp.add_argument("-r", "--retention", required=True,
                    help='e.g. "7 days", "12 hours"')
    sp.add_argument("--dry-run", action="store_true")

    sp = add("explain", cmd_explain, help="explain query planning")
    catalog(sp)
    sp.add_argument("-q", "--cql", required=True)

    sp = add("stats-count", cmd_stats_count, help="feature count")
    catalog(sp)
    sp.add_argument("-q", "--cql")

    sp = add("stats-bounds", cmd_stats_bounds, help="spatial bounds")
    catalog(sp)

    sp = add("stats-top-k", cmd_stats_top_k, help="top values of an attribute")
    catalog(sp)
    sp.add_argument("-a", "--attribute", required=True)
    sp.add_argument("-k", type=int, default=10)
    sp.add_argument("-q", "--cql")

    sp = add("stats-histogram", cmd_stats_histogram, help="attribute histogram")
    catalog(sp)
    sp.add_argument("-a", "--attribute", required=True)
    sp.add_argument("--bins", type=int, default=20)
    sp.add_argument("--bounds", help="lo,hi")
    sp.add_argument("-q", "--cql")

    add("version", cmd_version, help="print version")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    args.fn(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
