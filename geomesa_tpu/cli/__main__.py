"""``python -m geomesa_tpu.cli`` — the tools runner entry point
(tools/Runner.scala:21-26 analog)."""

import sys

from .main import main

if __name__ == "__main__":
    sys.exit(main())
