"""Typed system properties — the framework's config/flag system.

Mirrors the reference's three-tier config model (SURVEY.md §5): this module
is tier 1, the equivalent of ``GeoMesaSystemProperties.SystemProperty``
(geomesa-utils/.../conf/GeoMesaSystemProperties.scala:17-27) and the query
knobs in ``QueryProperties`` (geomesa-index-api/.../conf/
QueryProperties.scala:17-44).  Values resolve, in order: programmatic
override → environment variable (dots become underscores, upper-cased) →
default.  Tier 2 is per-schema user data (features/feature_type.py), tier
3 per-query hints (index/query options).
"""

from __future__ import annotations

import os
import threading
import warnings
from dataclasses import dataclass
from typing import Any

__all__ = ["SystemProperty", "SchemaOption", "QueryProperties",
           "ObsProperties", "ArrowProperties", "SchemaProperties",
           "ConfigProperties", "ResilienceProperties",
           "DensityProperties", "PlanningProperties",
           "set_property", "clear_property", "config_generation",
           "known_option_names", "check_option_name",
           "UnknownOptionWarning"]

_overrides: dict[str, Any] = {}
_lock = threading.Lock()
#: bumped on every programmatic override change — hot paths (obs
#: tracing) cache resolved property values keyed on this so they pay a
#: plain int read per call instead of the override lock, while
#: ``set_property`` still takes effect immediately
_generation = 0

#: the option registry (ISSUE 13): every declared knob — tier-1
#: SystemProperty AND tier-2 SchemaOption — keyed by name.  Filled by
#: ``_register_declarations`` at the bottom of this module; the static
#: analyzer (geomesa_tpu/analysis, check ``config-option``) reads the
#: SAME declarations off this file's AST, so the static and runtime
#: halves cannot drift.
_REGISTRY: dict[str, Any] = {}
#: names already warned about (one warning per unknown name, not one
#: per lookup)
_warned: set[str] = set()


class UnknownOptionWarning(UserWarning):
    """A ``geomesa.*`` option name nobody declared — almost always a
    typo that would otherwise silently read the default forever."""


def known_option_names() -> frozenset:
    """Every declared option name (system properties + schema
    options)."""
    return frozenset(_REGISTRY)


def check_option_name(name: str, *, raise_in_strict: bool = True) -> None:
    """Strict-option gate (ISSUE 13 satellite): a ``geomesa.*`` name
    that is not declared in this module warns — and RAISES under
    ``geomesa.config.strict`` — so a typo'd option fails loudly
    instead of silently defaulting.  Non-``geomesa.`` names pass
    untouched (embedders may ride the override store).
    ``raise_in_strict=False`` demotes strict mode to the warning
    (``clear_property``: removing a stale override is inherently safe
    and must stay possible WHILE strict is on)."""
    if not name.startswith("geomesa.") or name in _REGISTRY \
            or not _REGISTRY:
        return
    msg = (f"unregistered option {name!r}: not declared in "
           f"geomesa_tpu/config.py (typo?) — known names: "
           f"docs/configuration.md")
    if raise_in_strict and ConfigProperties.STRICT.to_bool():
        raise ValueError(msg)
    if name not in _warned:
        _warned.add(name)
        warnings.warn(msg, UnknownOptionWarning, stacklevel=3)


def config_generation() -> int:
    return _generation


def set_property(name: str, value) -> None:
    global _generation
    check_option_name(name)
    with _lock:
        _overrides[name] = value
        _generation += 1


def clear_property(name: str) -> None:
    global _generation
    check_option_name(name, raise_in_strict=False)
    with _lock:
        _overrides.pop(name, None)
        _generation += 1


@dataclass(frozen=True)
class SystemProperty:
    """A named, typed knob with env-var and programmatic override."""

    name: str
    default: Any

    @property
    def env_var(self) -> str:
        return self.name.replace(".", "_").upper()

    def get(self):
        check_option_name(self.name)
        with _lock:
            if self.name in _overrides:
                return _overrides[self.name]
        raw = os.environ.get(self.env_var)
        if raw is None:
            return self.default
        if isinstance(self.default, bool):
            return raw.strip().lower() in ("1", "true", "yes")
        if isinstance(self.default, int):
            return int(raw)
        if isinstance(self.default, float):
            return float(raw)
        return raw

    def to_int(self) -> int:
        return int(self.get())

    def to_bool(self) -> bool:
        return bool(self.get())


@dataclass(frozen=True)
class SchemaOption:
    """A declared tier-2 option: a ``geomesa.*`` key read from a
    schema's user data (``features/feature_type.py``) rather than the
    process environment.  Declared here purely so the option REGISTRY
    is complete — both the runtime strict mode and the static
    ``config-option`` check resolve every ``"geomesa.*"`` literal in
    the tree against these declarations; resolution itself stays where
    it always was (``sft.user_data.get(...)``)."""

    name: str
    default: Any = None
    doc: str = ""


class ConfigProperties:
    """The config system's own knobs."""

    #: strict option mode: unregistered ``geomesa.*`` names RAISE at
    #: ``set_property``/lookup instead of warning (CI wants typos
    #: fatal; interactive embedders may prefer the warning)
    STRICT = SystemProperty("geomesa.config.strict", False)


class SchemaProperties:
    """Tier-2 per-schema option declarations (the user-data keys the
    datastore and feature types honor — docs/configuration.md)."""

    #: index layout profile: ``lean`` selects the tiered SoA lean
    #: index families (docs/design.md)
    INDEX_PROFILE = SchemaOption("geomesa.index.profile", "",
                                 "index layout profile ('lean')")
    #: explicit index-version pin list, or 'current'
    INDEX_VERSIONS = SchemaOption("geomesa.index.versions", "",
                                  "pin index versions")
    #: which attribute is THE temporal axis (else first Date attr)
    INDEX_DTG = SchemaOption("geomesa.index.dtg", "",
                             "temporal attribute override")
    #: comma list restricting which index kinds build
    INDICES_ENABLED = SchemaOption("geomesa.indices.enabled", "",
                                   "restrict built indexes")
    #: z3 time-bin interval: 'day' | 'week' | 'month' | 'year'
    Z3_INTERVAL = SchemaOption("geomesa.z3.interval", "week",
                               "z3 time-bin period")
    #: xz curve resolution (g in the XZ-ordering papers)
    XZ_PRECISION = SchemaOption("geomesa.xz.precision", 12,
                                "xz curve precision")
    #: feature-id minting strategy ('z3' = locality-preserving)
    FID_STRATEGY = SchemaOption("geomesa.fid.strategy", "",
                                "feature-id strategy")
    #: age-off retention expression (age_off.py)
    AGE_OFF = SchemaOption("geomesa.age.off", "",
                           "age-off retention window")
    #: registered query interceptors (planning/interceptor.py)
    QUERY_INTERCEPTORS = SchemaOption("geomesa.query.interceptors", "",
                                      "query interceptor chain")
    #: lean-profile HBM budget in bytes for this schema's device tiers
    LEAN_HBM_BUDGET = SchemaOption("geomesa.lean.hbm.budget", 0,
                                   "lean device-tier byte budget")
    #: lean LSM size-tier factor (0 disables auto-compaction)
    LEAN_COMPACTION_FACTOR = SchemaOption(
        "geomesa.lean.compaction.factor", 4, "LSM size-tier factor")
    #: lean generation capacity in slots (rollover threshold)
    LEAN_GENERATION_SLOTS = SchemaOption(
        "geomesa.lean.generation.slots", 0, "generation slot capacity")


class QueryProperties:
    """Planner guardrails (QueryProperties.scala:17-44 equivalents)."""

    #: target number of scan ranges per query (split across time bins)
    SCAN_RANGES_TARGET = SystemProperty("geomesa.scan.ranges.target", 2000)
    #: query timeout in seconds; 0 disables (ThreadManagement reaper analog)
    QUERY_TIMEOUT = SystemProperty("geomesa.query.timeout", 0)
    #: skip the exact geometry re-check and trust index-key resolution
    #: accepted for parity with the reference (QueryProperties.scala); a
    #: deliberate no-op here: the exact double-precision re-check is FUSED
    #: into the scan kernel's candidate mask, so "loose" would save
    #: nothing — results are always exact at zero extra cost
    LOOSE_BBOX = SystemProperty("geomesa.query.loose.bounding.box", False)
    #: refuse queries that would scan the full table (opt-in, like the
    #: reference's BlockFullTableScans)
    BLOCK_FULL_TABLE_SCANS = SystemProperty(
        "geomesa.scan.block.full.table", False)
    #: cost strategy: 'stats' (cost-based) or 'index' (heuristic priority)
    COST_TYPE = SystemProperty("geomesa.query.cost.type", "stats")
    #: use the Pallas candidate-filter kernel on TPU backends (falls back
    #: to the fused XLA path automatically if lowering fails)
    PALLAS_SCAN = SystemProperty("geomesa.scan.pallas", True)


class ObsProperties:
    """Observability knobs (the ``geomesa.obs.*`` option family —
    docs/observability.md).  Sampler kind and the slow threshold are
    re-read per trace so tests and operators can flip them live via
    :func:`set_property`; capacities are read once at tracer
    construction."""

    #: master switch — off makes every span a shared no-op
    ENABLED = SystemProperty("geomesa.obs.enabled", True)
    #: root-span sampling: 'always', 'ratio', 'slow' (retain only
    #: slower-than-threshold traces), or 'never'
    SAMPLER = SystemProperty("geomesa.obs.sampler", "always")
    #: fraction of root spans recorded under the 'ratio' sampler
    SAMPLE_RATIO = SystemProperty("geomesa.obs.sample.ratio", 0.1)
    #: slow-query threshold in ms: traces at/over it land in the slow
    #: log (and are what the 'slow' sampler retains); <= 0 disables
    SLOW_MS = SystemProperty("geomesa.obs.slow.ms", 500.0)
    #: ring-buffer exporter capacity (traces)
    TRACE_CAPACITY = SystemProperty("geomesa.obs.trace.capacity", 256)
    #: JSONL trace-sink size cap in bytes: the exporter rotates so the
    #: live file plus one predecessor stay within this total (long
    #: bench runs must not grow the sink without bound); <= 0 disables
    #: rotation.  Re-read per export, so it is live-tunable.
    TRACE_MAX_BYTES = SystemProperty("geomesa.obs.trace.max_bytes",
                                     128 * 2 ** 20)
    #: slow-query log capacity (traces)
    SLOW_CAPACITY = SystemProperty("geomesa.obs.slow.capacity", 64)
    #: count XLA backend compiles via the jax.monitoring listener
    #: (jax.compile.* metrics); the classic silent TPU perf cliff
    RECOMPILE_TRACK = SystemProperty("geomesa.obs.recompile.track", True)
    #: access-temperature tracking (obs/heat.py): per-(schema, index,
    #: generation) touch counters folded into a decayed temperature
    #: score — the workload data plane the tier autopilot consumes.
    #: Off reduces every record site to one cached bool read.
    HEAT_ENABLED = SystemProperty("geomesa.obs.heat.enabled", True)
    #: temperature decay constant τ in seconds: each touch contributes
    #: ``exp(-(now - t)/τ)`` to a generation's score, so a touch fades
    #: to ~37% after τ seconds (half-life τ·ln 2 ≈ 0.69τ)
    HEAT_TAU_S = SystemProperty("geomesa.obs.heat.tau.s", 600.0)
    #: hard bound on tracked (schema, index, generation) entries —
    #: beyond it the coldest entries evict (bounded memory under
    #: generation churn)
    HEAT_MAX_ENTRIES = SystemProperty("geomesa.obs.heat.max.entries",
                                      8192)
    #: write-path device attribution: when a write runs under a
    #: RECORDING span, block on the live index generation at the end of
    #: the write so the trace carries honest block-until-ready device
    #: ms (the scan-span discipline).  Blocking only forces work that
    #: must complete anyway; off keeps appends fully pipelined even
    #: while traced
    WRITE_BLOCK = SystemProperty("geomesa.obs.write.block", True)
    #: background-job registry retention (obs/jobs.py): finished
    #: IngestJob/CompactionJob records kept for /debug/jobs
    JOBS_CAPACITY = SystemProperty("geomesa.obs.jobs.capacity", 128)
    #: /metrics.prom scrape cache: while a scrape is younger than this
    #: many ms, the next scrape reuses its rendered text instead of
    #: re-walking storage and re-publishing every gauge (aggressive
    #: scrapers must not hammer storage_report); <= 0 disables the
    #: cache (every scrape walks).  ``?mesh=1`` scrapes never cache —
    #: the mesh merge is a collective that must run when driven.
    SCRAPE_MIN_INTERVAL_MS = SystemProperty(
        "geomesa.obs.scrape.min.interval.ms", 0.0)
    #: hard cap on recorded spans per trace: past it, child spans
    #: yield the shared no-op and the root accumulates a
    #: ``spans.dropped`` count — a 10k-generation scan must not
    #: balloon the ring exporter; <= 0 disables the cap
    TRACE_MAX_SPANS = SystemProperty("geomesa.obs.trace.max.spans",
                                     4096)


class ArrowProperties:
    """Arrow-native streaming result path knobs (the ``geomesa.arrow.*``
    option family — docs/arrow.md, ISSUE 14).  All three are re-read
    per stream, so operators can tune a live serving process."""

    #: rows per emitted Arrow record batch on the streaming result path
    #: (``store.query_arrow`` default when ``chunk_rows`` is not passed;
    #: the reference's ArrowScan batch-size hint)
    CHUNK_ROWS = SystemProperty("geomesa.arrow.chunk.rows", 65536)
    #: distinct-value ceiling for AUTO dictionary encoding: a string
    #: attribute dictionary-encodes only while its observed cardinality
    #: (sampled on the first chunk) stays at/below this — past it the
    #: column ships as plain utf8 (a dictionary bigger than the data
    #: saves nothing and bloats every delta message)
    DICTIONARY_THRESHOLD = SystemProperty(
        "geomesa.arrow.dictionary.threshold", 1024)
    #: streaming-response flush threshold in bytes: the chunked
    #: Arrow-IPC web response (``/query?format=arrow``) coalesces
    #: encoded IPC messages until at least this many bytes are buffered
    #: before handing a chunk to the WSGI layer (tiny record batches
    #: must not become tiny socket writes); <= 0 flushes per batch
    STREAM_BUFFER_BYTES = SystemProperty(
        "geomesa.arrow.stream.buffer.bytes", 1 << 20)


class ResilienceProperties:
    """Resilience-layer knobs (ISSUE 16, geomesa_tpu/resilience):
    admission gating, degraded execution, and the deterministic
    fault-injection harness.  Everything defaults OFF — an unconfigured
    store behaves exactly as before this layer existed."""

    #: HBM admission ceiling in bytes: new queries shed (Backpressure)
    #: while the live ``storage.total.device_bytes`` gauge exceeds this;
    #: 0 disables the HBM check
    HBM_HEADROOM = SystemProperty("geomesa.resilience.hbm.headroom", 0)
    #: max concurrently-admitted queries per process; 0 = unbounded
    ADMISSION_MAX_CONCURRENT = SystemProperty(
        "geomesa.resilience.admission.max.concurrent", 0)
    #: how long an over-budget request may queue (ms) before shedding
    ADMISSION_QUEUE_MS = SystemProperty(
        "geomesa.resilience.admission.queue.ms", 50.0)
    #: bounded retries after a transient (RESOURCE_EXHAUSTED) device
    #: failure demotes the offending generation's payload to host
    RETRY_MAX = SystemProperty("geomesa.resilience.retry.max", 1)
    #: consecutive transient failures before a generation's device
    #: dispatch circuit opens (host-tier routing until cooldown)
    BREAKER_THRESHOLD = SystemProperty(
        "geomesa.resilience.breaker.threshold", 3)
    #: seconds an open breaker refuses device dispatch before half-open
    BREAKER_COOLDOWN_S = SystemProperty(
        "geomesa.resilience.breaker.cooldown.s", 30.0)
    #: armed fault points (resilience/faults.py): comma-separated
    #: ``point[:trigger][=kind]`` — bare point fires every hit, integer
    #: trigger fires on exactly the Nth hit, float < 1 fires with that
    #: seeded probability; kind is ``error`` (poison) or ``oom``
    #: (classified transient).  Empty disables injection entirely.
    FAULT_POINTS = SystemProperty("geomesa.resilience.fault.points", "")
    #: RNG seed for probabilistic fault triggers — same seed + same hit
    #: order = same injected failures (deterministic chaos runs)
    FAULT_SEED = SystemProperty("geomesa.resilience.fault.seed", 0)


class ServingProperties:
    """Fused serving plane knobs (ISSUE 17, geomesa_tpu/serving):
    query fusion — coalescing concurrent compatible queries into one
    batched device dispatch — and per-tenant fairness over it."""

    #: master switch for query fusion; False routes every request down
    #: the solo path untouched
    FUSE_ENABLED = SystemProperty("geomesa.serving.fuse.enabled", True)
    #: how long (ms) a batch leader lingers collecting riders before
    #: dispatching the fused batch
    FUSE_WINDOW_MS = SystemProperty("geomesa.serving.fuse.window.ms", 2.0)
    #: max requests fused into one batched dispatch; a full batch
    #: dispatches immediately without waiting out the window
    FUSE_MAX_BATCH = SystemProperty("geomesa.serving.fuse.max.batch", 64)
    #: per-tenant queued-request ceiling; a tenant at its ceiling sheds
    #: (Backpressure → 503) instead of growing the queue; 0 = unbounded
    TENANT_QUEUE_MAX = SystemProperty("geomesa.serving.tenant.queue.max", 0)
    #: deficit-round-robin quantum (window-count units) each tenant
    #: earns per batch-assembly pass — larger values trade fairness
    #: granularity for fewer scheduling rounds
    TENANT_QUANTUM = SystemProperty("geomesa.serving.tenant.quantum", 4)


class DensityProperties:
    """Density-pyramid knobs (ISSUE 18, docs/density.md): sealed
    generations precompute world-aligned multi-resolution density
    grids so whole-extent/zoomed-out heatmaps and ``/tiles/{z}/{x}/{y}``
    requests sum cached cells instead of rescanning history."""

    #: base pyramid resolution (cells per axis, power of two): each
    #: sealed generation's pyramid starts at a (base, base) world grid
    #: and halves down from there.  Tile requests whose effective world
    #: resolution exceeds the base fall back to the direct density scan
    PYRAMID_BASE = SystemProperty("geomesa.density.pyramid.base", 512)
    #: reduction-ladder depth; 0 = the full ladder down to 1×1
    PYRAMID_LEVELS = SystemProperty("geomesa.density.pyramid.levels", 0)
    #: byte ceiling for the per-index pyramid cache (the shared
    #: PartialCache LRU/invalidation policy density partials use)
    PYRAMID_CACHE_BYTES = SystemProperty(
        "geomesa.density.pyramid.cache.bytes", 256 * (1 << 20))
    #: build trigger: ``off`` (builds happen only on explicit
    #: ``store.build_pyramids`` / ``jobs.run_pyramid_build`` calls) or
    #: ``seal`` (a generation seal schedules a build-behind job —
    #: never blocking the append, never changing results)
    PYRAMID_BUILD = SystemProperty("geomesa.density.pyramid.build", "off")


class PlanningProperties:
    """Cost-based planning knobs (ISSUE 19, docs/planning.md):
    sketch-fed cardinality estimation and adaptive mid-query
    replanning.  All are re-read per query plan, so a live process
    retunes without restart."""

    #: sketch-fed estimation master switch: off makes the decider cost
    #: strategies from whole-store stats / heuristics only (the PR 4
    #: baseline — what the bench A/B compares against)
    ESTIMATOR_ENABLED = SystemProperty(
        "geomesa.planning.estimator.enabled", True)
    #: live-row floor below which the sketch tier is skipped entirely:
    #: the cold per-generation sketch folds (device dispatches + XLA
    #: compiles) cannot amortize on a store a whole scan finishes in
    #: milliseconds, and at small scale a misplanned strategy costs
    #: less than building the tables — the decider plans from
    #: whole-store stats / heuristics exactly as if the estimator were
    #: off.  0 sketches every store regardless of size
    ESTIMATOR_MIN_ROWS = SystemProperty(
        "geomesa.planning.estimator.min.rows", 262_144)
    #: assumed selectivity of an attribute equality with no usable
    #: stat (fraction of the store the strategy is costed at) — the
    #: named replacement for the old bare ``total / 10``
    SELECTIVITY_EQUALS_DEFAULT = SystemProperty(
        "geomesa.planning.selectivity.equals.default", 0.1)
    #: assumed selectivity of an attribute range/prefix with no usable
    #: stat — the named replacement for the old bare ``total / 4``
    SELECTIVITY_RANGE_DEFAULT = SystemProperty(
        "geomesa.planning.selectivity.range.default", 0.25)
    #: adaptive-replan divergence trigger: when a scan's candidate
    #: probe observes more than ``threshold × estimate`` rows, the
    #: remaining scan aborts and the query replans ONCE with the
    #: observed actual folded in; <= 0 disables replanning
    REPLAN_THRESHOLD = SystemProperty(
        "geomesa.planning.replan.threshold", 8.0)
    #: observed-row floor below which a divergence never triggers a
    #: replan — aborting a tiny scan costs more than finishing it
    REPLAN_MIN_ROWS = SystemProperty(
        "geomesa.planning.replan.min.rows", 4096)


class SloProperties:
    """SLO plane knobs (ISSUE 20, geomesa_tpu/obs/slo.py —
    docs/slo.md): per-class latency objectives, rolling burn windows,
    and the alert ring.  Everything re-reads through a
    config-generation cache, so a live process retunes without
    restart."""

    #: master switch: off makes the root-span finish hook a no-op —
    #: no stage ledger, no windows, no exemplars (tracing itself is
    #: governed separately by ``geomesa.obs.enabled``; the SLO plane
    #: only ever sees traces the tracer recorded)
    ENABLED = SystemProperty("geomesa.slo.enabled", True)
    #: latency/availability objectives, one per request class:
    #: comma-separated ``class:latency_ms:target`` triples.  A request
    #: counts against the class's error budget when it errored OR its
    #: end-to-end latency (admission queue included) exceeded
    #: ``latency_ms``; ``target`` is the good-fraction objective the
    #: burn rate normalizes against (burn = bad_fraction / (1-target))
    OBJECTIVES = SystemProperty(
        "geomesa.slo.objectives",
        "query:250:0.99,write:1000:0.99,tile.render:250:0.99")
    #: short burn window in seconds (the fast-burn signal)
    WINDOW_SHORT_S = SystemProperty("geomesa.slo.window.short.s", 300.0)
    #: long burn window in seconds (the sustained-burn confirmation)
    WINDOW_LONG_S = SystemProperty("geomesa.slo.window.long.s", 3600.0)
    #: rolling-window time-bucket width in seconds (retention is
    #: ceil(window.long.s / bucket.s) buckets per (class, tenant))
    BUCKET_S = SystemProperty("geomesa.slo.bucket.s", 10.0)
    #: multi-window alert threshold: an alert fires (edge-triggered)
    #: when BOTH windows' burn rates exceed this, and re-arms when the
    #: short window drops back under; <= 0 disables alerting
    BURN_ALERT = SystemProperty("geomesa.slo.burn.alert", 10.0)
    #: bounded /debug/alerts ring capacity (threshold crossings kept)
    ALERTS_CAPACITY = SystemProperty("geomesa.slo.alerts.capacity", 128)
    #: distinct-tenant label bound: past it, new tenants fold into the
    #: ``other`` label (bounded metric cardinality under tenant churn)
    TENANTS_MAX = SystemProperty("geomesa.slo.tenants.max", 64)


def _register_declarations() -> None:
    """Fill the option registry from the declaration classes above —
    the one place a knob becomes 'known' to the strict mode."""
    for cls in (QueryProperties, ObsProperties, ArrowProperties,
                SchemaProperties, ConfigProperties, ResilienceProperties,
                ServingProperties, DensityProperties, PlanningProperties,
                SloProperties):
        for value in vars(cls).values():
            if isinstance(value, (SystemProperty, SchemaOption)):
                _REGISTRY[value.name] = value


_register_declarations()

#: default scan-ranges budget (import-time snapshot users can override per
#: call; the live knob is QueryProperties.SCAN_RANGES_TARGET)
DEFAULT_MAX_RANGES = QueryProperties.SCAN_RANGES_TARGET.default
