"""Typed system properties — the framework's config/flag system.

Mirrors the reference's three-tier config model (SURVEY.md §5): this module
is tier 1, the equivalent of ``GeoMesaSystemProperties.SystemProperty``
(geomesa-utils/.../conf/GeoMesaSystemProperties.scala:17-27) and the query
knobs in ``QueryProperties`` (geomesa-index-api/.../conf/
QueryProperties.scala:17-44).  Values resolve, in order: programmatic
override → environment variable (dots become underscores, upper-cased) →
default.  Tier 2 is per-schema user data (features/feature_type.py), tier
3 per-query hints (index/query options).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any

__all__ = ["SystemProperty", "QueryProperties", "ObsProperties",
           "set_property", "clear_property", "config_generation"]

_overrides: dict[str, Any] = {}
_lock = threading.Lock()
#: bumped on every programmatic override change — hot paths (obs
#: tracing) cache resolved property values keyed on this so they pay a
#: plain int read per call instead of the override lock, while
#: ``set_property`` still takes effect immediately
_generation = 0


def config_generation() -> int:
    return _generation


def set_property(name: str, value) -> None:
    global _generation
    with _lock:
        _overrides[name] = value
        _generation += 1


def clear_property(name: str) -> None:
    global _generation
    with _lock:
        _overrides.pop(name, None)
        _generation += 1


@dataclass(frozen=True)
class SystemProperty:
    """A named, typed knob with env-var and programmatic override."""

    name: str
    default: Any

    @property
    def env_var(self) -> str:
        return self.name.replace(".", "_").upper()

    def get(self):
        with _lock:
            if self.name in _overrides:
                return _overrides[self.name]
        raw = os.environ.get(self.env_var)
        if raw is None:
            return self.default
        if isinstance(self.default, bool):
            return raw.strip().lower() in ("1", "true", "yes")
        if isinstance(self.default, int):
            return int(raw)
        if isinstance(self.default, float):
            return float(raw)
        return raw

    def to_int(self) -> int:
        return int(self.get())

    def to_bool(self) -> bool:
        return bool(self.get())


class QueryProperties:
    """Planner guardrails (QueryProperties.scala:17-44 equivalents)."""

    #: target number of scan ranges per query (split across time bins)
    SCAN_RANGES_TARGET = SystemProperty("geomesa.scan.ranges.target", 2000)
    #: query timeout in seconds; 0 disables (ThreadManagement reaper analog)
    QUERY_TIMEOUT = SystemProperty("geomesa.query.timeout", 0)
    #: skip the exact geometry re-check and trust index-key resolution
    #: accepted for parity with the reference (QueryProperties.scala); a
    #: deliberate no-op here: the exact double-precision re-check is FUSED
    #: into the scan kernel's candidate mask, so "loose" would save
    #: nothing — results are always exact at zero extra cost
    LOOSE_BBOX = SystemProperty("geomesa.query.loose.bounding.box", False)
    #: refuse queries that would scan the full table (opt-in, like the
    #: reference's BlockFullTableScans)
    BLOCK_FULL_TABLE_SCANS = SystemProperty(
        "geomesa.scan.block.full.table", False)
    #: cost strategy: 'stats' (cost-based) or 'index' (heuristic priority)
    COST_TYPE = SystemProperty("geomesa.query.cost.type", "stats")
    #: use the Pallas candidate-filter kernel on TPU backends (falls back
    #: to the fused XLA path automatically if lowering fails)
    PALLAS_SCAN = SystemProperty("geomesa.scan.pallas", True)


class ObsProperties:
    """Observability knobs (the ``geomesa.obs.*`` option family —
    docs/observability.md).  Sampler kind and the slow threshold are
    re-read per trace so tests and operators can flip them live via
    :func:`set_property`; capacities are read once at tracer
    construction."""

    #: master switch — off makes every span a shared no-op
    ENABLED = SystemProperty("geomesa.obs.enabled", True)
    #: root-span sampling: 'always', 'ratio', 'slow' (retain only
    #: slower-than-threshold traces), or 'never'
    SAMPLER = SystemProperty("geomesa.obs.sampler", "always")
    #: fraction of root spans recorded under the 'ratio' sampler
    SAMPLE_RATIO = SystemProperty("geomesa.obs.sample.ratio", 0.1)
    #: slow-query threshold in ms: traces at/over it land in the slow
    #: log (and are what the 'slow' sampler retains); <= 0 disables
    SLOW_MS = SystemProperty("geomesa.obs.slow.ms", 500.0)
    #: ring-buffer exporter capacity (traces)
    TRACE_CAPACITY = SystemProperty("geomesa.obs.trace.capacity", 256)
    #: JSONL trace-sink size cap in bytes: the exporter rotates so the
    #: live file plus one predecessor stay within this total (long
    #: bench runs must not grow the sink without bound); <= 0 disables
    #: rotation.  Re-read per export, so it is live-tunable.
    TRACE_MAX_BYTES = SystemProperty("geomesa.obs.trace.max_bytes",
                                     128 * 2 ** 20)
    #: slow-query log capacity (traces)
    SLOW_CAPACITY = SystemProperty("geomesa.obs.slow.capacity", 64)
    #: count XLA backend compiles via the jax.monitoring listener
    #: (jax.compile.* metrics); the classic silent TPU perf cliff
    RECOMPILE_TRACK = SystemProperty("geomesa.obs.recompile.track", True)
    #: access-temperature tracking (obs/heat.py): per-(schema, index,
    #: generation) touch counters folded into a decayed temperature
    #: score — the workload data plane the tier autopilot consumes.
    #: Off reduces every record site to one cached bool read.
    HEAT_ENABLED = SystemProperty("geomesa.obs.heat.enabled", True)
    #: temperature decay constant τ in seconds: each touch contributes
    #: ``exp(-(now - t)/τ)`` to a generation's score, so a touch fades
    #: to ~37% after τ seconds (half-life τ·ln 2 ≈ 0.69τ)
    HEAT_TAU_S = SystemProperty("geomesa.obs.heat.tau.s", 600.0)
    #: hard bound on tracked (schema, index, generation) entries —
    #: beyond it the coldest entries evict (bounded memory under
    #: generation churn)
    HEAT_MAX_ENTRIES = SystemProperty("geomesa.obs.heat.max.entries",
                                      8192)
    #: write-path device attribution: when a write runs under a
    #: RECORDING span, block on the live index generation at the end of
    #: the write so the trace carries honest block-until-ready device
    #: ms (the scan-span discipline).  Blocking only forces work that
    #: must complete anyway; off keeps appends fully pipelined even
    #: while traced
    WRITE_BLOCK = SystemProperty("geomesa.obs.write.block", True)
    #: background-job registry retention (obs/jobs.py): finished
    #: IngestJob/CompactionJob records kept for /debug/jobs
    JOBS_CAPACITY = SystemProperty("geomesa.obs.jobs.capacity", 128)


#: default scan-ranges budget (import-time snapshot users can override per
#: call; the live knob is QueryProperties.SCAN_RANGES_TARGET)
DEFAULT_MAX_RANGES = QueryProperties.SCAN_RANGES_TARGET.default
