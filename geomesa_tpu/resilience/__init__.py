"""Resilience layer (ISSUE 16): query deadlines + cooperative
cancellation, HBM admission control, degraded execution with a circuit
breaker, and a deterministic fault-injection harness.

Import surface is jax-free: everything here is host-side bookkeeping
(contextvars, locks, perf_counter comparisons) threaded through the
query/ingest/serving planes.  See docs/resilience.md for semantics.
"""

from .admission import AdmissionGate, AdmissionToken, Backpressure
from .admission import gate as admission_gate
from .deadline import (Cancelled, CancelScope, QueryTimeout, check_cancel,
                       current_scope, deadline_scope)
from .degrade import CircuitBreaker, breaker, classify_device_failure
from .degrade import retry_budget
from .faults import FAULT_POINTS, FaultInjected, FaultRegistry, fault_point
from .faults import registry as fault_registry

__all__ = [
    "QueryTimeout", "Cancelled", "CancelScope", "deadline_scope",
    "check_cancel", "current_scope",
    "Backpressure", "AdmissionToken", "AdmissionGate", "admission_gate",
    "classify_device_failure", "CircuitBreaker", "breaker", "retry_budget",
    "FAULT_POINTS", "FaultInjected", "FaultRegistry", "fault_point",
    "fault_registry",
]
