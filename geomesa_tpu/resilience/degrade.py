"""Degraded execution: device-failure classification, bounded retry
budget, and a per-generation circuit breaker (ISSUE 16).

Dispatch boundaries in the lean indexes wrap device scans with
``try/except``; on failure they ask :func:`classify_device_failure`
whether the error is *transient* (device memory pressure — the scan can
succeed after demoting the offending generations' payload to host) or
*poison* (bad input / logic error — retrying would fail identically, so
it propagates).  A transient classification triggers at most
``geomesa.resilience.retry.max`` demote-and-retry rounds, recorded as a
``resilience.degraded`` span attribute rather than a user-facing error.

The circuit breaker keeps a generation that trips repeatedly from
re-admitting device dispatch at all: after ``breaker.threshold``
consecutive transient failures the key's circuit opens for
``breaker.cooldown.s`` seconds, during which callers route that
generation through the host tier directly.
"""

from __future__ import annotations

import threading
import time

from .. import metrics as _metrics
from ..config import ResilienceProperties
from ..metrics import RESILIENCE_BREAKER_OPEN
from .faults import FaultInjected

__all__ = ["classify_device_failure", "CircuitBreaker", "breaker",
           "retry_budget"]

#: substrings (upper-cased match) that mark a device failure as memory
#: pressure rather than poison input.  XLA/TPU OOMs surface as
#: RESOURCE_EXHAUSTED status payloads; CPU jax raises bare
#: "out of memory" RuntimeErrors.
_TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "OUT OF MEMORY",
                      "HBM OOM", "ALLOCATION FAILURE")


def classify_device_failure(exc: BaseException) -> str:
    """``'transient'`` (retry after demotion) or ``'poison'``
    (propagate).  Injected faults classify by their armed kind."""
    if isinstance(exc, FaultInjected):
        return "transient" if exc.kind == "oom" else "poison"
    msg = str(exc).upper()
    for marker in _TRANSIENT_MARKERS:
        if marker in msg:
            return "transient"
    return "poison"


def retry_budget() -> int:
    return int(ResilienceProperties.RETRY_MAX.get() or 0)


class CircuitBreaker:
    """Consecutive-failure breaker keyed by an opaque hashable (the
    lean indexes use ``(catalog_key, gen_id)``).  Closed → counts
    failures; at threshold → open for the cooldown (``allows`` False,
    counted as ``resilience.breaker.open``); after cooldown → half-open
    (one trial dispatch; success resets, failure re-opens)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state: dict = {}  # key -> [consecutive_failures, open_until_t]

    def allows(self, key) -> bool:
        with self._lock:
            st = self._state.get(key)
            if st is None:
                return True
            failures, open_until = st
            threshold = int(
                ResilienceProperties.BREAKER_THRESHOLD.get() or 0)
            if threshold <= 0 or failures < threshold:
                return True
            if time.monotonic() >= open_until:
                # half-open: admit one trial; a failure re-opens below
                st[0] = threshold - 1
                return True
        _metrics.registry.counter(RESILIENCE_BREAKER_OPEN).inc()
        return False

    def record_failure(self, key) -> None:
        with self._lock:
            st = self._state.setdefault(key, [0, 0.0])
            st[0] += 1
            threshold = int(
                ResilienceProperties.BREAKER_THRESHOLD.get() or 0)
            if threshold > 0 and st[0] >= threshold:
                cooldown = float(
                    ResilienceProperties.BREAKER_COOLDOWN_S.get() or 0.0)
                st[1] = time.monotonic() + cooldown

    def record_success(self, key) -> None:
        with self._lock:
            self._state.pop(key, None)

    def reset(self) -> None:
        with self._lock:
            self._state.clear()


#: process-wide breaker (generations are process-local objects)
breaker = CircuitBreaker()
