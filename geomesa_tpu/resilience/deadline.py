"""Query deadlines and cooperative cancellation (ISSUE 16).

Mirrors the obs/trace.py propagation pattern: a contextvar carries the
active :class:`CancelScope` so deeply-nested scan code never threads a
deadline argument through its signatures — it calls :func:`check_cancel`
at natural yield points (between generation scans, range-decomposition
batches, Arrow chunks, compaction merge steps) and the ambient scope
decides whether to keep going, stop with partial results, or raise.

The checks are pure host-side ``time.perf_counter()`` comparisons: no
device sync, no data-dependent Python branching inside traced code, so
a deadline on a warm query cannot introduce a host sync or a recompile
(the gm-lint host-sync check covers the instrumented hot paths).

Generators need care: a generator's body runs AFTER the function that
created it returned, so an ambient scope installed around the creating
call is gone by iteration time.  Streaming code (arrow/stream.py)
therefore takes the scope as an explicit argument and passes it to
:func:`check_cancel` via ``scope=`` instead of relying on the
contextvar.
"""

from __future__ import annotations

import contextlib
import contextvars
import time

from .. import metrics as _metrics
from ..metrics import QUERY_TIMEOUTS

__all__ = ["QueryTimeout", "Cancelled", "CancelScope", "deadline_scope",
           "check_cancel", "current_scope"]


class QueryTimeout(TimeoutError):
    """The query's ``timeout_ms`` deadline expired and partial results
    were not requested.  web/app.py maps this to ``504``."""

    def __init__(self, message: str, elapsed_ms: float | None = None):
        super().__init__(message)
        self.elapsed_ms = elapsed_ms


class Cancelled(RuntimeError):
    """Raised at the next yield point after :meth:`CancelScope.cancel`."""


class CancelScope:
    """One query's deadline + cancellation state.

    ``timed_out`` latches once the deadline is first observed expired;
    with ``partial=True`` the scan layers use it to stop starting new
    work while still finishing the exactness-preserving steps (host
    recheck) over what was already scanned.
    """

    __slots__ = ("timeout_ms", "partial", "timed_out", "cancelled",
                 "_start_t", "_deadline_t", "_counted")

    def __init__(self, timeout_ms: float | None = None,
                 partial: bool = False):
        self.timeout_ms = timeout_ms
        self.partial = bool(partial)
        self.timed_out = False
        self.cancelled = False
        self._start_t = time.perf_counter()
        self._deadline_t = (self._start_t + float(timeout_ms) / 1000.0
                            if timeout_ms else None)
        self._counted = False

    def expired(self) -> bool:
        return (self._deadline_t is not None
                and time.perf_counter() >= self._deadline_t)

    def cancel(self) -> None:
        self.cancelled = True

    def elapsed_ms(self) -> float:
        return (time.perf_counter() - self._start_t) * 1000.0

    def remaining_ms(self) -> float | None:
        """Time left, or None when no deadline is set (never negative)."""
        if self._deadline_t is None:
            return None
        return max(0.0, (self._deadline_t - time.perf_counter()) * 1000.0)

    def poll(self) -> bool:
        """Non-raising check for streaming drains: True once cancelled
        or expired, latching ``timed_out`` (and counting
        ``query.timeout`` once) on first expiry.  A drain that must end
        with a well-formed EOS breaks on True instead of raising
        mid-stream."""
        if self.cancelled:
            return True
        if not self.expired():
            return False
        self.timed_out = True
        if not self._counted:
            self._counted = True
            _metrics.registry.counter(QUERY_TIMEOUTS).inc()
        return True


_current_scope: contextvars.ContextVar = contextvars.ContextVar(
    "geomesa_resilience_scope", default=None)


def current_scope() -> CancelScope | None:
    return _current_scope.get()


@contextlib.contextmanager
def deadline_scope(timeout_ms: float | None = None, partial: bool = False,
                   scope: CancelScope | None = None):
    """Install a :class:`CancelScope` for the body (nestable; the inner
    scope shadows the outer one for the duration).  Pass ``scope=`` to
    install an externally-created scope — the datastore does this so it
    can read ``timed_out`` after the body exits."""
    if scope is None:
        scope = CancelScope(timeout_ms, partial)
    token = _current_scope.set(scope)
    try:
        yield scope
    finally:
        _current_scope.reset(token)


def check_cancel(point: str = "", scope: CancelScope | None = None) -> bool:
    """The cooperative yield point.

    Returns False (fast, no allocation) when no scope is active or the
    deadline has not expired.  On expiry: latches ``timed_out``, counts
    ``query.timeout`` once per scope, then either returns True (partial
    mode — the caller stops starting new work) or raises
    :class:`QueryTimeout`.  An explicitly cancelled scope always raises
    :class:`Cancelled`.
    """
    s = scope if scope is not None else _current_scope.get()
    if s is None:
        return False
    if s.cancelled:
        raise Cancelled(f"query cancelled at {point or 'yield point'}")
    if not s.expired():
        return False
    s.timed_out = True
    if not s._counted:
        s._counted = True
        _metrics.registry.counter(QUERY_TIMEOUTS).inc()
    if s.partial:
        return True
    raise QueryTimeout(
        f"deadline of {s.timeout_ms} ms expired at "
        f"{point or 'yield point'} after {s.elapsed_ms():.1f} ms",
        elapsed_ms=s.elapsed_ms())
