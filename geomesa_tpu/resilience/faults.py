"""Deterministic fault injection (ISSUE 16).

A fixed registry of named injection points threaded through the write,
query, streaming, and compaction planes.  Arming is pure config —
``geomesa.resilience.fault.points`` holds a comma-separated spec of
``point[:trigger][=kind]`` entries:

- bare ``point`` fires on every hit;
- integer trigger (``compaction.merge_step:2``) fires on exactly the
  Nth hit of that point (then never again until re-armed);
- float trigger < 1 (``device.dispatch:0.25``) fires with that
  probability from a ``Random(geomesa.resilience.fault.seed)`` stream —
  same seed + same hit order = same failures, so chaos runs replay;
- ``kind`` is ``error`` (default: poison, propagates) or ``oom``
  (message carries RESOURCE_EXHAUSTED so degrade.py classifies it
  transient and exercises the demote-and-retry path).

The catalog of known points is closed: arming an unknown name raises at
the first injection check, and gm-lint's fault-point check validates
every literal reaching :func:`fault_point` against the catalog table in
docs/resilience.md.
"""

from __future__ import annotations

import random
import threading

from .. import config as _config
from .. import metrics as _metrics
from ..config import ResilienceProperties
from ..metrics import RESILIENCE_FAULTS

__all__ = ["FAULT_POINTS", "FaultInjected", "FaultRegistry", "fault_point",
           "registry"]

#: the closed catalog (docs/resilience.md "Fault-point catalog").
#: ``ingest.append`` stands where the issue sketch said ``wal.append``:
#: this store has no WAL — the append entry point is the equivalent
#: boundary between "row accepted" and "row indexed".
FAULT_POINTS = ("device.dispatch", "host.spill", "arrow.flush",
                "compaction.merge_step", "ingest.append",
                "pyramid.build")


class FaultInjected(RuntimeError):
    """An injected failure.  ``kind='oom'`` messages carry the
    RESOURCE_EXHAUSTED marker so the failure classifier treats them as
    transient device pressure."""

    def __init__(self, point: str, kind: str = "error"):
        marker = "RESOURCE_EXHAUSTED" if kind == "oom" else "INJECTED_FAULT"
        super().__init__(f"{marker}: injected fault at {point!r}")
        self.point = point
        self.kind = kind


class FaultRegistry:
    """Per-process injection state.  Disabled (the tier-1 default) the
    check is one generation compare + one empty-dict truth test — cheap
    enough for scan hot paths."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gen = -1
        self._arms: dict[str, tuple] = {}
        self._hits: dict[str, int] = {}
        self._rng = random.Random(0)

    def _refresh_locked(self) -> None:
        gen = _config.config_generation()
        if gen == self._gen:
            return
        spec = str(ResilienceProperties.FAULT_POINTS.get() or "")
        arms: dict[str, tuple] = {}
        for part in (p.strip() for p in spec.split(",") if p.strip()):
            kind = "error"
            if "=" in part:
                part, kind = part.rsplit("=", 1)
            trigger = None
            if ":" in part:
                part, raw = part.rsplit(":", 1)
                trigger = float(raw) if "." in raw else int(raw)
            if part not in FAULT_POINTS:
                raise ValueError(
                    f"unknown fault point {part!r}; known: {FAULT_POINTS}")
            if kind not in ("error", "oom"):
                raise ValueError(f"unknown fault kind {kind!r} for {part!r}")
            arms[part] = (trigger, kind)
        self._arms = arms
        self._hits = {}
        self._rng = random.Random(
            int(ResilienceProperties.FAULT_SEED.get() or 0))
        self._gen = gen

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)

    def maybe_fail(self, point: str) -> None:
        # disabled fast path: no lock, no allocation (hot scan loops)
        if _config.config_generation() == self._gen and not self._arms:
            return
        with self._lock:
            self._refresh_locked()
            arm = self._arms.get(point)
            if arm is None:
                return
            self._hits[point] = hit = self._hits.get(point, 0) + 1
            trigger, kind = arm
            if trigger is None:
                fire = True
            elif isinstance(trigger, float) and trigger < 1.0:
                fire = self._rng.random() < trigger
            else:
                fire = hit == int(trigger)
            if not fire:
                return
        _metrics.registry.counter(RESILIENCE_FAULTS).inc()
        raise FaultInjected(point, kind)


registry = FaultRegistry()


def fault_point(point: str) -> None:
    """The hook instrumented code calls: raises :class:`FaultInjected`
    when ``point`` is armed and its trigger fires, else returns."""
    registry.maybe_fail(point)
