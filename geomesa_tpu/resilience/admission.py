"""Admission control for the query plane (ISSUE 16).

A token gate in front of the datastore's query entry points: each query
acquires an :class:`AdmissionToken` before planning and releases it when
its results are fully delivered (for streamed Arrow responses that is
after the LAST chunk drains, not when the generator is created).  Two
shed conditions, both config-driven and both OFF by default:

- concurrency: more than ``geomesa.resilience.admission.max.concurrent``
  in-flight queries;
- HBM pressure: the live ``storage.total.device_bytes`` gauge above
  ``geomesa.resilience.hbm.headroom`` bytes (the gauge is maintained by
  obs/resource.py's storage publisher — size the headroom below the
  device's usable HBM minus the compiled-program/workspace reserve, see
  docs/resilience.md).

An over-budget request queues up to ``admission.queue.ms`` (a brief
wait absorbs bursts without queueing unboundedly), then sheds with
:class:`Backpressure`; web/app.py maps that to ``503 + Retry-After``.
Token release is idempotent — the chaos tests assert zero leaked tokens
after repeated shed/timeout/abort cycles.
"""

from __future__ import annotations

import threading
import time

from .. import config as _config
from .. import metrics as _metrics
from ..config import ResilienceProperties
from ..metrics import (QUERY_SHED, RESILIENCE_ADMISSION_ADMITTED,
                       RESILIENCE_ADMISSION_ACTIVE,
                       RESILIENCE_ADMISSION_QUEUE_MS)

__all__ = ["Backpressure", "AdmissionToken", "AdmissionGate", "gate"]

#: the storage gauge the HBM check reads (obs/resource.py publishes it)
_DEVICE_BYTES_GAUGE = "storage.total.device_bytes"


class Backpressure(RuntimeError):
    """The admission gate shed this request.  web/app.py maps it to
    ``503`` with ``Retry-After: ceil(retry_after_s)``."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class AdmissionToken:
    """One admitted query's slot.  ``release()`` is idempotent: the
    abort/timeout/normal-completion paths may all reach it without
    double-decrementing the in-flight count.  ``queue_ms`` is the wait
    this request spent inside the gate — the SLO plane's ``queue``
    stage (the wait happens BEFORE the root span opens, so only the
    token can carry it in)."""

    __slots__ = ("_gate", "_released", "queue_ms")

    def __init__(self, gate: "AdmissionGate | None"):
        self._gate = gate
        self._released = False
        self.queue_ms = 0.0

    @property
    def released(self) -> bool:
        return self._released

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        if self._gate is not None:
            self._gate._release()


class AdmissionGate:
    def __init__(self):
        self._cond = threading.Condition(threading.Lock())
        self._inflight = 0
        self._gen = -1
        self._max = 0
        self._queue_ms = 50.0
        self._headroom = 0
        # FIFO ticket queue: waiters admit in arrival order.  notify_all
        # wakes everyone, but only the queue head may take the freed
        # slot — without this a late arrival could barge past waiters
        # that had been queued for most of their budget.
        self._tickets: list[object] = []
        self._next_ticket = 0

    def _refresh_locked(self) -> None:
        gen = _config.config_generation()
        if gen == self._gen:
            return
        self._max = int(
            ResilienceProperties.ADMISSION_MAX_CONCURRENT.get() or 0)
        self._queue_ms = float(
            ResilienceProperties.ADMISSION_QUEUE_MS.get() or 0.0)
        self._headroom = int(ResilienceProperties.HBM_HEADROOM.get() or 0)
        self._gen = gen

    def _hbm_over_budget(self) -> bool:
        if self._headroom <= 0:
            return False
        return (_metrics.registry.gauge(_DEVICE_BYTES_GAUGE).value
                > self._headroom)

    @property
    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    def _ticket_count(self) -> int:
        """Queued-waiter count (test hook for the FIFO ordering pin)."""
        with self._cond:
            return len(self._tickets)

    def acquire(self, schema: str = "") -> AdmissionToken:
        t0 = time.perf_counter()
        with self._cond:
            self._refresh_locked()
            if self._max <= 0 and self._headroom <= 0:
                # gate disabled: admit unconditionally but still track
                # in-flight, so enabling the gate mid-flight sees truth.
                # The admitted counter and queue timer record here too —
                # dashboards must not undercount when the gate is off.
                self._inflight += 1
                _metrics.registry.gauge(
                    RESILIENCE_ADMISSION_ACTIVE).set(self._inflight)
                _metrics.registry.timer(RESILIENCE_ADMISSION_QUEUE_MS).update(
                    (time.perf_counter() - t0) * 1000.0)
                _metrics.registry.counter(
                    RESILIENCE_ADMISSION_ADMITTED).inc()
                token = AdmissionToken(self)
                token.queue_ms = (time.perf_counter() - t0) * 1000.0
                return token
            ticket = self._next_ticket
            self._next_ticket += 1
            self._tickets.append(ticket)
            queue_deadline = t0 + self._queue_ms / 1000.0
            try:
                # only the queue HEAD may take a freed slot: notify_all
                # wakes every waiter, and without the head check a late
                # arrival (or a waiter that happened to be scheduled
                # first) could barge past longer-queued requests
                while (self._tickets[0] != ticket
                       or (self._max > 0 and self._inflight >= self._max)
                       or self._hbm_over_budget()):
                    remaining = queue_deadline - time.perf_counter()
                    if remaining <= 0:
                        _metrics.registry.counter(QUERY_SHED).inc()
                        reason = ("concurrency"
                                  if (self._max > 0
                                      and self._inflight >= self._max)
                                  else ("hbm" if self._hbm_over_budget()
                                        else "queued"))
                        raise Backpressure(
                            f"admission shed ({reason}) for "
                            f"{schema or 'query'}: {self._inflight} in flight",
                            retry_after_s=max(0.05, self._queue_ms / 1000.0))
                    self._cond.wait(remaining)
                self._inflight += 1
                _metrics.registry.gauge(
                    RESILIENCE_ADMISSION_ACTIVE).set(self._inflight)
            finally:
                # success or shed, this waiter leaves the queue; wake
                # the rest so the new head can re-check its turn
                self._tickets.remove(ticket)
                self._cond.notify_all()
        _metrics.registry.timer(RESILIENCE_ADMISSION_QUEUE_MS).update(
            (time.perf_counter() - t0) * 1000.0)
        _metrics.registry.counter(RESILIENCE_ADMISSION_ADMITTED).inc()
        token = AdmissionToken(self)
        token.queue_ms = (time.perf_counter() - t0) * 1000.0
        return token

    def reset(self) -> None:
        """Zero the in-flight count and wake queued waiters — a
        leaked-token recovery hook for tests and operators, NOT part of
        the query path (live queries double-release harmlessly: tokens
        are idempotent and the count floors at zero)."""
        with self._cond:
            self._inflight = 0
            _metrics.registry.gauge(
                RESILIENCE_ADMISSION_ACTIVE).set(0)
            self._cond.notify_all()

    def _release(self) -> None:
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            _metrics.registry.gauge(
                RESILIENCE_ADMISSION_ACTIVE).set(self._inflight)
            self._cond.notify_all()


#: process-wide gate (one HBM, one process — the unit that sheds)
gate = AdmissionGate()
