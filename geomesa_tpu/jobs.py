"""Bulk ingest/export jobs: the geomesa-jobs + tools/ingest analog.

The reference runs converter ingest either locally (thread pool over
files — tools/ingest/LocalConverterIngest.scala) or distributed
(MapReduce with ConverterInputFormat mappers writing through
GeoMesaOutputFormat — tools/ingest/DistributedConverterIngest.scala,
jobs/mapreduce/GeoMesaOutputFormat.scala).  Here "mappers" are a thread
pool parsing files into columnar batches concurrently (host-bound
parse), and the "output format" is a single writer thread appending to
the store — keeping the store's append path single-writer the way a
BatchWriter serializes mutations.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor, as_completed
from dataclasses import dataclass, field

__all__ = ["IngestJob", "IngestResult", "run_ingest",
           "CompactionJob", "run_compaction",
           "PyramidJob", "run_pyramid_build"]


@dataclass
class IngestResult:
    """Counters the reference reports per ingest (EvaluationContext
    metrics + job counters)."""

    ingested: int = 0
    failed: int = 0
    files: int = 0
    errors: list = field(default_factory=list)


@dataclass
class IngestJob:
    """Converter ingest over many files with parallel parse.

    ``store`` — TpuDataStore (or anything with ``write(name, batch)``);
    ``converter_config`` — converter definition dict;
    ``workers`` — parse parallelism (the mapper count).
    """

    store: object
    type_name: str
    converter_config: dict
    workers: int = 4

    def run(self, paths: list[str]) -> IngestResult:
        """Run the ingest, registered in the background-job registry
        (obs/jobs, ISSUE 12): the run appears in ``/debug/jobs`` with
        ``setup``/``ingest`` phase spans, live per-file progress, and
        a terminal outcome — including ``failed`` when setup or a
        write raises (per-file parse errors still only count)."""
        from .obs.jobs import jobs_registry
        with jobs_registry.run("ingest", schema=self.type_name,
                               files=len(paths),
                               workers=self.workers) as job:
            return self._run(job, paths)

    def _run(self, job, paths: list[str]) -> IngestResult:
        from .io.converters import EvaluationContext, converter_from_config

        result = IngestResult()
        with job.phase("setup"):
            sft = self.store.get_schema(self.type_name)
            # one converter for the whole job: construction loads
            # enrichment caches (CSV parses), and convert() itself is
            # stateless, so the worker threads can share it safely
            conv = converter_from_config(sft, self.converter_config)

        def parse(path: str):
            ec = EvaluationContext()
            if conv.wants_path:
                batch = conv.convert(path, ec)
            else:
                with open(path, "rb") as f:
                    batch = conv.convert(f.read(), ec)
            return batch, ec

        with job.phase("ingest", files=len(paths)), \
                ThreadPoolExecutor(max_workers=self.workers) as pool:
            futures = {pool.submit(parse, p): p for p in paths}
            for fut in as_completed(futures):
                path = futures[fut]
                result.files += 1
                try:
                    batch, ec = fut.result()
                except Exception as e:  # noqa: BLE001 — count, keep going
                    result.errors.append(f"{path}: {e!r}")
                    result.failed += 1
                    continue
                result.failed += ec.failure
                result.errors.extend(ec.errors)
                if len(batch):
                    # single-writer append (BatchWriter role)
                    result.ingested += self.store.write(self.type_name, batch)
                job.progress(files=result.files,
                             ingested=result.ingested,
                             failed=result.failed)
        return result


def run_ingest(store, type_name: str, converter_config: dict,
               paths: list[str], workers: int = 4) -> IngestResult:
    return IngestJob(store, type_name, converter_config, workers).run(paths)


@dataclass
class CompactionJob:
    """Explicit LSM maintenance over a lean schema's generational
    indexes — the analog of the reference's ``compact`` tool command
    (an Accumulo major compaction request): fold sealed same-tier
    sorted runs so query/density fan-out stops growing with ingest
    history.  ``budget_ms`` bounds each run; an interrupted job resumes
    where it stopped, so schedulers can call it on a fixed cadence with
    a fixed budget (the BatchWriter + periodic-compaction operating
    pattern this store is built for).

    ``store`` — TpuDataStore; ``budget_ms`` — wall-clock bound per
    ``run()`` (None = run to completion).
    """

    store: object
    type_name: str
    budget_ms: float | None = None

    def run(self) -> dict:
        """Run one compaction pass, registered in the background-job
        registry (obs/jobs): the run appears in ``/debug/jobs`` with a
        ``compact`` phase span, per-index merged-group progress, and a
        terminal outcome — ``failed`` (with the error) when the store
        raises, so a compaction storm or a crashed pass is traceable
        instead of invisible."""
        from .obs.jobs import jobs_registry
        with jobs_registry.run("compaction", schema=self.type_name,
                               budget_ms=self.budget_ms) as job:
            with job.phase("compact"):
                out = self.store.compact(self.type_name,
                                         budget_ms=self.budget_ms)
            job.progress(
                merged_groups=sum(int(v.get("merged_groups", 0))
                                  for v in out.values()
                                  if isinstance(v, dict)),
                indexes=len(out))
            return out


def run_compaction(store, type_name: str,
                   budget_ms: float | None = None) -> dict:
    return CompactionJob(store, type_name, budget_ms).run()


@dataclass
class PyramidJob:
    """Build-behind density-pyramid maintenance over a lean schema
    (ISSUE 18): fold each sealed generation's whole-world density into
    its multi-resolution pyramid so interactive heatmap/tile requests
    stop rescanning immutable history.  Idempotent and resumable — a
    generation that already has a pyramid is skipped, so an
    interrupted build picks up the missing generations on the next
    pass while queries keep serving exact results through the scan
    fallback.

    ``store`` — TpuDataStore; ``type_name`` — the lean schema.
    """

    store: object
    type_name: str

    def run(self) -> int:
        """Run one build pass, registered in the background-job
        registry (obs/jobs): the run appears in ``/debug/jobs`` with a
        ``build`` phase span, built-pyramid progress, and a terminal
        outcome — ``failed`` (with the error) when a build raises, so
        an interrupted build-behind pass is traceable."""
        from .obs.jobs import jobs_registry
        with jobs_registry.run("pyramid", schema=self.type_name) as job:
            with job.phase("build"):
                built = self.store.build_pyramids(self.type_name)
            job.progress(built=built)
            return built


def run_pyramid_build(store, type_name: str) -> int:
    return PyramidJob(store, type_name).run()


def local_paths_for_process(paths: list[str], process_index: int,
                            process_count: int) -> list[str]:
    """Round-robin file split across processes — the MapReduce input
    split of DistributedConverterIngest (each mapper gets a file
    subset)."""
    return [p for i, p in enumerate(paths)
            if i % max(1, process_count) == process_index]


def run_distributed_ingest(sft, converter_config: dict, paths: list[str],
                           period="week", mesh=None, workers: int = 4):
    """Multi-process converter ingest → global sharded Z3 index (the
    reference's DistributedConverterIngest + GeoMesaOutputFormat,
    tools/ingest/DistributedConverterIngest.scala): every process runs
    this SAME function (multi-controller SPMD), parses its round-robin
    share of the files with a local thread pool (the mapper stage), and
    feeds only its LOCAL rows into ``ShardedZ3Index.build_multihost`` —
    the global index assembles via collective device placement with no
    host ever holding the full dataset.

    Returns ``(index, IngestResult)`` where the result carries THIS
    process's counters (job counters are per-mapper in the reference
    too).  Single-process runs degenerate to a local parse + sharded
    build, which is what CI exercises."""
    import jax
    import numpy as np

    from .io.converters import EvaluationContext, converter_from_config

    proc = jax.process_index()
    nproc = max(1, jax.process_count())
    my_paths = local_paths_for_process(paths, proc, nproc)
    conv = converter_from_config(sft, converter_config)
    result = IngestResult()
    batches = []

    def parse(path: str):
        ec = EvaluationContext()
        if conv.wants_path:
            return conv.convert(path, ec), ec
        with open(path, "rb") as f:
            return conv.convert(f.read(), ec), ec

    with ThreadPoolExecutor(max_workers=workers) as pool:
        futures = {pool.submit(parse, p): p for p in my_paths}
        for fut in as_completed(futures):
            path = futures[fut]
            result.files += 1
            try:
                batch, ec = fut.result()
            except Exception as e:  # noqa: BLE001 — count, keep going
                result.errors.append(f"{path}: {e!r}")
                result.failed += 1
                continue
            result.failed += ec.failure
            result.errors.extend(ec.errors)
            if len(batch):
                batches.append(batch)
                result.ingested += len(batch)

    from .parallel.scan import ShardedZ3Index

    if batches:
        local = batches[0]
        for b in batches[1:]:
            local = local.concat(b)
        x, y = local.geom_xy(sft.geom_field)
        dtg = local.column(sft.dtg_field)
    else:  # a process may legitimately hold zero rows; it still must
        # join the collective build with an empty block
        x = y = np.empty(0, dtype=np.float64)
        dtg = np.empty(0, dtype=np.int64)
    index = ShardedZ3Index.build_multihost(x, y, dtg, period=period,
                                           mesh=mesh)
    return index, result
