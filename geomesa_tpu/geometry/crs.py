"""Coordinate reference system transforms for query-result reprojection.

Analog of the reference's reprojection step in QueryPlanner.runQuery
(planning/QueryPlanner.scala:74-81, driven by the GeoTools ``Query`` CRS
settings) — applied after scan + filter, to the result only.

TPU-first: transforms are closed-form vectorized math over the columnar
geometry layout (``<geom>_x``/``<geom>_y`` point columns, packed coord
arrays for non-points), written generically over the array namespace so
they run under numpy on host or jax.numpy on device.  Supported natively:
EPSG:4326 (lon/lat degrees, the storage CRS) and EPSG:3857 (spherical web
mercator).  Additional CRSs plug in via :func:`register_crs` with forward
and inverse functions to/from 4326.
"""

from __future__ import annotations

import numpy as np

__all__ = ["transform", "register_crs", "reproject_batch", "EPSG_4326",
           "EPSG_3857"]

EPSG_4326 = "EPSG:4326"
EPSG_3857 = "EPSG:3857"

_R = 6378137.0                      # WGS84 spherical radius (meters)
_MAX_LAT = 85.05112877980659        # web-mercator latitude cutoff


def _merc_fwd(x, y, xp):
    lat = xp.clip(xp.asarray(y, dtype=xp.float64), -_MAX_LAT, _MAX_LAT)
    lon = xp.asarray(x, dtype=xp.float64)
    mx = _R * xp.radians(lon)
    my = _R * xp.log(xp.tan(np.pi / 4.0 + xp.radians(lat) / 2.0))
    return mx, my


def _merc_inv(x, y, xp):
    lon = xp.degrees(xp.asarray(x, dtype=xp.float64) / _R)
    lat = xp.degrees(
        2.0 * xp.arctan(xp.exp(xp.asarray(y, dtype=xp.float64) / _R))
        - np.pi / 2.0)
    return lon, lat


#: crs → (to_4326, from_4326); each fn is (x, y, xp) → (x', y')
_REGISTRY: dict[str, tuple] = {
    EPSG_4326: (lambda x, y, xp: (x, y), lambda x, y, xp: (x, y)),
    EPSG_3857: (_merc_inv, _merc_fwd),
}


def register_crs(code: str, to_4326, from_4326) -> None:
    """Register a custom CRS by its transforms to/from EPSG:4326.

    Each transform is ``(x, y, xp) -> (x', y')`` over array inputs, where
    ``xp`` is the array namespace (numpy or jax.numpy)."""
    _REGISTRY[_norm(code)] = (to_4326, from_4326)


def _norm(code: str) -> str:
    code = code.strip().upper()
    if code.isdigit():
        code = f"EPSG:{code}"
    if code == "CRS:84":  # axis-order-free alias for 4326
        code = EPSG_4326
    return code


def transform(x, y, src: str, dst: str, xp=np):
    """Vectorized coordinate transform ``src`` → ``dst`` (via 4326)."""
    src, dst = _norm(src), _norm(dst)
    for code in (src, dst):
        if code not in _REGISTRY:
            raise ValueError(f"unknown CRS {code!r}; register_crs() to add")
    if src == dst:
        return x, y
    to4326 = _REGISTRY[src][0]
    from4326 = _REGISTRY[dst][1]
    lon, lat = to4326(x, y, xp)
    return from4326(lon, lat, xp)


def reproject_batch(batch, dst: str, src: str = EPSG_4326):
    """Return a copy of a FeatureBatch with all geometry columns
    reprojected ``src`` → ``dst``; no-op when they match."""
    if _norm(dst) == _norm(src):
        return batch
    from ..features.batch import FeatureBatch

    cols = dict(batch.columns)
    for attr in batch.sft.attributes:
        if not attr.is_geometry:
            continue
        xk, yk = f"{attr.name}_x", f"{attr.name}_y"
        if xk in cols and yk in cols:
            cols[xk], cols[yk] = transform(cols[xk], cols[yk], src, dst)
        bk = f"{attr.name}_bbox"
        if bk in cols:
            bbox = np.asarray(cols[bk], dtype=np.float64)
            x0, y0 = transform(bbox[:, 0], bbox[:, 1], src, dst)
            x1, y1 = transform(bbox[:, 2], bbox[:, 3], src, dst)
            cols[bk] = np.stack([x0, y0, x1, y1], axis=1)
    geoms = batch.geoms
    if geoms is not None:
        gx, gy = transform(geoms.coords[:, 0], geoms.coords[:, 1], src, dst)
        # per-geometry bboxes: transforming corners is exact for the
        # axis-monotone transforms supported here
        bx0, by0 = transform(geoms.bbox[:, 0], geoms.bbox[:, 1], src, dst)
        bx1, by1 = transform(geoms.bbox[:, 2], geoms.bbox[:, 3], src, dst)
        from dataclasses import replace
        geoms = replace(geoms, coords=np.stack([gx, gy], axis=1),
                        bbox=np.stack([bx0, by0, bx1, by1], axis=1))
    return FeatureBatch(batch.sft, cols, batch.ids, geoms,
                        ids_explicit=batch.ids_explicit)
