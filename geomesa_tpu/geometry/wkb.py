"""WKB and TWKB geometry codecs.

The reference serializes geometries as WKB (well-known binary) and TWKB
(tiny WKB: varint-delta-encoded, precision-scaled) inside its kryo row
values (geomesa-features/.../serialization/WkbSerialization.scala,
TwkbSerialization.scala, VarIntEncoding.scala).  Host-side codecs here:
interchange with PostGIS/GeoTools tooling (WKB) and compact storage/wire
format (TWKB, typically 3-5× smaller for tracks).
"""

from __future__ import annotations

import struct

import numpy as np

from .types import (
    Geometry, LineString, MultiLineString, MultiPoint, MultiPolygon, Point,
    Polygon,
)

__all__ = ["wkb_encode", "wkb_decode", "twkb_encode", "twkb_decode"]

_WKB_TYPES = {
    "Point": 1, "LineString": 2, "Polygon": 3,
    "MultiPoint": 4, "MultiLineString": 5, "MultiPolygon": 6,
}


# ---------------------------------------------------------------------------
# WKB (little-endian, 2-D)
# ---------------------------------------------------------------------------

def wkb_encode(geom: Geometry) -> bytes:
    out = bytearray()
    _wkb_write(geom, out)
    return bytes(out)


def _wkb_write(geom: Geometry, out: bytearray) -> None:
    out.append(1)  # little endian
    t = _WKB_TYPES[geom.geom_type]
    out += struct.pack("<I", t)
    if isinstance(geom, Point):
        out += struct.pack("<dd", geom.x, geom.y)
    elif isinstance(geom, LineString):
        _wkb_coords(geom.coords, out)
    elif isinstance(geom, Polygon):
        rings = [geom.shell, *geom.holes]
        out += struct.pack("<I", len(rings))
        for r in rings:
            _wkb_coords(r, out)
    elif isinstance(geom, MultiPoint):
        out += struct.pack("<I", len(geom.coords))
        for x, y in geom.coords:
            _wkb_write(Point(float(x), float(y)), out)
    elif isinstance(geom, MultiLineString):
        out += struct.pack("<I", len(geom.lines))
        for l in geom.lines:
            _wkb_write(l, out)
    elif isinstance(geom, MultiPolygon):
        out += struct.pack("<I", len(geom.polygons))
        for p in geom.polygons:
            _wkb_write(p, out)
    else:  # pragma: no cover
        raise ValueError(f"cannot WKB-encode {geom.geom_type}")


def _wkb_coords(coords: np.ndarray, out: bytearray) -> None:
    out += struct.pack("<I", len(coords))
    out += np.asarray(coords, dtype="<f8").tobytes()


def wkb_decode(raw: bytes) -> Geometry:
    geom, _ = _wkb_read(memoryview(raw), 0)
    return geom


def _wkb_read(buf: memoryview, pos: int):
    little = buf[pos] == 1
    pos += 1
    fmt = "<I" if little else ">I"
    (t,) = struct.unpack_from(fmt, buf, pos)
    pos += 4
    # EWKB (PostGIS) flag bits + ISO WKB 1000/2000/3000 dimension offsets
    has_z = bool(t & 0x80000000)
    has_m = bool(t & 0x40000000)
    if t & 0x20000000:  # SRID present: consume (and discard) the 4-byte SRID
        pos += 4
    t &= 0x1FFFFFFF
    if t >= 1000:
        iso_dim = t // 1000
        has_z = has_z or iso_dim in (1, 3)
        has_m = has_m or iso_dim in (2, 3)
        t %= 1000
    ndim = 2 + has_z + has_m
    dfmt = "<" if little else ">"
    if t == 1:
        vals = struct.unpack_from(dfmt + "d" * ndim, buf, pos)
        return Point(vals[0], vals[1]), pos + 8 * ndim
    if t == 2:
        coords, pos = _wkb_read_coords(buf, pos, little, ndim)
        return LineString(coords), pos
    if t == 3:
        (n,) = struct.unpack_from(fmt, buf, pos)
        pos += 4
        rings = []
        for _ in range(n):
            r, pos = _wkb_read_coords(buf, pos, little, ndim)
            rings.append(r)
        return Polygon(rings[0], tuple(rings[1:])), pos
    if t in (4, 5, 6):
        (n,) = struct.unpack_from(fmt, buf, pos)
        pos += 4
        parts = []
        for _ in range(n):
            g, pos = _wkb_read(buf, pos)
            parts.append(g)
        if t == 4:
            return MultiPoint(np.array([[g.x, g.y] for g in parts])), pos
        if t == 5:
            return MultiLineString(tuple(parts)), pos
        return MultiPolygon(tuple(parts)), pos
    raise ValueError(f"unsupported WKB type {t}")


def _wkb_read_coords(buf: memoryview, pos: int, little: bool, ndim: int = 2):
    fmt = "<I" if little else ">I"
    (n,) = struct.unpack_from(fmt, buf, pos)
    pos += 4
    dt = "<f8" if little else ">f8"
    size = 8 * ndim * n
    coords = np.frombuffer(buf[pos:pos + size], dtype=dt).reshape(n, ndim)
    return coords[:, :2].astype(np.float64), pos + size


# ---------------------------------------------------------------------------
# TWKB (precision-scaled zigzag varint deltas)
# ---------------------------------------------------------------------------

def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _unzigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _varint(v: int, out: bytearray) -> None:
    v &= 0xFFFFFFFFFFFFFFFF
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf, pos: int):
    shift = 0
    val = 0
    while True:
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


class _TwkbWriter:
    def __init__(self, precision: int):
        self.scale = 10 ** precision
        self.out = bytearray()
        self.last = [0, 0]

    def header(self, wkb_type: int, precision: int) -> None:
        self.out.append(((_zigzag(precision) & 0x0F) << 4) | wkb_type)
        self.out.append(0)  # no metadata extras

    def coords(self, coords: np.ndarray, count_prefix: bool = True) -> None:
        q = np.round(np.asarray(coords, dtype=np.float64) * self.scale
                     ).astype(np.int64)
        if count_prefix:
            _varint(len(q), self.out)
        for x, y in q:
            _varint(_zigzag(int(x) - self.last[0]), self.out)
            _varint(_zigzag(int(y) - self.last[1]), self.out)
            self.last = [int(x), int(y)]


def twkb_encode(geom: Geometry, precision: int = 7) -> bytes:
    if not -8 <= precision <= 7:  # zigzag(precision) must fit the header nibble
        raise ValueError(f"TWKB precision must be in [-8, 7], got {precision}")
    w = _TwkbWriter(precision)
    t = _WKB_TYPES[geom.geom_type]
    w.header(t, precision)
    if isinstance(geom, Point):
        w.coords(np.array([[geom.x, geom.y]]), count_prefix=False)
    elif isinstance(geom, LineString):
        w.coords(geom.coords)
    elif isinstance(geom, MultiPoint):
        w.coords(geom.coords)
    elif isinstance(geom, Polygon):
        _varint(1 + len(geom.holes), w.out)
        for r in [geom.shell, *geom.holes]:
            w.coords(r)
    elif isinstance(geom, MultiLineString):
        _varint(len(geom.lines), w.out)
        for l in geom.lines:
            w.coords(l.coords)
    elif isinstance(geom, MultiPolygon):
        _varint(len(geom.polygons), w.out)
        for p in geom.polygons:
            _varint(1 + len(p.holes), w.out)
            for r in [p.shell, *p.holes]:
                w.coords(r)
    else:  # pragma: no cover
        raise ValueError(f"cannot TWKB-encode {geom.geom_type}")
    return bytes(w.out)


class _TwkbReader:
    def __init__(self, raw: bytes):
        self.buf = raw
        self.pos = 0
        self.last = [0, 0]
        head = raw[0]
        self.type = head & 0x0F
        self.precision = _unzigzag(head >> 4)
        self.scale = 10 ** self.precision
        self.pos = 2  # skip header + metadata byte

    def varint(self) -> int:
        v, self.pos = _read_varint(self.buf, self.pos)
        return v

    def coords(self, n: int | None = None) -> np.ndarray:
        if n is None:
            n = self.varint()
        out = np.empty((n, 2), dtype=np.float64)
        for i in range(n):
            self.last[0] += _unzigzag(self.varint())
            self.last[1] += _unzigzag(self.varint())
            out[i, 0] = self.last[0] / self.scale
            out[i, 1] = self.last[1] / self.scale
        return out


def twkb_decode(raw: bytes) -> Geometry:
    r = _TwkbReader(raw)
    t = r.type
    if t == 1:
        c = r.coords(1)
        return Point(float(c[0, 0]), float(c[0, 1]))
    if t == 2:
        return LineString(r.coords())
    if t == 3:
        rings = [r.coords() for _ in range(r.varint())]
        return Polygon(rings[0], tuple(rings[1:]))
    if t == 4:
        return MultiPoint(r.coords())
    if t == 5:
        return MultiLineString(tuple(LineString(r.coords())
                                     for _ in range(r.varint())))
    if t == 6:
        polys = []
        for _ in range(r.varint()):
            rings = [r.coords() for _ in range(r.varint())]
            polys.append(Polygon(rings[0], tuple(rings[1:])))
        return MultiPolygon(tuple(polys))
    raise ValueError(f"unsupported TWKB type {t}")
