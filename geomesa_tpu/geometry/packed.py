"""Packed SoA geometry columns: flat buffers for batches of geometries.

The reference serializes geometries per-row with WKB/TWKB codecs
(geomesa-features/.../serialization/TwkbSerialization.scala) because its
storage is row-oriented KV.  Device-resident columnar storage wants the
opposite: one flat coordinate buffer plus offset arrays (arrow-style
nesting), so vertex data can live in HBM and predicates can run as dense
array ops.

Nesting model (three levels, covering all seven WKT families):

``geometry → part → ring → coords``

* Point/LineString: 1 part, 1 ring.
* MultiPoint: 1 part, 1 ring (the point list).
* Polygon: 1 part, ring 0 = shell, rings 1.. = holes.
* MultiLineString: one part per line.
* MultiPolygon: one part per polygon.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)

__all__ = ["PackedGeometry", "pack_geometries", "packed_from_boxes",
           "GEOM_KIND"]

GEOM_KIND = {
    "Point": 0, "MultiPoint": 1, "LineString": 2,
    "MultiLineString": 3, "Polygon": 4, "MultiPolygon": 5,
}
_KIND_NAMES = {v: k for k, v in GEOM_KIND.items()}


def _expand_ranges_np(starts: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Concatenate ``arange(starts[k], starts[k]+counts[k])`` for all k
    (vectorized; the classic cumsum-of-deltas trick)."""
    starts = np.asarray(starts, dtype=np.int64)
    counts = np.asarray(counts, dtype=np.int64)
    nz = counts > 0
    starts, counts = starts[nz], counts[nz]
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    out = np.ones(total, dtype=np.int64)
    ends = np.cumsum(counts)
    out[0] = starts[0]
    out[ends[:-1]] = starts[1:] - (starts[:-1] + counts[:-1] - 1)
    return np.cumsum(out)


@dataclass
class PackedGeometry:
    """A column of N geometries in flat SoA buffers."""

    kinds: np.ndarray             # (N,) int8
    coords: np.ndarray            # (C, 2) float64
    ring_offsets: np.ndarray      # (R+1,) int64 → coords
    part_ring_offsets: np.ndarray # (P+1,) int64 → rings
    geom_part_offsets: np.ndarray # (N+1,) int64 → parts
    bbox: np.ndarray              # (N, 4) float64: xmin, ymin, xmax, ymax

    def __len__(self) -> int:
        return len(self.kinds)

    def geometry(self, i: int) -> Geometry:
        """Reconstruct the i-th geometry object (host-side)."""
        kind = _KIND_NAMES[int(self.kinds[i])]
        p0, p1 = self.geom_part_offsets[i], self.geom_part_offsets[i + 1]
        parts = []
        for p in range(p0, p1):
            r0, r1 = self.part_ring_offsets[p], self.part_ring_offsets[p + 1]
            rings = [
                self.coords[self.ring_offsets[r]:self.ring_offsets[r + 1]]
                for r in range(r0, r1)
            ]
            parts.append(rings)
        if kind == "Point":
            c = parts[0][0][0]
            return Point(float(c[0]), float(c[1]))
        if kind == "MultiPoint":
            return MultiPoint(parts[0][0])
        if kind == "LineString":
            return LineString(parts[0][0])
        if kind == "MultiLineString":
            return MultiLineString(tuple(LineString(p[0]) for p in parts))
        if kind == "Polygon":
            return Polygon(parts[0][0], tuple(parts[0][1:]))
        return MultiPolygon(tuple(Polygon(p[0], tuple(p[1:])) for p in parts))

    def take(self, positions) -> "PackedGeometry":
        """Row gather as pure offset arithmetic (CSR row selection) — no
        per-row geometry object rebuilds; the hot path for materializing
        non-point query results."""
        positions = np.asarray(positions)
        if positions.dtype == bool:
            positions = np.flatnonzero(positions)
        positions = positions.astype(np.int64)
        kinds = self.kinds[positions]
        bbox = self.bbox[positions]
        gp = self.geom_part_offsets
        part_counts = gp[positions + 1] - gp[positions]
        new_gp = np.concatenate([[0], np.cumsum(part_counts)])
        part_idx = _expand_ranges_np(gp[positions], part_counts)
        pr = self.part_ring_offsets
        ring_counts = pr[part_idx + 1] - pr[part_idx]
        new_pr = np.concatenate([[0], np.cumsum(ring_counts)])
        ring_idx = _expand_ranges_np(pr[part_idx], ring_counts)
        ro = self.ring_offsets
        coord_counts = ro[ring_idx + 1] - ro[ring_idx]
        new_ro = np.concatenate([[0], np.cumsum(coord_counts)])
        coord_idx = _expand_ranges_np(ro[ring_idx], coord_counts)
        return PackedGeometry(
            kinds=kinds, coords=self.coords[coord_idx],
            ring_offsets=new_ro, part_ring_offsets=new_pr,
            geom_part_offsets=new_gp, bbox=bbox)

    def concat(self, other: "PackedGeometry") -> "PackedGeometry":
        """Buffer concatenation with offset shifts (no object rebuilds)."""
        return PackedGeometry(
            kinds=np.concatenate([self.kinds, other.kinds]),
            coords=np.concatenate([self.coords, other.coords]),
            ring_offsets=np.concatenate(
                [self.ring_offsets,
                 other.ring_offsets[1:] + self.ring_offsets[-1]]),
            part_ring_offsets=np.concatenate(
                [self.part_ring_offsets,
                 other.part_ring_offsets[1:] + self.part_ring_offsets[-1]]),
            geom_part_offsets=np.concatenate(
                [self.geom_part_offsets,
                 other.geom_part_offsets[1:] + self.geom_part_offsets[-1]]),
            bbox=np.concatenate([self.bbox, other.bbox]))

    @staticmethod
    def concat_many(parts: list["PackedGeometry"]) -> "PackedGeometry":
        """One-pass concatenation of many packed columns (offset shifts
        computed per field) — pairwise ``concat`` over k chunks copies
        the accumulated buffers k times (O(total x k)); this copies
        each buffer exactly once (review r5)."""
        if len(parts) == 1:
            return parts[0]

        def offsets(field: str) -> np.ndarray:
            arrs = [getattr(parts[0], field)]
            base = arrs[0][-1]
            for p in parts[1:]:
                o = getattr(p, field)
                arrs.append(o[1:] + base)
                base = base + o[-1]
            return np.concatenate(arrs)

        return PackedGeometry(
            kinds=np.concatenate([p.kinds for p in parts]),
            coords=np.concatenate([p.coords for p in parts]),
            ring_offsets=offsets("ring_offsets"),
            part_ring_offsets=offsets("part_ring_offsets"),
            geom_part_offsets=offsets("geom_part_offsets"),
            bbox=np.concatenate([p.bbox for p in parts]))

    def rings_of(self, i: int) -> list[np.ndarray]:
        """All rings of geometry i as coordinate arrays."""
        p0, p1 = self.geom_part_offsets[i], self.geom_part_offsets[i + 1]
        r0, r1 = self.part_ring_offsets[p0], self.part_ring_offsets[p1]
        return [
            self.coords[self.ring_offsets[r]:self.ring_offsets[r + 1]]
            for r in range(r0, r1)
        ]


def _rings_for(geom: Geometry) -> tuple[int, list[list[np.ndarray]]]:
    if isinstance(geom, Point):
        return GEOM_KIND["Point"], [[np.array([[geom.x, geom.y]])]]
    if isinstance(geom, MultiPoint):
        return GEOM_KIND["MultiPoint"], [[geom.coords]]
    if isinstance(geom, LineString):
        return GEOM_KIND["LineString"], [[geom.coords]]
    if isinstance(geom, MultiLineString):
        return GEOM_KIND["MultiLineString"], [[l.coords] for l in geom.lines]
    if isinstance(geom, Polygon):
        return GEOM_KIND["Polygon"], [[geom.shell, *geom.holes]]
    if isinstance(geom, MultiPolygon):
        return GEOM_KIND["MultiPolygon"], [
            [p.shell, *p.holes] for p in geom.polygons
        ]
    raise ValueError(f"cannot pack {geom!r}")


def pack_geometries(geoms) -> PackedGeometry:
    kinds = np.empty(len(geoms), dtype=np.int8)
    coords_parts: list[np.ndarray] = []
    ring_lens: list[int] = []
    part_ring_counts: list[int] = []
    geom_part_counts: list[int] = []
    bbox = np.empty((len(geoms), 4), dtype=np.float64)

    for i, g in enumerate(geoms):
        kind, parts = _rings_for(g)
        kinds[i] = kind
        geom_part_counts.append(len(parts))
        for rings in parts:
            part_ring_counts.append(len(rings))
            for ring in rings:
                coords_parts.append(np.asarray(ring, dtype=np.float64))
                ring_lens.append(len(ring))
        env = g.envelope
        bbox[i] = env.as_tuple()

    coords = (
        np.vstack(coords_parts) if coords_parts else np.empty((0, 2), np.float64)
    )
    ring_offsets = np.concatenate([[0], np.cumsum(ring_lens)]).astype(np.int64)
    part_ring_offsets = np.concatenate(
        [[0], np.cumsum(part_ring_counts)]).astype(np.int64)
    geom_part_offsets = np.concatenate(
        [[0], np.cumsum(geom_part_counts)]).astype(np.int64)
    return PackedGeometry(
        kinds=kinds, coords=coords, ring_offsets=ring_offsets,
        part_ring_offsets=part_ring_offsets,
        geom_part_offsets=geom_part_offsets, bbox=bbox,
    )


def packed_from_boxes(bbox: np.ndarray) -> "PackedGeometry":
    """Vectorized axis-aligned rectangles ``(n, 4)`` → packed polygons:
    the OBJECT-FREE bulk-ingest path (constructing 200M Python Polygon
    objects would dominate a scale build; real bulk feeds — building
    footprints, tiles, coverage cells — arrive as envelope arrays
    anyway).  Shells follow the packer's convention (closed ring, CCW
    corner order)."""
    bb = np.ascontiguousarray(np.asarray(bbox, np.float64)
                              .reshape((-1, 4)))
    n = len(bb)
    coords = np.empty((n * 5, 2), np.float64)
    coords[0::5] = bb[:, [0, 1]]
    coords[1::5] = bb[:, [2, 1]]
    coords[2::5] = bb[:, [2, 3]]
    coords[3::5] = bb[:, [0, 3]]
    coords[4::5] = bb[:, [0, 1]]
    idx = np.arange(n + 1, dtype=np.int64)
    return PackedGeometry(
        kinds=np.full(n, GEOM_KIND["Polygon"], np.int8),
        coords=coords,
        ring_offsets=idx * 5,
        part_ring_offsets=idx.copy(),
        geom_part_offsets=idx.copy(),
        bbox=bb.copy())
