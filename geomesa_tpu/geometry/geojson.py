"""GeoJSON geometry codec (shared by converters and the GeoJSON API)."""

from __future__ import annotations

from .types import (
    Geometry, LineString, MultiLineString, MultiPoint, MultiPolygon, Point,
    Polygon,
)

__all__ = ["geojson_to_geometry", "geometry_to_geojson"]


def geojson_to_geometry(g: dict) -> Geometry:
    """GeoJSON geometry dict → framework Geometry."""
    t, c = g["type"], g.get("coordinates")
    if t == "Point":
        return Point(c[0], c[1])
    if t == "LineString":
        return LineString(c)
    if t == "Polygon":
        return Polygon(c[0], tuple(c[1:]))
    if t == "MultiPoint":
        return MultiPoint(c)
    if t == "MultiLineString":
        return MultiLineString(tuple(LineString(l) for l in c))
    if t == "MultiPolygon":
        return MultiPolygon(tuple(Polygon(p[0], tuple(p[1:])) for p in c))
    raise ValueError(f"unsupported GeoJSON geometry type {t!r}")


def geometry_to_geojson(geom: Geometry) -> dict:
    """Framework Geometry → GeoJSON geometry dict."""
    if isinstance(geom, Point):
        return {"type": "Point", "coordinates": [geom.x, geom.y]}
    if isinstance(geom, MultiPoint):
        return {"type": "MultiPoint", "coordinates": geom.coords.tolist()}
    if isinstance(geom, LineString):
        return {"type": "LineString", "coordinates": geom.coords.tolist()}
    if isinstance(geom, MultiLineString):
        return {"type": "MultiLineString",
                "coordinates": [l.coords.tolist() for l in geom.lines]}
    if isinstance(geom, Polygon):
        return {"type": "Polygon",
                "coordinates": [geom.shell.tolist()]
                + [h.tolist() for h in geom.holes]}
    if isinstance(geom, MultiPolygon):
        return {"type": "MultiPolygon",
                "coordinates": [[p.shell.tolist()]
                                + [h.tolist() for h in p.holes]
                                for p in geom.polygons]}
    raise ValueError(f"cannot encode {type(geom).__name__} as GeoJSON")
