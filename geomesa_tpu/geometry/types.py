"""Geometry object model (host side).

Minimal, self-contained replacement for the JTS types the reference builds
on (com.vividsolutions.jts.geom.*): coordinates are numpy ``(n, 2)``
float64 arrays; polygons are a shell plus optional holes; envelopes are
(xmin, ymin, xmax, ymax).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import ClassVar, Iterable, Sequence

import numpy as np

__all__ = [
    "Envelope", "Geometry", "Point", "MultiPoint", "LineString",
    "MultiLineString", "Polygon", "MultiPolygon",
]


@dataclass(frozen=True)
class Envelope:
    xmin: float
    ymin: float
    xmax: float
    ymax: float

    WHOLE_WORLD: ClassVar["Envelope"]  # assigned after class definition

    def intersects(self, other: "Envelope") -> bool:
        return not (
            self.xmax < other.xmin or other.xmax < self.xmin
            or self.ymax < other.ymin or other.ymax < self.ymin
        )

    def contains(self, other: "Envelope") -> bool:
        return (
            self.xmin <= other.xmin and self.ymin <= other.ymin
            and self.xmax >= other.xmax and self.ymax >= other.ymax
        )

    def intersection(self, other: "Envelope") -> "Envelope | None":
        if not self.intersects(other):
            return None
        return Envelope(
            max(self.xmin, other.xmin), max(self.ymin, other.ymin),
            min(self.xmax, other.xmax), min(self.ymax, other.ymax),
        )

    def expand(self, other: "Envelope") -> "Envelope":
        return Envelope(
            min(self.xmin, other.xmin), min(self.ymin, other.ymin),
            max(self.xmax, other.xmax), max(self.ymax, other.ymax),
        )

    @property
    def width(self) -> float:
        return self.xmax - self.xmin

    @property
    def height(self) -> float:
        return self.ymax - self.ymin

    @property
    def area(self) -> float:
        return max(0.0, self.width) * max(0.0, self.height)

    def as_tuple(self):
        return (self.xmin, self.ymin, self.xmax, self.ymax)


Envelope.WHOLE_WORLD = Envelope(-180.0, -90.0, 180.0, 90.0)


class Geometry:
    """Base class; subclasses expose ``envelope`` and ``geom_type``."""

    geom_type: str = "Geometry"

    @property
    def envelope(self) -> Envelope:
        raise NotImplementedError

    @property
    def is_point(self) -> bool:
        return isinstance(self, Point)


def _coords(a) -> np.ndarray:
    out = np.asarray(a, dtype=np.float64)
    if out.ndim != 2 or out.shape[1] != 2:
        raise ValueError(f"expected (n, 2) coordinates, got shape {out.shape}")
    return out


def _env_of(coords: np.ndarray) -> Envelope:
    return Envelope(
        float(coords[:, 0].min()), float(coords[:, 1].min()),
        float(coords[:, 0].max()), float(coords[:, 1].max()),
    )


@dataclass(frozen=True)
class Point(Geometry):
    x: float
    y: float
    geom_type = "Point"

    @property
    def envelope(self) -> Envelope:
        return Envelope(self.x, self.y, self.x, self.y)


@dataclass(frozen=True)
class MultiPoint(Geometry):
    coords: np.ndarray  # (n, 2)
    geom_type = "MultiPoint"

    def __post_init__(self):
        object.__setattr__(self, "coords", _coords(self.coords))

    @property
    def envelope(self) -> Envelope:
        return _env_of(self.coords)


@dataclass(frozen=True)
class LineString(Geometry):
    coords: np.ndarray  # (n, 2)
    geom_type = "LineString"

    def __post_init__(self):
        object.__setattr__(self, "coords", _coords(self.coords))

    @property
    def envelope(self) -> Envelope:
        return _env_of(self.coords)


@dataclass(frozen=True)
class MultiLineString(Geometry):
    lines: tuple
    geom_type = "MultiLineString"

    def __post_init__(self):
        object.__setattr__(
            self, "lines",
            tuple(l if isinstance(l, LineString) else LineString(l) for l in self.lines),
        )

    @property
    def envelope(self) -> Envelope:
        env = self.lines[0].envelope
        for l in self.lines[1:]:
            env = env.expand(l.envelope)
        return env


@dataclass(frozen=True)
class Polygon(Geometry):
    shell: np.ndarray          # (n, 2), closed or open (auto-closed)
    holes: tuple = ()
    geom_type = "Polygon"

    def __post_init__(self):
        shell = _coords(self.shell)
        if not np.array_equal(shell[0], shell[-1]):
            shell = np.vstack([shell, shell[:1]])
        object.__setattr__(self, "shell", shell)
        holes = []
        for h in self.holes:
            h = _coords(h)
            if not np.array_equal(h[0], h[-1]):
                h = np.vstack([h, h[:1]])
            holes.append(h)
        object.__setattr__(self, "holes", tuple(holes))

    @property
    def envelope(self) -> Envelope:
        return _env_of(self.shell)

    @classmethod
    def from_envelope(cls, env: Envelope) -> "Polygon":
        return cls(np.array([
            [env.xmin, env.ymin], [env.xmax, env.ymin],
            [env.xmax, env.ymax], [env.xmin, env.ymax], [env.xmin, env.ymin],
        ]))


@dataclass(frozen=True)
class MultiPolygon(Geometry):
    polygons: tuple
    geom_type = "MultiPolygon"

    def __post_init__(self):
        object.__setattr__(
            self, "polygons",
            tuple(p if isinstance(p, Polygon) else Polygon(p) for p in self.polygons),
        )

    @property
    def envelope(self) -> Envelope:
        env = self.polygons[0].envelope
        for p in self.polygons[1:]:
            env = env.expand(p.envelope)
        return env
