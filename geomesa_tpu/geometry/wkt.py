"""WKT (Well-Known Text) reader/writer for the geometry object model —
replaces the reference's use of JTS WKTReader (geomesa-utils
WKTUtils)."""

from __future__ import annotations

import re

import numpy as np

from .types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)

__all__ = ["geometry_from_wkt", "geometry_to_wkt"]

_TYPE_RE = re.compile(r"^\s*([A-Za-z]+)\s*(.*)$", re.DOTALL)


def _parse_coord_list(body: str) -> np.ndarray:
    pts = []
    for pair in body.split(","):
        parts = pair.split()
        if len(parts) < 2:
            raise ValueError(f"bad coordinate {pair!r}")
        pts.append((float(parts[0]), float(parts[1])))
    return np.asarray(pts, dtype=np.float64)


def _split_groups(body: str) -> list[str]:
    """Split a parenthesized group list '(...),(...),...' at depth 0."""
    groups, depth, start = [], 0, None
    for i, ch in enumerate(body):
        if ch == "(":
            if depth == 0:
                start = i + 1
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                groups.append(body[start:i])
    if depth != 0:
        raise ValueError("unbalanced parentheses in WKT")
    return groups


def geometry_from_wkt(wkt: str) -> Geometry:
    m = _TYPE_RE.match(wkt)
    if not m:
        raise ValueError(f"invalid WKT: {wkt!r}")
    gtype = m.group(1).upper()
    rest = m.group(2).strip()
    if rest.upper() == "EMPTY":
        raise ValueError(f"empty geometries not supported: {wkt!r}")
    if gtype == "POINT":
        coords = _parse_coord_list(_split_groups(rest)[0] if "(" in rest else rest)
        return Point(float(coords[0, 0]), float(coords[0, 1]))
    if gtype == "LINESTRING":
        return LineString(_parse_coord_list(_split_groups(rest)[0]))
    if gtype == "POLYGON":
        rings = [_parse_coord_list(g) for g in _split_groups(rest[1:-1])]
        return Polygon(rings[0], tuple(rings[1:]))
    if gtype == "MULTIPOINT":
        inner = rest[1:-1].strip()
        if "(" in inner:
            coords = np.vstack([_parse_coord_list(g) for g in _split_groups(inner)])
        else:
            coords = _parse_coord_list(inner)
        return MultiPoint(coords)
    if gtype == "MULTILINESTRING":
        return MultiLineString(
            tuple(LineString(_parse_coord_list(g)) for g in _split_groups(rest[1:-1]))
        )
    if gtype == "MULTIPOLYGON":
        polys = []
        for poly_body in _split_groups(rest[1:-1]):
            # poly_body is the polygon's ring list '(r1), (r2)…'
            ring_groups = _split_groups(poly_body)
            if ring_groups:
                rings = [_parse_coord_list(g) for g in ring_groups]
            else:  # bare ring without inner parens
                rings = [_parse_coord_list(poly_body)]
            polys.append(Polygon(rings[0], tuple(rings[1:])))
        return MultiPolygon(tuple(polys))
    raise ValueError(f"unsupported WKT type: {gtype}")


def _fmt(v: float) -> str:
    if v == int(v):
        return str(int(v))
    return repr(v)


def _coords_to_wkt(coords: np.ndarray) -> str:
    return ", ".join(f"{_fmt(x)} {_fmt(y)}" for x, y in coords)


def geometry_to_wkt(geom: Geometry) -> str:
    if isinstance(geom, Point):
        return f"POINT ({_fmt(geom.x)} {_fmt(geom.y)})"
    if isinstance(geom, LineString):
        return f"LINESTRING ({_coords_to_wkt(geom.coords)})"
    if isinstance(geom, Polygon):
        rings = [geom.shell, *geom.holes]
        inner = ", ".join(f"({_coords_to_wkt(r)})" for r in rings)
        return f"POLYGON ({inner})"
    if isinstance(geom, MultiPoint):
        return f"MULTIPOINT ({_coords_to_wkt(geom.coords)})"
    if isinstance(geom, MultiLineString):
        inner = ", ".join(f"({_coords_to_wkt(l.coords)})" for l in geom.lines)
        return f"MULTILINESTRING ({inner})"
    if isinstance(geom, MultiPolygon):
        parts = []
        for p in geom.polygons:
            rings = [p.shell, *p.holes]
            parts.append("(" + ", ".join(f"({_coords_to_wkt(r)})" for r in rings) + ")")
        return f"MULTIPOLYGON ({', '.join(parts)})"
    raise ValueError(f"unsupported geometry: {geom!r}")
