"""Vectorized geometry predicates.

The exact re-check stage of query evaluation: after index ranges produce
candidates (a superset), these predicates compute the final hit set — the
role the reference delegates to CQL geometry evaluation inside
FilterTransformIterator / FastFilterFactory (geomesa-filter).

All core tests are numpy-vectorized over points × segments.  Boundary
semantics follow JTS ``intersects``: points on a polygon boundary are
inside; touching segments intersect.
"""

from __future__ import annotations

import numpy as np

from .types import Envelope, Geometry, LineString, MultiLineString, MultiPoint, MultiPolygon, Point, Polygon

__all__ = [
    "bbox_intersects",
    "point_in_polygon",
    "points_in_packed_polygon",
    "points_on_rings",
    "segments_intersect",
    "geometry_intersects",
    "packed_intersects",
]

_EDGE_CHUNK = 4096  # bound the (points × edges) broadcast memory


def bbox_intersects(bbox: np.ndarray, window) -> np.ndarray:
    """(N, 4) bbox column vs one (xmin, ymin, xmax, ymax) window → mask."""
    bbox = np.asarray(bbox)
    return (
        (bbox[:, 0] <= window[2]) & (bbox[:, 2] >= window[0])
        & (bbox[:, 1] <= window[3]) & (bbox[:, 3] >= window[1])
    )


def _rings_of(geom: Geometry) -> list[np.ndarray]:
    if isinstance(geom, Polygon):
        return [geom.shell, *geom.holes]
    if isinstance(geom, MultiPolygon):
        out = []
        for p in geom.polygons:
            out.extend([p.shell, *p.holes])
        return out
    raise ValueError(f"expected polygonal geometry, got {geom.geom_type}")


def _crossing_parity(px: np.ndarray, py: np.ndarray, rings) -> np.ndarray:
    """Even-odd ray casting: odd number of upward/downward edge crossings to
    the right of the point ⇒ inside.  Holes flip parity naturally."""
    inside = np.zeros(px.shape, dtype=bool)
    for ring in rings:
        x1, y1 = ring[:-1, 0], ring[:-1, 1]
        x2, y2 = ring[1:, 0], ring[1:, 1]
        for s in range(0, len(x1), _EDGE_CHUNK):
            ex1, ey1 = x1[s:s + _EDGE_CHUNK], y1[s:s + _EDGE_CHUNK]
            ex2, ey2 = x2[s:s + _EDGE_CHUNK], y2[s:s + _EDGE_CHUNK]
            straddle = (ey1[None, :] > py[:, None]) != (ey2[None, :] > py[:, None])
            with np.errstate(divide="ignore", invalid="ignore"):
                xint = ex1[None, :] + (py[:, None] - ey1[None, :]) / (
                    ey2[None, :] - ey1[None, :]
                ) * (ex2[None, :] - ex1[None, :])
            hits = straddle & (px[:, None] < xint)
            inside ^= (np.sum(hits, axis=1) % 2).astype(bool)
    return inside


def points_on_rings(px: np.ndarray, py: np.ndarray, rings, eps: float = 0.0) -> np.ndarray:
    """True where a point lies exactly on any ring segment (boundary)."""
    on = np.zeros(px.shape, dtype=bool)
    for ring in rings:
        x1, y1 = ring[:-1, 0], ring[:-1, 1]
        x2, y2 = ring[1:, 0], ring[1:, 1]
        for s in range(0, len(x1), _EDGE_CHUNK):
            ex1, ey1 = x1[s:s + _EDGE_CHUNK], y1[s:s + _EDGE_CHUNK]
            ex2, ey2 = x2[s:s + _EDGE_CHUNK], y2[s:s + _EDGE_CHUNK]
            dx, dy = ex2 - ex1, ey2 - ey1
            vx = px[:, None] - ex1[None, :]
            vy = py[:, None] - ey1[None, :]
            cross = np.abs(vx * dy[None, :] - vy * dx[None, :])
            dot = vx * dx[None, :] + vy * dy[None, :]
            sq = (dx * dx + dy * dy)[None, :]
            on |= ((cross <= eps * np.sqrt(np.maximum(sq, 1e-300)))
                   & (dot >= 0) & (dot <= sq)).any(axis=1) if eps else (
                (cross == 0) & (dot >= 0) & (dot <= sq)).any(axis=1)
    return on


def point_in_polygon(px, py, geom: Geometry, include_boundary: bool = True) -> np.ndarray:
    """Vectorized point-in-(Multi)Polygon with even-odd hole handling."""
    px = np.asarray(px, dtype=np.float64)
    py = np.asarray(py, dtype=np.float64)
    rings = _rings_of(geom)
    inside = _crossing_parity(px, py, rings)
    if include_boundary and inside.ndim and not inside.all():
        # boundary test only for parity-outside points (x|y == x|(y&~x))
        # — the on-segment broadcast is the costlier half
        out = np.flatnonzero(~inside)
        inside[out] = points_on_rings(px[out], py[out], rings)
    elif include_boundary and not inside.ndim:
        inside = inside | points_on_rings(px, py, rings)
    return inside


def points_in_packed_polygon(px, py, packed, i: int) -> np.ndarray:
    """Point-in-polygon against geometry ``i`` of a PackedGeometry column."""
    rings = packed.rings_of(i)
    px = np.asarray(px, dtype=np.float64)
    py = np.asarray(py, dtype=np.float64)
    return _crossing_parity(px, py, rings) | points_on_rings(px, py, rings)


def _segment_orientations(p1, p2, q1, q2):
    """Broadcast (A,2)×(B,2) endpoints to the four orientation terms the
    crossing tests share; returns (p1, p2, q1, q2, d1, d2, d3, d4) with
    operands reshaped to (A, 1, 2)/(1, B, 2)."""
    p1 = np.asarray(p1, np.float64)[:, None, :]
    p2 = np.asarray(p2, np.float64)[:, None, :]
    q1 = np.asarray(q1, np.float64)[None, :, :]
    q2 = np.asarray(q2, np.float64)[None, :, :]

    def cross(o, a, b):
        return (a[..., 0] - o[..., 0]) * (b[..., 1] - o[..., 1]) - (
            a[..., 1] - o[..., 1]) * (b[..., 0] - o[..., 0])

    d1 = cross(q1, q2, p1)
    d2 = cross(q1, q2, p2)
    d3 = cross(p1, p2, q1)
    d4 = cross(p1, p2, q2)
    return p1, p2, q1, q2, d1, d2, d3, d4


def _proper_mask(d1, d2, d3, d4) -> np.ndarray:
    return ((((d1 > 0) & (d2 < 0)) | ((d1 < 0) & (d2 > 0)))
            & (((d3 > 0) & (d4 < 0)) | ((d3 < 0) & (d4 > 0))))


def segments_intersect(p1, p2, q1, q2) -> np.ndarray:
    """Vectorized proper-or-touching segment intersection.

    ``p1, p2``: (A, 2) segment endpoints; ``q1, q2``: (B, 2).  Returns
    (A, B) boolean matrix.  Uses orientation sign tests with collinear
    overlap handled by bbox checks.
    """
    p1, p2, q1, q2, d1, d2, d3, d4 = _segment_orientations(p1, p2, q1, q2)
    proper = _proper_mask(d1, d2, d3, d4)

    def on_bbox(a1, a2, b):
        return (
            (b[..., 0] >= np.minimum(a1[..., 0], a2[..., 0]))
            & (b[..., 0] <= np.maximum(a1[..., 0], a2[..., 0]))
            & (b[..., 1] >= np.minimum(a1[..., 1], a2[..., 1]))
            & (b[..., 1] <= np.maximum(a1[..., 1], a2[..., 1]))
        )

    touch = (
        ((d1 == 0) & on_bbox(q1, q2, p1))
        | ((d2 == 0) & on_bbox(q1, q2, p2))
        | ((d3 == 0) & on_bbox(p1, p2, q1))
        | ((d4 == 0) & on_bbox(p1, p2, q2))
    )
    return proper | touch


def _segments(geom: Geometry) -> tuple[np.ndarray, np.ndarray]:
    rings: list[np.ndarray] = []
    if isinstance(geom, LineString):
        rings = [geom.coords]
    elif isinstance(geom, MultiLineString):
        rings = [l.coords for l in geom.lines]
    elif isinstance(geom, (Polygon, MultiPolygon)):
        rings = _rings_of(geom)
    else:
        return np.empty((0, 2)), np.empty((0, 2))
    a = np.vstack([r[:-1] for r in rings]) if rings else np.empty((0, 2))
    b = np.vstack([r[1:] for r in rings]) if rings else np.empty((0, 2))
    return a, b


def _points_of(geom: Geometry) -> np.ndarray:
    if isinstance(geom, Point):
        return np.array([[geom.x, geom.y]])
    if isinstance(geom, MultiPoint):
        return geom.coords
    if isinstance(geom, LineString):
        return geom.coords
    if isinstance(geom, MultiLineString):
        return np.vstack([l.coords for l in geom.lines])
    if isinstance(geom, Polygon):
        return geom.shell
    if isinstance(geom, MultiPolygon):
        return np.vstack([p.shell for p in geom.polygons])
    raise ValueError(geom)


def all_vertices(geom: Geometry) -> np.ndarray:
    """Every vertex of a geometry, INCLUDING polygon hole rings (unlike
    ``_points_of``, whose shell-only view suffices for intersection
    seeding but not for distance)."""
    if isinstance(geom, (Polygon, MultiPolygon)):
        return np.vstack(_rings_of(geom))
    return _points_of(geom)


def points_to_geometry_dist(px, py, geom: Geometry) -> np.ndarray:
    """Vectorized planar distance (coordinate units) from points to a
    geometry: 0 inside polygons / on lines, else distance to the nearest
    vertex/segment.  Segment work is chunked to bound the (N × S)
    broadcast (same discipline as the edge-chunked predicates)."""
    px = np.asarray(px, dtype=np.float64)
    py = np.asarray(py, dtype=np.float64)
    out = np.full(px.shape, np.inf)
    if isinstance(geom, (Point, MultiPoint)):
        pts = _points_of(geom)
        for qx, qy in pts:
            out = np.minimum(out, np.hypot(px - qx, py - qy))
        return out
    a, b = _segments(geom)
    for s0 in range(0, len(a), _EDGE_CHUNK):
        aa = a[s0:s0 + _EDGE_CHUNK]
        bb = b[s0:s0 + _EDGE_CHUNK]
        ax, ay = aa[:, 0], aa[:, 1]
        bx, by = bb[:, 0], bb[:, 1]
        dx, dy = bx - ax, by - ay
        ln2 = dx * dx + dy * dy
        ln2 = np.where(ln2 == 0, 1.0, ln2)
        t = ((px[:, None] - ax[None, :]) * dx[None, :]
             + (py[:, None] - ay[None, :]) * dy[None, :]) / ln2[None, :]
        t = np.clip(t, 0.0, 1.0)
        cx = ax[None, :] + t * dx[None, :]
        cy = ay[None, :] + t * dy[None, :]
        d = np.hypot(px[:, None] - cx, py[:, None] - cy)
        out = np.minimum(out, d.min(axis=1))
    if isinstance(geom, (Polygon, MultiPolygon)):
        inside = point_in_polygon(px, py, geom)
        out = np.where(inside, 0.0, out)
    return out


def geometry_to_point_dist(geom: Geometry, qx: float, qy: float) -> float:
    """Planar distance from a geometry to a point (0 when the point is
    inside/on the geometry)."""
    if isinstance(geom, Point):
        return float(np.hypot(geom.x - qx, geom.y - qy))
    return float(points_to_geometry_dist(
        np.array([qx]), np.array([qy]), geom)[0])


def segments_cross_properly(p1, p2, q1, q2) -> np.ndarray:
    """Strict interior crossings only (touching/collinear excluded) —
    the test that distinguishes "within with boundary contact" from a
    genuine boundary violation."""
    _, _, _, _, d1, d2, d3, d4 = _segment_orientations(p1, p2, q1, q2)
    return _proper_mask(d1, d2, d3, d4)


def geometry_within(a: Geometry, b: Geometry) -> bool:
    """``a`` within ``b`` (boundary contact allowed): every vertex of
    ``a`` (hole rings included) lies in the closure of ``b`` and no
    segment of ``a`` properly crosses ``b``'s boundary.  Exact for the
    supported lattice up to degenerate collinear-overlap edge cases."""
    if not b.envelope.contains(a.envelope):
        return False
    if isinstance(b, (Polygon, MultiPolygon)):
        va = all_vertices(a)
        if not point_in_polygon(va[:, 0], va[:, 1], b).all():
            return False
        a1, a2 = _segments(a)
        b1, b2 = _segments(b)
        if len(a1) and len(b1) and bool(
                segments_cross_properly(a1, a2, b1, b2).any()):
            return False
        if len(a1):
            # a segment can leave b between two boundary vertices with
            # only touching (no proper) crossings — e.g. a chord across a
            # notch; its midpoint betrays it
            mx = (a1[:, 0] + a2[:, 0]) / 2
            my = (a1[:, 1] + a2[:, 1]) / 2
            if not point_in_polygon(mx, my, b).all():
                return False
        if isinstance(a, (Polygon, MultiPolygon)):
            # a hole of b lying strictly inside a's interior escapes both
            # tests above; any b-ring vertex strictly inside a betrays it
            vb = all_vertices(b)
            inside = point_in_polygon(vb[:, 0], vb[:, 1], a)
            if inside.any():
                idx = np.flatnonzero(inside)
                a_rings = _rings_of(a)
                on_edge = points_on_rings(vb[idx, 0], vb[idx, 1], a_rings)
                if bool((~on_edge).any()):
                    return False
        return True
    if isinstance(b, (LineString, MultiLineString)):
        # only puntal/lineal a can be within a line; vertices AND segment
        # midpoints must sit on it (vertices alone miss a diagonal whose
        # endpoints touch the line but whose body leaves it)
        if isinstance(a, (Polygon, MultiPolygon)):
            return False
        va = all_vertices(a)
        rings = ([b.coords] if isinstance(b, LineString)
                 else [l.coords for l in b.lines])
        if not bool(points_on_rings(va[:, 0], va[:, 1], rings).all()):
            return False
        a1, a2 = _segments(a)
        if len(a1):
            mx = (a1[:, 0] + a2[:, 0]) / 2
            my = (a1[:, 1] + a2[:, 1]) / 2
            if not bool(points_on_rings(mx, my, rings).all()):
                return False
        return True
    # b is (multi)point: a must be a coincident (multi)point
    if isinstance(a, (Point, MultiPoint)):
        bp = {tuple(p) for p in _points_of(b)}
        return all(tuple(p) in bp for p in _points_of(a))
    return False


def geometry_distance(a: Geometry, b: Geometry) -> float:
    """Planar min distance between two geometries (0 when intersecting).

    For non-crossing segment sets the minimum is attained at a vertex of
    one operand, so min(vertices(a)→b, vertices(b)→a) is exact once
    crossings are handled by the intersects check."""
    if geometry_intersects(a, b):
        return 0.0
    va = all_vertices(a)
    vb = all_vertices(b)
    d1 = points_to_geometry_dist(va[:, 0], va[:, 1], b).min()
    d2 = points_to_geometry_dist(vb[:, 0], vb[:, 1], a).min()
    return float(min(d1, d2))


def geometry_intersects(a: Geometry, b: Geometry) -> bool:
    """JTS-style ``intersects`` dispatch over the supported type lattice."""
    if not a.envelope.intersects(b.envelope):
        return False
    a_poly = isinstance(a, (Polygon, MultiPolygon))
    b_poly = isinstance(b, (Polygon, MultiPolygon))
    a_pts = _points_of(a)
    b_pts = _points_of(b)
    # vertex containment either direction
    if b_poly and point_in_polygon(a_pts[:, 0], a_pts[:, 1], b).any():
        return True
    if a_poly and point_in_polygon(b_pts[:, 0], b_pts[:, 1], a).any():
        return True
    # point-only operands are settled by containment / coincidence
    if isinstance(a, (Point, MultiPoint)) or isinstance(b, (Point, MultiPoint)):
        if isinstance(a, (Point, MultiPoint)) and isinstance(b, (Point, MultiPoint)):
            return bool(
                (np.abs(a_pts[:, None, :] - b_pts[None, :, :]).sum(axis=2) == 0).any()
            )
        pts, other = (a_pts, b) if isinstance(a, (Point, MultiPoint)) else (b_pts, a)
        if isinstance(other, (LineString, MultiLineString)):
            s1, s2 = _segments(other)
            rings = [np.vstack([p1, p2]) for p1, p2 in zip(s1, s2)]
            return bool(points_on_rings(pts[:, 0], pts[:, 1], rings).any())
        return False  # polygon cases already handled above
    # segment crossings
    a1, a2 = _segments(a)
    b1, b2 = _segments(b)
    if a1.size and b1.size:
        # chunk to bound memory
        for s in range(0, len(a1), _EDGE_CHUNK):
            if segments_intersect(a1[s:s + _EDGE_CHUNK], a2[s:s + _EDGE_CHUNK], b1, b2).any():
                return True
    return False


#: candidates per block for the packed re-check's broadcast stages
_CAND_CHUNK = 1 << 16


def _packed_edges(sub, pt_kind_of_coord: np.ndarray):
    """Edge endpoint indices of a PackedGeometry: consecutive coord pairs
    within each ring, excluding point-kind geometries (their 'rings' are
    point lists, not polylines)."""
    ro = sub.ring_offsets
    C = len(sub.coords)
    emask = np.ones(C, dtype=bool)
    emask[np.maximum(ro[1:] - 1, 0)] = False  # last coord of each ring
    emask &= ~pt_kind_of_coord
    return np.flatnonzero(emask)


def packed_intersects(packed, query: Geometry,
                      positions=None) -> np.ndarray:
    """Vectorized JTS-style ``intersects`` of EVERY candidate geometry in
    a PackedGeometry column against ONE query geometry.

    The batched form of :func:`geometry_intersects` — identical test
    structure (envelope → vertex containment both ways → point-kind
    coincidence/on-line → segment crossings) evaluated as dense array
    ops over the SoA buffers, replacing the per-candidate Python loop of
    the exact re-check (the server-side filter role,
    accumulo/data/AccumuloIndexAdapter.scala:181-195).  Returns a bool
    mask aligned with ``positions`` (or the whole column)."""
    sub = (packed if positions is None
           else packed.take(np.asarray(positions, dtype=np.int64)))
    n = len(sub)
    if n == 0:
        return np.zeros(0, dtype=bool)
    env = query.envelope
    alive = bbox_intersects(sub.bbox, env.as_tuple())
    hit = np.zeros(n, dtype=bool)
    if not alive.any():
        return hit

    gp, pr, ro = (sub.geom_part_offsets, sub.part_ring_offsets,
                  sub.ring_offsets)
    coords = sub.coords
    kinds = sub.kinds
    poly_kind = (kinds == 4) | (kinds == 5)
    line_kind = (kinds == 2) | (kinds == 3)
    pt_kind = (kinds == 0) | (kinds == 1)
    ring_geom = np.repeat(np.arange(n), pr[gp[1:]] - pr[gp[:-1]])
    coord_ring = np.repeat(np.arange(len(ro) - 1), np.diff(ro))
    coord_geom = ring_geom[coord_ring]
    part_of_ring = np.repeat(np.arange(len(pr) - 1), np.diff(pr))
    ring_rank = np.arange(len(ro) - 1) - pr[part_of_ring]

    b_poly = isinstance(query, (Polygon, MultiPolygon))
    b_line = isinstance(query, (LineString, MultiLineString))
    b_pt = isinstance(query, (Point, MultiPoint))
    b_pts = _points_of(query)

    # --- any A vertex in B (B polygonal); shell-only for polygon
    # candidates, all coords otherwise (_points_of semantics) ---
    if b_poly:
        a_pts_sel = ((~poly_kind[coord_geom])
                     | (ring_rank[coord_ring] == 0)) & alive[coord_geom]
        idx = np.flatnonzero(a_pts_sel)
        if len(idx):
            inb = point_in_polygon(coords[idx, 0], coords[idx, 1], query)
            np.logical_or.at(hit, coord_geom[idx], inb)

    # --- edges of line/poly candidates (owner per edge) ---
    eidx = _packed_edges(sub, pt_kind[coord_geom])
    e_owner = coord_geom[eidx]

    # --- any B vertex in A (A polygonal): per-candidate crossing parity
    # + boundary, chunked over candidate blocks ---
    poly_alive = np.flatnonzero(poly_kind & alive & ~hit)
    if len(poly_alive) and len(b_pts):
        pxq, pyq = b_pts[:, 0], b_pts[:, 1]
        # restrict to edges owned by live polygon candidates
        want = np.zeros(n, dtype=bool)
        want[poly_alive] = True
        esel = np.flatnonzero(want[e_owner])
        ea, eb = coords[eidx[esel]], coords[eidx[esel] + 1]
        eg = e_owner[esel]
        # chunk boundaries MUST align to candidate edge groups: a
        # candidate's crossing parity is over ALL its edges (splitting
        # a group across chunks would break the mod-2)
        group_starts = np.flatnonzero(np.r_[True, eg[1:] != eg[:-1]]) \
            if len(eg) else np.empty(0, np.int64)
        group_ends = np.r_[group_starts[1:], len(eg)] \
            if len(eg) else np.empty(0, np.int64)
        budget = max(int(_EDGE_CHUNK * 8 // max(len(pxq), 1)), 1)
        gi = 0
        while gi < len(group_starts):
            gj = gi  # extend while the NEXT group still fits the budget
            while (gj + 1 < len(group_starts)
                   and group_ends[gj + 1] - group_starts[gi] <= budget):
                gj += 1
            sl = slice(int(group_starts[gi]), int(group_ends[gj]))
            x1, y1 = ea[sl, 0], ea[sl, 1]
            x2, y2 = eb[sl, 0], eb[sl, 1]
            g = eg[sl]
            straddle = ((y1[None, :] > pyq[:, None])
                        != (y2[None, :] > pyq[:, None]))
            with np.errstate(divide="ignore", invalid="ignore"):
                xint = x1[None, :] + (pyq[:, None] - y1[None, :]) / (
                    y2[None, :] - y1[None, :]) * (x2[None, :] - x1[None, :])
            cross = straddle & (pxq[:, None] < xint)
            # boundary: B vertex exactly on the edge
            dx, dy = x2 - x1, y2 - y1
            vx = pxq[:, None] - x1[None, :]
            vy = pyq[:, None] - y1[None, :]
            crs = vx * dy[None, :] - vy * dx[None, :]
            dot = vx * dx[None, :] + vy * dy[None, :]
            sq = (dx * dx + dy * dy)[None, :]
            on = (crs == 0) & (dot >= 0) & (dot <= sq)
            # parity per (vertex, candidate): segment-sum crossings into
            # per-candidate bins (edges are candidate-contiguous)
            cuts = np.flatnonzero(np.r_[True, g[1:] != g[:-1]])
            owners = g[cuts]
            counts = np.add.reduceat(cross.astype(np.int32), cuts, axis=1)
            inside = (counts % 2).astype(bool)
            on_any = np.maximum.reduceat(on, cuts, axis=1)
            np.logical_or.at(hit, owners, (inside | on_any).any(axis=0))
            gi = gj + 1

    # --- point-kind candidates vs point/line queries ---
    if (b_pt or b_line):
        pt_alive = pt_kind & alive & ~hit
        idx = np.flatnonzero(pt_alive[coord_geom])
        if len(idx):
            px, py = coords[idx, 0], coords[idx, 1]
            if b_pt:
                same = ((px[:, None] == b_pts[None, :, 0])
                        & (py[:, None] == b_pts[None, :, 1])).any(axis=1)
            else:
                s1, s2 = _segments(query)
                rings = [np.vstack([p1, p2]) for p1, p2 in zip(s1, s2)]
                same = points_on_rings(px, py, rings)
            np.logical_or.at(hit, coord_geom[idx], same)

    # --- B point-kind vs line candidates: B points on A edges ---
    if b_pt:
        line_alive = np.zeros(n, dtype=bool)
        line_alive[np.flatnonzero(line_kind & alive & ~hit)] = True
        esel = np.flatnonzero(line_alive[e_owner])
        if len(esel):
            ea, eb = coords[eidx[esel]], coords[eidx[esel] + 1]
            eg = e_owner[esel]
            dx = eb[:, 0] - ea[:, 0]
            dy = eb[:, 1] - ea[:, 1]
            vx = b_pts[:, None, 0] - ea[None, :, 0]
            vy = b_pts[:, None, 1] - ea[None, :, 1]
            crs = vx * dy[None, :] - vy * dx[None, :]
            dot = vx * dx[None, :] + vy * dy[None, :]
            sq = (dx * dx + dy * dy)[None, :]
            on = ((crs == 0) & (dot >= 0) & (dot <= sq)).any(axis=0)
            np.logical_or.at(hit, eg, on)

    # --- segment crossings: A edges × B segments ---
    if not b_pt:
        q1, q2 = _segments(query)
        if len(q1):
            seg_alive = np.zeros(n, dtype=bool)
            seg_alive[np.flatnonzero((line_kind | poly_kind)
                                     & alive & ~hit)] = True
            esel = np.flatnonzero(seg_alive[e_owner])
            ea, eb = coords[eidx[esel]], coords[eidx[esel] + 1]
            eg = e_owner[esel]
            for s in range(0, len(ea), _EDGE_CHUNK):
                sl = slice(s, s + _EDGE_CHUNK)
                crossing = segments_intersect(ea[sl], eb[sl], q1, q2)
                np.logical_or.at(hit, eg[sl], crossing.any(axis=1))

    return hit & alive


def _strict_inside(pts: np.ndarray, poly: Geometry) -> np.ndarray:
    """Points strictly interior to a polygonal geometry (boundary
    excluded)."""
    if not len(pts):
        return np.zeros(0, dtype=bool)
    inside = point_in_polygon(pts[:, 0], pts[:, 1], poly,
                              include_boundary=True)
    on = points_on_rings(pts[:, 0], pts[:, 1], _rings_of(poly))
    return inside & ~on


def _interiors_intersect(a: Geometry, b: Geometry) -> bool:
    """Do the interiors of a and b intersect? (approximate DE-9IM
    interior-interior test: proper segment crossings + strict vertex /
    midpoint containment — exact for the supported lattice up to
    collinear-overlap degeneracies)."""
    a_poly = isinstance(a, (Polygon, MultiPolygon))
    b_poly = isinstance(b, (Polygon, MultiPolygon))
    a1, a2 = _segments(a)
    b1, b2 = _segments(b)
    if a1.size and b1.size and bool(
            segments_cross_properly(a1, a2, b1, b2).any()):
        return True
    if b_poly:
        va = all_vertices(a)
        if bool(_strict_inside(va, b).any()):
            return True
        if a1.size:
            mid = np.stack([(a1[:, 0] + a2[:, 0]) / 2,
                            (a1[:, 1] + a2[:, 1]) / 2], axis=1)
            if bool(_strict_inside(mid, b).any()):
                return True
    if a_poly:
        vb = all_vertices(b)
        if bool(_strict_inside(vb, a).any()):
            return True
        if b1.size:
            mid = np.stack([(b1[:, 0] + b2[:, 0]) / 2,
                            (b1[:, 1] + b2[:, 1]) / 2], axis=1)
            if bool(_strict_inside(mid, a).any()):
                return True
    if not a_poly and not b_poly and a1.size and b1.size:
        # line/line: shared collinear stretch — a segment midpoint of one
        # lying ON the other marks a 1-D shared interior
        mids_a = np.stack([(a1[:, 0] + a2[:, 0]) / 2,
                           (a1[:, 1] + a2[:, 1]) / 2], axis=1)
        rings_b = [np.vstack([p1, p2]) for p1, p2 in zip(b1, b2)]
        if bool(points_on_rings(mids_a[:, 0], mids_a[:, 1],
                                rings_b).any()):
            return True
    return False


def geometry_touches(a: Geometry, b: Geometry) -> bool:
    """JTS-style ``touches``: geometries intersect but their interiors do
    not (boundary-only contact)."""
    if not geometry_intersects(a, b):
        return False
    if isinstance(a, (Point, MultiPoint)):
        pts = _points_of(a)
        if isinstance(b, (Polygon, MultiPolygon)):
            return bool(points_on_rings(pts[:, 0], pts[:, 1],
                                        _rings_of(b)).any()
                        and not _strict_inside(pts, b).any())
        if isinstance(b, (LineString, MultiLineString)):
            lines = [b] if isinstance(b, LineString) else list(b.lines)
            ends = np.vstack([np.vstack([l.coords[0], l.coords[-1]])
                              for l in lines])
            return bool((np.abs(pts[:, None, :] - ends[None, :, :])
                         .sum(axis=2) == 0).any())
        return False  # point/point contact is equality, not touches
    if isinstance(b, (Point, MultiPoint)):
        return geometry_touches(b, a)
    return not _interiors_intersect(a, b)


def geometry_crosses(a: Geometry, b: Geometry) -> bool:
    """JTS-style ``crosses``: interiors intersect and the intersection's
    dimension is lower than the operands' max (line/line meeting at
    points; a line passing through a polygon)."""
    a_line = isinstance(a, (LineString, MultiLineString))
    b_line = isinstance(b, (LineString, MultiLineString))
    a_poly = isinstance(a, (Polygon, MultiPolygon))
    b_poly = isinstance(b, (Polygon, MultiPolygon))
    if a_line and b_line:
        a1, a2 = _segments(a)
        b1, b2 = _segments(b)
        return bool(a1.size and b1.size
                    and segments_cross_properly(a1, a2, b1, b2).any())
    if (a_line and b_poly) or (a_poly and b_line):
        line, poly = (a, b) if a_line else (b, a)
        v = all_vertices(line)
        s1, s2 = _segments(line)
        mids = np.vstack([v, np.stack(
            [(s1[:, 0] + s2[:, 0]) / 2, (s1[:, 1] + s2[:, 1]) / 2],
            axis=1)]) if s1.size else v
        inside = _strict_inside(mids, poly)
        outside = ~point_in_polygon(mids[:, 0], mids[:, 1], poly,
                                    include_boundary=True)
        return bool(inside.any() and outside.any())
    return False


def geometry_overlaps(a: Geometry, b: Geometry) -> bool:
    """JTS-style ``overlaps``: same dimension, interiors intersect,
    neither contains the other."""
    a_pt = isinstance(a, (Point, MultiPoint))
    b_pt = isinstance(b, (Point, MultiPoint))
    a_line = isinstance(a, (LineString, MultiLineString))
    b_line = isinstance(b, (LineString, MultiLineString))
    if a_pt != b_pt or a_line != b_line:
        return False  # different dimensions
    if a_pt:
        pa = {tuple(p) for p in _points_of(a)}
        pb = {tuple(p) for p in _points_of(b)}
        return bool(pa & pb) and bool(pa - pb) and bool(pb - pa)
    if not _interiors_intersect(a, b):
        return False
    return not geometry_within(a, b) and not geometry_within(b, a)
