"""Geometry model: the framework's replacement for the reference's JTS
dependency (used throughout geomesa-utils/geomesa-filter for geometry
parsing, envelopes and predicates).

Two representations:

* **Object form** (:mod:`geomesa_tpu.geometry.types`): small dataclasses
  (Point/LineString/Polygon/Multi*) for host-side planning, WKT I/O and
  tests.
* **Packed SoA form** (:mod:`geomesa_tpu.geometry.packed`): flat coordinate
  buffers + offset arrays, the columnar layout device kernels and the XZ
  indexes consume (bbox columns, vertex buffers).

Predicates (:mod:`geomesa_tpu.geometry.predicates`) are vectorized numpy
(crossing-number point-in-polygon, segment intersection, bbox algebra) —
used as the exact re-check stage after index-range candidate filtering,
the role the reference's CQL geometry evaluation plays in
FilterTransformIterator.
"""

from .crs import register_crs, reproject_batch, transform
from .packed import PackedGeometry, pack_geometries
from .predicates import (
    bbox_intersects,
    geometry_intersects,
    point_in_polygon,
    points_in_packed_polygon,
    points_on_rings,
    segments_intersect,
)
from .types import (
    Envelope,
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from .wkt import geometry_from_wkt, geometry_to_wkt
