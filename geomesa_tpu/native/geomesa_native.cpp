// Native host-side range decomposition for geomesa_tpu.
//
// The framework's device compute path is JAX/XLA/Pallas; this library is
// the native *host runtime* piece: the planner's hot host loops — z-order
// range decomposition (the role the reference delegates to the external
// sfcurve library, geomesa-z3/pom.xml:16-17, called from
// curve/Z2SFC.scala:52 and curve/Z3SFC.scala:61) and the XZ quad/octree
// sweeps (curve/XZ2SFC.scala:146-252, XZ3SFC analog).
//
// Semantics are bit-for-bit identical to the numpy implementations in
// geomesa_tpu/curve/{ranges,xz2,xz3}.py: the same level-synchronous
// frontier sweep, the same emit order, the same budget arithmetic, the
// same IEEE-754 double comparisons — so the Python fallback and the
// native path are interchangeable and differential-tested for equality.
//
// Exported C ABI (see geomesa_tpu/native/__init__.py for the ctypes
// binding):
//   gm_zranges    — Z2/Z3 morton-range decomposition (quad/octree).
//   gm_xz_ranges  — XZ2/XZ3 sequence-code range decomposition.
// Both return the number of merged [lo, hi] pairs written to `out`, or a
// negative required-capacity if `cap` pairs were insufficient.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstddef>
#include <vector>

namespace {

struct Range {
  int64_t lo;
  int64_t hi;
};

// Sort + merge overlapping/adjacent inclusive ranges, in place semantics of
// curve/ranges.py merge_ranges().
int64_t merge_and_emit(std::vector<Range>& ranges, int64_t* out, int64_t cap) {
  if (ranges.empty()) return 0;
  std::sort(ranges.begin(), ranges.end(),
            [](const Range& a, const Range& b) { return a.lo < b.lo; });
  std::vector<Range> merged;
  merged.reserve(ranges.size());
  Range cur = ranges[0];
  for (size_t i = 1; i < ranges.size(); ++i) {
    const Range& r = ranges[i];
    if (r.lo > cur.hi + 1) {
      merged.push_back(cur);
      cur = r;
    } else if (r.hi > cur.hi) {
      cur.hi = r.hi;
    }
  }
  merged.push_back(cur);
  int64_t n = static_cast<int64_t>(merged.size());
  if (n > cap) return -n;
  for (int64_t i = 0; i < n; ++i) {
    out[2 * i] = merged[i].lo;
    out[2 * i + 1] = merged[i].hi;
  }
  return n;
}

// De-interleave one dimension of a d-dim morton code: bits at positions
// dim, dim+d, dim+2d, ...
inline uint64_t extract_dim(uint64_t z, int dim, int dims, int bits) {
  uint64_t v = 0;
  for (int b = 0; b < bits; ++b) {
    v |= ((z >> (b * dims + dim)) & 1ULL) << b;
  }
  return v;
}

}  // namespace

extern "C" {

// Z2/Z3 morton-range decomposition (curve/ranges.py zranges()).
//
// mins/maxs: n_boxes * dims int64 inclusive normalized-int bounds,
// box-major ([b0d0, b0d1, ..., b1d0, ...]). Emits merged covering ranges.
int64_t gm_zranges(const int64_t* mins, const int64_t* maxs, int64_t n_boxes,
                   int32_t dims, int32_t bits, int64_t budget,
                   int32_t depth_cap, int64_t* out, int64_t cap) {
  if (dims != 2 && dims != 3) return -1;
  if (n_boxes <= 0) return 0;
  const int fanout = 1 << dims;
  if (depth_cap > bits) depth_cap = bits;

  // Frontier cells carry the z of their min corner; coordinates are
  // recovered by de-interleaving exactly as the numpy sweep does.
  std::vector<uint64_t> frontier(1, 0);
  std::vector<uint64_t> next;
  std::vector<Range> emitted_ranges;
  int64_t emitted = 0;

  for (int level = 0; level <= depth_cap; ++level) {
    if (frontier.empty()) break;
    const uint64_t side = 1ULL << (bits - level);
    const uint64_t zsize = 1ULL << (static_cast<uint64_t>(dims) * (bits - level));
    const bool bottom = (level == depth_cap);

    next.clear();
    std::vector<uint64_t> rest;
    for (uint64_t z : frontier) {
      uint64_t cmin[3], cmax[3];
      for (int d = 0; d < dims; ++d) {
        cmin[d] = extract_dim(z, d, dims, bits);
        cmax[d] = cmin[d] + (side - 1);
      }
      bool contained = false, overlaps = false;
      for (int64_t b = 0; b < n_boxes && !(contained && overlaps); ++b) {
        bool c = true, o = true;
        for (int d = 0; d < dims; ++d) {
          const uint64_t bmin = static_cast<uint64_t>(mins[b * dims + d]);
          const uint64_t bmax = static_cast<uint64_t>(maxs[b * dims + d]);
          c = c && (cmin[d] >= bmin) && (cmax[d] <= bmax);
          o = o && (cmin[d] <= bmax) && (cmax[d] >= bmin);
        }
        contained = contained || c;
        overlaps = overlaps || o;
      }
      if (bottom) contained = overlaps;
      if (contained) {
        emitted_ranges.push_back(
            {static_cast<int64_t>(z), static_cast<int64_t>(z + (zsize - 1))});
        ++emitted;
      } else if (overlaps) {
        rest.push_back(z);
      }
    }
    if (rest.empty()) break;
    if (emitted + static_cast<int64_t>(rest.size()) * fanout > budget) {
      // Budget exhausted: remaining frontier becomes covering ranges.
      for (uint64_t z : rest) {
        emitted_ranges.push_back(
            {static_cast<int64_t>(z), static_cast<int64_t>(z + (zsize - 1))});
      }
      break;
    }
    const uint64_t child_zsize =
        1ULL << (static_cast<uint64_t>(dims) * (bits - level - 1));
    for (uint64_t z : rest) {
      for (int q = 0; q < fanout; ++q) {
        next.push_back(z + static_cast<uint64_t>(q) * child_zsize);
      }
    }
    frontier.swap(next);
  }
  return merge_and_emit(emitted_ranges, out, cap);
}

// XZ2/XZ3 sequence-code range decomposition (curve/xz2.py / xz3.py
// ranges()).  Windows are pre-normalized [0,1] doubles, window-major
// (dims mins then dims maxs per window is split: wmins / wmaxs arrays).
// iv[i] = (fanout^(g-i) - 1) / (fanout - 1) subtree sizes are recomputed
// here (g <= 30 for dims=2, <= 20 for dims=3 keeps codes in int64).
int64_t gm_xz_ranges(const double* wmins, const double* wmaxs,
                     int64_t n_windows, int32_t dims, int32_t g,
                     int64_t budget, int64_t* out, int64_t cap) {
  if (dims != 2 && dims != 3) return -1;
  if (n_windows <= 0) return 0;
  const int fanout = 1 << dims;

  std::vector<int64_t> iv(g + 1);
  for (int i = 0; i <= g; ++i) {
    // (fanout^(g-i) - 1) / (fanout - 1)
    int64_t v = 0;
    for (int p = 0; p < g - i; ++p) v = v * fanout + 1;
    iv[i] = v;
  }

  struct Cell {
    int64_t k[3];  // integer cell coords at the current level
    int64_t cs;    // sequence code of the cell
  };
  std::vector<Cell> frontier(1);
  frontier[0] = {{0, 0, 0}, 0};
  std::vector<Cell> rest;
  std::vector<Range> emitted_ranges;
  int64_t emitted = 0;

  for (int level = 1; level <= g; ++level) {
    if (frontier.empty()) break;
    const double w = std::pow(0.5, level);
    rest.clear();
    for (const Cell& parent : frontier) {
      for (int q = 0; q < fanout; ++q) {
        Cell c;
        c.k[0] = (parent.k[0] << 1) + (q & 1);
        c.k[1] = (parent.k[1] << 1) + ((q >> 1) & 1);
        c.k[2] = dims == 3 ? (parent.k[2] << 1) + (q >> 2) : 0;
        c.cs = parent.cs + 1 + static_cast<int64_t>(q) * iv[level - 1];

        double lo[3], ext[3];
        for (int d = 0; d < dims; ++d) {
          lo[d] = static_cast<double>(c.k[d]) * w;
          ext[d] = lo[d] + 2.0 * w;  // extended footprint
        }
        bool contained = false, overlaps = false;
        for (int64_t b = 0; b < n_windows && !(contained && overlaps); ++b) {
          bool cn = true, ov = true;
          for (int d = 0; d < dims; ++d) {
            const double wmin = wmins[b * dims + d];
            const double wmax = wmaxs[b * dims + d];
            cn = cn && (wmin <= lo[d]) && (wmax >= ext[d]);
            ov = ov && (wmax >= lo[d]) && (wmin <= ext[d]);
          }
          contained = contained || cn;
          overlaps = overlaps || ov;
        }
        if (contained) {
          emitted_ranges.push_back({c.cs, c.cs + iv[level - 1]});
          ++emitted;
        } else if (overlaps) {
          rest.push_back(c);
        }
      }
    }
    if (rest.empty()) break;
    if (level == g ||
        emitted + static_cast<int64_t>(rest.size()) * fanout > budget) {
      // Bottom out: cover each remaining cell's whole subtree.
      for (const Cell& c : rest) {
        emitted_ranges.push_back({c.cs, c.cs + iv[level - 1]});
      }
      break;
    }
    // Partial matches emit their own code (large objects stored at this
    // cell) and descend.
    for (const Cell& c : rest) {
      emitted_ranges.push_back({c.cs, c.cs});
      ++emitted;
    }
    frontier.swap(rest);
  }
  return merge_and_emit(emitted_ranges, out, cap);
}

}  // extern "C"
