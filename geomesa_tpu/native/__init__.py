"""Native (C++) host-runtime components, loaded via ctypes.

The device compute path is JAX/XLA/Pallas; this package holds the native
*host* pieces — currently the planner's range-decomposition hot loops
(the role the reference outsources to the external ``sfcurve`` JVM
library, geomesa-z3/pom.xml:16-17).  The shared library is compiled from
:mod:`geomesa_native.cpp` on first use with the system ``g++`` and cached
by source hash; everything degrades gracefully to the numpy
implementations when a toolchain is unavailable or
``GEOMESA_TPU_NATIVE=0`` is set.

The native and numpy paths are semantically identical by construction
(same sweep, same emit order, same budget arithmetic) and are
differential-tested against each other in ``tests/test_native.py``.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

import numpy as np

__all__ = ["available", "zranges_native", "xz_ranges_native"]

_SRC = os.path.join(os.path.dirname(__file__), "geomesa_native.cpp")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False


def _cache_dir() -> str:
    override = os.environ.get("GEOMESA_TPU_NATIVE_CACHE")
    if override:
        return override
    return os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "geomesa_tpu",
    )


def _build() -> ctypes.CDLL | None:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    cache = _cache_dir()
    lib_path = os.path.join(cache, f"libgeomesa_native-{tag}.so")
    if not os.path.exists(lib_path):
        os.makedirs(cache, exist_ok=True)
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=cache)
        os.close(fd)
        try:
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                 "-o", tmp, _SRC],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, lib_path)  # atomic under concurrent builders
        except (OSError, subprocess.SubprocessError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError:
        return None
    lib.gm_zranges.restype = ctypes.c_int64
    lib.gm_zranges.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    lib.gm_xz_ranges.restype = ctypes.c_int64
    lib.gm_xz_ranges.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    return lib


def _load() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    with _LOCK:
        if not _TRIED:
            if os.environ.get("GEOMESA_TPU_NATIVE", "1") != "0":
                _LIB = _build()
            _TRIED = True
    return _LIB


def available() -> bool:
    """True when the native library compiled and loaded."""
    return _load() is not None


def _i64ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f64ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def _call_with_capacity(call, budget: int) -> np.ndarray | None:
    """Run a native range fn with a modest initial buffer, growing once to
    the exact required capacity on a negative return.  The budget bounds
    the emit count, but huge 'unlimited' budgets must not preallocate
    proportionally."""
    cap = min(int(budget), 4096) + 16
    out = np.empty(2 * cap, dtype=np.int64)
    n = call(out, cap)
    if n < 0:
        cap = -n
        out = np.empty(2 * cap, dtype=np.int64)
        n = call(out, cap)
        if n < 0:
            return None
    return out[: 2 * n].reshape(-1, 2).copy()


def zranges_native(mins: np.ndarray, maxs: np.ndarray, dims: int, bits: int,
                   budget: int, depth_cap: int) -> np.ndarray | None:
    """Native Z2/Z3 range decomposition; None when the library is absent."""
    lib = _load()
    if lib is None:
        return None
    mins = np.ascontiguousarray(mins, dtype=np.int64)
    maxs = np.ascontiguousarray(maxs, dtype=np.int64)
    return _call_with_capacity(
        lambda out, cap: lib.gm_zranges(
            _i64ptr(mins), _i64ptr(maxs), mins.shape[0], dims, bits,
            budget, depth_cap, _i64ptr(out), cap),
        budget)


def xz_ranges_native(wmins: np.ndarray, wmaxs: np.ndarray, dims: int, g: int,
                     budget: int) -> np.ndarray | None:
    """Native XZ2/XZ3 range decomposition over pre-normalized windows."""
    lib = _load()
    if lib is None:
        return None
    wmins = np.ascontiguousarray(wmins, dtype=np.float64)
    wmaxs = np.ascontiguousarray(wmaxs, dtype=np.float64)
    return _call_with_capacity(
        lambda out, cap: lib.gm_xz_ranges(
            _f64ptr(wmins), _f64ptr(wmaxs), wmins.shape[0], dims, g,
            budget, _i64ptr(out), cap),
        budget)
