"""Pluggable index registry: name → builder factories + applicability.

The analog of the reference's GeoMesaFeatureIndexFactory SPI
(index/api/GeoMesaFeatureIndexFactory.scala: pluggable index
implementations discovered by name, with per-schema enabled-index
configuration via the ``geomesa.indices`` user data —
utils/geotools/Conversions/RichSimpleFeatureType).  The built-in spatial/
temporal/attribute/id indexes register here; custom index types can
register too and are then buildable through ``TpuDataStore`` /
``_SchemaStore.index(name)`` and forceable with the ``QUERY_INDEX``
query hint.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

__all__ = ["IndexDescriptor", "register_index", "get_index",
           "available_indices", "supported_indices"]


@dataclass(frozen=True)
class IndexDescriptor:
    """One registered index type.

    ``build(store)`` → index instance for a single-chip store;
    ``build_sharded(store, mesh)`` → the mesh variant (may be None when
    the type has no sharded form — the host build is used);
    ``applicable(sft)`` → whether the schema supports this index
    (point/geometry/dtg requirements — the reference's
    ``GeoMesaFeatureIndex.supports``)."""

    name: str
    applicable: Callable
    build: Callable
    build_sharded: Callable | None = None


_REGISTRY: dict[str, IndexDescriptor] = {}


def register_index(desc: IndexDescriptor) -> None:
    """Register (or replace) an index type by name."""
    _REGISTRY[desc.name] = desc


def get_index(name: str) -> IndexDescriptor:
    if name not in _REGISTRY:
        raise KeyError(f"no index type {name!r} registered "
                       f"(have: {sorted(_REGISTRY)})")
    return _REGISTRY[name]


def available_indices() -> list[str]:
    return sorted(_REGISTRY)


def supported_indices(sft) -> list[str]:
    """Index types this schema can serve, honoring the schema's
    ``geomesa.indices.enabled`` restriction (None = all applicable) —
    the reference's per-schema index configuration."""
    enabled = sft.enabled_indices
    out = []
    for name, desc in _REGISTRY.items():
        if enabled is not None and name not in enabled:
            continue
        if desc.applicable(sft):
            out.append(name)
    return sorted(out)


# -- built-in registrations -------------------------------------------------

def _points(sft) -> bool:
    return bool(sft.geom_field and sft.is_points)


def _geoms(sft) -> bool:
    return bool(sft.geom_field)


def _register_builtins() -> None:
    register_index(IndexDescriptor(
        "z3",
        applicable=lambda sft: _points(sft) and bool(sft.dtg_field),
        build=lambda store: store._build_z3(),
        build_sharded=lambda store, mesh: store._build_z3()))
    register_index(IndexDescriptor(
        "z2", applicable=_points,
        build=lambda store: store._build_z2(),
        build_sharded=lambda store, mesh: store._build_z2()))
    register_index(IndexDescriptor(
        "xz3",
        applicable=lambda sft: _geoms(sft) and bool(sft.dtg_field),
        build=lambda store: store._build_xz3(),
        build_sharded=lambda store, mesh: store._build_xz3()))
    register_index(IndexDescriptor(
        "xz2", applicable=_geoms,
        build=lambda store: store._build_xz2(),
        build_sharded=lambda store, mesh: store._build_xz2()))
    register_index(IndexDescriptor(
        "id", applicable=lambda sft: True,
        build=lambda store: store._build_id()))
    def _attr_build(store):
        raise ValueError(
            "the attribute index is built per attribute — use "
            "_SchemaStore.attribute_index(name)")

    register_index(IndexDescriptor(
        "attr",
        applicable=lambda sft: any(a.indexed for a in sft.attributes),
        build=_attr_build))


_register_builtins()
