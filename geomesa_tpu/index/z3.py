"""Z3 point index: bbox + time queries over (lon, lat, dtg) point features.

TPU-native analog of the reference's Z3 index
(geomesa-index-api/.../index/z3/Z3IndexKeySpace.scala):

* **Key layout.** The reference writes ``[1B shard][2B bin][8B z][id]``
  rows (Z3IndexKeySpace.scala:60).  Here the same order lives as two
  sorted device columns — ``bins`` (int32) and ``z`` (int64) sorted
  lexicographically — plus ``pos``, the permutation into the original
  feature columns.  No shard byte: write/scan parallelism comes from mesh
  sharding, not key-prefix salting (SURVEY.md §2.7).
* **Write path.** ``build`` = host time-binning (calendar-aware,
  BinnedTime semantics) → jitted vectorized SFC encode (the reference's
  per-feature hot loop, Z3IndexKeySpace.toIndexKey:64-96, as one fused
  device kernel) → device lexsort (the KV store's implicit sort made
  explicit).
* **Query path.** Host planning mirrors Z3IndexKeySpace.getIndexValues/
  getRanges (:98-189): bin the time interval, decompose bbox × per-bin
  time windows into covering z-ranges with the scan-ranges budget split
  across bins (:166-168).  Device scan = vectorized binary-search seeks +
  one fixed-capacity gather + a fused candidate mask combining the
  normalized-int bounds check (filters/Z3Filter.scala:19-55 semantics)
  with the exact double-precision predicate (the reference's
  FilterTransformIterator CQL re-check).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..curve.binnedtime import TimePeriod, max_date_ms, max_offset, to_binned_time
from ..curve.sfc import Z3SFC, z3_sfc
from ..curve.zorder import deinterleave3
from ..config import DEFAULT_MAX_RANGES, QueryProperties
from ..obs import device_span
from ..ops.search import (
    coded_pos_bits, expand_ranges, gather_capacity, pack_coded,
    pack_wire, pad_boxes, pad_pow2, pad_ranges, run_packed_query,
    searchsorted2,
)


def _use_pallas_scan() -> bool:
    """Pallas candidate scan: on by default on TPU backends, off elsewhere
    (interpret mode would be slower than the fused XLA path)."""
    if not QueryProperties.PALLAS_SCAN.get():
        return False
    from ..ops.pallas_kernels import on_tpu
    return on_tpu()

__all__ = ["Z3PointIndex", "Z3QueryPlan", "plan_z3_query"]


@dataclass
class Z3QueryPlan:
    """Host-side scan plan: covering ranges + filter bounds (all numpy)."""

    # per-range arrays (R,)
    rbin: np.ndarray      # int32 time bin
    rzlo: np.ndarray      # int64 inclusive z lo
    rzhi: np.ndarray      # int64 inclusive z hi
    rtlo: np.ndarray      # int32 normalized time lo for the range's bin
    rthi: np.ndarray      # int32 normalized time hi
    # normalized-int spatial bounds (Z3Filter semantics), per box (B, 4)
    ixy: np.ndarray
    # exact double-precision bounds
    boxes: np.ndarray     # (B, 4) xmin, ymin, xmax, ymax
    t_lo_ms: int
    t_hi_ms: int

    @property
    def num_ranges(self) -> int:
        return len(self.rbin)


def _time_windows_by_bin(t_lo_ms: int, t_hi_ms: int, period: TimePeriod):
    """Split [lo, hi] ms into per-bin offset windows; mirror of the
    reference's ``timesByBin`` construction (Z3IndexKeySpace.scala:120-158):
    interior bins get the whole period, boundary bins get partial windows."""
    lo_ms = max(0, int(t_lo_ms))
    hi_ms = min(int(t_hi_ms), max_date_ms(period) - 1)
    if lo_ms > hi_ms:
        return {}
    blo_a, olo_a = to_binned_time(lo_ms, period)
    bhi_a, ohi_a = to_binned_time(hi_ms, period)
    blo, olo, bhi, ohi = int(blo_a), int(olo_a), int(bhi_a), int(ohi_a)
    whole = (0, max_offset(period))
    if blo == bhi:
        return {blo: (olo, ohi)}
    windows = {blo: (olo, whole[1]), bhi: (0, ohi)}
    for b in range(blo + 1, bhi):
        windows[b] = whole
    return windows


def plan_z3_query(
    boxes,
    t_lo_ms: int,
    t_hi_ms: int,
    period: TimePeriod | str = TimePeriod.WEEK,
    max_ranges: int = DEFAULT_MAX_RANGES,
    sfc=None,
) -> Z3QueryPlan:
    """Decompose bbox(es) + time interval into a covering-range scan plan.

    The scan-ranges budget is split across time bins as in
    Z3IndexKeySpace.getRanges (:166-168); whole-period bins share one
    decomposition, partial (boundary) bins get their own.  ``sfc``
    selects the curve (versioned index layouts: the legacy
    semi-normalized curve for v1, the current curve by default — the
    reference's Z3IndexV1..Vn read-path dispatch)."""
    period = TimePeriod.parse(period)
    sfc = sfc if sfc is not None else z3_sfc(period)
    boxes = np.atleast_2d(np.asarray(boxes, dtype=np.float64))
    windows = _time_windows_by_bin(t_lo_ms, t_hi_ms, period)
    empty = np.empty(0, dtype=np.int64)
    if not windows:
        return Z3QueryPlan(
            rbin=empty.astype(np.int32), rzlo=empty, rzhi=empty,
            rtlo=empty.astype(np.int32), rthi=empty.astype(np.int32),
            ixy=np.empty((0, 4), np.int32), boxes=boxes,
            t_lo_ms=int(t_lo_ms), t_hi_ms=int(t_hi_ms),
        )
    target = max(1, max_ranges // max(1, len(windows)))

    # group bins by identical time window so whole-period bins share one
    # range decomposition
    by_window: dict[tuple[int, int], list[int]] = {}
    for b, w in windows.items():
        by_window.setdefault(w, []).append(b)

    rbin, rzlo, rzhi, rtlo, rthi = [], [], [], [], []
    for (wlo, whi), bs in by_window.items():
        zr = sfc.ranges(boxes, [(wlo, whi)], max_ranges=target)
        itlo = sfc.time.normalize_scalar(float(wlo))
        ithi = sfc.time.normalize_scalar(float(whi))
        for b in sorted(bs):
            rbin.append(np.full(len(zr), b, dtype=np.int32))
            rzlo.append(zr[:, 0])
            rzhi.append(zr[:, 1])
            rtlo.append(np.full(len(zr), itlo, dtype=np.int32))
            rthi.append(np.full(len(zr), ithi, dtype=np.int32))

    ixy = np.stack(
        [
            [
                sfc.lon.normalize_scalar(b[0]),
                sfc.lat.normalize_scalar(b[1]),
                sfc.lon.normalize_scalar(b[2]),
                sfc.lat.normalize_scalar(b[3]),
            ]
            for b in boxes
        ]
    ).astype(np.int32)

    return Z3QueryPlan(
        rbin=np.concatenate(rbin),
        rzlo=np.concatenate(rzlo),
        rzhi=np.concatenate(rzhi),
        rtlo=np.concatenate(rtlo),
        rthi=np.concatenate(rthi),
        ixy=ixy,
        boxes=boxes,
        t_lo_ms=int(t_lo_ms),
        t_hi_ms=int(t_hi_ms),
    )


def candidate_mask(zc, rtlo_c, rthi_c, ixy, boxes, xc, yc, tc,
                   t_lo_ms, t_hi_ms, cqid=None, bqid=None, qtlo=None,
                   qthi=None):
    """Shared fused candidate filter: z-decode int-space bounds test
    (Z3Filter.inBounds, filters/Z3Filter.scala:19-55) AND the exact
    double-precision re-check (FilterTransformIterator) — used by the
    single-query, batched, and sharded scan programs so the mask
    semantics cannot diverge.

    ``rtlo_c``/``rthi_c`` are per-CANDIDATE normalized time bounds
    (already gathered by owning range).  With ``cqid``/``bqid`` given,
    boxes only apply to candidates of the same query; exact time bounds
    then come from ``qtlo``/``qthi`` per query instead of the scalars.
    """
    ix, iy, it = deinterleave3(zc.astype(jnp.uint64))
    ix = ix.astype(jnp.int32)
    iy = iy.astype(jnp.int32)
    it = it.astype(jnp.int32)
    box_pairs = (
        (ix[:, None] >= ixy[None, :, 0])
        & (iy[:, None] >= ixy[None, :, 1])
        & (ix[:, None] <= ixy[None, :, 2])
        & (iy[:, None] <= ixy[None, :, 3])
    )
    exact_pairs = (
        (xc[:, None] >= boxes[None, :, 0])
        & (yc[:, None] >= boxes[None, :, 1])
        & (xc[:, None] <= boxes[None, :, 2])
        & (yc[:, None] <= boxes[None, :, 3])
    )
    if cqid is not None:
        same_q = cqid[:, None] == bqid[None, :]
        box_pairs &= same_q
        exact_pairs &= same_q
        in_time_exact = (tc >= qtlo[cqid]) & (tc <= qthi[cqid])
    else:
        in_time_exact = (tc >= t_lo_ms) & (tc <= t_hi_ms)
    in_time_int = (it >= rtlo_c) & (it <= rthi_c)
    return (box_pairs.any(axis=1) & in_time_int
            & exact_pairs.any(axis=1) & in_time_exact)


def _scan_core(
    bins, z, pos, x, y, dtg,
    rbin, rzlo, rzhi, rtlo, rthi,
    ixy, boxes, t_lo_ms, t_hi_ms,
    capacity: int, use_pallas: bool,
):
    """The scan body shared by every single-query program: binary-search
    seeks + fixed-capacity gather + fused candidate mask.  The mask fuses
    the reference's two server-side stages — the z-decode int-space
    bounds test (Z3Iterator/Z3Filter, filters/Z3Filter.scala:19-55) and
    the exact double-precision re-check (FilterTransformIterator).
    Returns ``(posc, mask, total_candidates)``; only the wire packing
    differs between the jitted wrappers, so the hit semantics cannot
    diverge between them."""
    starts = searchsorted2(bins, z, rbin, rzlo, side="left")
    ends = searchsorted2(bins, z, rbin, rzhi, side="right")
    counts = jnp.maximum(ends - starts, 0)
    total = jnp.sum(counts)
    idx, valid, rid = expand_ranges(starts, counts, capacity)
    zc = z[idx]
    posc = pos[idx]
    xc = x[posc]
    yc = y[posc]
    tc = dtg[posc]
    if use_pallas:
        from ..ops.pallas_kernels import z3_mask_pallas
        mask_int = z3_mask_pallas(zc, ixy, rtlo[rid], rthi[rid])
        in_box_exact = (
            (xc[:, None] >= boxes[None, :, 0])
            & (yc[:, None] >= boxes[None, :, 1])
            & (xc[:, None] <= boxes[None, :, 2])
            & (yc[:, None] <= boxes[None, :, 3])
        ).any(axis=1)
        mask = (mask_int & in_box_exact
                & (tc >= t_lo_ms) & (tc <= t_hi_ms))
    else:
        mask = candidate_mask(zc, rtlo[rid], rthi[rid], ixy, boxes,
                              xc, yc, tc, t_lo_ms, t_hi_ms)
    return posc, valid & mask, total


@partial(jax.jit, static_argnames=("capacity", "use_pallas"))
def _query_packed(*args, capacity: int, use_pallas: bool):
    """The WHOLE scan as one dispatch returning a single packed int32
    vector ``[total_hi, total_lo, pos_0|-1, pos_1|-1, …]``.

    One program + one transfer per query: through a remote-device tunnel
    a host sync costs ~100ms, so the old plan (range bounds → host count
    → scan → host mask) paid three round trips where this pays one.
    ``total`` lets the host detect capacity overflow and retry bigger
    (rare; capacity is adaptive).  int32 wire: positions are int32
    throughout (build sorts an int32 iota), and the link pays ~125ms/MB
    — halving the packed bytes halves the dominant cost of a
    large-capacity query."""
    posc, mask, total = _scan_core(*args, capacity=capacity,
                                   use_pallas=use_pallas)
    return pack_wire(total, posc, mask, jnp.int32)


@partial(jax.jit, static_argnames=("capacity", "use_pallas"))
def _scan_keep_device(*args, capacity: int, use_pallas: bool):
    """Two-phase variant of :func:`_query_packed`: the packed vector
    stays ON DEVICE and only ``[total_candidates, total_hits]`` crosses
    to the host, which then dispatches :func:`_compact_hits` for a
    hits-sized transfer.  Pays one extra round trip (~100ms) to avoid
    shipping a capacity-sized buffer (~125ms/MB) — the winning trade
    once capacity is large and selectivity low."""
    posc, mask, total = _scan_core(*args, capacity=capacity,
                                   use_pallas=use_pallas)
    packed = jnp.where(mask, posc.astype(jnp.int32), jnp.int32(-1))
    totals = jnp.stack([total.astype(jnp.int64),
                        jnp.sum(mask).astype(jnp.int64)])
    return packed, totals


@partial(jax.jit, static_argnames=("k",))
def _compact_hits(packed, k: int):
    """Descending sort floats the valid (>= 0) positions to the front;
    the first ``k`` slots cover all hits (k = pow2 >= total_hits, so
    compiles bucket like the capacities do)."""
    return -jnp.sort(-packed)[:k]


#: capacity at which the two-phase (device-compact) read beats the
#: single-dispatch full-buffer transfer: an extra ~100ms round trip vs
#: ~125ms/MB of padded buffer
TWO_PHASE_MIN_CAPACITY = 1 << 19


@partial(jax.jit, static_argnames=("capacity", "pos_bits"))
def _query_many_packed(
    bins, z, pos, x, y, dtg,
    rbin, rzlo, rzhi, rtlo, rthi, rqid,
    ixy, boxes, bqid, qtlo, qthi,
    capacity: int, pos_bits: int = 40,
):
    """Batched multi-window scan: Q independent bbox+time queries in ONE
    dispatch (the reference's BatchScanner over many range sets,
    accumulated per query).  Each covering range and each box carries its
    owning query id; a candidate only matches boxes/time bounds of its own
    query.  Returns ``[total, (qid << pos_bits | pos)|-1, …]`` — one
    transfer decodes into per-query hit lists; when qid and pos together
    fit 31 bits the wire vector is int32 (halving the dominant
    device→host transfer, ~125ms/MB), else int64.  This amortizes the
    ~100ms remote dispatch round trip across e.g. a tube-select's
    per-segment windows or a kNN's expanding rings.
    """
    starts = searchsorted2(bins, z, rbin, rzlo, side="left")
    ends = searchsorted2(bins, z, rbin, rzhi, side="right")
    counts = jnp.maximum(ends - starts, 0)
    total = jnp.sum(counts)
    idx, valid, rid = expand_ranges(starts, counts, capacity)
    zc = z[idx]
    posc = pos[idx]
    cqid = rqid[rid]
    mask = valid & candidate_mask(
        zc, rtlo[rid], rthi[rid], ixy, boxes,
        x[posc], y[posc], dtg[posc], 0, 0,
        cqid=cqid, bqid=bqid, qtlo=qtlo, qthi=qthi)
    return pack_coded(total, cqid, posc, mask, pos_bits)




#: sentinel keys for capacity-padding slots: sort after every real key
#: and can never match a query range (real bins are small)
_SENTINEL_BIN = np.int32(np.iinfo(np.int32).max)
_SENTINEL_Z = np.int64(np.iinfo(np.int64).max)


@partial(jax.jit, static_argnames=("sfc",))
def _append_step(sfc, bins_a, z_a, pos_a, x_a, y_a, dtg_a, r,
                 xs, ys, offs, bs, ts, m_valid):
    """One static-shaped incremental append: encode the (padded) new
    batch, overwrite sentinel slots at the sorted tail with its keys,
    and re-sort the capacity-padded columns in place — all device-side,
    no host transfer.  On TPU the sort network (~230M keys/s) IS the
    cheapest merge: fine-grained gather/scatter merges run orders of
    magnitude slower than one dense sort, so the LSM "memtable merge"
    becomes "write into padding + sort".  Shapes depend only on
    (capacity, m_pad), so steady-state appends reuse one compile per
    bucket; the new feature values land at ``[r, r + m_pad)`` of the
    value columns (slots past m_valid belong to invalid rows that are
    never gathered)."""
    m_pad = xs.shape[0]
    z_b = sfc.index(xs, ys, offs)
    valid_b = jnp.arange(m_pad) < m_valid
    bs = jnp.where(valid_b, bs, _SENTINEL_BIN)
    z_b = jnp.where(valid_b, z_b, _SENTINEL_Z)
    payload = jnp.where(valid_b, r.astype(jnp.int32)
                        + jnp.arange(m_pad, dtype=jnp.int32), -1)
    # sentinels occupy the sorted tail, so the write window starts at r
    bins_w = jax.lax.dynamic_update_slice(bins_a, bs, (r,))
    z_w = jax.lax.dynamic_update_slice(z_a, z_b, (r,))
    pos_w = jax.lax.dynamic_update_slice(pos_a, payload, (r,))
    bins_m, z_m, pos_m = jax.lax.sort(
        (bins_w, z_w, pos_w), dimension=0, num_keys=2)
    x_a = jax.lax.dynamic_update_slice(x_a, xs, (r,))
    y_a = jax.lax.dynamic_update_slice(y_a, ys, (r,))
    dtg_a = jax.lax.dynamic_update_slice(dtg_a, ts, (r,))
    return bins_m, z_m, pos_m, x_a, y_a, dtg_a


@partial(jax.jit, static_argnames=("sfc",))
def _encode_sort_z3(sfc, xs, ys, os_, bs):
    """Key encode + 2-key variadic sort (bin-major), permutation as
    payload.  Module-level so repeated builds share one compile (Z3SFC is
    a frozen dataclass, hence a hashable static arg)."""
    zv = sfc.index(xs, ys, os_)
    return jax.lax.sort(
        (bs, zv, jnp.arange(zv.shape[0], dtype=jnp.int32)),
        dimension=0, num_keys=2)


#: current z3 key-layout version (v1 = legacy semi-normalized curve —
#: the reference's Z3IndexV1 era; see curve/legacy.py)
Z3_INDEX_VERSION = 2


def z3_sfc_for_version(period: TimePeriod, version: int):
    """Curve for a persisted index-layout version (the read-path
    dispatch of the reference's versioned indices,
    index/index/z3/legacy/Z3IndexV1.scala)."""
    if version >= 2:
        return z3_sfc(period)
    from ..curve.legacy import legacy_z3_sfc
    return legacy_z3_sfc(period)


class Z3PointIndex:
    """Device-resident Z3 index over point features with timestamps."""

    #: initial fixed gather capacity; grows adaptively on overflow so the
    #: common case is exactly ONE device dispatch + ONE transfer per query
    DEFAULT_CAPACITY = 1 << 15

    def __init__(self, period, bins, z, pos, x, y, dtg,
                 version: int = Z3_INDEX_VERSION):
        self.period = TimePeriod.parse(period)
        self.version = version
        self.sfc = z3_sfc_for_version(self.period, version)
        self.bins = bins
        self.z = z
        self.pos = pos
        self.x = x
        self.y = y
        self.dtg = dtg
        #: valid rows; append() capacity-pads the arrays with sentinel
        #: keys past this count
        self._n_rows = int(z.shape[0])
        self._capacity = self.DEFAULT_CAPACITY
        #: data time extent; queries clamp to it so an unbounded interval
        #: plans over the data's bins, not every bin since the epoch
        self.t_min_ms: int | None = None
        self.t_max_ms: int | None = None

    @classmethod
    def build(cls, x, y, dtg_ms, period: TimePeriod | str = TimePeriod.WEEK,
              xd=None, yd=None,
              version: int = Z3_INDEX_VERSION) -> "Z3PointIndex":
        """Encode keys (device) and sort (device lexsort, bin-major).
        ``xd``/``yd`` optionally supply already-device-resident coordinate
        arrays (shared with other indexes) to skip re-upload;
        ``version`` selects the key-layout curve (legacy for v1)."""
        period = TimePeriod.parse(period)
        sfc = z3_sfc_for_version(period, version)
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        dtg_ms = np.asarray(dtg_ms, dtype=np.int64)
        host_bins, host_offs = to_binned_time(dtg_ms, period)
        t_min = int(dtg_ms.min()) if len(dtg_ms) else 0
        t_max = int(dtg_ms.max()) if len(dtg_ms) else 0

        xd = jnp.asarray(x) if xd is None else xd
        yd = jnp.asarray(y) if yd is None else yd
        td = jnp.asarray(dtg_ms)
        bind = jnp.asarray(host_bins.astype(np.int32))
        offd = jnp.asarray(host_offs.astype(np.float64))

        bins_s, z_s, pos = _encode_sort_z3(sfc, xd, yd, offd, bind)
        idx = cls(period, bins=bins_s, z=z_s, pos=pos, x=xd, y=yd, dtg=td,
                  version=version)
        idx.t_min_ms, idx.t_max_ms = t_min, t_max
        return idx

    def __len__(self) -> int:
        return self._n_rows

    def _grow_capacity(self, cap: int) -> None:
        """Extend the resident columns to ``cap`` slots with sentinel
        keys (sort last, match nothing) — one reallocation per
        power-of-two growth step."""
        pad = cap - int(self.z.shape[0])
        if pad <= 0:
            return
        self.bins = jnp.concatenate(
            [self.bins, jnp.full((pad,), _SENTINEL_BIN, self.bins.dtype)])
        self.z = jnp.concatenate(
            [self.z, jnp.full((pad,), _SENTINEL_Z, self.z.dtype)])
        self.pos = jnp.concatenate(
            [self.pos, jnp.full((pad,), -1, self.pos.dtype)])
        self.x = jnp.concatenate([self.x, jnp.zeros((pad,), self.x.dtype)])
        self.y = jnp.concatenate([self.y, jnp.zeros((pad,), self.y.dtype)])
        self.dtg = jnp.concatenate(
            [self.dtg, jnp.zeros((pad,), self.dtg.dtype)])

    def append(self, x, y, dtg_ms) -> "Z3PointIndex":
        """Incremental ingest: encode the NEW batch, write its keys into
        the sentinel padding, and re-sort the capacity-padded columns in
        place, entirely device-resident — the win over a rebuild is
        skipping the host→device re-upload of the whole dataset, not the
        sort (on TPU the sort network IS the cheapest merge; see
        _append_step).  Shapes bucket by (capacity, pow2(m)), so
        steady-state appends reuse one compiled program (~270ms per 100k
        rows at 16M resident).  Returns self (mutated)."""
        x = np.asarray(x, dtype=np.float64)
        m = len(x)
        if m == 0:
            return self
        y = np.asarray(y, dtype=np.float64)
        dtg_ms = np.asarray(dtg_ms, dtype=np.int64)
        m_pad = gather_capacity(m, minimum=8)
        r = self._n_rows
        if r + m_pad > int(self.z.shape[0]):
            self._grow_capacity(gather_capacity(r + m_pad))
        host_bins, host_offs = to_binned_time(dtg_ms, self.period)
        pad = m_pad - m
        self.bins, self.z, self.pos, self.x, self.y, self.dtg = _append_step(
            self.sfc, self.bins, self.z, self.pos, self.x, self.y, self.dtg,
            jnp.int32(r),
            jnp.asarray(np.pad(x, (0, pad))),
            jnp.asarray(np.pad(y, (0, pad))),
            jnp.asarray(np.pad(host_offs.astype(np.float64), (0, pad))),
            jnp.asarray(np.pad(host_bins.astype(np.int32), (0, pad))),
            jnp.asarray(np.pad(dtg_ms, (0, pad))),
            jnp.int32(m))
        self._n_rows = r + m
        t_min = int(dtg_ms.min())
        t_max = int(dtg_ms.max())
        self.t_min_ms = t_min if self.t_min_ms is None else min(self.t_min_ms, t_min)
        self.t_max_ms = t_max if self.t_max_ms is None else max(self.t_max_ms, t_max)
        return self

    def _clamp_time(self, t_lo_ms, t_hi_ms) -> tuple[int, int]:
        """Clamp to the data's time extent; ``None`` bounds are open (no
        time constraint) and resolve to the extent itself."""
        t_lo_ms = self.t_min_ms if t_lo_ms is None else int(t_lo_ms)
        t_hi_ms = self.t_max_ms if t_hi_ms is None else int(t_hi_ms)
        if self.t_min_ms is not None:
            t_lo_ms = max(t_lo_ms, self.t_min_ms)
        if self.t_max_ms is not None:
            t_hi_ms = min(t_hi_ms, self.t_max_ms)
        return t_lo_ms, t_hi_ms

    def query(self, boxes, t_lo_ms: int, t_hi_ms: int,
              max_ranges: int = DEFAULT_MAX_RANGES) -> np.ndarray:
        """Return original-order positions of features matching
        bbox(es) ∧ time interval, exactly (oracle-equal hit sets)."""
        t_lo_ms, t_hi_ms = self._clamp_time(t_lo_ms, t_hi_ms)
        plan = plan_z3_query(boxes, t_lo_ms, t_hi_ms, self.period, max_ranges,
                             sfc=self.sfc)
        if plan.num_ranges == 0 or len(self) == 0:
            return np.empty(0, dtype=np.int64)
        # bucket the plan shapes so differently-shaped queries share
        # compiles (one compile per power-of-two range/box count)
        r = pad_ranges({"rbin": plan.rbin, "rzlo": plan.rzlo,
                        "rzhi": plan.rzhi, "rtlo": plan.rtlo,
                        "rthi": plan.rthi}, pad_pow2(plan.num_ranges))
        ixy, bxs = pad_boxes(plan.ixy, plan.boxes,
                             pad_pow2(len(plan.boxes), minimum=1))
        args = (
            self.bins, self.z, self.pos, self.x, self.y, self.dtg,
            jnp.asarray(r["rbin"]), jnp.asarray(r["rzlo"]),
            jnp.asarray(r["rzhi"]),
            jnp.asarray(r["rtlo"]), jnp.asarray(r["rthi"]),
            jnp.asarray(ixy), jnp.asarray(bxs),
            plan.t_lo_ms, plan.t_hi_ms,
        )
        def dispatch(capacity):
            from ..ops.pallas_kernels import GATES
            with device_span("query.scan.device", stage="packed",
                             capacity=capacity):
                # BOTH branches materialize inside the span (z2.py)
                return GATES["z3_scan"].run(
                    lambda: np.asarray(_query_packed(
                        *args, capacity=capacity, use_pallas=True)),
                    lambda: np.asarray(_query_packed(
                        *args, capacity=capacity, use_pallas=False)),
                    enabled=_use_pallas_scan())

        if self._capacity >= TWO_PHASE_MIN_CAPACITY:
            return self._query_two_phase(args)
        hits, self._capacity = run_packed_query(dispatch, self._capacity)
        return hits

    def _query_two_phase(self, args) -> np.ndarray:
        """Large-capacity scan: keep the packed vector on device, read
        the tiny totals, then transfer a device-compacted hits-sized
        slice (see _scan_keep_device).  When the hits nearly fill the
        capacity the compact dispatch buys nothing, so the packed buffer
        is read directly (same bytes as the single-phase path; only the
        totals round trip was extra)."""
        capacity = self._capacity
        while True:
            with device_span("query.scan.device", stage="two_phase",
                             capacity=capacity):
                packed, totals = _scan_keep_device(
                    *args, capacity=capacity, use_pallas=False)
                total, nhits = (int(v) for v in np.asarray(totals))
                if total > capacity:
                    capacity = gather_capacity(total)
                    continue
                # decay toward the observed candidate volume so one huge
                # query doesn't tax every later small one (re-growth
                # costs a single cheap retry dispatch)
                self._capacity = max(self.DEFAULT_CAPACITY,
                                     gather_capacity(total))
                k = gather_capacity(max(nhits, 1), minimum=8)
                if k >= capacity:  # dense result: compact can't shrink
                    out = np.asarray(packed)
                else:
                    out = np.asarray(_compact_hits(packed, k=k))
            return np.sort(out[out >= 0]).astype(np.int64)

    def query_many(self, windows,
                   max_ranges: int = DEFAULT_MAX_RANGES) -> list[np.ndarray]:
        """Batched queries: ``windows`` is a list of
        ``(boxes, t_lo_ms, t_hi_ms)``; returns one sorted position array
        per window — all windows scanned in ONE device dispatch (see
        _query_many_packed)."""
        n_q = len(windows)
        if n_q == 0 or len(self) == 0:
            return [np.empty(0, dtype=np.int64) for _ in range(n_q)]
        rbin, rzlo, rzhi, rtlo, rthi, rqid = [], [], [], [], [], []
        ixy, boxes, bqid = [], [], []
        qtlo = np.empty(n_q, dtype=np.int64)
        qthi = np.empty(n_q, dtype=np.int64)
        from ..resilience import check_cancel
        for q, (bxs, lo, hi) in enumerate(windows):
            # deadline yield point between range decompositions (ISSUE
            # 16): a partial break leaves the remaining windows with no
            # ranges — they simply return empty hit lists
            if check_cancel("query.decompose"):
                break
            lo, hi = self._clamp_time(lo, hi)
            # the scan-ranges target applies PER window, as in the
            # reference (each window is an independent scan): finer
            # covering ranges cost a bigger searchsorted batch (cheap)
            # but shrink the candidate gather + transfer (the dominant
            # cost)
            plan = plan_z3_query(bxs, lo, hi, self.period, max_ranges,
                                 sfc=self.sfc)
            qtlo[q] = plan.t_lo_ms
            qthi[q] = plan.t_hi_ms
            if plan.num_ranges == 0:
                continue
            rbin.append(plan.rbin)
            rzlo.append(plan.rzlo)
            rzhi.append(plan.rzhi)
            rtlo.append(plan.rtlo)
            rthi.append(plan.rthi)
            rqid.append(np.full(plan.num_ranges, q, dtype=np.int32))
            ixy.append(plan.ixy)
            boxes.append(plan.boxes)
            bqid.append(np.full(len(plan.boxes), q, dtype=np.int32))
        if not rbin:
            return [np.empty(0, dtype=np.int64) for _ in range(n_q)]
        ra = {"rbin": np.concatenate(rbin), "rzlo": np.concatenate(rzlo),
              "rzhi": np.concatenate(rzhi), "rtlo": np.concatenate(rtlo),
              "rthi": np.concatenate(rthi), "rqid": np.concatenate(rqid)}
        ra = pad_ranges(ra, pad_pow2(len(ra["rbin"])))
        ixy_c, boxes_c, bqid_c = pad_boxes(
            np.concatenate(ixy), np.concatenate(boxes),
            pad_pow2(sum(len(b) for b in boxes), minimum=1),
            np.concatenate(bqid))
        args = (
            self.bins, self.z, self.pos, self.x, self.y, self.dtg,
            jnp.asarray(ra["rbin"]), jnp.asarray(ra["rzlo"]),
            jnp.asarray(ra["rzhi"]), jnp.asarray(ra["rtlo"]),
            jnp.asarray(ra["rthi"]), jnp.asarray(ra["rqid"]),
            jnp.asarray(ixy_c), jnp.asarray(boxes_c), jnp.asarray(bqid_c),
            jnp.asarray(qtlo), jnp.asarray(qthi),
        )

        pos_bits = coded_pos_bits(len(self), n_q)

        def dispatch(capacity):
            with device_span("query.scan.device", stage="packed_many",
                             capacity=capacity):
                return np.asarray(_query_many_packed(
                    *args, capacity=capacity, pos_bits=pos_bits))

        coded, self._capacity = run_packed_query(dispatch, self._capacity)
        qids = coded >> pos_bits
        positions = coded & ((np.int64(1) << pos_bits) - 1)
        out = []
        for q in range(n_q):
            hits = positions[qids == q]
            # a feature can land in several of a query's covering ranges
            out.append(np.unique(hits))
        return out
