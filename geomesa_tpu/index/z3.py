"""Z3 point index: bbox + time queries over (lon, lat, dtg) point features.

TPU-native analog of the reference's Z3 index
(geomesa-index-api/.../index/z3/Z3IndexKeySpace.scala):

* **Key layout.** The reference writes ``[1B shard][2B bin][8B z][id]``
  rows (Z3IndexKeySpace.scala:60).  Here the same order lives as two
  sorted device columns — ``bins`` (int32) and ``z`` (int64) sorted
  lexicographically — plus ``pos``, the permutation into the original
  feature columns.  No shard byte: write/scan parallelism comes from mesh
  sharding, not key-prefix salting (SURVEY.md §2.7).
* **Write path.** ``build`` = host time-binning (calendar-aware,
  BinnedTime semantics) → jitted vectorized SFC encode (the reference's
  per-feature hot loop, Z3IndexKeySpace.toIndexKey:64-96, as one fused
  device kernel) → device lexsort (the KV store's implicit sort made
  explicit).
* **Query path.** Host planning mirrors Z3IndexKeySpace.getIndexValues/
  getRanges (:98-189): bin the time interval, decompose bbox × per-bin
  time windows into covering z-ranges with the scan-ranges budget split
  across bins (:166-168).  Device scan = vectorized binary-search seeks +
  one fixed-capacity gather + a fused candidate mask combining the
  normalized-int bounds check (filters/Z3Filter.scala:19-55 semantics)
  with the exact double-precision predicate (the reference's
  FilterTransformIterator CQL re-check).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..curve.binnedtime import TimePeriod, max_date_ms, max_offset, to_binned_time
from ..curve.sfc import Z3SFC, z3_sfc
from ..curve.zorder import deinterleave3
from ..config import DEFAULT_MAX_RANGES, QueryProperties
from ..ops.search import expand_ranges, gather_capacity, searchsorted2


def _use_pallas_scan() -> bool:
    """Pallas candidate scan: on by default on TPU backends, off elsewhere
    (interpret mode would be slower than the fused XLA path)."""
    if not QueryProperties.PALLAS_SCAN.get():
        return False
    from ..ops.pallas_kernels import on_tpu
    return on_tpu()

__all__ = ["Z3PointIndex", "Z3QueryPlan", "plan_z3_query"]


@dataclass
class Z3QueryPlan:
    """Host-side scan plan: covering ranges + filter bounds (all numpy)."""

    # per-range arrays (R,)
    rbin: np.ndarray      # int32 time bin
    rzlo: np.ndarray      # int64 inclusive z lo
    rzhi: np.ndarray      # int64 inclusive z hi
    rtlo: np.ndarray      # int32 normalized time lo for the range's bin
    rthi: np.ndarray      # int32 normalized time hi
    # normalized-int spatial bounds (Z3Filter semantics), per box (B, 4)
    ixy: np.ndarray
    # exact double-precision bounds
    boxes: np.ndarray     # (B, 4) xmin, ymin, xmax, ymax
    t_lo_ms: int
    t_hi_ms: int

    @property
    def num_ranges(self) -> int:
        return len(self.rbin)


def _time_windows_by_bin(t_lo_ms: int, t_hi_ms: int, period: TimePeriod):
    """Split [lo, hi] ms into per-bin offset windows; mirror of the
    reference's ``timesByBin`` construction (Z3IndexKeySpace.scala:120-158):
    interior bins get the whole period, boundary bins get partial windows."""
    lo_ms = max(0, int(t_lo_ms))
    hi_ms = min(int(t_hi_ms), max_date_ms(period) - 1)
    if lo_ms > hi_ms:
        return {}
    blo_a, olo_a = to_binned_time(lo_ms, period)
    bhi_a, ohi_a = to_binned_time(hi_ms, period)
    blo, olo, bhi, ohi = int(blo_a), int(olo_a), int(bhi_a), int(ohi_a)
    whole = (0, max_offset(period))
    if blo == bhi:
        return {blo: (olo, ohi)}
    windows = {blo: (olo, whole[1]), bhi: (0, ohi)}
    for b in range(blo + 1, bhi):
        windows[b] = whole
    return windows


def plan_z3_query(
    boxes,
    t_lo_ms: int,
    t_hi_ms: int,
    period: TimePeriod | str = TimePeriod.WEEK,
    max_ranges: int = DEFAULT_MAX_RANGES,
) -> Z3QueryPlan:
    """Decompose bbox(es) + time interval into a covering-range scan plan.

    The scan-ranges budget is split across time bins as in
    Z3IndexKeySpace.getRanges (:166-168); whole-period bins share one
    decomposition, partial (boundary) bins get their own.
    """
    period = TimePeriod.parse(period)
    sfc = z3_sfc(period)
    boxes = np.atleast_2d(np.asarray(boxes, dtype=np.float64))
    windows = _time_windows_by_bin(t_lo_ms, t_hi_ms, period)
    empty = np.empty(0, dtype=np.int64)
    if not windows:
        return Z3QueryPlan(
            rbin=empty.astype(np.int32), rzlo=empty, rzhi=empty,
            rtlo=empty.astype(np.int32), rthi=empty.astype(np.int32),
            ixy=np.empty((0, 4), np.int32), boxes=boxes,
            t_lo_ms=int(t_lo_ms), t_hi_ms=int(t_hi_ms),
        )
    target = max(1, max_ranges // max(1, len(windows)))

    # group bins by identical time window so whole-period bins share one
    # range decomposition
    by_window: dict[tuple[int, int], list[int]] = {}
    for b, w in windows.items():
        by_window.setdefault(w, []).append(b)

    rbin, rzlo, rzhi, rtlo, rthi = [], [], [], [], []
    for (wlo, whi), bs in by_window.items():
        zr = sfc.ranges(boxes, [(wlo, whi)], max_ranges=target)
        itlo = sfc.time.normalize_scalar(float(wlo))
        ithi = sfc.time.normalize_scalar(float(whi))
        for b in sorted(bs):
            rbin.append(np.full(len(zr), b, dtype=np.int32))
            rzlo.append(zr[:, 0])
            rzhi.append(zr[:, 1])
            rtlo.append(np.full(len(zr), itlo, dtype=np.int32))
            rthi.append(np.full(len(zr), ithi, dtype=np.int32))

    ixy = np.stack(
        [
            [
                sfc.lon.normalize_scalar(b[0]),
                sfc.lat.normalize_scalar(b[1]),
                sfc.lon.normalize_scalar(b[2]),
                sfc.lat.normalize_scalar(b[3]),
            ]
            for b in boxes
        ]
    ).astype(np.int32)

    return Z3QueryPlan(
        rbin=np.concatenate(rbin),
        rzlo=np.concatenate(rzlo),
        rzhi=np.concatenate(rzhi),
        rtlo=np.concatenate(rtlo),
        rthi=np.concatenate(rthi),
        ixy=ixy,
        boxes=boxes,
        t_lo_ms=int(t_lo_ms),
        t_hi_ms=int(t_hi_ms),
    )


@jax.jit
def _range_bounds(bins, z, rbin, rzlo, rzhi):
    starts = searchsorted2(bins, z, rbin, rzlo, side="left")
    ends = searchsorted2(bins, z, rbin, rzhi, side="right")
    return starts, jnp.maximum(ends - starts, 0)


@partial(jax.jit, static_argnames=("capacity",))
def _scan_candidates(
    bins, z, pos, x, y, dtg,
    starts, counts, rtlo, rthi,
    ixy, boxes, t_lo_ms, t_hi_ms,
    capacity: int,
):
    """Fixed-capacity candidate gather + fused filter.

    The mask fuses the reference's two server-side stages: the z-decode
    int-space bounds test (Z3Iterator/Z3Filter) and the exact geometry/time
    re-check (FilterTransformIterator) — one pass over gathered candidates.
    """
    idx, valid, rid = expand_ranges(starts, counts, capacity)
    zc = z[idx]
    posc = pos[idx]
    ix, iy, it = deinterleave3(zc.astype(jnp.uint64))
    ix = ix.astype(jnp.int32)
    iy = iy.astype(jnp.int32)
    it = it.astype(jnp.int32)
    # int-space spatial check against any box (B, 4)
    in_box_int = (
        (ix[:, None] >= ixy[None, :, 0])
        & (iy[:, None] >= ixy[None, :, 1])
        & (ix[:, None] <= ixy[None, :, 2])
        & (iy[:, None] <= ixy[None, :, 3])
    ).any(axis=1)
    in_time_int = (it >= rtlo[rid]) & (it <= rthi[rid])
    # exact double-precision predicate on the original columns
    xc = x[posc]
    yc = y[posc]
    tc = dtg[posc]
    in_box_exact = (
        (xc[:, None] >= boxes[None, :, 0])
        & (yc[:, None] >= boxes[None, :, 1])
        & (xc[:, None] <= boxes[None, :, 2])
        & (yc[:, None] <= boxes[None, :, 3])
    ).any(axis=1)
    in_time_exact = (tc >= t_lo_ms) & (tc <= t_hi_ms)
    mask = valid & in_box_int & in_time_int & in_box_exact & in_time_exact
    return posc, mask


@partial(jax.jit, static_argnames=("capacity",))
def _gather_candidates(z, pos, starts, counts, rtlo, rthi, capacity: int):
    """Stage 1 of the pallas scan: fixed-capacity gather of candidate keys
    plus per-candidate time bounds (by owning range)."""
    idx, valid, rid = expand_ranges(starts, counts, capacity)
    return z[idx], pos[idx], valid, rtlo[rid], rthi[rid]


@partial(jax.jit, static_argnames=())
def _exact_recheck(x, y, dtg, posc, boxes, t_lo_ms, t_hi_ms):
    """Stage 3: exact double-precision predicate on the original columns
    (the FilterTransformIterator re-check)."""
    xc = x[posc]
    yc = y[posc]
    tc = dtg[posc]
    in_box = (
        (xc[:, None] >= boxes[None, :, 0])
        & (yc[:, None] >= boxes[None, :, 1])
        & (xc[:, None] <= boxes[None, :, 2])
        & (yc[:, None] <= boxes[None, :, 3])
    ).any(axis=1)
    return in_box & (tc >= t_lo_ms) & (tc <= t_hi_ms)


#: tri-state: None = untried, True = pallas scan works on this backend,
#: False = failed once (e.g. Mosaic lowering) — stay on the XLA path
_pallas_scan_ok: bool | None = None


def _scan_candidates_pallas(bins, z, pos, x, y, dtg, starts, counts,
                            rtlo, rthi, ixy, boxes, t_lo_ms, t_hi_ms,
                            capacity: int):
    """Pallas variant of :func:`_scan_candidates`: the z-decode +
    int-bounds stage (Z3Filter.inBounds) runs as a fused VMEM kernel."""
    from ..ops.pallas_kernels import z3_mask_pallas

    zc, posc, valid, tlo_c, thi_c = _gather_candidates(
        z, pos, starts, counts, rtlo, rthi, capacity)
    mask_int = z3_mask_pallas(zc, ixy, tlo_c, thi_c)
    mask_exact = _exact_recheck(x, y, dtg, posc, boxes, t_lo_ms, t_hi_ms)
    return posc, valid & mask_int & mask_exact


class Z3PointIndex:
    """Device-resident Z3 index over point features with timestamps."""

    def __init__(self, period, bins, z, pos, x, y, dtg):
        self.period = TimePeriod.parse(period)
        self.sfc: Z3SFC = z3_sfc(self.period)
        self.bins = bins
        self.z = z
        self.pos = pos
        self.x = x
        self.y = y
        self.dtg = dtg

    @classmethod
    def build(cls, x, y, dtg_ms, period: TimePeriod | str = TimePeriod.WEEK) -> "Z3PointIndex":
        """Encode keys (device) and sort (device lexsort, bin-major)."""
        period = TimePeriod.parse(period)
        sfc = z3_sfc(period)
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        dtg_ms = np.asarray(dtg_ms, dtype=np.int64)
        host_bins, host_offs = to_binned_time(dtg_ms, period)

        xd = jnp.asarray(x)
        yd = jnp.asarray(y)
        td = jnp.asarray(dtg_ms)
        bind = jnp.asarray(host_bins.astype(np.int32))
        offd = jnp.asarray(host_offs.astype(np.float64))

        z = jax.jit(lambda a, b, c: sfc.index(a, b, c))(xd, yd, offd)
        order = jnp.lexsort((z, bind))
        return cls(
            period,
            bins=bind[order],
            z=z[order],
            pos=order.astype(jnp.int32),
            x=xd,
            y=yd,
            dtg=td,
        )

    def __len__(self) -> int:
        return int(self.z.shape[0])

    def query(self, boxes, t_lo_ms: int, t_hi_ms: int,
              max_ranges: int = DEFAULT_MAX_RANGES) -> np.ndarray:
        """Return original-order positions of features matching
        bbox(es) ∧ time interval, exactly (oracle-equal hit sets)."""
        plan = plan_z3_query(boxes, t_lo_ms, t_hi_ms, self.period, max_ranges)
        if plan.num_ranges == 0 or len(self) == 0:
            return np.empty(0, dtype=np.int64)
        starts, counts = _range_bounds(
            self.bins, self.z,
            jnp.asarray(plan.rbin), jnp.asarray(plan.rzlo), jnp.asarray(plan.rzhi),
        )
        total = int(jnp.sum(counts))
        if total == 0:
            return np.empty(0, dtype=np.int64)
        args = (
            self.bins, self.z, self.pos, self.x, self.y, self.dtg,
            starts, counts,
            jnp.asarray(plan.rtlo), jnp.asarray(plan.rthi),
            jnp.asarray(plan.ixy), jnp.asarray(plan.boxes),
            plan.t_lo_ms, plan.t_hi_ms,
        )
        capacity = gather_capacity(total)
        global _pallas_scan_ok
        posc = mask = None
        if _pallas_scan_ok is not False and _use_pallas_scan():
            try:
                posc, mask = _scan_candidates_pallas(*args, capacity=capacity)
                # materialize INSIDE the try: dispatch is async, so kernel
                # failures only surface when results are pulled to host
                posc = np.asarray(posc)
                mask = np.asarray(mask)
                _pallas_scan_ok = True
            except Exception:  # Mosaic lowering/runtime failure → XLA path
                _pallas_scan_ok = False
                posc = mask = None
        if posc is None:
            posc, mask = _scan_candidates(*args, capacity=capacity)
            posc = np.asarray(posc)
            mask = np.asarray(mask)
        return np.sort(posc[mask]).astype(np.int64)
