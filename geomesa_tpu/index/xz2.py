"""XZ2 index: intersects queries over geometries with extent (polygons,
lines).

Analog of the reference's XZ2 index
(geomesa-index-api/.../index/z2/XZ2IndexKeySpace.scala — key =
``[shard][8B sequence code][id]``): one sorted int64 code column +
permutation, with bbox columns for the candidate prefilter and packed
geometries for the exact predicate.

Scan = searchsorted over covering code ranges (host numpy; the column is
small relative to point tables and the exact geometry re-check dominates)
→ bbox mask → exact ``geometry_intersects``.  The bbox prefilter plays the
role the reference's server-side filters play; the exact stage mirrors its
client/iterator CQL re-check.
"""

from __future__ import annotations

import numpy as np

from ..config import DEFAULT_MAX_RANGES
from ..curve.xz2 import XZ2SFC, xz2_sfc
from ..geometry.packed import PackedGeometry, pack_geometries
from ..geometry.predicates import bbox_intersects, geometry_intersects
from ..geometry.types import Geometry, Polygon

__all__ = ["XZ2Index"]


class XZ2Index:
    """Host/device hybrid XZ2 index over non-point geometries."""

    def __init__(self, g: int, codes, pos, bbox, geoms: PackedGeometry | None):
        self.sfc: XZ2SFC = xz2_sfc(g)
        self.codes = codes        # (N,) int64 sorted
        self.pos = pos            # (N,) int32 permutation
        self.bbox = bbox          # (N, 4) float64, original order
        self.geoms = geoms        # packed geometries, original order

    @classmethod
    def build(cls, geoms, g: int = 12) -> "XZ2Index":
        packed = geoms if isinstance(geoms, PackedGeometry) else pack_geometries(geoms)
        sfc = xz2_sfc(g)
        bb = packed.bbox
        codes = sfc.index(bb[:, 0], bb[:, 1], bb[:, 2], bb[:, 3], xp=np)
        order = np.argsort(codes, kind="stable")
        return cls(g, codes[order].astype(np.int64), order.astype(np.int32),
                   bb, packed)

    def __len__(self) -> int:
        return len(self.codes)

    def query(self, geometry: Geometry,
              max_ranges: int = DEFAULT_MAX_RANGES,
              exact: bool = True) -> np.ndarray:
        """Original-order positions of geometries intersecting ``geometry``."""
        env = geometry.envelope
        ranges = self.sfc.ranges([env.as_tuple()], max_ranges=max_ranges)
        if not len(ranges) or not len(self):
            return np.empty(0, dtype=np.int64)
        starts = np.searchsorted(self.codes, ranges[:, 0], side="left")
        ends = np.searchsorted(self.codes, ranges[:, 1], side="right")
        cand = np.concatenate(
            [self.pos[s:e] for s, e in zip(starts, ends)]
        ) if len(starts) else np.empty(0, dtype=np.int64)
        if cand.size == 0:
            return np.empty(0, dtype=np.int64)
        cand = cand[bbox_intersects(self.bbox[cand], env.as_tuple())]
        if exact and self.geoms is not None and not _is_envelope(geometry, env):
            from ..geometry.predicates import packed_intersects
            cand = cand[packed_intersects(self.geoms, geometry, cand)]
        return np.sort(cand).astype(np.int64)


def _is_envelope(geometry: Geometry, env) -> bool:
    """True when the query geometry IS its envelope (bbox query) — the bbox
    prefilter is then already exact at envelope granularity."""
    if not isinstance(geometry, Polygon) or geometry.holes:
        return False
    shell = geometry.shell
    if len(shell) != 5:
        return False
    xs = set(shell[:, 0].tolist())
    ys = set(shell[:, 1].tolist())
    return xs == {env.xmin, env.xmax} and ys == {env.ymin, env.ymax}
