"""Shared LSM compaction policy for the lean generational indexes.

One definition of the size-tiered merge planner and the budgeted
merge-one-replan loop, parameterized by each index variant's tier
names, size metric, and merge mechanics — the policy appeared four
times (z3_lean, attr_lean, parallel/lean, parallel/attr_lean) and a
fix applied to one copy silently missed the others (review: the
factor=1 non-termination guard lives HERE, once).
"""

from __future__ import annotations

import time

__all__ = ["plan_size_tiered", "compact_incremental", "merged_capacity",
           "notify_generation_event", "replace_group"]


def notify_generation_event(index, kind: str, gen_ids: list) -> None:
    """Fan a generation-lifecycle event (``"seal"`` / ``"merge"``) out
    to an index's registered ``generation_listeners``.

    Listeners drive OPTIONAL build-behind work (the density-pyramid
    jobs of ISSUE 18); a listener failure must never break the ingest
    or compaction path that fired the event, so exceptions are
    swallowed here — listeners that want visibility run inside the job
    registry, which records the failure on its own record."""
    for listener in getattr(index, "generation_listeners", ()):
        try:
            listener(kind, list(gen_ids))
        except Exception:  # noqa: BLE001 — background hooks are best-effort
            pass


def replace_group(generations: list, group: list, merged) -> list:
    """The merge epilogue shared by every index variant: drop the
    source runs and place the merged run at the group's OLDEST position
    (list order is demotion age), returning the new generation list."""
    i0 = min(generations.index(g) for g in group)
    dead = {id(g) for g in group}
    out = [g for g in generations if id(g) not in dead]
    out.insert(i0, merged)
    return out


def plan_size_tiered(sealed: list, tiers: tuple, size_of, factor: int
                     ) -> list[list]:
    """Size-tiered merge plan: sealed same-tier runs bucketed by size
    class (log2 of ``size_of(run)``); any bucket holding ≥ ``factor``
    runs yields oldest-first groups of ``factor``.  Repeated
    application turns N flush-sized runs into O(log N) — merged runs
    land in higher buckets and cascade.

    ``factor`` is clamped to ≥ 2: a factor-1 "group" would replace a
    run with an identical-size merged run and re-plan it forever."""
    factor = max(2, int(factor))
    groups: list = []
    for tier in tiers:
        by_size: dict[int, list] = {}
        for g in sealed:
            if g.tier != tier:
                continue
            by_size.setdefault(max(1, int(size_of(g))).bit_length(),
                               []).append(g)
        for b in sorted(by_size):
            runs = by_size[b]
            while len(runs) >= factor:
                groups.append(runs[:factor])
                runs = runs[factor:]
    return groups


def compact_incremental(plan, merge_one, budget_ms: float | None = None,
                        max_groups: int | None = None) -> int:
    """The merge-one-replan loop shared by every compact(): each call
    makes ≥ 1 group of progress when any is eligible, then stops past
    ``budget_ms`` (wall clock — single-controller only) or
    ``max_groups`` (deterministic — the multihost-safe bound and the
    opportunistic trigger's one-group cap).  Returns groups merged;
    interrupted compaction resumes on the next call because the plan
    recomputes from the surviving runs."""
    from ..obs import span as obs_span
    t0 = time.perf_counter()
    groups = plan()
    if not groups:
        # nothing eligible — the common opportunistic post-append case.
        # No span and no timer sample: a bulk ingest calls this once per
        # append, and hundreds of ~0ms no-op traces would evict every
        # query trace from the ring and drive lean.compaction.ms's
        # quantiles to zero
        return 0
    merged = 0
    # ONE span for the whole merge-replan loop (this is the shared
    # policy every index variant routes through, so compaction work is
    # traced here exactly once): groups merged + wall ms, feeding the
    # lean.compaction.ms rollup alongside the existing merge counters
    from ..resilience import check_cancel, fault_point
    with obs_span("lean.compaction") as sp:
        while True:
            # an armed fault or an expired deadline interrupts BETWEEN
            # merges, where the store is always consistent: merge_one
            # swaps a fully-built merged run in atomically, and the
            # next compact() replans from whatever runs survive
            fault_point("compaction.merge_step")
            merge_one(groups[0])
            merged += 1
            if max_groups is not None and merged >= max_groups:
                break
            if (budget_ms is not None
                    and (time.perf_counter() - t0) * 1e3 >= budget_ms):
                break
            if check_cancel("compaction.merge_step"):
                break
            groups = plan()
            if not groups:
                break
        sp.set_attr("merged_groups", merged)
    from ..metrics import registry as _metrics
    _metrics.timer("lean.compaction.ms").update(
        (time.perf_counter() - t0) * 1e3)
    return merged


def merged_capacity(total_valid: int, total_source_cap: int,
                    gather_capacity) -> int:
    """Slot count for a merged run: the pow2 ``gather_capacity`` quantum
    when that fits inside the source runs' combined footprint (bounds
    the distinct merged shapes to O(log) so post-compaction scans reuse
    compiles), else exactly ``total_valid`` (padding must never make a
    merge GROW residency — slack-heavy sources release their slack)."""
    cap = gather_capacity(int(total_valid), minimum=8)
    return cap if cap <= total_source_cap else int(total_valid)
