"""LeanZ3Index: keys-on-device / payload-on-host Z3 index for
HBM-bounded scale (the 500M–1B single-chip path).

The full-fat :class:`geomesa_tpu.index.z3.Z3PointIndex` keeps x/y/dtg
resident next to its keys (40 B/point) so the exact re-check fuses into
the scan — the right trade below ~150M points/chip.  Past that, HBM is
the wall: a v5e chip has 15.75 GiB usable, and the append sort's HLO
temps cost ~1× the column bytes on top of the (donated) resident set
(measured on chip; the int64 z splits into 2×u32 lanes plus payload
copies).

This index is the reference's own storage split re-expressed for TPU:
the device holds only the SEARCHABLE keys — ``(bins int32, z int64,
pos int32)`` = 16 B/point — the role of the tablet server's key space,
while the payload columns stay in host RAM (the "value" fetch; clients
re-check exactly, AccumuloIndexAdapter.scala:181-195).  Scans seek +
gather candidate positions on device; the exact bbox+time mask runs
vectorized on the host payload.

**Generations.**  To pass 500M on ONE chip the keys split into sorted
GENERATIONS of bounded capacity (LSM-flavored): appends fill the
current generation and roll to a new one when full, so the append
sort's working set is one generation — resident ~16 B/pt TOTAL, sort
peak ~16 B/pt over ONE generation only.  Queries seek every generation
and union (positions are globally numbered).  With the default 2^28
generation cap: 500M points = 2 generations, 8 GiB resident, ≤8.6 GiB
peak during a generation's sort — comfortably inside one chip.

Reference mapping: Z3IndexKeySpace.scala:60 (key layout),
IndexAdapter.scala:95-106 (writers), BASELINE.json GDELT-1B north star.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..curve.binnedtime import TimePeriod, to_binned_time
from ..index.z3 import Z3_INDEX_VERSION, plan_z3_query, z3_sfc_for_version
from ..ops.search import (
    expand_ranges, gather_capacity, pad_pow2, pad_ranges, searchsorted2,
)

__all__ = ["LeanZ3Index"]

_SENTINEL_BIN = np.int32(np.iinfo(np.int32).max)
_SENTINEL_Z = np.int64(np.iinfo(np.int64).max)


@partial(jax.jit, static_argnames=("sfc",), donate_argnums=(1, 2, 3))
def _lean_append(sfc, bins, z, pos, r, xs, ys, offs, bs, ps, m):
    """Encode a slice's keys into the sentinel padding at sorted offset
    ``r`` and re-sort (donated: outputs alias the resident columns, so
    peak = resident + sort temps, not 2× resident + temps)."""
    z_new = sfc.index(xs, ys, offs)
    valid = jnp.arange(xs.shape[0]) < m
    b_new = jnp.where(valid, bs, _SENTINEL_BIN)
    z_new = jnp.where(valid, z_new, _SENTINEL_Z)
    p_new = jnp.where(valid, ps, jnp.int32(-1))
    bins = jax.lax.dynamic_update_slice(bins, b_new, (r,))
    z = jax.lax.dynamic_update_slice(z, z_new, (r,))
    pos = jax.lax.dynamic_update_slice(pos, p_new, (r,))
    return jax.lax.sort((bins, z, pos), dimension=0, num_keys=2)


@partial(jax.jit, static_argnames=("capacity",))
def _lean_scan(bins, z, pos, rb, rlo, rhi, capacity: int):
    """Seek + expand + gather candidate positions (covering-range
    members; the exact mask runs on the host payload)."""
    starts = searchsorted2(bins, z, rb, rlo, side="left")
    ends = searchsorted2(bins, z, rb, rhi, side="right")
    counts = jnp.maximum(ends - starts, 0)
    total = jnp.sum(counts)
    idx, valid_slot, _ = expand_ranges(starts, counts, capacity)
    cand = jnp.where(valid_slot, pos[idx], jnp.int32(-1))
    return cand, total


@jax.jit
def _lean_count_multi(rb, rlo, rhi, *cols):
    """Totals probe over EVERY generation in ONE dispatch: a 30-run
    store otherwise pays 30 tunnel round trips per probe (the dispatch
    RTT, ~100ms each, dominates the microseconds of seek work)."""
    outs = []
    for g in range(len(cols) // 2):
        b, z = cols[2 * g], cols[2 * g + 1]
        starts = searchsorted2(b, z, rb, rlo, side="left")
        ends = searchsorted2(b, z, rb, rhi, side="right")
        outs.append(jnp.sum(jnp.maximum(ends - starts, 0)))
    return jnp.stack(outs)


@partial(jax.jit, static_argnames=("capacity",))
def _lean_scan_multi(rb, rlo, rhi, capacity: int, *cols):
    """Candidate gather over every generation in ONE dispatch (the scan
    sibling of :func:`_lean_count_multi`); returns (G, capacity)."""
    outs = []
    for g in range(len(cols) // 3):
        b, z, pos = cols[3 * g], cols[3 * g + 1], cols[3 * g + 2]
        starts = searchsorted2(b, z, rb, rlo, side="left")
        ends = searchsorted2(b, z, rb, rhi, side="right")
        counts = jnp.maximum(ends - starts, 0)
        idx, valid_slot, _ = expand_ranges(starts, counts, capacity)
        outs.append(jnp.where(valid_slot, pos[idx], jnp.int32(-1)))
    return jnp.stack(outs)


#: generation-count compile bucket for the multi-generation programs
_GEN_BUCKET = 4


class _Generation:
    __slots__ = ("bins", "z", "pos", "n")

    def __init__(self, capacity: int):
        self.bins = jnp.full((capacity,), _SENTINEL_BIN, jnp.int32)
        self.z = jnp.full((capacity,), _SENTINEL_Z, jnp.int64)
        self.pos = jnp.full((capacity,), -1, jnp.int32)
        self.n = 0

    @property
    def capacity(self) -> int:
        return int(self.z.shape[0])

    def device_bytes(self) -> int:
        return self.capacity * (4 + 8 + 4)


class LeanZ3Index:
    """Generational keys-on-device Z3 index (see module doc)."""

    #: slots per generation.  Each append re-sorts its generation, so
    #: generation size trades sort cost per slice against run count per
    #: query: slice-sized generations (the scale-proof setting) sort
    #: each slice exactly once — the LSM run-per-flush shape — while
    #: larger generations amortize query seeks.  2^24 keeps the
    #: per-append sort ~0.5 s; a 500M store is then ~30 sorted runs and
    #: queries pay one (probe + scan) pair per run (~ms each, compiled
    #: once).
    GENERATION_SLOTS = 1 << 24
    DEFAULT_CAPACITY = 1 << 15
    #: slot budget for the batched (G × capacity) candidate buffer;
    #: beyond it queries fall back to per-generation buffers sized by
    #: each generation's own total
    BATCH_SCAN_BUDGET = 1 << 26

    def __init__(self, period: TimePeriod | str = TimePeriod.WEEK,
                 version: int = Z3_INDEX_VERSION,
                 generation_slots: int | None = None):
        self.period = TimePeriod.parse(period)
        self.version = version
        self.sfc = z3_sfc_for_version(self.period, version)
        self.generation_slots = generation_slots or self.GENERATION_SLOTS
        self.generations: list[_Generation] = []
        #: host payload slices (x, y, dtg) in append order; finalized
        #: into flat arrays lazily for the exact re-check
        self._payload: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        self._flat: tuple | None = None
        self._n_rows = 0
        self.t_min_ms: int | None = None
        self.t_max_ms: int | None = None

    def __len__(self) -> int:
        return self._n_rows

    def block(self) -> None:
        """Wait for every in-flight append (dispatches are async — honest
        ingest timing must block on the last generation's columns)."""
        if self.generations:
            import jax
            jax.block_until_ready(self.generations[-1].pos)

    def device_bytes(self) -> int:
        """Resident HBM of the key columns (the budget the scale proof
        asserts against docs/scale.md)."""
        return sum(g.device_bytes() for g in self.generations)

    def append(self, x, y, dtg_ms) -> "LeanZ3Index":
        """Stream one slice in: host payload retained by reference, keys
        encoded + merged into the current generation on device (rolling
        to a fresh generation when full)."""
        x = np.ascontiguousarray(x, dtype=np.float64)
        y = np.ascontiguousarray(y, dtype=np.float64)
        dtg_ms = np.ascontiguousarray(dtg_ms, dtype=np.int64)
        m_total = len(x)
        if m_total == 0:
            return self
        self._payload.append((x, y, dtg_ms))
        self._flat = None
        host_bins, host_offs = to_binned_time(dtg_ms, self.period)
        host_bins = host_bins.astype(np.int32)
        host_offs = host_offs.astype(np.float64)
        done = 0
        while done < m_total:
            if not self.generations or (
                    self.generations[-1].n >= self.generations[-1].capacity):
                self.generations.append(_Generation(self.generation_slots))
            gen = self.generations[-1]
            room = gen.capacity - gen.n
            take = min(room, m_total - done)
            m_pad = min(gather_capacity(take, minimum=8), room)
            sl = slice(done, done + take)
            pad = m_pad - take
            ps = np.arange(self._n_rows + done,
                           self._n_rows + done + take, dtype=np.int32)
            gen.bins, gen.z, gen.pos = _lean_append(
                self.sfc, gen.bins, gen.z, gen.pos, jnp.int32(gen.n),
                jnp.asarray(np.pad(x[sl], (0, pad))),
                jnp.asarray(np.pad(y[sl], (0, pad))),
                jnp.asarray(np.pad(host_offs[sl], (0, pad))),
                jnp.asarray(np.pad(host_bins[sl], (0, pad))),
                jnp.asarray(np.pad(ps, (0, pad), constant_values=-1)),
                jnp.int32(take))
            gen.n += take
            done += take
        self._n_rows += m_total
        t_min, t_max = int(dtg_ms.min()), int(dtg_ms.max())
        self.t_min_ms = (t_min if self.t_min_ms is None
                         else min(self.t_min_ms, t_min))
        self.t_max_ms = (t_max if self.t_max_ms is None
                         else max(self.t_max_ms, t_max))
        return self

    def _payload_flat(self):
        if self._flat is None:
            xs, ys, ts = zip(*self._payload) if self._payload else ((), (), ())
            self._flat = (np.concatenate(xs) if xs else np.empty(0),
                          np.concatenate(ys) if ys else np.empty(0),
                          np.concatenate(ts) if ts else np.empty(0, np.int64))
            # the per-slice references are no longer needed — drop them
            # so host RAM holds ONE copy of the payload
            self._payload = [tuple(self._flat)]
        return self._flat

    def _clamp_time(self, t_lo_ms, t_hi_ms) -> tuple[int, int]:
        t_lo_ms = self.t_min_ms if t_lo_ms is None else int(t_lo_ms)
        t_hi_ms = self.t_max_ms if t_hi_ms is None else int(t_hi_ms)
        if self.t_min_ms is not None:
            t_lo_ms = max(t_lo_ms, self.t_min_ms)
        if self.t_max_ms is not None:
            t_hi_ms = min(t_hi_ms, self.t_max_ms)
        return t_lo_ms, t_hi_ms

    def query(self, boxes, t_lo_ms, t_hi_ms,
              max_ranges: int = 2000, progress=None) -> np.ndarray:
        """Exact original-order positions: device candidate seeks over
        every generation + host exact bbox/time mask on the payload."""
        if self._n_rows == 0:  # before planning: open bounds clamp to a
            return np.empty(0, dtype=np.int64)  # nonexistent extent
        t_lo_ms, t_hi_ms = self._clamp_time(t_lo_ms, t_hi_ms)
        plan = plan_z3_query(boxes, t_lo_ms, t_hi_ms, self.period,
                             max_ranges, sfc=self.sfc)
        if plan.num_ranges == 0:
            return np.empty(0, dtype=np.int64)
        r = pad_ranges({"rbin": plan.rbin, "rzlo": plan.rzlo,
                        "rzhi": plan.rzhi}, pad_pow2(plan.num_ranges))
        rb = jnp.asarray(r["rbin"])
        rlo = jnp.asarray(r["rzlo"])
        rhi = jnp.asarray(r["rzhi"])
        # probe totals and gather candidates for ALL generations in one
        # dispatch each — per-generation dispatches cost a tunnel RTT
        # apiece, which dominated 500M-store queries (30 runs × 2 ×
        # ~120ms).  The list pads to a compile bucket with the LAST
        # generation repeated (no extra HBM; duplicate hits dedup below)
        gens = list(self.generations)
        n_pad = (-len(gens)) % _GEN_BUCKET
        padded = gens + [gens[-1]] * n_pad
        count_cols: list = []
        for gen in padded:
            count_cols += [gen.bins, gen.z]
        if progress is not None:
            progress(f"    probing {len(gens)} generations")
        totals = np.asarray(_lean_count_multi(rb, rlo, rhi, *count_cols))
        if int(totals[:len(gens)].sum()) == 0:
            return np.empty(0, dtype=np.int64)
        capacity = gather_capacity(int(totals.max()),
                                   minimum=self.DEFAULT_CAPACITY)
        if len(padded) * capacity <= self.BATCH_SCAN_BUDGET:
            scan_cols: list = []
            for gen in padded:
                scan_cols += [gen.bins, gen.z, gen.pos]
            packed = np.asarray(_lean_scan_multi(rb, rlo, rhi, capacity,
                                                 *scan_cols))
            flat = packed.ravel()
        else:
            # huge candidate sets: the shared-capacity batched buffer
            # would cost G × max-total slots of HBM — fall back to
            # per-generation scans sized by each generation's OWN total
            parts = []
            for gen, tot in zip(gens, totals[:len(gens)]):
                if int(tot) == 0:
                    continue
                cap_g = gather_capacity(int(tot),
                                        minimum=self.DEFAULT_CAPACITY)
                cand_g, _ = _lean_scan(gen.bins, gen.z, gen.pos,
                                       rb, rlo, rhi, cap_g)
                parts.append(np.asarray(cand_g))
            flat = np.concatenate(parts) if parts else np.empty(0,
                                                                np.int32)
        # unique: bucket padding repeats the last generation's hits
        cand = np.unique(flat[flat >= 0]).astype(np.int64)
        if not len(cand):
            return np.empty(0, dtype=np.int64)
        # exact host re-check on the payload (the client-side filter)
        x, y, t = self._payload_flat()
        boxes = np.atleast_2d(np.asarray(boxes, dtype=np.float64))
        cx, cy, ct = x[cand], y[cand], t[cand]
        in_box = np.zeros(len(cand), dtype=bool)
        for b in boxes:
            in_box |= ((cx >= b[0]) & (cy >= b[1])
                       & (cx <= b[2]) & (cy <= b[3]))
        keep = in_box & (ct >= t_lo_ms) & (ct <= t_hi_ms)
        return np.sort(cand[keep])
