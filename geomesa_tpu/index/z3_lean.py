"""LeanZ3Index: tiered generational Z3 index for HBM-bounded scale
(the 500M–1B single-chip path, and the scale profile of the store).

The full-fat :class:`geomesa_tpu.index.z3.Z3PointIndex` keeps x/y/dtg
resident next to its keys (40 B/point) so the exact re-check fuses into
the scan — the right trade below ~150M points/chip.  Past that, HBM is
the wall: a v5e chip has 15.75 GiB usable, and the append sort's HLO
temps cost ~1× the column bytes on top of the (donated) resident set
(measured on chip; the int64 z splits into 2×u32 lanes plus payload
copies).

This index is the reference's own storage split re-expressed for TPU:
the searchable keys — ``(bins int32, z int64, pos int32)`` = 16 B/point,
the role of the tablet server's key space — live in sorted GENERATIONS
of bounded capacity (LSM-flavored: appends fill the current generation
and roll to a new one when full, so the append sort's working set is
one generation), while the payload columns stay in host RAM (the
"value" fetch; clients re-check exactly,
AccumuloIndexAdapter.scala:181-195).

**Tiers.**  Each generation has a residency tier, demoted oldest-first
as the store outgrows ``hbm_budget_bytes`` (round-4 VERDICT #2/#7):

* ``full`` — keys AND an (x, y, t) payload copy on device (40 B/pt):
  the exact bbox+time mask runs fused on device per generation and only
  survivors cross the wire — no host gather at all (the full-fat scan's
  exactness at generational scale).
* ``keys`` — keys only on device (16 B/pt): device seeks + candidate
  gather; the exact mask runs vectorized on the host payload.
* ``host`` — the sorted key run spilled to host RAM (0 B HBM): numpy
  segmented searchsorted seeks.  This is how 1B points fit one chip —
  1B × 16 B = 16 GB exceeds HBM, so cold runs live beside the payload
  in host RAM while hot runs keep device seeks.

Queries batch ALL windows × ALL device generations into a fixed number
of dispatches (a totals probe + one scan per populated tier) — through
a remote tunnel each dispatch costs a ~100ms round trip, which
dominated per-generation scans (round-3).  Generation-count compile
buckets pad with a shared 8-slot EMPTY sentinel generation, so padding
does no seek/gather work (round-3 VERDICT weak #5).

**LSM lifecycle.**  Without maintenance, a streamed 1B build
accumulates ~60 generations and every query/density call fans out over
all of them (BENCH_r05: density_1b_ms 90.8s).  Two mechanisms bound
that growth:

* **Compaction** — :meth:`LeanZ3Index.compact`, a budgeted/resumable
  size-tiered K-way merge (device ``lax.sort`` for keys-tier runs,
  numpy lexsort for spilled host runs) that folds ≥ F same-tier
  same-size-class sealed runs into one, driving the run count to
  O(log N).  The reference delegates this to its key-value backend's
  periodic compaction; the lean store must run its own.
* **Sealed-generation density partials** — once a generation is sealed
  (demoted off the live slot), its contribution to a given density
  (boxes, window, env, grid) spec is immutable; the per-generation
  grids cache (LRU over specs) and warm repeat calls re-scan only the
  live generation and full-tier generations (whose value-exact edge
  masks the cache must not coarsen).

Reference mapping: Z3IndexKeySpace.scala:60 (key layout),
IndexAdapter.scala:95-106 (writers), AccumuloQueryPlan.scala:87-157
(scan plans over sorted runs), BASELINE.json GDELT-1B north star.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..curve.binnedtime import TimePeriod, to_binned_time
from ..index.z3 import Z3_INDEX_VERSION, plan_z3_query, z3_sfc_for_version
from ..metrics import (
    LEAN_COMPACTION_MERGES, LEAN_COMPACTION_ROWS,
    LEAN_DENSITY_CACHE_HITS, LEAN_DENSITY_CACHE_MISSES,
    LEAN_SKETCH_CACHE_HITS, LEAN_SKETCH_CACHE_MISSES,
    PYRAMID_BUILDS, PYRAMID_BUILD_MS, PYRAMID_SERVE_HITS,
    RESILIENCE_DEGRADED, RESILIENCE_RETRIES,
    WRITE_SEALS, WRITE_SPILLS, registry as _metrics,
)
from ..obs import device_span, obs_count, span as obs_span
from ..obs.heat import (
    heat_enabled, merge_index_generations, record_index_scan,
)
from ..ops.search import (
    coded_pos_bits, expand_ranges, gather_capacity, pad_boxes, pad_pow2,
    pad_ranges, searchsorted2, wire_dtype,
)

__all__ = ["LeanZ3Index", "HostStack", "merge_host_runs"]

_SENTINEL_BIN = np.int32(np.iinfo(np.int32).max)
_SENTINEL_Z = np.int64(np.iinfo(np.int64).max)

#: per-slot byte widths, derived ONCE from the column dtypes (bins
#: int32 + z int64 + pos int32 — positions are generation-local int32
#: here, unlike the sharded index's int64 gids — and the full tier
#: adds x/y f64 + t int64).  Every budget computation uses these, so a
#: dtype change cannot silently skew the HBM accounting.
KEYS_BYTES = 4 + 8 + 4
PAYLOAD_BYTES = 8 + 8 + 8
FULL_BYTES = KEYS_BYTES + PAYLOAD_BYTES


def _append_keys_body(sfc, bins, z, pos, r, base, xs, ys, offs, bs, m):
    """Shared append body (traced inline by both jitted wrappers so the
    two tiers cannot diverge): encode a slice's keys into the sentinel
    padding at sorted offset ``r`` and re-sort.  ``base`` is the
    generation's first global row id; positions are global."""
    z_new = sfc.index(xs, ys, offs)
    valid = jnp.arange(xs.shape[0]) < m
    b_new = jnp.where(valid, bs, _SENTINEL_BIN)
    z_new = jnp.where(valid, z_new, _SENTINEL_Z)
    p_new = jnp.where(valid, base + r
                      + jnp.arange(xs.shape[0], dtype=jnp.int32),
                      jnp.int32(-1))
    bins = jax.lax.dynamic_update_slice(bins, b_new, (r,))
    z = jax.lax.dynamic_update_slice(z, z_new, (r,))
    pos = jax.lax.dynamic_update_slice(pos, p_new, (r,))
    return jax.lax.sort((bins, z, pos), dimension=0, num_keys=2)


@partial(jax.jit, static_argnames=("sfc",), donate_argnums=(1, 2, 3))
def _lean_append(sfc, bins, z, pos, r, base, xs, ys, offs, bs, m):
    """``keys``-tier append (donated: outputs alias the resident
    columns, so peak = resident + sort temps, not 2× resident)."""
    return _append_keys_body(sfc, bins, z, pos, r, base, xs, ys, offs,
                             bs, m)


@partial(jax.jit, static_argnames=("sfc",),
         donate_argnums=(1, 2, 3, 4, 5, 6))
def _lean_append_full(sfc, bins, z, pos, xp, yp, tp, r, base,
                      xs, ys, offs, bs, ts, m):
    """The ``full``-tier append: keys via the shared body plus the
    (x, y, t) payload columns updated at ``[r, r+m_pad)`` in APPEND
    order (like the full-fat index, payload is gathered by position —
    ``pos - base`` — not sorted; _append_step, index/z3.py)."""
    bins, z, pos = _append_keys_body(sfc, bins, z, pos, r, base,
                                     xs, ys, offs, bs, m)
    xp = jax.lax.dynamic_update_slice(xp, xs, (r,))
    yp = jax.lax.dynamic_update_slice(yp, ys, (r,))
    tp = jax.lax.dynamic_update_slice(tp, ts, (r,))
    return bins, z, pos, xp, yp, tp


@jax.jit
def _lean_count_multi(rb, rlo, rhi, *cols):
    """Totals probe over EVERY device generation in ONE dispatch: a
    30-run store otherwise pays 30 tunnel round trips per probe (the
    dispatch RTT, ~100ms each, dominates the microseconds of seek
    work)."""
    outs = []
    for g in range(len(cols) // 2):
        b, z = cols[2 * g], cols[2 * g + 1]
        starts = searchsorted2(b, z, rb, rlo, side="left")
        ends = searchsorted2(b, z, rb, rhi, side="right")
        outs.append(jnp.sum(jnp.maximum(ends - starts, 0)))
    return jnp.stack(outs)


@partial(jax.jit, static_argnames=("capacity", "pos_bits"))
def _lean_scan_coded(rb, rlo, rhi, rqid, *cols,
                     capacity: int, pos_bits: int):
    """CANDIDATE gather over ``keys``-tier generations in ONE dispatch:
    per generation, seek + expand + gather global positions, coded as
    ``qid << pos_bits | pos`` (the multi-window wire layout of
    ops/search.pack_coded).  Returns (G, capacity); the exact bbox/time
    mask runs on the host payload."""
    dt = wire_dtype(pos_bits)
    outs = []
    for g in range(len(cols) // 3):
        b, z, pos = cols[3 * g], cols[3 * g + 1], cols[3 * g + 2]
        starts = searchsorted2(b, z, rb, rlo, side="left")
        ends = searchsorted2(b, z, rb, rhi, side="right")
        counts = jnp.maximum(ends - starts, 0)
        idx, valid, rid = expand_ranges(starts, counts, capacity)
        coded = ((rqid[rid].astype(dt) << dt(pos_bits))
                 | pos[idx].astype(dt))
        outs.append(jnp.where(valid, coded, dt(-1)))
    return jnp.stack(outs)


@partial(jax.jit, static_argnames=("capacity", "pos_bits"))
def _lean_scan_exact_keep(rb, rlo, rhi, rqid, boxes, bqid, qtlo, qthi,
                          *cols, capacity: int, pos_bits: int):
    """Two-phase sibling of :func:`_lean_scan_exact_coded`: the coded
    buffer STAYS ON DEVICE and only the hit count crosses; the host
    then dispatches :func:`_compact_coded` for a survivors-sized
    transfer.  The winning trade for candidate-heavy queries — the
    device already knows the exact survivors (full tier), so shipping
    a capacity-sized buffer at ~125ms/MB to keep 0.1%% of it is pure
    waste (the full-fat index's _scan_keep_device trade, index/z3.py)."""
    packed = _lean_scan_exact_coded(
        rb, rlo, rhi, rqid, boxes, bqid, qtlo, qthi, *cols,
        capacity=capacity, pos_bits=pos_bits)
    return packed, jnp.sum(packed >= 0)


@partial(jax.jit, static_argnames=("out_cap",))
def _lean_merge_keys(*cols, out_cap: int):
    """COMPACTION merge: fold K sorted ``keys``-tier runs into ONE
    sorted run in a single dispatch.  ``lax.sort`` over the
    concatenated columns is the same radix kernel appends use; every
    sentinel slot floats past the valid rows, so the leading
    ``out_cap`` (= total valid rows) slots ARE the merged run — the
    merged generation carries ZERO sentinel padding and releases every
    slack slot the K source runs held (the memory.py-budget visible
    effect of a merge)."""
    k = len(cols) // 3
    bins = jnp.concatenate([cols[3 * i] for i in range(k)])
    z = jnp.concatenate([cols[3 * i + 1] for i in range(k)])
    pos = jnp.concatenate([cols[3 * i + 2] for i in range(k)])
    bins, z, pos = jax.lax.sort((bins, z, pos), dimension=0, num_keys=2)
    return bins[:out_cap], z[:out_cap], pos[:out_cap]


def merge_host_runs(runs: list["HostRun"]) -> "HostRun":
    """COMPACTION merge for spilled runs: K sorted host runs fold into
    one sorted :class:`HostRun` via a composite (bin, z) lexsort —
    numpy's near-sorted merge path; the per-run bins columns are
    reconstructed from the segment tables (stacked runs hand their
    ``bins`` ownership to the :class:`HostStack`)."""
    bins = np.concatenate([
        np.repeat(r._bin_vals, np.diff(r._bin_starts)) for r in runs])
    z = np.concatenate([np.asarray(r.z) for r in runs])
    pos = np.concatenate([np.asarray(r.pos) for r in runs])
    order = np.lexsort((z, bins))
    return HostRun(np.ascontiguousarray(bins[order]),
                   np.ascontiguousarray(z[order]),
                   np.ascontiguousarray(pos[order]))


@partial(jax.jit, static_argnames=("k",))
def _compact_coded(packed, k: int):
    """Descending sort floats the valid (>= 0) coded hits to the front;
    the first ``k`` slots cover all survivors (k = pow2 >= hits)."""
    return -jnp.sort(-packed.ravel())[:k]


@jax.jit
def _lean_gather_payload(idx, xp, yp, tp):
    """Result-materialization column gather (ISSUE 14): ONE batched
    take of a full-tier generation's (x, y, t) payload for a chunk of
    hit offsets.  ``idx`` is padded to a gather_capacity bucket so warm
    repeats of the same result shape reuse the compiled program."""
    return xp[idx], yp[idx], tp[idx]


#: combined (G_pad × capacity) slot count at which the exact tier's
#: two-phase read (device compaction + survivors-sized transfer) beats
#: shipping the full coded buffer: an extra ~100ms round trip vs
#: ~125ms/MB of padded int buffer
_TWO_PHASE_MIN_SLOTS = 1 << 18


def _bins_spanned(t_lo_ms: int, t_hi_ms: int, period) -> int:
    """Time bins a clamped interval covers (per-window range budgets
    scale by it: a tiny box over 27 open-bounds bins would otherwise
    get 2000/27 ranges per bin — overcovering hundreds of thousands of
    candidates for a handful of hits)."""
    b_lo, _ = to_binned_time(np.int64(max(0, t_lo_ms)), period)
    b_hi, _ = to_binned_time(np.int64(max(0, t_hi_ms)), period)
    return max(1, int(b_hi) - int(b_lo) + 1)


#: hard per-window range cap after per-bin scaling (device seeks are
#: cheap — a 32k-range searchsorted batch is microseconds — but plan
#: assembly and upload are host work)
_MAX_RANGES_PER_WINDOW = 1 << 14


@partial(jax.jit, static_argnames=("capacity", "pos_bits"))
def _lean_scan_exact_coded(rb, rlo, rhi, rqid, boxes, bqid, qtlo, qthi,
                           *cols, capacity: int, pos_bits: int):
    """EXACT scan over ``full``-tier generations in ONE dispatch: seek +
    gather + the fused f64 bbox+time mask over the generation's DEVICE
    payload (round-4 VERDICT #7 — no host gather at all).  A candidate
    only matches boxes/time bounds of its own window (cqid/bqid, the
    _query_many_packed discipline).  Returns (G, capacity) coded hits;
    every non-negative slot is a TRUE hit."""
    dt = wire_dtype(pos_bits)
    outs = []
    for g in range(len(cols) // 7):
        b, z, pos, xp, yp, tp, base = cols[7 * g: 7 * g + 7]
        starts = searchsorted2(b, z, rb, rlo, side="left")
        ends = searchsorted2(b, z, rb, rhi, side="right")
        counts = jnp.maximum(ends - starts, 0)
        idx, valid, rid = expand_ranges(starts, counts, capacity)
        posc = pos[idx]
        local = jnp.maximum(posc - base, 0)
        xc = xp[local]
        yc = yp[local]
        tc = tp[local]
        cqid = rqid[rid]
        same_q = cqid[:, None] == bqid[None, :]
        in_box = (
            (xc[:, None] >= boxes[None, :, 0])
            & (yc[:, None] >= boxes[None, :, 1])
            & (xc[:, None] <= boxes[None, :, 2])
            & (yc[:, None] <= boxes[None, :, 3])
            & same_q
        ).any(axis=1)
        ok = (valid & in_box
              & (tc >= qtlo[cqid]) & (tc <= qthi[cqid]))
        coded = (cqid.astype(dt) << dt(pos_bits)) | posc.astype(dt)
        outs.append(jnp.where(ok, coded, dt(-1)))
    return jnp.stack(outs)


def _grid_accum(xc, yc, ok, env, width: int, height: int, grid):
    """Count masked points into a flat (height*width) float64 grid via
    sort + boundary differences (the ops/density.density_grid_sorted
    shape): integer counts from searchsorted bounds are EXACT at any
    magnitude (no f32 saturation at 2^24 — review r5) and the native
    int32 sort beats TPU's emulated-f64 scatter-add by ~20x at scale
    (11.8s → sub-second per 40M, measured on chip).  Masked rows sort
    to a sentinel cell past the grid."""
    fx = (xc - env[0]) / jnp.maximum(env[2] - env[0], 1e-12) * width
    fy = (yc - env[1]) / jnp.maximum(env[3] - env[1], 1e-12) * height
    gx = jnp.clip(fx.astype(jnp.int32), 0, width - 1)
    gy = jnp.clip(fy.astype(jnp.int32), 0, height - 1)
    flat = jnp.where(ok, gy * width + gx, jnp.int32(width * height))
    flat_s = jnp.sort(flat)
    bounds = jnp.searchsorted(
        flat_s, jnp.arange(width * height + 1, dtype=jnp.int32),
        side="left")
    return grid + (bounds[1:] - bounds[:-1]).astype(jnp.float64)


@partial(jax.jit, static_argnames=("sfc", "capacity", "width", "height"))
def _lean_density_full(sfc, rb, rlo, rhi, boxes, qtlo, qthi, env, *cols,
                       capacity: int, width: int, height: int):
    """DensityScan over ``full``-tier generations in ONE dispatch: seek
    + gather + the fused EXACT payload mask + grid scatter-add — only
    the (height, width) grid crosses the wire, never a candidate
    (round-4 VERDICT #2; DensityScan.scala:31-59 runs next to the data
    the same way).  The MASK runs on raw f64 payload (value-exact);
    grid BINNING goes through the z-cell midpoint (normalize →
    denormalize) so cell assignment is integer-deterministic across
    platforms — raw-f64 binning flipped boundary points by one grid
    cell between TPU and host f64 rounding (measured on chip)."""
    grid = jnp.zeros((height * width,), jnp.float64)
    for g in range(len(cols) // 7):
        b, z, pos, xp, yp, tp, base = cols[7 * g: 7 * g + 7]
        starts = searchsorted2(b, z, rb, rlo, side="left")
        ends = searchsorted2(b, z, rb, rhi, side="right")
        counts = jnp.maximum(ends - starts, 0)
        idx, valid, _rid = expand_ranges(starts, counts, capacity)
        local = jnp.maximum(pos[idx] - base, 0)
        xc, yc, tc = xp[local], yp[local], tp[local]
        in_box = (
            (xc[:, None] >= boxes[None, :, 0])
            & (yc[:, None] >= boxes[None, :, 1])
            & (xc[:, None] <= boxes[None, :, 2])
            & (yc[:, None] <= boxes[None, :, 3])
        ).any(axis=1)
        ok = valid & in_box & (tc >= qtlo) & (tc <= qthi)
        xd = sfc.lon.denormalize(sfc.lon.normalize(xc, xp=jnp), xp=jnp)
        yd = sfc.lat.denormalize(sfc.lat.normalize(yc, xp=jnp), xp=jnp)
        grid = _grid_accum(xd, yd, ok, env, width, height, grid)
    return grid.reshape((height, width))


@partial(jax.jit, static_argnames=("sfc", "capacity", "width", "height"))
def _lean_density_keys(sfc, rb, rlo, rhi, ixy, tb, env, *cols,
                       capacity: int, width: int, height: int):
    """DensityScan over ``keys``-tier generations: the z KEY decodes to
    CELL coordinates on device (21 bits/dim ≈ 1.7e-4°, orders finer
    than any density cell), so the grid accumulates with NO payload and
    NO host transfer.  Masks compare at CELL granularity in normalized
    space — ``ixy`` holds per-box normalized (ix0, iy0, ix1, iy1) and
    ``tb`` = (bin_lo, cell_lo, bin_hi, cell_hi) — which is EXACT for
    whole-extent scans and cell-inclusive (≤ one 1.7e-4° z cell of
    over-coverage at edges) otherwise; the cell CENTER lands each hit
    in its true grid cell whenever grid cells are coarser than z cells
    (every realistic density grid).

    Returns STACKED per-generation grids ``(G, height, width)`` — one
    dispatch either way, but per-generation partials let the caller
    CACHE each sealed generation's immutable contribution (the
    aggregate cache; the grids sum on the host)."""
    from ..curve.zorder import deinterleave3
    grids = []
    for g in range(len(cols) // 2):
        grid = jnp.zeros((height * width,), jnp.float64)
        b, z = cols[2 * g], cols[2 * g + 1]
        starts = searchsorted2(b, z, rb, rlo, side="left")
        ends = searchsorted2(b, z, rb, rhi, side="right")
        counts = jnp.maximum(ends - starts, 0)
        idx, valid, _rid = expand_ranges(starts, counts, capacity)
        zc = z[idx]
        bc = b[idx].astype(jnp.int64)
        ix, iy, it = deinterleave3(zc.astype(jnp.uint64))
        ix = ix.astype(jnp.int32)
        iy = iy.astype(jnp.int32)
        it = it.astype(jnp.int32)
        in_box = (
            (ix[:, None] >= ixy[None, :, 0])
            & (iy[:, None] >= ixy[None, :, 1])
            & (ix[:, None] <= ixy[None, :, 2])
            & (iy[:, None] <= ixy[None, :, 3])
        ).any(axis=1)
        after = (bc > tb[0]) | ((bc == tb[0]) & (it >= tb[1]))
        before = (bc < tb[2]) | ((bc == tb[2]) & (it <= tb[3]))
        ok = valid & in_box & after & before
        xd = sfc.lon.denormalize(ix, xp=jnp)
        yd = sfc.lat.denormalize(iy, xp=jnp)
        grid = _grid_accum(xd, yd, ok, env, width, height, grid)
        grids.append(grid.reshape((height, width)))
    return jnp.stack(grids)


@partial(jax.jit, static_argnames=("sfc", "width", "height", "world"))
def _lean_density_sweep(sfc, env, *zs, width: int, height: int,
                        world: bool):
    """WHOLE-EXTENT DensityScan: no seek, no expand — every slot of
    every generation decodes its grid cell straight from the z key and
    counts via sort + boundary differences.  With a world envelope AND
    power-of-two grid dims the binning is pure integer arithmetic
    ((cell * width) >> precision — exactly the midpoint binning when
    width divides 2^precision, which pow2 widths ≤ 2^20 do); any other
    envelope/width takes the f64 midpoint path so the fast and slow
    scan paths always bin identically (review r5).  Sentinel slots
    sort past the grid.  Returns STACKED per-generation grids
    ``(G, height, width)`` so sealed generations' partials can cache
    (see _lean_density_keys)."""
    from ..curve.zorder import deinterleave3
    grids = []
    p = sfc.lon.precision
    for z in zs:
        grid = jnp.zeros((height * width,), jnp.float64)
        ok = z != _SENTINEL_Z
        ix, iy, _it = deinterleave3(z.astype(jnp.uint64))
        if world:
            gx = ((ix.astype(jnp.int64) * width) >> p).astype(jnp.int32)
            gy = ((iy.astype(jnp.int64) * height) >> p).astype(jnp.int32)
        else:
            xd = sfc.lon.denormalize(ix.astype(jnp.int32), xp=jnp)
            yd = sfc.lat.denormalize(iy.astype(jnp.int32), xp=jnp)
            fx = ((xd - env[0]) / jnp.maximum(env[2] - env[0], 1e-12)
                  * width)
            fy = ((yd - env[1]) / jnp.maximum(env[3] - env[1], 1e-12)
                  * height)
            gx = jnp.clip(fx.astype(jnp.int32), 0, width - 1)
            gy = jnp.clip(fy.astype(jnp.int32), 0, height - 1)
        flat = jnp.where(ok, gy * width + gx,
                         jnp.int32(width * height))
        flat_s = jnp.sort(flat)
        bounds = jnp.searchsorted(
            flat_s, jnp.arange(width * height + 1, dtype=jnp.int32),
            side="left")
        grid = grid + (bounds[1:] - bounds[:-1]).astype(jnp.float64)
        grids.append(grid.reshape((height, width)))
    return jnp.stack(grids)


@partial(jax.jit, static_argnames=("bits", "nb"))
def _z3_cells_multi(b0, *cols, bits: int, nb: int):
    """Z3Histogram push-down fold over device generations in ONE
    dispatch (ISSUE 3): every slot's coarse cell is the TOP BITS of its
    z key (``z >> (63 - bits)`` — exactly Z3HistogramStat's cell
    function), so the per-generation (time-bin × cell) count tables
    accumulate with no payload and no candidate; only the tiny stacked
    tables cross the wire.  ``nb`` is the time-bin span ``[b0, b0+nb)``
    of the data extent; sentinel slots (and any out-of-span bin) fold
    into a discarded overflow slot."""
    size = nb << bits
    outs = []
    for g in range(len(cols) // 2):
        b, z = cols[2 * g], cols[2 * g + 1]
        mask = z != _SENTINEL_Z
        cell = z >> jnp.int64(63 - bits)
        flat = (b.astype(jnp.int64) - b0) * jnp.int64(1 << bits) + cell
        ok = mask & (flat >= 0) & (flat < size)
        flat = jnp.where(ok, flat, size).astype(jnp.int32)
        outs.append(jnp.zeros((size + 1,), jnp.int64)
                    .at[flat].add(1)[:size])
    return jnp.stack(outs)


_WORLD_ENV = (-180.0, -90.0, 180.0, 90.0)


#: generation-count compile bucket for the multi-generation programs
_GEN_BUCKET = 4

def _make_sentinel_cols(tier: str, slots: int):
    """Empty generation columns for bucket padding: FULL-SIZE (same
    slot count as the real generations, all-sentinel keys), so every
    padded program has the uniform shape ``(slots,) × G_pad`` and
    compiles once per BUCKET, not once per real generation count — at
    60 sorted runs over a remote-compile tunnel the difference is
    minutes of compile per checkpoint.  All-sentinel keys match zero
    seeks, so padding still does no real expand work (round-3 VERDICT
    weak #5); one shared buffer per index is passed for every padded
    slot (cached per-INSTANCE so its device arrays die with the index
    and eviction cannot steal another live index's padding)."""
    bins = jnp.full((slots,), _SENTINEL_BIN, jnp.int32)
    z = jnp.full((slots,), _SENTINEL_Z, jnp.int64)
    pos = jnp.full((slots,), -1, jnp.int32)
    if tier == "full":
        zero = jnp.zeros((slots,), jnp.float64)
        t0 = jnp.zeros((slots,), jnp.int64)
        return (bins, z, pos, zero, zero, t0, jnp.int32(0))
    return (bins, z, pos)


class HostRun:
    """One sorted key run spilled to host RAM (the ``host`` residency
    tier, single-chip AND per-shard on the mesh): numpy segmented
    searchsorted seeks — per distinct query bin, two vectorized
    z-searchsorted calls within the bin's segment (bins are few: the
    time-period bins of the data extent)."""

    __slots__ = ("bins", "z", "pos", "_bin_vals", "_bin_starts")

    def __init__(self, bins: np.ndarray, z: np.ndarray, pos: np.ndarray):
        self.bins, self.z, self.pos = bins, z, pos
        self._bin_vals, starts = np.unique(bins, return_index=True)
        self._bin_starts = np.append(starts, len(bins))

    def __len__(self) -> int:
        return len(self.z)

    def seek(self, rb, rlo, rhi):
        """Per-range [start, end) offsets into the run."""
        starts = np.zeros(len(rb), np.int64)
        ends = np.zeros(len(rb), np.int64)
        if len(self.z) == 0:
            return starts, ends
        for b in np.unique(rb):
            bi = np.searchsorted(self._bin_vals, b)
            if bi >= len(self._bin_vals) or self._bin_vals[bi] != b:
                continue
            s0, s1 = self._bin_starts[bi], self._bin_starts[bi + 1]
            seg = self.z[s0:s1]
            sel = rb == b
            starts[sel] = s0 + np.searchsorted(seg, rlo[sel], side="left")
            ends[sel] = s0 + np.searchsorted(seg, rhi[sel], side="right")
        return starts, ends

    def _expand(self, rb, rlo, rhi):
        """(flat z indices, owning range) for a range batch over THIS
        run — the single-run twin of :meth:`HostStack._expand`."""
        starts, ends = self.seek(rb, rlo, rhi)
        counts = np.maximum(ends - starts, 0)
        cum = np.cumsum(counts)
        total = int(cum[-1]) if len(cum) else 0
        if total == 0:
            return None, None
        j = np.arange(total)
        rid = np.searchsorted(cum, j, side="right")
        prev = np.where(rid > 0, cum[rid - 1], 0)
        return starts[rid] + (j - prev), rid

    def candidates(self, rb, rlo, rhi, rqid, pos_bits: int) -> np.ndarray:
        """Coded candidate positions ``qid << pos_bits | pos`` for a
        padded range batch (the numpy twin of the device expand)."""
        idx, rid = self._expand(rb, rlo, rhi)
        if idx is None:
            return np.empty(0, np.int64)
        return ((rqid[rid].astype(np.int64) << pos_bits)
                | self.pos[idx].astype(np.int64))

    def cell_counts(self, b0: int, nb: int, bits: int) -> np.ndarray:
        """Z3Histogram partial over THIS spilled run: flat
        ``(bin - b0) << bits | cell`` counts — the numpy twin of one
        generation's slice of :func:`_z3_cells_multi` (bins rebuild
        from the segment table; the stack owns the columns)."""
        bins = np.repeat(self._bin_vals,
                         np.diff(self._bin_starts)).astype(np.int64)
        cell = np.asarray(self.z).astype(np.int64) >> (63 - bits)
        size = nb << bits
        flat = (bins - b0) * (1 << bits) + cell
        ok = (flat >= 0) & (flat < size)
        return np.bincount(flat[ok], minlength=size)[:size] \
            .astype(np.int64)

    def sweep_partial(self, sfc, env, width: int, height: int,
                      world: bool) -> np.ndarray:
        """Whole-extent grid partial over THIS run (no seeks — every
        row decodes its cell from the z key; the numpy twin of one
        generation's slice of ``_lean_density_sweep``)."""
        from ..curve.zorder import deinterleave3
        ix, iy, _ = deinterleave3(np.asarray(self.z).astype(np.uint64),
                                  xp=np)
        p = sfc.lon.precision
        if world:
            gx = (ix.astype(np.int64) * width) >> p
            gy = (iy.astype(np.int64) * height) >> p
        else:
            xd = sfc.lon.denormalize(ix.astype(np.int64), xp=np)
            yd = sfc.lat.denormalize(iy.astype(np.int64), xp=np)
            gx = np.clip(((xd - env[0])
                          / max(env[2] - env[0], 1e-12)
                          * width).astype(np.int64), 0, width - 1)
            gy = np.clip(((yd - env[1])
                          / max(env[3] - env[1], 1e-12)
                          * height).astype(np.int64), 0, height - 1)
        return np.bincount(
            (gy * width + gx).astype(np.int64),
            minlength=width * height
        )[:width * height].reshape((height, width)).astype(np.float64)


def _bisect_segments(z: np.ndarray, vals: np.ndarray, lo: np.ndarray,
                     hi: np.ndarray, side: str) -> np.ndarray:
    """Vectorized binary search of ``vals[i]`` within the sorted
    segments ``z[lo[i]:hi[i]]`` — one numpy bisection loop serves EVERY
    (range × run-segment) pair at once, which is what makes host-tier
    seek cost flat in the number of spilled runs (round-4 VERDICT #9:
    the per-run Python loop serialized at the hundreds of host
    generations the 10B-per-pod story implies)."""
    lo = lo.astype(np.int64).copy()
    hi = hi.astype(np.int64).copy()
    while True:
        active = lo < hi
        if not active.any():
            return lo
        mid = (lo + hi) >> 1
        zm = z[np.where(active, mid, 0)]
        below = zm < vals if side == "left" else zm <= vals
        go = active & below
        lo = np.where(go, mid + 1, lo)
        hi = np.where(active & ~below, mid, hi)


class HostStack:
    """EVERY spilled run stacked into one contiguous key store with a
    global (bin → segment) table: a query batch seeks ALL host
    generations with two vectorized bisections total, instead of a
    Python loop per run per bin (round-4 VERDICT #9).

    The stack OWNS the concatenated arrays; each constituent
    :class:`HostRun`'s columns are re-pointed at views into them, so
    host RAM holds ONE copy of the spilled keys (a transient second
    copy exists only while a rebuild concatenates)."""

    __slots__ = ("z", "pos", "seg_bin", "seg_lo", "seg_hi", "seg_run",
                 "n_runs")

    def __init__(self, runs: list["HostRun"]):
        zs, ps, sb, sl, sh, sr = [], [], [], [], [], []
        off = 0
        for i, run in enumerate(runs):
            zs.append(run.z)
            ps.append(run.pos)
            sb.append(run._bin_vals)
            sl.append(off + run._bin_starts[:-1])
            sh.append(off + run._bin_starts[1:])
            sr.append(np.full(len(run._bin_vals), i, np.int32))
            off += len(run.z)
        self.n_runs = len(runs)
        self.z = (np.concatenate(zs) if zs
                  else np.empty(0, np.int64))
        self.pos = (np.concatenate(ps) if ps
                    else np.empty(0, np.int32))
        seg_bin = (np.concatenate(sb) if sb
                   else np.empty(0, np.int32))
        seg_lo = (np.concatenate(sl) if sl
                  else np.empty(0, np.int64))
        seg_hi = (np.concatenate(sh) if sh
                  else np.empty(0, np.int64))
        seg_run = (np.concatenate(sr) if sr
                   else np.empty(0, np.int32))
        order = np.argsort(seg_bin, kind="stable")
        self.seg_bin = seg_bin[order]
        self.seg_lo = seg_lo[order].astype(np.int64)
        self.seg_hi = seg_hi[order].astype(np.int64)
        self.seg_run = seg_run[order]
        # re-point the runs' columns at views of the stacked buffers so
        # the per-run copies free (the stack is now the owner)
        off = 0
        for run in runs:
            n = len(run.z)
            run.z = self.z[off:off + n]
            run.pos = self.pos[off:off + n]
            run.bins = None   # recoverable from the segment table
            off += n

    def density_partial(self, rb, rlo, rhi, sfc, ixy, tb, env,
                        width: int, height: int) -> np.ndarray:
        """Numpy DensityScan partial over every stacked host run — the
        host-tier contribution to the merged grid (same z-decoded CELL
        contract as the keys-tier device program)."""
        return self.density_partials(rb, rlo, rhi, sfc, ixy, tb, env,
                                     width, height).sum(axis=0)

    def density_partials(self, rb, rlo, rhi, sfc, ixy, tb, env,
                         width: int, height: int) -> np.ndarray:
        """PER-RUN DensityScan partials ``(n_runs, height, width)`` in
        the SAME single vectorized pass density_partial always took
        (two composite bisections total — flat in run count): each hit
        attributes to its owning run via the segment table, so every
        sealed host generation's immutable partial can cache
        individually without a per-run seek loop."""
        from ..curve.zorder import deinterleave3
        grids = np.zeros((self.n_runs, height, width), np.float64)
        idx, seg, _rid = self._expand(rb, rlo, rhi)
        if idx is None:
            return grids
        zc = self.z[idx]
        bc = self.seg_bin[seg].astype(np.int64)
        ix, iy, it = deinterleave3(zc.astype(np.uint64), xp=np)
        ix = ix.astype(np.int64)
        iy = iy.astype(np.int64)
        it = it.astype(np.int64)
        in_box = np.zeros(len(zc), bool)
        for b in np.atleast_2d(ixy):
            in_box |= ((ix >= b[0]) & (iy >= b[1])
                       & (ix <= b[2]) & (iy <= b[3]))
        ok = (in_box
              & ((bc > tb[0]) | ((bc == tb[0]) & (it >= tb[1])))
              & ((bc < tb[2]) | ((bc == tb[2]) & (it <= tb[3]))))
        if not ok.any():
            return grids
        xd = sfc.lon.denormalize(ix[ok], xp=np)
        yd = sfc.lat.denormalize(iy[ok], xp=np)
        gx = np.clip(((xd - env[0])
                      / max(env[2] - env[0], 1e-12) * width)
                     .astype(np.int64), 0, width - 1)
        gy = np.clip(((yd - env[1])
                      / max(env[3] - env[1], 1e-12) * height)
                     .astype(np.int64), 0, height - 1)
        np.add.at(grids, (self.seg_run[seg[ok]], gy, gx), 1.0)
        return grids

    def _expand(self, rb, rlo, rhi):
        """(flat z indices, owning segment, owning range) for a range
        batch — the shared expansion behind candidates() and
        density_partial().  Each range matches the [a, b) span of
        same-bin segments (one segment per run containing the bin);
        two composite bisections serve every pair."""
        if not len(self.z) or not len(rb):
            return None, None, None
        a = np.searchsorted(self.seg_bin, rb, side="left")
        b = np.searchsorted(self.seg_bin, rb, side="right")
        counts = np.maximum(b - a, 0)
        cum = np.cumsum(counts)
        total = int(cum[-1]) if len(cum) else 0
        if total == 0:
            return None, None, None
        j = np.arange(total)
        rid = np.searchsorted(cum, j, side="right")
        prev = np.where(rid > 0, cum[rid - 1], 0)
        seg = a[rid] + (j - prev)
        starts = _bisect_segments(self.z, rlo[rid], self.seg_lo[seg],
                                  self.seg_hi[seg], side="left")
        ends = _bisect_segments(self.z, rhi[rid], self.seg_lo[seg],
                                self.seg_hi[seg], side="right")
        cnt2 = np.maximum(ends - starts, 0)
        cum2 = np.cumsum(cnt2)
        tot2 = int(cum2[-1]) if len(cum2) else 0
        if tot2 == 0:
            return None, None, None
        k = np.arange(tot2)
        pid = np.searchsorted(cum2, k, side="right")
        prev2 = np.where(pid > 0, cum2[pid - 1], 0)
        return starts[pid] + (k - prev2), seg[pid], rid[pid]

    def candidates(self, rb, rlo, rhi, rqid, pos_bits: int) -> np.ndarray:
        """Coded candidate positions ``qid << pos_bits | pos`` across
        every stacked run for a padded range batch."""
        idx, _seg, rid = self._expand(rb, rlo, rhi)
        if idx is None:
            return np.empty(0, np.int64)
        return ((rqid[rid].astype(np.int64) << pos_bits)
                | self.pos[idx].astype(np.int64))


class _Generation:
    """One sorted key run.  ``tier`` ∈ {"full", "keys", "host"} (module
    doc); ``base`` is the global row id of its first row — generations
    cover contiguous global row ranges, so a ``full`` generation's
    payload is indexed by ``pos - base`` (append order).  ``gen_id`` is
    a store-lifetime-unique identity assigned by the owning index —
    compaction mints a FRESH id for each merged run, which is what
    keys (and therefore invalidates) the sealed-generation density
    partial cache."""

    __slots__ = ("bins", "z", "pos", "x", "y", "t", "n", "base", "tier",
                 "run", "gen_id")

    @classmethod
    def merged_keys(cls, bins, z, pos, n: int, base: int
                    ) -> "_Generation":
        """A compacted ``keys``-tier run from already-merged device
        columns (length == n: zero sentinel padding)."""
        gen = cls.__new__(cls)
        gen.bins, gen.z, gen.pos = bins, z, pos
        gen.x = gen.y = gen.t = None
        gen.n = int(n)
        gen.base = int(base)
        gen.tier = "keys"
        gen.run = None
        gen.gen_id = -1
        return gen

    @classmethod
    def merged_host(cls, run: HostRun, base: int) -> "_Generation":
        """A compacted ``host``-tier run from an already-merged
        :class:`HostRun`."""
        gen = cls.__new__(cls)
        gen.bins = gen.z = gen.pos = None
        gen.x = gen.y = gen.t = None
        gen.n = len(run)
        gen.base = int(base)
        gen.tier = "host"
        gen.run = run
        gen.gen_id = -1
        return gen

    def __init__(self, capacity: int, base: int, tier: str):
        self.bins = jnp.full((capacity,), _SENTINEL_BIN, jnp.int32)
        self.z = jnp.full((capacity,), _SENTINEL_Z, jnp.int64)
        self.pos = jnp.full((capacity,), -1, jnp.int32)
        if tier == "full":
            self.x = jnp.zeros((capacity,), jnp.float64)
            self.y = jnp.zeros((capacity,), jnp.float64)
            self.t = jnp.zeros((capacity,), jnp.int64)
        else:
            self.x = self.y = self.t = None
        self.n = 0
        self.base = base
        self.tier = tier
        self.run: HostRun | None = None
        self.gen_id = -1   # assigned by the owning index

    @property
    def capacity(self) -> int:
        return int(self.z.shape[0])

    def device_bytes(self) -> int:
        if self.tier == "host":
            return 0
        per = FULL_BYTES if self.tier == "full" else KEYS_BYTES
        return self.capacity * per

    def drop_payload(self) -> None:
        """full → keys: free the device payload copy (the host payload
        remains the source of truth for the exact mask)."""
        if self.tier == "full":
            self.x = self.y = self.t = None
            self.tier = "keys"

    def spill_to_host(self) -> None:
        """keys → host: fetch the sorted key run into host RAM as a
        :class:`HostRun`, freeing the HBM."""
        self.drop_payload()
        if self.tier != "keys":
            return
        bins = np.asarray(self.bins)
        z = np.asarray(self.z)
        pos = np.asarray(self.pos)
        # valid rows only: the sentinel padding sorts to the tail
        self.run = HostRun(bins[:self.n], z[:self.n], pos[:self.n])
        self.bins = self.z = self.pos = None
        self.tier = "host"


class LeanZ3Index:
    """Tiered generational keys-on-device Z3 index (see module doc)."""

    #: ``(schema, index_key)`` for access-temperature attribution
    #: (obs/heat) — stamped by the datastore; directly-built indexes
    #: record under a class-name fallback scope
    heat_scope: tuple | None = None

    #: slots per generation.  Each append re-sorts its generation, so
    #: generation size trades sort cost per slice against run count per
    #: query: slice-sized generations (the scale-proof setting) sort
    #: each slice exactly once — the LSM run-per-flush shape — while
    #: larger generations amortize query seeks.
    GENERATION_SLOTS = 1 << 24
    DEFAULT_CAPACITY = 1 << 15
    #: slot budget for a batched (G × capacity) candidate buffer; beyond
    #: it queries fall back to per-generation dispatches sized by each
    #: generation's own total
    BATCH_SCAN_BUDGET = 1 << 26
    #: default HBM budget for the key/payload residency (v5e usable
    #: 15.75 GiB minus scan/transfer slack; docs/scale.md)
    HBM_BUDGET_BYTES = int(13.5 * 2**30)
    #: size-tiered compaction trigger: merge when ≥ F sealed runs share
    #: a tier AND size class (the LSM merge policy the reference's
    #: key-value backends run server-side).  This class default serves
    #: EXPLICIT compact() calls; pass ``compaction_factor=F`` to the
    #: constructor to also run the trigger OPPORTUNISTICALLY after
    #: appends/demotions (bounded: one merge group per append).
    COMPACTION_FACTOR = 4
    #: distinct density grid/query specs whose per-generation partials
    #: are retained (LRU); each spec holds ≤ one (height, width) f64
    #: grid per sealed generation
    DENSITY_CACHE_SPECS = 4
    #: host-RAM ceiling for cached partials across all specs — large
    #: grids × many generations must not silently eat the host (the
    #: check runs at spec lookup, so one call may overshoot before the
    #: oldest specs evict)
    DENSITY_CACHE_MAX_BYTES = 512 * 2**20
    #: stat-sketch partial cache bounds (cell-count folds are small:
    #: time-bins × 2^bits int64 per sealed generation)
    SKETCH_CACHE_SPECS = 8
    SKETCH_CACHE_MAX_BYTES = 64 * 2**20
    #: density-pyramid cache spec bound (ISSUE 18): one spec per base
    #: resolution — two lets a live base-resolution retune keep serving
    #: off the old stack while the new one builds behind.  The byte
    #: ceiling comes from ``geomesa.density.pyramid.cache.bytes``.
    PYRAMID_CACHE_SPECS = 2

    def __init__(self, period: TimePeriod | str = TimePeriod.WEEK,
                 version: int = Z3_INDEX_VERSION,
                 generation_slots: int | None = None,
                 hbm_budget_bytes: int | None = None,
                 payload_on_device: bool = True,
                 compaction_factor: int | None = None):
        self.period = TimePeriod.parse(period)
        self.version = version
        self.sfc = z3_sfc_for_version(self.period, version)
        self.generation_slots = generation_slots or self.GENERATION_SLOTS
        self.hbm_budget_bytes = hbm_budget_bytes or self.HBM_BUDGET_BYTES
        #: whether NEW generations carry a device payload for the fused
        #: exact mask (they demote automatically under budget pressure)
        self.payload_on_device = payload_on_device
        self.generations: list[_Generation] = []
        #: host payload slices (x, y, dtg) in append order; finalized
        #: into flat arrays lazily for the exact re-check.  A store
        #: embedding this index supplies ``payload_provider`` instead
        #: (one host copy, owned by the store).
        self._payload: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        self._flat: tuple | None = None
        self.payload_provider = None
        self._n_rows = 0
        self.t_min_ms: int | None = None
        self.t_max_ms: int | None = None
        #: device program dispatches issued (tests pin dispatch counts;
        #: the tunnel RTT makes every dispatch ~100ms)
        self.dispatch_count = 0
        #: per-instance bucket-padding sentinel columns, keyed tier
        #: (see _make_sentinel_cols)
        self._sentinels: dict = {}
        #: stacked host-tier runs (built lazily on first query after a
        #: spill; seek cost flat in run count — see HostStack)
        self._host_stack: HostStack | None = None
        #: opportunistic size-tiered compaction factor (0 = off; the
        #: explicit compact() maintenance call works either way)
        self.compaction_factor = int(compaction_factor or 0)
        #: merge groups folded so far (observability; bench stanza)
        self.compactions = 0
        #: sealed-generation density partials: spec → {gen_id: grid}.
        #: A sealed (demoted keys/host) generation's contribution to a
        #: given (boxes, window, env, grid) spec is IMMUTABLE, so warm
        #: repeat density calls sum cached grids and re-scan only the
        #: live generation (+ full-tier generations, whose value-exact
        #: edge cells the cache must not coarsen).  The LRU + byte
        #: ceiling + compaction-invalidation policy is the shared
        #: :class:`~geomesa_tpu.index.partial_cache.PartialCache`.
        from .partial_cache import PartialCache
        self._density_cache = PartialCache(self.DENSITY_CACHE_SPECS,
                                           self.DENSITY_CACHE_MAX_BYTES)
        #: sealed-generation stat-sketch partials (ISSUE 3): the same
        #: policy over the z3 cell-count folds Z3Histogram pushes down
        self._sketch_cache = PartialCache(self.SKETCH_CACHE_SPECS,
                                          self.SKETCH_CACHE_MAX_BYTES)
        #: sealed-generation density pyramids (ISSUE 18): the same
        #: policy over whole-world multi-resolution grid stacks —
        #: spec is ``("pyramid", base)``, so rebuilds at a new base
        #: resolution coexist until the LRU retires the old one
        from ..config import DensityProperties
        self._pyramid_cache = PartialCache(
            self.PYRAMID_CACHE_SPECS,
            DensityProperties.PYRAMID_CACHE_BYTES.to_int())
        #: generation-lifecycle listeners (index/lsm
        #: notify_generation_event): ``listener(kind, gen_ids)`` fired
        #: on seal/merge — the build-behind hook pyramid jobs ride
        self.generation_listeners: list = []
        #: store-lifetime generation id source (see _Generation.gen_id)
        self._gen_counter = 0

    def _sentinel_cols(self, tier: str):
        if tier not in self._sentinels:
            self._sentinels[tier] = _make_sentinel_cols(
                tier, self.generation_slots)
        return self._sentinels[tier]

    def __len__(self) -> int:
        return self._n_rows

    def block(self) -> None:
        """Wait for every in-flight append (dispatches are async — honest
        ingest timing must block on the last generation's columns)."""
        for gen in reversed(self.generations):
            if gen.tier != "host":
                jax.block_until_ready(gen.pos)
                break

    def device_bytes(self) -> int:
        """Resident HBM of the key/payload columns (the budget the scale
        proof asserts against docs/scale.md)."""
        return sum(g.device_bytes() for g in self.generations)

    def host_key_bytes(self) -> int:
        """Host RAM held by spilled (``host``-tier) key runs."""
        return sum(g.n * KEYS_BYTES for g in self.generations
                   if g.tier == "host")

    def tier_counts(self) -> dict:
        out = {"full": 0, "keys": 0, "host": 0}
        for g in self.generations:
            out[g.tier] += 1
        return out

    def sentinel_bytes(self) -> int:
        """HBM charged for the lazily-allocated bucket-padding sentinel
        buffers (the budget's _budget_after_sentinels counterpart, but
        for buffers that EXIST rather than will exist)."""
        return sum(self.generation_slots
                   * (FULL_BYTES if tier == "full" else KEYS_BYTES)
                   for tier in self._sentinels)

    def storage_stats(self) -> dict:
        """Live byte accounting for the storage report (obs/resource,
        ISSUE 9): where this index's bytes sit — device key/payload
        runs vs host-spilled runs, per generation, plus the sealed-
        partial caches — from the SAME per-slot constants the HBM
        budget uses, so the report reconciling these against actual
        array nbytes is exactly a budget-accounting audit."""
        gens = [{"gen_id": g.gen_id, "tier": g.tier, "rows": int(g.n),
                 "capacity": 0 if g.tier == "host" else g.capacity,
                 "device_bytes": g.device_bytes(),
                 "host_bytes": (g.n * KEYS_BYTES
                                if g.tier == "host" else 0)}
                for g in self.generations]
        return {"kind": type(self).__name__, "rows": len(self),
                "tiers": self.tier_counts(),
                "device_bytes": self.device_bytes(),
                "host_bytes": self.host_key_bytes(),
                "sentinel_bytes": self.sentinel_bytes(),
                "hbm_budget_bytes": self.hbm_budget_bytes,
                "generations": gens,
                "caches": {"density": self._density_cache.stats(),
                           "sketch": self._sketch_cache.stats(),
                           "pyramid": self._pyramid_cache.stats()},
                "dispatches": self.dispatch_count}

    # -- write path -------------------------------------------------------
    def _new_generation(self, base: int) -> _Generation:
        tier = "full" if self.payload_on_device else "keys"
        if tier == "full":
            # would the payload survive rebalance?  The LIVE generation's
            # payload is RESERVED by the demotion policy (round-4 VERDICT
            # #5): older payloads drop first and older key runs spill to
            # host before it is touched, so the payload is doomed only if
            # the live full generation ALONE (plus the sentinel padding
            # buffers) busts the budget — don't allocate slots × 24 B of
            # HBM (and a transient spike) that _rebalance frees moments
            # later.
            floor = self.generation_slots * (FULL_BYTES
                                             + KEYS_BYTES + FULL_BYTES)
            if floor > self.hbm_budget_bytes:
                tier = "keys"
        gen = _Generation(self.generation_slots, base=base, tier=tier)
        gen.gen_id = self._next_gen_id()
        self.generations.append(gen)
        self._rebalance()
        return self.generations[-1]

    def _next_gen_id(self) -> int:
        self._gen_counter += 1
        return self._gen_counter

    def _budget_after_sentinels(self) -> int:
        """Effective budget: hbm_budget_bytes minus the shared full-size
        sentinel padding buffers queries will lazily allocate — a keys
        sentinel always, a full one only while full-tier generations
        exist (recomputed as tiers demote)."""
        per = self.generation_slots * KEYS_BYTES
        if any(g.tier == "full" for g in self.generations):
            per += self.generation_slots * FULL_BYTES
        return self.hbm_budget_bytes - per

    def _fits(self) -> bool:
        if not any(g.tier == "full" for g in self.generations):
            # the budget stops charging the full-tier sentinel once no
            # full generation exists — free the cached one so the
            # charge matches resident HBM
            self._sentinels.pop("full", None)
        return self.device_bytes() <= self._budget_after_sentinels()

    def _spill(self, gen: _Generation) -> None:
        # injected BEFORE the transfer: a faulted spill leaves the
        # generation on device, fully queryable (resilience chaos tests)
        from ..resilience import fault_point
        fault_point("host.spill")
        # the spill IS a blocking device→host transfer — a device span
        # so ingest traces carry its block-until-ready ms (ISSUE 12)
        with device_span("write.spill", gen_id=gen.gen_id,
                         rows=int(gen.n)):
            obs_count(WRITE_SPILLS)
            gen.spill_to_host()
        self._host_stack = None   # restacked lazily on the next query

    def _rebalance(self) -> None:
        """Demote oldest-first until the device residency (key/payload
        columns PLUS the shared sentinel padding buffers queries will
        allocate) fits the HBM budget: payload drops first (full →
        keys), then key runs spill to host RAM (keys → host).  The
        ACTIVE generation's keys never spill — appends sort there —
        and its PAYLOAD is reserved (round-4 VERDICT #5): the newest
        (hottest) generation keeps the fused device-exact path at any
        store size, older key runs spilling to host RAM to make room;
        it drops only as the last step before the budget is simply too
        small for one live generation."""
        if self._fits():
            return
        for gen in self.generations[:-1]:
            if gen.tier == "full":
                gen.drop_payload()
                if self._fits():
                    return
        for gen in self.generations[:-1]:
            if gen.tier == "keys":
                self._spill(gen)
                if self._fits():
                    return
        live = self.generations[-1] if self.generations else None
        if live is not None and live.tier == "full":
            # last resort: the budget cannot hold even one full live
            # generation — appends continue through the keys program
            live.drop_payload()
            if self._fits():
                return
        raise MemoryError(
            f"active generation ({self.generation_slots} slots) "
            f"exceeds hbm_budget_bytes={self.hbm_budget_bytes} "
            "minus the sentinel-padding overhead")

    def append(self, x, y, dtg_ms) -> "LeanZ3Index":
        """Stream one slice in: host payload retained by reference, keys
        encoded + merged into the current generation on device (rolling
        to a fresh generation when full)."""
        if self._n_rows + len(x) > np.iinfo(np.int32).max:
            raise ValueError("LeanZ3Index positions are int32: "
                             "2,147M rows max per index/shard")
        # injected at ENTRY, before any state mutates: a faulted append
        # loses the whole slice atomically — rows are either fully
        # indexed or absent, never half-ingested (resilience chaos tests)
        from ..resilience import fault_point
        fault_point("ingest.append")
        x = np.ascontiguousarray(x, dtype=np.float64)
        y = np.ascontiguousarray(y, dtype=np.float64)
        dtg_ms = np.ascontiguousarray(dtg_ms, dtype=np.int64)
        m_total = len(x)
        if m_total == 0:
            return self
        if self.payload_provider is None:
            self._payload.append((x, y, dtg_ms))
            self._flat = None
        host_bins, host_offs = to_binned_time(dtg_ms, self.period)
        host_bins = host_bins.astype(np.int32)
        host_offs = host_offs.astype(np.float64)
        done = 0
        while done < m_total:
            gen = (self.generations[-1] if self.generations else None)
            if gen is None or gen.tier == "host" or gen.n >= gen.capacity:
                # base = global row id of the generation's first row —
                # mid-append rollovers account for rows already consumed
                if gen is not None and gen.tier != "host":
                    # the live generation SEALS on rollover; the span
                    # covers the rebalance (demote/spill) it triggers
                    sealed_id = gen.gen_id
                    with obs_span("write.seal", gen_id=gen.gen_id,
                                  tier=gen.tier, rows=int(gen.n)):
                        obs_count(WRITE_SEALS)
                        gen = self._new_generation(self._n_rows + done)
                    # AFTER the seal span: listeners schedule optional
                    # build-behind work (density pyramids) and must
                    # never break or slow the append itself
                    from .lsm import notify_generation_event
                    notify_generation_event(self, "seal", [sealed_id])
                else:
                    gen = self._new_generation(self._n_rows + done)
            room = gen.capacity - gen.n
            take = min(room, m_total - done)
            m_pad = min(gather_capacity(take, minimum=8), room)
            sl = slice(done, done + take)
            pad = m_pad - take
            self.dispatch_count += 1
            if gen.tier == "full":
                (gen.bins, gen.z, gen.pos, gen.x, gen.y,
                 gen.t) = _lean_append_full(
                    self.sfc, gen.bins, gen.z, gen.pos,
                    gen.x, gen.y, gen.t,
                    jnp.int32(gen.n), jnp.int32(gen.base),
                    jnp.asarray(np.pad(x[sl], (0, pad))),
                    jnp.asarray(np.pad(y[sl], (0, pad))),
                    jnp.asarray(np.pad(host_offs[sl], (0, pad))),
                    jnp.asarray(np.pad(host_bins[sl], (0, pad))),
                    jnp.asarray(np.pad(dtg_ms[sl], (0, pad))),
                    jnp.int32(take))
            else:
                gen.bins, gen.z, gen.pos = _lean_append(
                    self.sfc, gen.bins, gen.z, gen.pos,
                    jnp.int32(gen.n), jnp.int32(gen.base),
                    jnp.asarray(np.pad(x[sl], (0, pad))),
                    jnp.asarray(np.pad(y[sl], (0, pad))),
                    jnp.asarray(np.pad(host_offs[sl], (0, pad))),
                    jnp.asarray(np.pad(host_bins[sl], (0, pad))),
                    jnp.int32(take))
            gen.n += take
            done += take
        self._n_rows += m_total
        t_min, t_max = int(dtg_ms.min()), int(dtg_ms.max())
        self.t_min_ms = (t_min if self.t_min_ms is None
                         else min(self.t_min_ms, t_min))
        self.t_max_ms = (t_max if self.t_max_ms is None
                         else max(self.t_max_ms, t_max))
        if self.compaction_factor:
            # opportunistic trigger after append/demotion: bounded to
            # ONE merge group so ingest latency stays O(generation)
            self.compact(factor=self.compaction_factor, max_groups=1)
        return self

    # -- compaction (LSM maintenance) -------------------------------------
    def _sealed(self) -> list[_Generation]:
        """Generations appends can no longer touch — everything but the
        live (last) one.  Only sealed runs merge; only sealed keys/host
        runs cache density partials."""
        return self.generations[:-1]

    def _compaction_groups(self, factor: int) -> list[list[_Generation]]:
        from .lsm import plan_size_tiered
        return plan_size_tiered(self._sealed(), ("keys", "host"),
                                lambda g: g.n, factor)

    def _merge_group(self, group: list[_Generation]) -> None:
        """Fold one same-tier group into a single sorted run placed at
        the group's oldest position (list order is demotion age).  The
        merged run gets a FRESH gen_id; the source runs' device slots /
        host buffers free with their python references and their cached
        density partials drop (stale grids must never double-count)."""
        from .lsm import merged_capacity, replace_group
        base = min(g.base for g in group)
        total = int(sum(g.n for g in group))
        if group[0].tier == "keys":
            cols: list = []
            for g in group:
                cols += [g.bins, g.z, g.pos]
            out_cap = merged_capacity(
                total, sum(g.capacity for g in group), gather_capacity)
            self.dispatch_count += 1
            bins, z, pos = _lean_merge_keys(*cols, out_cap=out_cap)
            merged = _Generation.merged_keys(bins, z, pos, n=total,
                                             base=base)
        else:
            merged = _Generation.merged_host(
                merge_host_runs([g.run for g in group]), base=base)
            self._host_stack = None   # restacked lazily
        merged.gen_id = self._next_gen_id()
        dead_ids = [g.gen_id for g in group]
        # the merged run inherits its sources' access temperature —
        # hot data must not read cold because maintenance renamed it.
        # Credited BEFORE the swap: a concurrent heat report prunes
        # tracker entries absent from its placement snapshot, and the
        # freshly-stamped merged entry rides the prune grace window
        # while dead ids may be long-cold
        merge_index_generations(self, dead_ids, merged.gen_id)
        # pyramid inheritance mirrors the heat inheritance above: the
        # merged run's pyramid is the exact elementwise SUM of its
        # sources' (same immutable keys, renamed), computed BEFORE the
        # stale parents drop — a merge must not send tile serving back
        # to the scan path when its inputs were already built
        self._inherit_pyramids(dead_ids, merged.gen_id)
        self.generations = replace_group(self.generations, group,
                                         merged)
        self._drop_cached_partials(dead_ids)
        self.compactions += 1
        _metrics.counter(LEAN_COMPACTION_MERGES).inc()
        _metrics.counter(LEAN_COMPACTION_ROWS).inc(total)
        from .lsm import notify_generation_event
        notify_generation_event(self, "merge", [merged.gen_id])

    def compact(self, budget_ms: float | None = None,
                factor: int | None = None,
                max_groups: int | None = None) -> dict:
        """Incremental size-tiered K-way merge compaction — the role
        the reference delegates to its key-value backend's periodic
        compaction (Accumulo/HBase major compaction), run here as an
        explicit maintenance job or opportunistically after appends.

        Merges one group at a time and re-plans (index/lsm.py), so a
        ``budget_ms`` deadline or ``max_groups`` cap interrupts cleanly
        BETWEEN merges and the next call resumes where this one
        stopped; each call makes progress (≥ 1 group when any is
        eligible) even at ``budget_ms=0``.  Query results are identical
        at every intermediate state — a merge only re-sorts the union
        of already-sealed runs.

        Returns ``{"merged_groups", "generations", "tiers"}``."""
        from .lsm import compact_incremental
        f = int(factor or self.compaction_factor
                or self.COMPACTION_FACTOR)
        merged = compact_incremental(
            lambda: self._compaction_groups(f), self._merge_group,
            budget_ms=budget_ms, max_groups=max_groups)
        if merged:
            # merged runs never out-size their sources — residency only
            # shrinks, but re-check so the budget invariant is explicit
            self._rebalance()
        return {"merged_groups": merged,
                "generations": len(self.generations),
                "tiers": self.tier_counts()}

    def _drop_cached_partials(self, gen_ids: list) -> None:
        self._density_cache.drop_generations(gen_ids)
        self._sketch_cache.drop_generations(gen_ids)
        self._pyramid_cache.drop_generations(gen_ids)

    def _inherit_pyramids(self, dead_ids: list, new_gen_id: int) -> None:
        """Compaction inheritance: when EVERY merged-away parent has a
        pyramid under a spec (same level set), the merged run gets
        their elementwise sum — bit-exact, because each parent level is
        the parent's exact count grid and the merged run is exactly the
        union of the parents' rows.  Any missing parent leaves the
        merged run pyramid-less (the next build fills it)."""
        from .pyramid import DensityPyramid
        for _spec, cache in self._pyramid_cache.items():
            parents = [cache.get(gid) for gid in dead_ids]
            if all(p is not None for p in parents):
                merged = DensityPyramid.sum(parents)
                if merged is not None:
                    self._pyramid_cache.add(cache, new_gen_id, merged)

    def _pyramid_level(self, gen_id: int, width: int):
        """The cached (width, width) pyramid grid for one sealed
        generation, or None — serving never waits on a build."""
        for _spec, cache in self._pyramid_cache.items():
            pyr = cache.get(gen_id)
            if pyr is not None:
                lvl = pyr.level(width)
                if lvl is not None:
                    return lvl
        return None

    def _cache_partial(self, cache: dict, gen_id: int, part) -> None:
        """Store one sealed-generation density partial (the shared
        PartialCache byte-ceiling policy)."""
        self._density_cache.add(cache, gen_id, part)

    def _density_spec_cache(self, spec) -> dict:
        """The per-generation partial dict for one density spec (LRU +
        byte ceiling — index/partial_cache)."""
        return self._density_cache.spec_cache(spec)

    # -- payload ----------------------------------------------------------
    def _payload_flat(self):
        if self.payload_provider is not None:
            return self.payload_provider()
        if self._flat is None:
            xs, ys, ts = zip(*self._payload) if self._payload else ((), (), ())
            self._flat = (np.concatenate(xs) if xs else np.empty(0),
                          np.concatenate(ys) if ys else np.empty(0),
                          np.concatenate(ts) if ts else np.empty(0, np.int64))
            # the per-slice references are no longer needed — drop them
            # so host RAM holds ONE copy of the payload
            self._payload = [tuple(self._flat)]
        return self._flat

    def _clamp_time(self, t_lo_ms, t_hi_ms) -> tuple[int, int]:
        t_lo_ms = self.t_min_ms if t_lo_ms is None else int(t_lo_ms)
        t_hi_ms = self.t_max_ms if t_hi_ms is None else int(t_hi_ms)
        if self.t_min_ms is not None:
            t_lo_ms = max(t_lo_ms, self.t_min_ms)
        if self.t_max_ms is not None:
            t_hi_ms = min(t_hi_ms, self.t_max_ms)
        return t_lo_ms, t_hi_ms

    # -- query path -------------------------------------------------------
    def query(self, boxes, t_lo_ms, t_hi_ms,
              max_ranges: int = 2000, progress=None) -> np.ndarray:
        """Exact original-order positions for one bbox(es)+time window."""
        return self.query_many([(boxes, t_lo_ms, t_hi_ms)],
                               max_ranges=max_ranges,
                               progress=progress)[0]

    def query_many(self, windows, max_ranges: int = 2000,
                   progress=None) -> list[np.ndarray]:
        """Batched multi-window scan: every window × every generation in
        a FIXED number of dispatches (totals probe + one scan per
        populated device tier), the BatchScanner-over-many-range-sets
        pattern the analytics processes build on (round-4 VERDICT #5).
        Returns one sorted exact-position array per window."""
        n_q = len(windows)
        if n_q == 0 or self._n_rows == 0:
            return [np.empty(0, dtype=np.int64) for _ in range(n_q)]
        # host planning per window; ranges concatenate with owning qid
        rbin, rzlo, rzhi, rqid = [], [], [], []
        w_boxes: list = []
        qtlo = np.empty(n_q, dtype=np.int64)
        qthi = np.empty(n_q, dtype=np.int64)
        from ..resilience import check_cancel
        with obs_span("query.decompose", windows=n_q) as dsp:
            for q, (bxs, lo, hi) in enumerate(windows):
                # yield point between range decompositions: a window
                # not yet planned scans nothing (partial mode), so the
                # planned windows' results stay exact
                if check_cancel("query.decompose"):
                    break
                lo, hi = self._clamp_time(lo, hi)
                qtlo[q], qthi[q] = lo, hi
                bxs = np.atleast_2d(np.asarray(bxs, dtype=np.float64))
                w_boxes.append(bxs)
                # per-BIN range budget: plan_z3_query splits its target
                # across the interval's bins, so open/long intervals
                # would starve each bin into hugely overcovering ranges
                # (895k candidates for 23 hits measured) — scale by the
                # bin count and let the hard cap bound plan cost
                budget = min(max_ranges * _bins_spanned(lo, hi,
                                                        self.period),
                             _MAX_RANGES_PER_WINDOW)
                plan = plan_z3_query(bxs, lo, hi, self.period, budget,
                                     sfc=self.sfc)
                if plan.num_ranges == 0:
                    continue
                rbin.append(plan.rbin)
                rzlo.append(plan.rzlo)
                rzhi.append(plan.rzhi)
                rqid.append(np.full(plan.num_ranges, q, dtype=np.int32))
            dsp.set_attr("ranges", int(sum(len(r) for r in rbin)))
        if not rbin:
            return [np.empty(0, dtype=np.int64) for _ in range(n_q)]
        ra = pad_ranges(
            {"rbin": np.concatenate(rbin), "rzlo": np.concatenate(rzlo),
             "rzhi": np.concatenate(rzhi), "rqid": np.concatenate(rqid)},
            pad_pow2(sum(len(r) for r in rbin)))
        rb = jnp.asarray(ra["rbin"])
        rlo = jnp.asarray(ra["rzlo"])
        rhi = jnp.asarray(ra["rzhi"])
        rq = jnp.asarray(ra["rqid"])
        pos_bits = coded_pos_bits(self._n_rows, n_q)

        full_gens = [g for g in self.generations if g.tier == "full"]
        keys_gens = [g for g in self.generations if g.tier == "keys"]
        host_gens = [g for g in self.generations if g.tier == "host"]

        # ONE totals probe across every device generation (full + keys)
        dev_gens = full_gens + keys_gens
        totals = np.empty(0)
        if dev_gens:
            padded = self._pad_bucket(dev_gens)
            count_cols: list = []
            for gen in padded:
                cols = (self._sentinel_cols("keys")
                        if gen is None else (gen.bins, gen.z))
                count_cols += [cols[0], cols[1]]
            if progress is not None:
                progress(f"    probing {len(dev_gens)} generations")
            self.dispatch_count += 1
            n_dev = int(sum(g.n for g in dev_gens))
            with device_span("query.scan.device", stage="probe",
                             runs=len(dev_gens), rows=n_dev,
                             bytes=n_dev * KEYS_BYTES):
                totals = np.asarray(_lean_count_multi(rb, rlo, rhi,
                                                      *count_cols))
        # adaptive-replan probe point (ISSUE 19): the device totals are
        # known BEFORE any gather, so aborting here discards nothing
        from ..planning.adaptive import check_replan
        dev_total = int(totals.sum()) if dev_gens else 0
        check_replan("query.scan.probe", dev_total)
        coded_parts: list = []
        # keys_cand also collects DEGRADED candidates from either
        # device tier (ISSUE 16): the recheck below restores exactness
        keys_cand: list = []
        # full tier: fused exact mask on device — survivors only
        if full_gens and not check_cancel("query.scan.full"):
            t_full = totals[:len(full_gens)]
            if int(t_full.sum()):
                boxes_c, bqid_c = self._concat_boxes(w_boxes)
                coded_parts += self._scan_tier(
                    full_gens, t_full, rb, rlo, rhi, rq, pos_bits,
                    exact_args=(jnp.asarray(boxes_c),
                                jnp.asarray(bqid_c),
                                jnp.asarray(qtlo), jnp.asarray(qthi)),
                    ra=ra, degraded_out=keys_cand)
        # keys tier: candidate gather — host exact mask below
        if keys_gens and not check_cancel("query.scan.keys"):
            t_keys = totals[len(full_gens):len(dev_gens)]
            if int(t_keys.sum()):
                keys_cand += self._scan_tier(
                    keys_gens, t_keys, rb, rlo, rhi, rq, pos_bits,
                    exact_args=None, ra=ra, degraded_out=keys_cand)
        # host tier: stacked numpy seeks — flat in run count, and no
        # dispatch at all (round-4 VERDICT #9)
        host_cand_n = 0
        if host_gens and not check_cancel("query.scan.host"):
            with obs_span("query.scan.host", stage="seek",
                          runs=len(host_gens)):
                if self._host_stack is None:
                    self._host_stack = HostStack(
                        [g.run for g in host_gens])
                coded = self._host_stack.candidates(
                    ra["rbin"], ra["rzlo"], ra["rzhi"], ra["rqid"],
                    pos_bits)
                host_cand_n = int(len(coded))
                if len(coded):
                    keys_cand.append(coded)
        if host_cand_n:
            # second probe point: host-tier candidates are counted
            # before the payload recheck, the expensive host step
            check_replan("query.scan.probe", dev_total + host_cand_n)
        if heat_enabled():
            # per-generation access temperature (obs/heat): device
            # generations attribute candidates exactly (the probe's
            # per-generation totals); the stacked host seek loses
            # per-run attribution, so host candidates split
            # proportionally to run size
            touches = [(g.gen_id, g.tier, int(g.n),
                        int(g.n) * (FULL_BYTES if g.tier == "full"
                                    else KEYS_BYTES),
                        int(totals[i]))
                       for i, g in enumerate(dev_gens)]
            n_host = sum(g.n for g in host_gens)
            touches += [(g.gen_id, "host", int(g.n),
                         int(g.n) * KEYS_BYTES,
                         int(round(host_cand_n * g.n / n_host)))
                        for g in host_gens]
            record_index_scan(self, touches)

        mask_bits = (np.int64(1) << pos_bits) - 1
        out = [np.empty(0, dtype=np.int64) for _ in range(n_q)]
        exact_hits = (np.concatenate(coded_parts) if coded_parts
                      else np.empty(0, np.int64))
        cand_hits = (np.concatenate(keys_cand) if keys_cand
                     else np.empty(0, np.int64))
        if len(cand_hits):
            # host exact mask on the payload (the client-side re-check
            # of keys/host-tier candidates) — its own scan.host stage
            # so the trace separates spill seeks from verification
            with obs_span("query.scan.host", stage="recheck",
                          candidates=int(len(cand_hits))):
                x, y, t = self._payload_flat()
                qids = (cand_hits >> pos_bits).astype(np.int64)
                cand = (cand_hits & mask_bits).astype(np.int64)
                cx, cy, ct = x[cand], y[cand], t[cand]
                keep = np.zeros(len(cand), dtype=bool)
                for q in range(n_q):
                    sel = qids == q
                    if not sel.any():
                        continue
                    in_box = np.zeros(int(sel.sum()), dtype=bool)
                    for b in w_boxes[q]:
                        in_box |= ((cx[sel] >= b[0]) & (cy[sel] >= b[1])
                                   & (cx[sel] <= b[2]) & (cy[sel] <= b[3]))
                    keep[sel] = (in_box & (ct[sel] >= qtlo[q])
                                 & (ct[sel] <= qthi[q]))
                cand_hits = cand_hits[keep]
        merged = np.concatenate([exact_hits, cand_hits])
        qids = (merged >> pos_bits).astype(np.int64)
        positions = (merged & mask_bits).astype(np.int64)
        for q in range(n_q):
            # unique: overlapping covering ranges can duplicate a row
            out[q] = np.unique(positions[qids == q])
        return out

    # -- result materialization (ISSUE 14) --------------------------------
    def gather_payload(self, positions: np.ndarray):
        """(x, y, t) columns for the given global row positions — the
        Arrow result path's column gather (arrow/stream.py).

        Rows living in a ``full``-tier generation gather ON DEVICE:
        one batched take per generation (:func:`_lean_gather_payload`
        over the payload columns the fused exact mask already keeps
        resident), so for the hot all-full store the geometry/time
        columns of a result never round-trip through the host column
        store at all.  Rows in ``keys``/``host``-tier generations
        gather from the host payload via one vectorized numpy take —
        the stacked-host-run half of the materialize contract.  Values
        are bit-identical to the host payload either way (the device
        copy was written from the same arrays), which is what makes
        the Arrow path byte-exact against the row-wise one."""
        positions = np.asarray(positions, dtype=np.int64)
        n = len(positions)
        if n == 0:
            return (np.empty(0, np.float64), np.empty(0, np.float64),
                    np.empty(0, np.int64))
        order = None
        sorted_pos = positions
        if n > 1 and not bool(np.all(positions[1:] >= positions[:-1])):
            # sorted segments per generation need sorted positions; a
            # sort-by query hands them in result order — gather sorted,
            # then scatter back through the inverse permutation
            order = np.argsort(positions, kind="stable")
            sorted_pos = positions[order]
        x = np.empty(n, np.float64)
        y = np.empty(n, np.float64)
        t = np.empty(n, np.int64)
        covered = np.zeros(n, dtype=bool)
        for gen in self.generations:
            if gen.tier != "full" or gen.n == 0:
                continue
            lo = int(np.searchsorted(sorted_pos, gen.base, side="left"))
            hi = int(np.searchsorted(sorted_pos, gen.base + gen.n,
                                     side="left"))
            if hi <= lo:
                continue
            m = hi - lo
            cap = gather_capacity(m, minimum=8)
            idx = np.zeros(cap, np.int32)
            idx[:m] = (sorted_pos[lo:hi] - gen.base).astype(np.int32)
            self.dispatch_count += 1
            with device_span("query.materialize", stage="gather",
                             runs=1, rows=m, bytes=m * PAYLOAD_BYTES):
                gx, gy, gt = _lean_gather_payload(jnp.asarray(idx),
                                                  gen.x, gen.y, gen.t)
                x[lo:hi] = np.asarray(gx)[:m]
                y[lo:hi] = np.asarray(gy)[:m]
                t[lo:hi] = np.asarray(gt)[:m]
            covered[lo:hi] = True
        if not covered.all():
            hx, hy, ht = self._payload_flat()
            rest = sorted_pos[~covered]
            x[~covered] = hx[rest]
            y[~covered] = hy[rest]
            t[~covered] = ht[rest]
        if order is not None:
            inv = np.empty(n, np.int64)
            inv[order] = np.arange(n)
            x, y, t = x[inv], y[inv], t[inv]
        return x, y, t

    # -- aggregation push-down (round-4 VERDICT #2) -----------------------
    def _plan_one(self, boxes, t_lo_ms, t_hi_ms, max_ranges: int):
        """Padded covering-range arrays for ONE window (the density /
        count scan shape)."""
        lo, hi = self._clamp_time(t_lo_ms, t_hi_ms)
        bxs = np.atleast_2d(np.asarray(boxes, dtype=np.float64))
        budget = min(max_ranges * _bins_spanned(lo, hi, self.period),
                     _MAX_RANGES_PER_WINDOW)
        plan = plan_z3_query(bxs, lo, hi, self.period, budget,
                             sfc=self.sfc)
        if plan.num_ranges == 0:
            return None
        ra = pad_ranges(
            {"rbin": plan.rbin, "rzlo": plan.rzlo, "rzhi": plan.rzhi},
            pad_pow2(plan.num_ranges))
        return ra, bxs, lo, hi

    def density(self, boxes, t_lo_ms, t_hi_ms, env,
                width: int = 256, height: int = 256,
                max_ranges: int = 2000) -> np.ndarray:
        """DensityScan push-down: the (height, width) heatmap of
        bbox+time hits accumulated NEXT TO THE KEYS — full-tier
        generations mask exactly on their device payload, keys-tier
        generations decode cell-accurate coordinates from the z key,
        host-tier runs contribute numpy partials; the grids merge as a
        sum.  Only grids cross the wire — a whole-extent heatmap over
        1B rows ships ``height*width`` floats, not a billion hits
        (round-4 VERDICT #2; DensityScan.scala:31-59 +
        AggregatingScan.scala:80-102)."""
        with obs_span("lean.density", grid=f"{width}x{height}",
                      generations=len(self.generations)):
            return self._density_scan(boxes, t_lo_ms, t_hi_ms, env,
                                      width, height, max_ranges)

    def _density_scan(self, boxes, t_lo_ms, t_hi_ms, env,
                      width: int, height: int,
                      max_ranges: int) -> np.ndarray:
        grid = np.zeros((height, width), np.float64)
        if self._n_rows == 0:
            return grid
        # whole-extent fast path: a covering box + the full time extent
        # needs no seeks at all — sweep every generation's z column
        lo_c, hi_c = self._clamp_time(t_lo_ms, t_hi_ms)
        bxs0 = np.atleast_2d(np.asarray(boxes, dtype=np.float64))
        covers = any(b[0] <= -180.0 and b[1] <= -90.0
                     and b[2] >= 180.0 and b[3] >= 90.0 for b in bxs0)
        if (covers and lo_c == self.t_min_ms and hi_c == self.t_max_ms):
            return self._density_sweep(env, width, height)
        planned = self._plan_one(boxes, t_lo_ms, t_hi_ms, max_ranges)
        if planned is None:
            return grid
        ra, bxs, lo, hi = planned
        rb = jnp.asarray(ra["rbin"])
        rlo = jnp.asarray(ra["rzlo"])
        rhi = jnp.asarray(ra["rzhi"])
        boxes_j = jnp.asarray(bxs)
        env_t = tuple(float(v) for v in env)
        env_j = jnp.asarray(np.asarray(env_t))
        # normalized-cell bounds for the decoded (keys/host) tiers:
        # cell-granular comparisons are exact for whole-extent scans and
        # cell-inclusive otherwise (see _lean_density_keys)
        b_lo, o_lo = to_binned_time(np.int64(max(0, lo)), self.period)
        b_hi, o_hi = to_binned_time(np.int64(max(0, hi)), self.period)
        tb = np.array([int(b_lo),
                       self.sfc.time.normalize_scalar(float(o_lo)),
                       int(b_hi),
                       self.sfc.time.normalize_scalar(float(o_hi))],
                      np.int64)
        ixy = np.stack([np.array(
            [self.sfc.lon.normalize_scalar(b[0]),
             self.sfc.lat.normalize_scalar(b[1]),
             self.sfc.lon.normalize_scalar(b[2]),
             self.sfc.lat.normalize_scalar(b[3])], np.int32)
            for b in bxs])
        live = self.generations[-1] if self.generations else None
        full_gens = [g for g in self.generations if g.tier == "full"]
        keys_gens = [g for g in self.generations if g.tier == "keys"]
        host_gens = [g for g in self.generations if g.tier == "host"]
        # sealed-generation partial cache: a demoted (keys/host)
        # generation's contribution to this exact (boxes, window, env,
        # grid) spec is IMMUTABLE — sum its cached grid and scan only
        # the rest.  Full-tier generations always re-scan: their fused
        # payload mask is value-exact at window edges and the cache
        # must not replace that with anything looser; the cached
        # keys/host partials are byte-identical to what their tier's
        # scan produces (cell-granular contract), so a warm call
        # returns exactly the cold call's grid.
        spec = ("scan", tuple(map(tuple, bxs.tolist())), int(lo),
                int(hi), env_t, width, height, int(max_ranges))
        cache = self._density_spec_cache(spec)
        # heat touches (obs/heat): density reads every generation —
        # match counts are unattributable (grids, not rows), so every
        # touch is a full-weight access; cache hits read zero bytes
        _ht: list | None = [] if heat_enabled() else None
        if _ht is not None:
            _ht += [(g.gen_id, "full", int(g.n),
                     int(g.n) * FULL_BYTES, None) for g in full_gens]
        keys_scan: list = []
        for g in keys_gens:
            part = cache.get(g.gen_id) if g is not live else None
            if part is None:
                keys_scan.append(g)
            else:
                obs_count(LEAN_DENSITY_CACHE_HITS)
                grid += part
            if _ht is not None:
                _ht.append((g.gen_id, g.tier, int(g.n),
                            0 if part is not None
                            else int(g.n) * KEYS_BYTES, None))
        dev_gens = full_gens + keys_scan
        totals = np.empty(0)
        if dev_gens:
            padded = self._pad_bucket(dev_gens)
            count_cols: list = []
            for gen in padded:
                cols = (self._sentinel_cols("keys")
                        if gen is None else (gen.bins, gen.z))
                count_cols += [cols[0], cols[1]]
            self.dispatch_count += 1
            with device_span("query.scan.device", stage="probe",
                             runs=len(dev_gens)):
                totals = np.asarray(_lean_count_multi(rb, rlo, rhi,
                                                      *count_cols))

        def _tier_groups(gens, tier_totals):
            cap = gather_capacity(int(tier_totals.max()),
                                  minimum=self.DEFAULT_CAPACITY)
            padded = self._pad_bucket(gens)
            if len(padded) * cap <= self.BATCH_SCAN_BUDGET:
                return [padded], [cap]
            return ([[g] for g, t in zip(gens, tier_totals) if int(t)],
                    [gather_capacity(int(t),
                                     minimum=self.DEFAULT_CAPACITY)
                     for t in tier_totals if int(t)])

        if full_gens and int(totals[:len(full_gens)].sum()):
            groups, caps = _tier_groups(full_gens,
                                        totals[:len(full_gens)])
            for group, cap in zip(groups, caps):
                cols: list = []
                for gen in group:
                    cols += list(self._sentinel_cols("full")
                                 if gen is None else
                                 (gen.bins, gen.z, gen.pos, gen.x,
                                  gen.y, gen.t, jnp.int32(gen.base)))
                self.dispatch_count += 1
                with device_span("query.scan.device", tier="full",
                                 runs=len(group)):
                    grid += np.asarray(_lean_density_full(
                        self.sfc, rb, rlo, rhi, boxes_j, jnp.int64(lo),
                        jnp.int64(hi), env_j, *cols, capacity=cap,
                        width=width, height=height), np.float64)
        if keys_scan:
            t_keys = totals[len(full_gens):len(dev_gens)]
            # zero-candidate generations contribute a zero grid — still
            # a cacheable (immutable) partial, computed for free
            parts = {id(g): np.zeros((height, width), np.float64)
                     for g in keys_scan}
            if int(t_keys.sum()):
                groups, caps = _tier_groups(keys_scan, t_keys)
                for group, cap in zip(groups, caps):
                    cols = []
                    for gen in group:
                        base = (self._sentinel_cols("keys")
                                if gen is None else (gen.bins, gen.z))
                        cols += [base[0], base[1]]
                    self.dispatch_count += 1
                    with device_span("query.scan.device", tier="keys",
                                     runs=len(group)):
                        stacked = np.asarray(_lean_density_keys(
                            self.sfc, rb, rlo, rhi, jnp.asarray(ixy),
                            jnp.asarray(tb), env_j, *cols, capacity=cap,
                            width=width, height=height), np.float64)
                    for i, gen in enumerate(group):
                        if gen is not None:
                            parts[id(gen)] = stacked[i]
            for g in keys_scan:
                part = parts[id(g)]
                grid += part
                if g is not live:
                    obs_count(LEAN_DENSITY_CACHE_MISSES)
                    self._cache_partial(cache, g.gen_id, part)
        # host tier: ONE stacked vectorized pass attributes hits to
        # their owning runs (flat in run count — the HostStack
        # discipline), yielding a cacheable per-generation partial
        # each; a fully-warm call touches no run at all
        if host_gens:
            if any(g.gen_id not in cache for g in host_gens):
                if self._host_stack is None:
                    self._host_stack = HostStack(
                        [g.run for g in host_gens])
                parts = self._host_stack.density_partials(
                    ra["rbin"], ra["rzlo"], ra["rzhi"], self.sfc, ixy,
                    tb, env_t, width, height)
                for g, part in zip(host_gens, parts):
                    # already-cached runs were recomputed by the
                    # stacked pass anyway — count neither a hit (no
                    # work was saved) nor a miss (nothing new cached)
                    if g.gen_id not in cache:
                        obs_count(LEAN_DENSITY_CACHE_MISSES)
                        self._cache_partial(cache, g.gen_id, part)
                    grid += part
                if _ht is not None:
                    _ht += [(g.gen_id, "host", int(g.n),
                             int(g.n) * KEYS_BYTES, None)
                            for g in host_gens]
            else:
                for g in host_gens:
                    obs_count(LEAN_DENSITY_CACHE_HITS)
                    grid += cache[g.gen_id]
                if _ht is not None:
                    _ht += [(g.gen_id, "host", int(g.n), 0, None)
                            for g in host_gens]
        if _ht:
            record_index_scan(self, _ht)
        return grid

    def _density_sweep(self, env, width: int, height: int) -> np.ndarray:
        """Whole-extent grid: one sweep dispatch per UNCACHED generation
        bucket (device) + one numpy pass per uncached host run.  Every
        SEALED generation's sweep partial caches under the grid spec —
        a whole-extent sweep is z-only and time-independent, so the
        partial survives even the generation's own later demotions
        (full → keys → host never changes its z rows); warm repeats
        re-sweep only the live generation."""
        env_t = tuple(float(v) for v in env)
        world = (env_t == _WORLD_ENV
                 and width & (width - 1) == 0
                 and height & (height - 1) == 0)
        env_j = jnp.asarray(np.asarray(env_t))
        grid = np.zeros((height, width), np.float64)
        live = self.generations[-1] if self.generations else None
        spec = ("sweep", env_t, width, height)
        cache = self._density_spec_cache(spec)
        # pyramid serving (ISSUE 18): a sealed generation whose built
        # pyramid carries this exact (world, pow2, square) resolution
        # contributes its cached level grid — bit-identical to what
        # sweeping it produces (docs/density.md), no keys touched.
        # Generations without a pyramid sweep as before: build-behind
        # never blocks or changes results
        pyr_ok = world and width == height
        dev = [g for g in self.generations if g.tier != "host"]
        scan: list = []
        for g in dev:
            part = None
            if g is not live:
                if pyr_ok:
                    part = self._pyramid_level(g.gen_id, width)
                    if part is not None:
                        obs_count(PYRAMID_SERVE_HITS)
                        grid += part
                        continue
                part = cache.get(g.gen_id)
            else:
                # the live partial is immutable FOR A GIVEN ROW COUNT
                # (the store is append-only: existing rows never
                # change), so a repeat sweep with no interleaved
                # appends is served without any dispatch — the
                # interactive-tile warm path.  Any append bumps g.n
                # and misses
                part = cache.get(("live", g.gen_id, int(g.n)))
            if part is None:
                scan.append(g)
            else:
                obs_count(LEAN_DENSITY_CACHE_HITS)
                grid += part
        for s in range(0, len(scan), _GEN_BUCKET * 2):
            chunk = scan[s:s + _GEN_BUCKET * 2]
            group = self._pad_bucket(chunk)
            zs = [(self._sentinel_cols("keys")[1] if g is None
                   else g.z) for g in group]
            self.dispatch_count += 1
            with device_span("query.scan.device", stage="sweep",
                             runs=len(chunk)):
                stacked = np.asarray(_lean_density_sweep(
                    self.sfc, env_j, *zs, width=width, height=height,
                    world=world), np.float64)
            for i, g in enumerate(chunk):
                part = stacked[i]
                grid += part
                if g is not live:
                    obs_count(LEAN_DENSITY_CACHE_MISSES)
                    self._cache_partial(cache, g.gen_id, part)
                else:
                    for k in [k for k in cache
                              if isinstance(k, tuple) and k[0] == "live"
                              and k[1] == g.gen_id]:
                        cache.pop(k)   # superseded row counts
                    self._cache_partial(
                        cache, ("live", g.gen_id, int(g.n)), part)
        scanned = {id(g) for g in scan}
        for g in self.generations:
            if g.tier != "host":
                continue
            if pyr_ok:
                lvl = self._pyramid_level(g.gen_id, width)
                if lvl is not None:
                    obs_count(PYRAMID_SERVE_HITS)
                    grid += lvl
                    continue
            part = cache.get(g.gen_id)
            if part is None:
                obs_count(LEAN_DENSITY_CACHE_MISSES)
                scanned.add(id(g))
                part = g.run.sweep_partial(self.sfc, env_t, width,
                                           height, world)
                self._cache_partial(cache, g.gen_id, part)
            else:
                obs_count(LEAN_DENSITY_CACHE_HITS)
            grid += part
        if heat_enabled() and self.generations:
            record_index_scan(self, [
                (g.gen_id, g.tier, int(g.n),
                 int(g.n) * KEYS_BYTES if id(g) in scanned else 0,
                 None)
                for g in self.generations])
        return grid

    def build_pyramids(self, base: int | None = None,
                       levels: int | None = None) -> int:
        """Build the density pyramid of every sealed generation that
        lacks one (ISSUE 18): one whole-world sweep per generation at
        the pow2 ``base`` resolution (device generations through the
        jitted sweep + 2×2 reduction ladder, spilled host runs through
        their numpy twins), cached under the shared PartialCache
        policy.  Idempotent build-behind: already-built generations
        are skipped, an interrupted build leaves every result exact
        (unbuilt generations simply keep sweeping), and the next call
        resumes with the missing ones.  Returns pyramids built."""
        from ..config import DensityProperties
        from ..ops.density import pyramid_reduce
        from ..resilience import fault_point
        from .pyramid import DensityPyramid, _ladder_depth, pyramid_spec
        base = int(base if base is not None
                   else DensityProperties.PYRAMID_BASE.to_int())
        if base <= 0 or base & (base - 1):
            raise ValueError(
                f"pyramid base must be a power of two, got {base}")
        levels = int(levels if levels is not None
                     else DensityProperties.PYRAMID_LEVELS.to_int())
        depth = _ladder_depth(base, levels)
        cache = self._pyramid_cache.spec_cache(pyramid_spec(base))
        env_j = jnp.asarray(np.asarray(_WORLD_ENV))
        built = 0
        for g in self._sealed():
            if g.gen_id in cache:
                continue
            fault_point("pyramid.build")
            t0 = time.perf_counter()
            with obs_span("pyramid.build", gen_id=g.gen_id,
                          tier=g.tier, base=base):
                if g.tier == "host":
                    pyr = DensityPyramid.from_base(
                        g.run.sweep_partial(self.sfc, _WORLD_ENV,
                                            base, base, True), levels)
                else:
                    group = self._pad_bucket([g])
                    zs = [(self._sentinel_cols("keys")[1] if gg is None
                           else gg.z) for gg in group]
                    self.dispatch_count += 1
                    with device_span("query.scan.device", stage="sweep",
                                     runs=1):
                        stacked = _lean_density_sweep(
                            self.sfc, env_j, *zs, width=base,
                            height=base, world=True)
                        base_dev = stacked[0]
                        lv = {base: np.asarray(base_dev, np.float64)}
                        if depth:
                            for arr in pyramid_reduce(base_dev, depth):
                                a = np.asarray(arr, np.float64)
                                lv[a.shape[0]] = a
                    pyr = DensityPyramid(lv)
            self._pyramid_cache.add(cache, g.gen_id, pyr)
            obs_count(PYRAMID_BUILDS)
            _metrics.timer(PYRAMID_BUILD_MS).update(
                (time.perf_counter() - t0) * 1e3)
            built += 1
        return built

    def density_tile(self, z: int, x: int, y: int, tile: int = 256,
                     max_ranges: int = 2000) -> np.ndarray:
        """One slippy map tile's density grid (index/pyramid.py):
        pyramid-served while ``tile·2^z`` stays at/below the pyramid
        base, direct bbox density scan beyond."""
        from .pyramid import density_tile as _tile
        return _tile(self, z, x, y, tile, max_ranges)

    def range_count(self, boxes, t_lo_ms, t_hi_ms,
                    max_ranges: int = 2000) -> int:
        """Exact-mask hit count with no candidate materialization (the
        StatsScan Count() push-down): a 1×1 density grid over the
        world."""
        return int(round(self.density(
            boxes, t_lo_ms, t_hi_ms, (-180.0, -90.0, 180.0, 90.0),
            1, 1, max_ranges=max_ranges).sum()))

    def z3_cell_counts(self, bits: int) -> dict:
        """WHOLE-EXTENT Z3Histogram push-down (ISSUE 3): fold every
        generation's sorted keys into coarse ``(time-bin, z-cell)``
        counts — the stat's own cell function applied to the key the
        index already stores, so no payload, no candidates, and an
        exactly-oracle-matching table (the keys were encoded by the
        same curve the stat bins with).  Sealed generations' tables
        cache under ``(bits, bin-span)`` (LRU + byte ceiling;
        compaction invalidates); warm repeats fold only the live
        generation.  Returns ``{(bin, cell): count}``."""
        out: dict = {}
        if self._n_rows == 0 or self.t_min_ms is None:
            return out
        b0, _ = to_binned_time(np.int64(max(0, self.t_min_ms)),
                               self.period)
        b1, _ = to_binned_time(np.int64(max(0, self.t_max_ms)),
                               self.period)
        b0, nb = int(b0), int(b1) - int(b0) + 1
        spec = ("z3cells", int(bits), b0, nb)
        cache = self._sketch_cache.spec_cache(spec)
        live = self.generations[-1] if self.generations else None
        total = np.zeros(nb << bits, np.int64)
        scan: list = []
        for g in self.generations:
            if g.tier == "host":
                continue
            part = cache.get(g.gen_id) if g is not live else None
            if part is None:
                scan.append(g)
            else:
                obs_count(LEAN_SKETCH_CACHE_HITS)
                total += part
        for s in range(0, len(scan), _GEN_BUCKET * 2):
            chunk = scan[s:s + _GEN_BUCKET * 2]
            group = self._pad_bucket(chunk)
            cols: list = []
            for g in group:
                c = (self._sentinel_cols("keys") if g is None
                     else (g.bins, g.z))
                cols += [c[0], c[1]]
            self.dispatch_count += 1
            with device_span("query.scan.device", stage="z3_cells",
                             runs=len(chunk)):
                stacked = np.asarray(_z3_cells_multi(
                    jnp.int64(b0), *cols, bits=int(bits), nb=nb))
            for i, g in enumerate(chunk):
                # copy, not a view: a cached view would pin the WHOLE
                # stacked bucket (padding + live rows) in host RAM and
                # break the cache's byte accounting
                part = np.array(stacked[i])
                total += part
                if g is not live:
                    obs_count(LEAN_SKETCH_CACHE_MISSES)
                    self._sketch_cache.add(cache, g.gen_id, part)
        scanned = {id(g) for g in scan}
        for g in self.generations:
            if g.tier != "host":
                continue
            part = cache.get(g.gen_id)
            if part is None:
                obs_count(LEAN_SKETCH_CACHE_MISSES)
                scanned.add(id(g))
                part = g.run.cell_counts(b0, nb, int(bits))
                self._sketch_cache.add(cache, g.gen_id, part)
            else:
                obs_count(LEAN_SKETCH_CACHE_HITS)
            total += part
        if heat_enabled() and self.generations:
            record_index_scan(self, [
                (g.gen_id, g.tier, int(g.n),
                 int(g.n) * KEYS_BYTES if id(g) in scanned else 0,
                 None)
                for g in self.generations])
        c_per_bin = 1 << bits
        for i in np.flatnonzero(total):
            out[(b0 + int(i) // c_per_bin, int(i) % c_per_bin)] = \
                int(total[i])
        return out

    # -- scan helpers -----------------------------------------------------
    @staticmethod
    def _pad_bucket(gens: list) -> list:
        """Pad a generation list to the compile bucket with ``None``
        (the shared empty sentinel generation — zero seek/gather work,
        round-3 VERDICT weak #5)."""
        n_pad = (-len(gens)) % _GEN_BUCKET
        return list(gens) + [None] * n_pad

    @staticmethod
    def _concat_boxes(w_boxes: list):
        """Concatenate per-window boxes with owning qids, padded to a
        compile bucket via the shared never-matching-box convention
        (ops/search.pad_boxes — the one definition of box padding)."""
        boxes_c = np.concatenate(w_boxes)
        bqid_c = np.concatenate(
            [np.full(len(b), q, dtype=np.int32)
             for q, b in enumerate(w_boxes)])
        _, boxes_c, bqid_c = pad_boxes(
            boxes_c, boxes_c, pad_pow2(len(boxes_c), minimum=1), bqid_c)
        return boxes_c, bqid_c

    def _scan_tier(self, gens, totals, rb, rlo, rhi, rq, pos_bits,
                   exact_args, ra=None, degraded_out=None) -> list:
        """Run one tier's batched scan, falling back to per-generation
        dispatches (each sized by its OWN total) when the shared-
        capacity batched buffer would exceed BATCH_SCAN_BUDGET slots.
        Only generations with CANDIDATES scan at all: under
        time-partitioned ingest a window's bins live in a handful of
        generations, and carrying the other 50 at the shared capacity
        tripled warm queries at 1B (measured; the probe already knows
        the per-generation totals).  Returns flat coded arrays
        (padding stripped).

        Degraded execution (ISSUE 16): with ``ra`` (the HOST range
        dict) and ``degraded_out`` given, a transient device failure
        (RESOURCE_EXHAUSTED) demotes the failed group to the host tier
        and answers it via host-seek CANDIDATES appended to
        ``degraded_out`` — the caller's host recheck keeps the result
        exact.  Generations whose circuit breaker is open skip device
        dispatch the same way.  Poison failures propagate."""
        from ..resilience import breaker, check_cancel, fault_point
        tier = "full" if exact_args is not None else "keys"
        live = [(g, t) for g, t in zip(gens, totals) if int(t)]
        if not live:
            return []
        can_degrade = ra is not None and degraded_out is not None
        if can_degrade:
            tripped = [g for g, _ in live
                       if not breaker.allows((id(self), g.gen_id))]
            if tripped:
                # open circuit: this generation's device dispatch keeps
                # tripping — route it through the host tier until the
                # breaker cools down (no device attempt at all)
                coded = self._degrade_to_host(tripped, ra, pos_bits,
                                              tier, reason="breaker")
                if len(coded):
                    degraded_out.append(coded)
                skip = set(id(g) for g in tripped)
                live = [(g, t) for g, t in live if id(g) not in skip]
                if not live:
                    return []
        gens = [g for g, _ in live]
        totals = np.asarray([t for _, t in live])
        capacity = gather_capacity(int(totals.max()),
                                   minimum=self.DEFAULT_CAPACITY)
        padded = self._pad_bucket(gens)
        if len(padded) * capacity <= self.BATCH_SCAN_BUDGET:
            groups = [padded]
            caps = [capacity]
        else:
            groups = [[g] for g, t in zip(gens, totals) if int(t)]
            caps = [gather_capacity(int(t), minimum=self.DEFAULT_CAPACITY)
                    for t in totals if int(t)]
        parts = []
        row_bytes = FULL_BYTES if tier == "full" else KEYS_BYTES
        for group, cap in zip(groups, caps):
            # deadline yield point between group dispatches: partial
            # mode stops STARTING groups (scanned ones stay exact)
            if check_cancel("query.scan.device"):
                break
            try:
                fault_point("device.dispatch")
                rows = int(sum(g.n for g in group if g is not None))
                with device_span("query.scan.device", tier=tier,
                                 runs=sum(1 for g in group
                                          if g is not None),
                                 rows=rows, bytes=rows * row_bytes):
                    cols: list = []
                    for gen in group:
                        if gen is None:
                            cols += list(self._sentinel_cols(tier))
                        elif tier == "full":
                            cols += [gen.bins, gen.z, gen.pos, gen.x,
                                     gen.y, gen.t, jnp.int32(gen.base)]
                        else:
                            cols += [gen.bins, gen.z, gen.pos]
                    self.dispatch_count += 1
                    if (tier == "full"
                            and len(group) * cap >= _TWO_PHASE_MIN_SLOTS):
                        # survivors-only transfer: keep the coded buffer
                        # on device, read the hit count, compact (full
                        # tier already masked exactly on device)
                        packed, nhits = _lean_scan_exact_keep(
                            rb, rlo, rhi, rq, *exact_args, *cols,
                            capacity=cap, pos_bits=pos_bits)
                        k = gather_capacity(max(int(nhits), 1), minimum=8)
                        self.dispatch_count += 1
                        flat = np.asarray(_compact_coded(packed, k=k))
                    else:
                        if tier == "full":
                            packed = _lean_scan_exact_coded(
                                rb, rlo, rhi, rq, *exact_args, *cols,
                                capacity=cap, pos_bits=pos_bits)
                        else:
                            packed = _lean_scan_coded(
                                rb, rlo, rhi, rq, *cols,
                                capacity=cap, pos_bits=pos_bits)
                        flat = np.asarray(packed).ravel()
            except Exception as e:  # noqa: BLE001 — classified below
                coded = self._dispatch_failed(group, e, ra, pos_bits,
                                              tier, can_degrade)
                if coded is None:
                    raise
                if len(coded):
                    degraded_out.append(coded)
                continue
            for g in group:
                if g is not None:
                    breaker.record_success((id(self), g.gen_id))
            # host-side candidate filtering is NOT device time — it
            # runs after the span so device_ms stays honest
            parts.append(flat[flat >= 0].astype(np.int64))
        return parts

    def _dispatch_failed(self, group, exc, ra, pos_bits, tier,
                         can_degrade):
        """Classify a failed device dispatch.  Transient (memory
        pressure) failures demote the group's generations to the host
        tier and return host-seek candidates — one bounded retry, off
        device, guaranteed not to re-OOM; returns None when the failure
        must propagate (poison input, degradation unavailable, or a
        zero retry budget)."""
        from ..resilience import (breaker, classify_device_failure,
                                  retry_budget)
        if (not can_degrade
                or classify_device_failure(exc) != "transient"):
            return None
        gens = [g for g in group if g is not None]
        for g in gens:
            breaker.record_failure((id(self), g.gen_id))
        if retry_budget() <= 0:
            return None
        obs_count(RESILIENCE_RETRIES)
        return self._degrade_to_host(gens, ra, pos_bits, tier,
                                     reason="transient")

    def _degrade_to_host(self, gens, ra, pos_bits, tier, reason):
        """Demote ``gens`` to the host tier (the PR 4 spill path) and
        answer their share of the scan as host-seek CANDIDATES — the
        caller's payload recheck restores exactness.  Recorded as a
        ``query.scan.degraded`` span with a ``resilience.degraded``
        attr, not a user-facing error."""
        with obs_span("query.scan.degraded", tier=tier, reason=reason,
                      runs=len(gens)) as sp:
            sp.set_attr("resilience.degraded", True)
            obs_count(RESILIENCE_DEGRADED, len(gens))
            for g in gens:
                if g.tier != "host":
                    self._spill(g)
            stack = HostStack([g.run for g in gens])
            return stack.candidates(ra["rbin"], ra["rzlo"], ra["rzhi"],
                                    ra["rqid"], pos_bits)
