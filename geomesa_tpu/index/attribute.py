"""Attribute index: equality/range/prefix queries on indexed attributes.

Analog of the reference's attribute index (geomesa-index-api/.../index/
attribute/ — lexicoded values via ``AttributeIndexKey.typeRegistry``
(AttributeIndexKey.scala:38), ``encodeForQuery`` :52).  Lexicographic byte
encoding is unnecessary here: the "table" is a host-side sorted column in
its natural dtype (numpy sort order == lexicoder order for numerics and
strings), plus the permutation.

**Secondary tier.**  The reference appends a secondary key — the date, or
the full Z3 key — after each lexicoded attribute value
(``AttributeIndexKeySpace`` sharing + ``DateIndexKeySpace``; tiered-range
assembly in ``GeoMesaFeatureIndex.getQueryStrategy``,
api/GeoMesaFeatureIndex.scala:248-338), so that ``attr = X AND dtg
DURING …`` seeks a sub-range instead of post-filtering.  Here the tier is
a second int64 sort key (epoch-millis dtg): rows are ordered by
``(value, secondary)`` via one lexsort, and equality/IN lookups refine
each value run with two extra ``searchsorted`` calls.  As in the
reference, tiers apply only when the primary is a point value (equality /
IN) — range and prefix scans span many value runs and fall back to the
planner's residual filter.
"""

from __future__ import annotations

import numpy as np

__all__ = ["AttributeIndex"]


class AttributeIndex:
    """Sorted-column index over one attribute, optionally tiered.

    Tier kinds (mirroring the reference's secondary-index selection —
    Z3 when the schema has geometry + date, date when only a date):

    * **date tier** — rows sorted by ``(value, dtg)``; equality runs
      refine by a time window.
    * **z3 tier** — rows sorted by ``(value, bin, z)``; equality runs
      refine by a Z3 scan plan's covering ``(bin, zlo, zhi)`` ranges,
      narrowing by space AND time.
    """

    def __init__(self, attr: str, values: np.ndarray, pos: np.ndarray,
                 secondary: np.ndarray | None = None,
                 sec_bins: np.ndarray | None = None,
                 sec_z: np.ndarray | None = None):
        self.attr = attr
        self.values = values      # sorted (by value, then tier keys)
        self.pos = pos
        self.secondary = secondary  # date tier: int64 dtg, sorted per run
        self.sec_bins = sec_bins    # z3 tier: int32 time bin
        self.sec_z = sec_z          # z3 tier: int64 z, sorted within bin

    @classmethod
    def build(cls, attr: str, column: np.ndarray,
              secondary: np.ndarray | None = None) -> "AttributeIndex":
        """Date-tiered (or untired) build."""
        col = np.asarray(column)
        if col.dtype == object:
            col = col.astype(str)
        if secondary is None:
            order = np.argsort(col, kind="stable")
            sec = None
        else:
            sec_col = np.asarray(secondary, dtype=np.int64)
            order = np.lexsort((sec_col, col))
            sec = sec_col[order]
        return cls(attr, col[order], order.astype(np.int64), sec)

    @classmethod
    def build_z3(cls, attr: str, column: np.ndarray, bins: np.ndarray,
                 z: np.ndarray) -> "AttributeIndex":
        """Z3-tiered build: ``bins``/``z`` are the feature's Z3 key parts
        (host-computed, same curve as the primary z3 index)."""
        col = np.asarray(column)
        if col.dtype == object:
            col = col.astype(str)
        bins = np.asarray(bins, dtype=np.int32)
        z = np.asarray(z, dtype=np.int64)
        order = np.lexsort((z, bins, col))
        return cls(attr, col[order], order.astype(np.int64),
                   sec_bins=bins[order], sec_z=z[order])

    def _refine_z3(self, lo: int, hi: int, z3_ranges) -> np.ndarray:
        """Positions of run [lo, hi) rows inside any covering
        ``(bin, zlo, zhi)`` range — per-range seeks over the run's
        (bin, z) sorted keys, the tiered-range assembly of
        GeoMesaFeatureIndex.getQueryStrategy (:248-338)."""
        rbin, rzlo, rzhi = z3_ranges
        run_bins = self.sec_bins[lo:hi]
        run_z = self.sec_z[lo:hi]
        b0 = np.searchsorted(run_bins, rbin, side="left")
        b1 = np.searchsorted(run_bins, rbin, side="right")
        parts = []
        for i in range(len(rbin)):
            s, e = int(b0[i]), int(b1[i])
            if s == e:
                continue
            zs = lo + s + np.searchsorted(run_z[s:e], rzlo[i], side="left")
            ze = lo + s + np.searchsorted(run_z[s:e], rzhi[i], side="right")
            if ze > zs:
                parts.append(self.pos[zs:ze])
        if not parts:
            return np.empty(0, dtype=np.int64)
        # plan ranges are disjoint per bin, so no dedupe needed
        return np.concatenate(parts)

    def __len__(self) -> int:
        return len(self.values)

    def _cast(self, v):
        if self.values.dtype.kind in ("U", "S"):
            return str(v)
        return v

    def _refine(self, lo: int, hi: int, sec_window) -> slice:
        """Narrow a value run [lo, hi) by the secondary window."""
        if sec_window is None or self.secondary is None or lo >= hi:
            return slice(lo, hi)
        s_lo, s_hi = sec_window
        run = self.secondary[lo:hi]
        i0 = lo if s_lo is None else lo + int(np.searchsorted(run, s_lo, side="left"))
        i1 = hi if s_hi is None else lo + int(np.searchsorted(run, s_hi, side="right"))
        return slice(i0, i1)

    def query_equals(self, value, sec_window=None,
                     z3_ranges=None) -> np.ndarray:
        """Positions where attr == value, tier-refined by an inclusive
        ``(lo, hi)`` dtg window (date tier) or a covering
        ``(rbin, rzlo, rzhi)`` plan (z3 tier)."""
        value = self._cast(value)
        lo = np.searchsorted(self.values, value, side="left")
        hi = np.searchsorted(self.values, value, side="right")
        if z3_ranges is not None and self.sec_z is not None:
            return np.sort(self._refine_z3(int(lo), int(hi), z3_ranges))
        return np.sort(self.pos[self._refine(lo, hi, sec_window)])

    def query_in(self, values, sec_window=None,
                 z3_ranges=None) -> np.ndarray:
        if not len(values):
            return np.empty(0, dtype=np.int64)
        return np.sort(np.unique(np.concatenate(
            [self.query_equals(v, sec_window, z3_ranges) for v in values])))

    def query_range(self, lo=None, hi=None, lo_inclusive=True,
                    hi_inclusive=True) -> np.ndarray:
        i0 = 0
        i1 = len(self.values)
        if lo is not None:
            i0 = np.searchsorted(self.values, self._cast(lo),
                                 side="left" if lo_inclusive else "right")
        if hi is not None:
            i1 = np.searchsorted(self.values, self._cast(hi),
                                 side="right" if hi_inclusive else "left")
        return np.sort(self.pos[i0:i1])

    def query_prefix(self, prefix: str) -> np.ndarray:
        """String prefix scan — serves LIKE 'abc%' (the reference's
        attribute-index LIKE optimization)."""
        if self.values.dtype.kind not in ("U", "S"):
            raise TypeError("prefix queries require a string attribute")
        lo = np.searchsorted(self.values, prefix, side="left")
        hi = np.searchsorted(self.values, prefix + "￿", side="right")
        return np.sort(self.pos[lo:hi])
