"""Attribute index: equality/range/prefix queries on indexed attributes.

Analog of the reference's attribute index (geomesa-index-api/.../index/
attribute/ — lexicoded values via ``AttributeIndexKey.typeRegistry``
(AttributeIndexKey.scala:38), ``encodeForQuery`` :52).  Lexicographic byte
encoding is unnecessary here: the "table" is a host-side sorted column in
its natural dtype (numpy sort order == lexicoder order for numerics and
strings), plus the permutation.  A secondary Z3/date tier (the reference's
tiered keys) is planned as a follow-up; date refinement currently happens
in the residual filter.
"""

from __future__ import annotations

import numpy as np

__all__ = ["AttributeIndex"]


class AttributeIndex:
    """Sorted-column index over one attribute."""

    def __init__(self, attr: str, values: np.ndarray, pos: np.ndarray):
        self.attr = attr
        self.values = values      # sorted
        self.pos = pos

    @classmethod
    def build(cls, attr: str, column: np.ndarray) -> "AttributeIndex":
        col = np.asarray(column)
        if col.dtype == object:
            col = col.astype(str)
        order = np.argsort(col, kind="stable")
        return cls(attr, col[order], order.astype(np.int64))

    def __len__(self) -> int:
        return len(self.values)

    def _cast(self, v):
        if self.values.dtype.kind in ("U", "S"):
            return str(v)
        return v

    def query_equals(self, value) -> np.ndarray:
        value = self._cast(value)
        lo = np.searchsorted(self.values, value, side="left")
        hi = np.searchsorted(self.values, value, side="right")
        return np.sort(self.pos[lo:hi])

    def query_in(self, values) -> np.ndarray:
        if not len(values):
            return np.empty(0, dtype=np.int64)
        return np.sort(np.unique(np.concatenate(
            [self.query_equals(v) for v in values])))

    def query_range(self, lo=None, hi=None, lo_inclusive=True,
                    hi_inclusive=True) -> np.ndarray:
        i0 = 0
        i1 = len(self.values)
        if lo is not None:
            i0 = np.searchsorted(self.values, self._cast(lo),
                                 side="left" if lo_inclusive else "right")
        if hi is not None:
            i1 = np.searchsorted(self.values, self._cast(hi),
                                 side="right" if hi_inclusive else "left")
        return np.sort(self.pos[i0:i1])

    def query_prefix(self, prefix: str) -> np.ndarray:
        """String prefix scan — serves LIKE 'abc%' (the reference's
        attribute-index LIKE optimization)."""
        if self.values.dtype.kind not in ("U", "S"):
            raise TypeError("prefix queries require a string attribute")
        lo = np.searchsorted(self.values, prefix, side="left")
        hi = np.searchsorted(self.values, prefix + "￿", side="right")
        return np.sort(self.pos[lo:hi])
