"""LeanXZ2Index: tiered generational XZ2 index — polygons/lines at the
lean profile's scale (round-4 VERDICT #4).

Round 4 capped non-point schemas at the full-fat host-side
:class:`~geomesa_tpu.index.xz2.XZ2Index` (~150M/chip); the reference's
XZ indexes are first-class at cluster scale
(geomesa-z3/.../curve/XZ2SFC.scala:54-77,
geomesa-index-api/.../index/z2/XZ2IndexKeySpace.scala:44).  This module
is the XZ2 key space on the lean generational machinery: the sequence
code IS an order-preserving int64, so the sorted runs, device/host
residency tiers, HBM budget, stacked host bisection and batched
seek programs of :class:`~geomesa_tpu.index.attr_lean.LeanAttrIndex`
serve it verbatim (key = xz2 code, secondary unused).

Queries plan covering code ranges host-side (``XZ2SFC.ranges`` — the
published Böhm et al. arithmetic), seek all generations in the fixed
dispatch pattern, and return CANDIDATE gids; the planner's residual
filter applies the exact geometry predicate (the client-side re-check,
exactly the full-fat index's split).
"""

from __future__ import annotations

import numpy as np

from ..config import DEFAULT_MAX_RANGES
from ..curve.xz2 import xz2_sfc
from ..geometry.types import Geometry
from .attr_lean import LeanAttrIndex

__all__ = ["LeanXZ2Index", "LeanXZ3Index", "XZ2Facade"]


class LeanCoreFacade:
    """Delegation base over a pluggable generational (key, sec, gid)
    core — the single definition of the core surface every lean XZ
    facade presents (review r5: hand-copied facades drift)."""

    def __init__(self, core):
        self._core = core

    def __len__(self) -> int:
        return len(self._core)

    @property
    def heat_scope(self):
        """Access-temperature scope (obs/heat) — held by the CORE,
        where the scans that record touches actually run."""
        return self._core.heat_scope

    @heat_scope.setter
    def heat_scope(self, scope) -> None:
        self._core.heat_scope = scope

    @property
    def generations(self):
        return self._core.generations

    @property
    def dispatch_count(self) -> int:
        return self._core.dispatch_count

    def device_bytes(self) -> int:
        return self._core.device_bytes()

    def host_key_bytes(self) -> int:
        return self._core.host_key_bytes()

    def tier_counts(self) -> dict:
        return self._core.tier_counts()

    def storage_stats(self) -> dict:
        """Byte accounting of the underlying generational core, tagged
        with the facade's own kind (obs/resource.StorageReport — the
        XZ tiers must be distinguishable from raw attribute runs)."""
        st = self._core.storage_stats()
        st["kind"] = type(self).__name__
        return st

    def block(self) -> None:
        self._core.block()

    @staticmethod
    def gather_payload(positions):
        """Result-materialization protocol hook (ISSUE 14): XZ runs
        key envelopes, and the packed polygon/line payload lives only
        in the host column store — ``None`` routes the Arrow result
        path to the column store's vectorized take (WKB encoding is
        the one inherently per-row step, arrow/schema._geom_arrays)."""
        return None

    @property
    def compactions(self) -> int:
        return self._core.compactions

    def compact(self, budget_ms: float | None = None,
                factor: int | None = None,
                max_groups: int | None = None) -> dict:
        """Incremental size-tiered merge compaction of the core's
        generational runs (the LSM maintenance job — see
        LeanAttrIndex.compact)."""
        return self._core.compact(budget_ms=budget_ms, factor=factor,
                                  max_groups=max_groups)

    def sketch_scan(self, fold):
        """Stat-sketch fold over the core's own (key, sec) runs
        (ISSUE 3) — direct-index surface parity with the lean family:
        e.g. a whole-window Count over an XZ run set with the same
        sealed-run partial cache.  A non-point lean STORE's attribute
        stats route through its attr indexes instead (stats_process);
        this exposes the fold for callers driving the XZ index
        directly (LeanAttrIndex.sketch_scan)."""
        return self._core.sketch_scan(fold)


class XZ2Facade(LeanCoreFacade):
    """Shared XZ2 surface — single-chip and sharded variants differ
    only in the core they plug in."""

    def __init__(self, core, g: int = 12):
        super().__init__(core)
        self.g = g
        self.sfc = xz2_sfc(g)

    def append_bboxes(self, bbox: np.ndarray,
                      base_gid: int | None = None) -> "XZ2Facade":
        """Stream one slice of per-feature envelopes (n, 4) in: encode
        sequence codes, merge into the current generation."""
        bb = np.asarray(bbox, np.float64).reshape((-1, 4))
        codes = self.sfc.index(bb[:, 0], bb[:, 1], bb[:, 2], bb[:, 3],
                               xp=np).astype(np.int64)
        self._core.append(codes, np.zeros(len(codes), np.int64),
                          base_gid=base_gid)
        return self

    def query(self, geometry: Geometry,
              max_ranges: int = DEFAULT_MAX_RANGES,
              exact: bool = True) -> np.ndarray:
        """CANDIDATE gids whose envelope code falls in the covering
        ranges of ``geometry``'s envelope.  ``exact`` is accepted for
        interface parity and ignored: exactness always comes from the
        caller's residual geometry predicate (the planner re-checks
        candidates; a device payload tier has nothing to re-check
        against here — the code is envelope-granular by design)."""
        env = geometry.envelope
        ranges = self.sfc.ranges([env.as_tuple()],
                                 max_ranges=max_ranges)
        if not len(ranges) or not len(self):
            return np.empty(0, dtype=np.int64)
        return self._core.query_ranges(
            [(int(lo), int(hi), None, None, 0) for lo, hi in ranges])


class LeanXZ2Index(XZ2Facade):
    """Single-chip generational tiered XZ2 index (module doc)."""

    def __init__(self, g: int = 12, generation_slots: int | None = None,
                 hbm_budget_bytes: int | None = None,
                 compaction_factor: int | None = None):
        super().__init__(LeanAttrIndex(
            "__xz2__", "long", generation_slots=generation_slots,
            hbm_budget_bytes=hbm_budget_bytes,
            compaction_factor=compaction_factor), g=g)


class LeanXZ3Index(LeanCoreFacade):
    """Generational tiered XZ3 index — polygons/lines WITH TIME at the
    lean scale (the reference's XZ3IndexKeySpace key =
    ``[2B bin][8B code]``; geomesa-index-api/.../index/z3/
    XZ3IndexKeySpace.scala).  The (bin, code) pair IS the attribute
    core's (key, sec) composite: per-bin code ranges seek with the
    same two-key searchsorted the whole lean family uses — bin
    equality narrows, code ranges span, residual exactness stays with
    the planner.  Range planning is the SHARED
    :func:`~geomesa_tpu.index.xz3.xz3_bin_code_ranges` the full-fat
    index uses."""

    def __init__(self, period="week", g: int = 12,
                 generation_slots: int | None = None,
                 hbm_budget_bytes: int | None = None, core=None,
                 compaction_factor: int | None = None):
        from ..curve.binnedtime import TimePeriod
        from ..curve.xz3 import xz3_sfc
        super().__init__(core if core is not None else LeanAttrIndex(
            "__xz3__", "long", generation_slots=generation_slots,
            hbm_budget_bytes=hbm_budget_bytes,
            compaction_factor=compaction_factor))
        self.period = TimePeriod.parse(period)
        self.g = g
        self.sfc = xz3_sfc(self.period, g)
        self.t_min_ms: int | None = None
        self.t_max_ms: int | None = None

    def append_bboxes(self, bbox: np.ndarray, dtg_ms: np.ndarray,
                      base_gid: int | None = None) -> "LeanXZ3Index":
        """Stream (envelope, timestamp) slices: per-row (bin, code)
        keys into the generational runs.  The time extent is AGREED
        under multihost (every process clamps open query bounds
        identically, or collective dispatches would diverge — the
        ShardedLeanZ3Index discipline)."""
        from ..curve.binnedtime import to_binned_time
        bb = np.asarray(bbox, np.float64).reshape((-1, 4))
        t = np.ascontiguousarray(dtg_ms, np.int64)
        bins, offs = to_binned_time(t, self.period)
        offs_f = offs.astype(np.float64)
        codes = self.sfc.index(bb[:, 0], bb[:, 1], offs_f,
                               bb[:, 2], bb[:, 3], offs_f,
                               xp=np).astype(np.int64)
        self._core.append(bins.astype(np.int64), codes,
                          base_gid=base_gid)
        t_min = int(t.min()) if len(t) else np.iinfo(np.int64).max
        t_max = int(t.max()) if len(t) else np.iinfo(np.int64).min
        if getattr(self._core, "_multihost", False):
            from ..parallel.multihost import allgather_concat
            trip = allgather_concat(np.array([[t_min, t_max]],
                                             dtype=np.int64))
            t_min = int(trip[:, 0].min())
            t_max = int(trip[:, 1].max())
        if t_min <= t_max:   # at least one row somewhere
            self.t_min_ms = (t_min if self.t_min_ms is None
                             else min(self.t_min_ms, t_min))
            self.t_max_ms = (t_max if self.t_max_ms is None
                             else max(self.t_max_ms, t_max))
        return self

    def query(self, geometry: Geometry, t_lo_ms=None, t_hi_ms=None,
              max_ranges: int = DEFAULT_MAX_RANGES,
              exact: bool = True) -> np.ndarray:
        """CANDIDATE gids for envelope ∩ [t_lo, t_hi] (open bounds
        clamp to the agreed data extent); the caller's residual
        predicate is the exactness stage."""
        if not len(self) or self.t_min_ms is None:
            return np.empty(0, dtype=np.int64)
        t_lo_ms = self.t_min_ms if t_lo_ms is None else int(t_lo_ms)
        t_hi_ms = self.t_max_ms if t_hi_ms is None else int(t_hi_ms)
        t_lo_ms = max(t_lo_ms, self.t_min_ms)
        t_hi_ms = min(t_hi_ms, self.t_max_ms)
        if t_lo_ms > t_hi_ms:
            return np.empty(0, dtype=np.int64)
        from .xz3 import xz3_bin_code_ranges
        env = geometry.envelope
        triples = xz3_bin_code_ranges(self.sfc, env.as_tuple(),
                                      t_lo_ms, t_hi_ms, self.period,
                                      max_ranges)
        if not triples:
            return np.empty(0, dtype=np.int64)
        return self._core.query_ranges(
            [(b, b, lo, hi, 0) for b, lo, hi in triples])
