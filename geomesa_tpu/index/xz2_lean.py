"""LeanXZ2Index: tiered generational XZ2 index — polygons/lines at the
lean profile's scale (round-4 VERDICT #4).

Round 4 capped non-point schemas at the full-fat host-side
:class:`~geomesa_tpu.index.xz2.XZ2Index` (~150M/chip); the reference's
XZ indexes are first-class at cluster scale
(geomesa-z3/.../curve/XZ2SFC.scala:54-77,
geomesa-index-api/.../index/z2/XZ2IndexKeySpace.scala:44).  This module
is the XZ2 key space on the lean generational machinery: the sequence
code IS an order-preserving int64, so the sorted runs, device/host
residency tiers, HBM budget, stacked host bisection and batched
seek programs of :class:`~geomesa_tpu.index.attr_lean.LeanAttrIndex`
serve it verbatim (key = xz2 code, secondary unused).

Queries plan covering code ranges host-side (``XZ2SFC.ranges`` — the
published Böhm et al. arithmetic), seek all generations in the fixed
dispatch pattern, and return CANDIDATE gids; the planner's residual
filter applies the exact geometry predicate (the client-side re-check,
exactly the full-fat index's split).
"""

from __future__ import annotations

import numpy as np

from ..config import DEFAULT_MAX_RANGES
from ..curve.xz2 import xz2_sfc
from ..geometry.types import Geometry
from .attr_lean import LeanAttrIndex

__all__ = ["LeanXZ2Index", "XZ2Facade"]


class XZ2Facade:
    """Shared XZ2 surface over a pluggable generational (key, sec, gid)
    core — the single definition both the single-chip and the sharded
    variants present (review r5: two hand-copied facades had already
    drifted)."""

    def __init__(self, core, g: int = 12):
        self.g = g
        self.sfc = xz2_sfc(g)
        self._core = core

    def __len__(self) -> int:
        return len(self._core)

    @property
    def generations(self):
        return self._core.generations

    @property
    def dispatch_count(self) -> int:
        return self._core.dispatch_count

    def device_bytes(self) -> int:
        return self._core.device_bytes()

    def tier_counts(self) -> dict:
        return self._core.tier_counts()

    def block(self) -> None:
        self._core.block()

    def append_bboxes(self, bbox: np.ndarray,
                      base_gid: int | None = None) -> "XZ2Facade":
        """Stream one slice of per-feature envelopes (n, 4) in: encode
        sequence codes, merge into the current generation."""
        bb = np.asarray(bbox, np.float64).reshape((-1, 4))
        codes = self.sfc.index(bb[:, 0], bb[:, 1], bb[:, 2], bb[:, 3],
                               xp=np).astype(np.int64)
        self._core.append(codes, np.zeros(len(codes), np.int64),
                          base_gid=base_gid)
        return self

    def query(self, geometry: Geometry,
              max_ranges: int = DEFAULT_MAX_RANGES,
              exact: bool = True) -> np.ndarray:
        """CANDIDATE gids whose envelope code falls in the covering
        ranges of ``geometry``'s envelope.  ``exact`` is accepted for
        interface parity and ignored: exactness always comes from the
        caller's residual geometry predicate (the planner re-checks
        candidates; a device payload tier has nothing to re-check
        against here — the code is envelope-granular by design)."""
        env = geometry.envelope
        ranges = self.sfc.ranges([env.as_tuple()],
                                 max_ranges=max_ranges)
        if not len(ranges) or not len(self):
            return np.empty(0, dtype=np.int64)
        return self._core.query_ranges(
            [(int(lo), int(hi), None, None, 0) for lo, hi in ranges])


class LeanXZ2Index(XZ2Facade):
    """Single-chip generational tiered XZ2 index (module doc)."""

    def __init__(self, g: int = 12, generation_slots: int | None = None,
                 hbm_budget_bytes: int | None = None):
        super().__init__(LeanAttrIndex(
            "__xz2__", "long", generation_slots=generation_slots,
            hbm_budget_bytes=hbm_budget_bytes), g=g)
