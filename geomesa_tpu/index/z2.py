"""Z2 point index: spatial-only bbox queries over (lon, lat) points.

TPU-native analog of the reference's Z2 index
(geomesa-index-api/.../index/z2/Z2IndexKeySpace.scala; key layout
``[shard][8B z][id]``, :42): one sorted int64 z column + permutation.
Supports multi-box (OR of bboxes) queries — the reference's
FilterSplitter-produced disjunctions (BASELINE config 2).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..curve.sfc import Z2SFC, z2_sfc
from ..curve.zorder import deinterleave2
from ..config import DEFAULT_MAX_RANGES
from ..obs import device_span
from ..ops.search import (
    coded_pos_bits, expand_ranges, gather_capacity, pack_coded,
    pack_wire, pad_boxes, pad_pow2, pad_ranges, run_packed_query,
)

__all__ = ["Z2PointIndex", "Z2QueryPlan", "plan_z2_query"]


@dataclass
class Z2QueryPlan:
    rzlo: np.ndarray   # (R,) int64
    rzhi: np.ndarray
    ixy: np.ndarray    # (B, 4) int32 normalized bounds
    boxes: np.ndarray  # (B, 4) float64 exact bounds

    @property
    def num_ranges(self) -> int:
        return len(self.rzlo)


#: current z2 key-layout version (v1 = legacy semi-normalized curve)
Z2_INDEX_VERSION = 2


def z2_sfc_for_version(version: int):
    """Curve for a persisted index-layout version (the reference's
    Z2IndexV1..Vn read-path dispatch, index/index/z2/legacy/)."""
    if version >= 2:
        return z2_sfc()
    from ..curve.legacy import legacy_z2_sfc
    return legacy_z2_sfc()


def plan_z2_query(boxes, max_ranges: int = DEFAULT_MAX_RANGES,
                  sfc=None) -> Z2QueryPlan:
    sfc = sfc if sfc is not None else z2_sfc()
    boxes = np.atleast_2d(np.asarray(boxes, dtype=np.float64))
    zr = sfc.ranges(boxes, max_ranges=max_ranges)
    ixy = np.stack(
        [
            [
                sfc.lon.normalize_scalar(b[0]),
                sfc.lat.normalize_scalar(b[1]),
                sfc.lon.normalize_scalar(b[2]),
                sfc.lat.normalize_scalar(b[3]),
            ]
            for b in boxes
        ]
    ).astype(np.int32)
    return Z2QueryPlan(rzlo=zr[:, 0], rzhi=zr[:, 1], ixy=ixy, boxes=boxes)


@partial(jax.jit, static_argnames=("capacity", "pos_bits"))
def _query_many_packed(z, pos, x, y, rzlo, rzhi, rqid, ixy, boxes, bqid,
                       capacity: int, pos_bits: int = 40):
    """Batched multi-box-set scan: Q independent queries in one dispatch
    (see z3._query_many_packed for the packed qid<<pos_bits|pos protocol
    and the int32/int64 wire choice)."""
    starts = jnp.searchsorted(z, rzlo, side="left")
    ends = jnp.searchsorted(z, rzhi, side="right")
    counts = jnp.maximum(ends - starts, 0)
    total = jnp.sum(counts)
    idx, valid, rid = expand_ranges(starts, counts, capacity)
    zc = z[idx]
    posc = pos[idx]
    cqid = rqid[rid]
    ix, iy = deinterleave2(zc.astype(jnp.uint64))
    ix = ix.astype(jnp.int64)
    iy = iy.astype(jnp.int64)
    same_q = cqid[:, None] == bqid[None, :]
    in_box_int = (
        same_q
        & (ix[:, None] >= ixy[None, :, 0])
        & (iy[:, None] >= ixy[None, :, 1])
        & (ix[:, None] <= ixy[None, :, 2])
        & (iy[:, None] <= ixy[None, :, 3])
    ).any(axis=1)
    xc = x[posc]
    yc = y[posc]
    in_box_exact = (
        same_q
        & (xc[:, None] >= boxes[None, :, 0])
        & (yc[:, None] >= boxes[None, :, 1])
        & (xc[:, None] <= boxes[None, :, 2])
        & (yc[:, None] <= boxes[None, :, 3])
    ).any(axis=1)
    mask = valid & in_box_int & in_box_exact
    return pack_coded(total, cqid, posc, mask, pos_bits)


@partial(jax.jit, static_argnames=("capacity", "use_pallas"))
def _query_packed(z, pos, x, y, rzlo, rzhi, ixy, boxes, capacity: int,
                  use_pallas: bool = False):
    """One-dispatch scan (seeks + gather + fused mask) returning the packed
    ``[total, pos|-1, …]`` vector — one device round trip per query (see
    z3._query_packed for the protocol rationale).  ``use_pallas`` routes
    the decode + R-box int test through the fused Pallas kernel (the
    Z2Filter.inBounds role); the exact float re-check stays XLA (it
    fuses)."""
    starts = jnp.searchsorted(z, rzlo, side="left")
    ends = jnp.searchsorted(z, rzhi, side="right")
    counts = jnp.maximum(ends - starts, 0)
    total = jnp.sum(counts)
    idx, valid, _ = expand_ranges(starts, counts, capacity)
    zc = z[idx]
    posc = pos[idx]
    if use_pallas:
        from ..ops.pallas_kernels import z2_mask_pallas
        in_box_int = z2_mask_pallas(zc, ixy)
    else:
        ix, iy = deinterleave2(zc.astype(jnp.uint64))
        ix = ix.astype(jnp.int64)
        iy = iy.astype(jnp.int64)
        in_box_int = (
            (ix[:, None] >= ixy[None, :, 0])
            & (iy[:, None] >= ixy[None, :, 1])
            & (ix[:, None] <= ixy[None, :, 2])
            & (iy[:, None] <= ixy[None, :, 3])
        ).any(axis=1)
    xc = x[posc]
    yc = y[posc]
    in_box_exact = (
        (xc[:, None] >= boxes[None, :, 0])
        & (yc[:, None] >= boxes[None, :, 1])
        & (xc[:, None] <= boxes[None, :, 2])
        & (yc[:, None] <= boxes[None, :, 3])
    ).any(axis=1)
    mask = valid & in_box_int & in_box_exact
    # int32 wire format — see z3._query_packed
    return pack_wire(total, posc, mask, jnp.int32)


from functools import lru_cache


@lru_cache(maxsize=8)
def _world_cell_boundaries(s: int):
    """Device-cached sorted z-prefix starts of the 2^s × 2^s world grid
    plus the flat permutation mapping z-order cells to (row, col)."""
    from ..curve.zorder import deinterleave2, interleave2
    ix, iy = np.meshgrid(np.arange(1 << s, dtype=np.uint64),
                         np.arange(1 << s, dtype=np.uint64))
    starts = np.asarray(interleave2(
        (ix.ravel() << np.uint64(31 - s)).astype(np.int64),
        (iy.ravel() << np.uint64(31 - s)).astype(np.int64),
        xp=np)).astype(np.int64)
    sorted_starts = np.sort(starts)
    sx, sy = deinterleave2(sorted_starts.astype(np.uint64), xp=np)
    row = (sy >> np.uint64(31 - s)).astype(np.int64)
    col = (sx >> np.uint64(31 - s)).astype(np.int64)
    perm = row * (1 << s) + col
    return jnp.asarray(sorted_starts), jnp.asarray(perm)


@partial(jax.jit, static_argnames=("s", "height", "width"))
def _density_world_program(z, starts, perm, n, s: int,
                           height: int, width: int):
    """One-dispatch world histogram: boundary seeks + diff + scatter by
    the static permutation + pooling, all on device; only the output
    grid crosses to host."""
    bounds = jnp.searchsorted(z, starts, side="left")
    counts = jnp.diff(jnp.append(bounds, n)).astype(jnp.float64)
    sq = jnp.zeros(((1 << s) * (1 << s),), jnp.float64).at[perm].set(counts)
    sq = sq.reshape(1 << s, 1 << s)
    return sq.reshape(height, (1 << s) // height,
                      width, (1 << s) // width).sum(axis=(1, 3))


@partial(jax.jit, static_argnames=("sfc",))
def _encode_sort_z2(sfc, a, b):
    zv = sfc.index(a, b)
    return jax.lax.sort(
        (zv, jnp.arange(zv.shape[0], dtype=jnp.int32)),
        dimension=0, num_keys=1)


#: sentinel key for append padding: sorts last, matches no query range
_SENTINEL_Z2 = np.int64(np.iinfo(np.int64).max)


@partial(jax.jit, static_argnames=("sfc",))
def _z2_append_step(sfc, z, pos, x, y, r, xs, ys, m):
    """Write a new batch's coords at the capacity tail, encode its z
    keys into the sentinel slots starting at sorted position ``r``, and
    re-sort keys+pos in place (see Z3PointIndex._append_step: on TPU the
    sort network IS the cheapest merge)."""
    x = jax.lax.dynamic_update_slice(x, xs, (r,))
    y = jax.lax.dynamic_update_slice(y, ys, (r,))
    z_new = sfc.index(xs, ys)
    valid = jnp.arange(xs.shape[0]) < m
    z_new = jnp.where(valid, z_new, _SENTINEL_Z2)
    pos_new = jnp.where(
        valid, r + jnp.arange(xs.shape[0], dtype=pos.dtype),
        pos.dtype.type(-1))
    z = jax.lax.dynamic_update_slice(z, z_new, (r,))
    pos = jax.lax.dynamic_update_slice(pos, pos_new, (r,))
    z, pos = jax.lax.sort((z, pos), dimension=0, num_keys=1)
    return z, pos, x, y




class Z2PointIndex:
    """Device-resident Z2 index over point features."""

    DEFAULT_CAPACITY = 1 << 15

    def __init__(self, z, pos, x, y, version: int = Z2_INDEX_VERSION,
                 n_rows: int | None = None):
        self.version = version
        self.sfc = z2_sfc_for_version(version)
        self.z = z
        self.pos = pos
        self.x = x
        self.y = y
        #: valid rows (the z/pos tail beyond this holds append-padding
        #: sentinels)
        self._n_rows = int(z.shape[0]) if n_rows is None else n_rows
        self._capacity = self.DEFAULT_CAPACITY

    @classmethod
    def build(cls, x, y, xd=None, yd=None,
              version: int = Z2_INDEX_VERSION) -> "Z2PointIndex":
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        sfc = z2_sfc_for_version(version)
        xd = jnp.asarray(x) if xd is None else xd
        yd = jnp.asarray(y) if yd is None else yd
        z_s, pos = _encode_sort_z2(sfc, xd, yd)
        return cls(z=z_s, pos=pos, x=xd, y=yd, version=version,
                   n_rows=len(x))

    def __len__(self) -> int:
        return self._n_rows

    def _grow_capacity(self, cap: int) -> None:
        pad = cap - int(self.z.shape[0])
        if pad <= 0:
            return
        self.z = jnp.concatenate(
            [self.z, jnp.full((pad,), _SENTINEL_Z2, self.z.dtype)])
        self.pos = jnp.concatenate(
            [self.pos, jnp.full((pad,), -1, self.pos.dtype)])
        self.x = jnp.concatenate([self.x, jnp.zeros((pad,), self.x.dtype)])
        self.y = jnp.concatenate([self.y, jnp.zeros((pad,), self.y.dtype)])

    def append(self, x, y) -> "Z2PointIndex":
        """Incremental ingest (the single-chip side of round-3 next #5):
        new rows land in the sentinel padding and the capacity-padded
        columns re-sort in place; shapes bucket by (capacity, pow2(m))
        so steady-state appends reuse one compiled program."""
        from ..ops.search import gather_capacity
        x = np.asarray(x, dtype=np.float64)
        m = len(x)
        if m == 0:
            return self
        y = np.asarray(y, dtype=np.float64)
        m_pad = gather_capacity(m, minimum=8)
        r = self._n_rows
        if r + m_pad > int(self.z.shape[0]):
            self._grow_capacity(gather_capacity(r + m_pad))
        pad = m_pad - m
        self.z, self.pos, self.x, self.y = _z2_append_step(
            self.sfc, self.z, self.pos, self.x, self.y, jnp.int32(r),
            jnp.asarray(np.pad(x, (0, pad))),
            jnp.asarray(np.pad(y, (0, pad))), jnp.int32(m))
        self._n_rows = r + m
        return self

    def query(self, boxes, max_ranges: int = DEFAULT_MAX_RANGES) -> np.ndarray:
        """Original-order positions matching any of the bboxes, exactly."""
        plan = plan_z2_query(boxes, max_ranges, sfc=self.sfc)
        if plan.num_ranges == 0 or len(self) == 0:
            return np.empty(0, dtype=np.int64)
        r = pad_ranges({"rzlo": plan.rzlo, "rzhi": plan.rzhi},
                       pad_pow2(plan.num_ranges))
        ixy, bxs = pad_boxes(plan.ixy, plan.boxes,
                             pad_pow2(len(plan.boxes), minimum=1))
        args = (self.z, self.pos, self.x, self.y,
                jnp.asarray(r["rzlo"]), jnp.asarray(r["rzhi"]),
                jnp.asarray(ixy), jnp.asarray(bxs))

        def dispatch(capacity):
            from ..ops.pallas_kernels import GATES
            from .z3 import _use_pallas_scan
            with device_span("query.scan.device", stage="packed",
                             capacity=capacity):
                # BOTH branches materialize inside the span: the XLA
                # thunk returns a lazy array, and an asarray deferred
                # to run_packed_query would block outside attribution
                return GATES["z2_scan"].run(
                    lambda: np.asarray(_query_packed(
                        *args, capacity=capacity, use_pallas=True)),
                    lambda: np.asarray(_query_packed(
                        *args, capacity=capacity, use_pallas=False)),
                    enabled=_use_pallas_scan())

        hits, self._capacity = run_packed_query(dispatch, self._capacity)
        return hits

    def density_world(self, width: int, height: int) -> np.ndarray:
        """Whole-world count grid straight from the SORTED z column:
        each cell of a power-of-two grid is one contiguous z-prefix
        range, so the histogram is G binary-search boundaries + adjacent
        differences — O(G log N), no pass over the data (the reference's
        DensityScan also reads the z-ordered table; here the sort order
        IS the aggregation).  ~1ms vs the O(N log N) sort path at 16M
        points.  Semantics match ``density_grid`` over the world
        envelope (clamping included) for unweighted counts."""
        import math

        a = int(math.log2(width))
        b = int(math.log2(height))
        if (1 << a) != width or (1 << b) != height or a > 15 or b > 15:
            raise ValueError("density_world needs power-of-two dims "
                             "(≤ 32768 per axis)")
        # with unequal per-axis bit counts a cell is NOT one contiguous
        # z range (an unconstrained bit of the shorter axis interleaves
        # between constrained bits), so compute the SQUARE grid at
        # s = max(a, b) — whose cells are exact z prefixes — and pool
        # the extra resolution down.  Boundaries and the cell
        # permutation are data-independent, cached on device per s; the
        # whole query is ONE dispatch downloading only the output grid.
        s = max(a, b)
        starts_d, perm_d = _world_cell_boundaries(s)
        grid = _density_world_program(
            self.z, starts_d, perm_d, jnp.int64(len(self)), s,
            height, width)
        return np.asarray(grid)

    def query_many(self, boxes_list,
                   max_ranges: int = DEFAULT_MAX_RANGES) -> list[np.ndarray]:
        """Batched spatial-only queries: one device dispatch for ALL the
        box sets; returns a sorted position array per entry."""
        n_q = len(boxes_list)
        if n_q == 0 or len(self) == 0:
            return [np.empty(0, dtype=np.int64) for _ in range(n_q)]
        rzlo, rzhi, rqid, ixy, bxs, bqid = [], [], [], [], [], []
        from ..resilience import check_cancel
        for q, boxes in enumerate(boxes_list):
            # deadline yield point between range decompositions (ISSUE
            # 16): see z3.query_many
            if check_cancel("query.decompose"):
                break
            # per-window scan-ranges budget (see z3.query_many)
            plan = plan_z2_query(boxes, max_ranges, sfc=self.sfc)
            if plan.num_ranges == 0:
                continue
            rzlo.append(plan.rzlo)
            rzhi.append(plan.rzhi)
            rqid.append(np.full(plan.num_ranges, q, dtype=np.int32))
            ixy.append(plan.ixy)
            bxs.append(plan.boxes)
            bqid.append(np.full(len(plan.boxes), q, dtype=np.int32))
        if not rzlo:
            return [np.empty(0, dtype=np.int64) for _ in range(n_q)]
        r = pad_ranges({"rzlo": np.concatenate(rzlo),
                        "rzhi": np.concatenate(rzhi),
                        "rqid": np.concatenate(rqid)},
                       pad_pow2(sum(len(a) for a in rzlo)))
        ixy_c, boxes_c, bqid_c = pad_boxes(
            np.concatenate(ixy), np.concatenate(bxs),
            pad_pow2(sum(len(b) for b in bxs), minimum=1),
            np.concatenate(bqid))

        pos_bits = coded_pos_bits(len(self), n_q)

        def dispatch(capacity):
            with device_span("query.scan.device", stage="packed_many",
                             capacity=capacity):
                return np.asarray(_query_many_packed(
                    self.z, self.pos, self.x, self.y,
                    jnp.asarray(r["rzlo"]), jnp.asarray(r["rzhi"]),
                    jnp.asarray(r["rqid"]), jnp.asarray(ixy_c),
                    jnp.asarray(boxes_c), jnp.asarray(bqid_c),
                    capacity=capacity, pos_bits=pos_bits,
                ))

        coded, self._capacity = run_packed_query(dispatch, self._capacity)
        qids = coded >> pos_bits
        positions = coded & ((np.int64(1) << pos_bits) - 1)
        return [np.unique(positions[qids == q]) for q in range(n_q)]
