"""Z2 point index: spatial-only bbox queries over (lon, lat) points.

TPU-native analog of the reference's Z2 index
(geomesa-index-api/.../index/z2/Z2IndexKeySpace.scala; key layout
``[shard][8B z][id]``, :42): one sorted int64 z column + permutation.
Supports multi-box (OR of bboxes) queries — the reference's
FilterSplitter-produced disjunctions (BASELINE config 2).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..curve.sfc import Z2SFC, z2_sfc
from ..curve.zorder import deinterleave2
from ..config import DEFAULT_MAX_RANGES
from ..ops.search import expand_ranges, gather_capacity

__all__ = ["Z2PointIndex", "Z2QueryPlan", "plan_z2_query"]


@dataclass
class Z2QueryPlan:
    rzlo: np.ndarray   # (R,) int64
    rzhi: np.ndarray
    ixy: np.ndarray    # (B, 4) int32 normalized bounds
    boxes: np.ndarray  # (B, 4) float64 exact bounds

    @property
    def num_ranges(self) -> int:
        return len(self.rzlo)


def plan_z2_query(boxes, max_ranges: int = DEFAULT_MAX_RANGES) -> Z2QueryPlan:
    sfc = z2_sfc()
    boxes = np.atleast_2d(np.asarray(boxes, dtype=np.float64))
    zr = sfc.ranges(boxes, max_ranges=max_ranges)
    ixy = np.stack(
        [
            [
                sfc.lon.normalize_scalar(b[0]),
                sfc.lat.normalize_scalar(b[1]),
                sfc.lon.normalize_scalar(b[2]),
                sfc.lat.normalize_scalar(b[3]),
            ]
            for b in boxes
        ]
    ).astype(np.int32)
    return Z2QueryPlan(rzlo=zr[:, 0], rzhi=zr[:, 1], ixy=ixy, boxes=boxes)


@jax.jit
def _range_bounds(z, rzlo, rzhi):
    starts = jnp.searchsorted(z, rzlo, side="left")
    ends = jnp.searchsorted(z, rzhi, side="right")
    return starts, jnp.maximum(ends - starts, 0)


@partial(jax.jit, static_argnames=("capacity",))
def _scan_candidates(z, pos, x, y, starts, counts, ixy, boxes, capacity: int):
    idx, valid, _ = expand_ranges(starts, counts, capacity)
    zc = z[idx]
    posc = pos[idx]
    ix, iy = deinterleave2(zc.astype(jnp.uint64))
    ix = ix.astype(jnp.int64)
    iy = iy.astype(jnp.int64)
    in_box_int = (
        (ix[:, None] >= ixy[None, :, 0])
        & (iy[:, None] >= ixy[None, :, 1])
        & (ix[:, None] <= ixy[None, :, 2])
        & (iy[:, None] <= ixy[None, :, 3])
    ).any(axis=1)
    xc = x[posc]
    yc = y[posc]
    in_box_exact = (
        (xc[:, None] >= boxes[None, :, 0])
        & (yc[:, None] >= boxes[None, :, 1])
        & (xc[:, None] <= boxes[None, :, 2])
        & (yc[:, None] <= boxes[None, :, 3])
    ).any(axis=1)
    return posc, valid & in_box_int & in_box_exact


class Z2PointIndex:
    """Device-resident Z2 index over point features."""

    def __init__(self, z, pos, x, y):
        self.sfc: Z2SFC = z2_sfc()
        self.z = z
        self.pos = pos
        self.x = x
        self.y = y

    @classmethod
    def build(cls, x, y) -> "Z2PointIndex":
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        sfc = z2_sfc()
        xd = jnp.asarray(x)
        yd = jnp.asarray(y)
        z = jax.jit(lambda a, b: sfc.index(a, b))(xd, yd)
        order = jnp.argsort(z)
        return cls(z=z[order], pos=order.astype(jnp.int32), x=xd, y=yd)

    def __len__(self) -> int:
        return int(self.z.shape[0])

    def query(self, boxes, max_ranges: int = DEFAULT_MAX_RANGES) -> np.ndarray:
        """Original-order positions matching any of the bboxes, exactly."""
        plan = plan_z2_query(boxes, max_ranges)
        if plan.num_ranges == 0 or len(self) == 0:
            return np.empty(0, dtype=np.int64)
        starts, counts = _range_bounds(
            self.z, jnp.asarray(plan.rzlo), jnp.asarray(plan.rzhi)
        )
        total = int(jnp.sum(counts))
        if total == 0:
            return np.empty(0, dtype=np.int64)
        posc, mask = _scan_candidates(
            self.z, self.pos, self.x, self.y,
            starts, counts,
            jnp.asarray(plan.ixy), jnp.asarray(plan.boxes),
            capacity=gather_capacity(total),
        )
        posc = np.asarray(posc)
        mask = np.asarray(mask)
        return np.sort(posc[mask]).astype(np.int64)
