"""Sealed-generation partial cache — the ONE definition of the
LRU + byte-ceiling + compaction-invalidation policy the lean tiered
indexes use for immutable per-generation aggregation partials.

PR 1 proved the shape on density grids (a 13x warm speedup at 1B);
the stat-sketch push-down caches the same way (ISSUE 3), so the policy
lives here instead of being hand-copied per aggregate kind:

* a cache holds per-SPEC dicts of ``{gen_id: partial}`` — a spec is
  whatever hashable tuple identifies one aggregation (query window,
  grid, fold config, ...);
* spec dicts are LRU-ordered; looking one up touches it and evicts the
  oldest OTHER specs past ``max_specs``;
* inserts respect a TOTAL byte ceiling across all specs (a single
  huge-partial spec must bound its own growth, not just evict
  siblings) — partials expose ``nbytes``;
* compaction mints fresh gen_ids for merged runs and calls
  :meth:`drop_generations` with the dead ids, so stale partials can
  never double-count.

Only SEALED generations may cache: the live run mutates under appends,
so callers never insert it (the caller owns that gate — it knows which
generation is live).

The SPEC MAP is lock-guarded (ISSUE 13): scrape threads walk
:meth:`stats` while query threads touch/evict specs, and an unlocked
LRU reorder racing an eviction corrupts the dict order that IS the
policy.  The per-spec inner dicts handed out by :meth:`spec_cache`
stay caller-owned — a spec's partials are only populated from the
scan path that owns the index, and reads of immutable partials are
safe; the lock's job is the cross-thread map structure.
"""

from __future__ import annotations

import threading

__all__ = ["PartialCache"]


class PartialCache:
    """LRU-of-specs store of immutable per-sealed-generation partials
    (module doc).  Exposes a dict-like surface over the spec map
    (``len``/``values``/``clear``/iteration) so diagnostics and tests
    can inspect it directly."""

    def __init__(self, max_specs: int, max_bytes: int):
        self.max_specs = int(max_specs)
        self.max_bytes = int(max_bytes)
        #: guarded-by: self._lock — spec -> {gen_id: partial}; dict
        #: order IS the LRU order, and scrapers race queries on it
        self._specs: dict = {}
        self._lock = threading.Lock()

    # -- dict-like inspection surface ---------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._specs)

    def __iter__(self):
        with self._lock:
            return iter(list(self._specs))

    def values(self):
        with self._lock:
            return list(self._specs.values())

    def items(self):
        with self._lock:
            return list(self._specs.items())

    def clear(self) -> None:
        with self._lock:
            self._specs.clear()

    # -- policy --------------------------------------------------------
    # gm-lint: holds: self._lock (internal sum; public paths lock first)
    def _cached_bytes(self) -> int:
        return sum(p.nbytes for c in self._specs.values()
                   for p in c.values())

    def cached_bytes(self) -> int:
        with self._lock:
            return self._cached_bytes()

    def stats(self) -> dict:
        """Storage-accounting view (obs/resource.StorageReport): spec
        count, total cached partials, resident bytes, and the policy
        ceilings they are bounded by."""
        with self._lock:
            return {"specs": len(self._specs),
                    "partials": sum(len(c) for c in self._specs.values()),
                    "bytes": self._cached_bytes(),
                    "max_specs": self.max_specs,
                    "max_bytes": self.max_bytes}

    def spec_cache(self, spec) -> dict:
        """The per-generation partial dict for one spec, LRU-touched;
        oldest OTHER specs evict past ``max_specs`` or the byte
        ceiling (inserts enforce the ceiling against the active spec
        too — :meth:`add`)."""
        with self._lock:
            cache = self._specs.pop(spec, None)
            if cache is None:
                cache = {}
                while len(self._specs) >= self.max_specs:
                    self._specs.pop(next(iter(self._specs)))
            self._specs[spec] = cache
            while (len(self._specs) > 1
                   and self._cached_bytes() > self.max_bytes):
                self._specs.pop(next(iter(self._specs)))
            return cache

    def add(self, cache: dict, gen_id: int, part) -> None:
        """Insert one sealed-generation partial unless it would push
        the TOTAL cached bytes — every spec, including the active one —
        past the ceiling."""
        with self._lock:
            if self._cached_bytes() + part.nbytes <= self.max_bytes:
                cache[gen_id] = part

    def drop_generations(self, gen_ids) -> None:
        """Invalidate every partial of the given (compacted-away)
        generations across all specs."""
        with self._lock:
            for cache in self._specs.values():
                for gid in gen_ids:
                    cache.pop(gid, None)
