"""Sealed-generation partial cache — the ONE definition of the
LRU + byte-ceiling + compaction-invalidation policy the lean tiered
indexes use for immutable per-generation aggregation partials.

PR 1 proved the shape on density grids (a 13x warm speedup at 1B);
the stat-sketch push-down caches the same way (ISSUE 3), so the policy
lives here instead of being hand-copied per aggregate kind:

* a cache holds per-SPEC dicts of ``{gen_id: partial}`` — a spec is
  whatever hashable tuple identifies one aggregation (query window,
  grid, fold config, ...);
* spec dicts are LRU-ordered; looking one up touches it and evicts the
  oldest OTHER specs past ``max_specs``;
* inserts respect a TOTAL byte ceiling across all specs (a single
  huge-partial spec must bound its own growth, not just evict
  siblings) — partials expose ``nbytes``;
* compaction mints fresh gen_ids for merged runs and calls
  :meth:`drop_generations` with the dead ids, so stale partials can
  never double-count.

Only SEALED generations may cache: the live run mutates under appends,
so callers never insert it (the caller owns that gate — it knows which
generation is live)."""

from __future__ import annotations

__all__ = ["PartialCache"]


class PartialCache:
    """LRU-of-specs store of immutable per-sealed-generation partials
    (module doc).  Exposes a dict-like surface over the spec map
    (``len``/``values``/``clear``/iteration) so diagnostics and tests
    can inspect it directly."""

    def __init__(self, max_specs: int, max_bytes: int):
        self.max_specs = int(max_specs)
        self.max_bytes = int(max_bytes)
        #: spec -> {gen_id: partial}; dict order IS the LRU order
        self._specs: dict = {}

    # -- dict-like inspection surface ---------------------------------
    def __len__(self) -> int:
        return len(self._specs)

    def __iter__(self):
        return iter(self._specs)

    def values(self):
        return self._specs.values()

    def items(self):
        return self._specs.items()

    def clear(self) -> None:
        self._specs.clear()

    # -- policy --------------------------------------------------------
    def cached_bytes(self) -> int:
        return sum(p.nbytes for c in self._specs.values()
                   for p in c.values())

    def stats(self) -> dict:
        """Storage-accounting view (obs/resource.StorageReport): spec
        count, total cached partials, resident bytes, and the policy
        ceilings they are bounded by."""
        return {"specs": len(self._specs),
                "partials": sum(len(c) for c in self._specs.values()),
                "bytes": self.cached_bytes(),
                "max_specs": self.max_specs,
                "max_bytes": self.max_bytes}

    def spec_cache(self, spec) -> dict:
        """The per-generation partial dict for one spec, LRU-touched;
        oldest OTHER specs evict past ``max_specs`` or the byte
        ceiling (inserts enforce the ceiling against the active spec
        too — :meth:`add`)."""
        cache = self._specs.pop(spec, None)
        if cache is None:
            cache = {}
            while len(self._specs) >= self.max_specs:
                self._specs.pop(next(iter(self._specs)))
        self._specs[spec] = cache
        while (len(self._specs) > 1
               and self.cached_bytes() > self.max_bytes):
            self._specs.pop(next(iter(self._specs)))
        return cache

    def add(self, cache: dict, gen_id: int, part) -> None:
        """Insert one sealed-generation partial unless it would push
        the TOTAL cached bytes — every spec, including the active one —
        past the ceiling."""
        if self.cached_bytes() + part.nbytes <= self.max_bytes:
            cache[gen_id] = part

    def drop_generations(self, gen_ids) -> None:
        """Invalidate every partial of the given (compacted-away)
        generations across all specs."""
        for cache in self._specs.values():
            for gid in gen_ids:
                cache.pop(gid, None)
