"""Per-generation multi-resolution density pyramids (ISSUE 18).

Sealed generations are immutable — the invariant the density-partial
and sketch caches already exploit — so the whole-extent aggregation
work for the sealed ~99% of a tiered store can be done ONCE at
seal/compaction time and reused by every subsequent bbox/zoom request:
a :class:`DensityPyramid` is a stack of power-of-two world-aligned
density grids (``base × base`` halving down to ``1 × 1``), one per
generation, built from the generation's keys by the existing
whole-extent sweep kernels plus the jitted 2×2 reduction ladder
(``ops/density.pyramid_reduce``).

Exactness: the base grid IS the generation's ``("sweep", world, base,
base)`` density partial (integer counts carried in float64), and each
ladder level is an exact 2×2 block sum — summing 2×2 blocks of a
``(2w, 2w)`` world grid equals binning the raw points at ``(w, w)``
(the ``(ix * width) >> precision`` world binning halves exactly), so a
pyramid-served grid is bit-identical to what the direct scan produces
at the same resolution.  Requests finer than the pyramid base fall
back to the direct scan path (the fallback contract in
docs/density.md).

Pyramids cache through the shared
:class:`~geomesa_tpu.index.partial_cache.PartialCache` policy
(LRU + byte ceiling + compaction invalidation); compaction-merged
generations inherit by SUMMING their parents' pyramids, mirroring
``HeatTracker.merge_generations``.
"""

from __future__ import annotations

import numpy as np

__all__ = ["DensityPyramid", "PYRAMID_SPEC", "density_tile",
           "pyramid_spec", "tile_env", "tile_grid_res"]

#: world extent every pyramid is aligned to (matches the lean sweep's
#: ``_WORLD_ENV`` — pyramids are whole-world, whole-time by design)
_WORLD = (-180.0, -90.0, 180.0, 90.0)

#: PartialCache spec-key TAG for pyramid entries — the full spec is
#: ``(PYRAMID_SPEC, base)`` so pyramids built at different base
#: resolutions coexist without colliding
PYRAMID_SPEC = "pyramid"


def pyramid_spec(base: int) -> tuple:
    return (PYRAMID_SPEC, int(base))


class DensityPyramid:
    """One sealed generation's density pyramid: a dict of square
    float64 world grids keyed by width (``base`` down the 2×2 ladder).
    Exposes ``nbytes`` (the PartialCache byte-ceiling contract) and
    elementwise :meth:`sum` for compaction inheritance."""

    __slots__ = ("levels",)

    def __init__(self, levels: dict[int, np.ndarray]):
        self.levels = levels

    @classmethod
    def from_base(cls, base_grid: np.ndarray, levels: int = 0
                  ) -> "DensityPyramid":
        """Build the full pyramid from a square pow2 base grid using
        the numpy reduction twin (the device ladder path passes its
        already-reduced levels to ``__init__`` directly).  ``levels``
        0 = the full ladder down to 1×1."""
        from ..ops.density import pyramid_reduce_np
        base_grid = np.asarray(base_grid, np.float64)
        w = base_grid.shape[0]
        depth = _ladder_depth(w, levels)
        out = {w: base_grid}
        for g in pyramid_reduce_np(base_grid, depth):
            out[g.shape[0]] = np.asarray(g, np.float64)
        return cls(out)

    @property
    def nbytes(self) -> int:
        return sum(g.nbytes for g in self.levels.values())

    @property
    def base(self) -> int:
        return max(self.levels)

    def level(self, width: int):
        """The (width, width) grid, or None when the ladder doesn't
        carry that resolution."""
        return self.levels.get(int(width))

    @staticmethod
    def sum(pyramids: list["DensityPyramid"]) -> "DensityPyramid | None":
        """Elementwise sum for compaction inheritance — defined only
        when every parent carries the same level set (None otherwise;
        the caller falls back to rebuilding from the merged keys)."""
        if not pyramids:
            return None
        widths = set(pyramids[0].levels)
        if any(set(p.levels) != widths for p in pyramids[1:]):
            return None
        return DensityPyramid({
            w: np.sum([p.levels[w] for p in pyramids], axis=0)
            for w in widths})


def _ladder_depth(base: int, levels: int) -> int:
    """Reduction steps below the base: ``levels`` when positive, else
    the full ladder down to 1×1 (log2 of the base)."""
    full = max(0, int(base).bit_length() - 1)
    return min(full, int(levels)) if int(levels) > 0 else full


def tile_grid_res(z: int, tile: int) -> int:
    """World grid resolution (cells per axis) a ``/tiles/{z}/..``
    request needs: ``tile · 2^z``."""
    return int(tile) << int(z)


def tile_env(z: int, x: int, y: int) -> tuple:
    """The (xmin, ymin, xmax, ymax) world envelope of slippy tile
    (z, x, y) on the plate-carrée grid this store serves (world split
    into 2^z × 2^z equal-degree tiles; y=0 is the NORTH row, matching
    the slippy-map convention, while grid row 0 is south)."""
    n = 1 << int(z)
    dx = 360.0 / n
    dy = 180.0 / n
    return (-180.0 + x * dx, -90.0 + (n - 1 - y) * dy,
            -180.0 + (x + 1) * dx, -90.0 + (n - y) * dy)


def density_tile(index, z: int, x: int, y: int, tile: int = 256,
                 max_ranges: int = 2000) -> np.ndarray:
    """One (tile, tile) density grid for slippy tile (z, x, y), served
    off a lean z3-family index (single-chip or sharded — anything with
    the ``density(boxes, lo, hi, env, w, h)`` push-down surface).

    While the needed world resolution ``tile·2^z`` stays at/below the
    configured pyramid base, the tile is a SLICE of the whole-world
    whole-time density at that resolution — the path the sealed
    generations' cached pyramids serve without scanning (the live run
    and any pyramid-less generation still sweep; results never
    change).  Finer zooms fall back to the direct bbox density scan
    over just the tile's envelope, under the cell-granularity contract
    of docs/density.md."""
    from ..config import DensityProperties
    from ..metrics import PYRAMID_SERVE_FALLBACKS, registry as _metrics
    n = 1 << int(z)
    res = tile_grid_res(z, tile)
    base = DensityProperties.PYRAMID_BASE.to_int()
    if res <= base and tile & (tile - 1) == 0:
        grid = index.density([_WORLD], None, None, _WORLD, res, res,
                             max_ranges=max_ranges)
        return np.asarray(grid, np.float64)[
            (n - 1 - y) * tile:(n - y) * tile,
            x * tile:(x + 1) * tile]
    _metrics.counter(PYRAMID_SERVE_FALLBACKS).inc()
    env = tile_env(z, x, y)
    return np.asarray(
        index.density([env], None, None, env, tile, tile,
                      max_ranges=max_ranges), np.float64)
