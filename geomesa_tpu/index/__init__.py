"""Feature indexes: sorted SoA device-resident index structures.

The TPU-native replacement for the reference's index layer
(geomesa-index-api): instead of writing ``[shard][bin][z][id]`` rows into a
distributed sorted KV store, each index keeps lexicographically sorted key
columns (plus a permutation into the feature columns) resident in device
HBM; queries decompose filters into key ranges on host and evaluate
seek + candidate-filter as fused array kernels on device.
"""

from .registry import (
    IndexDescriptor, available_indices, get_index, register_index,
    supported_indices,
)
from .z2 import Z2PointIndex
from .z3 import Z3PointIndex

__all__ = [
    "Z2PointIndex", "Z3PointIndex", "IndexDescriptor", "register_index",
    "get_index", "available_indices", "supported_indices",
]
