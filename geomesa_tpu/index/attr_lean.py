"""LeanAttrIndex: tiered generational attribute index for lean schemas.

The round-4 lean profile served ``{z3, id}`` only, so an
attribute-only ECQL on a 1B-row store degraded to a full host scan and
an attribute-selective + spatially-wide query gathered every spatial
candidate first.  The reference serves these from the lexicoded
attribute index with cost-based selection at any scale
(geomesa-index-api/.../index/attribute/AttributeIndexKey.scala:38-52,
.../strategies/AttributeFilterStrategy.scala); this module is that
index re-expressed in the lean profile's terms (round-4 VERDICT #1).

**Key layout.**  Sorted GENERATIONS (LSM runs, exactly the
:class:`~geomesa_tpu.index.z3_lean.LeanZ3Index` shape) of

    ``(key int64, sec int64, gid int32)``  — 20 B/row

where ``key`` is an ORDER-PRESERVING int64 encoding of the attribute
value (the lexicode analog of ``AttributeIndexKey.typeRegistry``):

* ints/longs/dates — the value itself (exact);
* floats/doubles — the IEEE-754 order-preserving bit transform (exact);
* strings — the first 8 UTF-8 bytes big-endian (a PREFIX code: ties
  share a key and the planner's residual filter disambiguates — the
  same candidate-superset contract every index here honors).

``sec`` is the epoch-millis dtg — the reference's date secondary tier
(``DateIndexKeySpace``): because runs sort by ``(key, sec)``, an
equality/IN lookup with a time window seeks the sub-range directly
(two-key :func:`~geomesa_tpu.ops.search.searchsorted2` — the same
kernel the z3 index seeks with).  Range/prefix scans span many value
runs and pass an open ``sec`` window, as in the reference.

**Tiers.**  ``device`` generations hold the three columns in HBM
(demoted oldest-first under ``hbm_budget_bytes``); ``host`` generations
spill to RAM and seek through one stacked vectorized bisection, flat in
run count (the :class:`~geomesa_tpu.index.z3_lean.HostStack`
discipline).  There is no ``full`` tier: the encoded key IS the
payload, so the device seek is already as exact as the encoding allows.

Queries batch every (window × generation) into a fixed number of
dispatches: one totals probe + one gather over all device generations,
bucket-padded with a shared empty sentinel generation.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..metrics import (RESILIENCE_DEGRADED, RESILIENCE_RETRIES,
                       WRITE_SEALS, WRITE_SPILLS)
from ..obs import device_span, obs_count, span as obs_span
from ..obs.heat import (
    heat_enabled, merge_index_generations, record_index_scan,
)
from ..ops.search import (
    coded_pos_bits, expand_ranges, gather_capacity, pad_pow2,
    searchsorted2, wire_dtype,
)

__all__ = ["LeanAttrIndex", "encode_attr_values", "encode_attr_value"]

_SENTINEL_KEY = np.int64(np.iinfo(np.int64).max)
_I64_MIN = np.int64(np.iinfo(np.int64).min)
_I64_MAX = np.int64(np.iinfo(np.int64).max)

#: per-slot bytes: key int64 + sec int64 + gid int32
SLOT_BYTES = 8 + 8 + 4

#: generation-count compile bucket for the multi-generation programs
#: (the z3_lean._GEN_BUCKET discipline)
_GEN_BUCKET = 4

#: attribute types served by the int64 lexicode (AttributeIndexKey's
#: typeRegistry analog); geometry/bytes/json are not indexable here,
#: matching the reference's indexable-type set
_NUMERIC_TYPES = {"int", "integer", "long", "float", "double", "date"}


def _encode_float64(vals: np.ndarray) -> np.ndarray:
    """IEEE-754 double → order-preserving signed int64 (NaNs sort
    last)."""
    v = np.ascontiguousarray(vals, np.float64) + 0.0   # -0.0 → +0.0
    bits = v.view(np.int64)
    # negative floats (sign bit set): map reversed into [-2^63, -1];
    # positives keep their bits — order-preserving in the signed view
    return np.where(bits < 0, np.int64(-1) - (bits ^ _I64_MIN), bits)


def _encode_strings(vals: np.ndarray) -> np.ndarray:
    """First 8 UTF-8 bytes, big-endian, as signed int64 — a prefix code
    (lexicographic byte order == unsigned integer order; shifting by
    2^63 makes it signed-comparable).  ``None`` encodes as the EMPTY
    key on both paths: the fast ``astype('S8')`` path would stringify
    it to ``b'None'`` while the unicode fallback yields ``b''`` — the
    candidate set of an equality query must not depend on which path a
    batch happened to take."""
    arr = np.asarray(vals)
    if arr.dtype == object:
        none_mask = arr == np.array(None)
        if none_mask.any():
            arr = arr.copy()
            arr[none_mask] = ""
    try:
        raw = arr.astype("S8")           # ASCII fast path (truncating)
    except UnicodeEncodeError:
        raw = np.array([("" if v is None else str(v)).encode("utf-8")[:8]
                        for v in arr], dtype="S8")
    u = np.ascontiguousarray(raw).view(">u8").astype(np.uint64).ravel()
    return (u ^ np.uint64(1 << 63)).view(np.int64)


def encode_attr_values(vals: np.ndarray, attr_type: str) -> np.ndarray:
    """Vectorized order-preserving int64 encoding for one column.

    Keys clamp to ``int64 max - 1``: the sentinel padding key is int64
    max, and a real key equal to it would let open-ended range seeks
    sweep every generation's padding into the candidate buffer.  The
    clamp aliases only the two topmost encodable values — a candidate
    superset the residual filter resolves, like string prefix ties."""
    t = attr_type.lower()
    if t in ("int", "integer", "long", "date"):
        keys = np.ascontiguousarray(vals, np.int64)
    elif t in ("float", "double"):
        keys = _encode_float64(np.asarray(vals, np.float64))
    elif t == "string":
        keys = _encode_strings(vals)
    else:
        raise TypeError(f"attribute type {attr_type!r} is not indexable "
                        "on a lean schema (indexable: numerics, dates, "
                        "strings)")
    return np.minimum(keys, _SENTINEL_KEY - 1)


def encode_attr_value(v, attr_type: str) -> np.int64:
    """Scalar twin of :func:`encode_attr_values` (query planning)."""
    return np.int64(encode_attr_values(np.array([v]), attr_type)[0])


def string_prefix_bounds(prefix: str) -> tuple[np.int64, np.int64]:
    """Inclusive key bounds covering every string starting with
    ``prefix`` (for LIKE 'abc%': [code(prefix·00…), code(prefix·ff…)])."""
    b = prefix.encode("utf-8")[:8]
    lo = int.from_bytes(b.ljust(8, b"\x00"), "big")
    hi = int.from_bytes(b.ljust(8, b"\xff"), "big")
    u = np.array([lo, hi], dtype=np.uint64) ^ np.uint64(1 << 63)
    s = u.view(np.int64)
    return np.int64(s[0]), np.int64(min(s[1], _SENTINEL_KEY - 1))


@partial(jax.jit, donate_argnums=(0, 1, 2))
def _attr_append(keys, sec, gid, r, new_k, new_s, new_g, m):
    """Merge one encoded slice into the generation's sentinel padding at
    sorted offset ``r`` and re-sort (donated: peak = resident + sort
    temps)."""
    valid = jnp.arange(new_k.shape[0]) < m
    k_new = jnp.where(valid, new_k, _SENTINEL_KEY)
    s_new = jnp.where(valid, new_s, jnp.int64(_I64_MAX))
    g_new = jnp.where(valid, new_g, jnp.int32(-1))
    keys = jax.lax.dynamic_update_slice(keys, k_new, (r,))
    sec = jax.lax.dynamic_update_slice(sec, s_new, (r,))
    gid = jax.lax.dynamic_update_slice(gid, g_new, (r,))
    return jax.lax.sort((keys, sec, gid), dimension=0, num_keys=2)


@jax.jit
def _attr_count_multi(qklo, qkhi, qslo, qshi, *cols):
    """Totals probe over every device generation in ONE dispatch."""
    outs = []
    for g in range(len(cols) // 2):
        k, s = cols[2 * g], cols[2 * g + 1]
        starts = searchsorted2(k, s, qklo, qslo, side="left")
        ends = searchsorted2(k, s, qkhi, qshi, side="right")
        outs.append(jnp.sum(jnp.maximum(ends - starts, 0)))
    return jnp.stack(outs)


@partial(jax.jit, static_argnames=("capacity", "pos_bits"))
def _attr_scan_coded(qklo, qkhi, qslo, qshi, qqid, *cols,
                     capacity: int, pos_bits: int):
    """Candidate gather over device generations in ONE dispatch,
    coded ``qid << pos_bits | gid``."""
    dt = wire_dtype(pos_bits)
    outs = []
    for g in range(len(cols) // 3):
        k, s, gid = cols[3 * g], cols[3 * g + 1], cols[3 * g + 2]
        starts = searchsorted2(k, s, qklo, qslo, side="left")
        ends = searchsorted2(k, s, qkhi, qshi, side="right")
        counts = jnp.maximum(ends - starts, 0)
        idx, valid, rid = expand_ranges(starts, counts, capacity)
        coded = ((qqid[rid].astype(dt) << dt(pos_bits))
                 | gid[idx].astype(dt))
        outs.append(jnp.where(valid, coded, dt(-1)))
    return jnp.stack(outs)


@partial(jax.jit, static_argnames=("out_cap",))
def _attr_merge(*cols, out_cap: int):
    """COMPACTION merge: fold K sorted (key, sec, gid) runs into ONE
    sorted run in a single dispatch — lax.sort over the concatenation
    floats every sentinel slot past the ``out_cap`` (= total valid)
    leading rows, so the merged run carries zero padding and releases
    the source runs' slack slots (the z3_lean._lean_merge_keys shape)."""
    k = len(cols) // 3
    keys = jnp.concatenate([cols[3 * i] for i in range(k)])
    sec = jnp.concatenate([cols[3 * i + 1] for i in range(k)])
    gid = jnp.concatenate([cols[3 * i + 2] for i in range(k)])
    keys, sec, gid = jax.lax.sort((keys, sec, gid), dimension=0,
                                  num_keys=2)
    return keys[:out_cap], sec[:out_cap], gid[:out_cap]


def merge_spilled_parts(parts: list[list]) -> list:
    """COMPACTION merge for spilled (key, sec, gid) runs: composite
    lexsort over the concatenation — the host twin of
    :func:`_attr_merge`.  Returns a fresh mutable part list (the
    _HostAttrStack re-pointing contract)."""
    k = np.concatenate([np.asarray(p[0]) for p in parts])
    s = np.concatenate([np.asarray(p[1]) for p in parts])
    g = np.concatenate([np.asarray(p[2]) for p in parts])
    order = np.lexsort((s, k))
    return [np.ascontiguousarray(k[order]),
            np.ascontiguousarray(s[order]),
            np.ascontiguousarray(g[order])]


@partial(jax.jit, static_argnames=("bins", "depth", "width", "is_float"))
def _attr_sketch_multi(slo, shi, hlo, hhi, *cols, bins: int, depth: int,
                       width: int, is_float: bool):
    """Stat-sketch fold over EVERY device generation in ONE dispatch
    (ISSUE 3): per run, the shared :func:`stats.sketch.device_fold_body`
    decodes the sorted keys and folds masked moments / histogram /
    count-min partials — only the tiny stacked partials cross the wire,
    never a key or candidate."""
    from ..stats.sketch import device_fold_body
    outs: list[list] = [[], [], [], [], [], [], []]
    for g in range(len(cols) // 2):
        res = device_fold_body(cols[2 * g], cols[2 * g + 1], slo, shi,
                               hlo, hhi, bins=bins, depth=depth,
                               width=width, is_float=is_float)
        for acc, r in zip(outs, res):
            acc.append(r)
    return tuple(jnp.stack(a) for a in outs)


def _bisect2(k: np.ndarray, s: np.ndarray, qk: np.ndarray,
             qs: np.ndarray, lo: np.ndarray, hi: np.ndarray,
             side: str) -> np.ndarray:
    """Vectorized composite-key binary search of ``(qk, qs)[i]`` within
    the (key, sec)-sorted segments ``[lo[i], hi[i])`` — the host-tier
    twin of :func:`~geomesa_tpu.ops.search.searchsorted2`, one bisection
    pass for every (range × run) pair (flat in run count)."""
    lo = lo.astype(np.int64).copy()
    hi = hi.astype(np.int64).copy()
    while True:
        active = lo < hi
        if not active.any():
            return lo
        mid = (lo + hi) >> 1
        safe = np.where(active, mid, 0)
        km, sm = k[safe], s[safe]
        if side == "left":
            below = (km < qk) | ((km == qk) & (sm < qs))
        else:
            below = (km < qk) | ((km == qk) & (sm <= qs))
        go = active & below
        lo = np.where(go, mid + 1, lo)
        hi = np.where(active & ~below, mid, hi)


class _HostAttrStack:
    """Spilled (key, sec, gid) runs stacked contiguously: each run is
    one segment; one composite bisection pass per query batch serves
    every host generation.  The stack OWNS the concatenated arrays —
    each constituent part (a mutable ``[k, s, g]`` list) is re-pointed
    at views into them so host RAM holds ONE copy of the spilled runs
    (the HostStack discipline; review r5)."""

    __slots__ = ("k", "s", "gid", "seg_lo", "seg_hi")

    def __init__(self, parts: list[list]):
        ks, ss, gs, lo, hi = [], [], [], [], []
        off = 0
        for k, s, g in parts:
            ks.append(k)
            ss.append(s)
            gs.append(g)
            lo.append(off)
            hi.append(off + len(k))
            off += len(k)
        self.k = np.concatenate(ks) if ks else np.empty(0, np.int64)
        self.s = np.concatenate(ss) if ss else np.empty(0, np.int64)
        self.gid = np.concatenate(gs) if gs else np.empty(0, np.int64)
        self.seg_lo = np.asarray(lo, np.int64)
        self.seg_hi = np.asarray(hi, np.int64)
        off = 0
        for part in parts:
            n = len(part[0])
            part[0] = self.k[off:off + n]
            part[1] = self.s[off:off + n]
            part[2] = self.gid[off:off + n]
            off += n

    def candidates(self, qklo, qkhi, qslo, qshi, qqid,
                   pos_bits: int) -> np.ndarray:
        if not len(self.k) or not len(qklo):
            return np.empty(0, np.int64)
        n_seg = len(self.seg_lo)
        n_q = len(qklo)
        # every (range × run) pair — runs are few (spilled generations)
        rid = np.repeat(np.arange(n_q), n_seg)
        seg = np.tile(np.arange(n_seg), n_q)
        lo0, hi0 = self.seg_lo[seg], self.seg_hi[seg]
        starts = _bisect2(self.k, self.s, qklo[rid], qslo[rid],
                          lo0, hi0, side="left")
        ends = _bisect2(self.k, self.s, qkhi[rid], qshi[rid],
                        lo0, hi0, side="right")
        cnt = np.maximum(ends - starts, 0)
        cum = np.cumsum(cnt)
        total = int(cum[-1]) if len(cum) else 0
        if total == 0:
            return np.empty(0, np.int64)
        j = np.arange(total)
        pid = np.searchsorted(cum, j, side="right")
        prev = np.where(pid > 0, cum[pid - 1], 0)
        idx = starts[pid] + (j - prev)
        return ((qqid[rid[pid]].astype(np.int64) << pos_bits)
                | self.gid[idx].astype(np.int64))


class _AttrGeneration:
    __slots__ = ("keys", "sec", "gid", "n", "tier", "spilled", "gen_id")

    @classmethod
    def merged_device(cls, keys, sec, gid, n: int) -> "_AttrGeneration":
        """A compacted device run from already-merged columns (length
        == n: zero sentinel padding)."""
        gen = cls.__new__(cls)
        gen.keys, gen.sec, gen.gid = keys, sec, gid
        gen.n = int(n)
        gen.tier = "device"
        gen.spilled = None
        gen.gen_id = -1
        return gen

    @classmethod
    def merged_host(cls, part: list) -> "_AttrGeneration":
        """A compacted host run from an already-merged spilled part."""
        gen = cls.__new__(cls)
        gen.keys = gen.sec = gen.gid = None
        gen.n = len(part[0])
        gen.tier = "host"
        gen.spilled = part
        gen.gen_id = -1
        return gen

    def __init__(self, capacity: int):
        self.keys = jnp.full((capacity,), _SENTINEL_KEY, jnp.int64)
        self.sec = jnp.full((capacity,), _I64_MAX, jnp.int64)
        self.gid = jnp.full((capacity,), -1, jnp.int32)
        self.n = 0
        self.tier = "device"
        self.spilled: tuple | None = None
        #: store-lifetime-unique run identity (assigned by the owning
        #: index; compaction mints fresh ids for merged runs — the
        #: sketch-partial cache invalidation key, like z3_lean)
        self.gen_id = -1

    @property
    def capacity(self) -> int:
        return int(self.keys.shape[0])

    def device_bytes(self) -> int:
        return 0 if self.tier == "host" else self.capacity * SLOT_BYTES

    def spill_to_host(self) -> None:
        if self.tier != "device":
            return
        # a mutable list: _HostAttrStack re-points it at views of the
        # stacked buffers so only one host copy survives
        self.spilled = [np.asarray(self.keys)[:self.n],
                        np.asarray(self.sec)[:self.n],
                        np.asarray(self.gid)[:self.n]]
        self.keys = self.sec = self.gid = None
        self.tier = "host"


class LeanAttrIndex:
    """Tiered generational attribute index (see module doc).

    ``queries`` take lists of inclusive int64 key ranges with optional
    per-range sec windows; results are CANDIDATE gids (the planner's
    residual filter makes them exact, as for every index here)."""

    #: ``(schema, index_key)`` for access-temperature attribution
    #: (obs/heat) — stamped by the datastore / the owning XZ facade
    heat_scope: tuple | None = None

    @staticmethod
    def gather_payload(positions):
        """Result-materialization protocol hook (ISSUE 14, uniform
        across the lean index families): the attribute runs hold
        LEXICODED keys — not a row-addressable payload — so there is
        nothing to gather on device; ``None`` tells the Arrow result
        path to take every column from the host column store (one
        vectorized numpy take per column).  The schema's SCALE index
        (z3) still device-gathers x/y/t for attr-strategy queries."""
        return None

    GENERATION_SLOTS = 1 << 24
    DEFAULT_CAPACITY = 1 << 15
    BATCH_SCAN_BUDGET = 1 << 26
    #: default HBM budget — the store splits its lean budget between
    #: the z3 index and the attribute indexes (docs/scale.md)
    HBM_BUDGET_BYTES = int(2.0 * 2 ** 30)
    #: size-tiered compaction trigger (explicit compact() default; pass
    #: compaction_factor=F to run it opportunistically after appends) —
    #: the index/z3_lean.LeanZ3Index policy on the attribute runs
    COMPACTION_FACTOR = 4
    #: distinct sketch-fold specs whose per-sealed-run partials are
    #: retained (LRU; the density-cache policy on sketch partials —
    #: each partial is a handful of scalars + small hist/cms tables)
    SKETCH_CACHE_SPECS = 8
    #: host-RAM ceiling across all cached sketch specs
    SKETCH_CACHE_MAX_BYTES = 64 * 2 ** 20

    def __init__(self, attr: str, attr_type: str,
                 generation_slots: int | None = None,
                 hbm_budget_bytes: int | None = None,
                 compaction_factor: int | None = None):
        self.attr = attr
        self.attr_type = attr_type.lower()
        if self.attr_type not in _NUMERIC_TYPES | {"string"}:
            raise TypeError(
                f"attribute {attr!r}: type {attr_type!r} is not "
                "indexable on a lean schema")
        self.generation_slots = generation_slots or self.GENERATION_SLOTS
        self.hbm_budget_bytes = hbm_budget_bytes or self.HBM_BUDGET_BYTES
        self.generations: list[_AttrGeneration] = []
        self._host_stack: _HostAttrStack | None = None
        self._n_rows = 0
        self.dispatch_count = 0
        self._sentinel: tuple | None = None
        #: opportunistic compaction factor (0 = off)
        self.compaction_factor = int(compaction_factor or 0)
        self.compactions = 0
        #: sealed-run sketch partials: fold spec → {gen_id: RunSketch}
        #: (the z3_lean density-cache policy — index/partial_cache)
        from .partial_cache import PartialCache
        self._sketch_cache = PartialCache(self.SKETCH_CACHE_SPECS,
                                          self.SKETCH_CACHE_MAX_BYTES)
        #: generation-lifecycle hooks ``(kind, gen_ids)`` fired on
        #: seal/merge (index/lsm.notify_generation_event)
        self.generation_listeners: list = []
        #: store-lifetime run-id source (see _AttrGeneration.gen_id)
        self._gen_counter = 0

    def _next_gen_id(self) -> int:
        self._gen_counter += 1
        return self._gen_counter

    def _roll_generation(self) -> "_AttrGeneration":
        """Open a fresh live generation and rebalance (the append
        rollover body, factored so the seal span wraps it once)."""
        gen = _AttrGeneration(self.generation_slots)
        gen.gen_id = self._next_gen_id()
        self.generations.append(gen)
        self._rebalance()
        return self.generations[-1]

    def __len__(self) -> int:
        return self._n_rows

    def device_bytes(self) -> int:
        return sum(g.device_bytes() for g in self.generations)

    def host_key_bytes(self) -> int:
        """Host RAM held by spilled (``host``-tier) runs — key + sec +
        gid per valid row (no padding survives a spill)."""
        return sum(g.n * SLOT_BYTES for g in self.generations
                   if g.tier == "host")

    def sentinel_bytes(self) -> int:
        """HBM of the lazily-allocated padding sentinel columns."""
        return (0 if self._sentinel is None
                else self.generation_slots * SLOT_BYTES)

    def tier_counts(self) -> dict:
        out = {"device": 0, "host": 0}
        for g in self.generations:
            out[g.tier] += 1
        return out

    def storage_stats(self) -> dict:
        """Live byte accounting for the storage report (obs/resource,
        ISSUE 9) — see LeanZ3Index.storage_stats; same contract over
        the (key, sec, gid) runs."""
        gens = [{"gen_id": g.gen_id, "tier": g.tier, "rows": int(g.n),
                 "capacity": 0 if g.tier == "host" else g.capacity,
                 "device_bytes": g.device_bytes(),
                 "host_bytes": (g.n * SLOT_BYTES
                                if g.tier == "host" else 0)}
                for g in self.generations]
        return {"kind": type(self).__name__, "rows": len(self),
                "attr": self.attr,
                "tiers": self.tier_counts(),
                "device_bytes": self.device_bytes(),
                "host_bytes": self.host_key_bytes(),
                "sentinel_bytes": self.sentinel_bytes(),
                "hbm_budget_bytes": self.hbm_budget_bytes,
                "generations": gens,
                "caches": {"sketch": self._sketch_cache.stats()},
                "dispatches": self.dispatch_count}

    def block(self) -> None:
        for gen in reversed(self.generations):
            if gen.tier == "device":
                jax.block_until_ready(gen.gid)
                break

    # -- write path -------------------------------------------------------
    def _sentinel_cols(self):
        if self._sentinel is None:
            slots = self.generation_slots
            self._sentinel = (
                jnp.full((slots,), _SENTINEL_KEY, jnp.int64),
                jnp.full((slots,), _I64_MAX, jnp.int64),
                jnp.full((slots,), -1, jnp.int32))
        return self._sentinel

    def _budget_after_sentinels(self) -> int:
        return (self.hbm_budget_bytes
                - self.generation_slots * SLOT_BYTES)

    def _rebalance(self) -> None:
        """Spill oldest-first until device residency (plus the sentinel
        padding buffer) fits the budget; the ACTIVE generation never
        spills (appends sort there)."""
        for gen in self.generations[:-1]:
            if self.device_bytes() <= self._budget_after_sentinels():
                return
            if gen.tier == "device":
                # blocking device→host transfer — traced with honest
                # block-until-ready ms (the write-span taxonomy)
                with device_span("write.spill", gen_id=gen.gen_id,
                                 rows=int(gen.n)):
                    obs_count(WRITE_SPILLS)
                    gen.spill_to_host()
                self._host_stack = None
        if self.device_bytes() > self._budget_after_sentinels():
            raise MemoryError(
                f"active attr generation ({self.generation_slots} "
                f"slots) exceeds hbm_budget_bytes="
                f"{self.hbm_budget_bytes}")

    def append(self, values, dtg_ms, base_gid: int | None = None
               ) -> "LeanAttrIndex":
        """Stream one column slice in: encode keys, merge into the
        current generation (rolling on full).  ``base_gid`` defaults to
        the running row count (the lean store's implicit ids)."""
        keys = encode_attr_values(values, self.attr_type)
        sec = np.ascontiguousarray(dtg_ms, np.int64)
        base = self._n_rows if base_gid is None else int(base_gid)
        if base + len(keys) > np.iinfo(np.int32).max:
            raise ValueError("LeanAttrIndex gids are int32: 2,147M rows "
                             "max per index/shard")
        m_total = len(keys)
        done = 0
        while done < m_total:
            gen = (self.generations[-1] if self.generations else None)
            if gen is None or gen.tier == "host" or gen.n >= gen.capacity:
                if gen is not None and gen.tier != "host":
                    # live run seals on rollover (write-span taxonomy)
                    sealed_id = gen.gen_id
                    with obs_span("write.seal", gen_id=gen.gen_id,
                                  tier=gen.tier, rows=int(gen.n)):
                        obs_count(WRITE_SEALS)
                        gen = self._roll_generation()
                    from .lsm import notify_generation_event
                    notify_generation_event(self, "seal", [sealed_id])
                else:
                    gen = self._roll_generation()
            room = gen.capacity - gen.n
            take = min(room, m_total - done)
            m_pad = min(gather_capacity(take, minimum=8), room)
            sl = slice(done, done + take)
            pad = m_pad - take
            gids = (base + done
                    + np.arange(take, dtype=np.int32)).astype(np.int32)
            self.dispatch_count += 1
            gen.keys, gen.sec, gen.gid = _attr_append(
                gen.keys, gen.sec, gen.gid, jnp.int32(gen.n),
                jnp.asarray(np.pad(keys[sl], (0, pad))),
                jnp.asarray(np.pad(sec[sl], (0, pad))),
                jnp.asarray(np.pad(gids, (0, pad))),
                jnp.int32(take))
            gen.n += take
            done += take
        self._n_rows += m_total
        if self.compaction_factor:
            # bounded opportunistic trigger: one merge group per append
            self.compact(factor=self.compaction_factor, max_groups=1)
        return self

    # -- compaction (LSM maintenance) -------------------------------------
    def _compaction_groups(self, factor: int) -> list[list]:
        from .lsm import plan_size_tiered
        return plan_size_tiered(self.generations[:-1],
                                ("device", "host"), lambda g: g.n,
                                factor)

    def _merge_group(self, group: list) -> None:
        from .lsm import merged_capacity, replace_group
        total = int(sum(g.n for g in group))
        if group[0].tier == "device":
            cols: list = []
            for g in group:
                cols += [g.keys, g.sec, g.gid]
            out_cap = merged_capacity(
                total, sum(g.capacity for g in group), gather_capacity)
            self.dispatch_count += 1
            keys, sec, gid = _attr_merge(*cols, out_cap=out_cap)
            merged = _AttrGeneration.merged_device(keys, sec, gid,
                                                   n=total)
        else:
            merged = _AttrGeneration.merged_host(
                merge_spilled_parts([g.spilled for g in group]))
            self._host_stack = None   # restacked lazily
        merged.gen_id = self._next_gen_id()
        # stale sketch partials must never double-count (the density
        # cache's compaction-mints-new-generation invalidation)
        dead_ids = [g.gen_id for g in group]
        self._sketch_cache.drop_generations(dead_ids)
        # merged run inherits its sources' access temperature —
        # BEFORE the swap, so a racing heat report's stale-entry
        # prune sees the fresh merged entry (grace window), never
        # the long-cold dead ids
        merge_index_generations(self, dead_ids, merged.gen_id)
        self.generations = replace_group(self.generations, group,
                                         merged)
        self.compactions += 1
        from ..metrics import (
            LEAN_COMPACTION_MERGES, LEAN_COMPACTION_ROWS,
            registry as _metrics,
        )
        _metrics.counter(LEAN_COMPACTION_MERGES).inc()
        _metrics.counter(LEAN_COMPACTION_ROWS).inc(total)
        from .lsm import notify_generation_event
        notify_generation_event(self, "merge", [merged.gen_id])

    def compact(self, budget_ms: float | None = None,
                factor: int | None = None,
                max_groups: int | None = None) -> dict:
        """Incremental size-tiered merge compaction over the attribute
        runs — merge one group, re-plan, stop past ``budget_ms`` or
        ``max_groups`` (≥ 1 group of progress per call; resumes on the
        next — index/lsm.py).  Candidate sets are identical at every
        intermediate state."""
        from .lsm import compact_incremental
        f = int(factor or self.compaction_factor
                or self.COMPACTION_FACTOR)
        merged = compact_incremental(
            lambda: self._compaction_groups(f), self._merge_group,
            budget_ms=budget_ms, max_groups=max_groups)
        if merged:
            self._rebalance()
        return {"merged_groups": merged,
                "generations": len(self.generations),
                "tiers": self.tier_counts()}

    # -- stat-sketch push-down (ISSUE 3) ----------------------------------
    def sketch_scan(self, fold) -> "RunSketch":
        """Fold every run's rows matching ``fold``'s sec window into ONE
        merged :class:`~geomesa_tpu.stats.sketch.RunSketch` — the
        StatsScan push-down re-expressed over the sorted key runs: the
        encoded key IS the value, so MinMax/Histogram/DescriptiveStats/
        Frequency (and Count) fold on DEVICE for device runs, host runs
        fold in one stacked numpy pass with per-run attribution, and no
        candidate row ever materializes.  Sealed runs' partials cache
        under ``fold`` (LRU + byte ceiling; compaction mints new
        gen_ids), so a warm repeat folds only the live run.

        ``want_values`` folds (TopK/Enumeration's exact value→count
        maps) are dict-valued and run host-side over the runs' key
        columns (device runs fetch once; the partial caches like any
        other)."""
        with obs_span("lean.sketch", attr=self.attr,
                      generations=len(self.generations)):
            return self._sketch_scan(fold)

    def _sketch_scan(self, fold) -> "RunSketch":
        from ..metrics import (
            LEAN_SKETCH_CACHE_HITS, LEAN_SKETCH_CACHE_MISSES,
        )
        from ..stats.sketch import RunSketch, fold_attr_runs
        merged = RunSketch()
        if not self.generations:
            return merged
        live = self.generations[-1]
        cache = self._sketch_cache.spec_cache(fold)
        dev_scan: list = []
        host_scan: list = []
        _ht: list | None = [] if heat_enabled() else None
        for g in self.generations:
            part = cache.get(g.gen_id) if g is not live else None
            if part is not None:
                obs_count(LEAN_SKETCH_CACHE_HITS)
                merged = merged + part
            elif g.tier == "device":
                dev_scan.append(g)
            else:
                host_scan.append(g)
            if _ht is not None:
                _ht.append((g.gen_id, g.tier, int(g.n),
                            0 if part is not None
                            else int(g.n) * SLOT_BYTES, None))
        if _ht:
            record_index_scan(self, _ht)
        is_float = self.attr_type in ("float", "double")
        new_parts: dict[int, object] = {}
        if dev_scan and not fold.want_values:
            # every uncached device run in ONE dispatch (bucket-padded:
            # all-sentinel padding folds to an empty partial)
            padded = (list(dev_scan)
                      + [None] * ((-len(dev_scan)) % _GEN_BUCKET))
            cols: list = []
            for g in padded:
                c = (self._sentinel_cols() if g is None
                     else (g.keys, g.sec))
                cols += [c[0], c[1]]
            self.dispatch_count += 1
            with device_span("query.scan.device", stage="sketch",
                             runs=len(dev_scan)):
                cnt, kmin, kmax, vsum, vsumsq, hist, cms = [
                    np.asarray(a) for a in _attr_sketch_multi(
                        jnp.int64(fold.slo), jnp.int64(fold.shi),
                        jnp.float64(fold.hlo), jnp.float64(fold.hhi),
                        *cols, bins=int(fold.bins), depth=int(fold.depth),
                        width=int(fold.width), is_float=is_float)]
            for i, g in enumerate(dev_scan):
                n = int(cnt[i])
                new_parts[id(g)] = RunSketch(
                    n, int(kmin[i]) if n else None,
                    int(kmax[i]) if n else None,
                    float(vsum[i]), float(vsumsq[i]),
                    np.array(hist[i]) if fold.bins else None,
                    np.array(cms[i]) if fold.depth else None)
        elif dev_scan:
            # exact value→count folds are dict-valued — host fold over
            # the fetched sorted key runs (valid rows sort to the front)
            runs = [(np.asarray(g.keys[:g.n]), np.asarray(g.sec[:g.n]))
                    for g in dev_scan]
            for g, p in zip(dev_scan,
                            fold_attr_runs(runs, fold, self.attr_type)):
                new_parts[id(g)] = p
        if host_scan:
            runs = [(g.spilled[0], g.spilled[1]) for g in host_scan]
            for g, p in zip(host_scan,
                            fold_attr_runs(runs, fold, self.attr_type)):
                new_parts[id(g)] = p
        for g in dev_scan + host_scan:
            p = new_parts[id(g)]
            merged = merged + p
            if g is not live:
                obs_count(LEAN_SKETCH_CACHE_MISSES)
                self._sketch_cache.add(cache, g.gen_id, p)
        return merged

    # -- query path -------------------------------------------------------
    def query_ranges(self, ranges: list, n_windows: int = 1,
                     total_rows: int | None = None) -> np.ndarray:
        """Candidate gids for inclusive composite ranges
        ``(klo, khi, slo, shi, qid)`` — equality narrows by sec, value
        ranges pass open sec bounds (module doc).  Returns coded
        ``qid << pos_bits | gid`` when ``n_windows > 1``, else plain
        sorted unique gids."""
        if not ranges or self._n_rows == 0:
            return np.empty(0, np.int64)
        n_pad = pad_pow2(len(ranges))
        qklo = np.full(n_pad, 1, np.int64)    # never-matching padding
        qkhi = np.full(n_pad, 0, np.int64)
        qslo = np.full(n_pad, 1, np.int64)
        qshi = np.full(n_pad, 0, np.int64)
        qqid = np.zeros(n_pad, np.int32)
        for i, (klo, khi, slo, shi, qid) in enumerate(ranges):
            qklo[i] = klo
            qkhi[i] = khi
            qslo[i] = _I64_MIN if slo is None else slo
            qshi[i] = _I64_MAX if shi is None else shi
            qqid[i] = qid
        pos_bits = coded_pos_bits(
            total_rows if total_rows is not None else self._n_rows,
            max(1, n_windows))
        jklo, jkhi = jnp.asarray(qklo), jnp.asarray(qkhi)
        jslo, jshi = jnp.asarray(qslo), jnp.asarray(qshi)
        dev_gens = [g for g in self.generations if g.tier == "device"]
        host_gens = [g for g in self.generations if g.tier == "host"]
        parts: list = []
        if dev_gens:
            padded = list(dev_gens)
            n_b = (-len(padded)) % _GEN_BUCKET
            padded += [None] * n_b
            count_cols: list = []
            for gen in padded:
                cols = (self._sentinel_cols() if gen is None
                        else (gen.keys, gen.sec, gen.gid))
                count_cols += [cols[0], cols[1]]
            self.dispatch_count += 1
            with device_span("query.scan.device", stage="probe",
                             runs=len(dev_gens),
                             rows=int(sum(g.n for g in dev_gens))):
                totals = np.asarray(_attr_count_multi(
                    jklo, jkhi, jslo, jshi, *count_cols))
            # adaptive-replan probe point (ISSUE 19): device totals are
            # known BEFORE any gather, so aborting here discards nothing
            from ..planning.adaptive import check_replan
            dev_total = int(totals.sum())
            check_replan("query.scan.probe", dev_total)
            if int(totals.sum()):
                capacity = gather_capacity(int(totals.max()),
                                           minimum=self.DEFAULT_CAPACITY)
                if len(padded) * capacity <= self.BATCH_SCAN_BUDGET:
                    groups = [padded]
                    caps = [capacity]
                else:
                    groups = [[g] for g, t in zip(dev_gens, totals)
                              if int(t)]
                    caps = [gather_capacity(int(t),
                                            minimum=self.DEFAULT_CAPACITY)
                            for t in totals if int(t)]
                from ..resilience import check_cancel, fault_point
                for group, cap in zip(groups, caps):
                    # deadline yield point between group dispatches
                    # (partial mode: unscanned groups' rows are simply
                    # absent — candidates are a subset either way)
                    if check_cancel("query.scan.device"):
                        break
                    try:
                        fault_point("device.dispatch")
                        cols = []
                        for gen in group:
                            cols += list(self._sentinel_cols()
                                         if gen is None
                                         else (gen.keys, gen.sec,
                                               gen.gid))
                        self.dispatch_count += 1
                        with device_span("query.scan.device",
                                         stage="gather",
                                         runs=len(group)):
                            packed = _attr_scan_coded(
                                jklo, jkhi, jslo, jshi,
                                jnp.asarray(qqid),
                                *cols, capacity=cap, pos_bits=pos_bits)
                            # the blocking device->host read belongs to
                            # the dispatch; host-side filtering does not
                            flat = np.asarray(packed).ravel()
                    except Exception as e:  # noqa: BLE001
                        coded = self._dispatch_failed(
                            group, e, qklo, qkhi, qslo, qshi, qqid,
                            pos_bits)
                        if coded is None:
                            raise
                        if len(coded):
                            parts.append(coded)
                        continue
                    parts.append(flat[flat >= 0].astype(np.int64))
        host_cand_n = 0
        if host_gens:
            with obs_span("query.scan.host", runs=len(host_gens)):
                if self._host_stack is None:
                    self._host_stack = _HostAttrStack(
                        [g.spilled for g in host_gens])
                coded = self._host_stack.candidates(
                    qklo, qkhi, qslo, qshi, qqid, pos_bits)
                host_cand_n = int(len(coded))
                if len(coded):
                    parts.append(coded)
        if host_cand_n:
            from ..planning.adaptive import check_replan
            check_replan("query.scan.probe",
                         (dev_total if dev_gens else 0) + host_cand_n)
        if heat_enabled():
            # heat touches: device runs attribute candidates exactly
            # from the probe totals; host candidates split
            # proportionally to run size (obs/heat module doc)
            touches = [(g.gen_id, g.tier, int(g.n),
                        int(g.n) * SLOT_BYTES,
                        int(totals[i]) if len(totals) else 0)
                       for i, g in enumerate(dev_gens)]
            n_host = sum(g.n for g in host_gens)
            touches += [(g.gen_id, "host", int(g.n),
                         int(g.n) * SLOT_BYTES,
                         int(round(host_cand_n * g.n / n_host)))
                        for g in host_gens]
            record_index_scan(self, touches)
        if not parts:
            return np.empty(0, np.int64)
        merged = np.concatenate(parts)
        if n_windows > 1:
            return merged
        mask = (np.int64(1) << pos_bits) - 1
        return np.unique(merged & mask)

    def _dispatch_failed(self, group, exc, qklo, qkhi, qslo, qshi, qqid,
                         pos_bits):
        """Degraded execution at the dispatch boundary (ISSUE 16):
        transient (memory-pressure) failures spill the failed group to
        host and answer via host-seek candidates — the planner's
        residual filter restores exactness; poison propagates (returns
        None).  Mirrors z3_lean's contract."""
        from ..resilience import (breaker, classify_device_failure,
                                  retry_budget)
        if classify_device_failure(exc) != "transient":
            return None
        gens = [g for g in group if g is not None]
        for g in gens:
            breaker.record_failure((id(self), g.gen_id))
        if retry_budget() <= 0:
            return None
        with obs_span("query.scan.degraded", tier="attr",
                      reason="transient", runs=len(gens)) as sp:
            sp.set_attr("resilience.degraded", True)
            obs_count(RESILIENCE_DEGRADED, len(gens))
            obs_count(RESILIENCE_RETRIES)
            for g in gens:
                if g.tier == "device":
                    with device_span("write.spill", gen_id=g.gen_id,
                                     rows=int(g.n)):
                        obs_count(WRITE_SPILLS)
                        g.spill_to_host()
            self._host_stack = None
            stack = _HostAttrStack([g.spilled for g in gens])
            return stack.candidates(qklo, qkhi, qslo, qshi, qqid,
                                    pos_bits)

    # planner-facing surface (mirrors index/attribute.AttributeIndex) --
    #: date-tier marker: equality/IN narrow by a dtg window
    secondary = True
    #: no z3 secondary on the lean attribute index (date tier only)
    sec_z = None

    def _sec(self, sec_window):
        if sec_window is None:
            return None, None
        return sec_window

    def query_equals(self, value, sec_window=None,
                     z3_ranges=None) -> np.ndarray:
        k = encode_attr_value(value, self.attr_type)
        slo, shi = self._sec(sec_window)
        return self.query_ranges([(k, k, slo, shi, 0)])

    def query_in(self, values, sec_window=None,
                 z3_ranges=None) -> np.ndarray:
        if not len(values):
            return np.empty(0, np.int64)
        slo, shi = self._sec(sec_window)
        ranges = []
        for v in values:
            k = encode_attr_value(v, self.attr_type)
            ranges.append((k, k, slo, shi, 0))
        return self.query_ranges(ranges)

    def query_range(self, lo=None, hi=None, lo_inclusive=True,
                    hi_inclusive=True) -> np.ndarray:
        """Candidate gids for a value range.  Bounds are conservatively
        INCLUSIVE at the key level (string prefix codes alias; numeric
        exclusive endpoints survive as candidates) — the residual filter
        applies the exact operator."""
        klo = (_I64_MIN if lo is None
               else encode_attr_value(lo, self.attr_type))
        # open hi stops just short of the sentinel key (encoded keys
        # clamp below it, so no real row is missed)
        khi = (_SENTINEL_KEY - 1 if hi is None
               else encode_attr_value(hi, self.attr_type))
        return self.query_ranges([(klo, khi, None, None, 0)])

    def query_prefix(self, prefix: str) -> np.ndarray:
        if self.attr_type != "string":
            raise TypeError("prefix queries require a string attribute")
        klo, khi = string_prefix_bounds(prefix)
        return self.query_ranges([(klo, khi, None, None, 0)])
