"""ID (record) index: feature-id point lookups.

Analog of the reference's id index (geomesa-index-api/.../index/id/
IdIndexKeySpace.scala — rows keyed by feature id, with UUID-optimized
byte encoding).  Here: a sorted string-id column + permutation; lookups
are binary searches."""

from __future__ import annotations

import numpy as np

__all__ = ["IdIndex", "LeanIdIndex"]


class LeanIdIndex:
    """Id lookups for the lean profile's IMPLICIT ids (row ``r`` ⇔
    ``f"{prefix}{r}"`` — features/lean.py; multihost stores prefix per
    process): no index structure at all, an id lookup is a prefix strip
    + integer parse + range check.  The O(1)-per-id analog of
    IdIndexKeySpace's direct row seek."""

    def __init__(self, n_rows: int, prefix: str = ""):
        self.n_rows = int(n_rows)
        self.prefix = prefix

    def __len__(self) -> int:
        return self.n_rows

    def query(self, ids) -> np.ndarray:
        out = []
        for fid in ids:
            s = str(fid)
            if self.prefix:
                if not s.startswith(self.prefix):
                    continue
                s = s[len(self.prefix):]
            # canonical decimal form only: '007' is NOT row 7's id
            if s.isdecimal() and str(int(s)) == s and int(s) < self.n_rows:
                out.append(int(s))
        return np.unique(np.asarray(sorted(out), dtype=np.int64))


class IdIndex:
    def __init__(self, ids: np.ndarray, pos: np.ndarray):
        self.ids = ids    # sorted string array
        self.pos = pos

    @classmethod
    def build(cls, ids) -> "IdIndex":
        ids = np.asarray(ids).astype(str)
        order = np.argsort(ids, kind="stable")
        srt = ids[order]
        if len(srt) > 1:
            dup = srt[1:] == srt[:-1]
            if dup.any():
                # ids identify exactly one row (the reference's id
                # generators never reuse ids); a duplicate here means a
                # broken writer upstream — failing beats silently
                # returning two rows for one id
                raise ValueError(
                    f"duplicate feature id {srt[1:][dup][0]!r}: feature "
                    "ids must be unique within a schema")
        return cls(srt, order.astype(np.int64))

    def __len__(self) -> int:
        return len(self.ids)

    def query(self, ids) -> np.ndarray:
        """Positions of the given feature ids (missing ids are skipped)."""
        out = []
        for fid in ids:
            fid = str(fid)
            lo = np.searchsorted(self.ids, fid, side="left")
            hi = np.searchsorted(self.ids, fid, side="right")
            out.append(self.pos[lo:hi])
        if not out:
            return np.empty(0, dtype=np.int64)
        # unique: repeated ids (or AND'd id filters) must not duplicate rows
        return np.unique(np.concatenate(out))
