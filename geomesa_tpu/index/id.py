"""ID (record) index: feature-id point lookups.

Analog of the reference's id index (geomesa-index-api/.../index/id/
IdIndexKeySpace.scala — rows keyed by feature id, with UUID-optimized
byte encoding).  Here: a sorted string-id column + permutation; lookups
are binary searches."""

from __future__ import annotations

import numpy as np

__all__ = ["IdIndex"]


class IdIndex:
    def __init__(self, ids: np.ndarray, pos: np.ndarray):
        self.ids = ids    # sorted string array
        self.pos = pos

    @classmethod
    def build(cls, ids) -> "IdIndex":
        ids = np.asarray(ids).astype(str)
        order = np.argsort(ids, kind="stable")
        srt = ids[order]
        if len(srt) > 1:
            dup = srt[1:] == srt[:-1]
            if dup.any():
                # ids identify exactly one row (the reference's id
                # generators never reuse ids); a duplicate here means a
                # broken writer upstream — failing beats silently
                # returning two rows for one id
                raise ValueError(
                    f"duplicate feature id {srt[1:][dup][0]!r}: feature "
                    "ids must be unique within a schema")
        return cls(srt, order.astype(np.int64))

    def __len__(self) -> int:
        return len(self.ids)

    def query(self, ids) -> np.ndarray:
        """Positions of the given feature ids (missing ids are skipped)."""
        out = []
        for fid in ids:
            fid = str(fid)
            lo = np.searchsorted(self.ids, fid, side="left")
            hi = np.searchsorted(self.ids, fid, side="right")
            out.append(self.pos[lo:hi])
        if not out:
            return np.empty(0, dtype=np.int64)
        # unique: repeated ids (or AND'd id filters) must not duplicate rows
        return np.unique(np.concatenate(out))
