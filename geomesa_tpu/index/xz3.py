"""XZ3 index: intersects + time queries over geometries with extent.

Analog of the reference's XZ3 index (geomesa-index-api/.../index/z3/
XZ3IndexKeySpace.scala — key = ``[shard][2B bin][8B code][id]``): sorted
(bin, code) pair columns + permutation, per-bin time windows planned the
same way as the Z3 point index.
"""

from __future__ import annotations

import numpy as np

from ..config import DEFAULT_MAX_RANGES
from ..curve.binnedtime import TimePeriod, max_offset, to_binned_time
from ..curve.xz3 import XZ3SFC, xz3_sfc
from ..geometry.packed import PackedGeometry, pack_geometries
from ..geometry.predicates import bbox_intersects, geometry_intersects
from ..geometry.types import Geometry
from .z3 import _time_windows_by_bin

__all__ = ["XZ3Index", "xz3_bin_code_ranges"]


def xz3_bin_code_ranges(sfc, env: tuple, t_lo_ms: int, t_hi_ms: int,
                        period, max_ranges: int) -> list:
    """Shared XZ3 range planning — per-bin covering ``(bin, code_lo,
    code_hi)`` triples for an envelope × interval (whole-period bins
    grouped to share one decomposition; the range budget splits across
    windows).  The one definition behind the full-fat AND lean XZ3
    indexes (review r5)."""
    windows = _time_windows_by_bin(t_lo_ms, t_hi_ms, period)
    if not windows:
        return []
    target = max(1, max_ranges // max(1, len(windows)))
    by_window: dict[tuple, list[int]] = {}
    for b, w in windows.items():
        by_window.setdefault(w, []).append(b)
    out = []
    xmin, ymin, xmax, ymax = env
    for (wlo, whi), bs in by_window.items():
        ranges = sfc.ranges(
            [(xmin, ymin, float(wlo), xmax, ymax, float(whi))],
            max_ranges=target)
        for b in bs:
            out.extend((int(b), int(lo), int(hi)) for lo, hi in ranges)
    return out


class XZ3Index:
    """Spatio-temporal index over non-point geometries with instant dtg."""

    def __init__(self, period, g, bins, codes, pos, bbox, dtg, geoms):
        self.period = TimePeriod.parse(period)
        self.sfc: XZ3SFC = xz3_sfc(self.period, g)
        self.bins = bins          # (N,) int32 sorted-major
        self.codes = codes        # (N,) int64 sorted within bin
        self.pos = pos
        self.bbox = bbox          # original order
        self.dtg = dtg            # (N,) int64 epoch ms, original order
        self.geoms = geoms

    @classmethod
    def build(cls, geoms, dtg_ms, period: TimePeriod | str = TimePeriod.WEEK,
              g: int = 12) -> "XZ3Index":
        packed = geoms if isinstance(geoms, PackedGeometry) else pack_geometries(geoms)
        period = TimePeriod.parse(period)
        sfc = xz3_sfc(period, g)
        dtg_ms = np.asarray(dtg_ms, dtype=np.int64)
        bins, offs = to_binned_time(dtg_ms, period)
        bb = packed.bbox
        # index the bbox at the feature's time instant (zmin == zmax == offset)
        offs_f = offs.astype(np.float64)
        codes = sfc.index(bb[:, 0], bb[:, 1], offs_f, bb[:, 2], bb[:, 3],
                          offs_f, xp=np).astype(np.int64)
        order = np.lexsort((codes, bins))
        return cls(period, g, bins[order].astype(np.int32), codes[order],
                   order.astype(np.int32), bb, dtg_ms, packed)

    def __len__(self) -> int:
        return len(self.codes)

    def query(self, geometry: Geometry, t_lo_ms: int, t_hi_ms: int,
              max_ranges: int = DEFAULT_MAX_RANGES,
              exact: bool = True) -> np.ndarray:
        env = geometry.envelope
        if not len(self):
            return np.empty(0, dtype=np.int64)
        # open bounds clamp to the data's extent — the same trick the
        # z3 point index uses, so a spatial-only query can ride xz3
        # when no xz2 index is enabled (review r5)
        if t_lo_ms is None:
            t_lo_ms = int(self.dtg.min())
        if t_hi_ms is None:
            t_hi_ms = int(self.dtg.max())
        bin_ranges = xz3_bin_code_ranges(self.sfc, env.as_tuple(),
                                         t_lo_ms, t_hi_ms, self.period,
                                         max_ranges)
        cands = []
        for b, rlo, rhi in bin_ranges:
            lo_i = np.searchsorted(self.bins, b, side="left")
            hi_i = np.searchsorted(self.bins, b, side="right")
            seg = self.codes[lo_i:hi_i]
            s = np.searchsorted(seg, rlo, side="left") + lo_i
            e = np.searchsorted(seg, rhi, side="right") + lo_i
            if e > s:
                cands.append(self.pos[s:e])
        if not cands:
            return np.empty(0, dtype=np.int64)
        cand = np.concatenate(cands)
        keep = bbox_intersects(self.bbox[cand], env.as_tuple())
        keep &= (self.dtg[cand] >= t_lo_ms) & (self.dtg[cand] <= t_hi_ms)
        cand = cand[keep]
        if exact and self.geoms is not None:
            from .xz2 import _is_envelope
            if not _is_envelope(geometry, env):
                from ..geometry.predicates import packed_intersects
                cand = cand[packed_intersects(self.geoms, geometry, cand)]
        return np.sort(cand).astype(np.int64)
